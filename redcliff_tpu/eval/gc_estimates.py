"""Per-family Granger-causal estimate dispatch for evaluation.

Rebuilds get_model_gc_estimates / get_model_gc_score_estimates
(/root/reference/evaluate/eval_utils.py:893-948): every model family exposes
its GC readout differently, and single-graph baselines are replicated K times
so cross-algorithm comparisons always see one estimate per true factor.
"""
from __future__ import annotations

import numpy as np

__all__ = ["get_model_gc_estimates", "get_model_gc_score_estimates",
           "get_model_gc_summary_matrices",
           "get_combined_gc_representations_across_factors"]


def _np_list(graphs):
    return [np.asarray(g) for g in graphs]


def _replicate(graphs, num_required):
    assert len(graphs) == 1, (
        f"expected a single generic estimate, got {len(graphs)}")
    return [graphs[0].copy() for _ in range(num_required)]


def get_model_gc_estimates(model, params, model_type, num_ests_required,
                           X=None):
    """List of ``num_ests_required`` per-factor GC matrices for any supported
    family (ref eval_utils.py:908-948). ``model_type`` uses the reference's
    naming: substring dispatch over REDCLIFF / cMLP / cLSTM / DGCNN /
    DYNOTEARS / NAVAR / DCSFA / NCFM."""
    mt = model_type
    if "REDCLIFF" in mt:
        mode = model.config.primary_gc_est_mode
        if "conditional" in mode:
            # system-level eval always forces the sample-independent readout
            # (ref eval_sysOptF1...py:172-175 overrides unconditionally)
            mode = "fixed_factor_exclusive"
        ests_by_sample = model.gc_as_lists(params, gc_est_mode=mode, X=X,
                                           threshold=False, ignore_lag=False,
                                           combine_wavelet_representations=True,
                                           rank_wavelets=False)
        assert len(ests_by_sample) == 1, (
            "expected a single sample-level estimate for system-level eval")
        gc_ests = _np_list(ests_by_sample[0])
        if len(gc_ests) < num_ests_required:
            gc_ests = _replicate(gc_ests, num_ests_required)
        return gc_ests

    if "DCSFA" in mt:
        return _np_list(model.gc(params, threshold=False,
                                 ignore_features=True))

    if "NCFM" in mt:
        # single-factor forecaster baselines keep their own factor count
        if "CMLP" in mt.upper():
            return _np_list(model.gc(params, threshold=False, ignore_lag=True,
                                     combine_wavelet_representations=True,
                                     rank_wavelets=False))
        return _np_list(model.gc(params, threshold=False,
                                 combine_wavelet_representations=True,
                                 rank_wavelets=False))

    if "DYNOTEARS" in mt:
        generic = [np.asarray(model.gc())]
    elif "NAVAR" in mt:
        generic = _np_list(model.gc(params, X=X, threshold=False,
                                    ignore_lag=True))
    elif "DGCNN" in mt:
        generic = _np_list(model.gc(params, threshold=False,
                                    combine_wavelet_representations=True))
    elif "cMLP" in mt or "CMLP" in mt:
        generic = _np_list(model.gc(params, threshold=False, ignore_lag=True,
                                    combine_wavelet_representations=True,
                                    rank_wavelets=False))
    elif "cLSTM" in mt or "CLSTM" in mt:
        generic = _np_list(model.gc(params, threshold=False,
                                    combine_wavelet_representations=True,
                                    rank_wavelets=False))
    else:
        raise NotImplementedError(f"unrecognized model_type: {model_type!r}")
    return _replicate(generic, num_ests_required)


def get_model_gc_summary_matrices(model, params, model_type,
                                  num_ests_required, X=None):
    """Per-factor LAG-SUMMED GC matrices ``(C, C)`` on the standard eval
    readout path — the OFFLINE counterpart of the live training-time graph
    summary (:mod:`redcliff_tpu.obs.quality`). The quality observatory's
    golden-parity contract (tests/test_quality.py) is that the live device
    summary's per-factor column norms match these matrices within 1e-6 and
    its top-k edge sets are identical, so the in-training signal can be
    trusted as science, not merely telemetry."""
    ests = get_model_gc_estimates(model, params, model_type,
                                  num_ests_required, X=X)
    out = []
    for e in ests:
        e = np.asarray(e, dtype=np.float32)
        out.append(e.sum(axis=2) if e.ndim == 3 else e)
    return out


def get_model_gc_score_estimates(model, params, model_type,
                                 num_ests_required, X=None, state=None):
    """Factor-score estimates per family (ref eval_utils.py:893-906):
    REDCLIFF returns its embedder weights on X, DCSFA its predicted
    probabilities, and graph-only baselines a flat ones vector."""
    mt = model_type
    if "REDCLIFF" in mt:
        _, _, _, weights = model.forward(params, X)
        return np.asarray(weights[0]).reshape(num_ests_required)
    if "DCSFA" in mt:
        scores = model.predict_proba(params, state, X)
        return np.asarray(scores).reshape(num_ests_required)
    if any(tag in mt for tag in ("cMLP", "CMLP", "cLSTM", "CLSTM", "DGCNN",
                                 "DYNOTEARS", "NAVAR")):
        return np.ones(num_ests_required)
    raise NotImplementedError(f"unrecognized model_type: {model_type!r}")


def get_combined_gc_representations_across_factors(estimated_gcs, true_gcs):
    """Element-wise sums of the per-factor estimates and truths — the
    "system graph" view used by combined-representation analyses
    (ref eval_utils.py:884-891). Returns (combo_est, combo_true)."""
    combo_true = np.sum([np.asarray(t, dtype=np.float64) for t in true_gcs],
                        axis=0)
    combo_est = np.sum([np.asarray(e, dtype=np.float64)
                        for e in estimated_gcs], axis=0)
    return combo_est, combo_true
