"""Evaluation layer: graph statistics, causal distances, GC-estimate
dispatch, cross-algorithm comparison, and grid-search selection
(rebuilds /root/reference/evaluate/, SURVEY.md §2.7)."""
from .analysis import (
    collect_summary_figures,
    complexity_category,
    condense_cross_experiment,
    factor_selection_table,
    generate_analysis_report,
    network_complexity,
    parse_system_name,
    run_cross_experiment_analysis,
    summarize_ablations,
    visualize_factors_across_folds,
    visualize_trained_model_factors,
)
from .causal_distances import ancestor_aid, oset_aid, parent_aid, shd
from .cross_alg import (
    ALL_POSSIBLE_ALGORITHMS,
    evaluate_algorithm_on_fold,
    find_run_directory,
    run_cross_algorithm_comparison,
)
from .edge_dynamics import (
    compute_edge_lock_performance_v3_stats,
    compute_edge_lock_performance_v4_stats,
    compute_edge_rank_performance_v1_stats,
    compute_edge_rank_performance_v2_stats,
    compute_key_edge_correlation_stats,
    compute_key_edge_covariance_stats,
    compute_smoothed_edge_cross_edge_rank_covariance_stats,
    compute_smoothed_edge_rank_covariance_stats,
    evaluate_dynamic_graph_estimates,
)
from .gc_estimates import get_model_gc_estimates, get_model_gc_score_estimates
from .grid_selection import (
    average_factor_histories,
    filter_incomplete_runs,
    load_grid_summaries,
    rank_runs,
    select_best_models,
)
from .factor_scoring import (
    average_factor_scoring_by_state,
    evaluate_avg_factor_scoring_across_recordings,
    factor_score_sweep,
)
from .model_io import load_artifact, load_model_for_eval
from .summaries import (
    extract_metric_table,
    load_full_comparison_summary,
    summarize_off_diag_f1,
    write_cross_experiment_report,
)
from .supervised_discovery import (
    prepare_data_for_modeling,
    run_d4ic_regime_pcmci_experiment,
    run_discovery_algorithm,
    run_supervised_discovery_evaluation,
    score_discovery_predictions,
)
from .system_level import (
    evaluate_fold_system_level,
    evaluate_system_level_cv,
    evaluate_system_level_gs,
    key_similarity_stats,
)
from .stats import (
    compute_fixed_f1_stats,
    compute_graph_comparison_stats,
    compute_key_stats,
    compute_optimal_f1_stats,
    summarize_values,
    three_view_optimal_f1_stats,
)

__all__ = [
    "collect_summary_figures", "complexity_category",
    "condense_cross_experiment", "factor_selection_table",
    "generate_analysis_report", "network_complexity", "parse_system_name",
    "run_cross_experiment_analysis", "summarize_ablations",
    "visualize_factors_across_folds", "visualize_trained_model_factors",
    "ancestor_aid", "oset_aid", "parent_aid", "shd",
    "compute_edge_lock_performance_v3_stats",
    "compute_edge_lock_performance_v4_stats",
    "compute_edge_rank_performance_v1_stats",
    "compute_edge_rank_performance_v2_stats",
    "compute_key_edge_correlation_stats",
    "compute_key_edge_covariance_stats",
    "compute_smoothed_edge_cross_edge_rank_covariance_stats",
    "compute_smoothed_edge_rank_covariance_stats",
    "evaluate_dynamic_graph_estimates",
    "ALL_POSSIBLE_ALGORITHMS", "evaluate_algorithm_on_fold",
    "find_run_directory", "run_cross_algorithm_comparison",
    "get_model_gc_estimates", "get_model_gc_score_estimates",
    "average_factor_histories", "filter_incomplete_runs",
    "load_grid_summaries", "rank_runs", "select_best_models",
    "load_artifact", "load_model_for_eval",
    "average_factor_scoring_by_state",
    "evaluate_avg_factor_scoring_across_recordings", "factor_score_sweep",
    "extract_metric_table", "load_full_comparison_summary",
    "summarize_off_diag_f1", "write_cross_experiment_report",
    "prepare_data_for_modeling", "run_d4ic_regime_pcmci_experiment",
    "run_discovery_algorithm",
    "run_supervised_discovery_evaluation", "score_discovery_predictions",
    "evaluate_fold_system_level", "evaluate_system_level_cv",
    "evaluate_system_level_gs", "key_similarity_stats",
    "compute_fixed_f1_stats", "compute_graph_comparison_stats",
    "compute_key_stats", "compute_optimal_f1_stats", "summarize_values",
    "three_view_optimal_f1_stats",
]
