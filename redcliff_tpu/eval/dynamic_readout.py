"""Dynamic-readout evaluation: state-score tracking + conditional-GC dynamics.

The paper's separating claim is not raw static edge prediction (its own D4IC
numbers put every algorithm within ~0.03 of each other on off-diag optimal-F1)
— it is that REDCLIFF-S produces *dynamic* readouts: a per-window factor-score
trace (which state is active now) and a window-conditioned causal graph
(which edges are active now). Static baselines emit one graph for the whole
recording and no state scores, so they structurally cannot track the oracle's
state activations. This module scores exactly that capability, rebuilt from
the reference's analysis surfaces:

* state-score traces vs oracle activations — the notebook's avg-factor-score
  trace panels (/root/reference/evaluate/eval_utils.py:953-1092) turned into
  numbers: per-factor Pearson correlation of the embedder weighting trace
  against the oracle activation trace, plus dominant-state accuracy;
* conditional-GC edge dynamics — the eval scripts' conditional modes
  (/root/reference/models/redcliff_s_cmlp.py:477-494) scored per window
  against the time-varying true graph (dominant state's graph at each step),
  via per-window off-diagonal optimal-F1 and per-edge Pearson tracking
  (the edge-dynamics statistic family, ref eval_utils.py:517-606).

Scoring conventions (documented, deliberate):
* the true dynamic graph at step t is the DOMINANT state's lag-normed
  adjacency (states ramp linearly between activations; dominance = argmax of
  the oracle trace, which for OneHot labels is the label itself);
* a static algorithm is scored with its single graph replicated across all
  windows — its per-window optimal-F1 is computed honestly (it can do well
  when factor graphs overlap), while its tracking correlation is 0 by
  definition (a constant trajectory has no covariance with the dynamics);
* supervised REDCLIFF factors are label-aligned by the training contract
  (factor-score loss ties factor k to label k; Hungarian alignment at the
  pretrain->train transition), so no re-alignment is applied at eval time.
"""
from __future__ import annotations

import os

import numpy as np

from .cross_alg import find_run_directory
from .gc_estimates import get_model_gc_estimates
from .model_io import load_model_for_eval
from .stats import compute_optimal_f1_stats, summarize_values

__all__ = [
    "lag_normed_graph",
    "true_dynamic_graph_history",
    "score_state_tracking",
    "score_dynamic_graph_tracking",
    "static_graph_history",
    "evaluate_dynamic_readouts_on_fold",
    "run_dynamic_readout_evaluation",
]


def lag_normed_graph(G):
    """(C, C[, L]) -> (C, C) L2 over the lag axis, scaled to max 1 (the
    normalized view the optimal-F1 battery scores, ref misc.py:39-44)."""
    G = np.asarray(G, dtype=np.float64)
    if G.ndim == 3:
        G = np.sqrt(np.sum(G * G, axis=-1))
    m = np.max(np.abs(G))
    return G / m if m > 0 else G


def _score_steps(recording_len, history, label_align="last"):
    """Number of scoreable windows and the label offset. Window i covers
    steps [i, i+history); its label anchor follows ``label_align``:
    "last" (the original convention — the window's final step), or "center"
    (step i + history//2 — for fast-switching systems the window's content
    reflects its middle, not its edge)."""
    num = recording_len - history
    off = history - 1 if label_align != "center" else history // 2
    return num, off


def _dominant_trace(Y, history, label_align):
    """(T',) dominant-state index per scoreable window under the alignment
    convention; "majority" votes over each window's steps (argmax per step,
    then the window's most frequent state)."""
    Y = np.asarray(Y)
    num, off = _score_steps(Y.shape[1], history, label_align)
    if label_align == "majority":
        per_step = np.argmax(Y, axis=0)  # (T,)
        win = np.lib.stride_tricks.sliding_window_view(per_step, history)
        win = win[:num]
        S = Y.shape[0]
        counts = np.stack([(win == s).sum(axis=1) for s in range(S)])
        return np.argmax(counts, axis=0)
    return np.argmax(Y[:, off: off + num], axis=0)


def true_dynamic_graph_history(Y, true_graphs, history, label_align="last"):
    """(T', C, C) truth: at each scoreable step, the dominant state's
    normalized graph. Y is the oracle (S, T) activation trace.

    Returns (hist, dom, valid): windows whose dominant label row has no
    corresponding truth graph (the pooled unsupervised-states row the curation
    appends when num_supervised < num_factors) are marked invalid — their true
    graph is a mixture of unidentified factors, so they cannot be scored."""
    num, _ = _score_steps(np.asarray(Y).shape[1], history, label_align)
    normed = np.stack([lag_normed_graph(g) for g in true_graphs])
    dom = _dominant_trace(Y, history, label_align)  # (T',)
    valid = dom < len(true_graphs)
    return normed[np.minimum(dom, len(true_graphs) - 1)], dom, valid


def _sliding_windows(recording, history):
    recording = np.asarray(recording)
    view = np.lib.stride_tricks.sliding_window_view(
        recording, history, axis=0)  # (T-history+1, C, history)
    num, _ = _score_steps(recording.shape[0], history)
    return np.transpose(view[:num], (0, 2, 1))


def score_state_tracking(weight_trace, Y, history, valid=None,
                         label_align="last"):
    """Embedder state-score tracking vs the oracle trace.

    weight_trace: (K, T') factor weightings per scoreable step;
    Y: (S, T) oracle activations; valid: optional (T',) window mask (windows
    dominated by the pooled unsupervised row have no supervised truth and are
    excluded from BOTH metrics, same rule as the graph-tracking path).
    Returns {state_score_r, dominant_state_acc} (None when unscoreable).
    ("majority" applies window-majority voting to the dominance
    classification; the continuous trace correlates against the CENTER-step
    activations in that mode, since a vote has no continuous analog.)
    """
    Y = np.asarray(Y, dtype=np.float64)
    w = np.asarray(weight_trace, dtype=np.float64)
    num, off = _score_steps(
        Y.shape[1], history,
        "center" if label_align == "majority" else label_align)
    dom_truth = _dominant_trace(Y[: w.shape[0]], history, label_align)
    truth = Y[: w.shape[0], off: off + num]
    w = w[:, :num]
    dom_truth = dom_truth[:num]
    if valid is not None:
        truth = truth[:, valid[:num]]
        w = w[:, valid[:num]]
        dom_truth = dom_truth[valid[:num]]
    if truth.shape[1] == 0:
        return {"state_score_r": None, "dominant_state_acc": None}
    rs = []
    for k in range(truth.shape[0]):
        a, b = w[k], truth[k]
        if np.std(b) <= 0:
            # a constant oracle trace defines no tracking target on this
            # recording — skip it (same convention as the degenerate-window
            # handling on the graph side), rather than scoring it 0 or 1
            continue
        rs.append(float(np.corrcoef(a, b)[0, 1]) if np.std(a) > 0 else 0.0)
    acc = float(np.mean(np.argmax(w, axis=0) == dom_truth))
    return {"state_score_r": float(np.mean(rs)) if rs else None,
            "dominant_state_acc": acc}


def score_dynamic_graph_tracking(est_hist, true_hist):
    """Per-window off-diag optimal-F1 + per-edge Pearson tracking between an
    estimated and the true dynamic graph history (both (T', C, C))."""
    est = np.asarray(est_hist, dtype=np.float64)
    true = np.asarray(true_hist, dtype=np.float64)
    C = est.shape[-1]
    off_mask = ~np.eye(C, dtype=bool)

    f1s = []
    for t in range(est.shape[0]):
        e, g = est[t][off_mask], (true[t][off_mask] > 1e-12).astype(np.float64)
        st = compute_optimal_f1_stats(e, g)
        if st:  # {} when the window's truth is degenerate (all 0 / all 1)
            f1s.append(st["f1"])

    # per-edge tracking: Pearson over time for off-diag edges whose true
    # trajectory varies; constant estimates score 0 (no tracking)
    et = est[:, off_mask]     # (T', E)
    tt = true[:, off_mask]
    varies = np.std(tt, axis=0) > 1e-12
    rs = []
    for j in np.nonzero(varies)[0]:
        if np.std(et[:, j]) > 1e-12:
            rs.append(float(np.corrcoef(et[:, j], tt[:, j])[0, 1]))
        else:
            rs.append(0.0)
    return {"dynamic_optimal_f1": float(np.mean(f1s)) if f1s else None,
            "edge_tracking_r": float(np.mean(rs)) if rs else None,
            "num_tracked_edges": int(varies.sum())}


def static_graph_history(G, num_steps):
    """Replicate a static (C, C[, L]) estimate across all windows."""
    normed = lag_normed_graph(G)
    return np.broadcast_to(normed[None], (num_steps,) + normed.shape)


def _redcliff_conditional_history(model, params, windows):
    """(T', C, C) window-conditioned system-graph estimate: the conditional
    factor mixture (ref conditional_factor_exclusive, :477-494), factor axis
    summed into one active graph per window."""
    G = model.gc(params, gc_est_mode="conditional_factor_exclusive",
                 X=windows, ignore_lag=True)  # (B, K, C, C, 1)
    G = np.asarray(G)[..., 0].sum(axis=1)
    m = np.max(np.abs(G), axis=(1, 2), keepdims=True)
    return G / np.where(m > 0, m, 1.0)


def default_history(run_dir, alg_name, true_graphs):
    """The per-algorithm window convention: REDCLIFF's embedder window
    (embed_lag — its conditional readout needs full windows), a static
    algorithm's lag depth (its estimate is window-independent)."""
    if alg_name.startswith("REDCLIFF"):
        model = load_model_for_eval(run_dir)[0]
        return int(model.config.embed_lag)
    return max(int(np.asarray(true_graphs[0]).shape[-1]), 2)


def evaluate_dynamic_readouts_on_fold(run_dir, alg_name, true_graphs, samples,
                                      num_supervised_factors,
                                      max_recordings=16, history=None,
                                      label_align="last"):
    """Score one trained run's dynamic readouts over validation recordings.

    samples: sequence of (x (T, C), y (S, T)) oracle-labeled recordings.
    Returns per-recording metric lists, aggregated by the caller.

    history: scoring window length; None = the per-algorithm default.
    For REDCLIFF it cannot be smaller than embed_lag (the embedder consumes
    full windows). label_align picks the window's label anchor ("last",
    "center", "majority") — fast-switching systems blur under "last".
    """
    loaded = load_model_for_eval(run_dir)
    model, params = loaded[0], loaded[1]
    is_redcliff = alg_name.startswith("REDCLIFF")
    if history is None:
        history = default_history(run_dir, alg_name, true_graphs)
    if is_redcliff:
        assert history >= int(model.config.embed_lag), (
            "REDCLIFF readout windows cannot be narrower than embed_lag")

    static_est = None
    if not is_redcliff:
        # X for the data-dependent readouts (NAVAR contribution statistics)
        X = np.stack([np.asarray(x) for x, _ in samples[:max_recordings]])
        ests = get_model_gc_estimates(model, params, alg_name,
                                      len(true_graphs), X=X)
        # a static algorithm's best shot at a time-varying truth is the union
        # of its per-component graphs (families with one graph replicate it,
        # so the max is a no-op; DCSFA emits one graph per NMF component and
        # scoring only component 0 would bias by arbitrary ordering)
        static_est = np.max([lag_normed_graph(g) for g in ests], axis=0)

    metrics = {"state_score_r": [], "dominant_state_acc": [],
               "dynamic_optimal_f1": [], "edge_tracking_r": []}
    for x, y in samples[:max_recordings]:
        x = np.asarray(x)
        y = np.asarray(y)
        true_hist, _, valid = true_dynamic_graph_history(
            y, true_graphs, history, label_align=label_align)
        num_steps = true_hist.shape[0]
        if is_redcliff:
            windows = _sliding_windows(x, history)
            # a common scoring grid wider than the embedder window trims each
            # window to embed_lag steps, preserving the label anchor's
            # RELATIVE position (last-anchor -> trailing slice, center-anchor
            # -> centered slice) so the model observes the span the truth is
            # anchored in
            el = int(model.config.embed_lag)
            if windows.shape[1] > el:
                # the trim anchor must use the SAME offset mapping as
                # score_state_tracking: "majority" has no continuous analog,
                # so its continuous truth is anchored at the window CENTER —
                # a trailing-slice trim there would score the model on a span
                # the truth is not anchored in (ADVICE r5 item 1)
                _, off = _score_steps(
                    x.shape[0], history,
                    "center" if label_align == "majority" else label_align)
                rel = (off % history) / max(history - 1, 1)
                start = int(round(rel * (history - el)))
                windows = windows[:, start: start + el, :]
            weightings, _ = model._embed(params, windows)
            w = np.asarray(weightings)[:, :num_supervised_factors].T
            st = score_state_tracking(w, y, history, valid=valid,
                                      label_align=label_align)
            if st["state_score_r"] is not None:
                metrics["state_score_r"].append(st["state_score_r"])
            if st["dominant_state_acc"] is not None:
                metrics["dominant_state_acc"].append(st["dominant_state_acc"])
            est_hist = _redcliff_conditional_history(model, params, windows)
        else:
            est_hist = static_graph_history(static_est, num_steps)
        if not valid.all():
            if not valid.any():
                continue
            est_hist, true_hist = est_hist[valid], true_hist[valid]
        gt = score_dynamic_graph_tracking(est_hist, true_hist)
        if gt["dynamic_optimal_f1"] is not None:
            metrics["dynamic_optimal_f1"].append(gt["dynamic_optimal_f1"])
        if gt["edge_tracking_r"] is not None:
            metrics["edge_tracking_r"].append(gt["edge_tracking_r"])
    return metrics


def run_dynamic_readout_evaluation(roots, data_args_by_fold, true_by_fold,
                                   num_folds, num_supervised_factors,
                                   save_root, max_recordings=16,
                                   cv_dset_name="data",
                                   common_window_grid=False,
                                   label_align="last"):
    """Dynamic-readout comparison across all trained algorithms and folds.

    roots: {alg_alias: trained-models root}; the run directory per fold is
    located by the same folder-name convention as the static cross-alg eval.
    Returns {alg: {metric: {mean, sem, n}}} and writes it to
    ``save_root/dynamic_readout_summary.json``.

    common_window_grid=True scores every algorithm over the SAME window
    count and label offsets (the max of the per-algorithm window defaults)
    so the cross-algorithm table compares like windows; False keeps the
    per-algorithm conventions, recorded in the emitted summary either way.
    """
    import json

    from ..data.shards import load_normalized_samples

    os.makedirs(save_root, exist_ok=True)
    # one shard load per fold, shared by every algorithm (the validation split
    # is hundreds of recordings; reloading it per (alg, fold) would dominate
    # wall-clock on a single core); recordings arrive z-scored like training
    samples_by_fold = {}
    for fold in range(num_folds):
        ds = load_normalized_samples(os.path.join(
            os.path.dirname(data_args_by_fold[fold]), "validation"))
        samples_by_fold[fold] = list(zip(ds.X, ds.Y))
    hist_by_alg = {
        alg: default_history(find_run_directory(alg_root, cv_dset_name, 0),
                             alg, true_by_fold[0])
        for alg, alg_root in roots.items()}
    common = max(hist_by_alg.values()) if common_window_grid else None
    out = {}
    for alg, alg_root in roots.items():
        per_alg = {}
        for fold in range(num_folds):
            samples = samples_by_fold[fold]
            run_dir = find_run_directory(alg_root, cv_dset_name, fold)
            m = evaluate_dynamic_readouts_on_fold(
                run_dir, alg, true_by_fold[fold], samples,
                num_supervised_factors, max_recordings=max_recordings,
                history=common, label_align=label_align)
            for key, vals in m.items():
                per_alg.setdefault(key, []).extend(vals)
        out[alg] = {}
        for key, vals in per_alg.items():
            if not vals:
                out[alg][key] = None
                continue
            s = summarize_values(vals)
            out[alg][key] = {"mean": s["mean"], "sem": s["mean_std_err"],
                             "n": len(vals)}
        out[alg]["scoring_window"] = (common if common is not None
                                      else hist_by_alg[alg])
    out["_conventions"] = {
        "common_window_grid": bool(common_window_grid),
        "label_align": label_align,
        "window_by_algorithm_default": hist_by_alg,
        "common_window": common,
    }
    with open(os.path.join(save_root, "dynamic_readout_summary.json"),
              "w") as f:
        json.dump(out, f, indent=2)
    return out
