"""Causal-graph distances: SHD and the adjustment-identification distances.

The reference scores supervised-discovery estimates with the external Rust
package ``gadjid`` (ancestor_aid / oset_aid / parent_aid / shd, imported at
/root/reference/evaluate/eval_algsT_by_expSynSys12112_forF1RocAucCausalDistStats.py:11-12
and called with ``edge_direction="from column to row"`` at :339-378).  This
module is a native reimplementation of those four metrics for DAG inputs,
following the definitions in "Adjustment Identification Distance: A gadjid for
Causal Structure Learning" (Henckel, Würtzen & Weichwald, arXiv:2402.08616):

For every ordered pair (x, y) of distinct nodes the *guess* graph proposes an
identification strategy for the total causal effect of x on y:
  - if y is not a descendant of x in the guess: the claim "zero effect";
  - otherwise an adjustment set Z derived from the guess —
      parent_aid:   Z = Pa_guess(x)
      ancestor_aid: Z = An_guess({x, y}) \\ (Forb_guess(x, y) ∪ {x, y})
                    (the canonical "Adjust" set of van der Zander et al.)
      oset_aid:     Z = O_guess(x, y) = Pa_guess(Cn(x, y)) \\ Forb_guess(x, y)
                    (the optimal adjustment set of Henckel et al. 2022)
The strategy is verified against the *true* graph: a zero-effect claim is
correct iff y ∉ De_true(x); an adjustment set is correct iff it satisfies the
adjustment criterion in the true DAG — Z ∩ Forb_true(x, y) = ∅ and Z
d-separates x from y in the proper back-door graph.  The distance is the number
of ordered pairs with an incorrect strategy; the normalized distance divides by
p·(p-1).

Here Cn(x, y) = De(x) ∩ An(y) \\ {x} (nodes on proper causal paths) and
Forb(x, y) = De(Cn(x, y)) ∪ {x}.

Cyclic inputs raise ValueError, mirroring gadjid's rejection of non-DAG inputs
(the reference wraps every call in try/except and records NaN).
"""
from __future__ import annotations

import numpy as np

__all__ = ["shd", "parent_aid", "ancestor_aid", "oset_aid"]


def _to_row_to_col(A, edge_direction):
    """Return boolean adjacency with A[i, j] == True meaning i -> j."""
    A = np.asarray(A)
    if A.ndim != 2 or A.shape[0] != A.shape[1]:
        raise ValueError("adjacency must be square")
    B = A != 0
    if edge_direction == "from column to row":
        B = B.T
    elif edge_direction != "from row to column":
        raise ValueError(f"unknown edge_direction: {edge_direction!r}")
    if np.any(np.diag(B)):
        raise ValueError("self-loops are not allowed")
    return B


def _reachability(B):
    """R[i, j] = True iff there is a directed path i -> ... -> j (length >= 1).
    Boolean matrix closure by repeated squaring."""
    n = B.shape[0]
    R = B.copy()
    while True:
        R2 = R | (R @ R)
        if np.array_equal(R2, R):
            break
        R = R2
    if np.any(np.diag(R)):
        raise ValueError("graph contains a cycle; AID/SHD require a DAG")
    return R


def shd(true_A, guess_A, edge_direction="from row to column"):
    """Structural Hamming distance between two directed graphs.

    Each unordered node pair {i, j} contributes one mistake when its edge
    status (none / i->j / j->i / both) differs between the graphs.
    Returns (normalized_distance, n_mistakes) with normalization p(p-1)/2.
    """
    T = _to_row_to_col(true_A, edge_direction)
    G = _to_row_to_col(guess_A, edge_direction)
    if T.shape != G.shape:
        raise ValueError("graphs must have the same number of nodes")
    diff = (T != G) | (T.T != G.T)
    iu = np.triu_indices(T.shape[0], k=1)
    mistakes = int(np.sum(diff[iu]))
    total = T.shape[0] * (T.shape[0] - 1) // 2
    return (mistakes / total if total else 0.0, mistakes)


def _causal_nodes(R, x, y):
    """Cn(x, y): nodes on proper causal paths from x to y (includes y when an
    effect exists). R is the strict-reachability matrix."""
    n = R.shape[0]
    de_x = R[x].copy()
    an_y = R[:, y].copy()
    an_y[y] = True
    cn = de_x & an_y
    cn[x] = False
    return cn


def _forbidden(R, x, y):
    """Forb(x, y) = De(Cn(x, y)) ∪ {x} (descendants include the node itself)."""
    cn = _causal_nodes(R, x, y)
    forb = cn.copy()
    if cn.any():
        forb |= np.any(R[cn], axis=0)
    forb[x] = True
    return forb


def _d_separated(B, x, y, Z):
    """d-separation of x and y given set Z (boolean mask) in DAG B via the
    moralized-ancestral-graph construction."""
    n = B.shape[0]
    # ancestors of {x, y} ∪ Z, including themselves
    seed = Z.copy()
    seed[x] = True
    seed[y] = True
    anc = seed.copy()
    frontier = seed.copy()
    while frontier.any():
        parents = np.any(B[:, frontier], axis=1) & ~anc
        anc |= parents
        frontier = parents
    # induced subgraph on anc, moralized and undirected
    sub = B & anc[:, None] & anc[None, :]
    moral = sub | sub.T
    # marry parents of every common child
    for c in np.flatnonzero(anc):
        ps = np.flatnonzero(sub[:, c])
        if len(ps) > 1:
            moral[np.ix_(ps, ps)] = True
    np.fill_diagonal(moral, False)
    # connectivity from x to y avoiding Z
    blocked = Z
    if blocked[x] or blocked[y]:
        # conditioning on an endpoint separates trivially in this construction
        return True
    visited = np.zeros(n, dtype=bool)
    visited[x] = True
    frontier = np.zeros(n, dtype=bool)
    frontier[x] = True
    while frontier.any():
        nxt = np.any(moral[frontier], axis=0) & ~visited & ~blocked
        if nxt[y]:
            return False
        visited |= nxt
        frontier = nxt
    return True


def _valid_adjustment_set(B, R, x, y, Z):
    """Adjustment criterion for (x, y) in DAG B: Z ∩ Forb(x, y) = ∅ and Z
    d-separates x from y in the proper back-door graph (B minus the edges
    x -> c for c ∈ Cn(x, y))."""
    if Z[x] or Z[y]:
        return False
    forb = _forbidden(R, x, y)
    if np.any(Z & forb):
        return False
    cn = _causal_nodes(R, x, y)
    pbd = B.copy()
    pbd[x, cn] = False
    return _d_separated(pbd, x, y, Z)


def _aid(true_A, guess_A, strategy, edge_direction):
    T = _to_row_to_col(true_A, edge_direction)
    G = _to_row_to_col(guess_A, edge_direction)
    if T.shape != G.shape:
        raise ValueError("graphs must have the same number of nodes")
    n = T.shape[0]
    RT = _reachability(T)
    RG = _reachability(G)
    mistakes = 0
    for x in range(n):
        for y in range(n):
            if x == y:
                continue
            if not RG[x, y]:
                # guess claims zero effect of x on y
                if RT[x, y]:
                    mistakes += 1
                continue
            if strategy == "parent":
                Z = G[:, x].copy()
            elif strategy == "ancestor":
                Z = RG[:, x] | RG[:, y]
                Z[x] = True
                Z[y] = True
                Z &= ~_forbidden(RG, x, y)
                Z[x] = False
                Z[y] = False
            elif strategy == "oset":
                cn = _causal_nodes(RG, x, y)
                Z = np.any(G[:, cn], axis=1) if cn.any() else np.zeros(n, bool)
                Z &= ~_forbidden(RG, x, y)
            else:
                raise ValueError(strategy)
            if not _valid_adjustment_set(T, RT, x, y, Z):
                mistakes += 1
    total = n * (n - 1)
    return (mistakes / total if total else 0.0, mistakes)


def parent_aid(true_A, guess_A, edge_direction="from row to column"):
    """Parent-adjustment identification distance (gadjid parent_aid parity).
    Returns (normalized_distance, n_mistakes)."""
    return _aid(true_A, guess_A, "parent", edge_direction)


def ancestor_aid(true_A, guess_A, edge_direction="from row to column"):
    """Ancestor-adjustment identification distance (gadjid ancestor_aid
    parity). Returns (normalized_distance, n_mistakes)."""
    return _aid(true_A, guess_A, "ancestor", edge_direction)


def oset_aid(true_A, guess_A, edge_direction="from row to column"):
    """Optimal-adjustment-set identification distance (gadjid oset_aid
    parity). Returns (normalized_distance, n_mistakes)."""
    return _aid(true_A, guess_A, "oset", edge_direction)
