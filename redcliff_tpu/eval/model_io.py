"""Model loading for evaluation.

The reference torch.loads entire pickled nn.Modules
(/root/reference/evaluate/eval_utils.py:797-801, DCSFA rebuilt from folder-name
hyperparameters :846-876).  This build's artifacts are {model_class, config,
params} pickles written by redcliff_tpu.train.trainer.save_model /
RedcliffTrainer._save_checkpoint and the dCSFA fit loop, so loading is a
registry lookup + reconstruction — no folder-name parsing required.

Artifacts written since the durable-checkpoint migration carry the runtime
checkpoint header (CRC + format version); ``runtime.checkpoint.read_checkpoint``
reads those AND legacy headerless pickles, so every loader below routes
through it.
"""
from __future__ import annotations

import os
import warnings

from ..runtime.checkpoint import read_checkpoint

__all__ = ["MODEL_REGISTRY", "load_model_for_eval", "load_artifact"]


def _registry():
    from ..models.clstm_fm import CLSTMFM
    from ..models.cmlp_fm import CMLPFM
    from ..models.dcsfa_nmf import DcsfaNmf, FullDCSFAModel
    from ..models.dgcnn import DGCNNModel
    from ..models.dynotears import DynotearsModel, DynotearsVanillaModel
    from ..models.navar import NAVAR, NAVARLSTM
    from ..models.redcliff import RedcliffSCMLP

    return {
        "RedcliffSCMLP": RedcliffSCMLP,
        "CMLPFM": CMLPFM,
        "CLSTMFM": CLSTMFM,
        "DGCNNModel": DGCNNModel,
        "DcsfaNmf": DcsfaNmf,
        "FullDCSFAModel": FullDCSFAModel,
        "DynotearsModel": DynotearsModel,
        "DynotearsVanillaModel": DynotearsVanillaModel,
        "NAVAR": NAVAR,
        "NAVARLSTM": NAVARLSTM,
    }


MODEL_REGISTRY = _registry


def load_artifact(path, best_model_name=None):
    """Load a raw artifact payload from a run dir or file path.

    ``best_model_name`` names the artifact explicitly (DCSFA cached-args make
    it configurable); otherwise the standard names are tried, newest first,
    falling back to a lone pickle-like file in the directory."""
    if os.path.isdir(path):
        if best_model_name:
            # an explicit name wins outright and must exist
            named = os.path.join(path, best_model_name)
            if not os.path.isfile(named):
                raise FileNotFoundError(
                    f"best_model_name {best_model_name!r} not found in "
                    f"{path!r}")
            return read_checkpoint(named)
        # cached-args may carry any best_model_name extension (the reference
        # synSys DCSFA args use dCSFA-NMF-best-model.pt); several may coexist
        # (e.g. a stale .pkl next to the current .pt). Order deterministically:
        # .pt (the reference cached-args' recorded name) before other
        # extensions, mtime only as a tie-break — mtimes are unreliable after
        # copy/rsync/untar, so they must not decide between formats
        cands = [x for x in os.listdir(path)
                 if x.startswith("dCSFA-NMF-best-model")]
        ext_rank = {".pt": 0, ".bin": 1, ".pkl": 2}
        cands.sort(key=lambda x: (
            ext_rank.get(os.path.splitext(x)[1], 3),
            -os.path.getmtime(os.path.join(path, x))))
        if not cands:
            # non-standard best_model_name: accept a LONE pickle-like file
            # that is not one of the known non-model artifacts
            non_model = {"training_meta_data_and_hyper_parameters.pkl",
                         "trainer_checkpoint.pkl"}
            loose = [x for x in os.listdir(path)
                     if x.endswith((".pt", ".pkl", ".bin"))
                     and x not in non_model]
            if len(loose) == 1:
                cands = loose
        names = ["final_best_model.bin"] + cands
        for name in names:
            cand = os.path.join(path, name)
            if os.path.isfile(cand):
                if len(cands) > 1:
                    # warn with the file actually chosen (final_best_model.bin
                    # outranks the dCSFA candidates when both coexist)
                    warnings.warn(
                        f"multiple dCSFA-NMF-best-model artifacts in "
                        f"{path!r}: {cands!r}; loading {name!r} (.pt "
                        f"preferred over .pkl, mtime tie-break)")
                path = cand
                break
        else:
            raise FileNotFoundError(
                f"no model artifact (final_best_model.bin / "
                f"dCSFA-NMF-best-model*) in {path!r}")
    return read_checkpoint(path)


def _migrate_config(config):
    """Fill config fields added after an artifact was pickled: unpickling
    restores __dict__ directly, bypassing dataclass defaults, so a config
    saved before a field existed lacks the attribute entirely."""
    import dataclasses

    if dataclasses.is_dataclass(config):
        for f in dataclasses.fields(config):
            if not hasattr(config, f.name):
                default = f.default if f.default is not dataclasses.MISSING \
                    else (f.default_factory()
                          if f.default_factory is not dataclasses.MISSING
                          else None)
                object.__setattr__(config, f.name, default)
    return config


def load_model_for_eval(path, model_class=None, best_model_name=None):
    """Reconstruct (model, params[, state]) from a saved artifact.

    Returns (model, params) for functional models, or (model, params, state)
    when the artifact carries encoder state (dCSFA).  ``model_class``
    overrides the class recorded in the payload (useful for alias loading,
    the reference's alg_name_alias concept).
    """
    payload = load_artifact(path, best_model_name=best_model_name)
    registry = _registry()
    cls_name = model_class or payload.get("model_class")
    if cls_name is None and "config" in payload:
        cls_name = type(payload["config"]).__name__.replace("Config", "")
    if cls_name not in registry:
        raise ValueError(f"unknown model class in artifact: {cls_name!r}")
    cls = registry[cls_name]
    config = _migrate_config(payload["config"])
    if cls_name in ("DynotearsModel", "DynotearsVanillaModel"):
        # solver-state artifacts: gc() reads instance state, no params pytree
        model = cls(config)
        for attr in ("state", "d_vars", "p_orders", "n", "a_est"):
            if attr in payload:
                setattr(model, attr, payload[attr])
        return model, None
    if cls_name in ("DcsfaNmf", "FullDCSFAModel"):
        model = cls.__new__(cls)
        model.config = config
        if cls_name == "FullDCSFAModel":
            # graph-shape metadata written by _artifact_payload; GC readout
            # is impossible without it
            missing = [a for a in ("num_nodes",
                                   "num_high_level_node_features")
                       if a not in payload]
            if missing:
                raise ValueError(
                    f"FullDCSFAModel artifact is missing {missing}; re-save "
                    "with DcsfaNmf._artifact_payload")
            model.gc_feature_layout = payload.get("gc_feature_layout",
                                                  "dirspec")
        for attr in ("num_nodes", "num_high_level_node_features",
                     "gc_feature_layout"):
            if attr in payload:
                setattr(model, attr, payload[attr])
        return model, payload["params"], payload.get("state", {})
    model = cls(config)
    if "state" in payload and payload["state"] is not None:
        return model, payload["params"], payload["state"]
    return model, payload["params"]
