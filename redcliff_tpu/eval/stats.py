"""Graph-comparison statistics for evaluation drivers.

Rebuilds the stat kernels of /root/reference/evaluate/eval_utils.py:
  - compute_OptimalF1_stats_betw_two_gc_graphs (:656-679) — the headline
    optimal-threshold-F1 metric with its edge-case gating
  - compute_f1_stats_betw_two_gc_graphs (:681-704) — fixed-cutoff F1s
  - compute_key_stats_betw_two_gc_graphs (:706-747) — ROC-AUC +
    sensitivity/specificity/likelihood-ratio sweeps
plus the three-view (norm / norm-off-diag / transposed) evaluation paradigm
used by every cross-algorithm sysOptF1 script
(ref eval_sysOptF1_crossAlg_d4IC_HSNR_bCgsParsim_REDCSmovNEWcMLP.py:179-202)
and the mean/median/std/SEM aggregation applied across factors and folds
(ref :218-237, :274-299).
"""
from __future__ import annotations

import numpy as np

from ..utils.metrics import (
    compute_f1,
    compute_negative_likelihood_ratio,
    compute_optimal_f1,
    compute_positive_likelihood_ratio,
    compute_sensitivity,
    compute_specificity,
    deltacon0,
    deltacon0_with_directed_degrees,
    deltaffinity,
    compute_cosine_similarity,
    path_length_mse,
    roc_auc,
)
from ..utils.misc import mask_diag_elements, normalize_array

__all__ = [
    "compute_optimal_f1_stats",
    "compute_fixed_f1_stats",
    "compute_key_stats",
    "compute_graph_comparison_stats",
    "three_view_optimal_f1_stats",
    "summarize_values",
    "DEFAULT_PRED_CUTOFFS",
]

DEFAULT_PRED_CUTOFFS = (0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9)


def _gate(est_A, true_A, caller):
    """The reference's shared edge-case gating: skip stats when either graph
    is non-finite or homogeneous (ref eval_utils.py:658-671). Returns the
    integer labels when comparable, else None."""
    est_A = np.asarray(est_A, dtype=np.float64)
    true_A = np.asarray(true_A, dtype=np.float64)
    if not np.isfinite(est_A.sum()):
        print(f"{caller}: WARNING - NON-FINITE VALUE ENCOUNTERED IN est_A",
              flush=True)
        return None
    if est_A.min() == est_A.max():
        print(f"{caller}: WARNING - HOMOGENOUS VALUES DETECTED IN est_A",
              flush=True)
        return None
    if not np.isfinite(true_A.sum()):
        print(f"{caller}: WARNING - NON-FINITE VALUE ENCOUNTERED IN true_A",
              flush=True)
        return None
    labels = true_A.ravel().astype(np.int64)
    if labels.min() == labels.max():
        print(f"{caller}: WARNING - HOMOGENOUS VALUES DETECTED IN labels",
              flush=True)
        return None
    return labels


def compute_optimal_f1_stats(est_A, true_A):
    """{"f1", "decision_threshold", "roc_auc"} via a best-F1 threshold scan,
    or {} when the inputs are degenerate (ref :656-679).

    "roc_auc" is an addition beyond the reference's stats dict (whose
    function name promises it but only emits f1 — ref :656): the flattened
    estimate scored against the binarized truth, same convention as the
    in-training tracking (ref model_utils.py:54-67)."""
    labels = _gate(est_A, true_A, "compute_optimal_f1_stats")
    if labels is None:
        return {}
    thresh, f1 = compute_optimal_f1(labels, np.asarray(est_A).ravel())
    # degrade to None rather than propagate, the same convention
    # compute_key_stats applies to its constituent metrics
    try:
        auc = roc_auc(labels, np.asarray(est_A).ravel())
    except Exception:
        auc = None
    return {"f1": f1, "decision_threshold": thresh, "roc_auc": auc}


def compute_fixed_f1_stats(est_A, true_A, pred_cutoffs=DEFAULT_PRED_CUTOFFS):
    """F1 at each fixed cutoff, keyed "f1_pc<cutoff>" (ref :681-704)."""
    labels = _gate(est_A, true_A, "compute_fixed_f1_stats")
    if labels is None:
        return {}
    out = {}
    for pc in pred_cutoffs:
        try:
            out[f"f1_pc{pc}"] = compute_f1(labels, np.asarray(est_A).ravel(),
                                           pc)
        except Exception:
            out[f"f1_pc{pc}"] = None
    return out


def compute_key_stats(est_A, true_A, pred_cutoffs=DEFAULT_PRED_CUTOFFS):
    """ROC-AUC plus sensitivity/specificity/PLR/NLR sweeps (ref :706-747)."""
    labels = _gate(est_A, true_A, "compute_key_stats")
    if labels is None:
        return {}
    preds = np.asarray(est_A, dtype=np.float64).ravel()
    out = {}
    try:
        out["roc_auc"] = roc_auc(labels, preds)
    except Exception:
        out["roc_auc"] = None
    for pc in pred_cutoffs:
        for name, fn in (
            ("sensitivity", compute_sensitivity),
            ("specificity", compute_specificity),
            ("PLR", compute_positive_likelihood_ratio),
            ("NLR", compute_negative_likelihood_ratio),
        ):
            try:
                out[f"{name}_pc{pc}"] = fn(labels, preds, pred_cutoff=pc)
            except Exception:
                out[f"{name}_pc{pc}"] = None
    return out


def compute_graph_comparison_stats(est_A, true_A, dcon0_eps=0.1,
                                   max_mse_path_length=None,
                                   make_graphs_undirected_for_dcon0=False):
    """Structural-similarity battery: DeltaCon0 family, Deltaffinity,
    path-length MSE, cosine similarity (the reference tracks these per epoch
    via general_utils/model_utils.py:90-209 and in eval summaries)."""
    est_A = np.asarray(est_A, dtype=np.float64)
    true_A = np.asarray(true_A, dtype=np.float64)
    out = {}
    try:
        out["deltacon0"] = deltacon0(
            est_A, true_A, dcon0_eps,
            make_graphs_undirected=make_graphs_undirected_for_dcon0)
    except Exception:
        out["deltacon0"] = None
    try:
        out["deltacon0_with_directed_degrees"] = \
            deltacon0_with_directed_degrees(est_A, true_A, dcon0_eps)
    except Exception:
        out["deltacon0_with_directed_degrees"] = None
    try:
        out["deltaffinity"] = deltaffinity(est_A, true_A, dcon0_eps,
                                           max_path_length=max_mse_path_length)
    except Exception:
        out["deltaffinity"] = None
    try:
        out["path_length_mse"] = path_length_mse(
            est_A, true_A, max_path_length=max_mse_path_length)
    except Exception:
        out["path_length_mse"] = None
    try:
        out["cosine_similarity"] = compute_cosine_similarity(est_A, true_A)
    except Exception:
        out["cosine_similarity"] = None
    return out


def three_view_optimal_f1_stats(est_gc, true_gc):
    """The sysOptF1 per-factor stat paradigms (ref :179-202): lag-summed,
    normalized graphs compared as-is, off-diagonal-masked, and with the
    estimate transposed. Returns the reference's paradigm-keyed dict."""
    est_gc = np.asarray(est_gc, dtype=np.float64)
    true_gc = np.asarray(true_gc, dtype=np.float64)
    if est_gc.ndim == 3:
        est_gc = est_gc.sum(axis=2)
    if true_gc.ndim == 3:
        true_gc = true_gc.sum(axis=2)
    off_est = mask_diag_elements(est_gc)
    off_true = mask_diag_elements(true_gc)
    n_est, n_true = normalize_array(est_gc), normalize_array(true_gc)
    n_off_est, n_off_true = normalize_array(off_est), normalize_array(off_true)
    return {
        "key_stats_estGC_norm_vs_trueGC_norm":
            compute_optimal_f1_stats(n_est, n_true),
        "key_stats_estGC_normOffDiag_vs_trueGC_normOffDiag":
            compute_optimal_f1_stats(n_off_est, n_off_true),
        "key_stats_estGC_normOffDiagTransposed_vs_trueGC_normOffDiag":
            compute_optimal_f1_stats(n_off_est.T, n_off_true),
    }


def summarize_values(values):
    """vals/mean/median/std/SEM summary of a list of scalars, the aggregation
    applied across factors then folds (ref :218-237)."""
    vals = [v for v in values if v is not None]
    if not vals:
        return {"vals": [], "mean": None, "median": None, "std_dev": None,
                "mean_std_err": None}
    arr = np.asarray(vals, dtype=np.float64)
    return {
        "vals": list(values),
        "mean": float(np.mean(arr)),
        "median": float(np.median(arr)),
        "std_dev": float(np.std(arr)),
        "mean_std_err": float(np.std(arr) / np.sqrt(len(arr))),
    }
