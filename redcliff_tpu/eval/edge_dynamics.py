"""Edge-dynamics statistics between estimated and true GC-graph histories.

Rebuilds the dynamics-evaluation family of /root/reference/evaluate/eval_utils.py:

  - compute_edgeLockPerformanceV4_stats_betw_two_gc_graphs   (ref :43-105)
  - compute_edgeLockPerformanceV3_stats_betw_two_gc_graphs   (ref :108-170)
  - compute_edgeRankPerformanceV2_stats_betw_two_gc_graphs   (ref :173-275)
  - compute_edgeRankPerformance_stats_betw_two_gc_graphs     (ref :278-406, "V1")
  - compute_smoothed_edge_crossEdgeRank_covariance_stats     (ref :409-471)
  - compute_smoothed_edge_rank_covariance_stats              (ref :474-514)
  - compute_key_edge_covariance_stats                        (ref :517-547)
  - compute_key_covariance_stats (score histories)           (ref :550-565)
  - compute_key_edge_correlation_stats                       (ref :568-606)
  - compute_key_spearman/pearson_correlation_stats (scores)  (ref :609-640)
  - compute_key_stats_betw_two_gc_score_vecs                 (ref :643-653)

These score how well an estimated dynamic graph (a history of (C, C) adjacency
snapshots, one per time window) locks onto the true graph's edge dynamics —
the statistics behind the paper's edge-dynamics analyses.

Implementation is fully vectorized: histories are (T, C, C) arrays, smoothing
is one sliding-mean over the time axis, ranking is one `rankdata(axis=...)`,
and the per-edge Pearson/Spearman statistics are computed for every edge in a
single pass — replacing the reference's O(C^2 * T * W) nested Python loops.
Output dict keys and filtering semantics match the reference exactly
(per-edge keys "i<-j", float aggregation keys on the true average smooth rank,
"smoothWindow{w}_avg_edge_rank_cov" summaries).

DOCUMENTED DIVERGENCE — the reference's
`compute_spearman_numerator_cov_of_ranked_variables` (ref
general_utils/metrics.py:88-94) computes the rank transforms of its inputs and
then DISCARDS them, returning the plain covariance of the raw inputs; every
"rank_cov" the reference reports is therefore just a covariance. This build
implements the documented intent (covariance of the rank-transformed
histories, i.e. the Spearman-correlation numerator). Pass
``match_reference_bug=True`` to any rank-covariance entry point to reproduce
the reference's actual (buggy) numbers.
"""
from __future__ import annotations

import numpy as np
from scipy.stats import rankdata
from scipy.stats import t as _student_t

from ..utils.metrics import roc_auc

__all__ = [
    "stack_history",
    "smooth_history",
    "dense_rank_per_window",
    "vector_pearson",
    "vector_spearman",
    "covariance",
    "spearman_numerator_cov",
    "compute_edge_lock_performance_v4_stats",
    "compute_edge_lock_performance_v3_stats",
    "compute_edge_rank_performance_v2_stats",
    "compute_edge_rank_performance_v1_stats",
    "compute_smoothed_edge_cross_edge_rank_covariance_stats",
    "compute_smoothed_edge_rank_covariance_stats",
    "compute_key_edge_covariance_stats",
    "compute_key_covariance_stats_betw_two_score_histories",
    "compute_key_edge_correlation_stats",
    "compute_key_spearman_correlation_stats_betw_two_score_histories",
    "compute_key_correlation_stats_betw_two_score_histories",
    "compute_key_stats_betw_two_gc_score_vecs",
    "evaluate_dynamic_graph_estimates",
]


# ---------------------------------------------------------------------------
# primitives


def stack_history(hist):
    """A history (list of (C, C) arrays, or an already-stacked (T, C, C)
    array) as a float64 (T, C, C) ndarray."""
    if isinstance(hist, np.ndarray) and hist.ndim == 3:
        return np.asarray(hist, dtype=np.float64)
    return np.stack([np.asarray(A, dtype=np.float64) for A in hist], axis=0)


def smooth_history(hist, window):
    """Sliding-mean smoothing with the reference's exact window convention:
    output[t] = mean(hist[t : t + window]) for t in 0..T-window-1, i.e. the
    smoothed history has length T - window even for window == 1
    (ref eval_utils.py:68-78)."""
    hist = stack_history(hist)
    T = hist.shape[0]
    if T - window < 1:
        raise ValueError(
            f"history of length {T} too short for smoothing window {window}")
    cs = np.concatenate([np.zeros((1,) + hist.shape[1:]), np.cumsum(hist, axis=0)])
    return (cs[window:T] - cs[: T - window]) / window


def dense_rank_per_window(hist, method="dense"):
    """Rank all C*C entries of each window's matrix jointly (the reference's
    convert_variable_to_rank_variable applied per window, ref metrics.py:72)."""
    hist = np.asarray(hist, dtype=np.float64)
    W = hist.shape[0]
    flat = hist.reshape(W, -1)
    return rankdata(flat, method=method, axis=1).reshape(hist.shape)


def _pearson_with_p(num, sx2, sy2, n):
    """Shared tail of the vectorized correlation statistics: r from the
    centered cross/auto sums plus scipy.linregress's two-sided t-test p-value."""
    den = np.sqrt(sx2 * sy2)
    with np.errstate(divide="ignore", invalid="ignore"):
        r = np.where(den > 0, num / np.where(den > 0, den, 1.0), np.nan)
    r = np.clip(r, -1.0, 1.0)
    df = n - 2
    if df <= 0:
        return r, np.full_like(r, np.nan)
    with np.errstate(divide="ignore", invalid="ignore"):
        tstat = r * np.sqrt(df / ((1.0 - r) * (1.0 + r)))
    p = 2.0 * _student_t.sf(np.abs(tstat), df)
    p = np.where(np.isfinite(tstat), p, 0.0)  # |r| == 1 -> p = 0, as scipy
    p = np.where(np.isnan(r), np.nan, p)
    return r, p


def vector_pearson(x, y, axis=0):
    """Pearson r and two-sided p for every lane of x/y along ``axis``,
    matching scipy.stats.linregress's (r, p) on each lane
    (the reference's per-edge linregress call, ref eval_utils.py:98)."""
    x = np.asarray(x, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    n = x.shape[axis]
    xm = x - x.mean(axis=axis, keepdims=True)
    ym = y - y.mean(axis=axis, keepdims=True)
    return _pearson_with_p((xm * ym).sum(axis),
                           (xm ** 2).sum(axis), (ym ** 2).sum(axis), n)


def vector_spearman(x, y, axis=0):
    """Spearman rho and two-sided p for every lane along ``axis``: Pearson on
    average-method ranks with the t-distribution p-value, matching
    scipy.stats.spearmanr per lane (ref eval_utils.py:358)."""
    rx = rankdata(np.asarray(x, dtype=np.float64), axis=axis)
    ry = rankdata(np.asarray(y, dtype=np.float64), axis=axis)
    return vector_pearson(rx, ry, axis=axis)


def covariance(x, y, axis=0):
    """Sample covariance (ddof=1) per lane — np.cov(X, Y)[0, 1] vectorized
    (ref metrics.py:79-86)."""
    x = np.asarray(x, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    n = x.shape[axis]
    xm = x - x.mean(axis=axis, keepdims=True)
    ym = y - y.mean(axis=axis, keepdims=True)
    return (xm * ym).sum(axis) / (n - 1)


def spearman_numerator_cov(x, y, axis=0, match_reference_bug=False):
    """Covariance of the rank-transformed lanes (Spearman numerator).

    The reference's version (ref metrics.py:88-94) ranks its inputs and then
    returns the covariance of the UN-ranked inputs; set
    ``match_reference_bug=True`` to reproduce that behavior."""
    if match_reference_bug:
        return covariance(x, y, axis=axis)
    rx = rankdata(np.asarray(x, dtype=np.float64), axis=axis)
    ry = rankdata(np.asarray(y, dtype=np.float64), axis=axis)
    return covariance(rx, ry, axis=axis)


def _prep(est_A_hist, true_A_hist):
    est = stack_history(est_A_hist)
    true = stack_history(true_A_hist)
    if est.shape != true.shape:
        raise ValueError(
            f"estimated {est.shape} and true {true.shape} histories differ")
    if est.ndim != 3 or est.shape[1] != est.shape[2]:
        raise ValueError(f"expected (T, C, C) histories, got {est.shape}")
    return est, true


def _paradigm_stat(paradigm, x, y):
    """(W, E) lanes -> per-edge stat dicts for one stat_paradigm."""
    if paradigm == "PearsonCorrelation":
        r, p = vector_pearson(x, y, axis=0)
        return [{"pearson_r": r[e], "pearson_p": p[e]} for e in range(x.shape[1])]
    if paradigm == "SpearmanCorrelation":
        r, p = vector_spearman(x, y, axis=0)
        return [{"spearman_r": r[e], "spearman_p": p[e]} for e in range(x.shape[1])]
    raise NotImplementedError(f"stat_paradigm {paradigm!r}")


# ---------------------------------------------------------------------------
# edgeLock family (smoothed-activation tracking)


def _edge_lock_stats(stat_paradigm, est_A_hist, true_A_hist,
                     smoothing_window_size, filter_inactive):
    est, true = _prep(est_A_hist, true_A_hist)
    C = est.shape[1]
    s_est = smooth_history(est, smoothing_window_size).reshape(-1, C * C)
    s_true = smooth_history(true, smoothing_window_size).reshape(-1, C * C)
    if stat_paradigm != "PearsonCorrelation":
        raise NotImplementedError(f"stat_paradigm {stat_paradigm!r}")
    stats = _paradigm_stat(stat_paradigm, s_est, s_true)
    stat_key = stat_paradigm + "_curr_paradigm_smooth_activ_hist_stat"

    if filter_inactive:
        true_ranks = dense_rank_per_window(
            s_true.reshape(-1, C, C)).reshape(-1, C * C)
        avg_true_rank = true_ranks.mean(axis=0)

    key_stats = {}
    for i in range(C):
        for j in range(C):
            e = i * C + j
            if filter_inactive and not (avg_true_rank[e] > 1.0 and i != j):
                continue  # no true activation (rank==1) or self-edge (ref :156)
            key_stats[f"{i}<-{j}"] = {stat_key: stats[e]}
    return key_stats


def compute_edge_lock_performance_v4_stats(stat_paradigm, est_A_hist,
                                           true_A_hist,
                                           smoothing_window_size=1):
    """Per-edge correlation between smoothed estimated and true edge-activation
    histories, for EVERY edge (ref compute_edgeLockPerformanceV4, :43-105)."""
    return _edge_lock_stats(stat_paradigm, est_A_hist, true_A_hist,
                            smoothing_window_size, filter_inactive=False)


def compute_edge_lock_performance_v3_stats(stat_paradigm, est_A_hist,
                                           true_A_hist,
                                           smoothing_window_size=1):
    """V4 restricted to truly-active off-diagonal edges (true average dense
    rank > 1; ref compute_edgeLockPerformanceV3, :108-170)."""
    return _edge_lock_stats(stat_paradigm, est_A_hist, true_A_hist,
                            smoothing_window_size, filter_inactive=True)


# ---------------------------------------------------------------------------
# edgeRank family (smoothed-rank tracking)


def _append_by_rank(key_stats, rank_key, entry):
    """The reference's secondary aggregation: per-edge stats also accumulate
    in lists keyed by the edge's true average smooth rank (a float key —
    ref eval_utils.py:262-273)."""
    if rank_key not in key_stats:
        key_stats[rank_key] = {k: [v] for k, v in entry.items()}
    else:
        for k, v in entry.items():
            key_stats[rank_key][k].append(v)


def compute_edge_rank_performance_v2_stats(stat_paradigm, est_A_hist,
                                           true_A_hist,
                                           smoothing_window_size=1):
    """Rank/activation MSE + correlation between smoothed est/true histories
    for truly-active off-diagonal edges, with per-edge AND per-true-rank
    aggregation (ref compute_edgeRankPerformanceV2, :173-275)."""
    est, true = _prep(est_A_hist, true_A_hist)
    C = est.shape[1]
    s_est = smooth_history(est, smoothing_window_size)
    s_true = smooth_history(true, smoothing_window_size)
    r_est = dense_rank_per_window(s_est).reshape(-1, C * C)
    r_true = dense_rank_per_window(s_true).reshape(-1, C * C)
    s_est = s_est.reshape(-1, C * C)
    s_true = s_true.reshape(-1, C * C)

    avg_true_rank = r_true.mean(axis=0)
    rank_mse = ((r_est - r_true) ** 2).mean(axis=0)
    activ_mse = ((s_est - s_true) ** 2).mean(axis=0)
    ranked_stats = _paradigm_stat(stat_paradigm, r_est, r_true)
    activ_stats = _paradigm_stat(stat_paradigm, s_est, s_true)
    rkey = stat_paradigm + "_curr_paradigm_ranked_smooth_hist_stat"
    akey = stat_paradigm + "_curr_paradigm_smooth_activ_hist_stat"

    key_stats = {}
    for i in range(C):
        for j in range(C):
            e = i * C + j
            if not (avg_true_rank[e] > 1.0 and i != j):
                continue
            entry = {
                "smooth_rank_MSE_across_windows": rank_mse[e],
                "smooth_activ_MSE_across_windows": activ_mse[e],
                rkey: ranked_stats[e],
                akey: activ_stats[e],
            }
            key_stats[f"{i}<-{j}"] = entry
            _append_by_rank(key_stats, avg_true_rank[e], entry)
    return key_stats


def compute_edge_rank_performance_v1_stats(stat_paradigm, est_A_hist,
                                           true_A_hist,
                                           smoothing_window_size=1):
    """Signed rank/activation deviation statistics + paradigm correlation
    (Pearson / Spearman / ROC_AUC) between smoothed est/true histories for
    truly-active off-diagonal edges (ref compute_edgeRankPerformance_stats,
    :278-406)."""
    est, true = _prep(est_A_hist, true_A_hist)
    C = est.shape[1]
    s_est = smooth_history(est, smoothing_window_size)
    s_true = smooth_history(true, smoothing_window_size)
    r_est = dense_rank_per_window(s_est).reshape(-1, C * C)
    r_true = dense_rank_per_window(s_true).reshape(-1, C * C)
    s_est = s_est.reshape(-1, C * C)
    s_true = s_true.reshape(-1, C * C)

    avg_true_rank = r_true.mean(axis=0)
    rank_diffs = r_est - r_true
    activ_diffs = s_est - s_true

    if stat_paradigm == "ROC_AUC":
        ranked_stats, activ_stats = None, None
    else:
        ranked_stats = _paradigm_stat(stat_paradigm, r_est, r_true)
        activ_stats = _paradigm_stat(stat_paradigm, s_est, s_true)
    rkey = stat_paradigm + "_curr_paradigm_ranked_smooth_hist_stat"
    akey = stat_paradigm + "_curr_paradigm_smooth_activ_hist_stat"

    key_stats = {}
    for i in range(C):
        for j in range(C):
            e = i * C + j
            if not (avg_true_rank[e] > 1.0 and i != j):
                continue
            if stat_paradigm == "ROC_AUC":
                # ref: roc_auc_score(true_ranks, est_ranks) in try/except ->
                # None unless the true ranks are binary, in which case sklearn
                # treats the larger rank as the positive class (:360-364);
                # activation stat is always None (:377)
                classes = np.unique(r_true[:, e])
                if classes.size == 2:
                    rstat = roc_auc(r_true[:, e] == classes[1], r_est[:, e])
                else:
                    rstat = None
                astat = None
            else:
                rstat, astat = ranked_stats[e], activ_stats[e]
            entry = {
                "avg_smooth_rank_diff": r_est[:, e].mean() - r_true[:, e].mean(),
                "avg_of_smooth_rank_diffs_across_windows": rank_diffs[:, e].mean(),
                "avg_smooth_activ_diff": s_est[:, e].mean() - s_true[:, e].mean(),
                "avg_of_smooth_activ_diffs_across_windows": activ_diffs[:, e].mean(),
                rkey: rstat,
                akey: astat,
            }
            key_stats[f"{i}<-{j}"] = entry
            _append_by_rank(key_stats, avg_true_rank[e], entry)
    return key_stats


# ---------------------------------------------------------------------------
# covariance / correlation summaries


def compute_smoothed_edge_cross_edge_rank_covariance_stats(
        est_A_hist, true_A_hist, smoothing_window_sizes=(1,),
        match_reference_bug=False):
    """Average per-edge rank-covariance between smoothed histories ranked
    ACROSS the matrix at each window (ref :409-471). One summary per
    smoothing window size."""
    est, true = _prep(est_A_hist, true_A_hist)
    key_stats = {}
    for w in smoothing_window_sizes:
        r_est = dense_rank_per_window(smooth_history(est, w), method="average")
        r_true = dense_rank_per_window(smooth_history(true, w), method="average")
        covs = spearman_numerator_cov(
            r_est.reshape(r_est.shape[0], -1), r_true.reshape(r_true.shape[0], -1),
            match_reference_bug=match_reference_bug)
        key_stats[f"smoothWindow{w}_avg_edge_rank_cov"] = covs.mean()
    return key_stats


def compute_smoothed_edge_rank_covariance_stats(
        est_A_hist, true_A_hist, smoothing_window_sizes=(1,),
        match_reference_bug=False):
    """Average per-edge rank-covariance between smoothed edge histories,
    ranked along each edge's own history (ref :474-514)."""
    est, true = _prep(est_A_hist, true_A_hist)
    key_stats = {}
    for w in smoothing_window_sizes:
        s_est = smooth_history(est, w).reshape(-1, est.shape[1] * est.shape[2])
        s_true = smooth_history(true, w).reshape(s_est.shape)
        covs = spearman_numerator_cov(
            s_est, s_true, match_reference_bug=match_reference_bug)
        key_stats[f"smoothWindow{w}_avg_edge_rank_cov"] = covs.mean()
    return key_stats


def compute_key_edge_covariance_stats(est_A_hist, true_A_hist,
                                      match_reference_bug=False):
    """Average covariance + rank-covariance over all raw edge histories
    (ref :517-547)."""
    est, true = _prep(est_A_hist, true_A_hist)
    E = est.shape[1] * est.shape[2]
    x, y = est.reshape(-1, E), true.reshape(-1, E)
    return {
        "avg_edge_cov": covariance(x, y).mean(),
        "avg_edge_rank_cov": spearman_numerator_cov(
            x, y, match_reference_bug=match_reference_bug).mean(),
    }


def compute_key_covariance_stats_betw_two_score_histories(
        est_h, true_h, match_reference_bug=False):
    """Covariance + rank-covariance between two 1-D score histories
    (ref :550-565)."""
    x = np.asarray(est_h, dtype=np.float64).reshape(-1)
    y = np.asarray(true_h, dtype=np.float64).reshape(-1)
    return {
        "cov": float(covariance(x, y)),
        "rank_cov": float(spearman_numerator_cov(
            x, y, match_reference_bug=match_reference_bug)),
    }


def compute_key_edge_correlation_stats(est_A_hist, true_A_hist):
    """Average Pearson + Spearman statistics over all raw edge histories
    (ref :568-606)."""
    est, true = _prep(est_A_hist, true_A_hist)
    E = est.shape[1] * est.shape[2]
    x, y = est.reshape(-1, E), true.reshape(-1, E)
    pr, pp = vector_pearson(x, y)
    sr, sp = vector_spearman(x, y)
    return {
        "avg_edge_pearson_r": pr.mean(),
        "avg_edge_pearson_p": pp.mean(),
        "avg_edge_spearman_r": sr.mean(),
        "avg_edge_spearman_p": sp.mean(),
    }


def compute_key_spearman_correlation_stats_betw_two_score_histories(est_h, true_h):
    """Spearman rho/p between two 1-D score histories (ref :609-623)."""
    x = np.asarray(est_h, dtype=np.float64).reshape(-1, 1)
    y = np.asarray(true_h, dtype=np.float64).reshape(-1, 1)
    r, p = vector_spearman(x, y)
    return {"sr": float(r[0]), "sp": float(p[0])}


def compute_key_correlation_stats_betw_two_score_histories(est_h, true_h):
    """Pearson r/p between two 1-D score histories (ref :626-640)."""
    x = np.asarray(est_h, dtype=np.float64).reshape(-1, 1)
    y = np.asarray(true_h, dtype=np.float64).reshape(-1, 1)
    r, p = vector_pearson(x, y)
    return {"r": float(r[0]), "p": float(p[0])}


def evaluate_dynamic_graph_estimates(est_A_hist, true_A_hist,
                                     stat_paradigm="PearsonCorrelation",
                                     smoothing_window_sizes=(1, 5, 10),
                                     match_reference_bug=False):
    """One-call bundle of the edge-dynamics family for the cross-algorithm /
    notebook drivers: given an estimated and a true dynamic-graph history,
    returns every dynamics statistic the reference's analysis layer consumes
    (the call pattern of ref eval_utils.py dynamics usage across the ICML
    notebook and eval scripts)."""
    sw = tuple(w for w in smoothing_window_sizes
               if w < stack_history(est_A_hist).shape[0])
    out = {
        "edge_lock_v4": compute_edge_lock_performance_v4_stats(
            stat_paradigm, est_A_hist, true_A_hist,
            smoothing_window_size=sw[0] if sw else 1),
        "edge_lock_v3": compute_edge_lock_performance_v3_stats(
            stat_paradigm, est_A_hist, true_A_hist,
            smoothing_window_size=sw[0] if sw else 1),
        "edge_rank_v2": compute_edge_rank_performance_v2_stats(
            stat_paradigm, est_A_hist, true_A_hist,
            smoothing_window_size=sw[0] if sw else 1),
        "edge_covariance": compute_key_edge_covariance_stats(
            est_A_hist, true_A_hist, match_reference_bug=match_reference_bug),
        "edge_correlation": compute_key_edge_correlation_stats(
            est_A_hist, true_A_hist),
        "smoothed_edge_rank_cov": compute_smoothed_edge_rank_covariance_stats(
            est_A_hist, true_A_hist, smoothing_window_sizes=sw or (1,),
            match_reference_bug=match_reference_bug),
        "smoothed_cross_edge_rank_cov":
            compute_smoothed_edge_cross_edge_rank_covariance_stats(
                est_A_hist, true_A_hist, smoothing_window_sizes=sw or (1,),
                match_reference_bug=match_reference_bug),
    }
    return out


def compute_key_stats_betw_two_gc_score_vecs(est_v, true_v):
    """Cosine similarity + MSE between two score vectors (ref :643-653)."""
    from ..utils.metrics import compute_cosine_similarity, compute_mse

    est_v = np.asarray(est_v, dtype=np.float64)
    true_v = np.asarray(true_v, dtype=np.float64)
    return {"cosine_similarity": compute_cosine_similarity(est_v, true_v),
            "mse": compute_mse(est_v, true_v)}
