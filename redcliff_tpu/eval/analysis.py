"""L6 analysis/reporting layer — the notebook-equivalent analysis driver.

Rebuilds, as one scripted module, the reporting capability the reference
spreads across its 91-cell analysis notebook
(/root/reference/evaluate/ICML2025_REDCLIFF_S_CMLP_Experiments_and_Analyses_
CodeRepo_Notebook.ipynb) and the summ_offDiagF1_* / plotCrossExpSummaries_*
condensers:

* network-complexity scoring + Low/Moderate/High banding
  (ref plotCrossExpSummaries_...py:63-66, notebook cell 83);
* cross-experiment condensation of ``full_comparrisson_summary.pkl`` trees
  into dataset-major mean/SEM arrays, segmented horizontal-bar figures, and
  pairwise-improvement-vs-baseline figures (ref plotCross...py:140-262);
* ablation summaries — per-variant factor-level stats and their differences
  against the full model (notebook cell 63);
* trained-model factor visualization, per fold and averaged across folds
  (notebook cells 20-32, 47-50);
* factor-count selection tables from cross-validated stopping criteria
  (notebook cells 34-35);
* figure collection/renaming into one report folder (the summ_offDiagF1_*
  scripts).

``generate_analysis_report`` chains these into the one-command regeneration
of the paper-style summary tables and figures from a tree of evaluation
artifacts.
"""
from __future__ import annotations

import os
import pickle
import shutil

import numpy as np

from ..runtime.checkpoint import read_checkpoint
from .summaries import (OFFDIAG_PARADIGM, load_full_comparison_summary,
                        summarize_off_diag_f1, write_cross_experiment_report)

__all__ = [
    "network_complexity",
    "complexity_category",
    "parse_system_name",
    "ALG_ALIASES",
    "condense_cross_experiment",
    "run_cross_experiment_analysis",
    "summarize_ablations",
    "visualize_trained_model_factors",
    "visualize_factors_across_folds",
    "factor_selection_table",
    "collect_summary_figures",
    "generate_analysis_report",
]

# paper display names (ref plotCrossExpSummaries_...py:13-28)
ALG_ALIASES = {
    "REDCLIFF_S_CMLP_WithSmoothing": "REDCLIFF-S (cMLP)",
    "REDCLIFF_S_CMLP": "REDCLIFF-S (cMLP)",
    "CMLP": "cMLP",
    "CLSTM": "cLSTM",
    "DCSFA": "dCSFA-NMF",
    "DYNOTEARS_Vanilla": "DYNOTEARS",
    "DYNOTEARS_Stochastic": "DYNOTEARS (Stochastic)",
    "NAVAR_CMLP": "NAVAR-P",
    "NAVAR_CLSTM": "NAVAR-R",
}


def network_complexity(num_nodes, num_edges):
    """Inverse off-diagonal sparsity: (num_edges / (C^2 - C))^-1 — the paper's
    network complexity score (ref plotCrossExpSummaries_...py:63, notebook
    cell 83). Lower edge density => higher complexity."""
    density = num_edges / (num_nodes**2 - num_nodes)
    return 1.0 / density


def complexity_category(score, moderate_lower_bound=7.0,
                        moderate_upper_bound=13.0):
    """Band a complexity score into the paper's Low/Moderate/High categories
    (ref plotCross...py:64-65, 144-149)."""
    if score <= moderate_lower_bound:
        return "Low"
    if score > moderate_upper_bound:
        return "High"
    return "Moderate"


def parse_system_name(name):
    """Extract {num_factors, num_nodes, num_edges} from either the curation
    folder form (``numF2_numSF2_numN12_numE11_...``) or the paper's shorthand
    (``nN12_nE11_nF2``)."""
    out = {}
    keys = {"numF": "num_factors", "numSF": "num_supervised_factors",
            "numN": "num_nodes", "numE": "num_edges",
            "nF": "num_factors", "nN": "num_nodes", "nE": "num_edges"}
    for part in str(name).split("_"):
        for prefix in sorted(keys, key=len, reverse=True):
            tail = part[len(prefix):]
            if part.startswith(prefix) and tail.isdigit():
                out.setdefault(keys[prefix], int(tail))
                break
    return out


def short_system_name(name):
    """``numF2_numSF2_numN12_numE11_...`` -> ``nN12_nE11_nF2`` (the paper's
    axis shorthand)."""
    d = parse_system_name(name)
    if {"num_nodes", "num_edges", "num_factors"} <= set(d):
        return f"nN{d['num_nodes']}_nE{d['num_edges']}_nF{d['num_factors']}"
    return str(name)


# ---------------------------------------------------------------------------
# Cross-experiment condensation (plotCrossExpSummaries capability)
# ---------------------------------------------------------------------------

def _factor_level_stats(cv_stats, paradigm, stat_root):
    """{alg: {mean, sem, vals}} for one cv dataset's paradigm block."""
    out = {}
    for alg, stats in cv_stats.get(paradigm, {}).items():
        if not isinstance(stats, dict):
            continue
        out[alg] = {
            "mean": stats.get(f"{stat_root}_mean_across_factors"),
            "sem": stats.get(f"{stat_root}_mean_std_err_across_factors"),
            "vals": stats.get(f"{stat_root}_vals_across_factors", []),
        }
    return out


def condense_cross_experiment(eval_root, paradigm=OFFDIAG_PARADIGM,
                              stat_root="f1", baseline_alg=None):
    """Walk ``eval_root/<system>/full_comparrisson_summary.pkl`` artifacts and
    condense each into per-algorithm mean/SEM plus (optionally) pairwise
    per-factor improvement of ``baseline_alg`` over each other algorithm
    (ref plotCross...py:160-186).

    Returns {system_key: {"alg_stats": {alg: {mean, sem, vals}},
    "improvements": {alg: {mean, sem}} | None, "complexity": float | None}}.
    """
    out = {}
    for sys_key in sorted(os.listdir(eval_root)):
        pkl_path = os.path.join(eval_root, sys_key,
                                "full_comparrisson_summary.pkl")
        if not os.path.isfile(pkl_path):
            continue
        full = load_full_comparison_summary(pkl_path)
        # cross-alg drivers write one cv entry per system root (ref :167)
        for cv_key, cv_stats in full.items():
            alg_stats = _factor_level_stats(cv_stats, paradigm, stat_root)
            improvements = None
            if baseline_alg is not None and baseline_alg in alg_stats:
                base_vals = alg_stats[baseline_alg]["vals"]
                improvements = {}
                for alg, st in alg_stats.items():
                    diffs = [b - v for b, v in zip(base_vals, st["vals"])]
                    if diffs:
                        improvements[alg] = {
                            "mean": float(np.mean(diffs)),
                            "sem": float(np.std(diffs) / np.sqrt(len(diffs))),
                        }
            meta = parse_system_name(sys_key)
            comp = None
            if {"num_nodes", "num_edges"} <= set(meta):
                comp = network_complexity(meta["num_nodes"],
                                          meta["num_edges"])
            out[f"{sys_key}::{cv_key}" if len(full) > 1 else sys_key] = {
                "alg_stats": alg_stats,
                "improvements": improvements,
                "complexity": comp,
                "cv_stats": cv_stats,
            }
    return out


def _dataset_major_arrays(condensed_items, alg_names, field):
    """Flat dataset-major [d0a0, d0a1, ..., d1a0, ...] mean/sem arrays for
    plot_cross_experiment_summary."""
    means, sems = [], []
    for _, entry in condensed_items:
        src = entry["alg_stats"] if field == "alg_stats" else entry["improvements"]
        for alg in alg_names:
            st = (src or {}).get(alg, {})
            m, s = st.get("mean"), st.get("sem")
            means.append(np.nan if m is None or not np.isfinite(m) else m)
            sems.append(0.0 if s is None or not np.isfinite(s) else s)
    return means, sems


def run_cross_experiment_analysis(eval_root, save_root,
                                  baseline_alg="REDCLIFF_S_CMLP_WithSmoothing",
                                  paradigm=OFFDIAG_PARADIGM, stat_root="f1",
                                  moderate_lower_bound=7.0,
                                  moderate_upper_bound=13.0,
                                  datasets_per_figure=7, plot=True):
    """The plotCrossExpSummaries driver (ref plotCross...py:140-262): band
    systems by network complexity, emit segmented cross-experiment summary
    figures (absolute performance + pairwise improvement vs the baseline)
    per band, and pickle ``system_details.pkl``.

    Returns {"system_details": ..., "by_category": {cat: [system keys]}}.
    """
    os.makedirs(save_root, exist_ok=True)
    condensed = condense_cross_experiment(eval_root, paradigm=paradigm,
                                          stat_root=stat_root,
                                          baseline_alg=baseline_alg)
    system_details = {}
    by_category = {"Low": [], "Moderate": [], "High": []}
    for sys_key, entry in condensed.items():
        cat = None
        if entry["complexity"] is not None:
            cat = complexity_category(entry["complexity"],
                                      moderate_lower_bound,
                                      moderate_upper_bound)
            by_category[cat].append(sys_key)
        system_details[sys_key] = {
            "dataset_name": short_system_name(sys_key),
            "dataset_complexity": entry["complexity"],
            "complexity_category": cat,
        }

    if plot:
        from ..utils.plotting import plot_cross_experiment_summary

        alg_names = sorted({a for e in condensed.values()
                            for a in e["alg_stats"]})
        display = [ALG_ALIASES.get(a, a) for a in alg_names]
        for cat, sys_keys in by_category.items():
            items = [(k, condensed[k]) for k in sys_keys]
            if not items:
                continue
            for seg in range(0, len(items), datasets_per_figure):
                chunk = items[seg: seg + datasets_per_figure]
                names = [system_details[k]["dataset_name"]
                         for k, _ in chunk]
                means, sems = _dataset_major_arrays(chunk, alg_names,
                                                    "alg_stats")
                plot_cross_experiment_summary(
                    os.path.join(
                        save_root,
                        f"{cat}_complexity_cross_synth_edge_prediction_"
                        f"plot{seg // datasets_per_figure}.png"),
                    means, sems, display, names,
                    title=f"Synthetic System Edge Prediction: "
                          f"{cat} Complexity",
                    xlabel="Avg. Optimal F1-Score ± SEM",
                    ylabel="Synthetic System Name (nC-nE-nK)",
                    abbreviate_dataset_names=False)
                if any(e["improvements"] for _, e in chunk):
                    means_i, sems_i = _dataset_major_arrays(
                        chunk, alg_names, "improvements")
                    plot_cross_experiment_summary(
                        os.path.join(
                            save_root,
                            f"{cat}_complexity_cross_pairwise_factorLevel_"
                            f"REDCImprovement_synth_edge_prediction_"
                            f"plot{seg // datasets_per_figure}.png"),
                        means_i, sems_i, display, names,
                        title=f"Pairwise Improvement of "
                              f"{ALG_ALIASES.get(baseline_alg, baseline_alg)}"
                              f": {cat} Complexity",
                        xlabel="Avg. Difference in Optimal F1-Score ± SEM",
                        ylabel="Synthetic System Name (nC-nE-nK)",
                        abbreviate_dataset_names=False)

    with open(os.path.join(save_root, "system_details.pkl"), "wb") as f:
        pickle.dump(system_details, f)
    return {"system_details": system_details, "by_category": by_category,
            "condensed": condensed}


# ---------------------------------------------------------------------------
# Ablation summaries (notebook cell 63)
# ---------------------------------------------------------------------------

def summarize_ablations(summaries_by_variant, full_model_key,
                        paradigm=OFFDIAG_PARADIGM, stat_root="f1",
                        algorithm=None):
    """Condense per-variant evaluation summaries into the ablation table: the
    variant's own factor-level mean ± SEM and the per-factor difference of
    the full model against it (notebook cell 63's CosSim-rho / response
    ablation analyses).

    ``summaries_by_variant`` maps variant name -> full_comparrisson_summary
    dict (each with one cv entry). ``algorithm`` selects which algorithm's
    stats to read inside each summary (default: the variant's only
    algorithm).
    """
    per_variant_vals = {}
    for variant, full in summaries_by_variant.items():
        (cv_key, cv_stats), = list(full.items())
        by_alg = cv_stats.get(paradigm, {})
        alg = algorithm
        if alg is None:
            algs = [a for a, v in by_alg.items() if isinstance(v, dict)]
            assert len(algs) == 1, (
                f"variant {variant!r} has algorithms {algs}; pass `algorithm`")
            alg = algs[0]
        per_variant_vals[variant] = by_alg[alg][
            f"{stat_root}_vals_across_factors"]

    full_vals = per_variant_vals[full_model_key]
    table = {}
    for variant, vals in per_variant_vals.items():
        vals = np.asarray(vals, dtype=np.float64)
        diffs = np.asarray(full_vals[: len(vals)]) - vals[: len(full_vals)]
        table[variant] = {
            "mean": float(np.mean(vals)),
            "sem": float(np.std(vals) / np.sqrt(len(vals))),
            "full_minus_variant_mean": float(np.mean(diffs)),
            "full_minus_variant_sem": float(np.std(diffs)
                                            / np.sqrt(len(diffs))),
            "vals": vals.tolist(),
        }
    return table


# ---------------------------------------------------------------------------
# Trained-model factor visualization (notebook cells 20-32, 47-50)
# ---------------------------------------------------------------------------

def visualize_trained_model_factors(run_dir, alg_name, num_factors, save_dir,
                                    X=None, true_gcs=None):
    """Load one trained run, read out its per-factor GC estimates, and write
    per-factor est(-vs-true) heatmaps plus the lag-summed factor panel
    (the notebook's per-fold model visualization cells). Returns the
    estimates."""
    from ..utils.plotting import (plot_gc_est_comparison,
                                  plot_gc_est_comparisons_by_factor)
    from .gc_estimates import get_model_gc_estimates
    from .model_io import load_model_for_eval

    loaded = load_model_for_eval(run_dir)
    model, params = loaded[0], loaded[1]
    ests = get_model_gc_estimates(model, params, alg_name, num_factors, X=X)
    os.makedirs(save_dir, exist_ok=True)
    for k, est in enumerate(ests):
        plot_gc_est_comparison(
            None if true_gcs is None else true_gcs[k], est,
            os.path.join(save_dir, f"factor_{k}_gc_est.png"))
    plot_gc_est_comparisons_by_factor(
        true_gcs, ests, os.path.join(save_dir, "all_factors_gc_est.png"))
    return ests


def visualize_factors_across_folds(run_dirs, alg_name, num_factors, save_dir,
                                   X=None, true_gcs=None):
    """Per-fold visualization + the cross-fold average panel (notebook
    "Avg. Across Folds" cell 30). Factor estimates are max-normalized before
    averaging so folds with different GC scales contribute equally."""
    from ..utils.plotting import plot_gc_est_comparisons_by_factor

    all_ests = []
    for fold, run_dir in enumerate(run_dirs):
        ests = visualize_trained_model_factors(
            run_dir, alg_name, num_factors,
            os.path.join(save_dir, f"fold_{fold}"), X=X, true_gcs=true_gcs)
        normed = []
        for e in ests:
            e = np.asarray(e, dtype=np.float64)
            peak = np.max(e)
            normed.append(e / peak if peak > 0 else e)
        all_ests.append(normed)
    avg = [np.mean([fold[k] for fold in all_ests], axis=0)
           for k in range(num_factors)]
    plot_gc_est_comparisons_by_factor(
        true_gcs, avg, os.path.join(save_dir, "avg_across_folds_gc_est.png"))
    return avg


# ---------------------------------------------------------------------------
# Factor-count selection (notebook cells 34-35)
# ---------------------------------------------------------------------------

def factor_selection_table(run_dirs_by_num_factors,
                           criteria_keys=("avg_forecasting_loss",
                                          "avg_factor_loss")):
    """Cross-validated stopping-criteria comparison across factor counts: for
    each candidate num_factors, the mean and SEM (across folds) of each
    criterion's best (minimum) epoch value. The notebook uses this to pick
    the TST 9-factor model (cells 34-35)."""
    table = {}
    for num_factors, run_dirs in run_dirs_by_num_factors.items():
        per_criterion = {k: [] for k in criteria_keys}
        for run_dir in run_dirs:
            meta_path = os.path.join(
                run_dir, "training_meta_data_and_hyper_parameters.pkl")
            # format-aware read: durable-header metas and legacy pickles
            meta = read_checkpoint(meta_path)
            for k in criteria_keys:
                hist = meta.get(k)
                if hist:
                    per_criterion[k].append(float(np.min(hist)))
        entry = {}
        for k, vals in per_criterion.items():
            if vals:
                entry[f"{k}_mean"] = float(np.mean(vals))
                entry[f"{k}_sem"] = float(np.std(vals) / np.sqrt(len(vals)))
                entry[f"{k}_vals"] = vals
        table[num_factors] = entry
    return table


# ---------------------------------------------------------------------------
# Figure collection (summ_offDiagF1_* capability)
# ---------------------------------------------------------------------------

def collect_summary_figures(eval_root, save_root,
                            figure_suffix="_by_algorithm.png"):
    """Gather per-system evaluation figures into one report folder, renamed
    with their system prefix (ref summ_offDiagF1_...py:21-40). Returns the
    copied paths."""
    os.makedirs(save_root, exist_ok=True)
    copied = []
    for sys_key in sorted(os.listdir(eval_root)):
        sys_dir = os.path.join(eval_root, sys_key)
        if not os.path.isdir(sys_dir):
            continue
        for sub in sorted(os.listdir(sys_dir)):
            sub_dir = os.path.join(sys_dir, sub)
            if not (os.path.isdir(sub_dir) and sub.startswith("cv")):
                continue
            for fname in sorted(os.listdir(sub_dir)):
                if fname.endswith(figure_suffix):
                    dst = os.path.join(save_root, f"{sys_key}_{fname}")
                    shutil.copy(os.path.join(sub_dir, fname), dst)
                    copied.append(dst)
    return copied


# ---------------------------------------------------------------------------
# One-command report
# ---------------------------------------------------------------------------

def generate_analysis_report(eval_root, save_root,
                             baseline_alg="REDCLIFF_S_CMLP_WithSmoothing",
                             paradigm=OFFDIAG_PARADIGM):
    """Regenerate the paper-style summary artifacts from a tree of
    per-system evaluation outputs (each ``eval_root/<system>/`` holding a
    ``full_comparrisson_summary.pkl``): headline off-diagonal-F1 CSV tables
    + grids, complexity-banded cross-experiment figures with improvement
    panels, and the collected per-system figures — the one command that
    replaces re-running the analysis notebook."""
    os.makedirs(save_root, exist_ok=True)
    report = {"tables": {}, "figures": []}

    # complexity-banded cross-experiment figures (one walk/load of the tree;
    # the condensed entries carry the raw cv stats for the tables below)
    cross = run_cross_experiment_analysis(
        eval_root, save_root, baseline_alg=baseline_alg, paradigm=paradigm)
    report["system_details"] = cross["system_details"]
    report["by_category"] = cross["by_category"]

    # per-system headline tables (summ capability)
    merged = {key: entry["cv_stats"]
              for key, entry in cross["condensed"].items()}
    if merged:
        report["tables"]["off_diag_f1"] = summarize_off_diag_f1(merged)
        write_cross_experiment_report(
            merged, save_root, paradigm=paradigm,
            stat="f1_mean_across_factors")

    # collected per-system figures
    report["figures"] = collect_summary_figures(eval_root, save_root)

    with open(os.path.join(save_root, "analysis_report.pkl"), "wb") as f:
        pickle.dump(report, f)
    return report
