"""Supervised-discovery evaluation: tidybench + PCMCI scored against
regime-resolved ground truth.

Rebuilds the eval_algsT flow
(/root/reference/evaluate/eval_algsT_by_expSynSys12112_forF1RocAucCausalDistStats.py):
windowed recordings concatenate into one long multivariate series with
per-regime step masks (prepare_data_for_modeling :45-80); each discovery
algorithm runs once per regime on the regime-masked data; predictions are
standardized off-diagonal scores; and each regime-factor prediction is scored
with optimal-F1 (+threshold), ROC-AUC on raw and thresholded predictions, and
the causal distances (ancestor/oset/parent AID and SHD) on the thresholded
masks plus their upper/lower-triangular restrictions (:313-400) — using the
native eval.causal_distances in place of the gadjid Rust wheel.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from ..models.pcmci import pcmci, pcmci_val_graph, rpcmci
from ..tidybench.lasar import lasar
from ..tidybench.qrbs import qrbs
from ..tidybench.selvar import selvar
from ..tidybench.slarac import slarac
from ..utils.metrics import compute_optimal_f1, roc_auc
from .causal_distances import ancestor_aid, oset_aid, parent_aid, shd

__all__ = [
    "prepare_data_for_modeling",
    "standardized_off_diagonal_predictions",
    "run_discovery_algorithm",
    "score_discovery_predictions",
    "run_supervised_discovery_evaluation",
    "run_d4ic_regime_pcmci_experiment",
]

SUPPORTED_ALGORITHMS = ("slarac", "qrbs", "lasar", "selvar", "PCMCI")


def _window_labels(x, y):
    """Normalize one window's labels to a (T, R) trace (1-D labels repeat
    over the window)."""
    x = np.asarray(x, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    if y.ndim == 1:
        y = np.repeat(y[:, None], x.shape[0], axis=1)
    return x, y.T


def _dominant_regime(x, y):
    """Per-step dominant regime (argmax of the label trace)."""
    x, labels = _window_labels(x, y)
    return x, np.argmax(labels, axis=1)


def prepare_data_for_modeling(samples):
    """Concatenate [(x (T, C), y (R, T)), ...] windows into one series with
    per-regime binary masks (ref :45-80): each step's dominant regime
    (argmax of the label trace) owns that step.

    Returns (data (T_total, N), labels (T_total, R), masks {r: (T_total, N)},
    T_window, T_total, N, num_regimes).
    """
    data_parts, label_parts = [], []
    T_window = None
    for x, y in samples:
        x, labels = _window_labels(x, y)
        if T_window is None:
            T_window = x.shape[0]
        data_parts.append(x)
        label_parts.append(labels)
    data = np.concatenate(data_parts)
    labels = np.concatenate(label_parts)
    T_total, N = data.shape
    num_regimes = labels.shape[1]
    masks = {r: np.zeros((T_total, N)) for r in range(num_regimes)}
    dominant = np.argmax(labels, axis=1)
    for r in range(num_regimes):
        masks[r][dominant == r, :] = 1.0
    return data, labels, masks, T_window, T_total, N, num_regimes


def standardized_off_diagonal_predictions(A, transpose=False):
    """Collapse lags (abs-sum) if present, optionally transpose to the
    columns-drive-rows convention, and zero the diagonal
    (ref get_standardized_off_diagonal_relation_predictions[_for_rpcmci]
    :82-100)."""
    A = np.asarray(A, dtype=np.float64)
    if A.ndim == 3:
        A = np.abs(A).sum(axis=2)
    if transpose:
        A = A.T
    return A * (1.0 - np.eye(A.shape[0]))


def _regime_segments(samples, regime, min_len):
    """Contiguous per-window step runs where ``regime`` dominates, as
    separate recordings (PCMCI's lag structure must not cross regime
    boundaries)."""
    segments = []
    for x, y in samples:
        x, dominant = _dominant_regime(x, y)
        start = None
        for t in range(len(dominant) + 1):
            active = t < len(dominant) and dominant[t] == regime
            if active and start is None:
                start = t
            elif not active and start is not None:
                if t - start > min_len:
                    segments.append(x[start:t])
                start = None
    return segments


def run_discovery_algorithm(samples, alg_name, maxlags=None,
                            pcmci_kwargs=None, prepared=None):
    """Per-regime GC score matrices from one discovery algorithm
    (ref run_tidybench_experiment :197-214).  Returns [pred (N, N)] indexed
    by regime.  ``prepared`` accepts a prepare_data_for_modeling result so
    multi-algorithm sweeps concatenate the windows once.

    ``maxlags`` defaults per algorithm to the reference's Table-2 settings:
    1 for the tidybench family and tau_max=2 for PCMCI (ref
    eval_algsT_...py:120).  An explicitly passed value is honored for every
    algorithm, PCMCI included."""
    if prepared is None:
        prepared = prepare_data_for_modeling(samples)
    data, _, masks, _, _, N, num_regimes = prepared
    lags = 1 if maxlags is None else maxlags
    preds = []
    for r in range(num_regimes):
        if alg_name == "slarac":
            raw = slarac(data * masks[r], maxlags=lags,
                         post_standardise=True)
        elif alg_name == "qrbs":
            raw = qrbs(data * masks[r], lags=lags, post_standardise=True)
        elif alg_name == "lasar":
            raw = lasar(data * masks[r], maxlags=lags,
                        post_standardise=True)
        elif alg_name == "selvar":
            raw = selvar(data * masks[r], maxlags=lags)
        elif alg_name == "PCMCI":
            # reference Table-2 setup: tau_max=2, pc_alpha=0.2,
            # alpha_level=0.01 (ref eval_algsT_...py:120)
            kw = dict(tau_max=2 if maxlags is None else maxlags,
                      pc_alpha=0.2, alpha_level=0.01)
            kw.update(pcmci_kwargs or {})
            graph_alpha = kw.get("alpha_level", 0.01)
            segs = _regime_segments(samples, r, min_len=kw["tau_max"])
            if not segs:
                preds.append(np.zeros((N, N)))
                continue
            res = pcmci(segs, **kw)
            raw = pcmci_val_graph(res, alpha_level=graph_alpha)
        else:
            raise ValueError(f"unsupported algorithm: {alg_name!r}")
        preds.append(standardized_off_diagonal_predictions(raw))
    return preds


def _aid_stats(true_graph, pred_mask):
    """AID/SHD battery on the full graph and its triangular restrictions,
    NaN on incompatible (cyclic) inputs (ref :338-400)."""
    out = {}
    views = {
        "": (true_graph, pred_mask),
        "upper_": (np.triu(true_graph), np.triu(pred_mask)),
        "lower_": (np.tril(true_graph), np.tril(pred_mask)),
    }
    for prefix, (tg, pm) in views.items():
        for name, fn in (("ancestor_aid", ancestor_aid),
                         ("oset_aid", oset_aid),
                         ("parent_aid", parent_aid), ("shd", shd)):
            key = f"{prefix}optF1Thresh_{name}"
            try:
                out[key] = fn(tg, pm, edge_direction="from column to row")
            except Exception:
                out[key] = np.nan
    return out


def score_discovery_predictions(preds_by_regime, true_graphs,
                                transpose_predictions=True):
    """Per-regime-factor scoring (ref :313-400).  ``true_graphs`` are the
    binarized, diagonal-masked per-factor ground truths; ``preds_by_regime``
    aligns with them by index.  Returns {"rf_<k>": stats dict}."""
    stats = {}
    for rf, true_graph in enumerate(true_graphs):
        true_graph = np.asarray(true_graph).astype(np.int8)
        labels = true_graph.ravel().astype(int)
        pred = np.asarray(preds_by_regime[rf], dtype=np.float64)
        if transpose_predictions:
            pred = pred.T
        # normalize by the max entry before scoring (ref :304 via
        # normalize_numpy_array) so optF1_thresh values are on the
        # reference's [0, 1] scale
        peak = np.max(pred)
        if peak > 0:
            pred = pred / peak
        entry = {}
        thresh, f1 = compute_optimal_f1(labels, pred.ravel())
        entry["optF1_thresh"] = thresh
        entry["optF1_score"] = f1
        mask = (pred > thresh).astype(np.float64)
        mask = mask * (1.0 - np.eye(mask.shape[0]))
        mask = mask.astype(np.int8)
        try:
            entry["roc_auc"] = roc_auc(labels, pred.ravel())
        except ValueError:
            entry["roc_auc"] = np.nan
        try:
            entry["optF1Thresh_roc_auc"] = roc_auc(
                labels, mask.ravel().astype(np.float64))
        except ValueError:
            entry["optF1Thresh_roc_auc"] = np.nan
        entry.update(_aid_stats(true_graph, mask))
        stats[f"rf_{rf}"] = entry
    return stats


def run_supervised_discovery_evaluation(samples, true_gc_factors,
                                        algorithms=("slarac", "qrbs",
                                                    "lasar", "selvar",
                                                    "PCMCI"),
                                        maxlags=None, save_path=None,
                                        transpose_predictions=True,
                                        pcmci_kwargs=None):
    """End-to-end Table-2 evaluation: binarize/diag-mask the true factor
    graphs (ref :250-258), run every algorithm per regime, score.  Returns
    {alg: {"preds": [...], "stats": {...}}} and optionally pickles it.
    ``maxlags=None`` keeps each algorithm's reference default (tidybench 1,
    PCMCI tau_max=2).  NB an explicit ``maxlags`` is shared by EVERY
    algorithm in the sweep — including PCMCI, whose Table-2 tau_max=2 it
    overrides (announced below so a tidybench-motivated maxlags=1 is not a
    silent PCMCI behavior change)."""
    if maxlags is not None and "PCMCI" in algorithms and maxlags != 2:
        print(f"run_supervised_discovery_evaluation: explicit maxlags="
              f"{maxlags} overrides PCMCI's reference tau_max=2",
              flush=True)
    true_graphs = []
    for g in true_gc_factors:
        g = np.asarray(g, dtype=np.float64)
        if g.ndim == 3:
            g = g.sum(axis=2)
        g = (g > 0).astype(int)
        np.fill_diagonal(g, 0)
        true_graphs.append(g)

    results = {}
    prepared = prepare_data_for_modeling(samples)
    for alg in algorithms:
        preds = run_discovery_algorithm(samples, alg, maxlags=maxlags,
                                        pcmci_kwargs=pcmci_kwargs,
                                        prepared=prepared)
        stats = score_discovery_predictions(
            preds, true_graphs, transpose_predictions=transpose_predictions)
        results[alg] = {"preds": preds, "stats": stats}
    if save_path:
        os.makedirs(save_path, exist_ok=True)
        with open(os.path.join(save_path,
                               "supervised_discovery_summary.pkl"),
                  "wb") as f:
            pickle.dump(results, f)
    return results


def _pcmci_graph_pred(result, alpha_level):
    """Binary significant-link matrix collapsed over lags (the notebook's
    ``pred_source="graph"`` option: get_pcmci_edge_preds_from_graph)."""
    sig = (result["p_matrix"] <= alpha_level).astype(np.float64)
    sig = sig * (np.abs(result["val_matrix"]) > 0)
    return sig[:, :, 1:]


def run_d4ic_regime_pcmci_experiment(samples, true_graphs,
                                     regime_source="oracle",
                                     pred_source="graph", transpose=True,
                                     tau_max=2, pc_alpha=0.2,
                                     alpha_level=0.01, rpcmci_kwargs=None):
    """The notebook's R-PCMCI D4IC experiment (ref ICML notebook cells
    69-81): per-regime PCMCI on D4IC windows scored with optimal F1 against
    each network's true graph, reporting per-regime scores + mean/SEM.

    ``regime_source``:
      * "oracle" — regimes from the label coefficients (argmax per window),
        the notebook's "causal regimes are known" case (cell 73);
      * "learned" — unsupervised regime discovery via the native rpcmci
        (tigramite-RPCMCI capability): windows are clustered by best-fitting
        regime VAR, then learned regimes are Hungarian-aligned to the true
        networks by optimal-F1 before scoring.

    ``pred_source`` is "graph" (binary significant links) or "val_matrix"
    (|MCI| strengths), matching the notebook's two experiment variants.
    Predictions are standardized (lag-collapsed, optionally transposed,
    diagonal zeroed) and max-normalized before compute_optimal_f1.
    """
    true_mats = []
    for g in true_graphs:
        g = np.asarray(g, dtype=np.float64)
        if g.ndim == 3:
            g = np.abs(g).sum(axis=2)
        g = (g > 0).astype(int)
        np.fill_diagonal(g, 0)
        true_mats.append(g)
    num_regimes = len(true_mats)

    def predictions_from(result):
        if result is None:
            return np.zeros_like(true_mats[0], dtype=np.float64)
        if pred_source == "graph":
            raw = _pcmci_graph_pred(result, alpha_level)
        elif pred_source == "val_matrix":
            raw = np.abs(result["val_matrix"])[:, :, 1:]
        else:
            raise ValueError(f"unsupported pred_source: {pred_source!r}")
        pred = standardized_off_diagonal_predictions(raw, transpose=transpose)
        peak = np.max(pred)
        return pred / peak if peak > 0 else pred

    if regime_source == "oracle":
        results_by_regime = {}
        for r in range(num_regimes):
            segs = _regime_segments(samples, r, min_len=tau_max)
            results_by_regime[r] = (
                pcmci(segs, tau_max=tau_max, pc_alpha=pc_alpha,
                      alpha_level=alpha_level) if segs else None)
        preds_by_regime = {r: predictions_from(results_by_regime[r])
                           for r in range(num_regimes)}
    elif regime_source == "learned":
        recs = [np.asarray(x, dtype=np.float64) for x, _ in samples]
        learned = rpcmci(recs, num_regimes, tau_max=tau_max,
                         pc_alpha=pc_alpha, alpha_level=alpha_level,
                         **(rpcmci_kwargs or {}))
        raw_preds = [predictions_from(learned["results"].get(k))
                     for k in range(num_regimes)]
        # align learned regimes to true networks: Hungarian on (1 - optF1)
        from scipy.optimize import linear_sum_assignment

        cost = np.zeros((num_regimes, num_regimes))
        for k, pred in enumerate(raw_preds):
            for r, truth in enumerate(true_mats):
                _, f1 = compute_optimal_f1(truth.ravel(), pred.ravel())
                cost[k, r] = 1.0 - f1
        rows, cols = linear_sum_assignment(cost)
        preds_by_regime = {int(r): raw_preds[int(k)]
                           for k, r in zip(rows, cols)}
    else:
        raise ValueError(f"unsupported regime_source: {regime_source!r}")

    scores = {}
    for r in range(num_regimes):
        _, f1 = compute_optimal_f1(true_mats[r].ravel(),
                                   preds_by_regime[r].ravel())
        scores[r] = f1
    vals = [scores[r] for r in range(num_regimes)]
    return {
        "optF1Scores_by_regime": scores,
        "cross_regime_mean": float(np.mean(vals)),
        # population-std SEM (ddof=0): the reference's convention everywhere
        # (notebook cell 73, eval stats summarize_values) — kept for output
        # parity even though sample-std SEM would be the textbook estimator
        "cross_regime_sem": float(np.std(vals) / np.sqrt(len(vals))),
        "preds_by_regime": preds_by_regime,
    }
