"""Cross-experiment summary condensation.

Rebuilds the summ_offDiagF1_* / plotCrossExpSummaries_* tooling
(/root/reference/evaluate/, SURVEY.md §2.7 "Summaries/plots"): condense the
``full_comparrisson_summary.pkl`` written by the cross-algorithm driver into
flat per-(dataset, algorithm) tables for the paper's headline statistic
(off-diagonal optimal F1 by default) and render the cross-experiment grid.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

__all__ = [
    "load_full_comparison_summary",
    "extract_metric_table",
    "summarize_off_diag_f1",
    "write_cross_experiment_report",
]

OFFDIAG_PARADIGM = "key_stats_estGC_normOffDiag_vs_trueGC_normOffDiag"


def load_full_comparison_summary(path):
    """Load a full_comparrisson_summary.pkl (file or containing directory)."""
    if os.path.isdir(path):
        path = os.path.join(path, "full_comparrisson_summary.pkl")
    with open(path, "rb") as f:
        return pickle.load(f)


def extract_metric_table(full_summary, paradigm=OFFDIAG_PARADIGM,
                         stat="f1_mean_across_factors"):
    """{dataset: {algorithm: value}} for one paradigm/statistic."""
    table = {}
    for dset, cv_stats in full_summary.items():
        by_alg = cv_stats.get(paradigm, {})
        table[dset] = {alg: stats.get(stat)
                       for alg, stats in by_alg.items()
                       if isinstance(stats, dict)}
    return table


def summarize_off_diag_f1(full_summary):
    """The paper's headline table: mean / median / SEM of the off-diagonal
    optimal-F1 per (dataset, algorithm) (the summ_offDiagF1_* scripts)."""
    out = {}
    for stat_suffix in ("mean", "median", "mean_std_err"):
        out[stat_suffix] = extract_metric_table(
            full_summary, OFFDIAG_PARADIGM,
            f"f1_{stat_suffix}_across_factors")
    return out


def write_cross_experiment_report(full_summary, save_root,
                                  paradigm=OFFDIAG_PARADIGM,
                                  stat="f1_mean_across_factors", plot=True):
    """Write the condensed table as CSV (+ heatmap grid) under save_root.
    Returns the table."""
    table = extract_metric_table(full_summary, paradigm, stat)
    os.makedirs(save_root, exist_ok=True)
    algs = sorted({a for d in table.values() for a in d})
    csv_path = os.path.join(save_root, f"{paradigm}__{stat}.csv")
    with open(csv_path, "w") as f:
        f.write("dataset," + ",".join(algs) + "\n")
        for dset, row in table.items():
            cells = [("" if row.get(a) is None else f"{row[a]:.6f}")
                     for a in algs]
            f.write(dset + "," + ",".join(cells) + "\n")
    if plot:
        try:
            from ..utils.plotting import plot_cross_experiment_summary_grid
            plot_cross_experiment_summary_grid(
                table, os.path.join(save_root, f"{paradigm}__{stat}.png"),
                stat, title=f"{stat} ({paradigm})")
        except ImportError:
            pass
    return table
