"""Grid-search model selection over training-history artifacts.

Rebuilds the eval_gs_* flow
(/root/reference/evaluate/eval_gs_REDCLIFF_S_CMLP_tst100hzRerun1024AvgReg_BSCgsSmooth1_dataFULL.py:26-175):
scan every run folder under a grid root for
``training_meta_data_and_hyper_parameters.pkl``, average per-factor metric
histories into per-epoch scalars, drop incomplete runs, and rank runs under
each selection criterion (minimize losses / maximize AUCs, plus summed
combinations), reporting the best run and best epoch per criterion.
"""
from __future__ import annotations

import os

import numpy as np

from ..runtime.checkpoint import read_checkpoint

__all__ = [
    "load_grid_summaries",
    "average_factor_histories",
    "filter_incomplete_runs",
    "rank_runs",
    "select_best_models",
]

# criterion -> (history key, direction).  "min" selects argmin, "max" argmax.
CRITERION_KEYS = {
    "roc_auc": ("avg_roc_auc_score_history", "max"),
    "roc_auc_OffDiag": ("avg_roc_auc_OffDiag_score_history", "max"),
    "forecasting_loss": ("avg_forecasting_loss", "min"),
    "factor_loss": ("avg_factor_loss", "min"),
    "fw_l1_penalty_history": ("avg_fw_l1_penalty_history", "min"),
    "gc_l1_history": ("avg_gc_factor_l1_history", "min"),
    "gc_cosine_sim_history": ("avg_gc_factor_cos_sim_history", "min"),
}


def load_grid_summaries(trained_models_root_path):
    """{run_folder_name: meta dict} for every run with a summary pickle
    (ref :70-76)."""
    out = {}
    for name in sorted(os.listdir(trained_models_root_path)):
        p = os.path.join(trained_models_root_path, name,
                         "training_meta_data_and_hyper_parameters.pkl")
        if os.path.isfile(p):
            # format-aware read: durable-header metas and legacy pickles
            out[name] = read_checkpoint(p)
    return out


def _mean_across_factors(history):
    """Per-epoch mean over a per-factor history laid out as either a list of
    per-epoch per-factor lists or a dict of per-factor lists (ref :83-110)."""
    if isinstance(history, dict):
        series = list(history.values())
        return [float(np.mean(t)) for t in zip(*series)]
    if history and isinstance(history[0], (list, tuple, np.ndarray)):
        return [float(np.mean(t)) for t in zip(*history)]
    return [float(x) for x in history]


def average_factor_histories(meta):
    """Attach avg_* per-epoch histories to a run's meta dict (ref :79-110).
    Missing histories yield empty lists so filter_incomplete_runs can drop
    the run."""
    out = dict(meta)

    def get(key, default=()):
        return meta.get(key, default)

    # roc histories are keyed by threshold; the reference reads entry 0.0
    for src, dst in (("roc_auc_histories", "avg_roc_auc_score_history"),
                     ("roc_auc_OffDiag_histories",
                      "avg_roc_auc_OffDiag_score_history")):
        hist = get(src, {})
        if isinstance(hist, dict):
            hist = hist.get(0.0, [])
        out[dst] = _mean_across_factors(hist) if len(hist) else []
    out["avg_fw_l1_penalty_history"] = [
        float(x) for x in get("avg_fw_l1_penalty", [])]
    out["avg_gc_factor_l1_history"] = _mean_across_factors(
        get("gc_factor_l1_loss_histories", []))
    out["avg_gc_factor_cos_sim_history"] = _mean_across_factors(
        get("gc_factor_cosine_sim_histories", {}))
    out["avg_gc_factor_deltacon0_history"] = _mean_across_factors(
        get("deltacon0_histories", []))
    out["avg_gc_factor_deltacon0_with_directed_degrees_history"] = \
        _mean_across_factors(get("deltacon0_with_directed_degrees_histories",
                                 []))
    out["avg_gc_factor_deltaffinity_history"] = _mean_across_factors(
        get("deltaffinity_histories", []))
    if "avg_forecasting_loss" in meta:
        out["avg_forecasting_loss"] = [
            float(x) for x in meta["avg_forecasting_loss"]]
    if "avg_factor_loss" in meta:
        out["avg_factor_loss"] = [float(x) for x in meta["avg_factor_loss"]]
    return out


def filter_incomplete_runs(summaries, vital_keys=("avg_forecasting_loss",
                                                  "avg_factor_loss",
                                                  "avg_gc_factor_cos_sim_history")):
    """Drop runs whose vital histories are missing or length-mismatched
    (ref :112-131)."""
    kept = {}
    for name, meta in summaries.items():
        lens = [len(meta.get(k, [])) for k in vital_keys]
        if 0 in lens or len(set(lens)) != 1:
            print(f"grid_selection: REMOVING run {name} ON ACCOUNT OF "
                  f"MISSING DATA", flush=True)
            continue
        kept[name] = meta
    return kept


def _criterion_history(meta, criterion):
    """Per-epoch history for a (possibly summed-combination) criterion
    (ref :140-175)."""
    if criterion in CRITERION_KEYS:
        key, direction = CRITERION_KEYS[criterion]
        return list(meta.get(key, [])), direction
    if "_and_" in criterion:
        parts = criterion.split("_and_")
        hists = []
        for p in parts:
            if p not in CRITERION_KEYS:
                raise ValueError(f"unknown criterion component: {p!r}")
            key, direction = CRITERION_KEYS[p]
            if direction != "min":
                raise ValueError(
                    f"combined criteria must minimize; {p!r} maximizes")
            hists.append(meta.get(key, []))
        combo = [float(sum(t)) for t in zip(*hists)]
        return combo, "min"
    raise ValueError(f"unknown criterion: {criterion!r}")


def rank_runs(summaries, criterion):
    """[(run_name, best_value, best_epoch)] sorted best-first under the
    criterion."""
    rows = []
    direction = "min"
    for name, meta in summaries.items():
        hist, direction = _criterion_history(meta, criterion)
        if not hist:
            continue
        arr = np.asarray(hist, dtype=np.float64)
        idx = int(np.argmax(arr)) if direction == "max" else int(np.argmin(arr))
        rows.append((name, float(arr[idx]), idx))
    rows.sort(key=lambda r: r[1], reverse=(direction == "max"))
    return rows


def select_best_models(trained_models_root_path,
                       selection_criteria=("forecasting_loss", "factor_loss",
                                           "gc_cosine_sim_history",
                                           "forecasting_loss_and_factor_loss_and_gc_cosine_sim_history")):
    """End-to-end grid selection (the eval_gs script flow): returns
    {criterion: {"ranking": [...], "best_run": name, "best_epoch": int}}."""
    raw = load_grid_summaries(trained_models_root_path)
    summaries = {k: average_factor_histories(v) for k, v in raw.items()}
    summaries = filter_incomplete_runs(summaries)
    out = {}
    for criterion in selection_criteria:
        ranking = rank_runs(summaries, criterion)
        out[criterion] = {
            "ranking": ranking,
            "best_run": ranking[0][0] if ranking else None,
            "best_epoch": ranking[0][2] if ranking else None,
        }
    return out
