"""Factor-score sweeps across recordings.

Rebuilds obtain_factor_score_weightings/classifications_across_recording
(/root/reference/general_utils/misc.py:57-82) and
evaluate_avg_factor_scoring_across_recordings
(/root/reference/evaluate/eval_utils.py:953-1092): slide the trained
embedder across a recording to trace per-state factor scores, average the
traces per dominant state, and plot them against the label traces.

TPU idiom: the reference loops one embedder call per timestep; here all
sliding windows batch into ONE embedder call (windows stacked on the batch
axis), so a T-step sweep is a single jit-compatible forward pass.
"""
from __future__ import annotations

import os

import numpy as np

__all__ = [
    "factor_score_sweep",
    "average_factor_scoring_by_state",
    "evaluate_avg_factor_scoring_across_recordings",
]


def _sliding_windows(recording, history, num_steps):
    """(T, C) -> (num_steps, history, C) windows ending at steps
    history..history+num_steps-1 (a strided view, no copies)."""
    recording = np.asarray(recording)
    view = np.lib.stride_tricks.sliding_window_view(
        recording, history, axis=0)          # (T-history+1, C, history)
    return np.transpose(view[:num_steps], (0, 2, 1))


def factor_score_sweep_both(model, params, recording,
                            num_supervised_factors, num_timesteps_to_score,
                            num_timesteps_in_input_history):
    """(weightings, classifications) traces, each
    (num_supervised_factors, num_timesteps_to_score), from ONE batched
    embedder pass over all sliding windows (ref misc.py:57-82 loops one
    embedder call per step and once per trace kind)."""
    recording = np.asarray(recording)
    if recording.ndim == 3:
        assert recording.shape[0] == 1
        recording = recording[0]
    assert recording.shape[0] >= (num_timesteps_to_score
                                  + num_timesteps_in_input_history)
    windows = _sliding_windows(recording, num_timesteps_in_input_history,
                               num_timesteps_to_score)
    weightings, class_preds = model._embed(params, windows)
    w = np.asarray(weightings)[:, :num_supervised_factors].T
    c = w if class_preds is None \
        else np.asarray(class_preds)[:, :num_supervised_factors].T
    return w, c


def factor_score_sweep(model, params, recording, num_supervised_factors,
                       num_timesteps_to_score, num_timesteps_in_input_history,
                       kind="weightings"):
    """(num_supervised_factors, num_timesteps_to_score) trace of embedder
    outputs across a recording (ref misc.py:57-82).

    kind: "weightings" (factor mixture weights) or "classifications"
    (supervised class logits/predictions).
    """
    w, c = factor_score_sweep_both(model, params, recording,
                                   num_supervised_factors,
                                   num_timesteps_to_score,
                                   num_timesteps_in_input_history)
    return w if kind == "weightings" else c


def _dominant_state(Y):
    """Window-level dominant state from a label array: (S, T) traces use the
    per-step argmax mode, flat labels the argmax (ref eval_utils.py:991-1011
    label-shape branches)."""
    Y = np.asarray(Y)
    while Y.ndim > 2 and Y.shape[-1] == 1:
        Y = Y[..., 0]
    if Y.ndim == 2 and Y.shape[1] > 1:
        per_step = np.argmax(Y, axis=0)
        vals, counts = np.unique(per_step, return_counts=True)
        return int(vals[np.argmax(counts)])
    return int(np.argmax(Y))


def average_factor_scoring_by_state(model, params, dataset, num_states,
                                    num_timesteps_to_score,
                                    num_timesteps_in_input_history,
                                    max_recordings_per_state=100):
    """{state: {"weightings": (K, T') mean trace, "classifications": ...,
    "count": n}} averaged over recordings whose dominant label is the state
    (ref eval_utils.py:953-1092 without the plotting side effects)."""
    sums = {s: {"weightings": None, "classifications": None, "count": 0}
            for s in range(num_states)}
    for idx in range(len(dataset.X)):
        x = dataset.X[idx]
        y = dataset.Y[idx]
        state = _dominant_state(y)
        if state >= num_states:
            continue
        if sums[state]["count"] >= max_recordings_per_state:
            continue
        w, c = factor_score_sweep_both(model, params, x, num_states,
                                       num_timesteps_to_score,
                                       num_timesteps_in_input_history)
        slot = sums[state]
        slot["weightings"] = w if slot["weightings"] is None \
            else slot["weightings"] + w
        slot["classifications"] = c if slot["classifications"] is None \
            else slot["classifications"] + c
        slot["count"] += 1
    for s, slot in sums.items():
        if slot["count"]:
            slot["weightings"] = slot["weightings"] / slot["count"]
            slot["classifications"] = slot["classifications"] / slot["count"]
    return sums


def evaluate_avg_factor_scoring_across_recordings(
        model, params, dataset, num_states, num_timesteps_to_score,
        num_timesteps_in_input_history, save_root_path, labels=None,
        max_recordings_per_state=100):
    """Average the factor-score traces per state and plot one figure per
    state (the reference's HC/OF/TS trace panels)."""
    summary = average_factor_scoring_by_state(
        model, params, dataset, num_states, num_timesteps_to_score,
        num_timesteps_in_input_history,
        max_recordings_per_state=max_recordings_per_state)
    try:
        from ..utils.plotting import plot_state_score_traces
    except ImportError:
        return summary
    os.makedirs(save_root_path, exist_ok=True)
    for s, slot in summary.items():
        if slot["count"] == 0:
            continue
        name = labels[s] if labels else f"state {s}"
        plot_state_score_traces(
            slot["weightings"],
            os.path.join(save_root_path,
                         f"avg_factor_weightings_state_{s}.png"),
            labels=labels, title=f"mean factor weightings | dominant {name}")
    return summary
