"""System-level estimation evaluation drivers (single model family).

Rebuilds the two ~600-line drivers at the bottom of the reference's eval
machinery — `perform_system_level_estimation_evaluation_of_cv_model`
(/root/reference/evaluate/eval_utils.py:1093) and
`perform_system_level_estimation_evaluation_of_gs` (:1692): walk a trained-
models root, pair every run with its dataset's true factor graphs, read out
GC estimates, and score each factor with the key similarity battery (cosine
similarity, MSE, directed/undirected DeltaCon0, DeltaCon0 with directed
degrees, Deltaffinity, ROC-AUC) on both the normal and transposed views,
aggregating mean/std across factors within a fold and then across folds.
Artifacts pickle under the reference's summary layout so downstream tooling
(grid selection, analysis reports) reads them uniformly.

Options mirror the reference's: Hungarian sorting of unsupervised estimates
onto the ground truth (``sort_unsupervised_ests``), averaging all estimated
graphs into one (``average_estimated_graphs_together``), excluding self
connections, and an identity-matrix baseline
(``evaluate_identity_baseline``).
"""
from __future__ import annotations

import os
import pickle
import re

import numpy as np

from ..utils.config import load_true_gc_factors
from ..utils.metrics import (
    compute_cosine_similarity,
    compute_mse,
    deltacon0,
    deltacon0_with_directed_degrees,
    deltaffinity,
    roc_auc,
)
from ..utils.misc import sort_unsupervised_estimates
from .gc_estimates import get_model_gc_estimates
from .model_io import load_model_for_eval

__all__ = [
    "key_similarity_stats",
    "evaluate_fold_system_level",
    "evaluate_system_level_cv",
    "evaluate_system_level_gs",
]


def key_similarity_stats(est, true, eps=0.1, in_degree_coeff=1.0,
                         out_degree_coeff=1.0, max_path_length=None):
    """The reference's per-factor system-level battery (ref :1286-1364):
    cosine sim, MSE, directed + undirected DeltaCon0, DeltaCon0 with
    directed degrees, Deltaffinity (all called as metric(true, est)), and
    ROC-AUC of the est scores against the binarized truth."""
    out = {
        "cos_sim": compute_cosine_similarity(true, est),
        "mse": compute_mse(true, est),
        "dir_deltacon0": deltacon0(true, est, eps),
        "undir_deltacon0": deltacon0(true, est, eps,
                                     make_graphs_undirected=True),
        "deltacon0_wDD": deltacon0_with_directed_degrees(
            true, est, eps, in_degree_coeff=in_degree_coeff,
            out_degree_coeff=out_degree_coeff),
        "deltaffinity": deltaffinity(true, est, eps,
                                     max_path_length=max_path_length),
    }
    labels = (true.ravel() > 0).astype(int)
    try:
        out["roc_auc"] = (roc_auc(labels, est.ravel())
                          if 0 < labels.sum() < len(labels) else 0.5)
    except ValueError:
        out["roc_auc"] = np.nan
    return out


METRIC_KEYS = ("cos_sim", "mse", "dir_deltacon0", "undir_deltacon0",
               "deltacon0_wDD", "deltaffinity", "roc_auc")


def evaluate_fold_system_level(est_gcs, true_gcs, eps=0.1,
                               in_degree_coeff=1.0, out_degree_coeff=1.0,
                               max_path_length=None,
                               exclude_self_connections=False,
                               sort_unsupervised_ests=False,
                               cost_criteria="CosineSimilarity",
                               unsupervised_start_index=0,
                               average_estimated_graphs_together=False,
                               evaluate_identity_baseline=False):
    """Score one run's per-factor estimates against its true graphs on the
    normal and transposed views.  Returns {"normal": {metric: [per-factor]},
    "transposed": {...}}.

    Operation order matches the reference exactly (ref :1249-1283):
    Hungarian sorting runs on the RAW (possibly lagged) estimates; the
    identity baseline then overwrites them (and skips normalization); self
    connections are excluded from the ESTIMATES only — the truth is never
    masked or normalized; estimates normalize by their full-tensor max
    BEFORE lag-summing; averaging applies only when there are more
    estimates than truths (which requires exactly one truth)."""
    ests = [np.asarray(e, dtype=np.float64) for e in est_gcs]
    trues = [np.asarray(t, dtype=np.float64) for t in true_gcs]
    if sort_unsupervised_ests:
        # the reference sorts on the RAW tensors (ref :1250); when a
        # non-lagged estimator meets lagged truths the raw shapes differ,
        # so the assignment cost falls back to lag-summed views while the
        # permutation still applies to the raw estimates
        same_dims = all(e.shape == t.shape for e, t in zip(ests, trues))
        cost_ests = ests if same_dims else [
            e.sum(axis=2) if e.ndim == 3 else e for e in ests]
        cost_trues = trues if same_dims else [
            t.sum(axis=2) if t.ndim == 3 else t for t in trues]
        _, matched_est, matched_true = sort_unsupervised_estimates(
            cost_ests, cost_trues, cost_criteria=cost_criteria,
            unsupervised_start_index=unsupervised_start_index,
            return_sorting_inds=True)
        u = unsupervised_start_index
        # slots sized by TRUTH count (as in misc.sort_unsupervised_estimates:
        # a truth index from the assignment can exceed the estimate count)
        tail = [None] * (len(trues) - u)
        for est_ind, gt_ind in zip(matched_est, matched_true):
            tail[gt_ind] = ests[u + est_ind]
        leftover = [ests[u + i] for i in range(len(ests) - u)
                    if i not in matched_est]
        # keep None placeholders for unmatched truths so positional pairing
        # with `trues` stays aligned; the scoring loop skips them
        ests = ests[:u] + tail + leftover
    if evaluate_identity_baseline:
        # overwrite with identity, keeping each estimate's rank (ref :1251)
        ests = [None if e is None
                else np.eye(e.shape[0])[:, :, None] if e.ndim == 3
                else np.eye(e.shape[0]) for e in ests]
    if exclude_self_connections:
        # estimates only — the reference never masks the truth (ref :1255)
        ests = [None if e is None
                else e * (1.0 - (np.eye(e.shape[0])[:, :, None] if e.ndim == 3
                                 else np.eye(e.shape[0]))) for e in ests]
    if not evaluate_identity_baseline:
        # full-tensor max BEFORE lag-summing (ref :1260); zero-max guarded
        # (the reference would emit NaNs there)
        ests = [None if e is None
                else e / np.max(e) if np.max(e) > 0 else e for e in ests]
    live_ests = [e for e in ests if e is not None]
    if average_estimated_graphs_together and len(live_ests) > len(trues):
        assert len(trues) == 1, (
            "averaging estimates together requires exactly one true graph "
            "(ref :1265)")
        ests = [np.mean(live_ests, axis=0)]

    out = {"normal": {k: [] for k in METRIC_KEYS},
           "transposed": {k: [] for k in METRIC_KEYS}}
    for true_gc, gc_est in zip(trues, ests):
        if gc_est is None:  # truth left unmatched by the Hungarian sort
            continue
        # lag-summed comparison only, for fairness between lagged and
        # non-lagged estimators (ref :1277-1280)
        if true_gc.ndim == 3:
            true_gc = true_gc.sum(axis=2)
        if gc_est.ndim == 3:
            gc_est = gc_est.sum(axis=2)
        for view, est in (("normal", gc_est), ("transposed", gc_est.T)):
            stats = key_similarity_stats(
                est, true_gc, eps=eps, in_degree_coeff=in_degree_coeff,
                out_degree_coeff=out_degree_coeff,
                max_path_length=max_path_length)
            for k in METRIC_KEYS:
                out[view][k].append(stats[k])
    return out


def _fold_token(name):
    m = re.search(r"fold[_]?(\d+)", name)
    return int(m.group(1)) if m else None


def _aggregate_folds(fold_stats):
    """{view: {metric: {"by_fold": {fold: [per-factor]}, "fold_means": [...],
    "fold_std_devs": [...], "cross_fold_mean", "cross_fold_std_dev"}}}.
    Std devs are population (ddof=0), the reference's convention."""
    out = {}
    for view in ("normal", "transposed"):
        out[view] = {}
        for k in METRIC_KEYS:
            by_fold = {f: s[view][k] for f, s in fold_stats.items()}
            means = [float(np.mean(v)) for v in by_fold.values() if v]
            stds = [float(np.std(v)) for v in by_fold.values() if v]
            out[view][k] = {
                "by_fold": by_fold,
                "fold_means": means,
                "fold_std_devs": stds,
                "cross_fold_mean": float(np.mean(means)) if means else None,
                "cross_fold_std_dev": float(np.std(means)) if means else None,
            }
    return out


def _true_graphs_from_args(data_args_file, model_type):
    return load_true_gc_factors(data_args_file, model_type=model_type)


def evaluate_system_level_cv(model_type, trained_models_root_path,
                             cv_split_names, files_of_cached_data_args,
                             save_dir, X_by_split=None, **options):
    """The CV-experiment driver (ref :1093-1690): for every cv split, match
    each fold's run directory (``final_best_model.bin`` present, fold token
    in the name) to its data cached-args file, score it, and aggregate
    across folds.  Writes
    ``<save_dir>/<split>_system_level_eval_summary.pkl`` per split and
    returns {split: aggregated stats}.

    ``options`` pass through to :func:`evaluate_fold_system_level` (plus
    ``eps``/degree coefficients). ``X_by_split`` supplies eval windows for
    families whose GC readout is data-dependent."""
    os.makedirs(save_dir, exist_ok=True)
    results = {}
    for split in cv_split_names:
        run_dirs = sorted(
            os.path.join(trained_models_root_path, d)
            for d in os.listdir(trained_models_root_path)
            if split in d
            and os.path.isdir(os.path.join(trained_models_root_path, d))
            and "final_best_model.bin" in os.listdir(
                os.path.join(trained_models_root_path, d)))
        args_files = sorted(f for f in files_of_cached_data_args
                            if split in os.path.basename(f))
        args_by_fold = {_fold_token(os.path.basename(f)): f
                        for f in args_files}
        fold_stats = {}
        for pos, run_dir in enumerate(run_dirs):
            fold = _fold_token(os.path.basename(run_dir))
            data_args = args_by_fold.get(fold)
            if data_args is None:
                if len(args_files) == 1:
                    data_args = args_files[0]
                else:
                    print(f"evaluate_system_level_cv: skipping {run_dir}: "
                          f"no data args for fold {fold}", flush=True)
                    continue
            true_gcs = _true_graphs_from_args(data_args, model_type)
            loaded = load_model_for_eval(run_dir)
            model, params = loaded[0], loaded[1]
            X = None if X_by_split is None else X_by_split.get(split)
            est_gcs = get_model_gc_estimates(model, params, model_type,
                                             len(true_gcs), X=X)
            # token-less run dirs get a position-derived string key so they
            # can never collide with a real fold's integer key; duplicate
            # fold tokens (e.g. a rerun directory) keep both results under
            # disambiguated keys instead of silently overwriting
            key = fold if fold is not None else f"pos_{pos}"
            if key in fold_stats:
                print(f"evaluate_system_level_cv: duplicate run for fold "
                      f"{key!r} ({run_dir}); keeping both", flush=True)
                key = f"{key}_pos{pos}"
            fold_stats[key] = evaluate_fold_system_level(est_gcs, true_gcs,
                                                         **options)
        agg = _aggregate_folds(fold_stats)
        results[split] = agg
        with open(os.path.join(save_dir,
                               f"{split}_system_level_eval_summary.pkl"),
                  "wb") as f:
            pickle.dump(agg, f)
    return results


def evaluate_system_level_gs(model_type, trained_models_root_path,
                             true_gc_factors, save_dir, X=None, **options):
    """The grid-search driver (ref :1692+): score every completed run under
    a grid root against ONE dataset's true factor graphs, so selection
    criteria can be compared against realized GC quality.  Writes
    ``<save_dir>/gs_system_level_eval_summary.pkl``; returns
    {run_name: {"normal": ..., "transposed": ...}}."""
    os.makedirs(save_dir, exist_ok=True)
    results = {}
    for d in sorted(os.listdir(trained_models_root_path)):
        run_dir = os.path.join(trained_models_root_path, d)
        if not (os.path.isdir(run_dir)
                and "final_best_model.bin" in os.listdir(run_dir)):
            continue
        loaded = load_model_for_eval(run_dir)
        model, params = loaded[0], loaded[1]
        est_gcs = get_model_gc_estimates(model, params, model_type,
                                         len(true_gc_factors), X=X)
        results[d] = evaluate_fold_system_level(est_gcs, true_gc_factors,
                                                **options)
    with open(os.path.join(save_dir, "gs_system_level_eval_summary.pkl"),
              "wb") as f:
        pickle.dump(results, f)
    return results
