"""Cross-algorithm system-level comparison driver.

One parameterized driver replacing the reference's 19 near-identical
eval_sysOptF1_crossAlg_* scripts (canonical walk-through:
/root/reference/evaluate/eval_sysOptF1_crossAlg_d4IC_HSNR_bCgsParsim_REDCSmovNEWcMLP.py:15-322):
for every (cv-dataset × fold × algorithm) it locates the trained run directory
by the shared folder-name convention, loads the artifact, reads per-factor GC
estimates, scores the three optimal-F1 stat paradigms per factor, and
aggregates mean/median/std/SEM across factors and then folds, writing
``results_summary.pkl`` per cv-dataset and a ``full_comparrisson_summary.pkl``
at the root (the reference's artifact names, kept for tooling parity).
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from .gc_estimates import get_model_gc_estimates
from .model_io import load_model_for_eval
from .stats import summarize_values, three_view_optimal_f1_stats

__all__ = [
    "ALL_POSSIBLE_ALGORITHMS",
    "find_run_directory",
    "evaluate_algorithm_on_fold",
    "run_cross_algorithm_comparison",
    "read_in_true_causal_graphs_for_all_datasets",
]


def read_in_true_causal_graphs_for_all_datasets(dataset_names,
                                                files_of_cached_data_args,
                                                data_vis_root_save_path=None):
    """Load every dataset's true per-factor GC tensors from its cached-args
    file, optionally writing ground-truth visualization folders
    (ref eval_utils.py:25-42). Returns [per-dataset [factor tensors]] in
    dataset order."""
    from ..utils.config import load_true_gc_factors

    true_causal_graphs = []
    for dset_name, dset_args in zip(dataset_names,
                                    files_of_cached_data_args):
        factors = load_true_gc_factors(dset_args)
        if data_vis_root_save_path is not None:
            vis_dir = os.path.join(data_vis_root_save_path, dset_name)
            os.makedirs(vis_dir, exist_ok=True)
            try:
                from ..utils.plotting import \
                    plot_gc_est_comparisons_by_factor
                plot_gc_est_comparisons_by_factor(
                    factors, None,
                    os.path.join(vis_dir, "true_gc_factors.png"))
            except ImportError:
                pass
        true_causal_graphs.append(factors)
    return true_causal_graphs

# ref eval_sysOptF1...py:75-87
ALL_POSSIBLE_ALGORITHMS = [
    "REDCLIFF_S_CMLP",
    "REDCLIFF_S_CLSTM",
    "REDCLIFF_S_DGCNN",
    "CMLP",
    "CLSTM",
    "DGCNN",
    "DCSFA",
    "DYNOTEARS_Stochastic",
    "DYNOTEARS_Vanilla",
    "NAVAR_CLSTM",
    "NAVAR_CMLP",
]


def select_algorithm_root(alg_name, root_paths):
    """Resolve the one trained-models root matching an algorithm name,
    with the reference's alias edge cases (ref :126-141)."""
    if alg_name in ("CMLP", "CLSTM", "DGCNN"):
        cands = [x for x in root_paths
                 if alg_name in x and "REDCLIFF" not in x and "NAVAR" not in x]
    else:
        cands = [x for x in root_paths if alg_name in x]
    if len(cands) != 1:
        raise ValueError(
            f"expected exactly one trained-models root for {alg_name!r}, "
            f"found {cands!r} in {root_paths!r}")
    return cands[0]


def find_run_directory(alg_root, cv_dset_name, fold_num):
    """Locate the single run dir for (dataset, fold) by folder-name
    convention (ref :143-153)."""
    cands = [
        os.path.join(alg_root, x) for x in os.listdir(alg_root)
        if os.path.isdir(os.path.join(alg_root, x))
        and cv_dset_name in x and f"fold{fold_num}" in x
    ]
    if len(cands) != 1:
        raise ValueError(
            f"expected exactly one run dir for ({cv_dset_name!r}, "
            f"fold {fold_num}) under {alg_root!r}, found {cands!r}")
    return cands[0]


def evaluate_algorithm_on_fold(run_dir, alg_name, true_gcs, X=None):
    """Per-factor three-view optimal-F1 stats + cross-factor summaries for one
    trained run (ref :169-237). Returns the alg_level_stats dict."""
    loaded = load_model_for_eval(run_dir)
    model, params = loaded[0], loaded[1]
    estimated_gcs = get_model_gc_estimates(model, params, alg_name,
                                           len(true_gcs), X=X)
    alg_level_stats = {}
    for factor_id, (est, true) in enumerate(zip(estimated_gcs, true_gcs)):
        alg_level_stats[f"factor_{factor_id}"] = \
            three_view_optimal_f1_stats(est, true)

    # cross-factor aggregation (ref :218-237)
    paradigms = {}
    for f_key, f_stats in alg_level_stats.items():
        for paradigm, stats in f_stats.items():
            for stat_key, val in stats.items():
                paradigms.setdefault(paradigm, {}).setdefault(
                    stat_key, []).append(val)
    for paradigm, stat_lists in paradigms.items():
        assert paradigm not in alg_level_stats
        alg_level_stats[paradigm] = {}
        for stat_key, vals in stat_lists.items():
            s = summarize_values(vals)
            alg_level_stats[paradigm][
                f"{stat_key}_vals_across_factors"] = s["vals"]
            alg_level_stats[paradigm][
                f"{stat_key}_mean_across_factors"] = s["mean"]
            alg_level_stats[paradigm][
                f"{stat_key}_median_across_factors"] = s["median"]
            alg_level_stats[paradigm][
                f"{stat_key}_std_dev_across_factors"] = s["std_dev"]
            alg_level_stats[paradigm][
                f"{stat_key}_mean_std_err_across_factors"] = s["mean_std_err"]
    return alg_level_stats


def run_cross_algorithm_comparison(root_paths_to_trained_models,
                                   true_causal_graphs, save_root_path,
                                   num_folds, algorithms=None, plot=False,
                                   eval_inputs=None):
    """Full comparison flow (ref :96-322).

    Args:
      root_paths_to_trained_models: list of per-algorithm trained-model roots.
      true_causal_graphs: {cv_dset_name: {fold: [true GC per factor]}}.
      save_root_path: output root; per-cv summaries and the full summary
        pickle land here in the reference layout.
      num_folds: folds per cv dataset.
      algorithms: explicit algorithm list; default = all recognized in roots
        (ref :90-94).
      plot: when True and utils.plotting is importable, emit the scatter/SEM
        comparison figures.
      eval_inputs: optional {cv_dset_name: {fold: X}} signal windows for
        families whose GC readout is data-dependent (NAVAR contribution
        statistics, conditional REDCLIFF modes).
    """
    if algorithms is None:
        # an algorithm participates iff its root resolves unambiguously
        # (ref :90-94, with the alias disambiguation of :126-141 applied)
        algorithms = []
        for a in ALL_POSSIBLE_ALGORITHMS:
            try:
                select_algorithm_root(a, root_paths_to_trained_models)
                algorithms.append(a)
            except ValueError:
                continue
    os.makedirs(save_root_path, exist_ok=True)
    full_summary = {}
    for cv_dset_name, folds in true_causal_graphs.items():
        cv_level_stats = {}
        cv_save = os.path.join(save_root_path, f"cv_{cv_dset_name}")
        os.makedirs(cv_save, exist_ok=True)
        for f_num in range(num_folds):
            true_gcs = folds[f_num]
            fold_X = None
            if eval_inputs is not None:
                fold_X = eval_inputs.get(cv_dset_name, {}).get(f_num)
            fold_level_stats = {}
            for alg_name in algorithms:
                alg_root = select_algorithm_root(
                    alg_name, root_paths_to_trained_models)
                run_dir = find_run_directory(alg_root, cv_dset_name, f_num)
                fold_level_stats[alg_name] = evaluate_algorithm_on_fold(
                    run_dir, alg_name, true_gcs, X=fold_X)
            cv_level_stats[f"fold_{f_num}_details"] = fold_level_stats
            # accumulate per-(paradigm, alg) value lists across folds
            for alg_name, alg_stats in fold_level_stats.items():
                for paradigm, stats in alg_stats.items():
                    if "factor_" in paradigm:
                        continue
                    pd = cv_level_stats.setdefault(paradigm, {}).setdefault(
                        alg_name, {})
                    for stat_key, val in stats.items():
                        if not stat_key.endswith("_vals_across_factors"):
                            continue
                        pd.setdefault(stat_key, []).extend(val)
        # cross-fold aggregation (ref :274-299)
        for paradigm, by_alg in cv_level_stats.items():
            if "_vs_" not in paradigm:
                continue
            for alg_name, stat_map in by_alg.items():
                for stat_val_key in list(stat_map.keys()):
                    if not stat_val_key.endswith("_vals_across_factors"):
                        continue
                    stat_key = stat_val_key[: -len("_vals_across_factors")]
                    s = summarize_values(stat_map[stat_val_key])
                    stat_map[f"{stat_key}_mean_across_factors"] = s["mean"]
                    stat_map[f"{stat_key}_median_across_factors"] = s["median"]
                    stat_map[f"{stat_key}_std_dev_across_factors"] = s["std_dev"]
                    stat_map[f"{stat_key}_mean_std_err_across_factors"] = \
                        s["mean_std_err"]
        if plot:
            _plot_cv_summaries(cv_level_stats, algorithms, cv_save)
        with open(os.path.join(cv_save, "results_summary.pkl"), "wb") as f:
            pickle.dump(cv_level_stats, f)
        full_summary[cv_dset_name] = cv_level_stats
    with open(os.path.join(save_root_path, "full_comparrisson_summary.pkl"),
              "wb") as f:
        pickle.dump(full_summary, f)
    return full_summary


def _plot_cv_summaries(cv_level_stats, algorithms, cv_save):
    try:
        from ..utils.plotting import \
            make_scatter_and_std_err_of_mean_plot_overlay
    except ImportError:
        return
    for paradigm, by_alg in cv_level_stats.items():
        if "_vs_" not in paradigm:
            continue
        stat_val_keys = set()
        for alg in by_alg.values():
            stat_val_keys |= {k for k in alg
                              if k.endswith("_vals_across_factors")}
        for svk in sorted(stat_val_keys):
            results = {a: by_alg[a].get(svk, []) for a in algorithms
                       if a in by_alg}
            make_scatter_and_std_err_of_mean_plot_overlay(
                results,
                os.path.join(cv_save,
                             f"factor_level_{paradigm}_{svk}_by_algorithm.png"),
                f"Comparing Factor-Level {svk[:-len('_vals_across_factors')]} "
                f"Between Algorithms", "Algorithm", svk, alpha=0.5)
