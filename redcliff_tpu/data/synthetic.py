"""Synthetic sVAR benchmark generator — dataset tool and test oracle.

Semantics-parity rebuild of /root/reference/data/data_utils.py: a 2-lag
sinusoid-driven (optionally nonlinear) VAR whose per-state rollouts are superimposed
with random linearly-interpolated activation weights, plus the random lagged-DAG
factory with orthogonality/connected-component constraints
(ref data_utils.py:47-240, 243-353).

Two implementations share one parameterization:

* ``rollout_np`` / ``generate_synthetic_data_np`` — host/numpy, loop-per-step,
  mirroring the reference for golden tests and CPU curation.
* ``rollout_scan`` / ``generate_synthetic_batch`` — the TPU path: the per-step
  update is a dense (D, D, L) elementwise-activated contraction inside
  ``jax.lax.scan``; whole batches are drawn with ``vmap`` from pre-split PRNG keys,
  so curation of an entire dataset is one jit'd program instead of a SLURM array.

Per-edge nonlinearities are encoded as an integer code tensor ``act_codes`` of shape
(D, D, L): 0 = identity, 1 = min(x, 0), 2 = max(x, 0) — the three activations the
reference curation driver uses (ref currate_...etNL.py:21,272).
"""
from __future__ import annotations

import random as _pyrandom
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from redcliff_tpu.utils.metrics import get_number_of_connected_components

ACT_IDENTITY, ACT_MIN0, ACT_MAX0 = 0, 1, 2


def _apply_act_np(x, codes):
    out = np.where(codes == ACT_MIN0, np.minimum(x, 0.0), x)
    out = np.where(codes == ACT_MAX0, np.maximum(x, 0.0), out)
    return out


def _apply_act(x, codes):
    out = jnp.where(codes == ACT_MIN0, jnp.minimum(x, 0.0), x)
    out = jnp.where(codes == ACT_MAX0, jnp.maximum(x, 0.0), out)
    return out


def _step_matrices(A, base_freqs):
    """Fold the self-connection dynamics into per-lag dense matrices.

    The reference treats diagonal entries specially (ref data_utils.py:69-78):
    lag-1 self term is A[i,i,0] * 2cos(2*pi*f_i) * x_{t-1,i} and lag-2 self term is
    -A[i,i,1] * x_{t-2,i}. Scaling the diagonal ahead of time makes the whole step
    one elementwise-activated (D, D) product per lag.
    """
    A = np.asarray(A, dtype=np.float64)
    D = A.shape[0]
    f = np.asarray(base_freqs, dtype=np.float64).reshape(D)
    M1 = A[:, :, 0].copy()
    M1[np.arange(D), np.arange(D)] *= 2.0 * np.cos(2.0 * np.pi * f)
    M2 = A[:, :, 1].copy() if A.shape[2] > 1 else np.zeros((D, D))
    M2[np.arange(D), np.arange(D)] *= -1.0
    return M1, M2


def nvar_step_np(x_tm1, x_tm2, M1, M2, act_codes, innovation, num_lags=2):
    """One step of the nonlinear VAR given pre-folded matrices (host version)."""
    pre1 = M1 * x_tm1[None, :]
    contrib = _apply_act_np(pre1, act_codes[:, :, 0]).sum(axis=1)
    if num_lags > 1:
        pre2 = M2 * x_tm2[None, :]
        contrib = contrib + _apply_act_np(pre2, act_codes[:, :, 1]).sum(axis=1)
    return contrib + innovation


def rollout_np(A, act_codes, base_freqs, noise_mu, noise_var, innovation_amp,
               recording_length, burnin_period, rng):
    """Host rollout matching ref data_utils.py:88-125 step-for-step.

    Innovations only enter through the self-connection branch, i.e. once per node
    per step. Returns (D, recording_length).
    """
    A = np.asarray(A, dtype=np.float64)
    D = A.shape[0]
    M1, M2 = _step_matrices(A, base_freqs)
    amp = np.asarray(innovation_amp, dtype=np.float64).reshape(D)
    mu = np.asarray(noise_mu, dtype=np.float64).reshape(D)
    var = np.asarray(noise_var, dtype=np.float64).reshape(D)
    avg_amp = float(np.mean(amp))

    x0 = rng.uniform(-avg_amp, avg_amp, D)
    innov = amp * rng.normal(mu, var)
    x1 = nvar_step_np(x0, x0, M1, M2, act_codes, innov, num_lags=1)
    samp = [x0, x1]
    for _ in range(recording_length + burnin_period):
        innov = amp * rng.normal(mu, var)
        samp.append(nvar_step_np(samp[-1], samp[-2], M1, M2, act_codes, innov))
    return np.stack(samp[2 + burnin_period :], axis=1)


# ---------------------------------------------------------------------------
# Device (lax.scan) rollout
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("recording_length", "burnin_period"))
def rollout_scan(key, M1, M2, act_codes, noise_mu, noise_var, innovation_amp,
                 recording_length, burnin_period):
    """lax.scan rollout of the 2-lag nonlinear VAR; returns (recording_length, D).

    Same dynamics as ``rollout_np`` with jax-PRNG innovations. Pre-folded
    (M1, M2) come from ``_step_matrices``.
    """
    D = M1.shape[0]
    amp = innovation_amp.reshape(D)
    mu = noise_mu.reshape(D)
    var = noise_var.reshape(D)
    avg_amp = jnp.mean(amp)
    k0, k1, kseq = jax.random.split(key, 3)

    x0 = jax.random.uniform(k0, (D,), minval=-avg_amp, maxval=avg_amp)
    innov1 = amp * (mu + var * jax.random.normal(k1, (D,)))
    pre1 = M1 * x0[None, :]
    x1 = _apply_act(pre1, act_codes[:, :, 0]).sum(axis=1) + innov1

    total = recording_length + burnin_period
    noise = mu[None, :] + var[None, :] * jax.random.normal(kseq, (total, D))

    def step(carry, eps):
        x_tm1, x_tm2 = carry
        c1 = _apply_act(M1 * x_tm1[None, :], act_codes[:, :, 0]).sum(axis=1)
        c2 = _apply_act(M2 * x_tm2[None, :], act_codes[:, :, 1]).sum(axis=1)
        x_t = c1 + c2 + amp * eps
        return (x_t, x_tm1), x_t

    _, xs = jax.lax.scan(step, (x1, x0), noise)
    return xs[burnin_period:]


@partial(jax.jit, static_argnames=("recording_length", "burnin_period", "label_type",
                                   "num_labeled_sys_states", "noise_type"))
def generate_synthetic_batch(key, M1_stack, M2_stack, act_codes_stack, base_params,
                             recording_length, burnin_period, num_labeled_sys_states,
                             label_type="Oracle", noise_type="white", noise_amp=0.1,
                             batch_size_key=None):
    """Draw one sample: superimpose every system state's rollout under random
    linear activation ramps, label per-step, add measurement noise
    (ref data_utils.py:137-240). vmap over split keys for a batch.

    Args:
      M1_stack, M2_stack, act_codes_stack: (S, D, D[, L]) stacked per-state systems.
      base_params: dict with 'noise_mu', 'noise_var', 'innovation_amp' (each (D,)).
    Returns:
      x: (recording_length, D) float32, y: (num_labels, recording_length) float32
      where num_labels = num_labeled_sys_states (+1 if unsupervised states exist).
    """
    S, D = M1_stack.shape[0], M1_stack.shape[1]
    n_extra = S - num_labeled_sys_states
    num_labels = num_labeled_sys_states + (1 if n_extra > 0 else 0)
    keys = jax.random.split(key, S + 2)
    amp = base_params["innovation_amp"].reshape(D)
    avg_amp = jnp.mean(amp)

    def one_state(i, carry):
        x_acc, y_acc = carry
        sig = rollout_scan(
            keys[i], M1_stack[i], M2_stack[i], act_codes_stack[i],
            base_params["noise_mu"], base_params["noise_var"], amp,
            recording_length, burnin_period,
        )  # (T, D)
        kw = jax.random.fold_in(keys[i], 1)
        w0, w1 = jax.random.uniform(kw, (2,))
        ramp = jnp.linspace(w0, w1, recording_length)
        x_acc = x_acc + sig * ramp[:, None]
        # supervised states write their own label row; the rest pool into the last row
        row = jnp.where(i < num_labels - 1, i, num_labels - 1)
        y_acc = y_acc.at[row].add(ramp)
        return x_acc, y_acc

    x = jnp.zeros((recording_length, D))
    y = jnp.zeros((num_labels, recording_length))
    x, y = jax.lax.fori_loop(0, S, one_state, (x, y))
    if n_extra > 0:
        y = y.at[num_labels - 1].multiply(1.0 / (S - (num_labels - 1)))

    if label_type == "OneHot":
        hot = jnp.argmax(y, axis=0)
        y = jax.nn.one_hot(hot, num_labels, axis=0)
    elif label_type != "Oracle":
        raise ValueError(f"Unrecognized label_type={label_type}")

    if noise_type == "white":
        eps = jax.random.uniform(keys[-1], (recording_length, D),
                                 minval=-avg_amp, maxval=avg_amp)
    elif noise_type == "gaussian":
        mu_c = jnp.mean(base_params["noise_mu"])
        var_c = jnp.mean(base_params["noise_var"])
        eps = mu_c + var_c * avg_amp * jax.random.normal(keys[-1], (recording_length, D))
    else:
        raise ValueError(f"Unrecognized noise_type={noise_type}")
    x = x + noise_amp * eps
    return x.astype(jnp.float32), y.astype(jnp.float32)


def generate_synthetic_dataset(key, graphs, act_code_tensors, base_freqs, noise_mu,
                               noise_var, innovation_amp, num_samples,
                               recording_length, burnin_period,
                               num_labeled_sys_states, label_type="Oracle",
                               noise_type="white", noise_amp=0.1):
    """Batched dataset curation on device: vmap of generate_synthetic_batch.

    Returns (X, Y) numpy arrays with X: (N, T, D), Y: (N, num_labels, T) — the
    (batch, time, channel) / label contract every model consumes (SURVEY.md §2.4).
    """
    S = len(graphs)
    M1s, M2s = zip(*[_step_matrices(g, base_freqs) for g in graphs])
    M1_stack = jnp.asarray(np.stack(M1s))
    M2_stack = jnp.asarray(np.stack(M2s))
    acts = jnp.asarray(np.stack(act_code_tensors).astype(np.int32))
    base_params = {
        "noise_mu": jnp.asarray(np.asarray(noise_mu, dtype=np.float32).reshape(-1)),
        "noise_var": jnp.asarray(np.asarray(noise_var, dtype=np.float32).reshape(-1)),
        "innovation_amp": jnp.asarray(np.asarray(innovation_amp, dtype=np.float32).reshape(-1)),
    }
    keys = jax.random.split(key, num_samples)
    gen = jax.vmap(
        lambda k: generate_synthetic_batch(
            k, M1_stack, M2_stack, acts, base_params, recording_length,
            burnin_period, num_labeled_sys_states, label_type, noise_type, noise_amp,
        )
    )
    X, Y = gen(keys)
    return np.asarray(X), np.asarray(Y)


def generate_synthetic_data_np(rng, graphs, act_code_tensors, base_freqs, noise_mu,
                               noise_var, innovation_amp, num_samples,
                               recording_length, burnin_period,
                               num_labeled_sys_states, label_type="Oracle",
                               noise_type="white", noise_amp=0.1):
    """Host/numpy twin of generate_synthetic_dataset (golden-test oracle)."""
    S = len(graphs)
    D = graphs[0].shape[0]
    n_extra = S - num_labeled_sys_states
    num_labels = num_labeled_sys_states + (1 if n_extra > 0 else 0)
    amp = np.asarray(innovation_amp, dtype=np.float64).reshape(D)
    avg_amp = float(np.mean(amp))
    X = np.zeros((num_samples, recording_length, D), dtype=np.float32)
    Y = np.zeros((num_samples, num_labels, recording_length), dtype=np.float32)
    for s in range(num_samples):
        x = np.zeros((D, recording_length))
        y_true = np.zeros((num_labels, recording_length))
        for state in range(S):
            sig = rollout_np(graphs[state], act_code_tensors[state], base_freqs,
                             noise_mu, noise_var, innovation_amp, recording_length,
                             burnin_period, rng)
            w0, w1 = rng.uniform(), rng.uniform()
            ramp = np.linspace(w0, w1, recording_length)
            x += sig * ramp[None, :]
            row = state if state < num_labels - 1 else num_labels - 1
            y_true[row] += ramp
        if n_extra > 0:
            y_true[-1] /= S - (num_labels - 1)
        if label_type == "Oracle":
            y = y_true
        elif label_type == "OneHot":
            y = np.zeros_like(y_true)
            y[np.argmax(y_true, axis=0), np.arange(recording_length)] = 1.0
        else:
            raise ValueError(label_type)
        if noise_type == "white":
            eps = rng.uniform(-avg_amp, avg_amp, (D, recording_length))
        elif noise_type == "gaussian":
            eps = rng.normal(np.mean(noise_mu), np.mean(noise_var) * avg_amp,
                             (D, recording_length))
        else:
            raise ValueError(noise_type)
        X[s] = (x + noise_amp * eps).T
        Y[s] = y
    return X, Y


def reference_curation_params(num_nodes):
    """The sVAR coefficient recipe used by the reference curation driver
    (ref currate_...etNL.py:72-75,277-281): per-node base frequencies
    pi*(707*i + i%2)/120000, standard-normal innovations with unit amplitude,
    off-diagonal edge strengths 0.3 at both lags, receiving-node damping 0.6,
    sending-node damping 1.0."""
    return {
        "base_freqs": np.pi * np.array([i * 707 + i % 2 for i in range(num_nodes)]) / 120000.0,
        "noise_mu": np.zeros(num_nodes),
        "noise_var": np.ones(num_nodes),
        "innovation_amp": np.ones(num_nodes),
        "off_diag_edge_strengths": (0.3, 0.3),
        "diag_receiving_node_forgetting_coeffs": (0.6, 0.6),
        "diag_sending_node_forgetting_coeffs": (1.0, 1.0),
        "recording_length": 100,
        "burnin_period": 10,
    }


# ---------------------------------------------------------------------------
# Random lagged-DAG factory (host; curation-time only)
# ---------------------------------------------------------------------------

def generate_lagged_adjacency_graphs_for_factor_model(
    num_nodes,
    num_lags,
    num_factors,
    make_factors_orthogonal,
    make_factors_singular_components,
    rand_seed=0,
    off_diag_edge_strengths=(0.1, 1.0),
    diag_receiving_node_forgetting_coeffs=(0.1, 1.0),
    diag_sending_node_forgetting_coeffs=(0.9, 1.0),
    num_edges_per_graph=None,
    max_formulation_attempts=100,
    nonlinear_act_codes_per_factor=None,
):
    """Random per-factor lagged adjacency tensors (ref data_utils.py:243-353).

    Each graph starts as lag-wise identity; off-diagonal edges (i, j, l) are drawn
    without replacement, with the involved nodes' self-connections damped by the
    forgetting coefficients. Graphs are re-drawn until the lag-summed graph has at
    most the allowed number of connected components; orthogonal factors remove the
    chosen (i, j) pairs (all lags) from the shared edge pool. Factor order is
    shuffled before returning.

    Returns (graphs, act_code_tensors, shuffled_factor_inds) where each graph is
    (num_nodes, num_nodes, num_lags) and each act-code tensor is an int array of the
    same shape (0 identity / 1 min0 / 2 max0).
    """
    _pyrandom.seed(rand_seed)
    np.random.seed(rand_seed)

    while True:  # restart_curration loop
        graphs = [None] * num_factors
        acts = [None] * num_factors
        max_comps = 1 if make_factors_singular_components else num_nodes
        n_edges = num_edges_per_graph or (num_nodes**2) // num_factors
        if make_factors_singular_components:
            assert n_edges >= num_nodes - 1

        available = [
            (i, j, k)
            for i in range(num_nodes)
            for j in range(num_nodes)
            for k in range(num_lags)
            if i != j
        ]
        available_ids = list(range(len(available)))
        restart = False

        for f_ind in range(num_factors):
            attempts = 0
            while True:
                A = np.zeros((num_nodes, num_nodes, num_lags))
                for l in range(num_lags):
                    A[:, :, l] += np.eye(num_nodes)
                A_codes = np.zeros((num_nodes, num_nodes, num_lags), dtype=np.int32)

                _pyrandom.shuffle(available_ids)
                chosen_ids = available_ids[:n_edges]
                chosen = [available[i] for i in chosen_ids]
                for x, y, z in chosen:
                    A[x, y, z] = off_diag_edge_strengths[z]
                    A[x, x, 0] *= diag_receiving_node_forgetting_coeffs[0]
                    A[x, x, 1] *= diag_receiving_node_forgetting_coeffs[1]
                    A[y, y, 0] *= diag_sending_node_forgetting_coeffs[0]
                    A[y, y, 1] *= diag_sending_node_forgetting_coeffs[1]
                    if (
                        nonlinear_act_codes_per_factor is not None
                        and nonlinear_act_codes_per_factor[f_ind] is not None
                    ):
                        A_codes[x, y, z] = nonlinear_act_codes_per_factor[f_ind][z]

                n_comps = get_number_of_connected_components(
                    A.sum(axis=2), add_self_connections=False
                )
                attempts += 1
                if n_comps <= max_comps:
                    break
                if attempts == max_formulation_attempts:
                    restart = True
                    break
            if restart:
                break

            graphs[f_ind] = A
            acts[f_ind] = A_codes
            if make_factors_orthogonal:
                exclude = set(chosen_ids)
                chosen_pairs = {(x, y) for (x, y, _) in chosen}
                for eid in available_ids[n_edges:]:
                    if (available[eid][0], available[eid][1]) in chosen_pairs:
                        exclude.add(eid)
                available_ids = [i for i in available_ids if i not in exclude]

        if not restart:
            break

    inds = list(range(num_factors))
    order = list(zip(graphs, acts, inds))
    _pyrandom.shuffle(order)
    graphs, acts, inds = map(list, zip(*order))
    return graphs, acts, inds
