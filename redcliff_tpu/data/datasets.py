"""Device-resident dataset containers.

Replaces the reference's pickle-per-__getitem__ Dataset/DataLoader stack
(ref data/synthetic_datasets.py:18-277, dream4_datasets.py:18-350,
local_field_potential_datasets.py:18-301) with one-shot loads into (N, T, C)
arrays. Per-channel z-score statistics are computed dataset-wide at construction
exactly like NormalizedSyntheticWVARDataset (ref synthetic_datasets.py:89-118);
the grid_search flag keeps only the first quarter of samples
(ref synthetic_datasets.py:126-129).
Input contracts: construction validates shape/dtype (a ragged or non-(N,T,C)
input raises :class:`InputContractError` naming the violation) and
quarantines non-finite samples with a COUNT (``quarantined_samples``) plus a
RuntimeWarning — never a silent drop, and never a NaN row silently poisoning
the normalization statistics and every batch downstream.
"""
from __future__ import annotations

import warnings

import numpy as np

__all__ = ["ArrayDataset", "InputContractError", "train_val_split"]


class InputContractError(ValueError):
    """Input data violates the dataset contract (shape/dtype/label length)."""


class ArrayDataset:
    """In-memory (N, T, C) signals + (N, ...) labels with channel normalization.

    Batches are yielded as plain numpy slices; callers hand them to jit'd steps
    (jax transfers once per batch — or pre-shard via parallel.grid for multi-chip).

    ``contract=True`` (default) enforces the input contract: X must be a
    dense rank-3 float-convertible array, Y (when given) must have matching
    length, and samples containing non-finite values are quarantined (dropped
    BEFORE normalization statistics, counted in ``quarantined_samples``, and
    warned about — the trainers' numerics sentinel then never sees NaN data
    that the loader could have caught).
    """

    _dev = None  # lazily-populated device-resident (X, Y) cache
    supports_device_batches = True  # trainers probe this before device=True
    quarantined_samples = 0

    def __init__(self, X, Y=None, normalize=True, stats=None, grid_search=False,
                 contract=True):
        X = np.asarray(X)
        if contract:
            if X.dtype == object:
                raise InputContractError(
                    "X is an object array (ragged sample list?); the dataset "
                    "contract requires a dense (N, T, C) numeric array")
            if X.ndim != 3:
                raise InputContractError(
                    f"X must be (num_samples, num_timesteps, num_channels); "
                    f"got shape {X.shape}")
            if not np.issubdtype(X.dtype, np.floating) \
                    and not np.issubdtype(X.dtype, np.integer):
                raise InputContractError(
                    f"X dtype {X.dtype} is not numeric")
        X = np.asarray(X, dtype=np.float32)
        Y = None if Y is None else np.asarray(Y, dtype=np.float32)
        if contract and Y is not None and len(Y) != len(X):
            raise InputContractError(
                f"label length {len(Y)} != sample count {len(X)}")
        if contract and len(X):
            good = np.isfinite(X).all(axis=(1, 2))
            if Y is not None:
                good &= np.isfinite(Y.reshape(len(Y), -1)).all(axis=1)
            n_bad = int(len(X) - good.sum())
            if n_bad:
                # quarantine BEFORE stats: one NaN sample would otherwise
                # poison the channel mean/std and normalize every clean
                # sample to NaN
                warnings.warn(
                    f"ArrayDataset: quarantined {n_bad}/{len(X)} samples "
                    f"containing non-finite values", RuntimeWarning,
                    stacklevel=2)
                X = X[good]
                Y = None if Y is None else Y[good]
            self.quarantined_samples = n_bad
        if normalize:
            # stats come from the FULL dataset even under grid_search subsetting,
            # matching the reference's order of operations
            # (ref synthetic_datasets.py:89-129: stats at init, slice after)
            if stats is None:
                mean = X.mean(axis=(0, 1))
                std = X.std(axis=(0, 1))
                std = np.where(std == 0.0, 1.0, std)
                stats = (mean, std)
            self.stats = stats
        else:
            self.stats = None
        if grid_search:
            keep = max(1, len(X) // 4)
            X = X[:keep]
            Y = None if Y is None else Y[:keep]
        if normalize:
            X = (X - self.stats[0]) / self.stats[1]
        self.X = X
        self.Y = Y

    def __len__(self):
        return len(self.X)

    @property
    def num_channels(self):
        return self.X.shape[2]

    @property
    def num_timesteps(self):
        return self.X.shape[1]

    def device_arrays(self, sharding=None):
        """The one HBM-resident (X, Y) copy per placement — the backing store
        for both ``batches(device=True)`` gathers and the epoch-scan batch
        stream (data/pipeline.py), which scans over *index* arrays into these.
        Keyed by sharding so a dataset shared between a single-device trainer
        and a mesh grid runner keeps one correctly-placed copy per placement
        instead of silently reusing the first caller's."""
        if self._dev is None:
            self._dev = {}
        if sharding not in self._dev:
            import jax

            put = ((lambda a: jax.device_put(a, sharding))
                   if sharding is not None else jax.numpy.asarray)
            self._dev[sharding] = (put(self.X),
                                   None if self.Y is None else put(self.Y))
        return self._dev[sharding]

    def batches(self, batch_size, rng=None, drop_remainder=False,
                device=False, sharding=None):
        """Yield (X, Y) minibatches; shuffled when an np.random.Generator is
        given.

        ``device=True`` caches the whole dataset in device memory once and
        slices batches with a device-side gather, so epochs re-ship only the
        (tiny) index array instead of the batch data host->device every step
        — the datasets here are orders of magnitude smaller than HBM. Keep
        the default (host numpy) in multi-process runs, where inputs must
        stay uncommitted to replicate across hosts.

        ``sharding`` (used with ``device=True``) places the cached copy with
        that sharding — pass a replicated mesh sharding so batch gathers for
        mesh-sharded programs stay on-device with no per-step resharding.
        One cached copy is kept per distinct sharding (None included), so
        mixed single-device and mesh callers each get a correctly-placed
        copy.
        """
        n = len(self.X)
        idx = np.arange(n)
        if rng is not None:
            rng.shuffle(idx)
        if device:
            import jax

            # multi-process guard lives here, not at call sites: committed
            # per-host arrays cannot replicate across hosts
            device = jax.process_count() == 1
        Xs, Ys = (self.device_arrays(sharding) if device
                  else (self.X, self.Y))
        stop = (n // batch_size) * batch_size if drop_remainder else n
        for start in range(0, stop, batch_size):
            sel = idx[start : start + batch_size]
            if len(sel) == 0:
                break
            yield Xs[sel], (None if Ys is None else Ys[sel])

    def num_batches(self, batch_size, drop_remainder=False):
        n = len(self.X)
        return n // batch_size if drop_remainder else int(np.ceil(n / batch_size))


def train_val_split(X, Y, val_fraction=0.2, rng=None, normalize=True, grid_search=False):
    """Split into normalized train/val ArrayDatasets; validation reuses the
    training normalization statistics (train is the only stats source, matching
    the reference's per-split dataset-wide stats usage)."""
    n = len(X)
    idx = np.arange(n)
    if rng is not None:
        rng.shuffle(idx)
    n_val = int(round(n * val_fraction))
    val_idx, train_idx = idx[:n_val], idx[n_val:]
    train = ArrayDataset(X[train_idx], None if Y is None else Y[train_idx],
                         normalize=normalize, grid_search=grid_search)
    val = ArrayDataset(X[val_idx], None if Y is None else Y[val_idx],
                       normalize=normalize, stats=train.stats)
    return train, val
