"""Synthetic sVAR dataset curation: shards + data cached-args.

Rebuilds the curation drivers around the generator (ref
/root/reference/data/currate_sVARwInnovativeContinuousGaussianNoise_data_etNL.py,
clean_...etNL.py, aggregate_synthetic_systems_datasets.py, and the save
helpers at data/data_utils.py:21-45): generate per-fold factor graphs and
superimposed recordings, shard the samples, and write the fold's cached-args
file with the ground-truth adjacency tensors serialized as strings.
"""
from __future__ import annotations

import os
import pickle
import shutil

import numpy as np

from ..utils.config import serialize_tensor_to_string
from .synthetic import (
    generate_lagged_adjacency_graphs_for_factor_model,
    generate_synthetic_data_np,
    reference_curation_params,
)

__all__ = [
    "save_data",
    "save_cached_args_file_for_data",
    "experiment_folder_name",
    "curate_synthetic_fold",
    "clean_incomplete_experiment_folders",
    "aggregate_synthetic_systems_datasets",
]


def save_data(save_path_for_data, samples, num_samples_in_dataset,
              num_samps_per_file, file_prefix="subset_"):
    """Shard [[x, y], ...] samples into subset pickles
    (ref data_utils.py:21-30)."""
    start, counter = 0, 0
    while start < num_samples_in_dataset:
        with open(os.path.join(save_path_for_data,
                               f"{file_prefix}{counter}.pkl"), "wb") as f:
            pickle.dump(samples[start : start + num_samps_per_file], f)
        start += num_samps_per_file
        counter += 1


def save_cached_args_file_for_data(data_root_path, num_channels,
                                   adjacency_tensors, final_file_name):
    """Write the data cached-args JSON with stringified ground-truth tensors
    (ref data_utils.py:32-45).  Tensors are stored reverse-lag-major so the
    readers' lag reversal restores them."""
    import json

    entries = {
        "data_root_path": data_root_path,
        "num_channels": str(num_channels),
    }
    for i, tensor in enumerate(adjacency_tensors):
        entries[f"net{i + 1}_adjacency_tensor"] = \
            serialize_tensor_to_string(np.asarray(tensor, dtype=np.float64))
    with open(os.path.join(data_root_path, final_file_name), "w") as f:
        json.dump(entries, f)


def experiment_folder_name(num_factors, num_supervised_factors, num_nodes,
                           num_edges_per_graph, edge_type_setting,
                           label_type_setting, noise_type, noise_level,
                           restriction_setting=""):
    """The hyperparameter-encoded folder-name convention the eval layer
    parses back (ref currate_...py:92-108)."""
    return "_".join([
        f"numF{num_factors}",
        f"numSF{num_supervised_factors}",
        f"numN{num_nodes}",
        f"numE{num_edges_per_graph}",
        f"edges{edge_type_setting}",
        f"labels{label_type_setting}",
        f"noiT-{noise_type}",
        "noiL-" + str(noise_level).replace(".", "-"),
        restriction_setting,
    ]).rstrip("_")


def curate_synthetic_fold(save_root, fold_id, num_nodes=6, num_lags=2,
                          num_factors=2, num_supervised_factors=2,
                          num_edges_per_graph=None,
                          num_samples_in_train_set=40,
                          num_samples_in_val_set=10,
                          sample_recording_len=100, burnin_period=10,
                          label_type_setting="Oracle", noise_type="white",
                          noise_level=0.1, make_factors_orthogonal=True,
                          make_factors_singular_components=False,
                          num_samples_per_file=100, folder_name=None,
                          rng=None):
    """Generate one CV fold of the synthetic sVAR benchmark
    (ref currate_...py:18-230): factor graphs seeded by fold (fold_id*333 so
    graphs repeat across hyperparameter settings), train/validation shards,
    and the fold's cached-args with stringified true graphs.

    Returns (fold_dir, graphs).
    """
    p = reference_curation_params(num_nodes)
    graphs, acts, _ = generate_lagged_adjacency_graphs_for_factor_model(
        num_nodes=num_nodes, num_lags=num_lags, num_factors=num_factors,
        make_factors_orthogonal=make_factors_orthogonal,
        make_factors_singular_components=make_factors_singular_components,
        rand_seed=fold_id * 333,
        off_diag_edge_strengths=p["off_diag_edge_strengths"],
        diag_receiving_node_forgetting_coeffs=
            p["diag_receiving_node_forgetting_coeffs"],
        diag_sending_node_forgetting_coeffs=
            p["diag_sending_node_forgetting_coeffs"],
        num_edges_per_graph=num_edges_per_graph)

    if folder_name is None:
        folder_name = experiment_folder_name(
            num_factors, num_supervised_factors, num_nodes,
            num_edges_per_graph if num_edges_per_graph is not None else "Auto",
            "Linear", label_type_setting, noise_type, noise_level)
    fold_dir = os.path.join(save_root, folder_name, f"fold_{fold_id}")
    train_dir = os.path.join(fold_dir, "train")
    val_dir = os.path.join(fold_dir, "validation")
    os.makedirs(train_dir, exist_ok=True)
    os.makedirs(val_dir, exist_ok=True)

    rng = rng or np.random.default_rng(9999 + fold_id)
    sets = {}
    for split, n in (("train", num_samples_in_train_set),
                     ("validation", num_samples_in_val_set)):
        X, Y = generate_synthetic_data_np(
            rng, graphs, acts, p["base_freqs"], p["noise_mu"],
            p["noise_var"], p["innovation_amp"], n, sample_recording_len,
            burnin_period, num_supervised_factors,
            label_type=label_type_setting, noise_type=noise_type,
            noise_amp=noise_level)
        sets[split] = [[X[i], Y[i]] for i in range(n)]
    save_data(train_dir, sets["train"], num_samples_in_train_set,
              num_samples_per_file)
    save_data(val_dir, sets["validation"], num_samples_in_val_set,
              num_samples_per_file)
    save_cached_args_file_for_data(
        fold_dir, num_nodes, graphs,
        f"data_fold{fold_id}_cached_args.txt")
    return fold_dir, graphs


def clean_incomplete_experiment_folders(root, num_folds):
    """Delete experiment folders missing folds or cached-args, and collect
    the surviving cached-args paths (ref clean_...etNL.py:30-40)."""
    kept = []
    for exp in sorted(os.listdir(root)):
        exp_dir = os.path.join(root, exp)
        if not os.path.isdir(exp_dir):
            continue
        complete = True
        cached = []
        for fold_id in range(num_folds):
            fold_dir = os.path.join(exp_dir, f"fold_{fold_id}")
            args_files = [
                os.path.join(fold_dir, x)
                for x in (os.listdir(fold_dir)
                          if os.path.isdir(fold_dir) else [])
                if "cached_args" in x
            ]
            if not os.path.isdir(fold_dir) or not args_files:
                complete = False
                break
            cached.extend(args_files)
        if complete:
            kept.extend(cached)
        else:
            print(f"clean: removing incomplete experiment {exp}", flush=True)
            shutil.rmtree(exp_dir)
    return kept


def aggregate_synthetic_systems_datasets(system_folders, dest_root,
                                         benchmark_name):
    """Collect selected system folders into one supervised-discovery
    benchmark directory (ref aggregate_synthetic_systems_datasets.py:23-62)."""
    dest = os.path.join(dest_root, benchmark_name)
    os.makedirs(dest, exist_ok=True)
    for folder in system_folders:
        name = os.path.basename(os.path.normpath(folder))
        target = os.path.join(dest, name)
        if not os.path.exists(target):
            shutil.copytree(folder, target)
    return dest
