"""Local-field-potential curation: TST and Social-Preference pipelines.

Rebuilds /root/reference/data/tst_100HzLP.py and
socialPreference_100HzLP.py: load per-channel .mat LFP recordings, mark MAD
outliers as NaN, Butterworth-filter (+ notch), draw NaN-avoiding random
windows per behavioral epoch, downsample (1 kHz -> 100 Hz by strided
decimation), and shard the windows in the shared pickle layout.  The epochs:
  TST (ref tst_100HzLP.py:147-158): HomeCage = first 300 s, OpenField and
  TailSuspension from the INT_TIME vector [of_start, of_dur, ts_start,
  ts_dur] (seconds); labels one-hot over (HC, OF, TS).
  SocPref (ref socialPreference_100HzLP.py:157-177): windows where the
  per-timestep S_Class / O_Class traces are active for the whole window;
  labels one-hot over (social, object).
"""
from __future__ import annotations

import os
import pickle

import numpy as np
import scipy.io as scio

from ..utils.time_series import (
    DEFAULT_MAD_THRESHOLD,
    HIGHCUT,
    LOW_PASS_CUTOFF,
    LOWCUT,
    ORDER,
    Q,
    draw_timesteps_to_sample_from,
    draw_timesteps_to_sample_from_using_label_reference,
    filter_signal,
    mark_outliers,
)

__all__ = [
    "load_lfp_data_matrix",
    "determine_keys_of_interest",
    "extract_epoch_windows",
    "preprocess_tst_raw_lfps_for_windowed_training",
    "preprocess_socpref_raw_lfps_for_windowed_training",
]


def load_lfp_data_matrix(raw_data_path, raw_file_name, keys_of_interest,
                         num_channels_in_samples, sample_freq=1000,
                         cutoff=LOW_PASS_CUTOFF, lowcut=LOWCUT,
                         highcut=HIGHCUT,
                         mad_threshold=DEFAULT_MAD_THRESHOLD, q=Q, order=ORDER,
                         apply_notch_filters=True, filter_type="lowpass"):
    """(C, T) filtered matrix with outliers NaN-masked
    (ref tst_100HzLP.py:18-64)."""
    raw = scio.loadmat(os.path.join(raw_data_path, raw_file_name))
    raw = {k: raw[k].reshape(-1).astype(float) for k in keys_of_interest}
    raw = mark_outliers(raw, sample_freq, cutoff=cutoff, lowcut=lowcut,
                        highcut=highcut, mad_threshold=mad_threshold,
                        filter_type=filter_type)
    rows = [
        filter_signal(raw[k], sample_freq, cutoff=cutoff, lowcut=lowcut,
                      highcut=highcut, q=q, order=order,
                      apply_notch_filters=apply_notch_filters,
                      filter_type=filter_type).reshape(1, -1)
        for k in keys_of_interest
    ]
    combined = np.vstack(rows)
    assert combined.shape[0] == num_channels_in_samples
    return combined


def determine_keys_of_interest(files_to_process, raw_data_path):
    """Channel keys present in every .mat file (ref tst_100HzLP.py:66-81)."""
    keys = None
    for name in files_to_process:
        raw = scio.loadmat(os.path.join(raw_data_path, name))
        useful = {k for k in raw.keys() if "__" not in k}
        keys = useful if keys is None else (keys & useful)
    return sorted(keys or [])


def extract_epoch_windows(raw_combined, epochs, window_size,
                          num_samples_per_label_type, downsampling_step_size,
                          rng=None, max_num_draws=10):
    """Draw NaN-avoiding windows per epoch from a (C, T) matrix.

    ``epochs``: [(start, stop, one_hot_label)].  Returns {epoch_index:
    [[window (T', C), label], ...]} with windows transposed and strided-
    decimated like the reference (ref tst_100HzLP.py:160-238).
    """
    rng = rng or np.random.default_rng()
    out = {}
    nan_cols = np.flatnonzero(np.isnan(raw_combined).any(axis=0))
    for e_idx, (start, stop, label) in enumerate(epochs):
        start, stop = int(start), int(stop)
        nan_locs = nan_cols[(nan_cols >= start) & (nan_cols < stop)]
        starts = draw_timesteps_to_sample_from(
            start, stop, window_size, num_samples_per_label_type, nan_locs,
            max_num_draws=max_num_draws, rng=rng)
        samples = []
        for s in starts:
            if s is None:
                continue
            win = raw_combined[:, s : s + window_size].T
            if np.isnan(np.sum(win)):
                # residual NaN despite the draw filter: stop collecting from
                # this recording, as the reference does (ref :196-201)
                break
            if downsampling_step_size > 1:
                win = win[::downsampling_step_size, :]
            samples.append([win, np.asarray(label, dtype=np.float64)])
        out[e_idx] = samples
    return out


def _save_subsets(samples, save_path, prefix, max_per_file):
    os.makedirs(save_path, exist_ok=True)
    for counter, i in enumerate(range(0, len(samples), max_per_file)):
        with open(os.path.join(
                save_path,
                f"{prefix}_processed_data_subset_{counter}.pkl"), "wb") as f:
            pickle.dump(samples[i : i + max_per_file], f)


def preprocess_tst_raw_lfps_for_windowed_training(
        lfp_data_path, label_data_path, preprocessed_data_save_path,
        post_processing_sample_freq, num_processed_samples=10000,
        sample_temp_window_size=1000, max_num_samps_per_preprocessed_file=100,
        sample_freq=1000, cutoff=LOW_PASS_CUTOFF, lowcut=LOWCUT,
        highcut=HIGHCUT, mad_threshold=DEFAULT_MAD_THRESHOLD, q=Q, order=ORDER,
        apply_notch_filters=True, filter_type="lowpass", rng=None):
    """Tail-Suspension-Test curation (ref tst_100HzLP.py:83-330): per mouse,
    pair ``*_LFP*.mat`` recordings with ``*_TIME*.mat`` INT_TIME epochs, draw
    windows per (HomeCage, OpenField, TailSuspension), decimate to
    ``post_processing_sample_freq`` and shard per mouse/state."""
    assert sample_freq > post_processing_sample_freq
    step = sample_freq // post_processing_sample_freq
    rng = rng or np.random.default_rng()

    lfp_files = sorted(x for x in os.listdir(lfp_data_path)
                       if "_LFP" in x and x.endswith(".mat"))
    time_files = sorted(x for x in os.listdir(label_data_path)
                        if "_TIME" in x and x.endswith(".mat"))
    mice = sorted({x.split("_")[0] for x in lfp_files})
    num_per_mouse = num_processed_samples // max(len(mice), 1)
    num_per_label = num_per_mouse // 3

    keys = determine_keys_of_interest(lfp_files, lfp_data_path)
    if "TailSuspension" in keys:
        keys.remove("TailSuspension")
    n_chans = len(keys)

    state_names = ("homeCage", "openField", "tailSuspension")
    for mouse in mice:
        m_lfp = [x for x in lfp_files if x.split("_")[0] == mouse]
        m_time = [x for x in time_files if x.split("_")[0] == mouse]
        if len(m_lfp) != len(m_time):
            print(f"preprocess_tst: skipping mouse {mouse}: "
                  f"{len(m_lfp)} LFP vs {len(m_time)} TIME files", flush=True)
            continue
        per_state = {0: [], 1: [], 2: []}
        for lfp_name, time_name in zip(m_lfp, m_time):
            assert lfp_name[:23] == time_name[:23]
            int_time = scio.loadmat(
                os.path.join(label_data_path, time_name))["INT_TIME"]
            int_time = np.asarray(int_time).reshape(-1)
            raw = load_lfp_data_matrix(
                lfp_data_path, lfp_name, keys, n_chans,
                sample_freq=sample_freq, cutoff=cutoff, lowcut=lowcut,
                highcut=highcut, mad_threshold=mad_threshold, q=q,
                order=order, apply_notch_filters=apply_notch_filters,
                filter_type=filter_type)
            epochs = [
                (0, 300 * sample_freq, [1.0, 0.0, 0.0]),
                (int_time[0] * sample_freq,
                 (int_time[0] + int_time[1]) * sample_freq, [0.0, 1.0, 0.0]),
                (int_time[2] * sample_freq,
                 (int_time[2] + int_time[3]) * sample_freq, [0.0, 0.0, 1.0]),
            ]
            wins = extract_epoch_windows(raw, epochs,
                                         sample_temp_window_size,
                                         num_per_label, step, rng=rng)
            for e_idx, samples in wins.items():
                per_state[e_idx].extend(samples)
        for e_idx, name in enumerate(state_names):
            _save_subsets(per_state[e_idx], preprocessed_data_save_path,
                          f"{mouse}_{name}",
                          max_num_samps_per_preprocessed_file)


def preprocess_socpref_raw_lfps_for_windowed_training(
        lfp_data_path, label_data_path, preprocessed_data_save_path,
        post_processing_sample_freq, num_processed_samples=10000,
        sample_temp_window_size=1000, max_num_samps_per_preprocessed_file=100,
        sample_freq=1000, cutoff=LOW_PASS_CUTOFF, lowcut=LOWCUT,
        highcut=HIGHCUT, mad_threshold=DEFAULT_MAD_THRESHOLD, q=Q, order=ORDER,
        apply_notch_filters=True, filter_type="lowpass", rng=None,
        recording_duration_sec=600):
    """Social-Preference curation (ref socialPreference_100HzLP.py:93-340):
    windows where S_Class / O_Class behavior traces stay active; labels
    one-hot (social, object)."""
    assert sample_freq > post_processing_sample_freq
    step = sample_freq // post_processing_sample_freq
    rng = rng or np.random.default_rng()
    rec_steps = recording_duration_sec * sample_freq

    label_files = sorted(x for x in os.listdir(label_data_path)
                         if "_Class" in x and x.endswith(".mat"))
    lfp_files = sorted(
        x for x in os.listdir(lfp_data_path)
        if "_LFP" in x and x.endswith(".mat")
        and any(x[:23] == lf[:23] for lf in label_files))
    mice = sorted({x.split("_")[0] for x in lfp_files})
    num_per_mouse = num_processed_samples // max(len(mice), 1)
    num_per_label = num_per_mouse // 2

    keys = determine_keys_of_interest(lfp_files, lfp_data_path)
    n_chans = len(keys)

    for mouse in mice:
        m_lfp = [x for x in lfp_files if x.split("_")[0] == mouse]
        m_cls = [x for x in label_files
                 if any(x[:23] == lf[:23] for lf in m_lfp)]
        if len(m_lfp) != len(m_cls):
            continue
        soc_samples, obj_samples = [], []
        for lfp_name, cls_name in zip(m_lfp, m_cls):
            assert lfp_name[:23] == cls_name[:23]
            mat = scio.loadmat(os.path.join(label_data_path, cls_name))
            start_step = sample_freq * int(
                np.asarray(mat["StartTime"]).reshape(-1)[0])
            raw = load_lfp_data_matrix(
                lfp_data_path, lfp_name, keys, n_chans,
                sample_freq=sample_freq, cutoff=cutoff, lowcut=lowcut,
                highcut=highcut, mad_threshold=mad_threshold, q=q,
                order=order, apply_notch_filters=apply_notch_filters,
                filter_type=filter_type)
            # shift the recording to the labeled interval so window starts
            # index signal and behavior traces identically
            # (ref socialPreference_100HzLP.py:175-177)
            raw = raw[:, start_step : start_step + rec_steps]
            soc_trace = np.asarray(mat["S_Class"])[0,
                start_step : start_step + rec_steps]
            obj_trace = np.asarray(mat["O_Class"])[0,
                start_step : start_step + rec_steps]
            nan_locs = np.flatnonzero(np.isnan(raw).any(axis=0))
            for trace, label, bucket in (
                    (soc_trace, [1.0, 0.0], soc_samples),
                    (obj_trace, [0.0, 1.0], obj_samples)):
                # per-mouse cap across recordings (ref :207-241)
                remaining = num_per_label - len(bucket)
                if remaining <= 0:
                    continue
                starts = draw_timesteps_to_sample_from_using_label_reference(
                    trace, sample_temp_window_size, remaining, nan_locs,
                    rng=rng)
                for s in starts:
                    if s is None:
                        continue
                    win = raw[:, s : s + sample_temp_window_size].T
                    if np.isnan(np.sum(win)):
                        break
                    if step > 1:
                        win = win[::step, :]
                    bucket.append([win, np.asarray(label)])
        _save_subsets(soc_samples, preprocessed_data_save_path,
                      f"{mouse}_social", max_num_samps_per_preprocessed_file)
        _save_subsets(obj_samples, preprocessed_data_save_path,
                      f"{mouse}_object", max_num_samps_per_preprocessed_file)
