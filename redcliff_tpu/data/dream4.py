"""DREAM4 InSilico preprocessing and the D4IC combo benchmark.

Rebuilds /root/reference/data/dream4.py and dream4_insilicoCombo.py:
  - parse the original DREAM4 time-series TSVs with their blank-line-separated
    recordings and perturbation halves (parse_orig_DREAM4_time_series_file,
    ref dream4.py:82-166)
  - individual and "singleDominantSuperPositional" preprocessed variants
    (ref dream4.py:168-254)
  - the D4IC benchmark: for each fold/split, superimpose the 5 DREAM4
    networks' signals with a dominant coefficient on one network and a
    background coefficient on the rest; the label is the coefficient vector
    (make_dream4_combo_dataset, ref dream4_insilicoCombo.py:83-151)
SNR tiers come from the background coefficient (ref :256-261): dominant 10.0
with background 0.0 (HSNR), 0.1 (MSNR), 1.0 (LSNR).
"""
from __future__ import annotations

import copy
import os
import pickle

import numpy as np

from ..utils.misc import make_kfolds_cv_splits
from .shards import save_cv_split

__all__ = [
    "parse_dream4_timeseries",
    "make_dream4_individual_dataset",
    "make_dream4_single_dominant_superpositional_dataset",
    "make_dream4_combo_dataset",
    "D4IC_SNR_TIERS",
]

POSSIBLE_NUM_CHANNELS = (10, 100)
POSSIBLE_NUM_TIME_POINTS = (21,)

# (dominant_coeff, background_coeff) per SNR tier
# (ref dream4_insilicoCombo.py:256-261: DOMINANT 10.0, BACKGROUND {0,0.1,1})
D4IC_SNR_TIERS = {"HSNR": (10.0, 0.0), "MSNR": (10.0, 0.1),
                  "LSNR": (10.0, 1.0)}


def parse_dream4_timeseries(orig_ts_file, apply_state_perspective=False):
    """Parse an original DREAM4 ``*_timeseries.tsv``.

    Layout (ref dream4.py:82-166): a quoted tab-separated header
    ("Time", gene ids), then recordings of 21 rows separated by blank lines;
    the first column is the measurement time.  With
    ``apply_state_perspective=True`` each recording splits into the
    first-half (perturbation applied, label [1, 0]) and second-half
    (perturbation removed, label [0, 1]) series; otherwise whole recordings
    carry label [1, 0].

    Returns (time_series list of (t, C) arrays, state_labels, meta_data).
    """
    with open(orig_ts_file, "r") as f:
        all_lines = [ln.rstrip("\n") for ln in f]

    header = [x.strip('"') for x in all_lines[0].split("\t")]
    assert header[0] == "Time"
    channel_ids = header[1:]
    num_channels = len(channel_ids)
    assert num_channels in POSSIBLE_NUM_CHANNELS

    recordings, time_points = [], []
    current = []
    first_recording = True
    for line in all_lines[1:]:
        if len(line) == 0:
            if current:
                recordings.append(np.concatenate(current, axis=0))
                first_recording = False
                current = []
            continue
        vals = [float(v) for v in line.split("\t")]
        current.append(np.asarray(vals[1:]).reshape(1, num_channels))
        if first_recording:
            time_points.append(int(vals[0]))
    if current:
        recordings.append(np.concatenate(current, axis=0))

    num_time_points = len(time_points)
    assert num_time_points in POSSIBLE_NUM_TIME_POINTS
    for rec in recordings:
        assert rec.shape == (num_time_points, num_channels)

    time_series, state_labels = [], []
    half = num_time_points // 2
    for rec in recordings:
        if apply_state_perspective:
            # first half: perturbation active; second half: relaxed
            # (ref dream4.py:121-125)
            time_series.append(rec[: half + 1])
            state_labels.append(np.array([1, 0]))
            time_series.append(rec[half + 1 :])
            state_labels.append(np.array([0, 1]))
        else:
            time_series.append(rec)
            state_labels.append(np.array([1, 0]))

    meta_data = {
        "num_channels": num_channels,
        "channel_ids": channel_ids,
        "num_time_points": num_time_points,
        "time_points": time_points,
        "apply_state_perspective": apply_state_perspective,
    }
    return time_series, state_labels, meta_data


def _num_kfolds_for(save_path):
    if "size10_" in save_path:
        return 5
    if "size100_" in save_path:
        return 10
    raise ValueError("Network Size must be stated as 10 or 100 in save_path")


def make_dream4_individual_dataset(orig_data_path, save_path,
                                   state_label_setting):
    """Per-network CV folds in the shared shard layout
    (ref dream4.py:168-189)."""
    num_kfolds = _num_kfolds_for(save_path)
    ts, labels, _ = parse_dream4_timeseries(
        orig_data_path, apply_state_perspective=state_label_setting)
    kfolds = make_kfolds_cv_splits(ts, labels, num_folds=num_kfolds)
    for cv_id in range(num_kfolds):
        save_cv_split(kfolds[cv_id]["train"], kfolds[cv_id]["validation"],
                      cv_id, save_path)


def make_dream4_single_dominant_superpositional_dataset(
        orig_data_path, save_path, state_label_setting,
        dominant_net_coeff=5.0, background_net_coeff=0.1):
    """For each network: scale its recordings by the dominant coefficient and
    add every other network's fold-aligned recordings scaled by the background
    coefficient (ref dream4.py:193-254)."""
    num_kfolds = _num_kfolds_for(save_path)
    network_folders = sorted(os.listdir(orig_data_path))
    kfolds_by_network, meta_data = [], []
    for net_folder in network_folders:
        folder = os.path.join(orig_data_path, net_folder)
        ts_files = [x for x in os.listdir(folder) if "_timeseries.tsv" in x]
        assert len(ts_files) == 1
        ts, labels, meta = parse_dream4_timeseries(
            os.path.join(folder, ts_files[0]),
            apply_state_perspective=state_label_setting)
        kfolds_by_network.append(
            make_kfolds_cv_splits(ts, labels, num_folds=num_kfolds))
        meta_data.append(meta)
    os.makedirs(save_path, exist_ok=True)
    with open(os.path.join(save_path, "meta_data.pkl"), "wb") as f:
        pickle.dump(meta_data, f)

    for i, dominant in enumerate(kfolds_by_network):
        net_save = os.path.join(save_path, network_folders[i])
        os.makedirs(net_save, exist_ok=True)
        combined = copy.deepcopy(dominant)
        for cv_id in range(num_kfolds):
            for split in ("train", "validation"):
                for el in combined[cv_id][split]:
                    el[0] = dominant_net_coeff * el[0]
        for j, background in enumerate(kfolds_by_network):
            if i == j:
                continue
            for cv_id in range(num_kfolds):
                for split in ("train", "validation"):
                    for el, bg_el in zip(combined[cv_id][split],
                                         background[cv_id][split]):
                        el[0] = el[0] + background_net_coeff * bg_el[0]
        for cv_id in range(num_kfolds):
            save_cv_split(combined[cv_id]["train"],
                          combined[cv_id]["validation"], cv_id, net_save)


def make_dream4_combo_dataset(orig_data_path, save_path, fold_id, split_name,
                              num_factors, dominant_coeff, background_coeff,
                              shuffle_rng=None):
    """Build one split of the D4IC benchmark
    (ref dream4_insilicoCombo.py:83-151): every factor network takes a turn as
    the dominant signal over sample-aligned background mixtures of the others;
    the label is the (num_factors, 1) coefficient vector."""
    factor_dirs = sorted(
        os.path.join(orig_data_path, x, f"fold_{fold_id}", split_name)
        for x in os.listdir(orig_data_path)
        if os.path.isdir(os.path.join(orig_data_path, x, f"fold_{fold_id}",
                                      split_name)))
    assert len(factor_dirs) == num_factors, (
        f"expected {num_factors} factor networks, found {factor_dirs!r}")

    factor_samples = []
    num_factor_samples = None
    for d in factor_dirs:
        data = []
        for shard in sorted(x for x in os.listdir(d)
                            if "subset" in x and x.endswith(".pkl")):
            with open(os.path.join(d, shard), "rb") as f:
                data.extend(s[0] for s in pickle.load(f))
        factor_samples.append(data)
        if num_factor_samples is None:
            num_factor_samples = len(data)
        assert num_factor_samples == len(data)

    combined = []
    for factor_id in range(num_factors):
        for samp_id in range(num_factor_samples):
            x = dominant_coeff * factor_samples[factor_id][samp_id]
            for bg in range(num_factors):
                if bg != factor_id:
                    x = x + background_coeff * factor_samples[bg][samp_id]
            y = np.full((num_factors, 1), background_coeff, dtype=np.float64)
            y[factor_id] = dominant_coeff
            combined.append([x, y])

    rng = shuffle_rng or np.random.default_rng(5)
    rng.shuffle(combined)

    split_dir = os.path.join(save_path, split_name)
    os.makedirs(split_dir, exist_ok=True)
    with open(os.path.join(split_dir, "subset_0.pkl"), "wb") as f:
        pickle.dump(combined, f)
    return combined


def make_d4ic_fold(orig_data_path, save_path, fold_id, num_factors=5,
                   snr_tier="HSNR", shuffle_rng=None):
    """Both splits of one D4IC fold at a named SNR tier
    (ref dream4_insilicoCombo.py kick_off_preprocessing_run :156-198)."""
    dominant, background = D4IC_SNR_TIERS[snr_tier]
    os.makedirs(save_path, exist_ok=True)
    for split in ("train", "validation"):
        make_dream4_combo_dataset(orig_data_path, save_path, fold_id, split,
                                  num_factors, dominant, background,
                                  shuffle_rng=shuffle_rng)
