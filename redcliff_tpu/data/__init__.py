"""Data layer: synthetic sVAR generation, DREAM4/D4IC curation, LFP
preprocessing, and device-resident dataset containers
(rebuilds /root/reference/data/, SURVEY.md §2.4)."""
from .datasets import ArrayDataset, train_val_split
from .pipeline import (
    choose_stream_mode,
    dispatch_budget,
    epoch_batch_plan,
    prefetch_batches,
    PrefetchIterator,
)
from .dream4 import (
    D4IC_SNR_TIERS,
    make_d4ic_fold,
    make_dream4_combo_dataset,
    make_dream4_individual_dataset,
    make_dream4_single_dominant_superpositional_dataset,
    parse_dream4_timeseries,
)
from .lfp import (
    determine_keys_of_interest,
    extract_epoch_windows,
    load_lfp_data_matrix,
    preprocess_socpref_raw_lfps_for_windowed_training,
    preprocess_tst_raw_lfps_for_windowed_training,
)
from .shards import (
    ShardedBatchDataset,
    apply_signal_format,
    load_normalized_split_datasets,
    load_shard_samples,
    samples_to_arrays,
    save_cv_split,
)

__all__ = [
    "ArrayDataset", "train_val_split",
    "choose_stream_mode", "dispatch_budget", "epoch_batch_plan",
    "prefetch_batches", "PrefetchIterator", "ShardedBatchDataset",
    "D4IC_SNR_TIERS", "make_d4ic_fold", "make_dream4_combo_dataset",
    "make_dream4_individual_dataset",
    "make_dream4_single_dominant_superpositional_dataset",
    "parse_dream4_timeseries",
    "determine_keys_of_interest", "extract_epoch_windows",
    "load_lfp_data_matrix",
    "preprocess_socpref_raw_lfps_for_windowed_training",
    "preprocess_tst_raw_lfps_for_windowed_training",
    "apply_signal_format", "load_normalized_split_datasets",
    "load_shard_samples", "samples_to_arrays", "save_cv_split",
]
