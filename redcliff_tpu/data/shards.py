"""Shared on-disk shard layout + signal-format feature transforms.

The reference's datasets live as ``fold_<k>/{train,validation}/subset_*.pkl``
shards holding ``[[x (T, C), y], ...]`` pairs (written by
general_utils/misc.py:222-238 save_cv_split, read back by every
Normalized*Dataset).  This build keeps that layout as the cross-process results
contract (SURVEY.md §7) but loads shards once into dense arrays instead of
re-unpickling per sample (ref synthetic_datasets.py:140-141 re-opens the shard
on every __getitem__).

Signal formats follow NormalizedDREAM4Dataset.__getitem__
(ref dream4_datasets.py:120-151): "original" (T, C) windows, "flattened"
feature vectors, and "directed_spectrum" / "directed_spectrum_vanilla"
high-level spectral features.
"""
from __future__ import annotations

import os
import pickle
import warnings

import numpy as np

from .. import obs as _obs
from ..runtime import faultinject as _faultinject
from ..runtime import watchdog as _watchdog
from ..utils.misc import flatten_directed_spectrum_features
from ..utils.time_series import make_high_level_signal_features
from .datasets import ArrayDataset

__all__ = [
    "save_cv_split",
    "load_shard_samples",
    "samples_to_arrays",
    "apply_signal_format",
    "load_normalized_split_datasets",
    "ShardedBatchDataset",
]


def save_cv_split(train_data, val_data, cv_id, save_path):
    """Write one CV fold in the reference layout (ref misc.py:222-238)."""
    root = os.path.join(save_path, f"fold_{cv_id}")
    os.makedirs(os.path.join(root, "train"))
    os.makedirs(os.path.join(root, "validation"))
    with open(os.path.join(root, "train", "subset_0.pkl"), "wb") as f:
        pickle.dump(train_data, f)
    with open(os.path.join(root, "validation", "subset_0.pkl"), "wb") as f:
        pickle.dump(val_data, f)


def load_shard_samples(data_path, drop_nan=True, report=None):
    """Load every ``subset_*.pkl`` under a split dir into a [[x, y], ...] list,
    quarantining non-finite-contaminated samples like the reference loaders
    (ref dream4_datasets.py:50-70) — but as a COUNTED quarantine (per-file
    tallies in ``report`` when a dict is passed, plus a RuntimeWarning), not
    a silent drop. inf counts as contamination too: a non-finite sample
    poisons normalization statistics exactly like a NaN one."""
    files = sorted(x for x in os.listdir(data_path)
                   if "subset_" in x and x.endswith(".pkl")
                   and "metadata" not in x)
    samples = []
    skipped = 0
    per_file = {}
    for name in files:
        with open(os.path.join(data_path, name), "rb") as f:
            for pair in pickle.load(f):
                x = np.asarray(pair[0], dtype=np.float32)
                if drop_nan and not np.isfinite(x).all():
                    skipped += 1
                    per_file[name] = per_file.get(name, 0) + 1
                    continue
                samples.append([x, np.asarray(pair[1], dtype=np.float32)])
    if report is not None:
        report["quarantined"] = skipped
        report["loaded"] = len(samples)
        report["quarantined_by_file"] = per_file
    if skipped:
        warnings.warn(
            f"load_shard_samples: quarantined {skipped} non-finite samples "
            f"under {data_path} ({per_file})", RuntimeWarning, stacklevel=2)
    return samples


class ShardedBatchDataset:
    """Streaming batch source for split dirs too large to materialize: holds
    ONE shard file in memory at a time instead of the whole fold.

    The train engines duck-type on ``supports_device_batches`` — this class
    reports False, so the grid runner / trainers route it through the
    host-side per-batch path behind the double-buffered prefetcher
    (data/pipeline.py): shard unpickling + normalization + slicing of batch
    t+1 overlap device compute of batch t.

    Construction makes one streaming statistics pass (per-channel sum /
    sum-of-squares over every shard) so batches z-score with the SAME
    dataset-wide channel stats the in-memory loaders use; non-finite samples
    are quarantined with a counted RuntimeWarning exactly like
    :func:`load_shard_samples`. Shuffling (``rng`` passed to ``batches``)
    permutes the shard ORDER and the samples within each shard — a bounded-
    memory approximation of a global shuffle (documented deviation from
    ``ArrayDataset``'s exact permutation); unshuffled iteration matches the
    concatenated-shard order bit-for-bit, which tests pin against
    ``ArrayDataset``.

    Torn files: a shard that fails to read back — truncated mid-write,
    bit-rotted pickle, vanished file — is quarantined PER FILE
    (``quarantined_files[name] = reason``, with a RuntimeWarning) and the
    stream continues with the remaining shards, the same degrade-don't-crash
    contract the per-sample non-finite quarantine established. This covers
    both construction and mid-stream reads (the file may tear between the
    stats pass and epoch N). Each shard read stamps the ``"shard_loader"``
    heartbeat so a read wedged on dead storage is a watchdog-visible hang,
    not a silent stall.

    Host-local streaming (multi-host scale-out, ROADMAP item 5 /
    docs/ARCHITECTURE.md "Elastic re-meshing & host-fault tolerance"):
    ``host_id``/``n_hosts`` restrict this instance to its host's round-robin
    slice of the sorted shard list (``files[host_id::n_hosts]`` by sorted
    index) — every shard is owned by exactly one host, uneven counts
    included (no shard dropped, none read twice). Quarantine — per-sample
    tallies AND torn-file records — stays per host: each host reports only
    the shards it owns, so one host's dead storage never poisons another's
    stream. The heartbeat is host-scoped too (``host<h>:shard_loader``),
    giving the watchdog's per-host staleness detector a real producer.
    Normalization statistics are computed over the host-local slice
    (documented deviation: the global-stats path is the in-memory loader;
    callers needing cross-host-identical stats precompute and pass
    ``normalize=False`` plus their own transform).
    """

    supports_device_batches = False

    def __init__(self, split_dir, normalize=True, host_id=None, n_hosts=None):
        self.split_dir = split_dir
        all_files = sorted(
            x for x in os.listdir(split_dir)
            if "subset_" in x and x.endswith(".pkl") and "metadata" not in x)
        if not all_files:
            raise FileNotFoundError(f"no subset_*.pkl shards under {split_dir}")
        if (host_id is None) != (n_hosts is None):
            raise ValueError("host_id and n_hosts must be given together")
        self.host_id = host_id
        self.n_hosts = n_hosts
        self._hb = ("shard_loader" if host_id is None
                    else _watchdog.host_component(host_id, "shard_loader"))
        if n_hosts is not None:
            if not (0 <= int(host_id) < int(n_hosts)):
                raise ValueError(
                    f"host_id {host_id} out of range for n_hosts {n_hosts}")
            # round-robin by sorted index: a partition of the shard list for
            # ANY (n_files, n_hosts) — no shard dropped, none assigned twice
            self.files = all_files[int(host_id)::int(n_hosts)]
            if not self.files:
                raise FileNotFoundError(
                    f"host {host_id}/{n_hosts} owns no shards under "
                    f"{split_dir} ({len(all_files)} shard file(s) < "
                    f"{n_hosts} hosts) — reduce n_hosts or write more "
                    f"shards")
        else:
            self.files = all_files
        self.normalize = normalize
        self.quarantined_samples = 0
        self.quarantined_files = {}
        self._shape_tc = None
        n = 0
        s = ss = None
        for name in self.files:
            X, _ = self._load_shard(name, count_quarantine=True)
            if not len(X):
                continue  # fully-quarantined shard
            if self._shape_tc is None:
                self._shape_tc = X.shape[1:]
            elif X.shape[1:] != self._shape_tc:
                raise ValueError(
                    f"shard {name} window shape {X.shape[1:]} != first "
                    f"shard's {self._shape_tc}")
            n += X.shape[0]
            # f64 accumulators: a streaming f32 sum over a big fold drifts
            part = X.astype(np.float64)
            s = part.sum(axis=(0, 1)) if s is None else s + part.sum(axis=(0, 1))
            ss = ((part ** 2).sum(axis=(0, 1)) if ss is None
                  else ss + (part ** 2).sum(axis=(0, 1)))
        self._n = n
        _watchdog.retire(self._hb)  # stats pass done; batches() re-arms
        if self._shape_tc is None:
            raise ValueError(
                f"every sample under {split_dir} was quarantined "
                f"(non-finite data or torn shard files: "
                f"{sorted(self.quarantined_files) or 'none torn'}) — "
                f"nothing to train on")
        shape_tc = self._shape_tc
        if normalize:
            cnt = max(n * shape_tc[0], 1)
            mean = s / cnt
            var = np.maximum(ss / cnt - mean ** 2, 0.0)
            std = np.sqrt(var)
            std = np.where(std == 0.0, 1.0, std)
            self.stats = (mean.astype(np.float32), std.astype(np.float32))
        else:
            self.stats = None
        if self.quarantined_samples:
            warnings.warn(
                f"ShardedBatchDataset: quarantined {self.quarantined_samples} "
                f"non-finite samples under {split_dir}", RuntimeWarning,
                stacklevel=2)

    def _empty(self):
        return (np.zeros((0,) + (self._shape_tc or (0, 0)), np.float32),
                np.zeros((0, 1), np.float32))

    def _load_shard(self, name, count_quarantine=False):
        # liveness + chaos hooks: stamped while a read is in flight (the
        # budget measures one shard load, not inter-load idle — batches()
        # retires the heartbeat when the stream ends). Host-local instances
        # stamp their host-scoped beat (host<h>:shard_loader)
        _watchdog.stamp(self._hb)
        _faultinject.hang_point("shard_loader")
        _faultinject.io_point("shard_read")
        try:
            # traced load span (ring-only, under the heartbeat's component
            # name): a flight record after a wedged/slow storage incident
            # shows which shard files were read last and how long each took
            with _obs.span("shard.load", component=self._hb, file=name):
                with open(os.path.join(self.split_dir, name), "rb") as f:
                    pairs = pickle.load(f)
        except (OSError, pickle.UnpicklingError, EOFError, ValueError,
                AttributeError, ImportError, IndexError) as e:
            # torn/truncated/vanished shard: quarantine the FILE and keep
            # streaming — the same degrade-don't-crash contract as the
            # per-sample non-finite quarantine
            if name not in self.quarantined_files:
                self.quarantined_files[name] = repr(e)
                warnings.warn(
                    f"ShardedBatchDataset: quarantined torn shard file "
                    f"{name} under {self.split_dir} ({e!r}); continuing "
                    f"with the remaining shards", RuntimeWarning,
                    stacklevel=3)
            return self._empty()
        keep = []
        for pair in pairs:
            x = np.asarray(pair[0], dtype=np.float32)
            y = np.asarray(pair[1], dtype=np.float32)
            # quarantine on non-finite X OR Y — the same per-sample
            # contract ArrayDataset enforces, so the shard stream and the
            # in-memory path train on identical sample sets
            if not np.isfinite(x).all() or not np.isfinite(y).all():
                if count_quarantine:
                    self.quarantined_samples += 1
                continue
            keep.append([x, pair[1]])
        return samples_to_arrays(keep) if keep else self._empty()

    def __len__(self):
        return self._n

    @property
    def num_timesteps(self):
        return self._shape_tc[0]

    @property
    def num_channels(self):
        return self._shape_tc[1]

    def batches(self, batch_size, rng=None, drop_remainder=False):
        """Yield normalized (X, Y) minibatches, streaming one shard at a
        time; samples left over from a shard carry into the next shard's
        pool, so only the final batch of the epoch can be short.

        One concatenation per shard (the short carry-over head is prepended
        once), then batches are yielded as views via a cursor — no
        per-batch recopying of the remaining buffer.

        A shard that tears between epochs is quarantined per file (see the
        class docstring) and the stream continues over the survivors."""
        try:
            files = list(self.files)
            if rng is not None:
                rng.shuffle(files)
            carry_X = carry_Y = None
            for name in files:
                X, Y = self._load_shard(name)
                if not len(X):
                    continue  # fully-quarantined shard: nothing to buffer
                if rng is not None:
                    order = rng.permutation(len(X))
                    X, Y = X[order], Y[order]
                if self.normalize:
                    X = (X - self.stats[0]) / self.stats[1]
                if carry_X is not None and len(carry_X):
                    X = np.concatenate([carry_X, X])
                    Y = np.concatenate([carry_Y, Y])
                stop = (len(X) // batch_size) * batch_size
                for start in range(0, stop, batch_size):
                    yield X[start : start + batch_size], \
                        Y[start : start + batch_size]
                carry_X, carry_Y = X[stop:], Y[stop:]
            if carry_X is not None and len(carry_X) and not drop_remainder:
                yield carry_X, carry_Y
        finally:
            # op-scoped liveness: idle between epochs is not a hang
            _watchdog.retire(self._hb)

    def num_batches(self, batch_size, drop_remainder=False):
        n = self._n
        return n // batch_size if drop_remainder else int(np.ceil(n / batch_size))


def load_normalized_samples(split_dir):
    """One split's recordings z-scored with the SAME per-split channel stats
    the training loaders apply (load_normalized_split_datasets) — the one
    shared recipe for eval paths that feed trained models raw recordings
    (models never saw unnormalized amplitudes). Returns an ArrayDataset."""
    from .datasets import ArrayDataset

    X, Y = samples_to_arrays(load_shard_samples(split_dir))
    return ArrayDataset(X, Y, normalize=True, grid_search=False)


def samples_to_arrays(samples):
    """[[x, y], ...] -> (X (N, T, C), Y (N, ...)) dense arrays.

    x is squeezed like the reference __getitem__ (a leading singleton batch
    axis may be present); y keeps its stored shape — the label-shape branch
    dispatch downstream depends on it (e.g. D4IC labels are (S, 1),
    ref dream4_datasets.py:153 applies no squeeze to y)."""
    X = np.stack([np.squeeze(s[0]) for s in samples]).astype(np.float32)
    Y = np.stack([np.atleast_1d(np.asarray(s[1]))
                  for s in samples]).astype(np.float32)
    return X, Y


def apply_signal_format(X, signal_format, max_num_features_per_series=None,
                        dirspec_params=None):
    """Transform normalized (N, T, C) windows per the signal_format switch
    (ref dream4_datasets.py:120-151). Returns (N, F) features for flattened /
    dirspec formats, or X unchanged for "original"."""
    if signal_format in ("original", "wavelet_decomp"):
        # "wavelet_decomp" inputs are decomposed by
        # load_normalized_split_datasets BEFORE normalization (the reference
        # stores the decomposition computed at curation time on the raw
        # signal, sample entry X_WAV_DECOMP_IND, ref
        # synthetic_datasets.py:28,102-103; this build decomposes at load
        # instead of tripling the stored sample size); by this point X is
        # already in its final (T, C*(level+1)) width either way
        return X
    if "directed_spectrum" in signal_format:
        assert dirspec_params is not None
        feats = []
        for i in range(X.shape[0]):
            x = X[i]
            if max_num_features_per_series is not None:
                x = x[:max_num_features_per_series, :]
            hl = make_high_level_signal_features(
                x, fs=dirspec_params["fs"],
                min_freq=dirspec_params["min_freq"],
                max_freq=dirspec_params["max_freq"],
                directed_spectrum=dirspec_params["directed_spectrum"],
                csd_params=dirspec_params["csd_params"])
            ds = np.asarray(hl["dir_spec"])[0]
            if "vanilla" in signal_format:
                feats.append(ds.reshape(-1))
            else:
                feats.append(flatten_directed_spectrum_features(ds).reshape(-1))
        return np.stack(feats).astype(np.float32)
    if "power_features" in signal_format:
        raise NotImplementedError(
            "power_features format is declared but unimplemented in the "
            "reference as well (ref dream4_datasets.py:146)")
    if "flattened" in signal_format:
        assert max_num_features_per_series is not None
        assert max_num_features_per_series > 0
        return X[:, :max_num_features_per_series, :].reshape(
            X.shape[0], -1).astype(np.float32)
    raise ValueError(f"unknown signal_format: {signal_format!r}")


def decompose_windows(X, wavelet_level, wavelet_type="db1"):
    """Stationary-wavelet-decompose a batch of raw (N, T, C) windows into
    (N, T, C*(level+1)), channel c's bands contiguous in
    [cA, cD_level, ..., cD_1] order — the layout stored by the reference's
    curation as sample entry X_WAV_DECOMP_IND (ref time_series.py:10-26,
    synthetic_datasets.py:28) and consumed by the models' wavelet GC
    condensation (models/cmlp.py condense_wavelet_gc)."""
    from ..utils.time_series import swt

    N, T, C = X.shape
    assert T % (2 ** wavelet_level) == 0, (
        f"swt needs T divisible by 2**level; got T={T}, "
        f"level={wavelet_level}")
    bands = swt(np.transpose(X, (0, 2, 1)), wavelet_type, wavelet_level)
    stacked = np.stack(bands, axis=2)  # (N, C, level+1, T)
    return np.transpose(
        stacked.reshape(N, C * (wavelet_level + 1), T), (0, 2, 1)
    ).astype(np.float32)


def load_normalized_split_datasets(data_root_path, signal_format="original",
                                   shuffle=True, shuffle_seed=0,
                                   max_num_features_per_series=None,
                                   dirspec_params=None, grid_search=True,
                                   average_region_map=None,
                                   wavelet_level=None):
    """(train, validation) ArrayDatasets from a fold directory, z-scored with
    per-split dataset-wide channel statistics like the reference loaders
    (ref dream4_datasets.py:168-190, local_field_potential_datasets.py:198-220).

    average_region_map ({region: [channel indices]}) averages channel groups
    before normalization (ref local_field_potential_datasets.py:118-133).

    For "wavelet_decomp" formats the raw windows are swt-decomposed FIRST and
    the per-series z-scoring applies to the decomposed representation —
    the reference's order (decomposition at curation on the raw signal,
    normalization of the stored decomposed entry at load).
    """
    out = []
    for split in ("train", "validation"):
        split_dir = os.path.join(data_root_path, split)
        report = {}
        samples = load_shard_samples(split_dir, report=report)
        X, Y = samples_to_arrays(samples)
        if average_region_map is not None:
            X = np.stack([X[:, :, idxs].mean(axis=2)
                          for idxs in average_region_map.values()], axis=2)
        if "wavelet_decomp" in signal_format:
            assert wavelet_level, (
                "signal_format 'wavelet_decomp' requires wavelet_level >= 1")
            X = decompose_windows(X, wavelet_level)
        if shuffle:
            rng = np.random.default_rng(shuffle_seed)
            order = rng.permutation(len(X))
            X, Y = X[order], Y[order]
        ds = ArrayDataset(X, Y, normalize=True, grid_search=grid_search)
        # surface the loader's quarantine tally on the dataset (the in-memory
        # contract check may add post-transform quarantines of its own)
        ds.source_quarantine_report = report
        if signal_format != "original":
            feats = apply_signal_format(
                ds.X, signal_format,
                max_num_features_per_series=max_num_features_per_series,
                dirspec_params=dirspec_params)
            ds.X_features = feats
        out.append(ds)
    return tuple(out)
