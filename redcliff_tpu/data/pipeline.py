"""Epoch-granular batch streams: device-resident index plans + host prefetch.

REDCLIFF-S fitting is a grid of many small models, so per-dispatch overhead —
not FLOPs — dominates the step budget (BASELINE.md: ~0.24 ms/step floor past
G~64; BENCH_r05 shows the k-batch scan already matters at G=1). Classic
dataflow systems keep the accelerator fed by an asynchronous host pipeline
(TensorFlow, arXiv:1605.08695), and TPU cost models confirm utilization at
these shapes is gated by launch/infeed overhead (arXiv:2008.01040). This
module owns the data half of that story; the engines (parallel/grid.py, the
trainers) own the compute half.

Three stream modes, resolved by :func:`choose_stream_mode`:

``"epoch"``
    The dataset lives in HBM (``ArrayDataset.device_arrays``); the epoch's
    shuffled batch order becomes a *device* permutation array and ONE jit'd
    dispatch gathers the permuted epoch in-graph and scans the whole epoch's
    updates (plus one per-batch step for the epoch remainder). Bit-identical
    to the per-batch path: :func:`epoch_batch_plan` consumes the shuffle rng
    exactly like ``ArrayDataset.batches``, and the engine gathers *outside*
    the scan so the scanned step math compiles identically to the k-batch
    scan (an in-body per-iteration gather lets XLA fuse differently and
    drift by 1 ulp).
``"kscan"``
    The pre-existing k-batch ``lax.scan`` over stacked batch *data*
    (``scan_batches`` groups) — still the mode for freeze-by-batch-free fits
    whose data cannot stay device-resident.
``"per_batch"``
    One dispatch per batch; host-resident streams ride the double-buffered
    :func:`prefetch_batches` so host assembly + ``device_put`` of batch t+1
    overlaps compute of batch t.

Nothing here imports jax at module scope (bench.py's backend-free parent may
import the data package); jax is pulled in lazily where a backend is already
live.
"""
from __future__ import annotations

import queue
import threading
import time

import numpy as np

from redcliff_tpu import obs as _obs
from redcliff_tpu.runtime import faultinject as _faultinject
from redcliff_tpu.runtime import watchdog as _watchdog

__all__ = [
    "epoch_batch_plan",
    "choose_stream_mode",
    "dataset_device_bytes",
    "prefetch_batches",
    "PrefetchIterator",
    "dispatch_budget",
    "DEFAULT_MAX_DEVICE_DATASET_BYTES",
    "STREAM_MODES",
]

STREAM_MODES = ("auto", "epoch", "kscan", "per_batch")

# HBM-residency ceiling for the epoch stream: datasets beyond this stay host
# resident (prefetched). The epoch dispatch materializes one transient
# permuted copy of the epoch in HBM (the out-of-scan gather that buys
# bit-identity with the per-batch path), so the true high-water mark is
# ~2x this value — 2 GiB keeps that comfortably inside any real chip's HBM
# alongside the grid state; every dataset in this repo is orders of
# magnitude smaller anyway.
DEFAULT_MAX_DEVICE_DATASET_BYTES = 2 << 30


def epoch_batch_plan(n, batch_size, rng=None):
    """One epoch's batch order as index arrays: ``(full_idx, rem_idx)``.

    ``full_idx`` is ``(num_full_batches, batch_size)`` int32 — the scan axis
    of the epoch-scan dispatch; ``rem_idx`` is the trailing short batch's
    indices (possibly empty). CONTRACT: consumes ``rng`` exactly like
    ``ArrayDataset.batches`` (one ``rng.shuffle`` of ``arange(n)``), so a
    checkpointed rng state replays the same stream regardless of stream mode
    — pinned by tests/test_data_pipeline.py.
    """
    idx = np.arange(n)
    if rng is not None:
        rng.shuffle(idx)
    nb = n // batch_size
    full = idx[: nb * batch_size].astype(np.int32).reshape(nb, batch_size)
    rem = idx[nb * batch_size :].astype(np.int32)
    return full, rem


def dataset_device_bytes(ds):
    """Estimated HBM footprint of caching ``ds`` device-resident (X + Y),
    or None when the dataset doesn't expose dense arrays."""
    X = getattr(ds, "X", None)
    if X is None:
        return None
    total = int(np.asarray(X).nbytes)
    Y = getattr(ds, "Y", None)
    if Y is not None:
        total += int(np.asarray(Y).nbytes)
    return total


def choose_stream_mode(mode, train_ds, *, scan_batches=0, batch_size=1,
                       single_phase=True, freeze_by_batch=False,
                       max_device_bytes=None, labels_required=True):
    """Resolve a configured stream mode ("auto" included) against what the
    dataset/engine can actually support. Returns one of
    ``"epoch" | "kscan" | "per_batch"``.

    Epoch streaming needs: a device-batch-capable dataset small enough for
    HBM (``max_device_bytes``), a single-process run (committed device arrays
    cannot replicate across hosts), labels (the grid step signature), at
    least one full batch, single-phase epochs, and no per-batch freeze
    choreography. ``"auto"`` degrades epoch -> kscan (when ``scan_batches >
    1``) -> per_batch; an explicitly requested mode that is ineligible
    degrades the same way rather than erroring (the eligibility can depend on
    runtime facts like process count).
    """
    if mode not in STREAM_MODES:
        raise ValueError(
            f"unknown stream_mode {mode!r}; valid: {STREAM_MODES}")
    limit = (DEFAULT_MAX_DEVICE_DATASET_BYTES
             if max_device_bytes is None else max_device_bytes)

    def epoch_ok():
        if freeze_by_batch or not single_phase:
            return False
        if not getattr(train_ds, "supports_device_batches", False):
            return False
        if labels_required and getattr(train_ds, "Y", None) is None:
            return False
        try:
            if len(train_ds) < batch_size:
                return False
        except TypeError:
            return False
        nbytes = dataset_device_bytes(train_ds)
        if nbytes is None or nbytes > limit:
            return False
        import jax

        return jax.process_count() == 1

    def kscan_ok():
        return scan_batches and scan_batches > 1 and not freeze_by_batch \
            and single_phase

    if mode in ("auto", "epoch") and epoch_ok():
        return "epoch"
    if mode in ("auto", "epoch", "kscan") and kscan_ok():
        return "kscan"
    return "per_batch"


def dispatch_budget(num_full_batches, num_remainder_batches=0,
                    scan_batches=0, mode="per_batch"):
    """Expected TRAIN dispatches per single-phase epoch for a stream mode —
    the contract the dispatch-tripwire test and bench.py both assert against.
    ``num_remainder_batches`` counts trailing short/label-less batches that
    always take the per-batch step."""
    if mode == "epoch":
        return (1 if num_full_batches else 0) + num_remainder_batches
    if mode == "kscan" and scan_batches and scan_batches > 1:
        k = scan_batches
        # full k-groups scan; the partial trailing group flushes per-batch
        return (num_full_batches // k
                + num_full_batches % k + num_remainder_batches)
    return num_full_batches + num_remainder_batches


class PrefetchIterator:
    """Double-buffered background prefetch: a daemon thread drains the
    source ``iterator`` up to ``depth`` items ahead, applying ``put`` (e.g.
    ``jax.device_put``) in the thread, so host batch assembly + H2D transfer
    of item t+1 overlap the consumer's compute on item t. ``depth=2`` is
    classic double buffering; ``put=None`` keeps items host-side (multi-host
    runs, where inputs must stay uncommitted numpy) and still overlaps the
    host-side slicing.

    Order-preserving and exception-transparent: an error raised by the
    source (or ``put``) re-raises at the consumer's ``next()``.

    An iterator object rather than a generator so teardown is an explicit,
    callable contract: :meth:`close` unblocks a producer waiting on a full
    queue, joins the thread (bounded), and retires the heartbeat — exactly
    what a consumer abandoning the stream mid-epoch needs (serve session
    teardown does this on every disconnect). ``close`` is idempotent and
    also runs via ``with`` (context manager), at normal end-of-stream, and
    as a ``__del__`` backstop, so a for-loop consumer that just drains the
    stream needs no code change from the old generator form.

    Liveness: the worker stamps the ``"prefetch"`` heartbeat per produced
    item AND while waiting on a full queue (a blocked-on-slow-consumer
    worker is healthy; a worker wedged in the source or in ``put`` stops
    stamping and the watchdog escalates). The heartbeat retires when the
    stream ends or closes, so inter-epoch idle never reads as a hang.
    """

    _END, _ERR = object(), object()

    def __init__(self, iterator, depth=2, put=None):
        self._closed = False
        if depth < 1:
            # passthrough mode: no thread, no queue — next() defers to the
            # source directly and close() has nothing to join
            self._source = iter(iterator)
            self._thread = None
            return
        self._source = None
        self._q = queue.Queue(maxsize=depth)
        self._cancel = threading.Event()
        self._put = put
        self._iterator = iterator
        self._thread = threading.Thread(
            target=self._worker, name="batch-prefetch", daemon=True)
        self._thread.start()

    def _put_blocking(self, item):
        """Enqueue, waiting out a full queue unless cancelled. EVERY
        enqueue — items, END, and ERR alike — must use this: dropping the
        END/ERR sentinel when the queue happens to be full would leave the
        consumer blocked on q.get() forever with the real error lost."""
        while not self._cancel.is_set():
            # a full queue means the CONSUMER is slow (e.g. compiling), not
            # that this thread is hung — keep the heartbeat alive
            _watchdog.stamp("prefetch")
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _worker(self):
        try:
            for item in self._iterator:
                if self._cancel.is_set():
                    return
                _watchdog.stamp("prefetch")
                _faultinject.hang_point("prefetch")
                # traced fill span (ring-only): the transform/device_put
                # half of producing one batch — a post-mortem flight record
                # shows what the prefetcher was filling when it wedged.
                # Enqueue-waiting on a full queue is deliberately outside
                # the span (a blocked-on-slow-consumer worker is healthy)
                with _obs.span("prefetch.fill", component="prefetch"):
                    if self._put is not None:
                        item = tuple(None if x is None else self._put(x)
                                     for x in item)
                _obs.counters.add("prefetch_items", 1)
                if not self._put_blocking(item):
                    return
            self._put_blocking(self._END)
        except BaseException as e:  # noqa: BLE001 — re-raised at consumer
            self._put_blocking((self._ERR, e))
        finally:
            # a cancelled worker retires its own heartbeat: its stamps
            # happen-before this (same thread), so an abandoning consumer
            # can never be overtaken by a late stamp re-registering the
            # beat after the consumer retired it (false-hang orphan)
            if self._cancel.is_set():
                _watchdog.retire("prefetch")

    def __iter__(self):
        return self

    def __next__(self):
        if self._thread is None:
            if self._closed:
                raise StopIteration
            return next(self._source)
        if self._closed:
            raise StopIteration
        # consumer-side stall accounting: time blocked on an empty
        # queue IS the pipeline's un-overlapped fill cost. Counted into
        # obs.counters (the grid folds it into dispatch_stats.
        # prefetch_stall_ms); stalls > 1 ms also land in the prefetch
        # flight ring
        t_get0 = time.perf_counter()
        item = self._q.get()
        wait_ms = (time.perf_counter() - t_get0) * 1e3
        _obs.counters.add("prefetch_stall_ms", wait_ms)
        if wait_ms > 1.0:
            _obs.record_span("prefetch.stall", wait_ms,
                             component="prefetch")
        if item is self._END:
            self.close()
            raise StopIteration
        if isinstance(item, tuple) and len(item) == 2 \
                and item[0] is self._ERR:
            self.close()
            raise item[1]
        return item

    def close(self):
        """Unblock and join the producer thread, retire the heartbeat.
        Idempotent; safe mid-stream (the abandonment path) and after
        end-of-stream alike. Buffered-but-undelivered items are dropped."""
        if self._closed:
            return
        self._closed = True
        if self._thread is None:
            return
        self._cancel.set()
        # unblock a producer waiting on a full queue, then let it exit
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        # bounded join, then retire: covers the normal end-of-stream case
        # (worker already gone, never saw the cancel) while the worker's
        # own cancelled-path retire above closes the abandonment race
        self._thread.join(timeout=5.0)
        _watchdog.retire("prefetch")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):
        # backstop only — explicit close() (or exhaustion) is the contract;
        # GC timing must not be load-bearing for thread teardown
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter may be tearing down
            pass


def prefetch_batches(iterator, depth=2, put=None):
    """Construct a :class:`PrefetchIterator` over ``iterator`` — see its
    docstring for the full contract. Kept as the call-site spelling (every
    engine loop reads ``for batch in prefetch_batches(...)``); consumers
    that may abandon the stream early should hold the returned object and
    call ``close()`` (or use it as a context manager)."""
    return PrefetchIterator(iterator, depth=depth, put=put)
