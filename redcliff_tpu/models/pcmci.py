"""PCMCI causal discovery with linear partial-correlation tests.

Native replacement for the external ``tigramite`` dependency the reference
uses for its Table-2 supervised-discovery comparisons (PCMCI / R-PCMCI with
the ParCorr test, imported at
/root/reference/evaluate/eval_algsT_by_expSynSys12112_forF1RocAucCausalDistStats.py:13-40;
the R-PCMCI usage there masks recording windows by regime and runs per-regime
discovery).

Implements the two-phase PCMCI algorithm of Runge et al. (Science Advances
2019): a per-target PC1 condition-selection phase over lagged candidates,
then the momentary-conditional-independence (MCI) phase conditioning on both
the target's and the source's selected parents.  The conditional-independence
primitive is ParCorr — partial correlation via OLS residualization with a
two-sided t-test.

Data enters as one (T, N) recording or a list of recordings (lagged samples
never span recording boundaries, which is how the reference feeds its
windowed datasets).
"""
from __future__ import annotations

import numpy as np
from scipy import stats

__all__ = ["parcorr_test", "pcmci", "pcmci_val_graph", "rpcmci_by_regime"]


def parcorr_test(x, y, Z=None):
    """Partial correlation of x and y given the columns of Z.

    Returns (r, p_value): Pearson correlation of the OLS residuals of x and y
    on [1, Z], with the two-sided t-test p-value at n - 2 - dim(Z) degrees of
    freedom (tigramite ParCorr semantics)."""
    x = np.asarray(x, dtype=np.float64).ravel()
    y = np.asarray(y, dtype=np.float64).ravel()
    n = len(x)
    dim_z = 0
    if Z is not None and np.size(Z) > 0:
        Z = np.asarray(Z, dtype=np.float64)
        Z = Z.reshape(n, -1)
        dim_z = Z.shape[1]
        design = np.column_stack([np.ones(n), Z])
        x = x - design @ np.linalg.lstsq(design, x, rcond=None)[0]
        y = y - design @ np.linalg.lstsq(design, y, rcond=None)[0]
    else:
        x = x - x.mean()
        y = y - y.mean()
    denom = np.sqrt(np.sum(x * x) * np.sum(y * y))
    if denom <= 0:
        return 0.0, 1.0
    r = float(np.clip(np.sum(x * y) / denom, -0.9999999, 0.9999999))
    df = n - 2 - dim_z
    if df <= 0:
        return r, 1.0
    t = r * np.sqrt(df / (1.0 - r * r))
    p = 2.0 * stats.t.sf(abs(t), df)
    return r, float(p)


def _lagged_samples(recordings, tau_max):
    """Stack (X_t, {X_{t-tau}}) rows from each recording without crossing
    boundaries.  Returns (present (M, N), lagged (M, N, tau_max))."""
    present, lagged = [], []
    for rec in recordings:
        rec = np.asarray(rec, dtype=np.float64)
        T = rec.shape[0]
        if T <= tau_max:
            continue
        present.append(rec[tau_max:])
        lagged.append(np.stack([rec[tau_max - tau : T - tau]
                                for tau in range(1, tau_max + 1)], axis=2))
    if not present:
        raise ValueError("no recording longer than tau_max")
    return np.concatenate(present), np.concatenate(lagged)


def _cand_series(lagged, i, tau):
    return lagged[:, i, tau - 1]


def pcmci(data, tau_max=1, pc_alpha=0.2, alpha_level=0.05,
          max_conds_dim=None, max_combinations=1):
    """Run PCMCI over lagged links (tau in 1..tau_max).

    Args:
      data: (T, N) array or list of (T_k, N) recordings.
      pc_alpha: removal threshold in the condition-selection phase.
      alpha_level: significance level defining the returned parent sets.
      max_conds_dim: cap on condition-set size in phase 1.
      max_combinations: number of strongest-condition subsets tried per size
        (1 = tigramite's default behavior of testing the top conditions).

    Returns dict with "val_matrix" and "p_matrix" of shape
    (N, N, tau_max + 1) — entry [i, j, tau] is the MCI statistic/p-value for
    X_i(t-tau) -> X_j(t) (tau = 0 slice kept zero/one for tigramite shape
    parity) — and "parents": {j: [(i, tau), ...] sorted by strength}.
    """
    if isinstance(data, np.ndarray) and data.ndim == 2:
        recordings = [data]
    else:
        recordings = list(data)
    N = np.asarray(recordings[0]).shape[1]
    present, lagged = _lagged_samples(recordings, tau_max)
    # phase 2 conditions on source parents shifted by tau, reaching lags up
    # to 2*tau_max; build the extended window when the data allows it
    try:
        present_ext, lagged_ext = _lagged_samples(recordings, 2 * tau_max)
        ext_tau_max = 2 * tau_max
    except ValueError:
        present_ext, lagged_ext = present, lagged
        ext_tau_max = tau_max

    candidates = [(i, tau) for i in range(N) for tau in range(1, tau_max + 1)]
    if max_conds_dim is None:
        max_conds_dim = len(candidates) - 1

    # ---- phase 1: PC1 condition selection per target -----------------------
    parents = {}
    for j in range(N):
        remaining = []
        strength = {}
        # the initialization pass doubles as the p_dim=0 (unconditional)
        # removal round
        for c in candidates:
            r, p = parcorr_test(present[:, j], _cand_series(lagged, *c))
            if p <= pc_alpha:
                remaining.append(c)
                strength[c] = abs(r)
        p_dim = 1
        while p_dim <= max_conds_dim and p_dim < len(remaining):
            removed_any = False
            # strongest-first ordering stabilizes the selection; one sort
            # per round, candidates iterate over a snapshot of it
            ordering = sorted(remaining, key=lambda c: -strength[c])
            for cand in ordering:
                if cand not in remaining:
                    continue
                others = [c for c in ordering
                          if c != cand and c in remaining]
                if len(others) < p_dim:
                    continue
                for start in range(max_combinations):
                    conds = others[start : start + p_dim]
                    if len(conds) < p_dim:
                        break
                    Z = np.column_stack(
                        [_cand_series(lagged, *c) for c in conds]) \
                        if conds else None
                    r, p = parcorr_test(present[:, j],
                                        _cand_series(lagged, *cand), Z)
                    strength[cand] = min(strength[cand], abs(r))
                    if p > pc_alpha:
                        remaining.remove(cand)
                        removed_any = True
                        break
            p_dim += 1
            if not removed_any and p_dim > 1:
                break
        parents[j] = sorted(remaining, key=lambda c: -strength[c])

    # ---- phase 2: MCI ------------------------------------------------------
    val = np.zeros((N, N, tau_max + 1))
    pmat = np.ones((N, N, tau_max + 1))
    for j in range(N):
        for (i, tau) in candidates:
            conds = [c for c in parents[j] if c != (i, tau)]
            # source parents shifted by tau (momentary conditioning); the
            # extended lag window makes lags up to 2*tau_max addressable
            for (k, ktau) in parents[i]:
                if ktau + tau <= ext_tau_max:
                    shifted = (k, ktau + tau)
                    if shifted not in conds and shifted != (i, tau):
                        conds.append(shifted)
            Z = np.column_stack(
                [_cand_series(lagged_ext, *c) for c in conds]) \
                if conds else None
            r, p = parcorr_test(present_ext[:, j],
                                _cand_series(lagged_ext, i, tau), Z)
            val[i, j, tau] = r
            pmat[i, j, tau] = p

    sig_parents = {
        j: sorted([(i, tau) for (i, tau) in candidates
                   if pmat[i, j, tau] <= alpha_level],
                  key=lambda c: -abs(val[c[0], j, c[1]]))
        for j in range(N)
    }
    return {"val_matrix": val, "p_matrix": pmat, "parents": sig_parents}


def pcmci_val_graph(result, alpha_level=0.05, ignore_lag=True):
    """Collapse a pcmci() result into a scored adjacency: entry (i, j) is the
    max |MCI value| over significant lags of X_i -> X_j (the graph the
    supervised-discovery scoring consumes)."""
    val = np.abs(result["val_matrix"]).copy()
    val[result["p_matrix"] > alpha_level] = 0.0
    if ignore_lag:
        return val[:, :, 1:].max(axis=2)
    return val[:, :, 1:]


def rpcmci_by_regime(recordings, regime_labels, num_regimes, tau_max=1,
                     pc_alpha=0.2, alpha_level=0.05):
    """Regime-resolved PCMCI: split recordings by their regime label and run
    discovery per regime (the reference's R-PCMCI data prep masks windows by
    regime, eval_algsT...py:45+).  Returns {regime: pcmci result}."""
    regime_labels = np.asarray(regime_labels).astype(int)
    assert len(regime_labels) == len(recordings)
    out = {}
    for regime in range(num_regimes):
        regs = [rec for rec, lab in zip(recordings, regime_labels)
                if lab == regime]
        if not regs:
            out[regime] = None
            continue
        out[regime] = pcmci(regs, tau_max=tau_max, pc_alpha=pc_alpha,
                            alpha_level=alpha_level)
    return out
