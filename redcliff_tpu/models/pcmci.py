"""PCMCI causal discovery with linear partial-correlation tests.

Native replacement for the external ``tigramite`` dependency the reference
uses for its Table-2 supervised-discovery comparisons (PCMCI / R-PCMCI with
the ParCorr test, imported at
/root/reference/evaluate/eval_algsT_by_expSynSys12112_forF1RocAucCausalDistStats.py:13-40;
the R-PCMCI usage there masks recording windows by regime and runs per-regime
discovery).

Implements the two-phase PCMCI algorithm of Runge et al. (Science Advances
2019): a per-target PC1 condition-selection phase over lagged candidates,
then the momentary-conditional-independence (MCI) phase conditioning on both
the target's and the source's selected parents.  The conditional-independence
primitive is ParCorr — partial correlation via OLS residualization with a
two-sided t-test.

Data enters as one (T, N) recording or a list of recordings (lagged samples
never span recording boundaries, which is how the reference feeds its
windowed datasets).
"""
from __future__ import annotations

import numpy as np
from scipy import stats

__all__ = ["parcorr_test", "pcmci", "pcmci_val_graph", "rpcmci_by_regime",
           "rpcmci"]


def parcorr_test(x, y, Z=None):
    """Partial correlation of x and y given the columns of Z.

    Returns (r, p_value): Pearson correlation of the OLS residuals of x and y
    on [1, Z], with the two-sided t-test p-value at n - 2 - dim(Z) degrees of
    freedom (tigramite ParCorr semantics)."""
    x = np.asarray(x, dtype=np.float64).ravel()
    y = np.asarray(y, dtype=np.float64).ravel()
    n = len(x)
    dim_z = 0
    if Z is not None and np.size(Z) > 0:
        Z = np.asarray(Z, dtype=np.float64)
        Z = Z.reshape(n, -1)
        dim_z = Z.shape[1]
        design = np.column_stack([np.ones(n), Z])
        x = x - design @ np.linalg.lstsq(design, x, rcond=None)[0]
        y = y - design @ np.linalg.lstsq(design, y, rcond=None)[0]
    else:
        x = x - x.mean()
        y = y - y.mean()
    denom = np.sqrt(np.sum(x * x) * np.sum(y * y))
    if denom <= 0:
        return 0.0, 1.0
    r = float(np.clip(np.sum(x * y) / denom, -0.9999999, 0.9999999))
    df = n - 2 - dim_z
    if df <= 0:
        return r, 1.0
    t = r * np.sqrt(df / (1.0 - r * r))
    p = 2.0 * stats.t.sf(abs(t), df)
    return r, float(p)


def _lagged_samples(recordings, tau_max):
    """Stack (X_t, {X_{t-tau}}) rows from each recording without crossing
    boundaries.  Returns (present (M, N), lagged (M, N, tau_max))."""
    present, lagged = [], []
    for rec in recordings:
        rec = np.asarray(rec, dtype=np.float64)
        T = rec.shape[0]
        if T <= tau_max:
            continue
        present.append(rec[tau_max:])
        lagged.append(np.stack([rec[tau_max - tau : T - tau]
                                for tau in range(1, tau_max + 1)], axis=2))
    if not present:
        raise ValueError("no recording longer than tau_max")
    return np.concatenate(present), np.concatenate(lagged)


def _cand_series(lagged, i, tau):
    return lagged[:, i, tau - 1]


def pcmci(data, tau_max=1, pc_alpha=0.2, alpha_level=0.05,
          max_conds_dim=None, max_combinations=1):
    """Run PCMCI over lagged links (tau in 1..tau_max).

    Args:
      data: (T, N) array or list of (T_k, N) recordings.
      pc_alpha: removal threshold in the condition-selection phase.
      alpha_level: significance level defining the returned parent sets.
      max_conds_dim: cap on condition-set size in phase 1.
      max_combinations: number of strongest-condition subsets tried per size
        (1 = tigramite's default behavior of testing the top conditions).

    Returns dict with "val_matrix" and "p_matrix" of shape
    (N, N, tau_max + 1) — entry [i, j, tau] is the MCI statistic/p-value for
    X_i(t-tau) -> X_j(t) (tau = 0 slice kept zero/one for tigramite shape
    parity) — and "parents": {j: [(i, tau), ...] sorted by strength}.
    """
    if isinstance(data, np.ndarray) and data.ndim == 2:
        recordings = [data]
    else:
        recordings = list(data)
    N = np.asarray(recordings[0]).shape[1]
    present, lagged = _lagged_samples(recordings, tau_max)
    # phase 2 conditions on source parents shifted by tau, reaching lags up
    # to 2*tau_max; build the extended window when the data allows it
    try:
        present_ext, lagged_ext = _lagged_samples(recordings, 2 * tau_max)
        ext_tau_max = 2 * tau_max
    except ValueError:
        present_ext, lagged_ext = present, lagged
        ext_tau_max = tau_max

    candidates = [(i, tau) for i in range(N) for tau in range(1, tau_max + 1)]
    if max_conds_dim is None:
        max_conds_dim = len(candidates) - 1

    # ---- phase 1: PC1 condition selection per target -----------------------
    parents = {}
    for j in range(N):
        remaining = []
        strength = {}
        # the initialization pass doubles as the p_dim=0 (unconditional)
        # removal round
        for c in candidates:
            r, p = parcorr_test(present[:, j], _cand_series(lagged, *c))
            if p <= pc_alpha:
                remaining.append(c)
                strength[c] = abs(r)
        p_dim = 1
        while p_dim <= max_conds_dim and p_dim < len(remaining):
            removed_any = False
            # strongest-first ordering stabilizes the selection; one sort
            # per round, candidates iterate over a snapshot of it
            ordering = sorted(remaining, key=lambda c: -strength[c])
            for cand in ordering:
                if cand not in remaining:
                    continue
                others = [c for c in ordering
                          if c != cand and c in remaining]
                if len(others) < p_dim:
                    continue
                for start in range(max_combinations):
                    conds = others[start : start + p_dim]
                    if len(conds) < p_dim:
                        break
                    Z = np.column_stack(
                        [_cand_series(lagged, *c) for c in conds]) \
                        if conds else None
                    r, p = parcorr_test(present[:, j],
                                        _cand_series(lagged, *cand), Z)
                    strength[cand] = min(strength[cand], abs(r))
                    if p > pc_alpha:
                        remaining.remove(cand)
                        removed_any = True
                        break
            p_dim += 1
            if not removed_any and p_dim > 1:
                break
        parents[j] = sorted(remaining, key=lambda c: -strength[c])

    # ---- phase 2: MCI ------------------------------------------------------
    val = np.zeros((N, N, tau_max + 1))
    pmat = np.ones((N, N, tau_max + 1))
    for j in range(N):
        for (i, tau) in candidates:
            conds = [c for c in parents[j] if c != (i, tau)]
            # source parents shifted by tau (momentary conditioning); the
            # extended lag window makes lags up to 2*tau_max addressable
            for (k, ktau) in parents[i]:
                if ktau + tau <= ext_tau_max:
                    shifted = (k, ktau + tau)
                    if shifted not in conds and shifted != (i, tau):
                        conds.append(shifted)
            Z = np.column_stack(
                [_cand_series(lagged_ext, *c) for c in conds]) \
                if conds else None
            r, p = parcorr_test(present_ext[:, j],
                                _cand_series(lagged_ext, i, tau), Z)
            val[i, j, tau] = r
            pmat[i, j, tau] = p

    sig_parents = {
        j: sorted([(i, tau) for (i, tau) in candidates
                   if pmat[i, j, tau] <= alpha_level],
                  key=lambda c: -abs(val[c[0], j, c[1]]))
        for j in range(N)
    }
    return {"val_matrix": val, "p_matrix": pmat, "parents": sig_parents}


def pcmci_val_graph(result, alpha_level=0.05, ignore_lag=True):
    """Collapse a pcmci() result into a scored adjacency: entry (i, j) is the
    max |MCI value| over significant lags of X_i -> X_j (the graph the
    supervised-discovery scoring consumes)."""
    val = np.abs(result["val_matrix"]).copy()
    val[result["p_matrix"] > alpha_level] = 0.0
    if ignore_lag:
        return val[:, :, 1:].max(axis=2)
    return val[:, :, 1:]


def _ridge_var_fit(feats, targs, lam):
    """Ridge-regularized linear VAR solve: feats (M, F), targs (M, N)."""
    F = feats.shape[1]
    A = feats.T @ feats + lam * np.eye(F)
    return np.linalg.solve(A, feats.T @ targs)  # (F, N)


def _var_design(rec, tau_max):
    """Per-recording lagged design matrix (T-tau, N*tau + 1) with intercept
    and its targets (T-tau, N)."""
    rec = np.asarray(rec, dtype=np.float64)
    T, N = rec.shape
    if T <= tau_max:
        return None, None
    cols = [rec[tau_max - tau : T - tau] for tau in range(1, tau_max + 1)]
    feats = np.concatenate(cols + [np.ones((T - tau_max, 1))], axis=1)
    return feats, rec[tau_max:]


def _viterbi_assign(errors, switching_penalty):
    """Min-cost per-step regime path: errors (T, K); transition cost
    ``switching_penalty`` per regime change."""
    T, K = errors.shape
    cost = errors[0].copy()
    back = np.zeros((T, K), dtype=int)
    for t in range(1, T):
        stay = cost
        best_prev = stay.min()
        trans = np.minimum(stay, best_prev + switching_penalty)
        back[t] = np.where(stay <= best_prev + switching_penalty,
                           np.arange(K), stay.argmin())
        cost = trans + errors[t]
    path = np.zeros(T, dtype=int)
    path[-1] = int(cost.argmin())
    for t in range(T - 1, 0, -1):
        path[t - 1] = back[t, path[t]]
    return path


def rpcmci(recordings, num_regimes, tau_max=1, assign_per="recording",
           n_iter=20, n_inits=3, switching_penalty=0.0, ridge_lam=1e-2,
           seed=0, pc_alpha=0.2, alpha_level=0.05):
    """Unsupervised regime-PCMCI: jointly learn a regime assignment and
    per-regime causal graphs from unlabeled recordings — the capability of
    tigramite's RPCMCI (Saggioro et al. 2020, "Reconstructing regime-dependent
    causal relationships from observational time series"; external Table-2
    dep in SURVEY §2.5 / ref evaluate notebook cell 71), implemented natively.

    Annealed alternating optimization: (a) fit one ridge-VAR error model per
    regime on its assigned samples; (b) reassign each unit to the regime
    whose model predicts it best — a whole recording when
    ``assign_per="recording"`` (the D4IC structure: one dominant network per
    window), or per time step via a min-cost path with ``switching_penalty``
    per regime change when ``assign_per="timestep"``. The best of ``n_inits``
    random initializations (lowest total prediction error) wins, then PCMCI
    runs per learned regime.

    Returns {"assignment", "results": {regime: pcmci result | None},
    "error": float}. ``assignment`` is (num_recordings,) int for recording
    mode (-1 marks recordings shorter than tau_max, which are excluded), or
    a list of per-recording (T - tau_max,) int paths for timestep mode (None
    for excluded recordings). Learned regime indices are arbitrary — align
    to ground truth with utils.metrics Hungarian matching before scoring.
    """
    recordings = [np.asarray(r, dtype=np.float64) for r in recordings]
    all_designs = [_var_design(rec, tau_max) for rec in recordings]
    # recordings too short for the lag structure are excluded; `keep` maps
    # filtered-design positions back to recording indices
    keep = [i for i, (f, _) in enumerate(all_designs) if f is not None]
    designs = [all_designs[i] for i in keep]
    if not designs:
        raise ValueError("no recording longer than tau_max")
    rng = np.random.default_rng(seed)
    R = len(designs)
    K = num_regimes

    def errors_for(W, feats, targs):
        resid = feats @ W - targs
        return (resid ** 2).sum(axis=1)  # per-step error

    best = None
    for _ in range(max(n_inits, 1)):
        if assign_per == "recording":
            assign = rng.integers(0, K, size=R)
        else:
            # contiguous-chunk random init: per-timestep random labels make
            # every regime fit the same average model (no identifiability);
            # chunks give the initial fits distinct temporal support
            assign = []
            for _, targs in designs:
                T_r = len(targs)
                chunk = max(T_r // (4 * K), tau_max + 1)
                labels = np.repeat(rng.integers(0, K, size=T_r // chunk + 1),
                                   chunk)[:T_r]
                assign.append(labels)
        for _ in range(n_iter):
            # (a) per-regime ridge-VAR fit over assigned rows
            Ws = []
            for k in range(K):
                rows_f, rows_t = [], []
                for r, (feats, targs) in enumerate(designs):
                    sel = (np.full(len(targs), assign[r] == k)
                           if assign_per == "recording" else assign[r] == k)
                    if np.any(sel):
                        rows_f.append(feats[sel])
                        rows_t.append(targs[sel])
                if rows_f:
                    Ws.append(_ridge_var_fit(np.concatenate(rows_f),
                                             np.concatenate(rows_t),
                                             ridge_lam))
                else:
                    Ws.append(None)  # empty regime: keep it empty
            # (b) reassignment
            new_assign = [] if assign_per == "timestep" else np.zeros(R, int)
            total = 0.0
            for r, (feats, targs) in enumerate(designs):
                errs = np.stack(
                    [errors_for(W, feats, targs) if W is not None
                     else np.full(len(targs), np.inf) for W in Ws], axis=1)
                if assign_per == "recording":
                    rec_err = errs.sum(axis=0)
                    new_assign[r] = int(rec_err.argmin())
                    total += rec_err[new_assign[r]]
                else:
                    # scale-free switching cost: `switching_penalty` is
                    # measured in average per-step errors, so the same value
                    # works across signal scales/noise levels
                    finite = errs.min(axis=1)
                    pen = switching_penalty * float(
                        finite[np.isfinite(finite)].mean())
                    path = _viterbi_assign(errs, pen)
                    new_assign.append(path)
                    total += errs[np.arange(len(path)), path].sum()
            if assign_per == "recording":
                converged = np.array_equal(new_assign, assign)
            else:
                converged = all(np.array_equal(a, b)
                                for a, b in zip(new_assign, assign))
            assign = new_assign
            if converged:
                break
        if best is None or total < best[0]:
            best = (total, assign)

    total, assign = best
    # final per-regime discovery on the learned segmentation
    if assign_per == "recording":
        results = rpcmci_by_regime([recordings[i] for i in keep], assign, K,
                                   tau_max=tau_max, pc_alpha=pc_alpha,
                                   alpha_level=alpha_level)
        full_assign = np.full(len(recordings), -1, dtype=int)
        full_assign[keep] = assign
        return {"assignment": full_assign, "results": results,
                "error": float(total)}

    results = {}
    for k in range(K):
        regs = []
        for d, i in enumerate(keep):
            rec = recordings[i]
            path = assign[d]
            start = None
            for t in range(len(path) + 1):
                active = t < len(path) and path[t] == k
                if active and start is None:
                    start = t
                elif not active and start is not None:
                    if t - start > tau_max:
                        # include the lag context before the segment
                        regs.append(rec[start : t + tau_max])
                    start = None
        results[k] = (pcmci(regs, tau_max=tau_max, pc_alpha=pc_alpha,
                            alpha_level=alpha_level) if regs else None)
    full_paths = [None] * len(recordings)
    for d, i in enumerate(keep):
        full_paths[i] = assign[d]
    return {"assignment": full_paths, "results": results,
            "error": float(total)}


def rpcmci_by_regime(recordings, regime_labels, num_regimes, tau_max=1,
                     pc_alpha=0.2, alpha_level=0.05):
    """Regime-resolved PCMCI: split recordings by their regime label and run
    discovery per regime (the reference's R-PCMCI data prep masks windows by
    regime, eval_algsT...py:45+).  Returns {regime: pcmci result}."""
    regime_labels = np.asarray(regime_labels).astype(int)
    assert len(regime_labels) == len(recordings)
    out = {}
    for regime in range(num_regimes):
        regs = [rec for rec, lab in zip(recordings, regime_labels)
                if lab == regime]
        if not regs:
            out[regime] = None
            continue
        out[regime] = pcmci(regs, tau_max=tau_max, pc_alpha=pc_alpha,
                            alpha_level=alpha_level)
    return out
