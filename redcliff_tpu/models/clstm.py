"""Tensorized cLSTM Granger-causal forecaster.

The reference keeps one single-layer LSTM + 1x1-conv head per output series and
loops over them in Python (ref models/clstm.py:12-112: ``nn.LSTM(num_series,
hidden)`` per series, predictions concatenated). Here the C per-series LSTMs are
one stacked weight block scanned over time:

    w_ih: (S, 4H, C)   input->gate weights, torch gate order (i, f, g, o)
    w_hh: (S, 4H, H)   hidden->gate weights
    b:    (S, 4H)      merged input+hidden bias
    head: w (S, H), b (S,)   the reference's Conv1d(hidden, 1, 1) readout

The input projection for every series and timestep is one einsum hoisted out of
the ``lax.scan`` (it has no sequential dependence), so the scan body is just the
small recurrent matmul + gate math — the XLA-friendly shape of an LSTM.

The Granger-causal readout is the column norm of ``w_ih`` over the gate axis
(ref clstm.py:126-156: ``torch.norm(net.lstm.weight_ih_l0, dim=0)``), one
reduction for all series at once; the proximal update soft-thresholds the same
column groups (ref clstm.py:114-123).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from redcliff_tpu.models import cmlp as cmlp_mod
from redcliff_tpu.ops.prox import soft_threshold_by_group_norm

__all__ = [
    "init_clstm_params",
    "clstm_forward",
    "clstm_gc",
    "clstm_prox_update",
]


def init_clstm_params(key, num_series: int, hidden: int):
    """Parameters for C per-series LSTMs as one batched pytree.

    All LSTM weights/biases follow torch's LSTM default U(±1/sqrt(hidden)); the
    head follows torch's Conv1d default U(±1/sqrt(fan_in=hidden)).
    """
    S = num_series
    bound = 1.0 / math.sqrt(hidden)
    k1, k2, k3, k4, k5 = jax.random.split(key, 5)

    def u(k, shape, b):
        return jax.random.uniform(k, shape, minval=-b, maxval=b)

    return {
        "w_ih": u(k1, (S, 4 * hidden, num_series), bound),
        "w_hh": u(k2, (S, 4 * hidden, hidden), bound),
        # torch keeps separate b_ih/b_hh, each U(±1/sqrt(H)); their sum enters
        # the gates, so one merged bias drawn twice and summed is equivalent
        "b": u(k3, (S, 4 * hidden), bound) + u(k4, (S, 4 * hidden), bound),
        "head": {
            "w": u(k5, (S, hidden), bound),
            "b": u(jax.random.split(k5)[1], (S,), bound),
        },
    }


def clstm_forward(params, X, hidden=None):
    """Forward pass over every output series at once.

    Args:
      params: pytree from init_clstm_params (leading axes may be added by vmap).
      X: (B, T, C) input signal.
      hidden: optional (h, c) carry, each (B, S, H), to continue a sequence.
    Returns:
      (preds (B, T, S), (h, c)) matching the reference's concatenated per-net
      outputs + hidden states (ref clstm.py:100-112).
    """
    w_ih, w_hh, b = params["w_ih"], params["w_hh"], params["b"]
    S, H4, _ = w_ih.shape
    H = H4 // 4
    B = X.shape[0]

    # input contributions for all series/timesteps at once: (T, B, S, 4H)
    zx = jnp.einsum("btc,sgc->tbsg", X, w_ih) + b

    if hidden is None:
        h0 = jnp.zeros((B, S, H), dtype=X.dtype)
        c0 = jnp.zeros((B, S, H), dtype=X.dtype)
    else:
        h0, c0 = hidden

    def step(carry, zx_t):
        h, c = carry
        z = zx_t + jnp.einsum("bsh,sgh->bsg", h, w_hh)
        zi, zf, zg, zo = jnp.split(z, 4, axis=-1)  # torch gate order i,f,g,o
        c = jax.nn.sigmoid(zf) * c + jax.nn.sigmoid(zi) * jnp.tanh(zg)
        h = jax.nn.sigmoid(zo) * jnp.tanh(c)
        return (h, c), h

    (h, c), hs = jax.lax.scan(step, (h0, c0), zx)  # hs: (T, B, S, H)
    preds = jnp.einsum("tbsh,sh->bts", hs, params["head"]["w"]) + params["head"]["b"]
    return preds, (h, c)


def clstm_gc(params, threshold=False, wavelet_mask=None, rank_wavelets=False,
             num_chans=None, combine_wavelet_representations=False):
    """Granger-causal readout: column norms of the input-hidden block over the
    gate axis (ref clstm.py:126-156). Returns (C_out, C_in); entry (i, j) scores
    series j driving series i."""
    GC = jnp.sqrt(jnp.sum(params["w_ih"] ** 2, axis=1))
    if rank_wavelets:
        assert wavelet_mask is not None
        GC = wavelet_mask * GC
    if combine_wavelet_representations and num_chans is not None and GC.shape[0] != num_chans:
        GC = cmlp_mod.condense_wavelet_gc(GC, num_chans)
    if threshold:
        return (GC > 0).astype(jnp.int32)
    return GC


def clstm_prox_update(params, lam, lr):
    """Proximal group soft-threshold on the input-hidden columns
    (ref clstm.py:114-123) — functional, one fused op for all series."""
    W = params["w_ih"]
    norm = jnp.sqrt(jnp.sum(W * W, axis=1, keepdims=True))
    return dict(params, w_ih=soft_threshold_by_group_norm(W, norm, lam * lr))
