"""cLSTM_FM — single-factor cLSTM forecaster baseline.

Functional rebuild of /root/reference/models/clstm_fm.py:16-393: a cLSTM (one
LSTM per series, tensorized here) trained teacher-forced on overlapping context
windows, with Adam + an L1 adjacency penalty in the loss (the reference
explicitly skips the prox update in favor of Adam+L1, ref clstm_fm.py:165-167 —
the prox op stays available via models.clstm.clstm_prox_update).

The reference's ``arrange_input`` (ref clstm_fm.py:95-122) copies every length-
``context`` window into a new tensor with a Python loop; here the same windows
are a single static gather, and the per-window batch stays fused with the model
batch axis.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from redcliff_tpu.models import clstm as clstm_mod
from redcliff_tpu.models import cmlp as cmlp_mod
from redcliff_tpu.ops import losses as L

__all__ = ["CLSTMFMConfig", "CLSTMFM", "arrange_input"]


def arrange_input(X, context):
    """Overlapping teacher-forcing windows (ref clstm_fm.py:95-112).

    X: (B, T, C) -> (inputs, targets), both (B*(T-context), context, C); the
    target window is the input window shifted one step forward.
    """
    assert context >= 1
    B, T, C = X.shape
    n = T - context
    idx = jnp.arange(context)[None, :] + jnp.arange(n)[:, None]  # (n, context)
    inp = X[:, idx, :].reshape(B * n, context, C)
    tgt = X[:, idx + 1, :].reshape(B * n, context, C)
    return inp, tgt


@dataclass(frozen=True)
class CLSTMFMConfig:
    num_chans: int
    gen_hidden: int
    context: int
    max_input_length: int | None = None
    forecast_coeff: float = 1.0
    adj_l1_coeff: float = 0.0
    dagness_coeff: float = 0.0  # defined-but-disabled in the reference loss
    wavelet_level: int | None = None

    @property
    def num_series(self):
        if self.wavelet_level is not None:
            return self.num_chans * (self.wavelet_level + 1)
        return self.num_chans


class CLSTMFM:
    """Pure-functional model following the shared trainer protocol."""

    def __init__(self, config: CLSTMFMConfig):
        self.config = config

    def init(self, key):
        return {
            "factor": clstm_mod.init_clstm_params(
                key, self.config.num_series, self.config.gen_hidden)
        }

    def forward(self, params, X_in, hidden=None):
        """Teacher-forced predictions over a context window: (B', ctx, C) ->
        (B', ctx, C). Single factor, so the reference's factor sum
        (ref clstm_fm.py:56-81) is one call."""
        preds, hidden = clstm_mod.clstm_forward(params["factor"], X_in, hidden)
        return preds, hidden

    def gc(self, params, threshold=False, ignore_lag=True,
           combine_wavelet_representations=False, rank_wavelets=False):
        """List of per-factor GC estimates — length 1 (ref clstm_fm.py:84-93).
        LSTMs have no lag axis, so ignore_lag only controls a trailing
        singleton-lag dim for contract parity with lagged models."""
        cfg = self.config
        mask = (
            cmlp_mod.build_wavelet_ranking_mask(
                cfg.num_series, wavelets_per_chan=cfg.num_series // cfg.num_chans)
            if rank_wavelets and cfg.wavelet_level is not None
            else None
        )
        g = clstm_mod.clstm_gc(
            params["factor"], threshold=threshold, wavelet_mask=mask,
            rank_wavelets=rank_wavelets, num_chans=cfg.num_chans,
            combine_wavelet_representations=combine_wavelet_representations)
        if not ignore_lag:
            g = g[:, :, None]
        return [g]

    def loss(self, params, X):
        """Combined loss on a raw batch X (B, T, C): context-windowed
        teacher-forced forecasting MSE summed per channel + L1 of the GC
        estimate (ref clstm_fm.py:125-138)."""
        cfg = self.config
        if cfg.max_input_length is not None:
            X = X[:, : cfg.max_input_length, :]
        X_in, X_tgt = arrange_input(X, cfg.context)
        preds, _ = self.forward(params, X_in)
        forecasting = cfg.forecast_coeff * L.channelwise_forecast_mse(preds, X_tgt)
        adj_l1 = cfg.adj_l1_coeff * jnp.sum(jnp.abs(self.gc(params)[0]))
        combo = forecasting + adj_l1
        return combo, {"forecasting_loss": forecasting, "adj_l1_penalty": adj_l1}

    def apply_prox(self, params, lam, lr, penalty="GL"):
        """Optional GISTA-style prox on the input-hidden columns
        (ref clstm.py:114-123). LSTM weights have no lag axis, so only the GL
        column-group structure exists — reject other penalties rather than
        silently training with a different one than configured."""
        if penalty != "GL":
            raise ValueError(
                f"cLSTM prox supports only the 'GL' penalty (got {penalty!r})")
        return dict(params, factor=clstm_mod.clstm_prox_update(params["factor"], lam, lr))

    # ---- trainer protocol -------------------------------------------------
    def normalization_coeffs(self):
        return {
            "forecasting_loss": self.config.forecast_coeff,
            "adj_l1_penalty": self.config.adj_l1_coeff,
        }

    def validation_criteria(self, params, val_metrics):
        """Early-stopping criterion: L1 norm of the (unthresholded) GC estimate
        (ref clstm_fm.py:283-301 stops on curr_l1_loss alone)."""
        return jnp.sum(jnp.abs(self.gc(params)[0]))
