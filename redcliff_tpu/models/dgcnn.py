"""DGCNN — dynamical graph CNN classifier over a learned adjacency.

JAX rebuild of the capability wrapped by /root/reference/models/dgcnn.py:15-239,
which delegates to torcheeg.models.DGCNN (EEG-style DGCNN: a trainable node
adjacency A, normalized to a propagation operator, driving a K-support graph
convolution stack, followed by a two-layer MLP head). The learned adjacency IS
the model's Granger-graph estimate, read out transposed
(ref dgcnn.py:47-61 — the reference found the transpose correlates better with
ground truth and this build keeps that contract).

Architecture (per the public DGCNN formulation):
  L = D^{-1/2} relu(A) D^{-1/2}
  supports = [I, L, L@L, ...]                     (num_layers entries)
  h = relu(sum_k  supports[k] @ x @ W_k)          x: (B, N, F)
  out = fc2(relu(fc1(flatten(h))))

BatchNorm deviation: the torcheeg model batch-normalizes input features with
running statistics; this build normalizes with per-batch statistics and learned
scale/shift only (no running-stat state), keeping the model purely functional.
"""
from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp

__all__ = ["DGCNNConfig", "DGCNNModel", "init_dgcnn_params", "dgcnn_forward", "dgcnn_gc"]


@dataclass(frozen=True)
class DGCNNConfig:
    num_channels: int
    num_wavelets_per_chan: int  # 1 when no wavelet decomposition
    num_features_per_node: int
    num_graph_conv_layers: int
    num_hidden_nodes: int
    num_classes: int
    fc_hidden: int = 64

    @property
    def num_nodes(self):
        return self.num_channels * self.num_wavelets_per_chan


def init_dgcnn_params(key, cfg: DGCNNConfig):
    N, F, H = cfg.num_nodes, cfg.num_features_per_node, cfg.num_hidden_nodes
    ks = jax.random.split(key, cfg.num_graph_conv_layers + 4)
    # xavier-normal adjacency like the public DGCNN init
    A = jax.random.normal(ks[0], (N, N)) * math.sqrt(2.0 / (N + N))

    def dense(k, d_in, d_out):
        bound = 1.0 / math.sqrt(d_in)
        kw, kb = jax.random.split(k)
        return {
            "w": jax.random.uniform(kw, (d_in, d_out), minval=-bound, maxval=bound),
            "b": jax.random.uniform(kb, (d_out,), minval=-bound, maxval=bound),
        }

    return {
        "A": A,
        "bn_scale": jnp.ones((F,)),
        "bn_shift": jnp.zeros((F,)),
        "gconv": [dense(ks[1 + i], F, H) for i in range(cfg.num_graph_conv_layers)],
        "fc1": dense(ks[-2], N * H, cfg.fc_hidden),
        "fc2": dense(ks[-1], cfg.fc_hidden, cfg.num_classes),
    }


def _normalize_adjacency(A):
    A = jax.nn.relu(A)
    d = jnp.sum(A, axis=1)
    d_inv_sqrt = 1.0 / jnp.sqrt(d + 1e-10)
    return d_inv_sqrt[:, None] * A * d_inv_sqrt[None, :]


def dgcnn_forward(params, X, eps=1e-5):
    """X: (B, N, F) node-feature matrix -> (B, num_classes) logits."""
    # per-batch feature normalization (see module docstring)
    mean = X.mean(axis=(0, 1))
    var = X.var(axis=(0, 1))
    Xn = (X - mean) / jnp.sqrt(var + eps)
    Xn = Xn * params["bn_scale"] + params["bn_shift"]

    L = _normalize_adjacency(params["A"])
    # supports are powers of L: I, L, L^2, ... (one per graph-conv layer)
    h = 0.0
    support = jnp.eye(L.shape[0], dtype=X.dtype)
    for layer in params["gconv"]:
        prop = jnp.einsum("nm,bmf->bnf", support, Xn)
        h = h + jnp.einsum("bnf,fh->bnh", prop, layer["w"]) + layer["b"]
        support = support @ L
    h = jax.nn.relu(h)
    flat = h.reshape(h.shape[0], -1)
    z = jax.nn.relu(flat @ params["fc1"]["w"] + params["fc1"]["b"])
    return z @ params["fc2"]["w"] + params["fc2"]["b"]


def dgcnn_gc(params, cfg: DGCNNConfig, threshold=False, combine_node_feature_edges=False):
    """Learned adjacency read out as the GC estimate, TRANSPOSED
    (ref dgcnn.py:47-61)."""
    GC = params["A"]
    if combine_node_feature_edges:
        w = cfg.num_wavelets_per_chan
        c = cfg.num_channels
        blocks = GC.reshape(c, w, c, w)
        GC = jnp.sqrt(jnp.sum(blocks * blocks, axis=(1, 3)))
    GC = GC.T
    if threshold:
        return (GC > 0).astype(jnp.int32)
    return GC


class DGCNNModel:
    """Supervised graph-conv classifier baseline (ref dgcnn.py DGCNN_Model):
    predicts factor/state labels from a signal window; its trained adjacency is
    the (single) system GC estimate."""

    def __init__(self, config: DGCNNConfig):
        self.config = config

    def init(self, key):
        return init_dgcnn_params(key, self.config)

    def forward(self, params, X):
        return dgcnn_forward(params, X)

    def loss(self, params, X, Y):
        """MSE between predicted logits and labels; label-shape dispatch follows
        the reference (ref dgcnn.py:147-159): (B,S,T)->slice at the feature
        horizon, (B,S,1)->squeeze, (B,S)->as-is."""
        F = self.config.num_features_per_node
        Y_pred = self.forward(params, jnp.transpose(X[:, :F, :], (0, 2, 1)))
        if Y.ndim == 3:
            Y_t = Y[:, :, F] if Y.shape[2] > F else Y[:, :, 0]
        else:
            Y_t = Y
        loss = jnp.mean((Y_pred - Y_t) ** 2)
        return loss, {"factor_loss": loss}

    def gc(self, params, threshold=False, ignore_lag=True,
           combine_wavelet_representations=False, rank_wavelets=False):
        g = dgcnn_gc(params, self.config, threshold=threshold,
                     combine_node_feature_edges=combine_wavelet_representations)
        if not ignore_lag:
            g = g[:, :, None]
        return [g]

    def validation_criteria(self, params, val_metrics):
        """Early stopping on the L1 norm of the normalized GC estimate plus the
        factor loss (ref dgcnn.py:176-199 stops on GC-est L1)."""
        g = jnp.abs(self.gc(params)[0])
        g = g / jnp.maximum(jnp.max(g), 1e-12)
        return jnp.sum(g) + val_metrics.get("factor_loss", 0.0)
