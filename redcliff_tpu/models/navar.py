"""NAVAR — Neural Additive Vector AutoRegression (MLP and LSTM variants).

Rebuild of the capability vendored at /root/reference/models/navar.py:9-246
(itself adapted from bartbussmann/NAVAR, MIT license): each source node feeds
its own small network whose outputs are additive *contributions* to every
target node's next value; predictions are the contribution sums plus a bias,
and the causal-score matrix is the standard deviation of each contribution
stream over the training set.

TPU-first deltas (same semantics):
* the reference's grouped Conv1d (MLP variant, ref navar.py:28-36) and
  per-node LSTM loop (LSTM variant, ref navar.py:148-175) become single
  batched einsums / one vmapped scan over the node axis;
* training runs through the shared generic Trainer on sliding lag windows
  (every window predicts its next step) instead of a bespoke epoch loop with
  one window per recording — strictly more supervision per batch, identical
  objective;
* the causal matrix is computed by a jit'd std over all training windows.

Orientation contract: causal_matrix[j, i] scores source j driving target i —
the reference's raw ``model.GC()`` layout (ref navar.py:122,243), which the
eval layer consumes as-is (ref evaluate/eval_utils.py:928-934).
"""
from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp

__all__ = ["NAVARConfig", "NAVAR", "NAVARLSTMConfig", "NAVARLSTM"]


def _u(key, shape, bound):
    return jax.random.uniform(key, shape, minval=-bound, maxval=bound)


@dataclass(frozen=True)
class NAVARConfig:
    num_nodes: int
    num_hidden: int
    maxlags: int
    hidden_layers: int = 1
    dropout: float = 0.0
    lambda1: float = 0.0


class NAVAR:
    """MLP variant: per-node lag window -> hidden -> N contributions."""

    def __init__(self, config: NAVARConfig):
        self.config = config

    def init(self, key):
        cfg = self.config
        N, H, L = cfg.num_nodes, cfg.num_hidden, cfg.maxlags
        ks = jax.random.split(key, 2 * cfg.hidden_layers + 2)
        # grouped-conv fan_in per torch: in_channels/groups * kernel
        params = {
            "w1": _u(ks[0], (N, H, L), 1.0 / math.sqrt(L)),
            "b1": _u(ks[1], (N, H), 1.0 / math.sqrt(L)),
            "hidden": [],
            "bias": jnp.full((N,), 1e-4),
        }
        for k in range(cfg.hidden_layers - 1):
            params["hidden"].append({
                "w": _u(ks[2 + 2 * k], (N, H, H), 1.0 / math.sqrt(H)),
                "b": _u(ks[3 + 2 * k], (N, H), 1.0 / math.sqrt(H)),
            })
        params["wc"] = _u(ks[-2], (N, N, H), 1.0 / math.sqrt(H))
        params["bc"] = _u(ks[-1], (N, N), 1.0 / math.sqrt(H))
        return params

    def forward(self, params, Xw, dropout_key=None):
        """Xw: (B, L, N) lag windows -> (predictions (B, N),
        contributions (B, N_src, N_tgt)) (ref navar.py:41-51)."""
        cfg = self.config
        h = jnp.einsum("bln,nhl->bnh", Xw, params["w1"]) + params["b1"]
        h = jax.nn.relu(h)
        h = self._dropout(h, dropout_key, 0)
        for i, layer in enumerate(params["hidden"]):
            h = jnp.einsum("bnh,ngh->bng", h, layer["w"]) + layer["b"]
            h = jax.nn.relu(h)
            h = self._dropout(h, dropout_key, i + 1)
        contributions = jnp.einsum("bnh,nmh->bnm", h, params["wc"]) + params["bc"]
        predictions = jnp.sum(contributions, axis=1) + params["bias"]
        return predictions, contributions

    def _dropout(self, h, key, salt):
        cfg = self.config
        if cfg.dropout <= 0.0 or key is None:
            return h
        keep = 1.0 - cfg.dropout
        mask = jax.random.bernoulli(jax.random.fold_in(key, salt), keep, h.shape)
        return jnp.where(mask, h / keep, 0.0)

    def _windows(self, X):
        """All sliding (lag -> next step) pairs from a raw batch (B, T, C)."""
        L = self.config.maxlags
        B, T, N = X.shape
        n = T - L
        idx = jnp.arange(L)[None, :] + jnp.arange(n)[:, None]
        Xw = X[:, idx, :].reshape(B * n, L, N)
        Yt = X[:, L:, :].reshape(B * n, N)
        return Xw, Yt

    def loss(self, params, X, rng=None):
        """MSE on next-step predictions + contribution L1
        (ref navar.py:96-101: lambda1/N * mean over samples of the summed
        absolute contributions). ``rng`` (threaded by the Trainer when
        cfg.dropout > 0) activates dropout; None means eval mode."""
        cfg = self.config
        Xw, Yt = self._windows(X)
        preds, contributions = self.forward(params, Xw, rng)
        loss_pred = jnp.mean((preds - Yt) ** 2)
        loss_l1 = (cfg.lambda1 / cfg.num_nodes) * jnp.mean(
            jnp.sum(jnp.abs(contributions), axis=(1, 2)))
        combo = loss_pred + loss_l1
        return combo, {"forecasting_loss": loss_pred, "contribution_l1": loss_l1}

    def causal_matrix(self, params, X):
        """std of each contribution stream over all training windows
        (ref navar.py:119-122; torch.std is the UNBIASED estimator, hence
        ddof=1). Returns (N_src, N_tgt)."""
        Xw, _ = self._windows(X)
        _, contributions = self.forward(params, Xw)
        return jnp.std(contributions, axis=0, ddof=1)

    # ---- trainer protocol ------------------------------------------------
    gc_requires_data = True

    @property
    def wants_rng(self):
        return self.config.dropout > 0.0

    def gc(self, params, X=None, threshold=False, ignore_lag=True,
           combine_wavelet_representations=False, rank_wavelets=False):
        """Trainer/eval-protocol GC readout: the causal matrix in the
        reference's raw (source, target) orientation (ref navar.py:53-54,122).
        NAVAR's causal scores are contribution statistics over data, so X is
        required."""
        if X is None:
            raise ValueError("NAVAR GC estimates require data (X)")
        cm = self.causal_matrix(params, X)
        if threshold:
            cm = (cm > 0).astype(jnp.int32)
        return [cm if ignore_lag else cm[:, :, None]]

    def normalization_coeffs(self):
        return {}


@dataclass(frozen=True)
class NAVARLSTMConfig:
    num_nodes: int
    num_hidden: int
    maxlags: int
    hidden_layers: int = 1
    dropout: float = 0.0
    lambda1: float = 0.0


class NAVARLSTM:
    """LSTM variant: one (stacked) LSTM per source node over its scalar series,
    a linear head emitting N contributions per step (ref navar.py:129-175)."""

    def __init__(self, config: NAVARLSTMConfig):
        self.config = config

    def init(self, key):
        cfg = self.config
        N, H = cfg.num_nodes, cfg.num_hidden
        bound = 1.0 / math.sqrt(H)
        layers = []
        for l in range(cfg.hidden_layers):
            d_in = 1 if l == 0 else H
            k1, k2, k3, k4, key = jax.random.split(key, 5)
            layers.append({
                "w_ih": _u(k1, (N, 4 * H, d_in), bound),
                "w_hh": _u(k2, (N, 4 * H, H), bound),
                "b": _u(k3, (N, 4 * H), bound) + _u(k4, (N, 4 * H), bound),
            })
        kf1, kf2, key = jax.random.split(key, 3)
        return {
            "lstm": layers,
            "fc": {"w": _u(kf1, (N, H, N), bound), "b": _u(kf2, (N, N), bound)},
            "bias": jnp.full((N,), 1e-4),
        }

    def forward(self, params, Xw, rng=None):
        """Xw: (B, T, N) -> (predictions (B, T, N_tgt),
        contributions (B, T, N_src, N_tgt)). ``rng`` activates inter-layer
        dropout (torch nn.LSTM semantics: after every layer but the last,
        ref navar.py:151)."""
        cfg = self.config
        H = cfg.num_hidden
        B, T, N = Xw.shape
        # layer input: (T, B, N, d_in); layer 0 sees each node's scalar series
        x = jnp.transpose(Xw, (1, 0, 2))[..., None]
        n_layers = len(params["lstm"])
        for li, layer in enumerate(params["lstm"]):
            zx = jnp.einsum("tbnd,ngd->tbng", x, layer["w_ih"]) + layer["b"]

            def step(carry, zx_t, w_hh=layer["w_hh"]):
                h, c = carry
                z = zx_t + jnp.einsum("bnh,ngh->bng", h, w_hh)
                zi, zf, zg, zo = jnp.split(z, 4, axis=-1)
                c = jax.nn.sigmoid(zf) * c + jax.nn.sigmoid(zi) * jnp.tanh(zg)
                h = jax.nn.sigmoid(zo) * jnp.tanh(c)
                return (h, c), h

            h0 = jnp.zeros((B, N, H), dtype=Xw.dtype)
            _, hs = jax.lax.scan(step, (h0, h0), zx)
            x = hs  # (T, B, N, H)
            if cfg.dropout > 0.0 and rng is not None and li < n_layers - 1:
                keep = 1.0 - cfg.dropout
                mask = jax.random.bernoulli(
                    jax.random.fold_in(rng, li), keep, x.shape)
                x = jnp.where(mask, x / keep, 0.0)
        contributions = jnp.einsum("tbnh,nhm->btnm", x, params["fc"]["w"]) + params["fc"]["b"]
        predictions = jnp.sum(contributions, axis=2) + params["bias"]
        return predictions, contributions

    def loss(self, params, X, rng=None):
        """Full-sequence LSTM run with MSE at the final step + contribution L1
        over all steps (ref navar.py:213-222: the LSTM consumes X[:, :, :-1]
        whole — maxlags is unused in the reference's LSTM forward — and the
        loss reads the final prediction)."""
        cfg = self.config
        Xw = X[:, :-1, :]
        Yt = X[:, -1, :]
        preds, contributions = self.forward(params, Xw, rng)
        loss_pred = jnp.mean((preds[:, -1, :] - Yt) ** 2)
        B, T = contributions.shape[:2]
        loss_l1 = (cfg.lambda1 / cfg.num_nodes) * jnp.mean(
            jnp.sum(jnp.abs(contributions.reshape(B * T, -1)), axis=1))
        combo = loss_pred + loss_l1
        return combo, {"forecasting_loss": loss_pred, "contribution_l1": loss_l1}

    def causal_matrix(self, params, X):
        """std over (batch x time) of the (N, N) contribution streams from the
        full sequences (ref navar.py:240-243; torch.std => ddof=1)."""
        _, contributions = self.forward(params, X[:, :-1, :])
        N = self.config.num_nodes
        return jnp.std(contributions.reshape(-1, N, N), axis=0, ddof=1)

    # ---- trainer protocol ------------------------------------------------
    gc_requires_data = True

    @property
    def wants_rng(self):
        return self.config.dropout > 0.0

    def gc(self, params, X=None, threshold=False, ignore_lag=True,
           combine_wavelet_representations=False, rank_wavelets=False):
        """Trainer/eval-protocol GC readout (see NAVAR.gc)."""
        if X is None:
            raise ValueError("NAVARLSTM GC estimates require data (X)")
        cm = self.causal_matrix(params, X)
        if threshold:
            cm = (cm > 0).astype(jnp.int32)
        return [cm if ignore_lag else cm[:, :, None]]

    def normalization_coeffs(self):
        return {}
