"""Time-series transformer encoder (mvts_transformer capability).

JAX rebuild of /root/reference/models/ts_transformer.py (vendored
mvts_transformer): TSTransformerEncoder (:145, masked-reconstruction head)
and TSTransformerEncoderClassiregressor (:192, flattened masked pooling into
a linear class/regression head).  The reference imports this surface into the
factor-score embedders (redcliff_factor_score_embedders.py:7) but never
instantiates it; this build keeps it a first-class usable module.

Architecture: input projection × sqrt(d_model) + fixed-sinusoid or learnable
positional encoding, N pre-activation-free encoder layers (multi-head
attention + FFN) with either LayerNorm or the mvts "BatchNorm" variant
(normalizing each feature over batch×time; functional batch statistics here,
matching the DGCNN deviation note), gelu/relu activation.  Attention is one
batched einsum per layer — MXU-shaped, no per-head Python loops.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "TSTransformerConfig",
    "TSTransformerEncoder",
    "TSTransformerEncoderClassiregressor",
    "init_ts_transformer_params",
    "ts_transformer_encode",
]


@dataclass(frozen=True)
class TSTransformerConfig:
    feat_dim: int
    max_len: int
    d_model: int
    n_heads: int
    num_layers: int
    dim_feedforward: int
    num_classes: int = 0          # 0 -> reconstruction head (encoder)
    pos_encoding: str = "fixed"   # {"fixed", "learnable"}
    activation: str = "gelu"      # {"gelu", "relu"}
    norm: str = "BatchNorm"       # {"BatchNorm", "LayerNorm"}

    def __post_init__(self):
        assert self.d_model % self.n_heads == 0
        assert self.pos_encoding in ("fixed", "learnable")
        assert self.activation in ("gelu", "relu")
        assert self.norm in ("BatchNorm", "LayerNorm")


def _act(cfg):
    if cfg.activation == "gelu":
        # exact (erf) form: torch F.gelu's default, which the reference uses;
        # jax.nn.gelu defaults to the tanh approximation
        return lambda x: jax.nn.gelu(x, approximate=False)
    return jax.nn.relu


def _dense_init(key, d_in, d_out):
    bound = 1.0 / math.sqrt(d_in)
    kw, kb = jax.random.split(key)
    return {
        "w": jax.random.uniform(kw, (d_in, d_out), minval=-bound,
                                maxval=bound),
        "b": jax.random.uniform(kb, (d_out,), minval=-bound, maxval=bound),
    }


@lru_cache(maxsize=32)
def _fixed_pos_encoding_np(max_len, d_model):
    """Sinusoidal table (ref FixedPositionalEncoding :37-60), memoized as
    numpy (a cached jnp array created under jit would leak a tracer)."""
    pe = np.zeros((max_len, d_model), dtype=np.float32)
    position = np.arange(max_len)[:, None].astype(np.float32)
    div = np.exp(np.arange(0, d_model, 2) * (-math.log(10000.0) / d_model))
    pe[:, 0::2] = np.sin(position * div)
    pe[:, 1::2] = np.cos(position * div[: pe[:, 1::2].shape[1]])
    return pe


def _fixed_pos_encoding(max_len, d_model):
    return jnp.asarray(_fixed_pos_encoding_np(max_len, d_model))


def init_ts_transformer_params(key, cfg: TSTransformerConfig):
    keys = jax.random.split(key, 3 + 6 * cfg.num_layers)
    params = {"project_inp": _dense_init(keys[0], cfg.feat_dim, cfg.d_model)}
    if cfg.pos_encoding == "learnable":
        params["pos"] = 0.02 * jax.random.normal(
            keys[1], (cfg.max_len, cfg.d_model))
    def _weight_init(k, d_in, d_out):
        bound = 1.0 / math.sqrt(d_in)
        return jax.random.uniform(k, (d_in, d_out), minval=-bound,
                                  maxval=bound)

    layers = []
    k_idx = 2
    for _ in range(cfg.num_layers):
        layers.append({
            # attention projections carry no bias (the reference disables
            # bias in its BatchNorm layer "to mitigate numerical
            # instabilities", ts_transformer.py:102)
            "wq": _weight_init(keys[k_idx], cfg.d_model, cfg.d_model),
            "wk": _weight_init(keys[k_idx + 1], cfg.d_model, cfg.d_model),
            "wv": _weight_init(keys[k_idx + 2], cfg.d_model, cfg.d_model),
            "wo": _weight_init(keys[k_idx + 3], cfg.d_model, cfg.d_model),
            "ff1": _dense_init(keys[k_idx + 4], cfg.d_model,
                               cfg.dim_feedforward),
            "ff2": _dense_init(keys[k_idx + 5], cfg.dim_feedforward,
                               cfg.d_model),
            "norm1_scale": jnp.ones((cfg.d_model,)),
            "norm1_shift": jnp.zeros((cfg.d_model,)),
            "norm2_scale": jnp.ones((cfg.d_model,)),
            "norm2_shift": jnp.zeros((cfg.d_model,)),
        })
        k_idx += 6
    params["layers"] = layers
    if cfg.num_classes > 0:
        params["output"] = _dense_init(keys[-1],
                                       cfg.d_model * cfg.max_len,
                                       cfg.num_classes)
    else:
        params["output"] = _dense_init(keys[-1], cfg.d_model, cfg.feat_dim)
    return params


def _norm(x, scale, shift, kind, eps=1e-5):
    if kind == "LayerNorm":
        mean = x.mean(axis=-1, keepdims=True)
        var = x.var(axis=-1, keepdims=True)
    else:
        # mvts "BatchNorm": each feature normalized over batch and time
        mean = x.mean(axis=(0, 1), keepdims=True)
        var = x.var(axis=(0, 1), keepdims=True)
    return (x - mean) / jnp.sqrt(var + eps) * scale + shift


def _attention(layer, x, pad_mask, n_heads, seq_mesh=None, seq_axis="seq"):
    """Batched multi-head self-attention. x: (B, T, D); pad_mask: (B, T)
    True = keep.  With ``seq_mesh``, attention runs as ring attention with
    the time axis sharded over the mesh (parallel.sequence) — exact, but
    per-device memory is O(T / n_devices)."""
    B, T, D = x.shape
    H, hd = n_heads, D // n_heads
    q = (x @ layer["wq"]).reshape(B, T, H, hd)
    k = (x @ layer["wk"]).reshape(B, T, H, hd)
    v = (x @ layer["wv"]).reshape(B, T, H, hd)
    if seq_mesh is not None:
        from redcliff_tpu.parallel.sequence import ring_attention

        out = ring_attention(q, k, v, seq_mesh,
                             axis_name=seq_axis).reshape(B, T, D)
        return out @ layer["wo"]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(hd)
    if pad_mask is not None:
        neg = jnp.finfo(x.dtype).min
        logits = jnp.where(pad_mask[:, None, None, :], logits, neg)
    attn = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", attn, v).reshape(B, T, D)
    return out @ layer["wo"]


def ts_transformer_encode(params, cfg: TSTransformerConfig, X,
                          padding_masks=None, seq_mesh=None, seq_axis="seq"):
    """(B, T, feat_dim) -> (B, T, d_model) encoder embeddings
    (ref TSTransformerEncoder.forward :169-190 up to the output head).

    ``seq_mesh`` turns on sequence parallelism for long recordings: the time
    axis shards across the mesh, attention runs as ring attention, and the
    remaining (time-local) projections/FFN/norms are auto-partitioned by XLA
    along the same axis — the mvts BatchNorm's batch×time statistics become
    mesh psums, so results match the dense path exactly.  Padding masks are
    not supported in this mode (long-recording encoding doesn't pad)."""
    B, T, _ = X.shape
    if seq_mesh is not None:
        assert padding_masks is None, \
            "padding_masks unsupported under sequence parallelism"
        from redcliff_tpu.parallel.sequence import sequence_sharded

        X = sequence_sharded(X, seq_mesh, seq_axis)
    x = (X @ params["project_inp"]["w"] + params["project_inp"]["b"]) \
        * math.sqrt(cfg.d_model)
    if cfg.pos_encoding == "learnable":
        x = x + params["pos"][None, :T]
    else:
        x = x + _fixed_pos_encoding(cfg.max_len, cfg.d_model)[None, :T]
    for layer in params["layers"]:
        a = _attention(layer, x, padding_masks, cfg.n_heads,
                       seq_mesh=seq_mesh, seq_axis=seq_axis)
        x = _norm(x + a, layer["norm1_scale"], layer["norm1_shift"], cfg.norm)
        h = _act(cfg)(x @ layer["ff1"]["w"] + layer["ff1"]["b"])
        h = h @ layer["ff2"]["w"] + layer["ff2"]["b"]
        x = _norm(x + h, layer["norm2_scale"], layer["norm2_shift"], cfg.norm)
    return _act(cfg)(x)


class TSTransformerEncoder:
    """Masked-reconstruction transformer (ref TSTransformerEncoder :145-190):
    embeddings project back to feat_dim per step."""

    def __init__(self, config: TSTransformerConfig):
        assert config.num_classes == 0, \
            "use TSTransformerEncoderClassiregressor for a class head"
        self.config = config

    def init(self, key):
        return init_ts_transformer_params(key, self.config)

    def forward(self, params, X, padding_masks=None, seq_mesh=None):
        z = ts_transformer_encode(params, self.config, X, padding_masks,
                                  seq_mesh=seq_mesh)
        return z @ params["output"]["w"] + params["output"]["b"]

    def loss(self, params, X, Y=None, padding_masks=None):
        """Masked reconstruction MSE (the mvts pretraining objective)."""
        recon = self.forward(params, X, padding_masks)
        target = X if Y is None else Y
        err = (recon - target) ** 2
        if padding_masks is not None:
            err = err * padding_masks[:, :, None]
            denom = jnp.maximum(padding_masks.sum() * X.shape[2], 1)
            loss = err.sum() / denom
        else:
            loss = err.mean()
        return loss, {"recon_loss": loss}


class TSTransformerEncoderClassiregressor:
    """Classifier/regressor head over flattened masked embeddings
    (ref :192-250): padding embeddings are zeroed before the flattened linear
    output layer; no softmax (loss applies it)."""

    def __init__(self, config: TSTransformerConfig):
        assert config.num_classes > 0
        self.config = config

    def init(self, key):
        return init_ts_transformer_params(key, self.config)

    def forward(self, params, X, padding_masks=None):
        cfg = self.config
        z = ts_transformer_encode(params, cfg, X, padding_masks)
        if padding_masks is not None:
            z = z * padding_masks[:, :, None]
        # pad the time axis to max_len so the flattened head is static-shape
        T = z.shape[1]
        if T < cfg.max_len:
            z = jnp.pad(z, ((0, 0), (0, cfg.max_len - T), (0, 0)))
        flat = z.reshape(z.shape[0], -1)
        return flat @ params["output"]["w"] + params["output"]["b"]

    def loss(self, params, X, Y, padding_masks=None):
        """Softmax cross-entropy on integer or one-hot labels."""
        logits = self.forward(params, X, padding_masks)
        if Y.ndim == 1:
            Y = jax.nn.one_hot(Y, self.config.num_classes)
        logp = jax.nn.log_softmax(logits, axis=-1)
        loss = -jnp.mean(jnp.sum(Y * logp, axis=-1))
        return loss, {"class_loss": loss}
