"""dCSFA-NMF — supervised NMF autoencoder over directed-spectrum features.

TPU-native rebuild of the capability in /root/reference/models/dcsfa_nmf.py
(NmfBase :26, DcsfaNmf :490, FullDCSFAModel :1282) and its near-duplicate
/root/reference/models/dcsfa_nmf_vanillaDirSpec.py (identical training model;
only the GC feature layout differs — see ``gc_feature_layout`` below).

The model learns K non-negative factors ``W_nmf`` (k, d) over high-level signal
features, an encoder mapping features to non-negative factor scores
``s`` (B, k), and one logistic-regression head per *supervised* factor.  The
first ``n_sup_networks`` components are tied to task labels; the supervised
rows of ``W_nmf`` are the per-state networks whose directed-spectrum blocks are
read out as Granger-causal graphs (ref dcsfa_nmf.py:1299-1326).

Design deltas from the reference (same behavior, TPU idiom):
  - The sklearn NMF pretraining (ref :179-269) is replaced by a native
    NNDSVD-initialized multiplicative-update NMF (`nmf_fit`) — MU iterations
    are pure matmuls, ideal for the MXU, and run under one `lax.fori_loop`.
  - The component→task assignment keeps the reference's Mann-Whitney-U AUC
    ranking (ref :226-259), computed rank-based in numpy on host.
  - Encoder BatchNorm carries running statistics in an explicit functional
    `state` pytree (torch semantics: batch stats in training, running stats in
    eval, momentum 0.1).
  - Encoder pretraining freezes `W_nmf` (ref :867) via an optax-masked
    optimizer so frozen/grad-less parameters see neither updates nor weight
    decay, exactly like torch's grad=None skip.
  - The per-epoch WeightedRandomSampler (ref :877,1032) becomes a host-side
    weighted index draw feeding fixed-shape device batches.
"""
from __future__ import annotations

import dataclasses
import math
import os
import pickle
from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..runtime.checkpoint import write_checkpoint
from ..utils.metrics import roc_auc
from ..utils.misc import unflatten_directed_spectrum_features

__all__ = [
    "nndsvd_init",
    "nmf_fit",
    "mann_whitney_auc",
    "DcsfaNmfConfig",
    "DcsfaNmf",
    "FullDCSFAModel",
]

_EPS = 1e-7


# ---------------------------------------------------------------------------
# Native NMF pretraining (replaces sklearn.decomposition.NMF, ref :198-210)
# ---------------------------------------------------------------------------

def nndsvd_init(X, n_components, fill_mean=False, random_state=0):
    """Nonnegative double SVD initialization (Boutsidis & Gallopoulos 2008).

    ``fill_mean=True`` matches sklearn's "nndsvda" (zeros replaced by the data
    mean, required for multiplicative updates so zeros aren't absorbing).
    """
    X = np.asarray(X, dtype=np.float64)
    U, S, Vt = np.linalg.svd(X, full_matrices=False)
    U, S, Vt = U[:, :n_components], S[:n_components], Vt[:n_components]
    W = np.zeros((X.shape[0], n_components))
    H = np.zeros((n_components, X.shape[1]))
    W[:, 0] = np.sqrt(S[0]) * np.abs(U[:, 0])
    H[0, :] = np.sqrt(S[0]) * np.abs(Vt[0, :])
    for j in range(1, n_components):
        u, v = U[:, j], Vt[j, :]
        u_p, u_n = np.maximum(u, 0), np.maximum(-u, 0)
        v_p, v_n = np.maximum(v, 0), np.maximum(-v, 0)
        n_up, n_un = np.linalg.norm(u_p), np.linalg.norm(u_n)
        n_vp, n_vn = np.linalg.norm(v_p), np.linalg.norm(v_n)
        term_p, term_n = n_up * n_vp, n_un * n_vn
        if term_p >= term_n:
            sigma = term_p
            u_sel = u_p / max(n_up, _EPS)
            v_sel = v_p / max(n_vp, _EPS)
        else:
            sigma = term_n
            u_sel = u_n / max(n_un, _EPS)
            v_sel = v_n / max(n_vn, _EPS)
        W[:, j] = np.sqrt(S[j] * sigma) * u_sel
        H[j, :] = np.sqrt(S[j] * sigma) * v_sel
    if fill_mean:
        avg = X.mean()
        W[W == 0] = avg
        H[H == 0] = avg
    return W, H


def nmf_fit(X, n_components, max_iter=100, loss="MSE"):
    """Unsupervised NMF by multiplicative updates, jitted on device.

    loss="MSE" uses Lee-Seung Frobenius updates; loss="IS" uses the
    beta-divergence (beta=0, Itakura-Saito) rules — matching the reference's
    solver choice per reconstruction loss (ref :198-207).

    Returns (scores S, components H): X ≈ S @ H.
    """
    Xn = np.asarray(X, dtype=np.float32)
    W0, H0 = nndsvd_init(Xn, n_components, fill_mean=(loss == "IS"))
    if loss == "MSE" and max_iter > 0:
        # plain nndsvd zeros are absorbing under MU; nudge them off zero
        W0[W0 == 0] = _EPS
        H0[H0 == 0] = _EPS

    @jax.jit
    def run(X, W, H):
        def mse_step(_, WH):
            W, H = WH
            H = H * (W.T @ X) / (W.T @ W @ H + _EPS)
            W = W * (X @ H.T) / (W @ (H @ H.T) + _EPS)
            return W, H

        def is_step(_, WH):
            W, H = WH
            V = W @ H + _EPS
            H = H * (W.T @ (X / (V * V))) / (W.T @ (1.0 / V) + _EPS)
            V = W @ H + _EPS
            W = W * ((X / (V * V)) @ H.T) / ((1.0 / V) @ H.T + _EPS)
            return W, H

        step = is_step if loss == "IS" else mse_step
        return jax.lax.fori_loop(0, max_iter, step, (W, H))

    W, H = run(jnp.asarray(Xn), jnp.asarray(W0, jnp.float32),
               jnp.asarray(H0, jnp.float32))
    return np.asarray(W), np.asarray(H)


def mann_whitney_auc(pos, neg):
    """AUC = U / (n_pos * n_neg) with average-rank tie handling — identical to
    scipy.stats.mannwhitneyu's U as used at ref :229-231."""
    pos = np.asarray(pos, dtype=np.float64).ravel()
    neg = np.asarray(neg, dtype=np.float64).ravel()
    if len(pos) == 0 or len(neg) == 0:
        # degenerate single-class task (e.g. under the 0.6 pretrain split):
        # no ordering information, fall back to chance
        return 0.5
    combined = np.concatenate([pos, neg])
    order = np.argsort(combined, kind="mergesort")
    ranks = np.empty(len(combined))
    ranks[order] = np.arange(1, len(combined) + 1)
    # average ranks over ties
    sorted_vals = combined[order]
    i = 0
    while i < len(sorted_vals):
        j = i
        while j + 1 < len(sorted_vals) and sorted_vals[j + 1] == sorted_vals[i]:
            j += 1
        if j > i:
            ranks[order[i : j + 1]] = ranks[order[i : j + 1]].mean()
        i = j + 1
    U = ranks[: len(pos)].sum() - len(pos) * (len(pos) + 1) / 2.0
    return U / (len(pos) * len(neg))


# ---------------------------------------------------------------------------
# Model
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class DcsfaNmfConfig:
    """Hyper-parameters of DcsfaNmf (ref dcsfa_nmf.py:557-576 defaults)."""
    n_components: int = 32
    n_sup_networks: int = 1
    n_intercepts: int = 1
    use_deep_encoder: bool = True
    h: int = 256
    optim_name: str = "AdamW"      # {"AdamW","Adam","SGD"} (ref :164-175)
    recon_loss: str = "MSE"        # {"MSE","IS"} (ref :147-161)
    recon_weight: float = 1.0
    sup_weight: float = 1.0
    sup_recon_weight: float = 1.0
    sup_recon_type: str = "Residual"   # {"Residual","All"} (ref :418-423)
    sup_smoothness_weight: float = 1.0
    feature_groups: Optional[tuple] = None   # ((lb, ub), ...) feature spans
    group_weights: Optional[tuple] = None
    fixed_corr: tuple = ()         # per-sup-net in {"n/a","positive","negative"}
    momentum: float = 0.9
    lr: float = 1e-3

    def __post_init__(self):
        if self.recon_loss not in ("MSE", "IS"):
            raise ValueError(f"{self.recon_loss} is not supported")
        if self.optim_name not in ("AdamW", "Adam", "SGD"):
            raise ValueError(f"{self.optim_name} is not supported")
        # normalize fixed_corr exactly like ref :89-103
        fc = self.fixed_corr
        if not fc:
            fc = tuple("n/a" for _ in range(self.n_sup_networks))
        elif isinstance(fc, str):
            if fc.lower() not in ("positive", "negative", "n/a"):
                raise ValueError(
                    "fixed corr must be a list or in {positive,negative,n/a}")
            # replicate across all supervised networks (the reference keeps a
            # length-1 list here, ref :92-100, which breaks for
            # n_sup_networks > 1 — deliberate fix)
            fc = tuple(fc.lower() for _ in range(self.n_sup_networks))
        else:
            fc = tuple(str(c).lower() for c in fc)
            assert len(fc) == self.n_sup_networks
        for c in fc:
            if c not in ("n/a", "positive", "negative"):
                raise ValueError(f"Unsupported fixed_corr value: {c}")
        object.__setattr__(self, "fixed_corr", fc)
        if self.feature_groups is not None and self.group_weights is None:
            fg = self.feature_groups
            span = fg[-1][-1] - fg[0][0]
            object.__setattr__(
                self, "group_weights",
                tuple(span / (ub - lb) for (lb, ub) in fg))


def _dense_init(key, d_in, d_out):
    bound = 1.0 / math.sqrt(d_in)
    kw, kb = jax.random.split(key)
    return {
        "w": jax.random.uniform(kw, (d_in, d_out), minval=-bound, maxval=bound),
        "b": jax.random.uniform(kb, (d_out,), minval=-bound, maxval=bound),
    }


class DcsfaNmf:
    """Functional dCSFA-NMF with the reference's full training recipe:
    NMF pretrain → encoder pretrain → joint supervised fit with best-model
    checkpointing on ``val_mse/var + (1 - mean val AUC)`` (ref :1092-1101)."""

    def __init__(self, config: DcsfaNmfConfig):
        self.config = config

    # -- parameters ---------------------------------------------------------

    def init(self, key, dim_in):
        cfg = self.config
        k_nmf, k_e1, k_e2, k_phi, k_beta = jax.random.split(key, 5)
        params = {
            # raw parameter; softplus() makes it non-negative (ref :140-144)
            "W_nmf": jax.random.uniform(k_nmf, (cfg.n_components, dim_in)),
            "phi": jax.random.normal(k_phi, (cfg.n_sup_networks,)),
            "beta": jax.random.normal(k_beta,
                                      (cfg.n_sup_networks, cfg.n_intercepts)),
        }
        if cfg.use_deep_encoder:
            params["enc1"] = _dense_init(k_e1, dim_in, cfg.h)
            params["enc2"] = _dense_init(k_e2, cfg.h, cfg.n_components)
            params["bn_scale"] = jnp.ones((cfg.h,))
            params["bn_shift"] = jnp.zeros((cfg.h,))
            state = {"bn_mean": jnp.zeros((cfg.h,)),
                     "bn_var": jnp.ones((cfg.h,))}
        else:
            params["enc1"] = _dense_init(k_e1, dim_in, cfg.n_components)
            state = {}
        return params, state

    # -- pieces -------------------------------------------------------------

    def get_w_nmf(self, params):
        return jax.nn.softplus(params["W_nmf"])

    def encode(self, params, state, X, train):
        """features -> non-negative factor scores s (ref encoder :592-604)."""
        cfg = self.config
        z = X @ params["enc1"]["w"] + params["enc1"]["b"]
        if cfg.use_deep_encoder:
            if train:
                mean = z.mean(axis=0)
                var = z.var(axis=0)
                n = z.shape[0]
                unbiased = var * n / max(n - 1, 1)
                state = {
                    "bn_mean": 0.9 * state["bn_mean"] + 0.1 * mean,
                    "bn_var": 0.9 * state["bn_var"] + 0.1 * unbiased,
                }
            else:
                mean, var = state["bn_mean"], state["bn_var"]
            z = (z - mean) / jnp.sqrt(var + 1e-5)
            z = z * params["bn_scale"] + params["bn_shift"]
            z = jax.nn.leaky_relu(z, 0.01)
            z = z @ params["enc2"]["w"] + params["enc2"]["b"]
        return jax.nn.softplus(z), state

    def get_phi(self, params):
        """(n_sup_networks,) logistic slopes with correlation constraints
        (ref :707-740)."""
        cfg = self.config
        cols = []
        for j, corr in enumerate(cfg.fixed_corr):
            p = params["phi"][j]
            if corr == "positive":
                p = jax.nn.softplus(p)
            elif corr == "negative":
                p = -jax.nn.softplus(p)
            cols.append(p)
        return jnp.stack(cols)

    def class_predictions(self, params, s, intercept_mask=None,
                          avg_intercept=False):
        """Per-sup-network logistic predictions (ref :629-685)."""
        cfg = self.config
        phi = self.get_phi(params)                       # (S,)
        if cfg.n_intercepts == 1:
            icpt = params["beta"][:, 0]                  # (S,)
        elif intercept_mask is not None and not avg_intercept:
            icpt = intercept_mask @ params["beta"].T     # (B, S)
        else:
            icpt = params["beta"].mean(axis=1)           # (S,)
        logits = s[:, : cfg.n_sup_networks] * phi[None, :] + icpt
        return jax.nn.sigmoid(logits)

    def _recon_terms(self, params, X, s):
        """recon_weight*full + sup_recon_weight*supervised (ref :396-426)."""
        cfg = self.config
        W = self.get_w_nmf(params)
        X_recon = s @ W
        recon = cfg.recon_weight * self._eval_recon_loss(X_recon, X)
        S = cfg.n_sup_networks
        if cfg.sup_recon_type == "Residual":
            # scores that would best explain the unsupervised residual
            # (ref get_residual_scores :292-313)
            resid = X - s[:, S:] @ W[S:, :]
            w_sup = W[:S, :]
            s_h = resid @ w_sup.T @ jnp.linalg.inv(w_sup @ w_sup.T)
            sup_loss = jnp.linalg.norm(s[:, :S] - s_h) / (
                1.0 - cfg.sup_smoothness_weight
                * jnp.exp(-jnp.linalg.norm(s_h)))
        elif cfg.sup_recon_type == "All":
            sup_loss = self._recon_loss_f(s[:, :S] @ W[:S, :], X)
        else:
            raise ValueError(f"{cfg.sup_recon_type} is not supported")
        return recon + cfg.sup_recon_weight * sup_loss

    def _recon_loss_f(self, X_pred, X_true):
        if self.config.recon_loss == "IS":
            ratio = (X_true + _EPS) / (X_pred + _EPS)
            return jnp.mean(ratio - jnp.log(ratio) - 1.0)
        return jnp.mean((X_pred - X_true) ** 2)

    def _eval_recon_loss(self, X_pred, X_true):
        cfg = self.config
        if cfg.feature_groups is None:
            return self._recon_loss_f(X_pred, X_true)
        total = 0.0
        for wgt, (lb, ub) in zip(cfg.group_weights, cfg.feature_groups):
            total += wgt * self._recon_loss_f(X_pred[:, lb:ub],
                                              X_true[:, lb:ub])
        return total

    def loss(self, params, state, batch, train):
        """Returns (recon_loss, pred_loss, new_state) (ref forward :743-792)."""
        X, y, task_mask, pred_weight, intercept_mask = batch
        s, new_state = self.encode(params, state, X, train)
        recon_loss = self._recon_terms(params, X, s)
        y_pred = self.class_predictions(params, s, intercept_mask,
                                        avg_intercept=False)
        p = jnp.clip(y_pred * task_mask, _EPS, 1.0 - _EPS)
        t = y * task_mask
        bce = -(t * jnp.log(p) + (1.0 - t) * jnp.log(1.0 - p))
        pred_loss = self.config.sup_weight * jnp.mean(pred_weight * bce)
        return recon_loss, pred_loss, new_state

    # -- optimizers ---------------------------------------------------------

    def _make_optimizer(self, lr, trainable_mask=None):
        cfg = self.config
        if cfg.optim_name == "AdamW":
            tx = optax.adamw(lr, weight_decay=0.01)
        elif cfg.optim_name == "Adam":
            tx = optax.adam(lr)
        else:
            tx = optax.sgd(lr, momentum=cfg.momentum)
        if trainable_mask is not None:
            tx = optax.masked(tx, trainable_mask)
        return tx

    # -- pretraining --------------------------------------------------------

    def pretrain_nmf(self, params, X, y, nmf_max_iter=100):
        """NMF pretrain + Mann-Whitney-AUC component→task ordering
        (ref :179-269). Returns (params, per-task AUCs)."""
        cfg = self.config
        s_nmf, components = nmf_fit(X, cfg.n_components, max_iter=nmf_max_iter,
                                    loss=cfg.recon_loss)
        y = np.asarray(y)
        selected, selected_aucs = [], []
        remaining = list(range(cfg.n_components))
        for sup_net in range(cfg.n_sup_networks):
            aucs = np.array([
                mann_whitney_auc(s_nmf[y[:, sup_net] >= 0.6, c],
                                 s_nmf[y[:, sup_net] < 0.6, c])
                for c in range(cfg.n_components)])
            order_abs = np.argsort(np.abs(aucs - 0.5))[::-1]
            order_pos = np.argsort(aucs)[::-1]
            order_neg = np.argsort(1.0 - aucs)[::-1]
            for taken in selected:
                order_abs = order_abs[order_abs != taken]
                order_pos = order_pos[order_pos != taken]
                order_neg = order_neg[order_neg != taken]
            corr = cfg.fixed_corr[sup_net]
            current = {"n/a": order_abs, "positive": order_pos,
                       "negative": order_neg}[corr][0]
            selected.append(int(current))
            selected_aucs.append(float(aucs[current]))
            remaining = [c for c in remaining if c != current]
        final_order = selected + [c for c in remaining if c not in selected]
        sorted_H = components[final_order].astype(np.float64)
        # inverse softplus so softplus(param) reproduces the NMF components
        # (ref inverse_softplus :130-138); numerically stable form
        # x + log1p(-exp(-x)) above the expm1 overflow range
        xe = sorted_H + 1e-5
        w_raw = np.where(
            xe > 30.0, xe + np.log1p(-np.exp(-np.minimum(xe, 700.0))),
            np.log(np.expm1(np.minimum(xe, 30.0)) + 1e-5)).astype(np.float32)
        params = dict(params)
        params["W_nmf"] = jnp.asarray(w_raw)
        return params, selected_aucs

    # -- fit ----------------------------------------------------------------

    def _build_step(self, pretrain):
        cfg = self.config
        if pretrain and cfg.use_deep_encoder:
            trainable = lambda p: {
                k: k in ("enc1", "enc2", "bn_scale", "bn_shift") for k in p}
        elif pretrain:
            trainable = lambda p: {k: k == "enc1" for k in p}
        else:
            trainable = None

        def total_loss(params, state, batch):
            recon, pred, new_state = self.loss(params, state, batch, True)
            loss = recon if pretrain else recon + pred
            return loss, (recon, pred, new_state)

        tx = self._make_optimizer(
            cfg.lr, trainable_mask=trainable if trainable else None)

        @jax.jit
        def step(params, state, opt_state, batch):
            (loss, (recon, pred, new_state)), grads = jax.value_and_grad(
                total_loss, has_aux=True)(params, state, batch)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, new_state, opt_state, (loss, recon, pred)

        return tx, step

    @staticmethod
    def _weighted_batches(rng, n, batch_size, weights):
        """WeightedRandomSampler(+DataLoader) equivalent (ref :877,1031-1033):
        n draws with replacement ∝ weights, chunked into batches."""
        p = np.asarray(weights, dtype=np.float64)
        p = p / p.sum()
        idx = rng.choice(n, size=n, replace=True, p=p)
        return [idx[i : i + batch_size] for i in range(0, n, batch_size)]

    def fit(self, key, X, y, y_pred_weights=None, task_mask=None,
            intercept_mask=None, y_sample_groups=None, n_epochs=100,
            n_pre_epochs=100, nmf_max_iter=100, batch_size=128, lr=None,
            pretrain=True, X_val=None, y_val=None, y_pred_weights_val=None,
            task_mask_val=None, save_folder=None,
            best_model_name="dCSFA-NMF-best-model.pkl", verbose=False,
            seed=0):
        """Full training recipe (ref fit :901-1122). Returns
        (params, state, histories-dict)."""
        cfg = self.config
        if lr is not None and lr != cfg.lr:
            self.config = dataclasses.replace(cfg, lr=lr)
            cfg = self.config
        X = np.asarray(X, dtype=np.float32)
        y = np.asarray(y, dtype=np.float32)
        n = X.shape[0]
        if intercept_mask is None:
            intercept_mask = np.ones((n, cfg.n_intercepts), dtype=np.float32)
        if task_mask is None:
            task_mask = np.ones_like(y)
        if y_pred_weights is None:
            y_pred_weights = np.ones((n, 1), dtype=np.float32)
        if y_sample_groups is None:
            sample_weights = np.ones(n)
        else:
            y_sample_groups = np.asarray(y_sample_groups).squeeze()
            counts = {g: np.sum(y_sample_groups == g)
                      for g in np.unique(y_sample_groups)}
            sample_weights = np.array(
                [1.0 / counts[g] for g in y_sample_groups])

        params, state = self.init(key, X.shape[1])
        histories = {"training": [], "recon": [], "pred": [],
                     "val_recon": [], "val_pred": []}
        rng = np.random.default_rng(seed)

        if pretrain:
            params, _ = self.pretrain_nmf(params, X, y, nmf_max_iter)
            tx_pre, pre_step = self._build_step(pretrain=True)
            opt_state = tx_pre.init(params)
            for _ in range(n_pre_epochs):
                for bidx in self._weighted_batches(rng, n, batch_size,
                                                   sample_weights):
                    batch = (X[bidx], y[bidx], task_mask[bidx],
                             y_pred_weights[bidx], intercept_mask[bidx])
                    params, state, opt_state, _ = pre_step(
                        params, state, opt_state, batch)

        tx, step = self._build_step(pretrain=False)
        opt_state = tx.init(params)

        has_val = X_val is not None and y_val is not None
        if has_val:
            X_val = np.asarray(X_val, dtype=np.float32)
            y_val = np.asarray(y_val, dtype=np.float32)
            if task_mask_val is None:
                task_mask_val = np.ones_like(y_val)
            best = {"performance": 1e8, "epoch": -1, "params": params,
                    "state": state, "val_recon": 1e8, "val_aucs": None}
            val_var = float(np.var(X_val))

        for epoch in range(n_epochs):
            e_loss = e_recon = e_pred = 0.0
            batches = self._weighted_batches(rng, n, batch_size,
                                             sample_weights)
            for bidx in batches:
                batch = (X[bidx], y[bidx], task_mask[bidx],
                         y_pred_weights[bidx], intercept_mask[bidx])
                params, state, opt_state, (l, r, p) = step(
                    params, state, opt_state, batch)
                e_loss += float(l); e_recon += float(r); e_pred += float(p)
            histories["training"].append(e_loss / len(batches))

            # epoch-end train metrics (ref :1061-1074): MSE + binarized AUC
            X_recon, y_pred, _ = self.transform(params, state, X,
                                                avg_intercept=False,
                                                intercept_mask=intercept_mask)
            histories["recon"].append(float(np.mean((X - X_recon) ** 2)))
            train_aucs = []
            for j in range(cfg.n_sup_networks):
                m = task_mask[:, j] == 1
                try:
                    train_aucs.append(roc_auc(y[m, j] >= 0.6,
                                              (y_pred[m, j] >= 0.6)
                                              .astype(np.float64)))
                except ValueError:
                    train_aucs.append(float("nan"))
            histories["pred"].append(train_aucs)

            if has_val:
                Xr_val, yp_val, _ = self.transform(params, state, X_val)
                val_mse = float(np.mean((X_val - Xr_val) ** 2))
                val_aucs = []
                for j in range(cfg.n_sup_networks):
                    m = task_mask_val[:, j] == 1
                    try:
                        val_aucs.append(roc_auc(y_val[m, j] >= 0.6,
                                                (yp_val[m, j] >= 0.6)
                                                .astype(np.float64)))
                    except ValueError:
                        val_aucs.append(float("nan"))
                histories["val_recon"].append(val_mse)
                histories["val_pred"].append(val_aucs)
                perf = val_mse / max(val_var, _EPS) + (
                    1.0 - float(np.nanmean(val_aucs))
                    if not np.all(np.isnan(val_aucs)) else 1.0)
                if not np.isnan(perf) and perf < best["performance"]:
                    best.update(performance=perf, epoch=epoch, params=params,
                                state=state, val_recon=val_mse,
                                val_aucs=val_aucs)
                    if save_folder:
                        # durable write: a preemption mid-save can't tear
                        # the best-model artifact
                        write_checkpoint(
                            os.path.join(save_folder, best_model_name),
                            self._artifact_payload(params, state))
            if verbose:
                print(f"dCSFA-NMF epoch {epoch}: loss "
                      f"{histories['training'][-1]:.6f}", flush=True)

        self.last_params, self.last_state = params, state
        if has_val:
            histories["best_epoch"] = best["epoch"]
            histories["best_val_recon"] = best["val_recon"]
            histories["best_val_aucs"] = best["val_aucs"]
            if best["epoch"] < 0:
                # no epoch ever produced a finite validation criterion
                # (e.g. single-class y_val); fall back to the final params
                # rather than silently returning the untrained initial ones
                import warnings
                warnings.warn(
                    "dCSFA-NMF: validation criterion was never finite; "
                    "returning last-epoch parameters")
            else:
                params, state = best["params"], best["state"]
        return params, state, histories

    def _artifact_payload(self, params, state):
        """Self-describing artifact so eval.model_io can reconstruct the
        exact class (incl. FullDCSFAModel graph-shape metadata)."""
        payload = {"model_class": type(self).__name__,
                   "config": self.config,
                   "params": jax.device_get(params),
                   "state": jax.device_get(state)}
        for attr in ("num_nodes", "num_high_level_node_features",
                     "gc_feature_layout"):
            if hasattr(self, attr):
                payload[attr] = getattr(self, attr)
        return payload

    # -- inference ----------------------------------------------------------

    def transform(self, params, state, X, intercept_mask=None,
                  avg_intercept=True):
        """(X_recon, y_pred, s) in eval mode (ref transform :796-836)."""
        X = jnp.asarray(X, dtype=jnp.float32)
        s, _ = self.encode(params, state, X, train=False)
        X_recon = s @ self.get_w_nmf(params)
        y_pred = self.class_predictions(params, s, intercept_mask,
                                        avg_intercept=avg_intercept)
        return (np.asarray(X_recon), np.asarray(y_pred), np.asarray(s))

    def predict_proba(self, params, state, X, return_scores=False):
        _, y_pred, s = self.transform(params, state, X)
        return (y_pred, s) if return_scores else y_pred

    def predict(self, params, state, X, return_scores=False):
        _, y_pred, s = self.transform(params, state, X)
        return (y_pred > 0.5, s) if return_scores else (y_pred > 0.5)

    def project(self, params, state, X):
        return self.transform(params, state, X)[2]

    def reconstruct(self, params, state, X, component=None):
        X_recon, _, s = self.transform(params, state, X)
        if component is not None:
            W = np.asarray(self.get_w_nmf(params))
            return np.outer(s[:, component], W[component, :])
        return X_recon

    def score(self, params, state, X, y, groups=None, return_dict=False):
        """Per-task AUCs, optionally split by group (ref :1232-1277; the
        reference computes ungrouped AUCs per group — here each group is
        actually masked, the sensible reading of that code)."""
        _, y_pred, _ = self.transform(params, state, X)
        y = np.asarray(y)
        if groups is None:
            return np.array([roc_auc(y[:, j], y_pred[:, j])
                             for j in range(self.config.n_sup_networks)])
        groups = np.asarray(groups).squeeze()
        auc_dict = {
            g: [roc_auc(y[groups == g, j], y_pred[groups == g, j])
                for j in range(self.config.n_sup_networks)]
            for g in np.unique(groups)}
        if return_dict:
            return auc_dict
        return np.mean(np.vstack([auc_dict[g] for g in np.unique(groups)]),
                       axis=0)


class FullDCSFAModel(DcsfaNmf):
    """DcsfaNmf + Granger-graph readout over directed-spectrum feature blocks
    (ref dcsfa_nmf.py:1282-1356 / dcsfa_nmf_vanillaDirSpec.py FullDCSFAModel).

    gc_feature_layout:
      "dirspec" — W_nmf rows are per-node blocks of flattened directed-spectrum
        features; unflattened via the (2n-1)-per-node layout
        (ref dcsfa_nmf.py:1299-1312).
      "vanilla" — W_nmf rows reshape directly to (n, n, F)
        (ref dcsfa_nmf_vanillaDirSpec.py get_factor_GC).
    """

    def __init__(self, num_nodes=5, num_high_level_node_features=25,
                 config: DcsfaNmfConfig = None, gc_feature_layout="dirspec",
                 **cfg_kw):
        if config is None:
            config = DcsfaNmfConfig(**cfg_kw)
        super().__init__(config)
        assert gc_feature_layout in ("dirspec", "vanilla")
        self.num_nodes = num_nodes
        self.num_high_level_node_features = num_high_level_node_features
        self.gc_feature_layout = gc_feature_layout

    @property
    def dim_in(self):
        n, F = self.num_nodes, self.num_high_level_node_features
        if self.gc_feature_layout == "dirspec":
            return n * F * (2 * n - 1)
        return n * n * F

    def get_factor_gc(self, factor, threshold=True, ignore_features=True):
        n, F = self.num_nodes, self.num_high_level_node_features
        factor = np.asarray(factor).reshape(1, -1)
        if self.gc_feature_layout == "dirspec":
            node_len = F * (2 * n - 1)
            assert factor.shape[1] == n * node_len
            node_subfactors = factor.reshape(n, node_len)
            # accumulate_shared_entries matches the reference readout, whose
            # unflatten doubles off-diagonal entries (ref dcsfa_nmf.py:1305
            # via misc.py:178-195)
            raw = unflatten_directed_spectrum_features(
                node_subfactors, accumulate_shared_entries=True)
        else:
            raw = factor.reshape(n, n, F)
        GC = raw * raw
        if ignore_features:
            GC = GC.sum(axis=2)
        if threshold:
            return (GC > 0).astype(np.int32)
        return GC

    def gc(self, params, threshold=True, ignore_features=True):
        """One (n, n) graph per NMF component, supervised components first
        (ref GC :1315-1326)."""
        W = np.asarray(self.get_w_nmf(params))
        return [self.get_factor_gc(W[i], threshold=threshold,
                                   ignore_features=ignore_features)
                for i in range(W.shape[0])]

    # Reference alias
    GC = gc

    def evaluate(self, params, state, X, y, GC_true, save_path=None,
                 threshold=False, ignore_features=True):
        """Recon/score/GC MSE summary (ref evaluate :1329-1356, minus the
        matplotlib side effects, which live in utils.plotting)."""
        GC_est = self.gc(params, threshold=threshold,
                         ignore_features=ignore_features)
        gc_mse = [(i, j, float(np.mean((np.asarray(ge, dtype=np.float64)
                                        - np.asarray(gt, dtype=np.float64))
                                       ** 2)))
                  for i, ge in enumerate(GC_est)
                  for j, gt in enumerate(GC_true)]
        X = np.asarray(X, dtype=np.float32)
        X_hat = self.reconstruct(params, state, X)
        y_hat = self.predict_proba(params, state, X)
        recon_mse = float(np.mean((X_hat - X) ** 2))
        score_mse = float(np.mean((y_hat - np.asarray(y)) ** 2))
        summary = {"gc_mse": gc_mse, "recon_mse": recon_mse,
                   "score_mse": score_mse, "avg_recon_mse": recon_mse,
                   "avg_score_mse": score_mse}
        if save_path:
            with open(os.path.join(save_path, "eval_summary.pkl"), "wb") as f:
                pickle.dump(summary, f)
        return summary
