"""cMLP_FM — single-factor cMLP forecaster baseline ("NCFM with 1 factor").

Functional rebuild of /root/reference/models/cmlp_fm.py:58-475: a cMLP rolled out
autoregressively for num_sims steps, trained with Adam on channelwise forecasting
MSE plus an L1 adjacency penalty on the unlagged GC estimate (no prox in fit,
matching the reference's choice at cmlp_fm.py:165-167 — the prox op is still
available through redcliff_tpu.ops.prox for GISTA-style training).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Tuple

import jax
import jax.numpy as jnp

from redcliff_tpu.models import cmlp as cmlp_mod
from redcliff_tpu.ops import losses as L
from redcliff_tpu.ops import prox as prox_mod

__all__ = ["CMLPFMConfig", "CMLPFM"]


@dataclass(frozen=True)
class CMLPFMConfig:
    num_chans: int
    gen_lag: int
    gen_hidden: Tuple[int, ...]
    input_length: int
    num_sims: int = 1
    forecast_coeff: float = 1.0
    adj_l1_coeff: float = 0.0
    wavelet_level: int | None = None

    @property
    def num_series(self):
        if self.wavelet_level is not None:
            return self.num_chans * (self.wavelet_level + 1)
        return self.num_chans

    @property
    def sim_output_length(self):
        """Per-sim prediction length: the cMLP emits T-lag+1 steps per window."""
        return self.input_length - self.gen_lag + 1

    @property
    def total_output_length(self):
        return self.num_sims * self.sim_output_length


class CMLPFM:
    """Pure-functional model: params pytree + apply fns, one jit'd train step."""

    def __init__(self, config: CMLPFMConfig):
        self.config = config

    def init(self, key):
        return {
            "factor": cmlp_mod.init_cmlp_params(
                key, self.config.num_series, self.config.gen_lag, list(self.config.gen_hidden)
            )
        }

    def forward(self, params, X_in):
        """Autoregressive multi-sim forecast (ref cmlp_fm.py:96-148).

        X_in: (B, input_length, C). Each sim emits (B, T', C) predictions with
        T' = input_length - lag + 1; the next sim's window is the previous window
        shifted by T' with predictions appended. Returns (B, num_sims*T', C).
        """
        cfg = self.config
        window = X_in
        sims = []
        for _ in range(cfg.num_sims):
            preds = cmlp_mod.cmlp_forward(params["factor"], window)
            sims.append(preds)
            Tp = preds.shape[1]
            if Tp == window.shape[1]:
                window = preds
            else:
                window = jnp.concatenate([window[:, Tp:, :], preds], axis=1)
        return jnp.concatenate(sims, axis=1)

    def gc(self, params, threshold=False, ignore_lag=True,
           combine_wavelet_representations=False, rank_wavelets=False):
        """List of per-factor GC estimates — length 1 here (ref cmlp_fm.py:150-160)."""
        cfg = self.config
        mask = (
            cmlp_mod.build_wavelet_ranking_mask(
                cfg.num_series, wavelets_per_chan=cfg.num_series // cfg.num_chans
            )
            if rank_wavelets and cfg.wavelet_level is not None
            else None
        )
        return [
            cmlp_mod.cmlp_gc(
                params["factor"], threshold=threshold, ignore_lag=ignore_lag,
                wavelet_mask=mask, rank_wavelets=rank_wavelets,
                num_chans=cfg.num_chans,
                combine_wavelet_representations=combine_wavelet_representations,
            )
        ]

    def loss(self, params, X):
        """Combined loss on a raw batch X: (B, T, C) with
        T >= input_length + total_output_length (ref cmlp_fm.py:156-180, 198-210)."""
        cfg = self.config
        preds = self.forward(params, X[:, : cfg.input_length, :])
        targets = X[:, cfg.input_length : cfg.input_length + cfg.total_output_length, :]
        forecasting = cfg.forecast_coeff * L.channelwise_forecast_mse(preds, targets)
        gc = self.gc(params, ignore_lag=True)[0]
        adj_l1 = cfg.adj_l1_coeff * jnp.sum(jnp.abs(gc))
        combo = forecasting + adj_l1
        return combo, {"forecasting_loss": forecasting, "adj_l1_penalty": adj_l1}

    def apply_prox(self, params, lam, lr, penalty="GL"):
        """Optional GISTA prox on the first-layer block (ref cmlp.py:117-144).
        GL dispatches through the fused Pallas TPU kernel (jnp fallback off-TPU
        and for GSGL/H)."""
        from redcliff_tpu.ops.pallas_prox import gl_prox

        new_w = gl_prox(params["factor"][0]["w"], lam, lr, penalty)
        factor = [dict(params["factor"][0], w=new_w)] + list(params["factor"][1:])
        return dict(params, factor=factor)

    # ---- trainer protocol -------------------------------------------------
    def normalization_coeffs(self):
        """Loss-part coefficients divided out in validation reporting so
        grid-search runs are comparable (ref cmlp_fm.py validate_training)."""
        return {
            "forecasting_loss": self.config.forecast_coeff,
            "adj_l1_penalty": self.config.adj_l1_coeff,
        }

    def validation_criteria(self, params, val_metrics):
        """Early-stopping criterion: normalized GC L1 + val forecasting loss
        (ref cmlp_fm.py:352-356: curr_l1_loss + avg_val_forecasting_loss)."""
        gc = self.gc(params, ignore_lag=False)[0]
        gc = gc / jnp.maximum(jnp.max(gc), 1e-12)
        return jnp.sum(jnp.abs(gc)) + val_metrics["forecasting_loss"]
