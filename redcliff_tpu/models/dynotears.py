"""DYNOTEARS — dynamic-Bayesian-network structure learning.

Equivalents of the reference's vendored solver and its two model wrappers:

* solver: ref models/causalnex_dynotears.py (`from_numpy_dynamic` :162,
  `_learn_dynamic_structure` :333, `_h` :393, `_func` :407, `_grad` :435,
  free functions `dynotears_h_constraint` :513 / `dynotears_objective` :527) —
  augmented-Lagrangian dual ascent over (W, A) with the NOTEARS acyclicity
  penalty h(W) = tr(exp(W∘W)) − d and scipy L-BFGS-B inner solves on the
  non-negative (plus, minus) split parameterization;
* stochastic wrapper: ref models/dynotears.py:14-168 — per-sample refits over
  minibatch streams with warm-started (wa, ρ, α, h) state;
* vanilla wrapper: ref models/dynotears_vanilla.py:14-75 — one-shot fit that
  averages per-sample lagged matrices.

This is a host-side small-matrix solver (d ≤ tens), so numpy/scipy is the
right substrate — the TPU-side win for this family comes from running many
independent fits across the hyperparameter grid engine, not from porting
L-BFGS-B to the chip. The objective/gradient here are one vectorized
expression per call rather than the reference's per-block assembly.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional

import numpy as np
import scipy.linalg as slin
import scipy.optimize as sopt

from ..runtime.checkpoint import write_checkpoint

__all__ = [
    "reshape_wa", "dynotears_h_constraint", "dynotears_objective",
    "dynotears_solve", "DynotearsState", "DynotearsModel",
    "DynotearsVanillaModel",
]


def reshape_wa(wa_vec, d_vars, p_orders):
    """(plus, minus)-split vector → (W [d,d], A [p·d, d]).

    Layout matches the reference's `_reshape_wa` (ref causalnex_dynotears.py:301):
    first 2·d² entries are W⁺ rows then W⁻ rows; the rest alternates per lag
    block A⁺ / A⁻.
    """
    wa = np.asarray(wa_vec).reshape(2 * (p_orders + 1) * d_vars, d_vars)
    w_mat = wa[:d_vars] - wa[d_vars : 2 * d_vars]
    rest = wa[2 * d_vars :].reshape(2 * p_orders, d_vars * d_vars)
    a_mat = (rest[0::2] - rest[1::2]).reshape(p_orders * d_vars, d_vars)
    return w_mat, a_mat


def dynotears_h_constraint(wa_vec, d_vars, p_orders):
    """NOTEARS acyclicity value of the intra-slice W: tr(exp(W∘W)) − d."""
    w_mat, _ = reshape_wa(wa_vec, d_vars, p_orders)
    return float(np.trace(slin.expm(w_mat * w_mat)) - d_vars)


def dynotears_objective(X, Xlags, wa_vec, rho, alpha, d_vars, p_orders,
                        lambda_a, lambda_w, n):
    """Penalized least-squares score (ref causalnex_dynotears.py:527-552):
    ½/n‖X(I−W) − Xlags·A‖² + ½ρh² + αh + λ‖·‖₁ (the L1 is the plain sum of the
    non-negative split vector)."""
    w_mat, a_mat = reshape_wa(wa_vec, d_vars, p_orders)
    resid = X @ (np.eye(d_vars) - w_mat) - Xlags @ a_mat
    loss = 0.5 / n * float(np.sum(resid * resid))
    h = dynotears_h_constraint(wa_vec, d_vars, p_orders)
    wa_vec = np.asarray(wa_vec)
    l1 = lambda_w * wa_vec[: 2 * d_vars**2].sum() + \
        lambda_a * wa_vec[2 * d_vars**2 :].sum()
    return loss + 0.5 * rho * h * h + alpha * h + l1


def _grad_split(M_w, M_a, lambda_w, lambda_a, d_vars, p_orders):
    """Map gradients w.r.t. (W, A) onto the (plus, minus) split layout:
    ∂/∂plus = g + λ, ∂/∂minus = −g + λ."""
    gw = np.concatenate([M_w, -M_w], axis=0).ravel() + lambda_w
    ga = M_a.reshape(p_orders, d_vars * d_vars)
    ga = np.hstack([ga, -ga]).ravel() + lambda_a
    return np.concatenate([gw, ga])


@dataclass
class DynotearsState:
    """Warm-startable solver state threaded across minibatch refits
    (the reference passed these through `from_numpy_dynamic` keyword args)."""
    wa_est: Optional[np.ndarray] = None
    rho: float = 1.0
    alpha: float = 0.0
    h_value: float = np.inf
    h_new: float = np.inf
    wa_new: Optional[np.ndarray] = None


@dataclass
class DynotearsResult:
    w_mat: np.ndarray
    a_mat: np.ndarray
    state: DynotearsState
    n: int
    d_vars: int
    p_orders: int


def _bounds(d_vars, p_orders, tabu_edges, tabu_parent_nodes, tabu_child_nodes):
    """Box constraints: all split entries ≥ 0; banned entries pinned to 0
    (self-loops in W always; tabu edges/parents/children per lag)."""
    tabu_edges = set(tabu_edges or [])
    parents = set(tabu_parent_nodes or [])
    children = set(tabu_child_nodes or [])

    def banned(lag, i, j):
        return (lag == 0 and i == j) or (lag, i, j) in tabu_edges \
            or i in parents or j in children

    bnds = [(0, 0) if banned(0, i, j) else (0, None)
            for i in range(d_vars) for j in range(d_vars)] * 2
    for k in range(1, p_orders + 1):
        bnds.extend([(0, 0) if banned(k, i, j) else (0, None)
                     for i in range(d_vars) for j in range(d_vars)] * 2)
    return bnds


def dynotears_solve(X, Xlags, lambda_w=0.1, lambda_a=0.1, max_iter=100,
                    h_tol=1e-8, w_threshold=0.0, tabu_edges=None,
                    tabu_parent_nodes=None, tabu_child_nodes=None,
                    grad_step=1.0, state: Optional[DynotearsState] = None):
    """Augmented-Lagrangian DYNOTEARS fit of one (X, Xlags) pair.

    Equivalent of ref `from_numpy_dynamic`/`_learn_dynamic_structure`
    (causalnex_dynotears.py:162-510): inner L-BFGS-B solves over the
    non-negative split vector, ρ×10 escalation while h fails to shrink 4×,
    dual update α += ρh, exit at h ≤ h_tol. ``state`` warm-starts
    (wa, ρ, α, h) exactly like the reference's threaded keyword args.
    """
    X = np.asarray(X, dtype=np.float64)
    Xlags = np.asarray(Xlags, dtype=np.float64)
    if X.size == 0 or Xlags.size == 0:
        raise ValueError("input data must be non-empty")
    if X.shape[0] != Xlags.shape[0]:
        raise ValueError("X and Xlags must have the same number of rows")
    if Xlags.shape[1] % X.shape[1] != 0:
        raise ValueError("Xlags columns must be a multiple of X columns")
    n, d_vars = X.shape
    p_orders = Xlags.shape[1] // d_vars
    bnds = _bounds(d_vars, p_orders, tabu_edges, tabu_parent_nodes,
                   tabu_child_nodes)

    st = state or DynotearsState()
    wa_est = (np.zeros(2 * (p_orders + 1) * d_vars**2)
              if st.wa_est is None else np.array(st.wa_est, dtype=np.float64))
    # the reference resets h_new to a copy of h_value on every call that
    # threads warm-start state (causalnex_dynotears.py:478-492)
    rho, alpha = st.rho, st.alpha
    h_value = st.h_value
    h_new = h_value
    # pre-seeded so a trivially-satisfied inner loop (h_value == 0) still has
    # an iterate to adopt (ref inits wa_new to zeros / a copy of wa_est)
    wa_new = (np.zeros_like(wa_est) if st.wa_new is None else wa_est.copy())

    eye = np.eye(d_vars)
    XtX = X.T @ X
    XltX = Xlags.T @ X
    XtXl = X.T @ Xlags
    XltXl = Xlags.T @ Xlags

    def func(wa_vec):
        return dynotears_objective(X, Xlags, wa_vec, rho, alpha, d_vars,
                                   p_orders, lambda_a, lambda_w, n)

    def grad(wa_vec):
        w_mat, a_mat = reshape_wa(wa_vec, d_vars, p_orders)
        e_mat = slin.expm(w_mat * w_mat)
        # ∂W of ½/n‖X(I−W) − Xl·A‖² = −1/n·Xᵀ(X(I−W) − Xl·A); likewise for A
        loss_grad_w = -1.0 / n * (XtX @ (eye - w_mat) - XtXl @ a_mat)
        obj_grad_w = loss_grad_w + \
            (rho * (np.trace(e_mat) - d_vars) + alpha) * e_mat.T * w_mat * 2
        loss_grad_a = -1.0 / n * (XltX @ (eye - w_mat) - XltXl @ a_mat)
        return grad_step * _grad_split(obj_grad_w, loss_grad_a, lambda_w,
                                       lambda_a, d_vars, p_orders)

    for n_iter in range(max_iter):
        while rho < 1e20 and (h_new > 0.25 * h_value or h_new == np.inf):
            res = sopt.minimize(func, wa_est, method="L-BFGS-B", jac=grad,
                                bounds=bnds)
            wa_new = res.x
            h_new = dynotears_h_constraint(wa_new, d_vars, p_orders)
            if h_new > 0.25 * h_value:
                rho *= 10
        wa_est = wa_new
        h_value = h_new
        alpha += rho * h_value
        if h_value <= h_tol:
            break

    w_mat, a_mat = reshape_wa(wa_est, d_vars, p_orders)
    w_mat = np.where(np.abs(w_mat) < w_threshold, 0.0, w_mat)
    a_mat = np.where(np.abs(a_mat) < w_threshold, 0.0, a_mat)
    out_state = DynotearsState(wa_est=wa_est, rho=rho, alpha=alpha,
                               h_value=h_value, h_new=h_new, wa_new=wa_new)
    return DynotearsResult(w_mat=w_mat, a_mat=a_mat, state=out_state,
                           n=n, d_vars=d_vars, p_orders=p_orders)


# --------------------------------------------------------------- model wrappers

@dataclass
class DynotearsConfig:
    """Shared hyperparameters of both wrappers (ref models/dynotears.py:15-35,
    dynotears_vanilla.py:15-25)."""
    lambda_w: float = 0.1
    lambda_a: float = 0.1
    max_iter: int = 100
    h_tol: float = 1e-8
    w_threshold: float = 0.0
    grad_step: float = 1.0
    lag_size: int = 1
    tabu_edges: Optional[list] = None
    tabu_parent_nodes: Optional[list] = None
    tabu_child_nodes: Optional[list] = None
    # which pieces of solver state are carried across per-sample refits
    # (ref models/dynotears.py fit() reuse_* flags; wa_est always carries)
    reuse_rho: bool = False
    reuse_alpha: bool = False
    reuse_h_val: bool = False
    reuse_h_new: bool = False
    reuse_wa_new: bool = False


def _split_windows(X, lag_size):
    """One recording (T, C) → the reference's (X_in, Xlags) pair: the first
    T−lag rows regressed against the rows lag steps later
    (ref models/dynotears.py:85-87 — note the reference feeds the *later*
    values as the 'lagged' design; kept as-is for parity)."""
    return X[: -lag_size], X[lag_size:]


class DynotearsModel:
    """Stochastic DYNOTEARS: per-sample warm-started refits over minibatch
    epochs with early stopping on mean validation objective
    (ref models/dynotears.py:14-168)."""

    def __init__(self, config: DynotearsConfig = None, **kw):
        self.config = config or DynotearsConfig(**kw)
        self.state = DynotearsState()
        self.d_vars = None
        self.p_orders = None
        self.n = None

    # -- GC readout: the lagged weight matrix (ref models/dynotears.py:37-42)
    def gc(self):
        assert self.d_vars is not None, "fit the model before reading GC"
        _, a_mat = reshape_wa(self.state.wa_est, self.d_vars, self.p_orders)
        return a_mat

    GC = gc

    def _fit_one(self, x_in, x_lag):
        cfg = self.config
        res = dynotears_solve(
            x_in, x_lag, lambda_w=cfg.lambda_w, lambda_a=cfg.lambda_a,
            max_iter=cfg.max_iter, h_tol=cfg.h_tol,
            w_threshold=cfg.w_threshold, tabu_edges=cfg.tabu_edges,
            tabu_parent_nodes=cfg.tabu_parent_nodes,
            tabu_child_nodes=cfg.tabu_child_nodes, grad_step=cfg.grad_step,
            state=self.state)
        self.d_vars, self.p_orders, self.n = res.d_vars, res.p_orders, res.n
        new = DynotearsState(wa_est=res.state.wa_est,
                             rho=res.state.rho if cfg.reuse_rho else self.state.rho,
                             alpha=res.state.alpha if cfg.reuse_alpha else self.state.alpha,
                             h_value=res.state.h_value if cfg.reuse_h_val else self.state.h_value,
                             h_new=res.state.h_new if cfg.reuse_h_new else self.state.h_new,
                             wa_new=res.state.wa_new if cfg.reuse_wa_new else self.state.wa_new)
        self.state = new

    def _mean_objective(self, ds, batch_size):
        cfg = self.config
        total, count = 0.0, 0
        for X, _ in ds.batches(batch_size):
            for b in range(X.shape[0]):
                x_in, x_lag = _split_windows(np.asarray(X[b], np.float64),
                                             cfg.lag_size)
                total += dynotears_objective(
                    x_in, x_lag, self.state.wa_est, self.state.rho,
                    self.state.alpha, self.d_vars, self.p_orders,
                    cfg.lambda_a, cfg.lambda_w, self.n)
                count += 1
        return total / max(count, 1)

    def save_checkpoint(self, save_dir, it, val_history, best_loss, best_it,
                        state=None, shape=None):
        """Persist the best-so-far solver state (the reference checkpoints its
        best_model deepcopy, not the current iterate)."""
        os.makedirs(save_dir, exist_ok=True)
        state = state if state is not None else self.state
        d_vars, p_orders, n = shape or (self.d_vars, self.p_orders, self.n)
        # durable checkpoint writes (atomic + CRC + .prev), like the trainers
        write_checkpoint(os.path.join(save_dir, "final_best_model.bin"),
                         {"model_class": type(self).__name__,
                          "config": self.config, "state": state,
                          "d_vars": d_vars, "p_orders": p_orders,
                          "n": n})
        write_checkpoint(
            os.path.join(save_dir,
                         "training_meta_data_and_hyper_parameters.pkl"),
            {"epoch": it, "val_avg_loss_history": val_history,
             "best_loss": best_loss, "best_it": best_it})

    def fit(self, train_ds, val_ds, save_dir=None, max_data_iter=10,
            batch_size=32, num_iters_prior_to_stop=10, check_every=5,
            verbose=False):
        """Epochs of per-sample refits; early stop when the mean validation
        objective has not improved for ``num_iters_prior_to_stop`` epochs."""
        cfg = self.config
        val_history = []
        best_loss, best_it, best_state = np.inf, None, None
        best_shape = None
        for it in range(max_data_iter):
            for X, _ in train_ds.batches(batch_size):
                for b in range(X.shape[0]):
                    x_in, x_lag = _split_windows(np.asarray(X[b], np.float64),
                                                 cfg.lag_size)
                    self._fit_one(x_in, x_lag)
            cur = self._mean_objective(val_ds, batch_size)
            val_history.append(cur)
            if verbose:
                print(f"DynotearsModel.fit: epoch {it} val={cur:.6f}",
                      flush=True)
            if cur < best_loss:
                best_loss, best_it = cur, it
                best_state = DynotearsState(**vars(self.state))
                best_shape = (self.d_vars, self.p_orders, self.n)
            elif best_it is not None and \
                    (it - best_it) == num_iters_prior_to_stop:
                break
            elif best_it is None and it + 1 >= num_iters_prior_to_stop:
                # validation objective never became finite (NaN data or a
                # diverged fit): stop instead of crashing on best_it - None
                break
            if save_dir is not None and it % check_every == 0:
                self.save_checkpoint(save_dir, it, val_history, best_loss,
                                     best_it, state=best_state,
                                     shape=best_shape)
        if best_state is not None:
            self.state = best_state
            self.d_vars, self.p_orders, self.n = best_shape
        if save_dir is not None:
            self.save_checkpoint(save_dir, len(val_history) - 1, val_history,
                                 best_loss, best_it)
        return best_loss, val_history


class DynotearsVanillaModel:
    """One-shot DYNOTEARS: independent cold-start fits per sample, summed
    lagged matrices scaled by 1/num_nodes (ref models/dynotears_vanilla.py:40-71
    — the reference divides by the node count rather than the sample count;
    kept, as it only rescales the scores)."""

    def __init__(self, config: DynotearsConfig = None, **kw):
        self.config = config or DynotearsConfig(**kw)
        self.a_est = None

    def gc(self):
        return self.a_est

    GC = gc

    def fit(self, X_train, save_dir=None, max_samples=None):
        """X_train: (num_samples, T, C) array of recordings."""
        cfg = self.config
        X_train = np.asarray(X_train, dtype=np.float64)
        num_samples, _, num_nodes = X_train.shape
        if max_samples is not None:
            num_samples = min(num_samples, max_samples)
        # _split_windows always yields a single-lag design, so every per-sample
        # a_mat is (num_nodes, num_nodes) regardless of lag_size
        acc = np.zeros((num_nodes, num_nodes))
        for s in range(num_samples):
            x_in, x_lag = _split_windows(X_train[s], cfg.lag_size)
            res = dynotears_solve(
                x_in, x_lag, lambda_w=cfg.lambda_w, lambda_a=cfg.lambda_a,
                max_iter=cfg.max_iter, h_tol=cfg.h_tol,
                w_threshold=cfg.w_threshold, tabu_edges=cfg.tabu_edges,
                tabu_parent_nodes=cfg.tabu_parent_nodes,
                tabu_child_nodes=cfg.tabu_child_nodes, state=None)
            acc = acc + res.a_mat
        self.a_est = acc / (1.0 * num_nodes)
        if save_dir is not None:
            os.makedirs(save_dir, exist_ok=True)
            write_checkpoint(os.path.join(save_dir, "final_best_model.bin"),
                             {"model_class": type(self).__name__,
                              "config": self.config, "a_est": self.a_est})
        return self.a_est
