"""REDCLIFF-S-cMLP — the paper's model, TPU-native.

Functional rebuild of /root/reference/models/redcliff_s_cmlp.py:18-1766 and its
state-smoothing variant redcliff_s_cmlp_withStateSmoothing.py (the smoothing
penalty is a config coefficient here instead of a 1,790-line near-clone file):
K cMLP factor forecasters + a factor-score embedder whose window-conditioned
weightings mix the per-factor one-step predictions; first-layer weight norms of
each factor are the per-state Granger-causal graph estimates.

TPU-first deltas from the reference (same semantics):
* the K factors are ONE stacked weight block driven by vmap — the reference's
  ``for i in range(K): factors[i](window)`` inner hot loop (ref :302-310)
  becomes a single batched einsum chain;
* both forward-pass modes unroll num_sims as a static loop of fused steps;
* all 9 GC readout modes are dense tensor expressions returning a
  (samples, factors, C, C[, L]) array instead of nested Python lists;
* the multi-term loss (ref :620-686) is computed without re-extracting GC
  twice through Python loops — one readout feeds both the cosine and L1 terms.
"""
from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Tuple

import jax
import jax.numpy as jnp

from redcliff_tpu.models import clstm as clstm_mod
from redcliff_tpu.models import cmlp as cmlp_mod
from redcliff_tpu.models.embedders import build_embedder, CEmbedder, DGCNNEmbedder
from redcliff_tpu.ops import losses as L
from redcliff_tpu.ops.factor_mix import factor_mix

__all__ = ["RedcliffSCMLPConfig", "RedcliffSCMLP", "TRAINING_MODES", "GC_EST_MODES",
           "phase_schedule"]

TRAINING_MODES = (
    "pretrain_embedder_then_acclimate_factors_then_combined",
    "pretrain_embedder_then_post_train_factor_withComboCosSimL1FreezeByEpoch",
    "pretrain_embedder_then_post_train_factor_withComboCosSimL1FreezeByBatch",
    "pretrain_embedder_then_post_train_factor_withL1FreezeByEpoch",
    "pretrain_embedder_then_post_train_factor_withL1FreezeByBatch",
    "pretrain_embedder_then_post_train_factor",
    "pretrain_embedder_and_pretrain_factor_then_combined",
    "pretrain_embedder_then_combined",
    "pretrain_factor_then_combined",
    "combined",
)

GC_EST_MODES = (
    "fixed_factor_exclusive",
    "raw_embedder",
    "conditional_factor_exclusive",
    "fixed_embedder_exclusive",
    "conditional_embedder_exclusive",
    "fixed_factor_fixed_embedder",
    "conditional_factor_fixed_embedder",
    "fixed_factor_conditional_embedder",
    "conditional_factor_conditional_embedder",
)

FORWARD_PASS_MODES = (
    "apply_factor_weights_at_each_sim_step",
    "apply_factor_weights_after_sim_completion",
)


def phase_schedule(cfg, epoch):
    """Epoch -> tuple of phase names under cfg.training_mode (ref batch_update
    :696-714). Shared by the per-point trainer and the grid runner."""
    mode = cfg.training_mode
    if epoch <= cfg.num_pretrain_epochs - 1:
        phases = []
        if "pretrain_embedder" in mode:
            phases.append("embedder_pretrain")
        if "pretrain_factor" in mode:
            phases.append("factor_pretrain")
        return tuple(phases)
    if ("acclimate_factors" in mode
            and epoch <= cfg.num_pretrain_epochs + cfg.num_acclimation_epochs - 1):
        return ("factor_pretrain",)
    if "combined" in mode:
        return ("combined",)
    if "post_train_factor" in mode:
        return ("post_train",)
    raise NotImplementedError(mode)


def _smooth_on(coeff):
    """True when the smoothing penalty should be computed: statically decidable
    for concrete coefficients; always-on for traced (grid-axis) coefficients."""
    try:
        return float(coeff) > 0.0
    except Exception:  # traced value — must include the term in the graph
        return True


@dataclass(frozen=True)
class RedcliffSCMLPConfig:
    num_chans: int
    gen_lag: int
    gen_hidden: Tuple[int, ...]
    embed_lag: int
    embed_hidden_sizes: Tuple[int, ...]
    num_factors: int
    num_supervised_factors: int
    # loss coefficients (ref :44-52)
    forecast_coeff: float = 1.0
    factor_score_coeff: float = 1.0
    factor_cos_sim_coeff: float = 0.0
    factor_weight_l1_coeff: float = 0.0
    adj_l1_reg_coeff: float = 0.0
    dagness_reg_coeff: float = 0.0  # defined-but-disabled in the reference loss
    dagness_lag_coeff: float = 0.0
    dagness_node_coeff: float = 0.0
    use_sigmoid_restriction: bool = True
    sigmoid_eccentricity_coeff: float = 10.0
    # the canonical experiment pairs a DGCNN embedder with the
    # conditional_factor_fixed_embedder readout
    # (ref train/REDCLIFF_S_CMLP_d4IC_BSCgs1_cached_args.txt)
    factor_score_embedder_type: str = "DGCNN"
    dgcnn_num_graph_conv_layers: int = 2
    dgcnn_num_hidden_nodes: int = 32
    primary_gc_est_mode: str = "conditional_factor_fixed_embedder"
    forward_pass_mode: str = "apply_factor_weights_at_each_sim_step"
    num_sims: int = 1
    wavelet_level: int | None = None
    # factor forecaster family: "cMLP" (the paper's model) or "cLSTM" (the
    # REDCLIFF_S_CLSTM variant the reference factory declares but never
    # shipped — model_utils.py:341 imports a missing file; implemented here).
    # cLSTM factors use gen_hidden[0] as the per-series LSTM width and read
    # GC from the input-weight column norms (no lag axis).
    factor_network_type: str = "cMLP"
    training_mode: str = "pretrain_embedder_and_pretrain_factor_then_combined"
    num_pretrain_epochs: int = 0
    num_acclimation_epochs: int = 0
    # state-smoothing variant (ref redcliff_s_cmlp_withStateSmoothing.py:30,50):
    # coefficient 0 disables the penalty, recovering the base model exactly
    factor_weight_smoothing_penalty_coeff: float = 0.0
    # the reference's ctor default (:23) is never overridden by any driver
    state_score_smoothing_epsilon: float = 0.0001

    def __post_init__(self):
        assert self.factor_network_type in ("cMLP", "cLSTM"), \
            self.factor_network_type
        assert self.training_mode in TRAINING_MODES, self.training_mode
        assert self.primary_gc_est_mode in GC_EST_MODES, self.primary_gc_est_mode
        assert self.forward_pass_mode in FORWARD_PASS_MODES, self.forward_pass_mode
        if "pretrain" in self.training_mode:
            assert self.num_pretrain_epochs > 0
        else:
            assert self.num_pretrain_epochs == 0
        if "acclimate" in self.training_mode:
            assert self.num_acclimation_epochs > 0
        else:
            assert self.num_acclimation_epochs == 0
        if self.factor_score_embedder_type == "DGCNN":
            assert self.primary_gc_est_mode != "conditional_embedder_exclusive"
        # every mode with an embedder GC component needs a causal embedder type
        # (ref CAUSAL_EMBEDDER_TYPES, redcliff_s_cmlp.py:92,454); fail at config
        # construction rather than deep inside the first jit'd training step
        if ("embedder" in self.primary_gc_est_mode
                and self.factor_score_embedder_type not in ("cEmbedder", "DGCNN")):
            raise ValueError(
                f"primary_gc_est_mode={self.primary_gc_est_mode!r} reads a GC "
                f"estimate out of the embedder, which requires "
                f"factor_score_embedder_type 'cEmbedder' or 'DGCNN' (got "
                f"{self.factor_score_embedder_type!r})")

    @property
    def num_series(self):
        if self.wavelet_level is not None:
            return self.num_chans * (self.wavelet_level + 1)
        return self.num_chans

    @property
    def max_lag(self):
        return max(self.gen_lag, self.embed_lag)

    @property
    def output_length(self):
        """Each sim emits one step in both forward modes (windows of exactly
        gen_lag feed the factors)."""
        return 1


class RedcliffSCMLP:
    def __init__(self, config: RedcliffSCMLPConfig):
        self.config = config
        cfg = config
        self.embedder = build_embedder(
            cfg.factor_score_embedder_type,
            num_chans=cfg.num_chans, num_series=cfg.num_series,
            embed_lag=cfg.embed_lag, embed_hidden_sizes=list(cfg.embed_hidden_sizes),
            num_factors=cfg.num_factors,
            num_supervised_factors=cfg.num_supervised_factors,
            use_sigmoid_restriction=cfg.use_sigmoid_restriction,
            sigmoid_eccentricity_coeff=cfg.sigmoid_eccentricity_coeff,
            wavelet_level=cfg.wavelet_level,
            dgcnn_args={
                "num_features_per_node": cfg.embed_lag,
                "num_graph_conv_layers": cfg.dgcnn_num_graph_conv_layers,
                "num_hidden_nodes": cfg.dgcnn_num_hidden_nodes,
            },
        )

    # ------------------------------------------------------------------ params
    def init(self, key):
        cfg = self.config
        ke, kf = jax.random.split(key)
        factor_keys = jax.random.split(kf, cfg.num_factors)
        if cfg.factor_network_type == "cLSTM":
            factors = jax.vmap(
                lambda k: clstm_mod.init_clstm_params(
                    k, cfg.num_series, cfg.gen_hidden[0])
            )(factor_keys)
        else:
            factors = jax.vmap(
                lambda k: cmlp_mod.init_cmlp_params(
                    k, cfg.num_series, cfg.gen_lag, list(cfg.gen_hidden))
            )(factor_keys)
        return {"embedder": self.embedder.init(ke), "factors": factors}

    def _factor_apply(self, factor_params, window):
        """One factor network's one-step prediction on a (B, lag, C) window
        -> (B, 1, C). cLSTM factors consume the window sequentially and emit
        the final step's forecast."""
        if self.config.factor_network_type == "cLSTM":
            preds, _ = clstm_mod.clstm_forward(factor_params, window)
            return preds[:, -1:, :]
        return cmlp_mod.cmlp_forward(factor_params, window)

    # ----------------------------------------------------------------- forward
    def _embed(self, params, window):
        """Embedder call on the last embed_lag steps; DGCNN takes node-major
        input (ref :286-294)."""
        cfg = self.config
        w = window[:, -cfg.embed_lag :, :]
        if cfg.factor_score_embedder_type == "DGCNN":
            return self.embedder.apply(params["embedder"], jnp.transpose(w, (0, 2, 1)))
        return self.embedder.apply(params["embedder"], w)

    def _factor_step(self, params, window):
        """All K factors' one-step predictions on the last gen_lag steps:
        (K, B, 1, C)."""
        cfg = self.config
        w = window[:, -cfg.gen_lag :, :]
        return jax.vmap(lambda p: self._factor_apply(p, w))(params["factors"])

    def forward(self, params, X, factor_weightings=None):
        """Returns (x_sims (B, num_sims, C), factor_preds (num_sims, K, B, 1, C),
        factor_weighting_preds list, state_label_preds list) — the reference's
        4-tuple (ref :384-408)."""
        cfg = self.config
        if cfg.forward_pass_mode == "apply_factor_weights_at_each_sim_step":
            return self._forward_stepwise(params, X, factor_weightings)
        return self._forward_post_weighted(params, X, factor_weightings)

    def _forward_stepwise(self, params, X, fixed_weightings=None):
        """ref :249-319 — new weightings from the sliding window at every sim step."""
        cfg = self.config
        window = X
        sims, fw_preds, label_preds, factor_preds = [], [], [], []
        for s in range(cfg.num_sims):
            weightings, logits = self._embed(params, window)
            if fixed_weightings is not None:
                weightings = fixed_weightings
            label_preds.append(logits if logits is not None else weightings)
            preds = self._factor_step(params, window)  # (K, B, 1, C)
            # fused factor-mix (ops/factor_mix.py): Pallas kernel on real
            # TPU, the exact historical einsum everywhere else
            combined = factor_mix(weightings, preds)
            sims.append(combined)
            fw_preds.append(weightings)
            factor_preds.append(preds)
            window = jnp.concatenate([window[:, combined.shape[1] :, :], combined], axis=1)
        return jnp.concatenate(sims, axis=1), factor_preds, fw_preds, label_preds

    def _forward_post_weighted(self, params, X, fixed_weightings=None):
        """ref :322-381 — weightings computed once; each factor rolls out its own
        autoregressive simulation; the weighted sum happens at completion."""
        cfg = self.config
        weightings, logits = self._embed(params, X)
        if fixed_weightings is not None:
            weightings = fixed_weightings
        if logits is None:
            logits = weightings
        label_preds = [logits for _ in range(cfg.num_sims)]

        K = cfg.num_factors
        win = jnp.broadcast_to(X[None, :, -cfg.gen_lag :, :],
                               (K,) + X[:, -cfg.gen_lag :, :].shape)
        per_factor_sims = []
        for s in range(cfg.num_sims):
            preds = jax.vmap(self._factor_apply)(params["factors"], win)  # (K, B, 1, C)
            per_factor_sims.append(preds)
            win = jnp.concatenate([win[:, :, preds.shape[2] :, :], preds], axis=2)
        factor_sims = jnp.concatenate(per_factor_sims, axis=2)  # (K, B, S, C)
        x_sims = factor_mix(weightings, factor_sims)
        return x_sims, per_factor_sims, [weightings], label_preds

    # ---------------------------------------------------------------------- GC
    def factor_gc(self, params, threshold=False, ignore_lag=True,
                  combine_wavelet_representations=False, rank_wavelets=False):
        """(K, C, C[, L]) per-factor readouts (ref :440-451 via cmlp.GC; cLSTM
        factors read the input-weight column norms, ref clstm.py:126-156)."""
        cfg = self.config
        if cfg.factor_network_type == "cLSTM":
            mask = None
            if rank_wavelets and cfg.wavelet_level is not None:
                mask = cmlp_mod.build_wavelet_ranking_mask(
                    cfg.num_series,
                    wavelets_per_chan=cfg.num_series // cfg.num_chans)
            G = jax.vmap(
                lambda p: clstm_mod.clstm_gc(
                    p, threshold=threshold, wavelet_mask=mask,
                    rank_wavelets=rank_wavelets, num_chans=cfg.num_chans,
                    combine_wavelet_representations=
                    combine_wavelet_representations)
            )(params["factors"])
            return G if ignore_lag else G[..., None]
        mask = None
        if rank_wavelets and cfg.wavelet_level is not None:
            mask = cmlp_mod.build_wavelet_ranking_mask(
                cfg.num_series, wavelets_per_chan=cfg.num_series // cfg.num_chans)
        return jax.vmap(
            lambda p: cmlp_mod.cmlp_gc(
                p, threshold=threshold, ignore_lag=ignore_lag, wavelet_mask=mask,
                rank_wavelets=rank_wavelets, num_chans=cfg.num_chans,
                combine_wavelet_representations=combine_wavelet_representations)
        )(params["factors"])

    def _raw_embedder_gc(self, params, threshold=False, ignore_lag=True,
                         combine_wavelet_representations=False, rank_wavelets=False):
        """(K, C, Le) or (C, C, 1) depending on embedder type (ref :453-475); the
        wavelet flags are forwarded to the embedder readout (ref :456-461)."""
        if isinstance(self.embedder, CEmbedder):
            G = self.embedder.gc(
                params["embedder"], threshold=threshold, ignore_lag=ignore_lag,
                combine_wavelet_representations=combine_wavelet_representations,
                rank_wavelets=rank_wavelets)
            if G.ndim == 2:
                G = G[:, :, None]
            return G
        if isinstance(self.embedder, DGCNNEmbedder):
            G = self.embedder.gc(
                params["embedder"], threshold=threshold,
                combine_node_feature_edges=combine_wavelet_representations)
            return G[:, :, None]
        raise ValueError(
            "raw_embedder GC requires a causal embedder type (cEmbedder or DGCNN)")

    def _fixed_embedder_gc(self, params, threshold=False, ignore_lag=True,
                           combine_wavelet_representations=False, rank_wavelets=False):
        """'System' graph: per-lag outer product of the embedder rows over the
        factor axis, E[:, :, l] = R[:, :, l]^T R[:, :, l] (ref :496-515)."""
        R = self._raw_embedder_gc(
            params, threshold=threshold, ignore_lag=ignore_lag,
            combine_wavelet_representations=combine_wavelet_representations,
            rank_wavelets=rank_wavelets)
        if isinstance(self.embedder, DGCNNEmbedder):
            return R
        return jnp.einsum("kal,kbl->abl", R, R)

    def _conditional_embedder_gc(self, params, X, threshold=False, ignore_lag=True,
                                 combine_wavelet_representations=False,
                                 rank_wavelets=False):
        """(B, K, C, C, Le): per-sample per-factor outer products weighted by the
        window-conditioned factor weightings (ref :517-546)."""
        if isinstance(self.embedder, DGCNNEmbedder):
            raise ValueError(
                "conditional_embedder_exclusive is not supported for DGCNN embedders")
        R = self._raw_embedder_gc(
            params, threshold=threshold, ignore_lag=ignore_lag,
            combine_wavelet_representations=combine_wavelet_representations,
            rank_wavelets=rank_wavelets)
        weightings, _ = self._embed(params, X)
        outer = jnp.einsum("kal,kcl->kacl", R, R)  # (K, C, C, Le)
        return jnp.einsum("bk,kacl->bkacl", weightings, outer)

    def gc(self, params, gc_est_mode=None, X=None, threshold=False, ignore_lag=True,
           combine_wavelet_representations=False, rank_wavelets=False):
        """All 9 readout modes (ref :411-617). Returns a (S, K', C, C, L') array:
        S == 1 for fixed modes, batch size for conditional modes; K' == num_factors
        for factor modes, 1 for embedder-exclusive modes. For 'raw_embedder' the
        (1, 1, K, C, Le) raw map is returned unsquared.
        When ignore_lag=True, L' == 1 (lag already reduced inside the norms)."""
        cfg = self.config
        mode = gc_est_mode or cfg.primary_gc_est_mode
        kw = dict(threshold=threshold, ignore_lag=ignore_lag,
                  combine_wavelet_representations=combine_wavelet_representations,
                  rank_wavelets=rank_wavelets)

        def factor_g():
            G = self.factor_gc(params, **kw)
            return G[:, :, :, None] if G.ndim == 3 else G  # (K, C, C, L)

        lag_clip = min(cfg.gen_lag, cfg.embed_lag)

        if mode == "fixed_factor_exclusive":
            return factor_g()[None]  # (1, K, C, C, L)
        if mode == "raw_embedder":
            return self._raw_embedder_gc(params, **kw)[None, None]
        if mode == "fixed_embedder_exclusive":
            return self._fixed_embedder_gc(params, **kw)[None, None]
        if mode == "conditional_factor_exclusive":
            weightings, _ = self._embed(params, X)
            return jnp.einsum("bk,kacl->bkacl", weightings, factor_g())
        if mode == "conditional_embedder_exclusive":
            return self._conditional_embedder_gc(params, X, **kw)
        if mode == "fixed_factor_fixed_embedder":
            G = factor_g()
            E = self._fixed_embedder_gc(params, **kw)
            if not ignore_lag:
                return (G[:, :, :, -lag_clip:] + E[None, :, :, -lag_clip:])[None]
            return (G + E[None])[None]
        if mode == "conditional_factor_fixed_embedder":
            weightings, _ = self._embed(params, X)
            G = jnp.einsum("bk,kacl->bkacl", weightings, factor_g())
            E = self._fixed_embedder_gc(params, **kw)
            if not ignore_lag:
                return G[..., -lag_clip:] + E[None, None, :, :, -lag_clip:]
            return G + E[None, None]
        if mode == "fixed_factor_conditional_embedder":
            G = factor_g()
            Ec = self._conditional_embedder_gc(params, X, **kw)
            if not ignore_lag:
                return Ec[..., -lag_clip:] + G[None, :, :, :, -lag_clip:]
            return Ec + G[None]
        if mode == "conditional_factor_conditional_embedder":
            weightings, _ = self._embed(params, X)
            G = jnp.einsum("bk,kacl->bkacl", weightings, factor_g())
            Ec = self._conditional_embedder_gc(params, X, **kw)
            if not ignore_lag:
                return G[..., -lag_clip:] + Ec[..., -lag_clip:]
            return G + Ec
        raise ValueError(f"GC EST MODE == {mode} IS NOT SUPPORTED")

    def gc_as_lists(self, params, gc_est_mode=None, X=None, **kw):
        """Host-side view matching the reference's list-of-lists contract
        (ref :411-419: outer list = sample, inner = factor, tensors (C, C, L))."""
        import numpy as np

        arr = np.asarray(self.gc(params, gc_est_mode, X=X, **kw))
        return [[arr[s, k] for k in range(arr.shape[1])] for s in range(arr.shape[0])]

    # -------------------------------------------------------------------- loss
    def compute_loss(self, params, conditioning_X, preds, targets, factor_scores,
                     factor_labels, gc_est_mode=None, embedder_pretrain_loss=False,
                     factor_pretrain_loss=False, coeffs=None, need_gc=None,
                     need_gc_lagged=None):
        """Multi-term loss (ref :620-686 + smoothing variant :667-727).

        factor_scores: list (num_sims) of (B, n) state-label predictions.
        factor_labels: Y with shape (B, S, T) | (B, S, 1) | (B, S).
        coeffs: optional dict of per-call coefficient overrides — may hold traced
        scalars, which is how the grid runner vmaps one compiled step over a
        hyperparameter axis. When traced coefficients are in play the static
        need_gc/need_gc_lagged flags must be supplied by the caller (derived from
        the grid's max coefficient).
        """
        cfg = self.config
        mode = gc_est_mode or cfg.primary_gc_est_mode

        def C(name):
            if coeffs is not None and name in coeffs:
                return coeffs[name]
            return getattr(cfg, name)

        # GC readouts feed only the cosine and adjacency penalties; skip them
        # entirely when the static coefficients are zero (XLA cannot eliminate
        # 0*x for floats, so guarding here removes real hot-path work)
        if need_gc is None:
            need_gc = _smooth_on(C("factor_cos_sim_coeff"))
        if need_gc_lagged is None:
            need_gc_lagged = _smooth_on(C("adj_l1_reg_coeff"))
        gc = (self.gc(params, mode, X=conditioning_X, threshold=False,
                      ignore_lag=True) if need_gc else None)
        gc_lagged = (self.gc(params, mode, X=conditioning_X, threshold=False,
                             ignore_lag=False) if need_gc_lagged else None)

        forecasting_loss = C("forecast_coeff") * L.channelwise_forecast_mse(preds, targets)

        factor_loss = jnp.array(0.0)
        S = cfg.num_supervised_factors
        if factor_scores and factor_scores[0] is not None and S > 0:
            Y = factor_labels
            if Y.ndim == 3:
                if Y.shape[2] > cfg.max_lag:
                    # per-sim-step supervision from the aligned label trace
                    # (ref :631-634)
                    for l, yhat in enumerate(factor_scores):
                        if cfg.max_lag + l >= Y.shape[2]:
                            break
                        y = Y[:, :, cfg.max_lag + l]
                        factor_loss = factor_loss + C("factor_score_coeff") * jnp.mean(
                            (yhat[:, :S] - y[:, :S]) ** 2)
                else:
                    # static-label datasets (D4IC): average all sim scores
                    # (ref :635-641)
                    y = Y[:, :, 0]
                    yhat = sum(factor_scores) / float(len(factor_scores))
                    factor_loss = factor_loss + C("factor_score_coeff") * jnp.mean(
                        (yhat[:, :S] - y[:, :S]) ** 2)
            elif Y.ndim == 2:
                y = Y
                yhat = sum(factor_scores) / float(len(factor_scores))
                factor_loss = factor_loss + C("factor_score_coeff") * jnp.mean(
                    (yhat[:, :S] - y[:, :S]) ** 2)
            else:
                raise NotImplementedError(f"labels with ndim {Y.ndim}")

        fw_l1_penalty = C("factor_weight_l1_coeff") * L.factor_weight_l1(factor_scores[0])

        # smoothing penalty on factor scores across sim steps (Smooth variant)
        fw_smoothing_penalty = jnp.array(0.0)
        if _smooth_on(C("factor_weight_smoothing_penalty_coeff")) and cfg.num_sims >= 2:
            if cfg.num_sims == 2:
                diff = factor_scores[0] - factor_scores[1]
                mask = jax.lax.stop_gradient(
                    diff > cfg.state_score_smoothing_epsilon)
                fw_smoothing_penalty = jnp.sum((diff * mask) ** 2)
            else:
                for i in range(cfg.num_sims - 2):
                    s0, s1, s2 = factor_scores[i], factor_scores[i + 1], factor_scores[i + 2]
                    full = s2 - s0
                    d21 = s2 - s1
                    m21 = jax.lax.stop_gradient(jnp.abs(d21) > jnp.abs(full))
                    fw_smoothing_penalty = fw_smoothing_penalty + jnp.sum((d21 * m21) ** 2)
                    if i == 0:
                        d10 = s1 - s0
                        m10 = jax.lax.stop_gradient(jnp.abs(d10) > jnp.abs(full))
                        fw_smoothing_penalty = fw_smoothing_penalty + jnp.sum((d10 * m10) ** 2)
            fw_smoothing_penalty = (
                C("factor_weight_smoothing_penalty_coeff") * fw_smoothing_penalty)

        # cosine-similarity penalty between factor graphs, summed over samples
        # (ref :657-670); lag axis of the unlagged readout is size 1
        factor_cos_sim_penalty = jnp.array(0.0)
        if need_gc and gc.shape[1] > 1:
            G2 = gc[..., 0] if gc.ndim == 5 else gc
            factor_cos_sim_penalty = C("factor_cos_sim_coeff") * jnp.sum(
                L.pairwise_cosine_penalty(G2, include_diag=False))

        adj_l1_penalty = jnp.array(0.0)
        if need_gc_lagged:
            adj_l1_penalty = C("adj_l1_reg_coeff") * L.lag_weighted_adjacency_l1(gc_lagged)

        if embedder_pretrain_loss:
            assert not factor_pretrain_loss
            combo = factor_loss + fw_l1_penalty + fw_smoothing_penalty
        elif factor_pretrain_loss:
            combo = (forecasting_loss + fw_l1_penalty + fw_smoothing_penalty
                     + adj_l1_penalty + factor_cos_sim_penalty)
        else:
            combo = (forecasting_loss + factor_loss + fw_l1_penalty
                     + fw_smoothing_penalty + adj_l1_penalty + factor_cos_sim_penalty)

        parts = {
            "forecasting_loss": forecasting_loss,
            "factor_loss": factor_loss,
            "factor_cos_sim_penalty": factor_cos_sim_penalty,
            "fw_l1_penalty": fw_l1_penalty,
            "fw_smoothing_penalty": fw_smoothing_penalty,
            "adj_l1_penalty": adj_l1_penalty,
        }
        return combo, parts

    def loss_for_phase(self, params, X, Y, phase, coeffs=None, need_gc=None,
                       need_gc_lagged=None):
        """One batch's loss under a training phase (ref batch_update :689-890):
        phase in {'embedder_pretrain', 'factor_pretrain', 'combined', 'post_train'}.
        Factor-pretrain and post-train run the forward WITHOUT regenerating
        weightings per step in the reference only insofar as weightings are
        produced by the (frozen) embedder — functionally identical here since
        gradient flow is controlled by the optimizer masks, not eval() flags."""
        cfg = self.config
        W = cfg.max_lag
        x_sims, _, _, label_preds = self.forward(params, X[:, :W, :])
        targets = X[:, W : W + cfg.num_sims * cfg.output_length, :]
        conditioning = X[:, : cfg.embed_lag, :]
        return self.compute_loss(
            params, conditioning, x_sims, targets, label_preds, Y,
            embedder_pretrain_loss=(phase == "embedder_pretrain"),
            factor_pretrain_loss=(phase in ("factor_pretrain", "post_train")),
            coeffs=coeffs, need_gc=need_gc, need_gc_lagged=need_gc_lagged,
        )

    # ------------------------------------------------------------------- prox
    def apply_prox(self, params, lam, lr, penalty="GL"):
        """GISTA-style proximal update on the stacked factor first-layer
        block (K, C_out, H, C_in, L) — the trainers'/grid engine's
        ``prox_penalty`` production path. GL dispatches through the fused
        Pallas TPU kernel (ops/pallas_prox.py; jnp reference off-TPU and
        for GSGL/H). cMLP factors only: a cLSTM factor has no lag-
        structured first-layer block to group."""
        if self.config.factor_network_type != "cMLP":
            raise ValueError(
                "apply_prox requires cMLP factor networks (the GL group "
                "structure lives in the lagged first-layer block)")
        from redcliff_tpu.ops.pallas_prox import gl_prox

        factors = params["factors"]
        new_w = gl_prox(factors[0]["w"], lam, lr, penalty)
        new_factors = [dict(factors[0], w=new_w)] + list(factors[1:])
        return dict(params, factors=new_factors)

    # -------------------------------------------------------- factor alignment
    def permute_factors(self, params, order):
        """Reorder the stacked factor params along K (used by the Hungarian
        alignment at the pretrain->train transition, ref :147-202)."""
        import numpy as np

        idx = jnp.asarray(np.asarray(order, dtype=np.int32))
        factors = jax.tree.map(lambda leaf: leaf[idx], params["factors"])
        return dict(params, factors=factors)

    def normalization_coeffs(self):
        cfg = self.config
        return {
            "forecasting_loss": cfg.forecast_coeff,
            "factor_loss": cfg.factor_score_coeff,
            "factor_cos_sim_penalty": cfg.factor_cos_sim_coeff,
            "fw_l1_penalty": cfg.factor_weight_l1_coeff,
            "fw_smoothing_penalty": cfg.factor_weight_smoothing_penalty_coeff,
            "adj_l1_penalty": cfg.adj_l1_reg_coeff,
        }
