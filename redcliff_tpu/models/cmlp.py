"""Tensorized cMLP Granger-causal forecaster.

The reference keeps one small MLP per output series and loops over them in Python
(ref models/cmlp.py:12-101: per-net Conv1d(num_series->hidden, kernel=lag) + 1x1
convs, outputs concatenated). Here the C per-series networks are one weight block
batched over the output-series axis, so the whole forward pass is two einsums that
XLA maps straight onto the MXU, and vmap adds factor/grid axes for free:

    layer 0:  w (C_out, H, C_in, L), b (C_out, H)
    layer i:  w (C_out, H_out, H_in), b (C_out, H_out)       [1x1 convs]
    final layer has H_out == 1.

The Granger-causal readout is the group norm of layer 0 over (H[, L])
(ref cmlp.py:147-203), one reduction for all series at once.

Lag-axis convention matches the reference conv: weight index l multiplies input
timestep t+l within a window, so l == 0 touches the MOST-lagged value.
"""
from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "init_cmlp_params",
    "cmlp_forward",
    "cmlp_gc",
    "init_mlp_params",
    "mlp_forward",
    "build_wavelet_ranking_mask",
    "condense_wavelet_gc",
    "first_layer_weights",
]


def _xavier_uniform(key, shape, fan_in, fan_out):
    limit = math.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(key, shape, minval=-limit, maxval=limit)


def _torch_conv_default(key, shape, fan_in):
    """torch's default Conv init: kaiming-uniform(a=sqrt(5)) == U(±1/sqrt(fan_in))."""
    bound = 1.0 / math.sqrt(fan_in)
    return jax.random.uniform(key, shape, minval=-bound, maxval=bound)


def init_cmlp_params(key, num_series: int, lag: int, hidden: Sequence[int]):
    """Parameters for C per-series MLPs as one batched pytree.

    Layer 0 is xavier-uniform like the reference (ref cmlp.py:20); later layers and
    all biases use torch's conv default init. Returns a list of {"w", "b"} dicts.
    """
    C = num_series
    dims = list(hidden) + [1]
    layers = []
    k0, kb0, key = jax.random.split(key, 3)
    # per-series xavier: each series' (H, C, L) kernel drawn independently like the
    # reference's per-net init; fan_in/fan_out follow torch conv semantics
    w0 = _xavier_uniform(k0, (C, dims[0], C, lag), fan_in=C * lag, fan_out=dims[0] * lag)
    b0 = _torch_conv_default(kb0, (C, dims[0]), fan_in=C * lag)
    layers.append({"w": w0, "b": b0})
    d_in = dims[0]
    for d_out in dims[1:]:
        kw, kb, key = jax.random.split(key, 3)
        layers.append(
            {
                "w": _torch_conv_default(kw, (C, d_out, d_in), fan_in=d_in),
                "b": _torch_conv_default(kb, (C, d_out), fan_in=d_in),
            }
        )
        d_in = d_out
    return layers


def init_mlp_params(key, num_series: int, lag: int, hidden: Sequence[int]):
    """Single MLP (one output stream): the reference's MLP unit (ref cmlp.py:12-35).
    Delegates to init_cmlp_params with a one-entry output-series axis and strips
    it, so the init scheme stays defined in exactly one place. The cMLP is C of
    these batched; the cEmbedder is K of these batched."""
    batched = init_cmlp_params(key, num_series, lag, hidden)
    return [jax.tree.map(lambda leaf: leaf[0], layer) for layer in batched]


def mlp_forward(params, X):
    """Single-MLP forward: (B, T, C) -> (B, T-lag+1, 1). Delegates to the batched
    cmlp_forward with a singleton output-series axis (which lands as the final
    size-1 channel of the result)."""
    batched = [jax.tree.map(lambda leaf: leaf[None], layer) for layer in params]
    return cmlp_forward(batched, X)


def lagged_windows(X, lag):
    """(B, T, C) -> (B, T-lag+1, C, L) sliding windows; window t covers steps
    [t, t+lag), so the window predicts step t+lag."""
    T = X.shape[1]
    return jnp.stack([X[:, l : T - lag + 1 + l, :] for l in range(lag)], axis=-1)


def cmlp_forward(params, X):
    """Forward pass over every output series at once.

    Args:
      params: pytree from init_cmlp_params (optionally with leading batch axes
        added via vmap).
      X: (B, T, C) with T >= lag.
    Returns:
      (B, T-lag+1, C) one-step predictions, matching the reference's concatenated
      per-net outputs (ref cmlp.py:90-101).
    """
    w0 = params[0]["w"]
    lag = w0.shape[-1]
    Xw = lagged_windows(X, lag)  # (B, T', C_in, L)
    h = jnp.einsum("btcl,ohcl->btoh", Xw, w0) + params[0]["b"]
    for layer in params[1:]:
        h = jax.nn.relu(h)
        h = jnp.einsum("btoh,ogh->btog", h, layer["w"]) + layer["b"]
    return h[..., 0]


def first_layer_weights(params):
    return params[0]["w"]


def cmlp_gc(params, threshold=False, ignore_lag=True, wavelet_mask=None,
            rank_wavelets=False, num_chans=None, combine_wavelet_representations=False):
    """Granger-causal readout: norms of the layer-0 block (ref cmlp.py:147-203).

    Returns (C_out, C_in) if ignore_lag else (C_out, C_in, L). Entry (i, j[, l])
    scores series j driving series i. Optional wavelet ranking mask and
    channel-block condensation mirror the reference's wavelet pathway.
    """
    w0 = params[0]["w"]  # (C_out, H, C_in, L)
    if ignore_lag:
        GC = jnp.sqrt(jnp.sum(w0 * w0, axis=(1, 3)))
    else:
        GC = jnp.sqrt(jnp.sum(w0 * w0, axis=1))
    if rank_wavelets:
        assert wavelet_mask is not None
        GC = wavelet_mask * GC if ignore_lag else wavelet_mask[:, :, None] * GC
    if combine_wavelet_representations and num_chans is not None and GC.shape[0] != num_chans:
        GC = condense_wavelet_gc(GC, num_chans)
    if threshold:
        return (GC > 0).astype(jnp.int32)
    return GC


def build_wavelet_ranking_mask(num_series, wavelets_per_chan=4):
    """Wavelet-ranking mask weighting low-frequency bands up (ref cmlp.py:62-82):
    mask[i, j] = 1.3^(2*(r - i%w)) * 1.3^(2*(r - j%w)) with r = w // 4."""
    assert wavelets_per_chan == 4, "reference supports 4 wavelets per channel"
    rank_factor = wavelets_per_chan // 4
    idx = np.arange(num_series) % wavelets_per_chan
    row = 1.3 ** (2.0 * (rank_factor - 1.0 * idx))
    return jnp.asarray(row[:, None] * row[None, :])


def condense_wavelet_gc(GC, num_chans):
    """Sum wavelet-band blocks down to channel granularity.

    Uses the mathematically consistent block stride (num_series // num_chans);
    the reference strides by wavelet_level instead of wavelet_level+1
    (ref cmlp.py:186-199), a latent indexing bug this build does not reproduce.
    """
    ns = GC.shape[0]
    w = ns // num_chans
    if GC.ndim == 2:
        return GC.reshape(num_chans, w, num_chans, w).sum(axis=(1, 3))
    return GC.reshape(num_chans, w, num_chans, w, GC.shape[-1]).sum(axis=(1, 3))
