"""Factor-score embedders: map a recent signal window to (factor weightings,
optional class logits).

JAX rebuild of /root/reference/models/redcliff_factor_score_embedders.py:

* ``VanillaSingleObjective``  — MLPClassifierForSingleObjective (ref :51-100):
  bias-free 2-D conv stack collapsing (series, time) to an embedding, then a
  bias-free linear to K factor scores; optional sigmoid restriction with an
  eccentricity coefficient.
* ``VanillaMultiObjective``   — MLPClassifierForMultipleObjectives (ref :104-179):
  same trunk; the FIRST num_out_classes embedding dims are simultaneously the
  supervised factor scores and the class logits; remaining dims pass through a
  linear to unsupervised scores.
* ``CEmbedder``               — cEmbedder (ref :183-331): one cMLP-style network
  per factor over the window; the first-layer weight norms expose a (K, C[, L])
  "system" GC readout.
* ``DGCNNEmbedder``           — wraps the DGCNN model (ref :335-392); its learned
  adjacency is the embedder GC readout. Takes NODE-MAJOR input (B, C, T).

All are pure functions over param pytrees; each class bundles init/apply/gc with
a shared calling convention:  apply(params, X) -> (weightings (B, K),
class_logits (B, n_classes) | None).
"""
from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from redcliff_tpu.models import cmlp as cmlp_mod
from redcliff_tpu.models import dgcnn as dgcnn_mod

__all__ = [
    "VanillaSingleObjective",
    "VanillaMultiObjective",
    "CEmbedder",
    "DGCNNEmbedder",
    "build_embedder",
]


def _uniform_fanin(key, shape, fan_in):
    bound = 1.0 / math.sqrt(fan_in)
    return jax.random.uniform(key, shape, minval=-bound, maxval=bound)


def _sigmoid_restrict(scores, ecc):
    """Sigmoid restriction with eccentricity coefficient: squashes factor
    weightings to (0, 1) while pushing activations away from the linear regime
    (ref embedders :96-99)."""
    return jax.nn.sigmoid(ecc * scores)


def _trunk_shapes(num_series, num_in_timesteps):
    """The bias-free conv trunk both Vanilla embedders share (ref :68-76,131-139):
    Conv2d(1->h, (num_series, tkw), pad (0, tkw//2)) -> relu ->
    Conv2d(h->h, (1, num_in_timesteps)) -> relu, yielding (B, h)."""
    tkw = num_in_timesteps - ((num_in_timesteps - 1) % 2)
    return tkw


def _init_trunk(key, num_series, num_in_timesteps, hidden):
    tkw = _trunk_shapes(num_series, num_in_timesteps)
    k1, k2 = jax.random.split(key)
    return {
        # conv1: kernel (h, 1, num_series, tkw) in torch layout -> store (h, num_series, tkw)
        "conv1": _uniform_fanin(k1, (hidden, num_series, tkw), fan_in=num_series * tkw),
        # conv2: (h, h, 1, num_in_timesteps) -> (h, h, num_in_timesteps)
        "conv2": _uniform_fanin(k2, (hidden, hidden, num_in_timesteps), fan_in=hidden * num_in_timesteps),
    }


def _apply_trunk(trunk, X, num_series, num_in_timesteps):
    """X: (B, T, C) -> (B, hidden) embedding. Implements the two bias-free convs
    with 'same'-ish padding on the first (pad tkw//2 both sides of time)."""
    B, T, C = X.shape
    assert T == num_in_timesteps and C == num_series
    tkw = _trunk_shapes(num_series, num_in_timesteps)
    x = jnp.transpose(X, (0, 2, 1))  # (B, C, T)
    pad = tkw // 2
    xp = jnp.pad(x, ((0, 0), (0, 0), (pad, pad)))
    # conv over full series height: windows over time of width tkw
    Tout = T + 2 * pad - tkw + 1
    wins = jnp.stack([xp[:, :, t : t + tkw] for t in range(Tout)], axis=1)  # (B, Tout, C, tkw)
    h = jax.nn.relu(jnp.einsum("btcw,hcw->bht", wins, trunk["conv1"]))  # (B, h, Tout)
    # conv2 kernel width = num_in_timesteps exactly (Tout == T when tkw odd)
    h2 = jax.nn.relu(jnp.einsum("bht,ght->bg", h, trunk["conv2"]))  # (B, h)
    return h2


@dataclass(frozen=True)
class VanillaSingleObjective:
    """Unsupervised factor weighting embedder (ref :51-100)."""

    num_series: int
    num_in_timesteps: int
    num_factor_scores: int
    hidden: int
    use_sigmoid_restriction: bool = True
    sigmoid_eccentricity_coeff: float = 10.0

    def init(self, key):
        kt, kw = jax.random.split(key)
        return {
            "trunk": _init_trunk(kt, self.num_series, self.num_in_timesteps, self.hidden),
            "head": _uniform_fanin(kw, (self.hidden, self.num_factor_scores), fan_in=self.hidden),
        }

    def apply(self, params, X, use_final_activation=True):
        emb = _apply_trunk(params["trunk"], X, self.num_series, self.num_in_timesteps)
        scores = emb @ params["head"]
        if self.use_sigmoid_restriction:
            scores = _sigmoid_restrict(scores, self.sigmoid_eccentricity_coeff)
        return scores, None


@dataclass(frozen=True)
class VanillaMultiObjective:
    """Supervised+unsupervised embedder (ref :104-179): supervised scores are the
    first num_out_classes embedding dims; class logits share those dims."""

    num_series: int
    num_in_timesteps: int
    num_factor_scores: int
    num_out_classes: int
    hidden: int
    use_sigmoid_restriction: bool = True
    sigmoid_eccentricity_coeff: float = 10.0

    def init(self, key):
        kt, kw = jax.random.split(key)
        p = {"trunk": _init_trunk(kt, self.num_series, self.num_in_timesteps, self.hidden)}
        n_unsup = self.num_factor_scores - self.num_out_classes
        if n_unsup > 0:
            p["unsup_head"] = _uniform_fanin(
                kw, (self.hidden - self.num_out_classes, n_unsup),
                fan_in=self.hidden - self.num_out_classes,
            )
        return p

    def apply(self, params, X, use_final_activation=True):
        emb = _apply_trunk(params["trunk"], X, self.num_series, self.num_in_timesteps)
        sup = emb[:, : self.num_out_classes]
        if self.num_factor_scores - self.num_out_classes > 0:
            unsup = emb[:, self.num_out_classes :] @ params["unsup_head"]
            scores = jnp.concatenate([sup, unsup], axis=1)
        else:
            scores = sup
        logits = emb[:, : self.num_out_classes]
        if self.use_sigmoid_restriction:
            scores = _sigmoid_restrict(scores, self.sigmoid_eccentricity_coeff)
            if use_final_activation:
                # class logits get a plain sigmoid without eccentricity (ref :176-177)
                logits = jax.nn.sigmoid(logits)
        return scores, logits


@dataclass(frozen=True)
class CEmbedder:
    """One cMLP-style network per factor prediction (ref :183-331). Exposes a
    (K, C[, L]) GC readout from first-layer norms so the embedder itself yields a
    factor-to-channel causal map."""

    num_chans: int
    num_class_preds: int
    num_factor_preds: int
    use_sigmoid_restriction: bool
    sigmoid_eccentricity_coeff: float
    lag: int
    hidden: tuple
    wavelet_level: int | None = None

    @property
    def num_series(self):
        if self.wavelet_level is not None:
            return self.num_chans * (self.wavelet_level + 1)
        return self.num_chans

    def init(self, key):
        keys = jax.random.split(key, self.num_factor_preds)
        # one independent single-output MLP per factor (ref :240: one MLP unit per
        # factor pred), K-batched
        return {
            "nets": jax.vmap(
                lambda k: cmlp_mod.init_mlp_params(k, self.num_series, self.lag, list(self.hidden))
            )(keys)
        }

    def apply(self, params, X, use_final_activation=True):
        """X: (B, T, C) with T == lag: each factor's MLP emits one scalar, and the
        concatenation is the weighting vector (ref :253-257 requires T' == 1)."""
        out = jax.vmap(lambda p: cmlp_mod.mlp_forward(p, X))(params["nets"])  # (K, B, T', 1)
        weightings = jnp.transpose(out[:, :, -1, 0], (1, 0))  # (B, K)
        logits = None
        if self.num_class_preds > 0:
            logits = weightings[:, : self.num_class_preds]
            if use_final_activation and self.use_sigmoid_restriction:
                logits = jax.nn.sigmoid(logits)
        if self.use_sigmoid_restriction:
            weightings = _sigmoid_restrict(weightings, self.sigmoid_eccentricity_coeff)
        return weightings, logits

    def gc(self, params, threshold=False, ignore_lag=True,
           combine_wavelet_representations=False, rank_wavelets=False):
        """(K, C[, L]) first-layer norms per factor network (ref :275-331).
        With wavelet decomposition, rank_wavelets applies the (K, num_series)
        ranking mask (ref :209-228) and combine_wavelet_representations sums
        each channel's wavelet-band block down to (K, num_chans[, L])."""
        w0 = params["nets"][0]["w"]  # (K, H, C, L)
        if ignore_lag:
            G = jnp.sqrt(jnp.sum(w0 * w0, axis=(1, 3)))  # (K, C)
        else:
            G = jnp.sqrt(jnp.sum(w0 * w0, axis=1))  # (K, C, L)
        if rank_wavelets:
            assert self.wavelet_level is not None
            mask = self._wavelet_mask()
            G = mask * G if ignore_lag else mask[:, :, None] * G
        if self.wavelet_level is not None and combine_wavelet_representations:
            w = self.num_series // self.num_chans
            if ignore_lag:
                G = G.reshape(G.shape[0], self.num_chans, w).sum(axis=2)
            else:
                G = G.reshape(G.shape[0], self.num_chans, w, G.shape[-1]).sum(axis=2)
        if threshold:
            return (G > 0).astype(jnp.int32)
        return G

    def _wavelet_mask(self):
        """(K, num_series) ranking mask: column factor 1.3^(2*(r - j%w)) per band,
        rows uniform across factors (ref :209-228 builds the same outer product
        with a single row of the channel-block mask)."""
        import numpy as np

        w = self.num_series // self.num_chans
        assert w == 4, "reference supports 4 wavelets per channel"
        rank_factor = w // 4
        col = 1.3 ** (2.0 * (rank_factor - 1.0 * (np.arange(self.num_series) % w)))
        row = np.full(self.num_factor_preds, 1.3 ** (2.0 * rank_factor))
        return jnp.asarray(row[:, None] * col[None, :])


@dataclass(frozen=True)
class DGCNNEmbedder:
    """DGCNN-backed embedder (ref :335-392). Input is node-major (B, C, T)."""

    num_channels: int
    num_wavelets_per_chan: int
    num_features_per_node: int
    num_graph_conv_layers: int
    num_hidden_nodes: int
    sigmoid_eccentricity_coeff: float
    use_sigmoid_restriction: bool
    num_factors: int
    num_classes: int

    def _cfg(self):
        return dgcnn_mod.DGCNNConfig(
            num_channels=self.num_channels,
            num_wavelets_per_chan=self.num_wavelets_per_chan,
            num_features_per_node=self.num_features_per_node,
            num_graph_conv_layers=self.num_graph_conv_layers,
            num_hidden_nodes=self.num_hidden_nodes,
            num_classes=self.num_factors,
        )

    def init(self, key):
        return dgcnn_mod.init_dgcnn_params(key, self._cfg())

    def apply(self, params, X, use_final_activation=True):
        """X: (B, N, F) node-major (the REDCLIFF forward transposes before calling,
        ref redcliff_s_cmlp.py:287)."""
        if X.shape[2] != self.num_features_per_node:
            X = jnp.transpose(X, (0, 2, 1))
        weightings = dgcnn_mod.dgcnn_forward(params, X)
        logits = None
        if self.num_classes > 0:
            logits = weightings[:, : self.num_classes]
            if use_final_activation and self.use_sigmoid_restriction:
                logits = jax.nn.sigmoid(logits)
        if self.use_sigmoid_restriction:
            weightings = _sigmoid_restrict(weightings, self.sigmoid_eccentricity_coeff)
        return weightings, logits

    def gc(self, params, threshold=False, combine_node_feature_edges=False):
        return dgcnn_mod.dgcnn_gc(params, self._cfg(), threshold=threshold,
                                  combine_node_feature_edges=combine_node_feature_edges)


def build_embedder(embedder_type, *, num_chans, num_series, embed_lag,
                   embed_hidden_sizes, num_factors, num_supervised_factors,
                   use_sigmoid_restriction, sigmoid_eccentricity_coeff=10.0,
                   wavelet_level=None, dgcnn_args=None):
    """Embedder factory mirroring the reference's constructor dispatch
    (ref redcliff_s_cmlp.py:109-137)."""
    if embedder_type == "Vanilla_Embedder":
        if num_supervised_factors > 0:
            return VanillaMultiObjective(
                num_series=num_series, num_in_timesteps=embed_lag,
                num_factor_scores=num_factors, num_out_classes=num_supervised_factors,
                hidden=embed_hidden_sizes[0],
                use_sigmoid_restriction=use_sigmoid_restriction,
                sigmoid_eccentricity_coeff=sigmoid_eccentricity_coeff,
            )
        return VanillaSingleObjective(
            num_series=num_series, num_in_timesteps=embed_lag,
            num_factor_scores=num_factors, hidden=embed_hidden_sizes[0],
            use_sigmoid_restriction=use_sigmoid_restriction,
            sigmoid_eccentricity_coeff=sigmoid_eccentricity_coeff,
        )
    if embedder_type == "cEmbedder":
        return CEmbedder(
            num_chans=num_chans, num_class_preds=num_supervised_factors,
            num_factor_preds=num_factors,
            use_sigmoid_restriction=use_sigmoid_restriction,
            sigmoid_eccentricity_coeff=sigmoid_eccentricity_coeff,
            lag=embed_lag, hidden=tuple(embed_hidden_sizes),
            wavelet_level=wavelet_level,
        )
    if embedder_type == "DGCNN":
        args = dgcnn_args or {}
        return DGCNNEmbedder(
            num_channels=num_chans,
            num_wavelets_per_chan=(wavelet_level + 1) if wavelet_level is not None else 1,
            num_features_per_node=args.get("num_features_per_node", embed_lag),
            num_graph_conv_layers=args.get("num_graph_conv_layers", 2),
            num_hidden_nodes=args.get("num_hidden_nodes", 32),
            sigmoid_eccentricity_coeff=sigmoid_eccentricity_coeff,
            use_sigmoid_restriction=use_sigmoid_restriction,
            num_factors=num_factors, num_classes=num_supervised_factors,
        )
    raise NotImplementedError(f"factor_score_embedder_type == {embedder_type}")
