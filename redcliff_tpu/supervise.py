"""``python -m redcliff_tpu.supervise -- <driver cmd ...>`` — the crash-loop
supervisor entry point (implementation: :mod:`redcliff_tpu.runtime.supervisor`).

Restarts the driver on preemption / watchdog-hang / crash with backoff, stops
on clean exit, numerics abort, or a spent deadline, and writes a
``run_ledger.jsonl`` audit trail of every attempt.
"""
from redcliff_tpu.runtime.supervisor import main

if __name__ == "__main__":
    raise SystemExit(main())
