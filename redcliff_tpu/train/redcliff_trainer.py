"""REDCLIFF-S training choreography.

Rebuild of the reference's fit loop (ref models/redcliff_s_cmlp.py:1159-1628) as a
functional trainer:

* epoch-scheduled phases (pretrain embedder / pretrain+acclimate factors /
  combined / post-train) select among jit'd step functions; two Adam optimizers
  with torch-style coupled weight decay cover the embedder and factor groups
  (ref general_utils/model_utils.py:749-762);
* the Freeze-by-epoch/batch accept-revert choreography (ref :866-885,
  1116-1156, 1469-1515) becomes a two-pytree candidate-vs-accepted pattern with
  per-factor jnp.where swaps — no deepcopies;
* Hungarian factor alignment at the pretrain->train transition
  (initialize_factors_with_prior, ref :147-202);
* early stopping on the weighted (factor, forecast, cosSim) criteria
  (ref :1466-1538), with histories and checkpoints in the reference's on-disk
  layout, plus exact optimizer-state resume (the reference warns it has none,
  ref :245).
"""
from __future__ import annotations

import copy
import os
import sys
import time
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
import optax

from redcliff_tpu import obs
from redcliff_tpu.data import pipeline
from redcliff_tpu.models.redcliff import RedcliffSCMLP, phase_schedule
from redcliff_tpu.obs import MetricLogger
from redcliff_tpu.obs import memory as _obsmem
from redcliff_tpu.obs import profiling as _profiling
from redcliff_tpu.obs import quality as _obsquality
from redcliff_tpu.runtime import checkpoint as durable_ckpt
from redcliff_tpu.runtime import compileobs, faultinject, numerics
from redcliff_tpu.runtime import watchdog as rt_watchdog
from redcliff_tpu.runtime.numerics import NumericsPolicy
from redcliff_tpu.ops import autotune as _autotune
from redcliff_tpu.train.freeze import apply_freeze
from redcliff_tpu.train.tracking import GCProgressTracker
from redcliff_tpu.utils.misc import factor_alignment_order
from redcliff_tpu.utils.precision import (check_precision_mode,
                                          matmul_precision_ctx,
                                          resolve_matmul_precision)

__all__ = ["RedcliffTrainConfig", "RedcliffTrainer", "RedcliffFitResult"]


@dataclass
class RedcliffTrainConfig:
    embed_lr: float = 1e-3
    embed_eps: float = 1e-8
    embed_weight_decay: float = 0.0
    gen_lr: float = 1e-3
    gen_eps: float = 1e-8
    gen_weight_decay: float = 0.0
    max_iter: int = 100
    lookback: int = 5
    check_every: int = 50
    batch_size: int = 32
    seed: int = 0
    verbose: int = 0
    stopping_criteria_forecast_coeff: float = 1.0
    stopping_criteria_factor_coeff: float = 1.0
    stopping_criteria_cosSim_coeff: float = 1.0
    max_factor_prior_batches: int = 10
    unsupervised_start_index: int = 0
    max_samples_for_gc_tracking: int = 40  # ref MAX_NUM_SAMPS_FOR_GC_PROGRESS_TRACKING
    profile_dir: str | None = None  # opt-in jax.profiler trace output dir
    # bounded profiler capture window (obs/profiling.py): "epoch:N" /
    # "epoch:N-M" brackets jax.profiler around exactly those epochs, with
    # the artifact under the run dir (or profile_dir) and a `profile` event
    # announcing it. None = follow REDCLIFF_PROFILE; profile_dir alone now
    # means ONE bounded steady-state window, never a whole-fit trace
    profile_window: str | None = None
    # matmul precision for every jit'd step (train/eval/label-pred/freeze,
    # forward + backward): None = backend default; "bfloat16" runs MXU
    # passes in bf16 (params stay f32) — the standard TPU speed/accuracy
    # trade for models whose loss tolerates it. Expert override; production
    # fits use precision_mode below
    matmul_precision: str | None = None
    # production precision mode (utils/precision.py): "f32" (default —
    # bit-identical decision streams to a build without the knob) or
    # "mixed" (bf16 MXU contractions, f32 master params/reductions) with
    # the numerics sentinel watching the precision cliff: a skip/rollback
    # storm auto-demotes the fit to f32 (schema-registered `precision`
    # event; the demotion persists in the checkpoint so a resume can never
    # silently re-promote). Part of the resume fingerprint
    precision_mode: str = "f32"
    # GISTA-style proximal update on the stacked factor first-layer block
    # after each factor optimizer step ("GL" | "GSGL" | "H"; None = off).
    # GL routes through the fused Pallas TPU kernel in production
    # (ops/pallas_prox.py; jnp reference off-TPU). Update-math knobs: both
    # join the resume fingerprint
    prox_penalty: str | None = None
    prox_lam: float = 0.0
    # grid engine only: drive lax.scan over groups of this many pre-staged
    # device-resident batches per dispatch (amortizes per-step dispatch
    # overhead at large G); <= 1 keeps the one-dispatch-per-batch path.
    # Ignored in FreezeByBatch modes (accept/revert runs between batches)
    scan_batches: int = 0
    # batch-stream execution mode (data/pipeline.py): "auto" resolves to the
    # EPOCH engine — one jit'd dispatch scans the whole epoch's batch
    # indices against the HBM-resident dataset — when eligible, degrading to
    # the k-batch scan (scan_batches) and then per-batch dispatch. All modes
    # are bit-identical; "per_batch"/"kscan"/"epoch" force a mode (still
    # degrading when ineligible, e.g. multi-process or freeze-by-batch)
    stream_mode: str = "auto"
    # double-buffered host prefetch depth for streams that stay host
    # resident (shard streams, multi-process runs): batch assembly +
    # device_put of batch t+1 overlap compute of batch t. <= 0 disables
    prefetch_batches: int = 2
    # hand periodic checkpoint saves to a background writer thread (the
    # device->host gather + CRC+.prev write stop stalling the train loop;
    # completion barrier at the next save / fit end). Single-process only —
    # multi-host saves run collective gathers and stay synchronous
    async_checkpointing: bool = True
    # elastic grid scheduling (grid engine only; parallel/compaction.py):
    # at check-window boundaries, when the live-lane count drops below the
    # next power-of-two bucket, gather the surviving lanes into a compacted
    # grid and stop paying FLOPs for retired lanes. Per-lane update streams
    # are bit-identical to the uncompacted run; results/failures report
    # under original point ids. Single-process only (multi-host grids skip
    # compaction rather than re-spanning hosts mid-fit)
    compaction: bool = True
    # pad the grid's execution width up to the power-of-two bucket ladder
    # with masked filler lanes (never surfaced in results), so heterogeneous
    # sweeps and post-compaction grids reuse a small set of compiled
    # programs instead of one program per exact (shape, G). Also lifts the
    # grid-size-divides-mesh requirement (filler lanes absorb the remainder)
    g_bucket: bool = True
    # persistent XLA compilation cache directory (runtime/compileobs.py):
    # compiled grid programs are cached under a versioned subdir
    # (jax/jaxlib/backend/schema) so restarts, supervisor re-attempts, and
    # resumed preemptions warm-start instead of recompiling. None = follow
    # the REDCLIFF_COMPILE_CACHE env var (unset -> disabled)
    compile_cache_dir: str | None = None
    # numerical fault policy (in-graph non-finite skip guard; divergence
    # rollback + lr backoff in the per-point trainer, per-lane quarantine
    # causes in the grid engine); None disables the sentinel
    numerics: NumericsPolicy | None = field(default_factory=NumericsPolicy)

    def __post_init__(self):
        check_precision_mode(self.precision_mode)
        if self.prox_penalty not in (None, "GL", "GSGL", "H"):
            raise ValueError(
                f"prox_penalty must be one of None/'GL'/'GSGL'/'H', got "
                f"{self.prox_penalty!r}")


@dataclass
class RedcliffFitResult:
    params: dict
    best_it: int
    best_loss: float
    histories: dict
    tracker: GCProgressTracker
    final_val_loss: float
    # non-None when the numerics sentinel aborted the fit (recorded cause,
    # e.g. "all_nonfinite_validation")
    aborted: str | None = None


def _torch_style_adam(lr, eps, weight_decay):
    """torch.optim.Adam semantics: weight decay added to the gradient BEFORE
    the moment updates (coupled, not AdamW). Wrapped in
    ``optax.inject_hyperparams`` so the learning rate lives in the optimizer
    STATE and the DivergenceMonitor can back it off without recompiling."""

    def make(learning_rate):
        chain = []
        if weight_decay > 0:
            chain.append(optax.add_decayed_weights(weight_decay))
        chain.append(optax.adam(learning_rate, b1=0.9, b2=0.999, eps=eps))
        return optax.chain(*chain)

    return optax.inject_hyperparams(make)(learning_rate=lr)


class RedcliffTrainer:
    def __init__(self, model: RedcliffSCMLP, config: RedcliffTrainConfig):
        self.model = model
        self.config = config
        # persistent compile cache + compile counters (no-op when neither
        # the config knob nor REDCLIFF_COMPILE_CACHE is set)
        compileobs.enable_cache(config.compile_cache_dir)
        compileobs.install()
        self.optA = _torch_style_adam(config.embed_lr, config.embed_eps,
                                      config.embed_weight_decay)
        self.optB = _torch_style_adam(config.gen_lr, config.gen_eps,
                                      config.gen_weight_decay)
        self._guard = config.numerics is not None and config.numerics.enabled
        # effective matmul precision (utils/precision.py): the legacy
        # matmul_precision knob wins, else precision_mode resolves it.
        # "mixed" fits are DEMOTABLE: a sentinel skip/rollback storm rebuilds
        # every step at f32 mid-fit and persists the demotion
        self._precision = resolve_matmul_precision(config.precision_mode,
                                                   config.matmul_precision)
        self._demotable = (config.precision_mode == "mixed"
                           and self._guard and self._precision is not None)
        self._demoted = False
        self._steps = {}
        self._build_steps()
        self._maybe_tune_kernels()

    def _maybe_tune_kernels(self):
        """Autotune the hot-path Pallas tilings for this model's shapes on
        real TPU hardware (the shared shape-math lives in
        ops/autotune.py:tune_for_model). No-op off-TPU / when searching is
        disabled."""
        _autotune.tune_for_model(self.model.config, self.config.batch_size,
                                 prox_penalty=self.config.prox_penalty)

    def _demote_to_f32(self):
        """Rebuild every jit'd step at f32 (the sentinel-triggered precision
        demotion). Idempotent; the caller logs the `precision` event."""
        self._precision = None
        self._demoted = True
        self._build_steps()

    # ------------------------------------------------------------------ phases
    def phase_for_epoch(self, epoch):
        """Epoch -> phase names (shared schedule, ref batch_update :696-714)."""
        return phase_schedule(self.model.config, epoch)

    def _build_steps(self):
        model = self.model

        precision = self._precision

        guard = self._guard
        prox_pen = self.config.prox_penalty
        prox_lam = self.config.prox_lam
        prox_lr = self.config.gen_lr

        def make_step(phase):
            def step(params, optA_state, optB_state, X, Y, nstate):
                with matmul_precision_ctx(precision):
                    (combo, parts), grads = jax.value_and_grad(
                        lambda p: model.loss_for_phase(p, X, Y, phase),
                        has_aux=True,
                    )(params)

                def apply(tree):
                    params, optA_state, optB_state = tree
                    if phase == "embedder_pretrain":
                        upd, optA_state = self.optA.update(
                            grads["embedder"], optA_state, params["embedder"])
                        params = dict(params,
                                      embedder=optax.apply_updates(params["embedder"], upd))
                    elif phase in ("factor_pretrain", "post_train"):
                        upd, optB_state = self.optB.update(
                            grads["factors"], optB_state, params["factors"])
                        params = dict(params,
                                      factors=optax.apply_updates(params["factors"], upd))
                    else:  # combined
                        updA, optA_state = self.optA.update(
                            grads["embedder"], optA_state, params["embedder"])
                        updB, optB_state = self.optB.update(
                            grads["factors"], optB_state, params["factors"])
                        params = dict(
                            params,
                            embedder=optax.apply_updates(params["embedder"], updA),
                            factors=optax.apply_updates(params["factors"], updB),
                        )
                    if (prox_pen is not None
                            and phase != "embedder_pretrain"):
                        # GISTA prox after the factor gradient step; GL
                        # rides the fused Pallas kernel on real TPUs
                        params = model.apply_prox(params, prox_lam,
                                                  prox_lr, prox_pen)
                    return params, optA_state, optB_state

                tree = (params, optA_state, optB_state)
                if guard:
                    # numerics sentinel: skip the whole two-optimizer update
                    # in-graph when the loss or any gradient is non-finite
                    tree, nstate, _ = numerics.guarded_update(
                        tree, grads, combo, apply, nstate)
                else:
                    tree = apply(tree)
                params, optA_state, optB_state = tree
                return params, optA_state, optB_state, combo, parts, nstate

            return jax.jit(step)

        for phase in ("embedder_pretrain", "factor_pretrain", "combined", "post_train"):
            self._steps[phase] = make_step(phase)

        def eval_loss(params, X, Y):
            with matmul_precision_ctx(precision):
                return model.loss_for_phase(params, X, Y, "combined")

        self._eval_loss = jax.jit(eval_loss)

        def label_preds_fn(params, X):
            W = model.config.max_lag
            with matmul_precision_ctx(precision):
                _, _, _, label_preds = model.forward(params, X[:, :W, :])
            return label_preds[0]

        self._label_preds = jax.jit(label_preds_fn)

        # freeze choreography shared with the grid engine (train/freeze.py)
        def freeze_fn(c, a):
            with matmul_precision_ctx(precision):
                return apply_freeze(model, model.config.training_mode, c, a)

        self._freeze_step = (jax.jit(freeze_fn)
                             if "Freeze" in model.config.training_mode
                             else None)

    # --------------------------------------------------------------- alignment
    def align_factors_with_labels(self, params, train_ds):
        """Hungarian-align factor indices to supervised labels using the first
        predicted factor weighting on up to max_factor_prior_batches batches
        (ref initialize_factors_with_prior :147-202)."""
        cfg = self.model.config
        tc = self.config
        preds, labels = [], []
        for b, (X, Y) in enumerate(train_ds.batches(tc.batch_size)):
            if b >= tc.max_factor_prior_batches:
                break
            _, _, fw, _ = self.model.forward(
                jax.tree.map(jnp.asarray, params), jnp.asarray(X[:, : cfg.max_lag, :]))
            preds.append(np.asarray(fw[0]))
            if Y.ndim == 3:
                col = cfg.max_lag if Y.shape[2] > cfg.max_lag else 0
                labels.append(np.asarray(Y[:, :, col]))
            else:
                labels.append(np.asarray(Y))
        preds = np.vstack(preds)
        labels = np.vstack(labels)
        order = factor_alignment_order(
            preds, labels, cfg.num_factors,
            unsupervised_start_index=tc.unsupervised_start_index)
        return self.model.permute_factors(params, order)

    # --------------------------------------------------------------------- fit
    def fit(self, params, train_ds, val_ds, true_GC=None, save_dir=None,
            resume=True, factor_mesh=None) -> RedcliffFitResult:
        """``factor_mesh`` shards the K factor networks across the mesh like
        experts (parallel.mesh.shard_factor_axis) — XLA partitions the
        per-factor compute and inserts the psum at the mixture sum. K must
        divide by the mesh size."""
        # env-armed liveness watchdog (REDCLIFF_WATCHDOG): same heartbeat/
        # escalation contract as the grid engine — no preemption guard here,
        # so a confirmed hang goes straight to the hard-exit rung
        wd = rt_watchdog.maybe_start()
        # bounded profiler capture window (obs/profiling.py): profile_window
        # / REDCLIFF_PROFILE / the profile_dir alias — scoped around the fit
        # so an early exit inside the window still closes the capture
        pw = _profiling.window_for(self.config, run_dir=save_dir,
                                   max_iter=self.config.max_iter)
        with pw, wd as live_wd:
            return self._fit(params, train_ds, val_ds, true_GC=true_GC,
                             save_dir=save_dir, resume=resume,
                             factor_mesh=factor_mesh, wd=live_wd, pw=pw)

    def _fit(self, params, train_ds, val_ds, true_GC=None, save_dir=None,
             resume=True, factor_mesh=None, wd=None,
             pw=_profiling.NOOP) -> RedcliffFitResult:
        model, cfg = self.model, self.model.config
        tc = self.config
        self._true_GC = true_GC
        rng = np.random.default_rng(tc.seed)
        if factor_mesh is not None:
            from redcliff_tpu.parallel.mesh import shard_factor_axis

            assert cfg.num_factors % factor_mesh.devices.size == 0, (
                f"num_factors {cfg.num_factors} must divide by the factor "
                f"mesh size {factor_mesh.devices.size}")
            params = shard_factor_axis(params, factor_mesh)
        # optax init zeros_like the (possibly sharded) params, so optimizer
        # state inherits the factor sharding automatically
        optA_state = self.optA.init(params["embedder"])
        optB_state = self.optB.init(params["factors"])
        mode = cfg.training_mode
        freeze_by_batch = "FreezeByBatch" in mode
        freeze = "Freeze" in mode

        # always track — the pairwise-cosine histories feed the stopping
        # criterion and need no ground truth; the reference's fit tracks
        # unconditionally (ref :1349-1403), and the grid engine always
        # includes the cosine term, so criteria now agree across engines
        # on unlabeled runs too (truth-dependent histories stay empty)
        tracker = GCProgressTracker(
            num_supervised_factors=cfg.num_supervised_factors,
            num_chans=cfg.num_chans, num_factors=cfg.num_factors,
        )

        histories = {
            "avg_forecasting_loss": [], "avg_factor_loss": [],
            "avg_factor_cos_sim_penalty": [], "avg_fw_l1_penalty": [],
            "avg_adj_penalty": [], "avg_fw_smoothing_penalty": [],
            "avg_combo_loss": [],
            "factor_score_train_acc_history": [], "factor_score_train_tpr_history": [],
            "factor_score_train_tnr_history": [], "factor_score_train_fpr_history": [],
            "factor_score_train_fnr_history": [],
            "factor_score_val_acc_history": [], "factor_score_val_tpr_history": [],
            "factor_score_val_tnr_history": [], "factor_score_val_fpr_history": [],
            "factor_score_val_fnr_history": [],
        }
        best_it = None
        best_loss = np.inf
        best_params = params
        accepted = params  # Freeze-mode accepted tree ("best_model" analog)
        iter_start = 0
        aligned = False

        ckpt_path = os.path.join(save_dir, "trainer_checkpoint.pkl") if save_dir else None
        ck = None
        if resume and ckpt_path:
            # durable load: CRC-verified, corrupt generations quarantined to
            # *.bad with .prev fallback; legacy raw pickles still read
            ck, _src = durable_ckpt.load_checkpoint(ckpt_path)
        if ck is not None:
            params = jax.tree.map(jnp.asarray, ck["params"])
            best_params = jax.tree.map(jnp.asarray, ck["best_params"])
            accepted = jax.tree.map(jnp.asarray, ck["accepted"])
            optA_state = jax.tree.map(
                lambda x: jnp.asarray(x) if isinstance(x, np.ndarray) else x, ck["optA_state"])
            optB_state = jax.tree.map(
                lambda x: jnp.asarray(x) if isinstance(x, np.ndarray) else x, ck["optB_state"])
            # checkpoints from before the inject_hyperparams migration hold
            # bare chain states; wrap them so resume keeps working
            optA_state = numerics.adopt_legacy_opt_state(
                self.optA, params["embedder"], optA_state)
            optB_state = numerics.adopt_legacy_opt_state(
                self.optB, params["factors"], optB_state)
            histories = ck["histories"]
            best_it, best_loss = ck["best_it"], ck["best_loss"]
            iter_start = ck["epoch"] + 1
            aligned = ck.get("aligned", False)
            if tracker is not None and ck.get("tracker_state") is not None:
                tracker.__dict__.update(ck["tracker_state"])
            if ck.get("precision_demoted") and self._demotable \
                    and not self._demoted:
                # the checkpointed fit already demoted to f32 mid-run; a
                # resume must never silently re-promote to bf16 — rebuild
                # the steps at f32 before the first dispatch
                self._demote_to_f32()
            if factor_mesh is not None:
                # checkpoints hold plain numpy: re-apply the factor sharding
                # to every resumed tree or the run would silently continue
                # unsharded (and per-device memory sized for 1/N factors
                # would overflow on real chips)
                from jax.sharding import NamedSharding, PartitionSpec

                params = shard_factor_axis(params, factor_mesh)
                best_params = shard_factor_axis(best_params, factor_mesh)
                accepted = shard_factor_axis(accepted, factor_mesh)
                fac_sh = NamedSharding(factor_mesh,
                                       PartitionSpec(
                                           factor_mesh.axis_names[0]))
                rep = NamedSharding(factor_mesh, PartitionSpec())
                put = lambda sh: (lambda x: jax.device_put(x, sh)
                                  if hasattr(x, "ndim") and x.ndim > 0
                                  else x)
                optB_state = jax.tree.map(put(fac_sh), optB_state)
                optA_state = jax.tree.map(put(rep), optA_state)

        # ---- model-quality observatory (obs/quality.py) ------------------
        # the single-lane analog of the grid engine's per-lane summaries:
        # a jit'd graph readout on the check_every cadence (pure read of
        # params — update streams untouched), folded into convergence
        # diagnostics + schema-registered `quality` events; live AUROC/AUPR
        # when ``true_GC`` is in hand. Nothing is built when
        # REDCLIFF_QUALITY=0 (zero-cost contract)
        qmon = qual_fn = qual_Xw = None
        if _obsquality.enabled():
            qfirst = next(iter(val_ds.batches(tc.batch_size)), None)
            if qfirst is not None:
                qual_Xw = jnp.asarray(np.asarray(qfirst[0])[
                    : tc.max_samples_for_gc_tracking, : cfg.max_lag, :])
                # jit once per trainer (keyed by the top-k knob), like the
                # __init__-built step programs: a second fit must not
                # recompile the summary (zero-recompile discipline)
                qk = _obsquality.topk_k()
                if getattr(self, "_qual_fn", None) is None \
                        or self._qual_fn_k != qk:
                    self._qual_fn = jax.jit(
                        _obsquality.make_summary_fn(model, k=qk))
                    self._qual_fn_k = qk
                qual_fn = self._qual_fn
                qmon = _obsquality.QualityMonitor(
                    true_gc=true_GC, mode=_obsquality.readout_mode(cfg))

        last_it = iter_start - 1
        policy = tc.numerics if self._guard else None
        monitor = (numerics.DivergenceMonitor(policy)
                   if policy is not None else None)
        nstate = numerics.init_numerics_state()
        prev_skipped = 0
        step_counter = 0
        aborted = None
        # background checkpoint writer: periodic saves hand their
        # device->host materialization + durable write to a thread
        # (completion barrier at the next save / fit end)
        writer = (durable_ckpt.AsyncCheckpointWriter()
                  if save_dir and tc.async_checkpointing
                  and jax.process_count() == 1 else None)
        logger = MetricLogger(save_dir)
        if wd is not None:
            wd.bind(logger=logger)  # hang incidents land in metrics.jsonl
        # try/finally: an exception mid-fit must still close the jsonl
        # handle (otherwise buffered context is lost and the fd leaks)
        try:
            logger.log("fit_start", model="RedcliffSCMLP", training_mode=mode,
                       shape=obs.schema.shape_desc(cfg),
                       train_config=tc, resume_epoch=iter_start)
            # kernel-tiling searches/lookups performed at construction
            # (ops/autotune.py) land as schema-registered events
            for atrec in _autotune.drain_records():
                logger.log("autotune", **atrec)
            if self._demoted and iter_start > 0:
                logger.log("precision", kind="resume_demoted",
                           epoch=iter_start - 1, mode_from="mixed",
                           mode_to="f32")
            # analytical HBM prediction (obs/memory.py): live params + best
            # + accepted copies + Adam moments + the device-batch dataset
            # cache — shape metadata only, no device work. extra_copies=2
            # counts best_params and the Freeze-mode accepted tree
            try:
                mp = _obsmem.trainer_footprint(
                    params, (optA_state, optB_state), extra_copies=2,
                    train_ds=train_ds, val_ds=val_ds)
                hr = _obsmem.check_headroom(mp["total_bytes"])
                logger.log("memory", kind="predicted",
                           epoch=iter_start - 1,
                           predicted_bytes=mp["total_bytes"],
                           params_bytes=mp["params_bytes"],
                           opt_bytes=mp["opt_bytes"],
                           dataset_bytes=mp["dataset_bytes"],
                           fits=hr["fits"], bytes_limit=hr["bytes_limit"],
                           budget_bytes=hr["budget_bytes"],
                           headroom_bytes=hr["headroom_bytes"],
                           backend=hr["backend"])
            except Exception:  # noqa: BLE001 — telemetry must not fail fits
                pass
            for it in range(iter_start, tc.max_iter):
                rt_watchdog.stamp("epoch_engine")
                pw.on_epoch_start(it)
                t_epoch0 = time.perf_counter()
                last_it = it
                # Hungarian alignment at the pretrain->train transition (ref :1304-1309)
                if (not aligned and "pretrain_factor" in mode
                        and it == cfg.num_pretrain_epochs and cfg.num_supervised_factors > 0):
                    params = self.align_factors_with_labels(params, train_ds)
                    aligned = True

                phases = self.phase_for_epoch(it)
                conf_mat = (np.zeros((cfg.num_supervised_factors,) * 2)
                            if cfg.num_supervised_factors > 0 else None)

                # device-resident batches when the dataset supports them;
                # host-resident streams (shard streams, duck-typed batches()
                # sources) ride the double-buffered prefetcher so batch
                # assembly + device_put of batch t+1 overlap compute of t
                dev_kw = ({"device": True}
                          if getattr(train_ds, "supports_device_batches", False)
                          else {})
                batch_src = train_ds.batches(tc.batch_size, rng=rng, **dev_kw)
                if not dev_kw and tc.prefetch_batches > 0:
                    put = (jax.device_put if jax.process_count() == 1
                           else None)
                    batch_src = pipeline.prefetch_batches(
                        batch_src, depth=tc.prefetch_batches, put=put)
                for X, Y in batch_src:
                    rt_watchdog.stamp("batch_loop")
                    X = faultinject.poison_batch(X, step_counter)
                    skip = faultinject.skip_update(step_counter)
                    step_counter += 1
                    if skip:
                        continue
                    for phase in phases:
                        params, optA_state, optB_state, _, _, nstate = \
                            self._steps[phase](params, optA_state, optB_state,
                                               X, Y, nstate)
                        if conf_mat is not None and phase in ("embedder_pretrain", "combined"):
                            conf_mat += self._confusion(params, X, Y)
                    if freeze_by_batch:
                        params, accepted = self._apply_freeze(params, accepted)

                if conf_mat is not None and conf_mat.sum() > 0:
                    self._append_conf_stats(conf_mat, histories, "train")

                # per-epoch GC tracking on the first val batch (ref :1349-1403)
                if tracker is not None:
                    self._epoch_gc_tracking(params, val_ds, tracker)

                val = self.validate(params, val_ds, histories)
                histories["avg_forecasting_loss"].append(val["forecasting_loss"])
                histories["avg_factor_loss"].append(val["factor_loss"])
                histories["avg_factor_cos_sim_penalty"].append(val["factor_cos_sim_penalty"])
                histories["avg_fw_l1_penalty"].append(val["fw_l1_penalty"])
                histories["avg_adj_penalty"].append(val["adj_l1_penalty"])
                histories["avg_fw_smoothing_penalty"].append(val.get("fw_smoothing_penalty", 0.0))
                histories["avg_combo_loss"].append(val["combo_loss"])

                # stopping criteria (ref :1466-1538) — computed BEFORE any
                # best/freeze bookkeeping so the numerics sentinel can veto a
                # diverged epoch outright
                criteria = None
                stop_early = False
                past_pretrain = (it >= cfg.num_pretrain_epochs
                                 + cfg.num_acclimation_epochs)
                if past_pretrain:
                    cos_mean = tracker.latest_mean_supervised_cosine() if tracker else 0.0
                    if cfg.num_supervised_factors > 1:
                        criteria = (tc.stopping_criteria_factor_coeff * val["factor_loss"]
                                    + tc.stopping_criteria_forecast_coeff * val["forecasting_loss"]
                                    + tc.stopping_criteria_cosSim_coeff * cos_mean)
                    elif cfg.num_supervised_factors == 1:
                        criteria = (tc.stopping_criteria_factor_coeff * val["factor_loss"]
                                    + tc.stopping_criteria_forecast_coeff * val["forecasting_loss"])
                    else:
                        criteria = tc.stopping_criteria_forecast_coeff * val["forecasting_loss"]

                # numerics sentinel: anomaly accounting + rollback/abort
                # verdict for this epoch (all phases route through the same
                # guarded steps, so the counters cover every phase)
                rolled_back = False
                if monitor is not None:
                    nhost = numerics.numerics_summary(nstate)
                    if nhost["skipped"] > prev_skipped:
                        logger.log("anomaly", epoch=it, cause="nonfinite_grad",
                                   epoch_skipped_steps=nhost["skipped"]
                                   - prev_skipped, **nhost)
                    prev_skipped = nhost["skipped"]
                    action = monitor.check(
                        it, nhost,
                        None if criteria is None else float(criteria))
                    if action.kind == "rollback":
                        # rollback() returns the snapshot with both injected
                        # learning rates already backed off (compounding
                        # across repeated rollbacks of the same snapshot)
                        snap = monitor.rollback()
                        params = snap["params"]
                        accepted = snap["accepted"]
                        optA_state = snap["optA_state"]
                        optB_state = snap["optB_state"]
                        nstate = numerics.reset_consecutive(nstate)
                        logger.log(
                            "numerics", kind="rollback", epoch=it,
                            cause=action.cause,
                            restored_epoch=monitor.snapshot_epoch,
                            lr_scale=monitor.lr_scale,
                            learning_rates=numerics.current_learning_rates(
                                (optA_state, optB_state)),
                            rollbacks=monitor.rollbacks)
                        if self._demotable and not self._demoted:
                            # the precision cliff: a mixed-mode fit whose
                            # sentinel just rolled back auto-demotes to f32
                            # — the restored snapshot continues under f32
                            # steps, and the demotion persists in every
                            # later checkpoint
                            self._demote_to_f32()
                            logger.log("precision", kind="demote", epoch=it,
                                       cause=action.cause,
                                       mode_from="mixed", mode_to="f32",
                                       rollbacks=monitor.rollbacks, **nhost)
                        rolled_back = True
                    elif action.kind == "abort":
                        aborted = action.cause
                        # numerics-abort escalation dumps the crash flight
                        # recorder next to metrics.jsonl (last spans per
                        # component — post-mortems stop depending on what
                        # happened to be flushed)
                        fr = obs.flight.dump_for_logger(
                            logger, reason="numerics_abort",
                            extra={"epoch": it, "cause": action.cause})
                        logger.log("numerics", kind="abort", epoch=it,
                                   cause=action.cause, flight_record=fr,
                                   **nhost)
                    elif criteria is None or np.isfinite(criteria):
                        monitor.note_good(
                            it, {"params": params, "accepted": accepted,
                                 "optA_state": optA_state,
                                 "optB_state": optB_state})

                if not rolled_back and aborted is None:
                    if past_pretrain:
                        if freeze:
                            params, accepted = self._apply_freeze(params, accepted)
                            if criteria < best_loss:
                                best_loss = criteria
                                best_it = it
                            elif best_it is not None and (it - best_it) == tc.lookback * tc.check_every:
                                # deliberate deviation: the reference's Freeze-mode
                                # stop rule (ref :1510-1515) is inert because the
                                # factor-status update above it is debug-disabled
                                # (ref :1490 "FOR DEBUGGING"), so Freeze runs always
                                # hit max_iter; we apply the standard lookback rule
                                # in all modes so Freeze runs terminate too
                                if tc.verbose:
                                    print("Stopping early")
                                stop_early = True
                            best_params = accepted
                        else:
                            if criteria < best_loss:
                                best_loss = criteria
                                best_it = it
                                best_params = params
                            elif best_it is not None and (it - best_it) == tc.lookback * tc.check_every:
                                if tc.verbose:
                                    print("Stopping early")
                                stop_early = True
                    else:
                        best_it = it
                        best_params = params

                # log before honoring the early stop so the stopping epoch's
                # record (criteria included) lands in metrics.jsonl
                logger.log("epoch", epoch=it, phases=list(phases), criteria=criteria,
                           epoch_ms=round(
                               (time.perf_counter() - t_epoch0) * 1e3, 3),
                           **val, **(tracker.latest_as_dict() if tracker else {}))
                # live graph-quality summary on the check cadence
                # (obs/quality.py): one jit'd readout of params, host-folded
                # into convergence diagnostics; single lane id 0
                if qmon is not None and it % tc.check_every == 0:
                    qhost = {qk: np.asarray(qv)[None]
                             for qk, qv in qual_fn(params, qual_Xw).items()}
                    qrec = qmon.update(it, qhost, np.zeros(1, np.int32))
                    logger.log("quality", **qrec)
                pw.on_epoch_end(it, logger=logger)
                if stop_early or aborted is not None:
                    break
                if rolled_back:
                    continue  # the restored epoch takes no best/ckpt updates

                if it % tc.check_every == 0 and save_dir:
                    self._save_checkpoint(save_dir, it, best_params, accepted, params,
                                          optA_state, optB_state, histories, best_it,
                                          best_loss, tracker, aligned,
                                          writer=writer)
                if tc.verbose and it % max(1, tc.check_every) == 0:
                    print(f"epoch {it} phases={phases}: val_combo={val['combo_loss']:.5f}")

            final_val = self.validate(best_params, val_ds, None)
            # measured watermark where the backend reports it (None on CPU)
            if _obsmem.polling_enabled():
                wm = _obsmem.poll_watermark()
                if wm is not None:
                    logger.log("memory", kind="measured", epoch=last_it,
                               bytes_in_use=wm["bytes_in_use"],
                               peak_bytes=wm["peak_bytes"],
                               bytes_limit=wm["bytes_limit"],
                               n_devices=wm["n_devices"],
                               device_kind=wm["device_kind"])
            logger.log("fit_end", best_it=best_it if best_it is not None else 0,
                       best_loss=float(best_loss),
                       final_val_loss=final_val["combo_loss"],
                       aborted=aborted,
                       quality=(qmon.snapshot()
                                if qmon is not None and qmon.windows
                                else None))
        finally:
            rt_watchdog.retire("epoch_engine")
            rt_watchdog.retire("batch_loop")
            # close an open capture window while the logger can still
            # record the truncated `profile` event (pw's own __exit__ in
            # fit() unwinds after this logger is closed)
            pw.finish(logger=logger)
            logger.close()
            if writer is not None:
                # join the in-flight write on EVERY exit path: a background
                # write failure re-raises on clean exits and is warned (not
                # masked) while another exception is already propagating
                writer.__exit__(*sys.exc_info())
        if save_dir:
            # periodic background writes were already joined — and their
            # failures raised — by the finally block's writer.__exit__
            self._save_checkpoint(save_dir, last_it, best_params, accepted, params,
                                  optA_state, optB_state, histories, best_it,
                                  best_loss, tracker, aligned, writer=writer)
            if writer is not None:
                writer.wait()  # the final state must be durable on return
        return RedcliffFitResult(
            params=best_params, best_it=best_it if best_it is not None else 0,
            best_loss=float(best_loss), histories=histories, tracker=tracker,
            final_val_loss=final_val["combo_loss"], aborted=aborted,
        )

    # ----------------------------------------------------------------- helpers
    def _apply_freeze(self, candidate, accepted):
        """Accept/revert per-factor updates (ref :866-885, 1469-1515)."""
        return self._freeze_step(candidate, accepted)

    def _confusion(self, params, X, Y):
        cfg = self.model.config
        S = cfg.num_supervised_factors
        preds = np.asarray(self._label_preds(params, X))
        Y = np.asarray(Y)
        if Y.ndim == 3:
            col = cfg.max_lag if Y.shape[2] > cfg.max_lag else 0
            y = Y[:, :S, col]
        else:
            y = Y[:, :S]
        pred_cls = preds[:, :S].argmax(axis=1)
        true_cls = y.argmax(axis=1)
        cm = np.zeros((S, S))
        for t, p in zip(true_cls, pred_cls):
            cm[t, p] += 1
        return cm

    @staticmethod
    def _append_conf_stats(cm, histories, split):
        """Multi-class TPR/TNR/FPR/FNR/ACC from a confusion matrix
        (ref :1327-1346)."""
        TP = np.diag(cm)
        FP = cm.sum(axis=0) - TP
        FN = cm.sum(axis=1) - TP
        TN = cm.sum() - (FP + FN + TP)
        with np.errstate(divide="ignore", invalid="ignore"):
            histories[f"factor_score_{split}_acc_history"].append((TP + TN) / (TP + FP + FN + TN))
            histories[f"factor_score_{split}_tpr_history"].append(TP / (TP + FN))
            histories[f"factor_score_{split}_tnr_history"].append(TN / (TN + FP))
            histories[f"factor_score_{split}_fpr_history"].append(FP / (FP + TN))
            histories[f"factor_score_{split}_fnr_history"].append(FN / (TP + FN))

    def _epoch_gc_tracking(self, params, val_ds, tracker):
        cfg = self.model.config
        tc = self.config
        for X, _ in val_ds.batches(tc.batch_size):
            Xw = jnp.asarray(X[: tc.max_samples_for_gc_tracking, : cfg.max_lag, :])
            # condense wavelet-band blocks to channel granularity so tracking
            # compares (C, C) against the true graphs (the reference's
            # checkpoint tracking passes combine_wavelet_representations=True,
            # ref redcliff_s_cmlp.py:1092-1107); a no-op for non-wavelet runs
            lagged = np.asarray(self.model.gc(params, cfg.primary_gc_est_mode, X=Xw,
                                              threshold=False, ignore_lag=False,
                                              combine_wavelet_representations=True))
            nolag = np.asarray(self.model.gc(params, cfg.primary_gc_est_mode, X=Xw,
                                             threshold=False, ignore_lag=True,
                                             combine_wavelet_representations=True))[..., 0]
            est_lagged = [[lagged[s, k] for k in range(lagged.shape[1])]
                          for s in range(lagged.shape[0])]
            est_nolag = [[nolag[s, k] for k in range(nolag.shape[1])]
                         for s in range(nolag.shape[0])]
            tracker.update(true_GC=self._true_GC, est_by_sample=est_lagged,
                           est_by_sample_lagsummed=est_nolag)
            break  # only the first batch (ref :1403)

    def validate(self, params, val_ds, histories):
        cfg = self.model.config
        tc = self.config
        coeffs = self.model.normalization_coeffs()
        sums = {}
        combo_sum = 0.0
        n = 0
        conf_mat = (np.zeros((cfg.num_supervised_factors,) * 2)
                    if cfg.num_supervised_factors > 0 and histories is not None else None)
        for X, Y in val_ds.batches(tc.batch_size):
            combo, parts = self._eval_loss(params, X, Y)
            combo_sum += float(combo)
            for k, v in parts.items():
                c = coeffs.get(k, 1.0)
                sums[k] = sums.get(k, 0.0) + float(v) / (c if c > 0 else 1.0)
            if conf_mat is not None:
                conf_mat += self._confusion(params, X, Y)
            n += 1
        if n == 0:
            raise ValueError("validation dataset yielded no batches")
        out = {k: v / n for k, v in sums.items()}
        out["combo_loss"] = combo_sum / n
        if conf_mat is not None and conf_mat.sum() > 0:
            self._append_conf_stats(conf_mat, histories, "val")
        return out

    _true_GC = None

    def _save_checkpoint(self, save_dir, it, best_params, accepted, params,
                         optA_state, optB_state, histories, best_it, best_loss,
                         tracker, aligned, writer=None):
        """``writer`` (AsyncCheckpointWriter) moves the device->host
        materialization + durable writes onto a background thread: the main
        thread only deep-copies the host-mutable state (histories/tracker —
        the train loop keeps appending to the live objects) and kicks off
        the async device->host copies. The device trees are safe to share
        with the thread as-is: this trainer's steps do not donate buffers."""
        if writer is not None and jax.process_count() == 1:
            # deep copies only on the async path, where the background
            # thread would otherwise read objects the loop keeps appending
            hist_snap = copy.deepcopy(histories)
            tracker_meta = (copy.deepcopy(tracker.as_dict())
                            if tracker is not None else None)
            tracker_state = (None if tracker is None
                             else copy.deepcopy(dict(tracker.__dict__)))
            for tree in (best_params, accepted, params, optA_state,
                         optB_state):
                for leaf in jax.tree.leaves(tree):
                    if hasattr(leaf, "copy_to_host_async"):
                        leaf.copy_to_host_async()
            writer.submit(lambda: self._write_checkpoint_files(
                save_dir, it, best_params, accepted, params, optA_state,
                optB_state, hist_snap, best_it, best_loss, tracker_meta,
                tracker_state, aligned))
        else:
            self._write_checkpoint_files(
                save_dir, it, best_params, accepted, params, optA_state,
                optB_state, histories, best_it, best_loss,
                tracker.as_dict() if tracker is not None else None,
                None if tracker is None else dict(tracker.__dict__),
                aligned)

    def _write_checkpoint_files(self, save_dir, it, best_params, accepted,
                                params, optA_state, optB_state, histories,
                                best_it, best_loss, tracker_meta,
                                tracker_state, aligned):
        # all three artifacts ride the durable checkpoint writer (atomic
        # tmp+replace, CRC header, .prev generation): a preemption mid-write
        # can no longer tear the resume state
        os.makedirs(save_dir, exist_ok=True)
        durable_ckpt.write_checkpoint(
            os.path.join(save_dir, "final_best_model.bin"),
            {
                "model_class": "RedcliffSCMLP",
                "config": self.model.config,
                "params": jax.tree.map(np.asarray, best_params),
            })
        meta = {"epoch": it, "best_loss": float(best_loss), "best_it": best_it,
                **histories}
        if tracker_meta is not None:
            meta.update(tracker_meta)
        durable_ckpt.write_checkpoint(
            os.path.join(save_dir,
                         "training_meta_data_and_hyper_parameters.pkl"), meta)
        to_np = lambda t: jax.tree.map(
            lambda x: np.asarray(x) if isinstance(x, jnp.ndarray) else x, t)
        durable_ckpt.write_checkpoint(
            os.path.join(save_dir, "trainer_checkpoint.pkl"),
            {
                "epoch": it,
                "params": to_np(params),
                "best_params": to_np(best_params),
                "accepted": to_np(accepted),
                "optA_state": to_np(optA_state),
                "optB_state": to_np(optB_state),
                "histories": histories,
                "best_it": best_it,
                "best_loss": float(best_loss),
                "aligned": aligned,
                # sentinel-triggered precision demotion (mixed -> f32):
                # resumes rebuild their steps at f32 before dispatching
                "precision_demoted": self._demoted,
                "tracker_state": tracker_state,
            })
