"""Freeze-mode accept/revert choreography, shared by the per-point trainer and
the grid engine.

The reference's Freeze training modes keep a candidate and an accepted copy of
every factor network; after each batch (FreezeByBatch) or epoch (FreezeByEpoch)
a per-factor decision statistic chooses, factor by factor, whether the
candidate update is kept or reverted (ref models/redcliff_s_cmlp.py:866-885,
1116-1156, 1469-1515 — there via model deepcopies and per-factor Python loops;
here as two pytrees merged with per-factor jnp.where masks, vmappable over a
grid axis).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["factor_decision_stats", "freeze_accept_vector", "swap_factors",
           "apply_freeze"]


def factor_decision_stats(model, params):
    """Per-factor (matrix 1-norm, mean pairwise cosine) of the unlagged factor
    GC estimates (ref determine_which_factors_need_updates :1116-1156).

    NB the reference's np.linalg.norm(mat, ord=1) on the 2-D normalized
    estimate is the MATRIX 1-norm — the max over columns of the column's
    absolute row sum — not the entrywise L1 (an early version here summed all
    entries; the direct A/B in test_reference_parity_training.py pins the
    matrix norm)."""
    G = model.factor_gc(params, ignore_lag=True)  # (K, C, C)
    G = G / jnp.maximum(jnp.max(jnp.abs(G), axis=(1, 2), keepdims=True), 1e-12)
    l1 = jnp.max(jnp.sum(jnp.abs(G), axis=1), axis=-1)  # (K,) max column sum
    flat = G.reshape(G.shape[0], -1)
    norms = jnp.maximum(jnp.linalg.norm(flat, axis=1), 1e-8)
    cos = (flat @ flat.T) / (norms[:, None] * norms[None, :])
    K = G.shape[0]
    off = 1.0 - jnp.eye(K)
    avg_cos = jnp.sum(cos * off, axis=1) / jnp.maximum(K - 1.0, 1.0)
    return l1, avg_cos


def freeze_accept_vector(mode, new_stats, old_stats):
    """(K,) bool accept mask from the training mode's decision rule
    (ref :866-885): 'withComboCosSimL1' accepts when cos*l1 shrinks,
    'withL1' when l1 shrinks."""
    l1_new, cos_new = new_stats
    l1_old, cos_old = old_stats
    if "withComboCosSimL1" in mode:
        return (cos_new * l1_new) < (cos_old * l1_old)
    if "withL1" in mode:
        return l1_new < l1_old
    raise NotImplementedError(f"no freeze decision rule in mode {mode!r}")


def swap_factors(candidate, accepted, accept_vec):
    """accept_vec: (K,) bool — True takes the candidate factor into the
    accepted tree AND keeps it in the candidate; False reverts the candidate
    factor to the accepted one. The accepted tree's embedder follows the
    candidate ONLY on rounds where at least one factor was accepted (ref
    :880-885: update_cached_factor_score_embedder is set inside the accept
    branch, so a zero-accept round leaves the cached embedder untouched)."""

    def pick(c_leaf, a_leaf):
        m = accept_vec.reshape((-1,) + (1,) * (c_leaf.ndim - 1))
        return jnp.where(m, c_leaf, a_leaf)

    any_accept = jnp.any(accept_vec)
    merged = jax.tree.map(pick, candidate["factors"], accepted["factors"])
    emb = jax.tree.map(lambda c, a: jnp.where(any_accept, c, a),
                       candidate["embedder"], accepted["embedder"])
    new_candidate = dict(candidate, factors=merged)
    new_accepted = dict(accepted, factors=merged, embedder=emb)
    return new_candidate, new_accepted


def apply_freeze(model, mode, candidate, accepted):
    """One accept/revert round for a single (candidate, accepted) pair.
    Traceable: vmap over a leading grid axis for the grid engine, jit for the
    per-point trainer."""
    accept = freeze_accept_vector(
        mode,
        factor_decision_stats(model, candidate),
        factor_decision_stats(model, accepted))
    return swap_factors(candidate, accepted, accept)
