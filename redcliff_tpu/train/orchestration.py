"""L4 orchestration: model factory, data dispatch, and fit dispatch.

Rebuilds the orchestration utilities of
/root/reference/general_utils/model_utils.py — create_model_instance (:338),
get_data_for_model_training (:641), call_model_fit_method (:745) — on top of
the typed configs: args dicts produced by utils.config readers map onto the
functional model configs and trainers.  The reference's declared-but-absent
REDCLIFF_S_CLSTM / REDCLIFF_S_DGCNN variants (factory imports at
model_utils.py:341,344 with no model files) raise NotImplementedError here
with an explicit message instead of the reference's ImportError.
"""
from __future__ import annotations

import os

import jax
import numpy as np

__all__ = [
    "create_model_instance",
    "get_data_for_model_training",
    "call_model_fit_method",
    "call_model_eval_method",
    "generate_signal_from_sequential_factor_model",
]


def generate_signal_from_sequential_factor_model(model, params, x0,
                                                 sim_steps):
    """Autoregressive signal generation from a trained factor model
    (ref general_utils/model_utils.py:316-336): starting from the context
    window ``x0`` (B, context, C), predict one step, slide the window, and
    repeat for ``sim_steps`` — as one ``lax.scan`` instead of the
    reference's Python loop over device tensors. Works with any model whose
    ``forward(params, window)`` returns the simulated steps first (REDCLIFF
    variants, cMLP_FM/cLSTM_FM). Returns (B, sim_steps, C)."""
    import jax.numpy as jnp

    x0 = jnp.asarray(x0)

    def step(window, _):
        out = model.forward(params, window)
        sims = out[0] if isinstance(out, tuple) else out
        pred = sims[:, 0, :]
        window = jnp.concatenate([window[:, 1:, :], pred[:, None, :]],
                                 axis=1)
        return window, pred

    _, preds = jax.lax.scan(step, x0, None, length=sim_steps)
    return jnp.transpose(preds, (1, 0, 2))


def _coeff(args_dict, key, default=0.0):
    return float(args_dict.get("coeff_dict", {}).get(key, default))


def _build_redcliff(args_dict, employ_version_with_smoothing_loss,
                    factor_network_type, gen_lag, gen_hidden, embed_lag,
                    **coeff_overrides):
    """Shared REDCLIFF config builder for the cMLP/cLSTM factor variants
    (they differ only in lag/hidden sourcing and factor_network_type)."""
    from ..models.redcliff import RedcliffSCMLP, RedcliffSCMLPConfig

    emb_args = dict(args_dict.get("factor_score_embedder_args", []))
    smoothing_coeff = _coeff(args_dict,
                             "FACTOR_WEIGHT_SMOOTHING_PENALTY_COEFF") \
        if employ_version_with_smoothing_loss else 0.0
    cfg = RedcliffSCMLPConfig(
        num_chans=args_dict["num_channels"],
        gen_lag=gen_lag,
        gen_hidden=gen_hidden,
        embed_lag=embed_lag,
        embed_hidden_sizes=tuple(args_dict["embed_hidden_sizes"]),
        num_factors=args_dict["num_factors"],
        num_supervised_factors=args_dict["num_supervised_factors"],
        factor_network_type=factor_network_type,
        forecast_coeff=_coeff(args_dict, "FORECAST_COEFF", 1.0),
        factor_score_coeff=_coeff(args_dict, "FACTOR_SCORE_COEFF"),
        factor_cos_sim_coeff=_coeff(args_dict, "FACTOR_COS_SIM_COEFF"),
        factor_weight_l1_coeff=_coeff(args_dict, "FACTOR_WEIGHT_L1_COEFF"),
        adj_l1_reg_coeff=_coeff(args_dict, "ADJ_L1_REG_COEFF"),
        dagness_reg_coeff=_coeff(args_dict, "DAGNESS_REG_COEFF"),
        use_sigmoid_restriction=args_dict["use_sigmoid_restriction"],
        sigmoid_eccentricity_coeff=emb_args.get(
            "sigmoid_eccentricity_coeff", 10.0),
        factor_score_embedder_type=args_dict["factor_score_embedder_type"],
        dgcnn_num_graph_conv_layers=emb_args.get("num_graph_conv_layers", 2),
        dgcnn_num_hidden_nodes=emb_args.get("num_hidden_nodes", 32),
        primary_gc_est_mode=args_dict["primary_gc_est_mode"],
        forward_pass_mode=args_dict["forward_pass_mode"],
        num_sims=args_dict["num_sims"],
        wavelet_level=args_dict.get("wavelet_level"),
        training_mode=args_dict["training_mode"],
        num_pretrain_epochs=args_dict["num_pretrain_epochs"],
        num_acclimation_epochs=args_dict.get("num_acclimation_epochs", 0),
        factor_weight_smoothing_penalty_coeff=smoothing_coeff,
        **coeff_overrides,
    )
    return RedcliffSCMLP(cfg)


def create_model_instance(args_dict, employ_version_with_smoothing_loss=False):
    """Build the model object described by a parsed args dict
    (ref model_utils.py:338-639).  Returns the model instance; functional
    models are initialized via model.init(key) by the fit dispatch."""
    model_type = args_dict["model_type"]

    if "REDCLIFF" in model_type and "DGCNN" in model_type:
        raise NotImplementedError(
            f"{model_type} is declared by the reference factory "
            "(model_utils.py:344) but its model file was never "
            "published; see SURVEY.md §2.2")

    if "REDCLIFF" in model_type and "CLSTM" in model_type:
        # declared-but-absent in the reference (model_utils.py:341 imports a
        # missing file); implemented here as cLSTM factor networks inside the
        # shared REDCLIFF core.  The cLSTM-family schema carries context /
        # num_in_timesteps instead of gen_lag / embed_lag, and an int
        # gen_hidden (the per-series LSTM width).
        if "_S_" not in model_type:
            raise NotImplementedError(
                "only the supervised REDCLIFF_S_CLSTM variant is defined")
        gen_hidden = args_dict["gen_hidden"]
        if isinstance(gen_hidden, int):
            gen_hidden = (gen_hidden,)
        return _build_redcliff(
            args_dict, employ_version_with_smoothing_loss,
            factor_network_type="cLSTM",
            gen_lag=args_dict["context"],
            gen_hidden=tuple(gen_hidden),
            embed_lag=args_dict.get("num_in_timesteps",
                                    args_dict.get("embed_lag",
                                                  args_dict["context"])))

    if "REDCLIFF" in model_type and "CMLP" in model_type:
        if "_S_" not in model_type:
            # the reference factory raises here too (model_utils.py:414)
            raise NotImplementedError(
                "only the supervised REDCLIFF_S_CMLP variant exists; the "
                "unsupervised REDCLIFF_CMLP is unimplemented in the "
                "reference as well")
        return _build_redcliff(
            args_dict, employ_version_with_smoothing_loss,
            factor_network_type="cMLP",
            gen_lag=args_dict["gen_lag"],
            gen_hidden=tuple(args_dict["gen_hidden"]),
            embed_lag=args_dict["embed_lag"],
            dagness_lag_coeff=_coeff(args_dict, "DAGNESS_LAG_COEFF"),
            dagness_node_coeff=_coeff(args_dict, "DAGNESS_NODE_COEFF"))

    if "cMLP" in model_type or "CMLP" in model_type:
        from ..models.cmlp_fm import CMLPFM, CMLPFMConfig

        if "NAVAR" in model_type:
            from ..models.navar import NAVAR, NAVARConfig
            return NAVAR(NAVARConfig(
                num_nodes=args_dict["num_nodes"],
                num_hidden=args_dict["num_hidden"],
                maxlags=args_dict["maxlags"],
                hidden_layers=args_dict["hidden_layers"],
                dropout=args_dict["dropout"],
                lambda1=float(args_dict.get("lambda1", 0.0))))
        return CMLPFM(CMLPFMConfig(
            num_chans=args_dict["num_channels"],
            gen_lag=args_dict["gen_lag"],
            gen_hidden=tuple(args_dict["gen_hidden"]),
            input_length=args_dict["input_length"],
            num_sims=args_dict["num_sims"],
            forecast_coeff=_coeff(args_dict, "FORECAST_COEFF", 1.0),
            adj_l1_coeff=_coeff(args_dict, "ADJ_L1_REG_COEFF"),
            wavelet_level=args_dict.get("wavelet_level")))

    if "cLSTM" in model_type or "CLSTM" in model_type:
        if "NAVAR" in model_type:
            from ..models.navar import NAVARLSTM, NAVARLSTMConfig
            return NAVARLSTM(NAVARLSTMConfig(
                num_nodes=args_dict["num_nodes"],
                num_hidden=args_dict["num_hidden"],
                maxlags=args_dict["maxlags"],
                hidden_layers=args_dict["hidden_layers"],
                dropout=args_dict["dropout"],
                lambda1=float(args_dict.get("lambda1", 0.0))))
        from ..models.clstm_fm import CLSTMFM, CLSTMFMConfig
        return CLSTMFM(CLSTMFMConfig(
            num_chans=args_dict["num_channels"],
            gen_hidden=args_dict["gen_hidden"],
            context=args_dict["context"],
            max_input_length=args_dict.get("max_input_length"),
            forecast_coeff=_coeff(args_dict, "FORECAST_COEFF", 1.0),
            adj_l1_coeff=_coeff(args_dict, "ADJ_L1_REG_COEFF"),
            dagness_coeff=_coeff(args_dict, "DAGNESS_REG_COEFF"),
            wavelet_level=args_dict.get("wavelet_level")))

    if "DCSFA" in model_type:
        from ..models.dcsfa_nmf import DcsfaNmfConfig, FullDCSFAModel
        layout = "vanilla" if "vanilla" in args_dict.get(
            "signal_format", "") else "dirspec"
        return FullDCSFAModel(
            num_nodes=args_dict["num_channels"],
            num_high_level_node_features=
                args_dict["num_high_level_node_features"],
            gc_feature_layout=layout,
            config=DcsfaNmfConfig(
                n_components=args_dict["n_components"],
                n_sup_networks=args_dict["n_sup_networks"],
                h=args_dict["h"],
                momentum=args_dict["momentum"],
                lr=args_dict["lr"],
                recon_weight=args_dict["recon_weight"],
                sup_weight=args_dict["sup_weight"],
                sup_recon_weight=args_dict["sup_recon_weight"],
                sup_smoothness_weight=args_dict["sup_smoothness_weight"]))

    if "DGCNN" in model_type:
        from ..models.dgcnn import DGCNNConfig, DGCNNModel
        return DGCNNModel(DGCNNConfig(
            num_channels=args_dict["num_channels"],
            num_wavelets_per_chan=args_dict.get("num_wavelets_per_chan", 1),
            num_features_per_node=args_dict["num_features_per_node"],
            num_graph_conv_layers=args_dict["num_graph_conv_layers"],
            num_hidden_nodes=args_dict["num_hidden_nodes"],
            num_classes=args_dict["num_classes"]))

    if "DYNOTEARS" in model_type:
        from ..models.dynotears import (
            DynotearsConfig,
            DynotearsModel,
            DynotearsVanillaModel,
        )
        cfg = DynotearsConfig(
            lambda_w=args_dict["lambda_w"],
            lambda_a=args_dict["lambda_a"],
            max_iter=args_dict["max_iter"],
            h_tol=args_dict["h_tol"],
            w_threshold=args_dict["w_threshold"],
            lag_size=args_dict["lag_size"],
            grad_step=float(args_dict.get("grad_step", 1.0)),
            tabu_edges=args_dict.get("tabu_edges"),
            tabu_parent_nodes=args_dict.get("tabu_parent_nodes"),
            tabu_child_nodes=args_dict.get("tabu_child_nodes"),
            reuse_rho=bool(args_dict.get("reuse_rho", False)),
            reuse_alpha=bool(args_dict.get("reuse_alpha", False)),
            reuse_h_val=bool(args_dict.get("reuse_h_val", False)),
            reuse_h_new=bool(args_dict.get("reuse_h_new", False)))
        if "Vanilla" in model_type:
            return DynotearsVanillaModel(cfg)
        return DynotearsModel(cfg)

    raise ValueError(f"UNRECOGNIZED model_type == {model_type}")


def get_data_for_model_training(args_dict, grid_search=True, shuffle=True,
                                shuffle_seed=0):
    """(train, validation) datasets for a parsed args dict
    (ref model_utils.py:641-743): the data_root_path carries fold splits in
    the shared shard layout; signal format and dirspec parameters follow the
    model family."""
    from ..data.shards import load_normalized_split_datasets

    return load_normalized_split_datasets(
        args_dict["data_root_path"],
        signal_format=args_dict.get("signal_format", "original"),
        shuffle=shuffle, shuffle_seed=shuffle_seed,
        max_num_features_per_series=args_dict.get(
            "max_num_features_per_series",
            args_dict.get("num_node_features")),
        dirspec_params=args_dict.get("dirspec_params"),
        grid_search=grid_search,
        average_region_map=args_dict.get("average_region_map"),
        wavelet_level=args_dict.get("wavelet_level"))


def call_model_fit_method(model, args_dict, train_ds, val_ds, save_dir=None,
                          seed=0):
    """Construct the family-appropriate trainer/optimizers and fit
    (ref model_utils.py:745-1059).  Returns (params_or_state, fit_result)."""
    from ..models.dcsfa_nmf import DcsfaNmf
    from ..models.dynotears import DynotearsModel, DynotearsVanillaModel
    from ..models.redcliff import RedcliffSCMLP

    model_type = args_dict["model_type"]
    save_dir = save_dir or args_dict.get("save_path")
    if save_dir:
        os.makedirs(save_dir, exist_ok=True)
    key = jax.random.PRNGKey(seed)

    if isinstance(model, RedcliffSCMLP):
        from .redcliff_trainer import RedcliffTrainConfig, RedcliffTrainer
        tc = RedcliffTrainConfig(
            embed_lr=args_dict["embed_lr"],
            embed_eps=args_dict["embed_eps"],
            embed_weight_decay=args_dict["embed_weight_decay"],
            gen_lr=args_dict["gen_lr"],
            gen_eps=args_dict["gen_eps"],
            gen_weight_decay=args_dict["gen_weight_decay"],
            max_iter=args_dict["max_iter"],
            lookback=args_dict["lookback"],
            check_every=args_dict["check_every"],
            batch_size=args_dict["batch_size"],
            verbose=args_dict.get("verbose", 0),
            seed=seed,
            stopping_criteria_forecast_coeff=args_dict.get(
                "stopping_criteria_forecast_coeff", 1.0),
            stopping_criteria_factor_coeff=args_dict.get(
                "stopping_criteria_factor_coeff", 1.0),
            stopping_criteria_cosSim_coeff=args_dict.get(
                "stopping_criteria_cosSim_coeff", 1.0),
            max_factor_prior_batches=args_dict.get(
                "max_factor_prior_batches", 10),
            unsupervised_start_index=args_dict.get(
                "unsupervised_start_index", 0))
        trainer = RedcliffTrainer(model, tc)
        params = model.init(key)
        result = trainer.fit(params, train_ds, val_ds,
                             true_GC=args_dict.get("true_GC_factors"),
                             save_dir=save_dir)
        return result.params, result

    if isinstance(model, (DynotearsModel, DynotearsVanillaModel)):
        if isinstance(model, DynotearsVanillaModel):
            model.fit(train_ds.X, save_dir=save_dir)
            return model.gc(), model
        model.fit(train_ds, val_ds, save_dir=save_dir,
                  max_data_iter=args_dict.get("max_data_iter", 10),
                  batch_size=args_dict.get("batch_size", 32),
                  num_iters_prior_to_stop=args_dict.get(
                      "num_iters_prior_to_stop", 10),
                  check_every=args_dict.get("check_every", 5),
                  verbose=bool(args_dict.get("verbose", 0)))
        return model.gc(), model

    if isinstance(model, DcsfaNmf):
        X_tr = getattr(train_ds, "X_features", None)
        X_val = getattr(val_ds, "X_features", None)
        if X_tr is None:
            raise ValueError(
                "DCSFA training requires feature-format datasets "
                "(signal_format='directed_spectrum*'); got raw windows")
        def dcsfa_labels(ds):
            """Label traces (N, R, T) average over time (the reference's
            average_label_over_time_steps=True, ref synthetic_datasets.py:335)
            and slice to the fit contract's n_sup_networks columns
            (ref dcsfa_nmf.py fit docstring: y is [n_samples, n_sup_networks])."""
            y = np.asarray(ds.Y)
            if y.ndim == 3:
                y = y.mean(axis=2)
            y = y.reshape(len(ds), -1)
            return y[:, : model.config.n_sup_networks]

        y_tr = dcsfa_labels(train_ds)
        y_val = dcsfa_labels(val_ds)
        params, state, hist = model.fit(
            key, X_tr, y_tr, X_val=X_val, y_val=y_val,
            n_epochs=args_dict.get("n_epochs", 100),
            n_pre_epochs=args_dict.get("n_pre_epochs", 100),
            nmf_max_iter=args_dict.get("nmf_max_iter", 100),
            batch_size=args_dict.get("batch_size", 128),
            save_folder=save_dir,
            best_model_name=args_dict.get("best_model_name",
                                          "dCSFA-NMF-best-model.pkl"))
        return (params, state), hist

    # generic single-optimizer families (cMLP_FM, cLSTM_FM, DGCNN, NAVAR)
    from .trainer import TrainConfig, Trainer
    tc = TrainConfig(
        learning_rate=args_dict.get("gen_lr",
                                    args_dict.get("learning_rate", 1e-3)),
        max_iter=args_dict.get("max_iter", args_dict.get("epochs", 100)),
        lookback=args_dict.get("lookback", 5),
        check_every=args_dict.get("check_every", 50),
        batch_size=args_dict.get("batch_size", 32),
        seed=seed,
        verbose=args_dict.get("verbose", 0))
    # DGCNN is the only supervised classifier among the generic families;
    # the forecasters (cMLP_FM/cLSTM_FM/NAVAR) consume labels only for
    # GC-progress tracking
    trainer = Trainer(model, tc, has_labels="DGCNN" in model_type)
    params = model.init(key)
    result = trainer.fit(params, train_ds, val_ds,
                         true_GC=args_dict.get("true_GC_factors"),
                         save_dir=save_dir)
    return result.params, result


def _avg_loss_parts(loss_fn, val_ds, batch_size):
    """Average (combo, parts) of a jit'd loss over the validation batches,
    accumulating on device (one host transfer at the end)."""
    import jax.numpy as jnp

    combo_sum = 0.0
    part_sums = {}
    n = 0
    for X, Y in val_ds.batches(batch_size):
        combo, parts = loss_fn(X, Y)
        combo_sum = combo_sum + combo
        for k, v in parts.items():
            part_sums[k] = part_sums.get(k, 0.0) + v
        n += 1
    if n == 0:
        raise ValueError("validation dataset yielded no batches")
    out = {k: float(jnp.asarray(v)) / n for k, v in part_sums.items()}
    out["combo_loss"] = float(jnp.asarray(combo_sum)) / n
    return out


def _normalized_gc_l1(gc):
    gc = np.asarray(gc, dtype=np.float64)
    return float(np.abs(gc / max(np.max(np.abs(gc)), 1e-12)).sum())


def call_model_eval_method(model, params, args_dict, val_ds, state=None):
    """Uniform per-family "evaluate this trained model" dispatch
    (ref general_utils/model_utils.py:1061-1343): every model family maps to
    its validation-loss decomposition (plus the GC-L1 terms the reference's
    grid selection consumes).

    Returns a dict with a ``components`` list in the reference's positional
    order for that family plus the same values under stable names. The
    reference's cMLP/cLSTM branches append ``components + components + [l1]``
    (ref :1098, :1287 — the list is doubled before the L1 norm is appended);
    that positional layout is reproduced so index-based consumers match.
    """
    import jax.numpy as jnp

    from ..models.clstm_fm import CLSTMFM
    from ..models.cmlp_fm import CMLPFM
    from ..models.dcsfa_nmf import DcsfaNmf
    from ..models.dgcnn import DGCNNModel
    from ..models.dynotears import DynotearsModel, DynotearsVanillaModel
    from ..models.navar import NAVAR, NAVARLSTM
    from ..models.redcliff import RedcliffSCMLP

    batch_size = int(args_dict.get("batch_size", 32))

    if isinstance(model, RedcliffSCMLP):
        coeffs = model.normalization_coeffs()
        loss_fn = jax.jit(
            lambda X, Y: model.loss_for_phase(params, X, Y, "combined"))
        parts = _avg_loss_parts(loss_fn, val_ds, batch_size)
        norm = {k: v / (coeffs.get(k, 1.0) or 1.0) for k, v in parts.items()}
        if model.config.factor_network_type == "cLSTM":
            order = ("forecasting_loss", "factor_loss",
                     "factor_cos_sim_penalty", "fw_l1_penalty",
                     "adj_l1_penalty", "dagness_reg_penalty", "combo_loss")
        else:  # cMLP variant carries the lag/node dagness terms (ref :1146)
            order = ("forecasting_loss", "factor_loss",
                     "factor_cos_sim_penalty", "fw_l1_penalty",
                     "adj_l1_penalty", "dagness_reg_penalty",
                     "dagness_lag_penalty", "dagness_node_penalty",
                     "combo_loss")
        named = {k: norm.get(k, 0.0) for k in order}
        return {"components": [named[k] for k in order], **named}

    if isinstance(model, (NAVAR, NAVARLSTM)):
        # not covered by the reference dispatch (its string matching falls
        # through to ValueError for NAVAR_* types); provided here so L5/L6
        # never hand-wire a family
        loss_fn = jax.jit(lambda X, Y: model.loss(params, X))
        parts = _avg_loss_parts(loss_fn, val_ds, batch_size)
        named = {
            "forecasting_loss": parts.get("forecasting_loss", 0.0),
            "contribution_l1": parts.get("contribution_l1", 0.0),
            "combo_loss": parts["combo_loss"],
        }
        return {"components": list(named.values()), **named}

    if isinstance(model, CMLPFM):
        loss_fn = jax.jit(lambda X, Y: model.loss(params, X))
        parts = _avg_loss_parts(loss_fn, val_ds, batch_size)
        named = {
            "forecasting_loss": parts.get("forecasting_loss", 0.0),
            "adj_l1_penalty": parts.get("adj_l1_penalty", 0.0),
            "dagness_reg_penalty": parts.get("dagness_reg_penalty", 0.0),
            "dagness_lag_penalty": parts.get("dagness_lag_penalty", 0.0),
            "dagness_node_penalty": parts.get("dagness_node_penalty", 0.0),
            "combo_loss": parts["combo_loss"],
        }
        comps = list(named.values())
        l1 = _normalized_gc_l1(model.gc(params, ignore_lag=False)[0])
        named["normalized_gc_l1"] = l1
        return {"components": comps + comps + [l1], **named}

    if isinstance(model, CLSTMFM):
        loss_fn = jax.jit(lambda X, Y: model.loss(params, X))
        parts = _avg_loss_parts(loss_fn, val_ds, batch_size)
        named = {
            "forecasting_loss": parts.get("forecasting_loss", 0.0),
            "adj_l1_penalty": parts.get("adj_l1_penalty", 0.0),
            "dagness_penalty": parts.get("dagness_penalty", 0.0),
            "smoothing_penalty": parts.get("smoothing_penalty", 0.0),
            "combo_loss": parts["combo_loss"],
        }
        comps = list(named.values())
        l1 = float(jnp.sum(jnp.abs(jnp.asarray(model.gc(params)[0]))))
        named["gc_l1"] = l1
        return {"components": comps + comps + [l1], **named}

    if isinstance(model, DcsfaNmf):
        if state is None and isinstance(params, tuple) and len(params) == 2:
            params, state = params
        # real (non-synthetic) datasets have no ground-truth graphs — the
        # config layer sets true_GC_tensor to None; gc_mse is then empty
        true_gc = args_dict.get("true_GC_tensor")
        if true_gc is None:
            true_gc = []
        summary = model.evaluate(
            params, state, getattr(val_ds, "X_features", val_ds.X),
            np.asarray(val_ds.Y).reshape(len(val_ds), -1),
            true_gc,
            save_path=args_dict.get("save_root_path"),
            threshold=False, ignore_features=True)
        return {"components": [summary["recon_mse"], summary["avg_recon_mse"],
                               summary["score_mse"], summary["avg_score_mse"],
                               summary["gc_mse"]], **summary}

    if isinstance(model, DGCNNModel):
        loss_fn = jax.jit(lambda X, Y: model.loss(params, X, Y))
        parts = _avg_loss_parts(loss_fn, val_ds, batch_size)
        # the reference rescales the GC estimate to the true no-lag max (1.6)
        # before the L1 (ref :1316-1328)
        gc = np.asarray(model.gc(params)[0], dtype=np.float64)
        gc = 1.6 * gc / max(np.max(gc), 1e-12)
        gc = gc * (gc >= 0.0)
        l1 = float(np.abs(gc).sum())
        return {"components": [parts["factor_loss"], l1],
                "factor_loss": parts["factor_loss"], "scaled_gc_l1": l1}

    if isinstance(model, DynotearsModel):
        avg = float(model._mean_objective(val_ds, batch_size))
        return {"components": [avg], "avg_val_loss": avg}

    if isinstance(model, DynotearsVanillaModel):
        from ..models.dynotears import _split_windows, dynotears_objective
        cfg = model.config
        a = np.asarray(model.gc(), dtype=np.float64)
        d = a.shape[0]
        # score the averaged lagged graph as a single-lag solution with no
        # intra-window W, in the solver's (plus, minus)-split vector layout
        # (reshape_wa contract: W+ rows, W- rows, then A+/A- flat blocks)
        wa = np.concatenate([
            np.zeros(2 * d * d),              # W+ = W- = 0
            np.maximum(a, 0.0).reshape(-1),   # A+
            np.maximum(-a, 0.0).reshape(-1),  # A-
        ])
        total, count = 0.0, 0
        for X, _ in val_ds.batches(batch_size):
            for b in range(X.shape[0]):
                x_in, x_lag = _split_windows(
                    np.asarray(X[b], np.float64), cfg.lag_size)
                total += dynotears_objective(
                    x_in, x_lag, wa, 0.0, 0.0, d, 1,
                    cfg.lambda_a, cfg.lambda_w, x_in.shape[0])
                count += 1
        avg = total / max(count, 1)
        return {"components": [avg], "avg_val_loss": avg}

    raise ValueError(
        f"call_model_eval_method: unsupported model type {type(model).__name__}")
