"""Host-side per-epoch GC progress tracking.

Semantics-parity rebuild of the reference's metric trackers
(ref general_utils/model_utils.py:18-209): per-factor F1/ROC-AUC at fixed
thresholds, DeltaCon0-family similarities, normalized L1 norms, and pairwise
cosine similarities, each appended to history lists every epoch. Estimates and
truths are max-normalized before comparison; 3-D (lagged) inputs are lag-summed.

Inputs here are plain numpy: ``true_GC`` is a list of (C, C, L) ground-truth
tensors; ``est_by_sample`` is a list (samples) of lists (factors) of (C, C[, L])
estimate arrays — the same nesting the reference's GC() returns.
"""
from __future__ import annotations

import numpy as np

from redcliff_tpu.utils.metrics import (
    compute_cosine_similarity,
    deltacon0,
    deltacon0_with_directed_degrees,
    deltaffinity,
    get_f1_score,
    path_length_mse,
    roc_auc,
)

__all__ = ["GCProgressTracker"]


def _prep(mat, remove_self_connections):
    mat = np.asarray(mat, dtype=np.float64)
    if mat.ndim == 3:
        mat = mat.sum(axis=2)
    if remove_self_connections:
        mat = mat.copy()
        np.fill_diagonal(mat, 0.0)
    m = np.max(mat)
    if m != 0.0:
        mat = mat / m
    return mat


class GCProgressTracker:
    """Accumulates the reference's per-epoch GC metric histories."""

    def __init__(self, num_supervised_factors, num_chans, num_factors=None,
                 f1_thresholds=(0.0,), deltacon_eps=0.1):
        S = num_supervised_factors
        self.S = S
        self.num_chans = num_chans
        K = num_factors if num_factors is not None else S
        self.K = K
        self.deltacon_eps = deltacon_eps
        self.f1_thresholds = list(f1_thresholds)
        self.f1score_histories = {t: [[] for _ in range(S)] for t in self.f1_thresholds}
        self.f1score_OffDiag_histories = {t: [[] for _ in range(S)] for t in self.f1_thresholds}
        self.roc_auc_histories = {t: [[] for _ in range(S)] for t in self.f1_thresholds}
        self.roc_auc_OffDiag_histories = {t: [[] for _ in range(S)] for t in self.f1_thresholds}
        self.gc_factor_l1_loss_histories = [[] for _ in range(S)]
        self.gc_factor_cosine_sim_histories = {
            f"{i}and{j}": [] for i in range(S) for j in range(S) if i < j
        }
        self.gc_factorUnsupervised_cosine_sim_histories = {
            f"{i}and{j}": [] for i in range(S, K) for j in range(S, K) if i < j
        }
        self.deltacon0_histories = [[] for _ in range(S)]
        self.deltacon0_with_directed_degrees_histories = [[] for _ in range(S)]
        self.deltaffinity_histories = [[] for _ in range(S)]
        self.path_length_mse_histories = {
            p: [[] for _ in range(S)] for p in range(1, num_chans)
        }

    # -- individual trackers (each mirrors one reference function) ----------

    def _roc_stats(self, true_GC, est_by_sample, remove_self):
        """ref model_utils.py:18-88."""
        out_f1 = {t: [] for t in self.f1_thresholds}
        out_auc = {t: [] for t in self.f1_thresholds}
        n_est = min(len(est_by_sample[0]), len(true_GC))
        n_s = len(est_by_sample)
        # normalization/diag-masking is threshold- and sample-invariant: prep once
        truths = [_prep(true_GC[i], remove_self) for i in range(n_est)]
        labels = [t.ravel().astype(int) for t in truths]
        prepped = [[_prep(sample[i], remove_self) for i in range(n_est)]
                   for sample in est_by_sample]
        for thresh in self.f1_thresholds:
            f1_sums = np.zeros(n_est)
            auc_sums = np.zeros(n_est)
            for sample in prepped:
                for i in range(n_est):
                    est = sample[i] * (sample[i] > thresh)
                    f1_sums[i] += get_f1_score(est, truths[i])
                    if labels[i].sum() == 0:
                        auc_sums[i] += 0.5
                    else:
                        auc_sums[i] += roc_auc(labels[i], est.ravel())
            # single shared estimate replicated across supervised slots when the
            # model produces fewer estimates than supervised states
            for i in range(self.S):
                src = 0 if n_est == 1 and self.S > 1 else min(i, n_est - 1)
                out_f1[thresh].append(f1_sums[src] / n_s)
                out_auc[thresh].append(auc_sums[src] / n_s)
        return out_f1, out_auc

    def update(self, true_GC, est_by_sample, est_by_sample_lagsummed=None):
        """Append one epoch of metrics. ``est_by_sample`` carries lagged (C, C, L)
        estimates (used for F1/AUC/deltacon after lag-summing, ref fit loop at
        redcliff_s_cmlp.py:1349-1400); ``est_by_sample_lagsummed`` optionally
        carries the ignore_lag readouts used for the cosine histories.

        ``true_GC=None`` skips the truth-dependent histories (F1/AUC/deltacon
        family) while still tracking the truth-free ones — L1 norms and the
        pairwise cosines the stopping criterion consumes. The reference's fit
        always has ground truth in hand, so its tracking is unconditional
        (ref :1349-1403); this keeps the same criteria semantics on unlabeled
        runs."""
        n_s = len(est_by_sample)
        if true_GC is not None:
            f1, auc = self._roc_stats(true_GC, est_by_sample, remove_self=False)
            f1_od, auc_od = self._roc_stats(true_GC, est_by_sample, remove_self=True)
            for t in self.f1_thresholds:
                for i in range(self.S):
                    self.f1score_histories[t][i].append(f1[t][i])
                    self.roc_auc_histories[t][i].append(auc[t][i])
                    self.f1score_OffDiag_histories[t][i].append(f1_od[t][i])
                    self.roc_auc_OffDiag_histories[t][i].append(auc_od[t][i])

            # deltacon0 family (ref model_utils.py:90-161); note reference
            # argument order: similarity(truth, estimate)
            n_est = min(len(est_by_sample[0]), len(true_GC))
            dc0 = np.zeros(n_est)
            dc0dd = np.zeros(n_est)
            daf = np.zeros(n_est)
            plm = {p: np.zeros(n_est) for p in self.path_length_mse_histories}
            for sample in est_by_sample:
                for i in range(n_est):
                    truth = _prep(true_GC[i], False)
                    est = _prep(sample[i], False)
                    dc0[i] += deltacon0(truth, est, self.deltacon_eps)
                    dc0dd[i] += deltacon0_with_directed_degrees(truth, est, self.deltacon_eps)
                    daf[i] += deltaffinity(truth, est, self.deltacon_eps)
                    _, per_k = path_length_mse(truth, est)
                    for p, mse in zip(range(1, self.num_chans), per_k):
                        plm[p][i] += mse
            for i in range(self.S):
                src = 0 if n_est == 1 and self.S > 1 else min(i, n_est - 1)
                self.deltacon0_histories[i].append(dc0[src] / n_s)
                self.deltacon0_with_directed_degrees_histories[i].append(dc0dd[src] / n_s)
                self.deltaffinity_histories[i].append(daf[src] / n_s)
                for p in plm:
                    self.path_length_mse_histories[p][i].append(plm[p][src] / n_s)

        # normalized L1 norms (ref model_utils.py:163-189)
        K_est = len(est_by_sample[0])
        l1_sums = np.zeros(K_est)
        for sample in est_by_sample:
            for i in range(K_est):
                e = np.asarray(sample[i], dtype=np.float64)
                m = np.max(e)
                if m != 0:
                    e = e / m
                l1_sums[i] += np.abs(e).sum()
        for i in range(self.S):
            self.gc_factor_l1_loss_histories[i].append(l1_sums[min(i, K_est - 1)] / n_s)

        # pairwise cosine similarities (ref model_utils.py:191-209)
        cos_src = est_by_sample_lagsummed if est_by_sample_lagsummed is not None else est_by_sample
        self._track_cosines(
            [[np.asarray(s[i]) for i in range(min(self.S, len(s)))] for s in cos_src],
            self.gc_factor_cosine_sim_histories, label_offset=0,
        )
        self._track_cosines(
            [[np.asarray(s[i]) for i in range(self.S, len(s))] for s in cos_src],
            self.gc_factorUnsupervised_cosine_sim_histories, label_offset=self.S,
        )

    def _track_cosines(self, est_by_sample, histories, label_offset):
        sums = {}
        n_s = 0
        for sample in est_by_sample:
            n_s += 1
            for i in range(len(sample)):
                for j in range(i + 1, len(sample)):
                    # DOCUMENTED DEVIATION from ref model_utils.py:191-209,
                    # which divides by max(np.max(x), 1e-300): when an
                    # estimate is all-non-positive (possible for conditional
                    # GC modes with unrestricted, sign-free embedder
                    # weightings) that floor scales entries by ~1e300 and the
                    # cosine dot product overflows to +-inf — an -inf then
                    # poisons the stopping criterion and auto-wins model
                    # selection. Guard like the grid engine's point_cos
                    # (parallel/grid.py): scale only by a strictly positive
                    # max; cosine's own norm floor keeps the result finite.
                    a = np.asarray(sample[i], dtype=np.float64)
                    b = np.asarray(sample[j], dtype=np.float64)
                    ma, mb = np.max(a), np.max(b)
                    a = a / ma if ma > 0 else a
                    b = b / mb if mb > 0 else b
                    key = f"{i + label_offset}and{j + label_offset}"
                    sums[key] = sums.get(key, 0.0) + compute_cosine_similarity(a, b)
        for key, total in sums.items():
            if key in histories:
                histories[key].append(total / n_s)

    def latest_as_dict(self):
        """Most recent epoch's metrics, flattened for structured logging
        (one key per factor/threshold/pair)."""
        out = {}
        for t in self.f1_thresholds:
            for i in range(self.S):
                if self.f1score_histories[t][i]:
                    out[f"f1_t{t}_factor{i}"] = self.f1score_histories[t][i][-1]
                    out[f"roc_auc_t{t}_factor{i}"] = self.roc_auc_histories[t][i][-1]
                    out[f"f1_offdiag_t{t}_factor{i}"] = self.f1score_OffDiag_histories[t][i][-1]
                    out[f"roc_auc_offdiag_t{t}_factor{i}"] = self.roc_auc_OffDiag_histories[t][i][-1]
        for i in range(self.S):
            if self.deltacon0_histories[i]:
                out[f"deltacon0_factor{i}"] = self.deltacon0_histories[i][-1]
                out[f"deltaffinity_factor{i}"] = self.deltaffinity_histories[i][-1]
            # tracked even without ground truth (unlabeled runs): own gate
            if self.gc_factor_l1_loss_histories[i]:
                out[f"gc_l1_factor{i}"] = self.gc_factor_l1_loss_histories[i][-1]
        for key, h in self.gc_factor_cosine_sim_histories.items():
            if h:
                out[f"cosine_sim_{key}"] = h[-1]
        return out

    def latest_mean_supervised_cosine(self):
        """Mean of the most recent supervised pairwise cosines — the stopping
        criterion component (ref redcliff_s_cmlp.py:1467)."""
        vals = [h[-1] for h in self.gc_factor_cosine_sim_histories.values() if h]
        return float(np.mean(vals)) if vals else 0.0

    def as_dict(self):
        return {
            "f1score_histories": self.f1score_histories,
            "f1score_OffDiag_histories": self.f1score_OffDiag_histories,
            "roc_auc_histories": self.roc_auc_histories,
            "roc_auc_OffDiag_histories": self.roc_auc_OffDiag_histories,
            "gc_factor_l1_loss_histories": self.gc_factor_l1_loss_histories,
            "gc_factor_cosine_sim_histories": self.gc_factor_cosine_sim_histories,
            "gc_factorUnsupervised_cosine_sim_histories": self.gc_factorUnsupervised_cosine_sim_histories,
            "deltacon0_histories": self.deltacon0_histories,
            "deltacon0_with_directed_degrees_histories": self.deltacon0_with_directed_degrees_histories,
            "deltaffinity_histories": self.deltaffinity_histories,
            "path_length_mse_histories": self.path_length_mse_histories,
        }
