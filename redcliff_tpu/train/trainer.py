"""Generic trainer — the single fit loop shared by the model zoo.

The reference embeds a bespoke fit()/batch_update()/validate_training() loop in
every model class (SURVEY.md §1: redcliff_s_cmlp.py:1159-1628, cmlp_fm.py:264-416,
dgcnn.py:122-199, ...). This build factors that into one functional trainer:

* a model exposes ``init``, ``loss(params, X[, Y]) -> (combo, parts)``, ``gc``,
  and optionally ``apply_prox`` and ``validation_criteria``;
* the trainer owns the jit'd Adam step, epoch loop, early stopping with
  lookback*check_every patience, per-epoch GC tracking vs oracle graphs, and
  checkpointing in the reference's on-disk layout (final_best_model.bin +
  training_meta_data_and_hyper_parameters.pkl).

Checkpoints fix the reference's no-optimizer-resume gap
(ref redcliff_s_cmlp.py:245): optimizer state is saved and restored exactly.
All checkpoint artifacts are written through the durable
:mod:`redcliff_tpu.runtime.checkpoint` format (atomic + CRC + ``.prev``
generation); :func:`load_model` reads both the durable format and legacy
headerless pickles. The train step carries the numerics sentinel
(:mod:`redcliff_tpu.runtime.numerics`): non-finite loss/gradients skip the
update in-graph, and the host-side DivergenceMonitor rolls back / backs off
the learning rate / aborts per the configured :class:`NumericsPolicy`.
"""
from __future__ import annotations

import copy
import os
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax

from redcliff_tpu import obs
from redcliff_tpu.data import pipeline
from redcliff_tpu.obs import MetricLogger
from redcliff_tpu.obs import memory as _obsmem
from redcliff_tpu.obs import profiling as _profiling
from redcliff_tpu.obs import quality as _obsquality
from redcliff_tpu.runtime import checkpoint as durable_ckpt
from redcliff_tpu.runtime import compileobs, faultinject, numerics
from redcliff_tpu.runtime import watchdog as rt_watchdog
from redcliff_tpu.runtime.numerics import NumericsPolicy
from redcliff_tpu.train.tracking import GCProgressTracker
from redcliff_tpu.utils.precision import (check_precision_mode,
                                          matmul_precision_ctx,
                                          resolve_matmul_precision)

__all__ = ["TrainConfig", "Trainer", "FitResult", "save_model", "load_model"]


@dataclass
class TrainConfig:
    learning_rate: float = 1e-3
    max_iter: int = 100
    lookback: int = 5
    check_every: int = 50
    batch_size: int = 32
    seed: int = 0
    prox_penalty: str | None = None  # "GL" | "GSGL" | "H"
    prox_lam: float = 0.0
    verbose: int = 0
    profile_dir: str | None = None  # opt-in jax.profiler trace output dir
    # bounded profiler capture window ("epoch:N" / "epoch:N-M",
    # obs/profiling.py); None = follow REDCLIFF_PROFILE. profile_dir alone
    # now captures ONE bounded steady-state window, never the whole fit
    profile_window: str | None = None
    # double-buffered host prefetch depth for datasets without device-batch
    # support (shard streams): batch assembly + device_put of batch t+1
    # overlap compute of batch t (data/pipeline.py). <= 0 disables
    prefetch_batches: int = 2
    # hand periodic checkpoint saves to a background writer thread — the
    # device->host gather + durable CRC+.prev write stop stalling the epoch
    # loop (completion barrier at the next save / fit end)
    async_checkpointing: bool = True
    # persistent XLA compilation cache base dir (runtime/compileobs.py);
    # None = follow the REDCLIFF_COMPILE_CACHE env var (unset -> disabled)
    compile_cache_dir: str | None = None
    # numerical fault policy (in-graph skip guard + divergence rollback);
    # None disables the sentinel entirely
    numerics: NumericsPolicy | None = field(default_factory=NumericsPolicy)
    # production precision mode (utils/precision.py): "f32" (default;
    # bit-identical to a config without the knob) or "mixed" (bf16 MXU
    # contractions, f32 master params/reductions). The numerics sentinel
    # watches the cliff: a rollback under "mixed" auto-demotes the fit to
    # f32 (schema-registered `precision` event, demotion persisted in the
    # checkpoint so a resume can never silently re-promote)
    precision_mode: str = "f32"

    def __post_init__(self):
        check_precision_mode(self.precision_mode)


@dataclass
class FitResult:
    params: Any
    best_it: int
    best_loss: float
    histories: dict
    tracker: GCProgressTracker | None
    final_val_loss: float
    # non-None when the numerics sentinel aborted the fit (e.g.
    # "all_nonfinite_validation"); params are still best_params — the
    # best-criteria iterate seen, which is the initial params when no
    # epoch's criteria ever went finite
    aborted: str | None = None


def save_model(save_dir, model, params, extra=None):
    """Persist {config, params} under the reference's artifact name (durable
    checkpoint format: atomic write, CRC header, ``.prev`` generation)."""
    os.makedirs(save_dir, exist_ok=True)
    payload = {
        "model_class": type(model).__name__,
        "config": model.config,
        "params": jax.tree.map(np.asarray, params),
    }
    if extra:
        payload.update(extra)
    durable_ckpt.write_checkpoint(
        os.path.join(save_dir, "final_best_model.bin"), payload)


def load_model(save_dir_or_file):
    """Read a model artifact — durable-format and legacy raw-pickle files
    both load (the runtime reader falls back on unpickling when the CRC
    header is absent)."""
    path = save_dir_or_file
    if os.path.isdir(path):
        path = os.path.join(path, "final_best_model.bin")
    return durable_ckpt.read_checkpoint(path)


class Trainer:
    def __init__(self, model, config: TrainConfig, has_labels=False):
        self.model = model
        self.config = config
        self.has_labels = has_labels
        compileobs.enable_cache(config.compile_cache_dir)
        compileobs.install()
        # inject_hyperparams makes the learning rate part of the optimizer
        # STATE, so the DivergenceMonitor can back it off on rollback without
        # recompiling the step
        self.optimizer = optax.inject_hyperparams(optax.adam)(
            learning_rate=config.learning_rate)
        self._guard = config.numerics is not None and config.numerics.enabled
        # effective matmul precision (utils/precision.py); "mixed" fits are
        # demotable: a sentinel rollback rebuilds the steps at f32
        self._precision = resolve_matmul_precision(config.precision_mode)
        self._demotable = (config.precision_mode == "mixed" and self._guard
                           and self._precision is not None)
        self._demoted = False
        self._build_steps()

    def _demote_to_f32(self):
        """Rebuild the jit'd steps at f32 (sentinel-triggered demotion)."""
        self._precision = None
        self._demoted = True
        self._build_steps()

    def _build_steps(self):
        model, cfg = self.model, self.config
        use_labels = self.has_labels
        wants_rng = bool(getattr(model, "wants_rng", False))

        def loss_fn(params, X, Y, rng):
            if use_labels:
                return model.loss(params, X, Y)
            if wants_rng:
                return model.loss(params, X, rng=rng)
            return model.loss(params, X)

        guard = self._guard
        precision = self._precision

        def train_step(params, opt_state, X, Y, rng, nstate):
            with matmul_precision_ctx(precision):
                (combo, parts), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params, X, Y, rng)

            def apply(tree):
                p, o = tree
                updates, o = self.optimizer.update(grads, o, p)
                p = optax.apply_updates(p, updates)
                if cfg.prox_penalty is not None:
                    p = model.apply_prox(p, cfg.prox_lam, cfg.learning_rate,
                                         cfg.prox_penalty)
                return p, o

            if guard:
                # in-graph numerics sentinel: a non-finite loss or gradient
                # skips the whole update via lax.cond (no host sync); the
                # counters ride along device-side
                (params, opt_state), nstate, _ = numerics.guarded_update(
                    (params, opt_state), grads, combo, apply, nstate)
            else:
                params, opt_state = apply((params, opt_state))
            return params, opt_state, combo, parts, nstate

        def eval_step(params, X, Y):
            with matmul_precision_ctx(precision):
                return loss_fn(params, X, Y, None)

        self._wants_rng = wants_rng
        self._train_step = jax.jit(train_step)
        self._eval_step = jax.jit(eval_step)

    # ------------------------------------------------------------------
    def validate(self, params, val_ds):
        """Average per-batch loss parts over the validation set, with loss-term
        coefficients divided out for grid-search comparability
        (ref redcliff_s_cmlp.py:1683-1703, cmlp_fm.py validate_training)."""
        sums: dict[str, float] = {}
        combo_sum = 0.0
        n = 0
        coeffs = getattr(self.model, "normalization_coeffs", lambda: {})()
        for X, Y in val_ds.batches(self.config.batch_size):
            combo, parts = self._eval_step(params, X, Y)
            combo_sum += float(combo)
            for k, v in parts.items():
                c = coeffs.get(k, 1.0)
                sums[k] = sums.get(k, 0.0) + float(v) / (c if c > 0 else 1.0)
            n += 1
        if n == 0:
            raise ValueError(
                "validation dataset yielded no batches — increase val_fraction or "
                "dataset size (empty validation would make early stopping undefined)"
            )
        out = {k: v / n for k, v in sums.items()}
        out["combo_loss"] = combo_sum / n
        return out

    def _gc_kwargs(self, track_X):
        """Per-family ``model.gc`` keyword plumbing shared by the tracker
        and the quality observatory: data-dependent estimates take the
        tracking window, and wavelet-band blocks are condensed so readouts
        compare (C, C) against the true graphs (same convention as the
        REDCLIFF trainer; ref checkpoint tracking passes
        combine_wavelet_representations=True). Covers both the
        wavelet_level families (cMLP/cLSTM FM) and DGCNN's
        num_wavelets_per_chan-expanded node axis."""
        kw = {}
        if getattr(self.model, "gc_requires_data", False):
            kw["X"] = track_X
        mcfg = self.model.config
        if (getattr(mcfg, "wavelet_level", None) is not None
                or getattr(mcfg, "num_wavelets_per_chan", 1) > 1):
            kw["combine_wavelet_representations"] = True
        return kw

    def _epoch_gc_tracking(self, params, tracker, true_GC, track_X=None):
        kw = self._gc_kwargs(track_X)
        ests = [np.asarray(g) for g in self.model.gc(params, ignore_lag=False, **kw)]
        ests_nolag = [np.asarray(g) for g in self.model.gc(params, ignore_lag=True, **kw)]
        tracker.update(true_GC, [ests], est_by_sample_lagsummed=[ests_nolag])

    def fit(self, params, train_ds, val_ds, true_GC=None, save_dir=None,
            resume=True) -> FitResult:
        cfg = self.config
        rng = np.random.default_rng(cfg.seed)
        opt_state = self.optimizer.init(params)
        tracker = None
        if true_GC is not None:
            tracker = GCProgressTracker(
                num_supervised_factors=len(true_GC),
                num_chans=true_GC[0].shape[0],
                num_factors=getattr(self.model.config, "num_factors", len(true_GC)),
            )

        histories = {
            "avg_forecasting_loss": [], "avg_adj_penalty": [], "avg_combo_loss": [],
        }
        best_it = None
        best_loss = np.inf
        best_params = params
        iter_start = 0

        ckpt_path = os.path.join(save_dir, "trainer_checkpoint.pkl") if save_dir else None
        if resume and ckpt_path:
            # durable load: CRC-verified, quarantines corrupt generations to
            # *.bad, falls back to .prev, and still reads legacy raw pickles
            ck, _src = durable_ckpt.load_checkpoint(ckpt_path)
        else:
            ck = None
        if ck is not None:
            params = jax.tree.map(jnp.asarray, ck["params"])
            opt_state = jax.tree.map(
                lambda x: jnp.asarray(x) if isinstance(x, np.ndarray) else x,
                ck["opt_state"],
            )
            # checkpoints from before the inject_hyperparams migration hold
            # a bare adam state; wrap it so resume keeps working
            opt_state = numerics.adopt_legacy_opt_state(self.optimizer,
                                                        params, opt_state)
            histories = ck["histories"]
            best_it, best_loss = ck["best_it"], ck["best_loss"]
            best_params = jax.tree.map(jnp.asarray, ck["best_params"])
            iter_start = ck["epoch"] + 1
            if tracker is not None and ck.get("tracker_state") is not None:
                tracker.__dict__.update(ck["tracker_state"])
            if ck.get("precision_demoted") and self._demotable \
                    and not self._demoted:
                # the checkpointed fit demoted mixed -> f32 mid-run; resume
                # must stay f32 (never silently re-promote)
                self._demote_to_f32()

        # ---- model-quality observatory (obs/quality.py) ------------------
        # this trainer's GC readouts are per-family host calls (model.gc
        # numpy lists), so the quality summary rides the HOST twin
        # (summarize_host) on the check_every cadence — no device work
        # beyond the readout the tracker already pays; entropy is None on
        # this path (no factor scores). Disabled per REDCLIFF_QUALITY=0;
        # a family whose readout throws disables itself (telemetry must
        # never fail a fit)
        qmon = (_obsquality.QualityMonitor(true_gc=true_GC,
                                           mode="host_readout")
                if _obsquality.enabled() else None)

        track_X = None
        if ((tracker is not None or qmon is not None)
                and getattr(self.model, "gc_requires_data", False)):
            # data-dependent GC estimates (e.g. NAVAR contribution stds) are
            # tracked on the first validation batch, like the reference's
            # per-epoch eval (ref redcliff_s_cmlp.py:1403)
            for X, _ in val_ds.batches(cfg.batch_size):
                track_X = jnp.asarray(X)
                break

        step_key = jax.random.PRNGKey(cfg.seed) if self._wants_rng else None
        step_counter = 0
        last_it = iter_start - 1
        # batches as device-side gathers from an HBM-resident copy: epochs
        # re-ship only index arrays, not batch data. Datasets without the
        # capability (shard streams, duck-typed batches() sources) keep the
        # plain call and ride the double-buffered prefetcher instead
        dev_kw = ({"device": True}
                  if getattr(train_ds, "supports_device_batches", False)
                  else {})

        def train_batch_iter():
            src = train_ds.batches(cfg.batch_size, rng=rng, **dev_kw)
            if not dev_kw and cfg.prefetch_batches > 0:
                put = jax.device_put if jax.process_count() == 1 else None
                src = pipeline.prefetch_batches(
                    src, depth=cfg.prefetch_batches, put=put)
            return src
        policy = cfg.numerics if self._guard else None
        monitor = (numerics.DivergenceMonitor(policy)
                   if policy is not None else None)
        nstate = numerics.init_numerics_state()
        prev_skipped = 0
        aborted = None
        # background checkpoint writer (completion barrier at the next save
        # and at fit end); multi-process saves stay synchronous
        writer = (durable_ckpt.AsyncCheckpointWriter()
                  if save_dir and cfg.async_checkpointing
                  and jax.process_count() == 1 else None)
        logger = MetricLogger(save_dir)
        # env-armed liveness watchdog, same contract as the grid engine
        wd = rt_watchdog.maybe_start(logger=logger)
        # try/finally: an exception mid-fit must still close the jsonl handle
        # (otherwise buffered context is lost and the fd leaks)
        try:
            logger.log("fit_start", model=type(self.model).__name__,
                       shape=obs.schema.shape_desc(self.model.config),
                       train_config=cfg, resume_epoch=iter_start)
            # analytical HBM prediction (obs/memory.py): shape metadata
            # only — live params + the best copy + optimizer state + the
            # device-batch dataset cache
            try:
                mp = _obsmem.trainer_footprint(
                    params, (opt_state,), extra_copies=1,
                    train_ds=train_ds, val_ds=val_ds)
                hr = _obsmem.check_headroom(mp["total_bytes"])
                logger.log("memory", kind="predicted",
                           epoch=iter_start - 1,
                           predicted_bytes=mp["total_bytes"],
                           params_bytes=mp["params_bytes"],
                           opt_bytes=mp["opt_bytes"],
                           dataset_bytes=mp["dataset_bytes"],
                           fits=hr["fits"], bytes_limit=hr["bytes_limit"],
                           budget_bytes=hr["budget_bytes"],
                           headroom_bytes=hr["headroom_bytes"],
                           backend=hr["backend"])
            except Exception:  # noqa: BLE001 — telemetry must not fail fits
                pass
            # bounded profiler capture window (obs/profiling.py): replaces
            # the old unbounded whole-fit profiler_trace wrap
            pw = _profiling.window_for(cfg, run_dir=save_dir,
                                       max_iter=cfg.max_iter)
            with pw, wd:
                for it in range(iter_start, cfg.max_iter):
                    rt_watchdog.stamp("epoch_engine")
                    pw.on_epoch_start(it)
                    t_epoch0 = time.perf_counter()
                    last_it = it
                    for X, Y in train_batch_iter():
                        rt_watchdog.stamp("batch_loop")
                        step_rng = (jax.random.fold_in(step_key, step_counter)
                                    if self._wants_rng else None)
                        X = faultinject.poison_batch(X, step_counter)
                        skip = faultinject.skip_update(step_counter)
                        step_counter += 1
                        if skip:
                            continue
                        params, opt_state, _, _, nstate = self._train_step(
                            params, opt_state, X, Y, step_rng, nstate)

                    if tracker is not None:
                        self._epoch_gc_tracking(params, tracker, true_GC, track_X)

                    val = self.validate(params, val_ds)
                    histories["avg_forecasting_loss"].append(val.get("forecasting_loss", 0.0))
                    histories["avg_adj_penalty"].append(val.get("adj_l1_penalty", 0.0))
                    histories["avg_combo_loss"].append(val["combo_loss"])

                    if hasattr(self.model, "validation_criteria"):
                        criteria = float(self.model.validation_criteria(params, val))
                    else:
                        criteria = val["combo_loss"]

                    logger.log("epoch", epoch=it, criteria=criteria,
                               epoch_ms=round(
                                   (time.perf_counter() - t_epoch0) * 1e3, 3),
                               **val,
                               **(tracker.latest_as_dict() if tracker else {}))
                    # live graph-quality summary on the check cadence
                    # (obs/quality.py host twin; single lane id 0)
                    if qmon is not None and it % cfg.check_every == 0:
                        try:
                            mats = [np.asarray(g) for g in self.model.gc(
                                params, ignore_lag=False,
                                **self._gc_kwargs(track_X))]
                            qrec = qmon.update(
                                it, _obsquality.summarize_host(mats),
                                np.zeros(1, np.int32))
                            logger.log("quality", **qrec)
                        except Exception:  # noqa: BLE001 — telemetry must
                            qmon = None    # never fail a fit
                    pw.on_epoch_end(it, logger=logger)

                    if monitor is not None:
                        nhost = numerics.numerics_summary(nstate)
                        if nhost["skipped"] > prev_skipped:
                            logger.log("anomaly", epoch=it,
                                       cause="nonfinite_grad",
                                       epoch_skipped_steps=nhost["skipped"]
                                       - prev_skipped, **nhost)
                        prev_skipped = nhost["skipped"]
                        action = monitor.check(it, nhost, criteria)
                        if action.kind == "rollback":
                            # rollback() returns the snapshot with its
                            # injected learning rates already backed off
                            # (compounding across repeated rollbacks)
                            params, opt_state = monitor.rollback()
                            nstate = numerics.reset_consecutive(nstate)
                            logger.log(
                                "numerics", kind="rollback", epoch=it,
                                cause=action.cause,
                                restored_epoch=monitor.snapshot_epoch,
                                lr_scale=monitor.lr_scale,
                                learning_rates=numerics.current_learning_rates(
                                    opt_state),
                                rollbacks=monitor.rollbacks)
                            if self._demotable and not self._demoted:
                                # precision cliff: a mixed-mode rollback
                                # auto-demotes the fit to f32
                                self._demote_to_f32()
                                logger.log("precision", kind="demote",
                                           epoch=it, cause=action.cause,
                                           mode_from="mixed", mode_to="f32",
                                           rollbacks=monitor.rollbacks,
                                           **nhost)
                            continue  # re-run from the snapshot; no best/ckpt update
                        if action.kind == "abort":
                            aborted = action.cause
                            # numerics-abort escalation dumps the crash
                            # flight recorder (last spans/events per
                            # component) next to metrics.jsonl — the
                            # post-mortem no longer depends on what
                            # happened to be flushed
                            fr = obs.flight.dump_for_logger(
                                logger, reason="numerics_abort",
                                extra={"epoch": it, "cause": action.cause})
                            logger.log("numerics", kind="abort", epoch=it,
                                       cause=action.cause,
                                       flight_record=fr, **nhost)
                            break
                        if np.isfinite(criteria):
                            monitor.note_good(it, (params, opt_state))

                    if criteria < best_loss:
                        best_loss = criteria
                        best_it = it
                        best_params = params
                    elif best_it is not None and (it - best_it) == cfg.lookback * cfg.check_every:
                        if cfg.verbose:
                            print("Stopping early")
                        break

                    if it % cfg.check_every == 0 and save_dir:
                        self._save_checkpoint(save_dir, it, best_params, opt_state, params,
                                              histories, best_it, best_loss, tracker,
                                              writer=writer)
                    if cfg.verbose and it % max(1, cfg.check_every) == 0:
                        print(f"epoch {it}: val_combo={val['combo_loss']:.5f} criteria={criteria:.5f}")

            final_val = self.validate(best_params, val_ds)
            # measured watermark where the backend reports it (None on CPU)
            if _obsmem.polling_enabled():
                wm = _obsmem.poll_watermark()
                if wm is not None:
                    logger.log("memory", kind="measured", epoch=last_it,
                               bytes_in_use=wm["bytes_in_use"],
                               peak_bytes=wm["peak_bytes"],
                               bytes_limit=wm["bytes_limit"],
                               n_devices=wm["n_devices"],
                               device_kind=wm["device_kind"])
            logger.log("fit_end", best_it=best_it if best_it is not None else 0,
                       best_loss=float(best_loss),
                       final_val_loss=final_val["combo_loss"],
                       aborted=aborted,
                       quality=(qmon.snapshot()
                                if qmon is not None and qmon.windows
                                else None))
        finally:
            rt_watchdog.retire("epoch_engine")
            rt_watchdog.retire("batch_loop")
            logger.close()
            if writer is not None:
                # join the in-flight write on EVERY exit path: a background
                # write failure re-raises on clean exits and is warned (not
                # masked) while another exception is already propagating
                writer.__exit__(*sys.exc_info())
        if save_dir:
            # stamp the actual last trained epoch so a later resume with a larger
            # max_iter continues from where training really stopped; the resumable
            # state keeps the LAST iterate (params + its opt_state), while
            # final_best_model.bin holds best_params. (Periodic background
            # writes were already joined — and their failures raised — by
            # the finally block's writer.__exit__ above.)
            self._save_checkpoint(save_dir, last_it, best_params, opt_state,
                                  params, histories, best_it, best_loss,
                                  tracker, writer=writer)
            if writer is not None:
                writer.wait()  # the final state must be durable on return
        params = best_params
        return FitResult(
            params=params, best_it=best_it if best_it is not None else 0,
            best_loss=float(best_loss), histories=histories, tracker=tracker,
            final_val_loss=final_val["combo_loss"], aborted=aborted,
        )

    def _save_checkpoint(self, save_dir, it, best_params, opt_state, params,
                         histories, best_it, best_loss, tracker, writer=None):
        """All three artifacts go through the durable checkpoint writer
        (atomic tmp+replace, CRC header, trailing .prev generation) — a
        preemption mid-write can no longer tear the resume state.

        ``writer`` (AsyncCheckpointWriter) moves the device->host
        materialization + writes onto a background thread; the main thread
        only deep-copies the host-mutable state (histories/tracker — the
        loop keeps appending to the live objects) and kicks off the async
        device->host copies. Sharing the device trees with the thread is
        safe: this trainer's steps do not donate buffers."""
        if writer is not None and jax.process_count() == 1:
            # deep copies only on the async path, where the background
            # thread would otherwise read objects the loop keeps appending
            hist_snap = copy.deepcopy(histories)
            tracker_meta = (copy.deepcopy(tracker.as_dict())
                            if tracker is not None else None)
            tracker_state = (None if tracker is None
                             else copy.deepcopy(dict(tracker.__dict__)))
            for tree in (best_params, params, opt_state):
                for leaf in jax.tree.leaves(tree):
                    if hasattr(leaf, "copy_to_host_async"):
                        leaf.copy_to_host_async()
            writer.submit(lambda: self._write_checkpoint_files(
                save_dir, it, best_params, opt_state, params, hist_snap,
                best_it, best_loss, tracker_meta, tracker_state))
        else:
            self._write_checkpoint_files(
                save_dir, it, best_params, opt_state, params, histories,
                best_it, best_loss,
                tracker.as_dict() if tracker is not None else None,
                None if tracker is None else dict(tracker.__dict__))

    def _write_checkpoint_files(self, save_dir, it, best_params, opt_state,
                                params, histories, best_it, best_loss,
                                tracker_meta, tracker_state):
        os.makedirs(save_dir, exist_ok=True)
        save_model(save_dir, self.model, best_params)
        meta = {
            "epoch": it,
            "best_loss": float(best_loss),
            "best_it": best_it,
            **histories,
        }
        if tracker_meta is not None:
            meta.update(tracker_meta)
        durable_ckpt.write_checkpoint(
            os.path.join(save_dir,
                         "training_meta_data_and_hyper_parameters.pkl"), meta)
        durable_ckpt.write_checkpoint(
            os.path.join(save_dir, "trainer_checkpoint.pkl"),
            {
                "epoch": it,
                "params": jax.tree.map(np.asarray, params),
                "best_params": jax.tree.map(np.asarray, best_params),
                "opt_state": jax.tree.map(
                    lambda x: np.asarray(x) if isinstance(x, jnp.ndarray) else x,
                    opt_state,
                ),
                "histories": histories,
                "best_it": best_it,
                "best_loss": float(best_loss),
                # sentinel-triggered precision demotion (mixed -> f32)
                "precision_demoted": self._demoted,
                "tracker_state": tracker_state,
            },
        )
