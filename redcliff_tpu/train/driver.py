"""L5 experiment drivers: array-task grid search and vmapped grid execution.

Rebuilds the reference's train-script template (canonical:
/root/reference/train/REDCLIFF_S_CMLP_d4IC_BSCgs1.py): hyperparameter
cartesian products indexed by an array-task id (SLURM-launchable per host),
hparam-encoded run-folder names the eval layer parses back, auto-resume on
existing artifacts, and the dataset-dependent coefficient rescaling done in
every driver (ref :98-105).

TPU-first addition: ``run_coefficient_grid`` trains many coefficient
variations of one REDCLIFF model concurrently — the train step vmapped over
the grid axis and sharded over the device mesh (parallel.grid), replacing
one-process-per-grid-point SLURM arrays (SURVEY.md §2.8, §7 delta 3).
"""
from __future__ import annotations

import os
import random
from itertools import product

import numpy as np

from ..utils.config import read_in_data_args, read_in_model_args
from .orchestration import (
    call_model_fit_method,
    create_model_instance,
    get_data_for_model_training,
)

__all__ = [
    "run_folder_name",
    "rescale_dataset_dependent_coefficients",
    "kick_off_model_training_experiment",
    "set_up_and_run_experiments",
    "run_coefficient_grid",
]


def run_folder_name(args_dict):
    """Hyperparameter-encoded run folder (ref :19-30); the eval layer locates
    runs by dataset/fold substrings of this name."""
    cd = args_dict.get("coeff_dict", {})

    def fmt(v, clip=None):
        s = str(v).replace(".", "-")
        return s[:clip] if clip else s

    parts = [str(args_dict["model_type"]), str(args_dict["data_set_name"])]
    if "FORECAST_COEFF" in cd:
        parts.append("fc" + fmt(cd["FORECAST_COEFF"]))
    if "FACTOR_SCORE_COEFF" in cd:
        parts.append("fsc" + fmt(cd["FACTOR_SCORE_COEFF"]))
    if "FACTOR_COS_SIM_COEFF" in cd:
        parts.append("fcsc" + fmt(cd["FACTOR_COS_SIM_COEFF"], 8))
    if "FACTOR_WEIGHT_L1_COEFF" in cd:
        parts.append("fwl1c" + fmt(cd["FACTOR_WEIGHT_L1_COEFF"]))
    if "ADJ_L1_REG_COEFF" in cd:
        parts.append("al1c" + fmt(cd["ADJ_L1_REG_COEFF"], 8))
    return "_".join(parts)


def rescale_dataset_dependent_coefficients(args_dict):
    """The per-driver coefficient normalization (ref :98-105):
    FACTOR_COS_SIM_COEFF is divided by the number of factor pairs' triangular
    sum, ADJ_L1_REG_COEFF by K*sqrt(C^2 - 1), and the stopping-criteria
    coefficients mirror the loss coefficients."""
    cd = args_dict["coeff_dict"]
    K = args_dict["num_factors"]
    C = args_dict["num_channels"]
    if "FACTOR_COS_SIM_COEFF" in cd and K > 1:
        cd["FACTOR_COS_SIM_COEFF"] = (
            cd["FACTOR_COS_SIM_COEFF"] / sum(1.0 * i for i in range(1, K)))
    if "ADJ_L1_REG_COEFF" in cd:
        cd["ADJ_L1_REG_COEFF"] = (
            cd["ADJ_L1_REG_COEFF"] * (1.0 / K)
            * (1.0 / np.sqrt(C ** 2.0 - 1.0)))
    args_dict["stopping_criteria_forecast_coeff"] = cd.get(
        "FORECAST_COEFF", 1.0)
    args_dict["stopping_criteria_factor_coeff"] = cd.get(
        "FACTOR_SCORE_COEFF", 1.0)
    args_dict["stopping_criteria_cosSim_coeff"] = cd.get(
        "FACTOR_COS_SIM_COEFF", 1.0)
    return args_dict


def kick_off_model_training_experiment(args_dict, resume_training=False,
                                       grid_search=False, seed=0):
    """One training run end-to-end (ref :17-63): resolve/clean the run dir,
    auto-resume when artifacts exist, load data, build the model, fit."""
    save_dir = os.path.join(args_dict["save_root_path"],
                            run_folder_name(args_dict))
    args_dict["save_path"] = save_dir
    if not os.path.exists(save_dir):
        os.makedirs(save_dir)
    elif "final_best_model.bin" in os.listdir(save_dir):
        resume_training = True
    else:
        for f in os.listdir(save_dir):
            path = os.path.join(save_dir, f)
            if os.path.isfile(path):
                os.remove(path)

    train_ds, val_ds = get_data_for_model_training(args_dict,
                                                   grid_search=grid_search)
    model = create_model_instance(
        args_dict,
        employ_version_with_smoothing_loss="Smooth" in
        args_dict["model_type"] or "WithSmoothing" in args_dict["model_type"])
    params, result = call_model_fit_method(
        model, args_dict, train_ds, val_ds, save_dir=save_dir, seed=seed)
    return model, params, result


def set_up_and_run_experiments(args_dict, files_of_cached_model_args,
                               files_of_cached_data_args,
                               possible_model_types, possible_data_sets,
                               shuffle_seed=0, task_id=None,
                               grid_search=False):
    """Array-task entry point (ref :66-110): pick one (model_type, dataset)
    from the shuffled cartesian product by task id (1-based, from
    SLURM_ARRAY_TASK_ID when not given), read its cached-args, rescale
    coefficients, and run."""
    combos = list(product(possible_model_types, possible_data_sets))
    random.Random(shuffle_seed).shuffle(combos)
    if task_id is None:
        task_id = int(os.environ["SLURM_ARRAY_TASK_ID"])
    model_type, data_set_name = combos[task_id - 1]

    args_dict["model_type"] = model_type
    matches = [x for x in files_of_cached_model_args if model_type in x]
    assert len(matches) == 1, (model_type, matches)
    args_dict["model_cached_args_file"] = matches[0]

    args_dict["data_set_name"] = data_set_name
    matches = [x for x in files_of_cached_data_args if data_set_name in x]
    assert len(matches) == 1, (data_set_name, matches)
    args_dict["data_cached_args_file"] = matches[0]

    read_in_model_args(args_dict)
    read_in_data_args(args_dict)
    if "coeff_dict" in args_dict and "REDCLIFF" in model_type:
        rescale_dataset_dependent_coefficients(args_dict)

    kick_off_model_training_experiment(args_dict, grid_search=grid_search)
    return task_id


def run_coefficient_grid(model, train_config, grid_points, train_ds, val_ds,
                         key=None, mesh=None, max_iter=None,
                         init_point_params=None, checkpoint_dir=None,
                         checkpoint_every=None, run_dir=None,
                         fit_deadline_s=None, grid_deadline_s=None,
                         true_gc=None):
    """Train G coefficient/optimizer variations of one REDCLIFF model
    concurrently on the device mesh (see parallel.grid.RedcliffGridRunner).

    grid_points: list of dicts over the grid axes (e.g. {"gen_lr": ...,
    "factor_cos_sim_coeff": ...}).  Returns the GridResult with per-point
    best params/criteria.

    init_point_params: ONE unstacked parameter pytree replicated across the
    grid axis — the SLURM-array pattern's initialization (every per-point
    process seeds identically, ref :122-127); default = independent per-point
    seeds from ``key``.

    checkpoint_dir + checkpoint_every: periodic full-state checkpoints with
    bit-identical resume (RedcliffGridRunner.fit) — the preemption story for
    long grid runs. Checkpoints are durable (atomic+CRC+.prev generation,
    corrupt files quarantined to *.bad) and carry a full compatibility
    fingerprint, and SIGTERM/SIGINT triggers a final checkpoint
    (runtime/preempt.py) before raising ``Preempted``.

    Graceful degradation: grid points whose validation loss goes non-finite,
    or whose in-graph numerics guard reports a stuck lane (consecutive
    non-finite gradients), are quarantined (lane frozen; the rest of the
    grid keeps training) and recorded to ``failures.json`` in ``run_dir``
    (default: checkpoint_dir) — one {"point", "epoch", "cause", "hparams"}
    record per quarantined point (cause: ``nonfinite_grad`` vs
    ``nonfinite_val``), plus the run context. No file is written when the
    run has no failures.

    Wall-clock budgets (ARCHITECTURE.md "Liveness & supervision"):
    ``fit_deadline_s`` (scalar or per-point) evicts over-budget lanes into
    ``failures`` with cause ``"deadline"`` after forcing a checkpoint;
    ``grid_deadline_s`` ends the whole fit resumably
    (:class:`~redcliff_tpu.runtime.preempt.DeadlineExceeded`, supervisor
    taxonomy code 20). Under ``python -m redcliff_tpu.supervise`` with
    ``REDCLIFF_WATCHDOG`` set, a hung fit is detected, hard-exited, and
    restarted from the durable checkpoint bit-identically.

    Elastic scheduling (ARCHITECTURE.md "Elastic grid scheduling & compile
    caching"): with the default ``train_config.compaction``/``g_bucket``
    the grid's execution width rides a power-of-two bucket ladder and
    COMPACTS as lanes early-stop or quarantine — results and
    ``failures.json`` records stay indexed by original point id — and
    ``train_config.compile_cache_dir`` (or ``REDCLIFF_COMPILE_CACHE``)
    enables the persistent, versioned XLA compilation cache so restarted
    attempts warm-start instead of recompiling every grid program.

    Host-fault tolerance (ARCHITECTURE.md "Elastic re-meshing & host-fault
    tolerance"): ``mesh="auto"`` builds the largest viable mesh over the
    VISIBLE devices — ``jax.devices()`` capped by ``REDCLIFF_MESH_DEVICES``,
    the knob the supervisor degrades after a ``host_lost`` exit — so a
    supervised driver resumes a dropped-host sweep on the surviving devices
    automatically: the grid engine re-shards the checkpointed lanes onto
    the smaller mesh (structured ``remesh`` event in metrics.jsonl) and
    results keep reporting under original point ids.

    Model-quality observatory (obs/quality.py, ``REDCLIFF_QUALITY``):
    ``true_gc`` — the dataset's ground-truth graphs (synthetic sVAR /
    DREAM4; list of ``(C, C[, L])`` arrays) — adds live per-lane
    AUROC/AUPR to the per-check-window ``quality`` events and the
    ``dispatch_stats["quality"]`` convergence snapshot. Telemetry only:
    results are bit-identical with or without it.
    """
    import jax

    from ..parallel.grid import GridSpec, RedcliffGridRunner

    grid_points = list(grid_points)
    if isinstance(mesh, str):
        if mesh != "auto":
            raise ValueError(f"mesh must be a Mesh, None, or 'auto'; "
                             f"got {mesh!r}")
        from ..parallel import remesh as _remesh

        mesh = _remesh.visible_mesh(n_lanes=len(grid_points))
    spec = GridSpec(points=grid_points, fit_deadline_s=fit_deadline_s,
                    grid_deadline_s=grid_deadline_s)
    runner = RedcliffGridRunner(model, train_config, spec, mesh=mesh)
    key = key if key is not None else jax.random.PRNGKey(train_config.seed)
    init = (runner.init_grid_from(init_point_params)
            if init_point_params is not None else None)
    # the stacked init is built here solely for this fit: hand ownership over
    # instead of paying a defensive copy of the whole grid state
    result = runner.fit(key, train_ds, val_ds, max_iter=max_iter,
                        init_params=init, copy_init=False,
                        checkpoint_dir=checkpoint_dir,
                        checkpoint_every=checkpoint_every,
                        true_gc=true_gc)
    failures_dir = run_dir if run_dir is not None else checkpoint_dir
    if result.failures and failures_dir is not None \
            and jax.process_index() == 0:
        import json

        os.makedirs(failures_dir, exist_ok=True)
        with open(os.path.join(failures_dir, "failures.json"), "w") as f:
            json.dump({"grid_size": len(spec.points),
                       "training_mode": model.config.training_mode,
                       "seed": train_config.seed,
                       "failures": result.failures}, f, indent=2)
    return result
