"""Training layer: generic and REDCLIFF trainers, orchestration dispatch,
and experiment drivers (rebuilds /root/reference/train/ + the fit/dispatch
half of general_utils/model_utils.py)."""
from .driver import (
    kick_off_model_training_experiment,
    rescale_dataset_dependent_coefficients,
    run_coefficient_grid,
    run_folder_name,
    set_up_and_run_experiments,
)
from .orchestration import (
    call_model_fit_method,
    create_model_instance,
    get_data_for_model_training,
)
from .redcliff_trainer import RedcliffTrainConfig, RedcliffTrainer
from .trainer import FitResult, TrainConfig, Trainer, load_model, save_model

__all__ = [
    "kick_off_model_training_experiment",
    "rescale_dataset_dependent_coefficients",
    "run_coefficient_grid", "run_folder_name", "set_up_and_run_experiments",
    "call_model_fit_method", "create_model_instance",
    "get_data_for_model_training",
    "RedcliffTrainConfig", "RedcliffTrainer",
    "FitResult", "TrainConfig", "Trainer", "load_model", "save_model",
]
