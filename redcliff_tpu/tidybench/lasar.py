"""LASAR — LASso Auto-Regression with subsample-averaged debiased refits.

Behavioral equivalent of /root/reference/tidybench/lasar.py:16-98: for the full
series and many bootstrap subsamples, run a per-target, per-lag-block
cross-validated lasso (LARS path) to select parents, then refit ordinary least
squares on the selected columns to debias; average the absolute refit
coefficients over subsamples and aggregate over lags.

Kept deliberately: the reference selects only variables with *positive* lasso
coefficients (``coef_ > 0``) and fits lag blocks sequentially against the
running residual — both are part of the published algorithm's behavior.
"""
from __future__ import annotations

import numpy as np

from redcliff_tpu.tidybench.slarac import _DEFAULT_FRACTIONS
from redcliff_tpu.tidybench.utils import common_pre_post_processing

__all__ = ["lasar"]


def _lasso_var_coeffs(data, maxlags, cv, rng, bootstrap_rows=None):
    from sklearn.linear_model import LassoLarsCV

    T, N = data.shape
    Y = data[maxlags:]
    Z = np.concatenate([data[maxlags - k : T - k] for k in range(1, maxlags + 1)],
                       axis=1)
    if bootstrap_rows is not None:
        idx = rng.integers(0, Y.shape[0], size=bootstrap_rows)
        Y, Z = Y[idx], Z[idx]

    scores = np.zeros((N, N * maxlags))
    selector = LassoLarsCV(cv=cv, n_jobs=1)
    for j in range(N):
        target = Y[:, j].copy()
        selected = np.zeros(N * maxlags, dtype=bool)
        for lag in range(maxlags):
            sl = slice(N * lag, N * (lag + 1))
            selector.fit(Z[:, sl], target)
            selected[sl] = selector.coef_ > 0
            target -= selector.predict(Z[:, sl])
        ZZ = Z[:, selected]
        if ZZ.shape[1]:
            beta, *_ = np.linalg.lstsq(ZZ.T @ ZZ, ZZ.T @ Y[:, j], rcond=None)
            scores[j, selected] = beta
    return scores


@common_pre_post_processing
def lasar(data, maxlags=1, n_subsamples=100, subsample_sizes=_DEFAULT_FRACTIONS,
          cv=5, aggregate_lags=None, rng=None):
    """Score lagged links via subsample-averaged lasso-selected OLS refits.

    ``aggregate_lags`` maps (N_to, maxlags, N_from) → N×N (default max over
    lags, transposed so (i, j) reads X_i → X_j); ``rng`` seeds the subsampling.
    """
    data = np.asarray(data, dtype=np.float64)
    rng = np.random.default_rng(rng)
    if aggregate_lags is None:
        aggregate_lags = lambda x: x.max(axis=1).T  # noqa: E731
    T, N = data.shape

    scores = np.abs(_lasso_var_coeffs(data, maxlags, cv, rng))
    fractions = rng.choice(np.asarray(subsample_sizes), size=n_subsamples)
    for frac in fractions:
        rows = int(np.round(frac * T))
        scores += np.abs(
            _lasso_var_coeffs(data, maxlags, cv, rng, bootstrap_rows=rows))
    scores /= n_subsamples + 1
    return aggregate_lags(scores.reshape(N, maxlags, N))
