"""QRBS — Quantiles of Ridge-regressed Bootstrap Samples.

Behavioral equivalent of /root/reference/tidybench/qrbs.py:14-63: regress the
first difference of the series on the stacked lagged values with ridge
regression over many bootstrap samples, aggregate |coefficients| over lags, and
take a per-link quantile across the bootstrap distribution. The returned matrix
has the parents of variable j in column j (scores are transposed at the end).

The ridge solve (with intercept, matching sklearn's default) is done in closed
form for all N targets at once.
"""
from __future__ import annotations

import numpy as np

from redcliff_tpu.tidybench.utils import common_pre_post_processing

__all__ = ["qrbs"]


def _ridge_fit_coefs(X, y, alpha):
    """Intercept-bearing ridge: center, solve (XᵀX+αI)β = Xᵀy → (targets, feats)."""
    Xc = X - X.mean(axis=0)
    yc = y - y.mean(axis=0)
    G = Xc.T @ Xc
    G[np.diag_indices_from(G)] += alpha
    beta = np.linalg.solve(G, Xc.T @ yc)
    return beta.T


@common_pre_post_processing
def qrbs(data, lags=1, alpha=0.005, q=0.75, n_resamples=600, rng=None):
    """Bootstrap-ridge scoring of lagged links.

    ``q`` picks the quantile of the per-link |coefficient| bootstrap
    distribution (1 = max effect, 0.5 = median). ``rng`` is a numpy Generator
    (or seed) for the bootstrap draws.
    """
    data = np.asarray(data, dtype=np.float64)
    rng = np.random.default_rng(rng)
    T, N = data.shape

    # Target: one-step difference; design: lag blocks ordered t−1, t−2, … t−lags.
    y = np.diff(data, axis=0)[lags - 1 :]
    X = np.concatenate([data[lags - d : T - d] for d in range(1, lags + 1)], axis=1)

    k = int(np.floor(T * 0.7))
    per_boot = np.empty((n_resamples, N, N))
    for b in range(n_resamples):
        idx = rng.integers(0, X.shape[0], size=k)
        coefs = _ridge_fit_coefs(X[idx], y[idx], alpha)  # (N, lags·N)
        per_boot[b] = np.abs(coefs.reshape(N, lags, N)).sum(axis=1)

    scores = np.quantile(per_boot, q, axis=0)
    return scores.T  # parents of j in column j
