"""SELVAR — Selective auto-regressive model (hill-climbed structure + lags).

Equivalent of /root/reference/tidybench/selvar.py:20-60 and its Fortran core
selvarF.f (SLVAR/GTPRSS/GTCOEF/GTRSS/GTSTAT). The compute core here is C++
(native/selvar.cpp, built on demand and bound with ctypes); a numpy
implementation of the identical algorithm serves as fallback and as the parity
oracle in the tests.

Algorithm: for each target variable j, hill-climb over per-source lag
assignments A[i, j] ∈ {0..maxlags} (0 = no edge), scored by the leave-one-out
PRESS statistic Σ_t (e_t / (1 − h_t))² accumulated over batches of consecutive
time points; report batch-averaged absolute OLS coefficients of the selected
model as edge scores.

Deliberate divergence from the Fortran: its DORGQR call formed only the first
NV rows of Q yet read all BS workspace rows as leverages (selvarF.f:193-204),
so most rows' h_t were Householder remnants; both backends here compute the
true PRESS leverage h_t = d_tᵀ(DᵀD)⁻¹d_t for every row, which can select
different structures on borderline candidates (in favor of the correct
statistic).
"""
from __future__ import annotations

import numpy as np

from redcliff_tpu.tidybench import native
from redcliff_tpu.tidybench.utils import common_pre_post_processing

__all__ = ["selvar", "slvar", "gtcoef", "gtstat"]


# ---------------------------------------------------------------- numpy core

def _clamp_ml(ml, T):
    return 1 if (ml >= T or ml < 1) else ml


def _clamp_bs(bs_box, T, ml):
    """The Fortran clamps the caller's batch size in place on every scoring
    call, so the clamp persists as the adaptive max-lag grows; ``bs_box`` is a
    one-element list emulating that in-out argument."""
    if bs_box[0] < 0:
        bs_box[0] = (T - ml) // (-bs_box[0])
    if bs_box[0] == 0:  # documented-but-unhandled case in the Fortran
        bs_box[0] = T - ml
    if bs_box[0] > T - ml:
        bs_box[0] = T - ml
    return bs_box[0]


def _design(X, j, ml, bs, batch, src, lags):
    base = ml + batch * bs
    t0 = base + np.arange(bs)
    D = np.ones((bs, 1 + len(src)))
    for s, (i, l) in enumerate(zip(src, lags)):
        D[:, 1 + s] = X[t0 - l, i]
    return D, X[t0, j]


def _press_np(X, ml, bs_box, A, j):
    T, N = X.shape
    ml = _clamp_ml(ml, T)
    bs = _clamp_bs(bs_box, T, ml)
    src = [i for i in range(N) if A[i, j] > 0]
    lags = [A[i, j] for i in src]
    p = 1 + len(src)
    if p > bs:
        return -1.0
    nf = (T - ml) // bs
    if nf < 1:
        return -1.0
    score = 0.0
    for k in range(nf):
        D, y = _design(X, j, ml, bs, k, src, lags)
        G = D.T @ D
        try:
            L = np.linalg.cholesky(G)
        except np.linalg.LinAlgError:
            return -1.0
        beta = np.linalg.solve(L.T, np.linalg.solve(L, D.T @ y))
        resid = y - D @ beta
        Z = np.linalg.solve(L, D.T)          # (p, bs); h_t = ‖Z[:, t]‖²
        h = np.einsum("pt,pt->t", Z, Z)
        score += float(np.sum((resid / (1.0 - h)) ** 2))
    return score


def _gtcoef_np(X, ml, bs, A, job="ABS", nrm=0):
    T, N = X.shape
    # a lag larger than ml would index before the series start; raise ml from
    # the lag matrix (the reference's GTCOEF read out of bounds here)
    ml = max(ml, int(np.max(A)) if np.size(A) else 0)
    ml = _clamp_ml(ml, T)
    bs_box = [bs]
    bs = _clamp_bs(bs_box, T, ml)
    nf = (T - ml) // bs
    B = np.zeros((N, N))
    V = np.zeros(N)
    for j in range(N):
        src = [i for i in range(N) if A[i, j] > 0]
        lags = [A[i, j] for i in src]
        for k in range(nf):
            D, y = _design(X, j, ml, bs, k, src, lags)
            try:
                beta = np.linalg.solve(D.T @ D, D.T @ y)
            except np.linalg.LinAlgError:
                continue
            V[j] += float(np.sum((y - D @ beta) ** 2)) / (bs * nf)
            for s, i in enumerate(src):
                c = beta[1 + s]
                v = abs(c) if job == "ABS" else c * c if job == "SQR" else c
                B[i, j] += v / nf
    if nrm > 0:
        with np.errstate(divide="ignore", invalid="ignore"):
            B = B / np.sqrt(B**2 + V[None, :] / V[:, None])
    return B


def _gtrss_np(X, ml, bs, A, j):
    T, N = X.shape
    # guard for direct callers (no-op when the caller already raised ml, as
    # the gtstat frontend does before computing its nf/bs normalization)
    ml = max(ml, int(np.max(A)) if np.size(A) else 0)
    ml = _clamp_ml(ml, T)
    bs_box = [bs]
    bs = _clamp_bs(bs_box, T, ml)
    nf = (T - ml) // bs
    src = [i for i in range(N) if A[i, j] > 0]
    lags = [A[i, j] for i in src]
    score = 0.0
    for k in range(nf):
        D, y = _design(X, j, ml, bs, k, src, lags)
        try:
            beta = np.linalg.solve(D.T @ D, D.T @ y)
        except np.linalg.LinAlgError:
            continue
        score += float(np.sum((y - D @ beta) ** 2))
    return score / (nf * bs)


def _slvar_np(X, bs, ml, mxitr):
    T, N = X.shape
    adaptive = ml < 1
    ml = _clamp_ml(ml, T)
    bs_box = [bs]
    _clamp_bs(bs_box, T, ml)
    A = np.zeros((N, N), dtype=np.int32)
    itr = 0
    if mxitr != 0:
        for j in range(N):
            itr = 0
            if adaptive:
                ml = 1
            scr = _press_np(X, ml, bs_box, A, j)
            improved = True
            while improved and (mxitr < 0 or itr < mxitr):
                itr += 1
                improved = False
                best, ibst, kbst = scr, -1, 0
                for K in range(ml + 1):
                    for i in range(N):
                        cur = A[i, j]
                        if K == cur:
                            continue
                        A[i, j] = K
                        s = _press_np(X, ml, bs_box, A, j)
                        A[i, j] = cur
                        if s >= 0.0 and s < best:
                            best, ibst, kbst = s, i, K
                if ibst >= 0:
                    A[ibst, j] = kbst
                    scr = best
                    improved = True
                if adaptive:
                    ml = min(ml + 1, T // 2)
    B = _gtcoef_np(X, ml, bs_box[0], A, job="ABS", nrm=0)
    return B, A, itr


# ------------------------------------------------------------------- frontend

def slvar(data, batchsize=-1, maxlags=-1, mxitr=-1, backend="auto"):
    """Run the full SELVAR search. Returns (scores, lags, info).

    backend: "auto" (native C++ with numpy fallback), "native", or "numpy".
    """
    X = np.ascontiguousarray(data, dtype=np.float64)
    if backend in ("auto", "native"):
        out = native.slvar_native(X, batchsize, maxlags, mxitr)
        if out is not None:
            return out
        if backend == "native":
            raise RuntimeError("native SELVAR library could not be built")
    return _slvar_np(X, batchsize, maxlags, mxitr)


def gtcoef(data, A, maxlags=-1, batchsize=-1, job="ABS", nrm=0, backend="auto"):
    """Batch-averaged (abs/squared/raw) coefficients for a fixed lag matrix.
    ``maxlags < 1`` infers the lag ceiling from ``A`` (as ``gtstat`` does)."""
    X = np.ascontiguousarray(data, dtype=np.float64)
    if maxlags < 1:
        maxlags = max(int(np.max(A)) if np.size(A) else 1, 1)
    if backend in ("auto", "native"):
        out = native.gtcoef_native(X, maxlags, batchsize, A, job=job, nrm=nrm)
        if out is not None:
            return out
        if backend == "native":
            raise RuntimeError("native SELVAR library could not be built")
    return _gtcoef_np(X, maxlags, batchsize, np.asarray(A), job=job, nrm=nrm)


def gtstat(data, A, maxlags=-1, batchsize=-1, job="DF", backend="auto"):
    """Per-edge statistics for a fixed lag matrix: "DF" (delta-RSS), "LR"
    (log likelihood ratio), or "FS" (F statistic). Returns (stats, df)."""
    X = np.ascontiguousarray(data, dtype=np.float64)
    A = np.asarray(A, dtype=np.int32)
    if backend in ("auto", "native"):
        out = native.gtstat_native(X, maxlags, batchsize, A, job=job)
        if out is not None:
            return out
        if backend == "native":
            raise RuntimeError("native SELVAR library could not be built")
    T, N = X.shape
    # one consistent lag ceiling for the whole statistic: at least every lag
    # in A (a smaller explicit maxlags would index before the series start)
    ml = max(maxlags, int(A.max()) if A.size else 0)
    ml = _clamp_ml(ml, T)
    bs_box = [batchsize]
    bs = _clamp_bs(bs_box, T, ml)
    nf = (T - ml) // bs
    B = np.zeros((N, N))
    DF = np.zeros((N, 2), dtype=np.int32)
    for j in range(N):
        full = _gtrss_np(X, ml, bs, A, j)
        for i in range(N):
            if A[i, j] <= 0:
                continue
            DF[j, 0] += nf
            saved = A[i, j]
            A[i, j] = 0
            reduced = _gtrss_np(X, ml, bs, A, j)
            A[i, j] = saved
            if job == "FS":
                B[i, j] = (reduced - full) / full
            elif job == "LR":
                B[i, j] = (np.log(reduced) - np.log(full)) * nf * bs
            else:
                B[i, j] = reduced - full
        DF[j, 1] = DF[j, 0] - nf
    if job == "FS":
        for j in range(N):
            DF[j, 1] = bs * nf - DF[j, 0]
            DF[j, 0] = nf
            B[:, j] *= DF[j, 1]
    return B, DF


@common_pre_post_processing
def selvar(data, maxlags=1, batchsize=-1, mxitr=-1, trace=0, backend="auto"):
    """SELVAR edge scores: (i, j) scores the link X_i → X_j.

    maxlags < 0 enables the adaptive per-target lag search; batchsize < 0 sets
    the batch to the maximum available span; mxitr < 0 runs the hill climb to
    convergence. ``trace`` is accepted for signature parity and ignored.
    """
    scores, _, _ = slvar(data, batchsize=batchsize, maxlags=maxlags,
                         mxitr=mxitr, backend=backend)
    return scores
