// SELVAR — Selective auto-regressive model: native core.
//
// TPU-framework-native C++ equivalent of the reference's one in-repo native
// component, /root/reference/tidybench/selvarF.f (462 lines of Fortran 77,
// f2py + LAPACK DGELS/DORGQR). Same algorithm, fresh implementation:
//
//   * per-target hill climb over (source, lag) edge assignments, scored by the
//     leave-one-out PRESS statistic  sum_t (e_t / (1 - h_t))^2  accumulated
//     over batches of consecutive time points;
//   * optional adaptive max-lag mode (maxlags < 0): the lag ceiling starts at
//     1 and grows by one per hill-climb iteration, capped at T/2;
//   * final scores are batch-averaged |OLS coefficients| of the selected model
//     (GTCOEF "ABS"), with optional variance normalization;
//   * per-edge likelihood-ratio / F / delta-RSS statistics (GTSTAT).
//
// Where the Fortran ran LAPACK DGELS (QR least squares) + DORGQR (explicit Q
// for leverages), this uses normal equations with a Cholesky factorization:
// beta = (D'D)^-1 D'y and leverage h_t = d_t' (D'D)^-1 d_t. Deliberate
// divergence: the Fortran called DORGQR with M=NV, forming only the first NV
// rows of Q, then read all BS rows of the workspace (selvarF.f:193-204) — so
// its h_t for rows beyond NV were Householder-workspace remnants, not
// leverages. This implementation computes the true PRESS leverage for every
// row; selected structures can therefore differ from the Fortran's on
// borderline candidates (in favor of the correct statistic). Singular designs
// score as -1 (infeasible) instead of returning a partial score.
//
// Matrix conventions: X is row-major (T, N); A and B are row-major (N, N) with
// A[i*N + j] = the lag of edge i -> j (0 = edge absent).

#include <cmath>
#include <cstring>
#include <vector>

namespace {

// Cholesky factorization G = L L' in place (lower triangle). Returns false if
// G is not positive definite (singular design).
bool cholesky(std::vector<double>& G, int p) {
  for (int c = 0; c < p; ++c) {
    double diag = G[c * p + c];
    for (int k = 0; k < c; ++k) diag -= G[c * p + k] * G[c * p + k];
    if (!(diag > 0.0)) return false;
    diag = std::sqrt(diag);
    G[c * p + c] = diag;
    for (int r = c + 1; r < p; ++r) {
      double v = G[r * p + c];
      for (int k = 0; k < c; ++k) v -= G[r * p + k] * G[c * p + k];
      G[r * p + c] = v / diag;
    }
  }
  return true;
}

// Solve L z = b in place (forward), then optionally L' x = z (backward).
void forward_solve(const std::vector<double>& L, int p, double* b) {
  for (int r = 0; r < p; ++r) {
    double v = b[r];
    for (int k = 0; k < r; ++k) v -= L[r * p + k] * b[k];
    b[r] = v / L[r * p + r];
  }
}

void backward_solve(const std::vector<double>& L, int p, double* b) {
  for (int r = p - 1; r >= 0; --r) {
    double v = b[r];
    for (int k = r + 1; k < p; ++k) v -= L[k * p + r] * b[k];
    b[r] = v / L[r * p + r];
  }
}

// Batched design for target j under edge/lag assignment column A[., j].
// Row t of D is [1, X[t0 - lag_i, i] for each i with lag_i > 0], where
// t0 = ML + k*BS + t ranges over batch k's target rows.
struct Design {
  int p = 0;                 // columns (1 + #active sources)
  std::vector<int> src;      // active source indices
  std::vector<int> lag;      // their lags
};

Design active_set(const int* A, int N, int j) {
  Design d;
  d.p = 1;
  for (int i = 0; i < N; ++i) {
    int l = A[i * N + j];
    if (l > 0) {
      d.src.push_back(i);
      d.lag.push_back(l);
      ++d.p;
    }
  }
  return d;
}

// Effective batch size: the Fortran clamps the caller's BS in place on every
// scoring call (pass-by-reference), so the clamp persists across calls as the
// adaptive max-lag grows. bs is therefore in-out here too.
int clamp_bs(int* bs, int T, int ML) {
  if (*bs < 0) *bs = (T - ML) / (-*bs);
  if (*bs == 0) *bs = T - ML;  // guard: the Fortran documented but never
                               // handled BS == 0 (integer division SIGFPE)
  if (*bs > T - ML) *bs = T - ML;
  return *bs;
}

int clamp_ml(int ML, int T) { return (ML >= T || ML < 1) ? 1 : ML; }

// Leave-one-out PRESS for target j. Returns -1 if infeasible/singular.
double press_score(int T, int N, const double* X, int ML, int* bs,
                   const int* A, int j) {
  ML = clamp_ml(ML, T);
  int BS = clamp_bs(bs, T, ML);
  Design d = active_set(A, N, j);
  if (d.p > BS) return -1.0;
  int NF = (T - ML) / BS;
  if (NF < 1) return -1.0;

  std::vector<double> D(BS * d.p), G(d.p * d.p), beta(d.p), col(d.p);
  double score = 0.0;
  for (int k = 0; k < NF; ++k) {
    int base = ML + k * BS;
    for (int t = 0; t < BS; ++t) {
      D[t * d.p] = 1.0;
      for (size_t s = 0; s < d.src.size(); ++s)
        D[t * d.p + 1 + s] = X[(base + t - d.lag[s]) * N + d.src[s]];
    }
    // G = D'D, rhs = D'y
    std::fill(G.begin(), G.end(), 0.0);
    std::fill(beta.begin(), beta.end(), 0.0);
    for (int t = 0; t < BS; ++t) {
      double y = X[(base + t) * N + j];
      for (int a = 0; a < d.p; ++a) {
        beta[a] += D[t * d.p + a] * y;
        for (int b = 0; b <= a; ++b) G[a * d.p + b] += D[t * d.p + a] * D[t * d.p + b];
      }
    }
    for (int a = 0; a < d.p; ++a)
      for (int b = a + 1; b < d.p; ++b) G[a * d.p + b] = G[b * d.p + a];
    if (!cholesky(G, d.p)) return -1.0;
    forward_solve(G, d.p, beta.data());
    backward_solve(G, d.p, beta.data());
    for (int t = 0; t < BS; ++t) {
      double y = X[(base + t) * N + j], pred = 0.0;
      for (int a = 0; a < d.p; ++a) {
        pred += D[t * d.p + a] * beta[a];
        col[a] = D[t * d.p + a];
      }
      forward_solve(G, d.p, col.data());  // z = L^-1 d_t ; h_t = |z|^2
      double h = 0.0;
      for (int a = 0; a < d.p; ++a) h += col[a] * col[a];
      double e = (y - pred) / (1.0 - h);
      score += e * e;
    }
  }
  return score;
}

// OLS fit of target j on one batch. Returns false on singularity.
bool batch_ols(int T, int N, const double* X, int base, int BS, int j,
               const Design& d, std::vector<double>& beta, double* rss) {
  std::vector<double> D(BS * d.p), G(d.p * d.p);
  beta.assign(d.p, 0.0);
  for (int t = 0; t < BS; ++t) {
    D[t * d.p] = 1.0;
    for (size_t s = 0; s < d.src.size(); ++s)
      D[t * d.p + 1 + s] = X[(base + t - d.lag[s]) * N + d.src[s]];
  }
  std::fill(G.begin(), G.end(), 0.0);
  for (int t = 0; t < BS; ++t) {
    double y = X[(base + t) * N + j];
    for (int a = 0; a < d.p; ++a) {
      beta[a] += D[t * d.p + a] * y;
      for (int b = 0; b <= a; ++b) G[a * d.p + b] += D[t * d.p + a] * D[t * d.p + b];
    }
  }
  for (int a = 0; a < d.p; ++a)
    for (int b = a + 1; b < d.p; ++b) G[a * d.p + b] = G[b * d.p + a];
  if (!cholesky(G, d.p)) return false;
  forward_solve(G, d.p, beta.data());
  backward_solve(G, d.p, beta.data());
  if (rss) {
    double acc = 0.0;
    for (int t = 0; t < BS; ++t) {
      double y = X[(base + t) * N + j], pred = 0.0;
      for (int a = 0; a < d.p; ++a) pred += D[t * d.p + a] * beta[a];
      acc += (y - pred) * (y - pred);
    }
    *rss = acc;
  }
  return true;
}

}  // namespace

extern "C" {

// Batch-averaged coefficients of the selected model (GTCOEF equivalent).
// job: 0 = raw, 1 = |coef|, 2 = coef^2. nrm > 0 normalizes by residual
// variances: B_ij / sqrt(B_ij^2 + V_j / V_i).
int selvar_gtcoef(int T, int N, const double* X, int ML, int BS, const int* A,
                  int job, int nrm, double* B) {
  // A lag larger than ML would index before the series start; infer/raise ML
  // from the lag matrix (the reference's GTCOEF read out of bounds here).
  for (int idx = 0; idx < N * N; ++idx) ML = std::max(ML, A[idx]);
  ML = clamp_ml(ML, T);
  clamp_bs(&BS, T, ML);
  int NF = (T - ML) / BS;
  std::vector<double> V(N, 0.0), beta;
  std::memset(B, 0, sizeof(double) * N * N);
  for (int j = 0; j < N; ++j) {
    Design d = active_set(A, N, j);
    for (int k = 0; k < NF; ++k) {
      double rss = 0.0;
      if (!batch_ols(T, N, X, ML + k * BS, BS, j, d, beta, &rss)) continue;
      V[j] += rss / (double(BS) * NF);
      for (size_t s = 0; s < d.src.size(); ++s) {
        double c = beta[1 + s];
        double v = (job == 1) ? std::fabs(c) : (job == 2) ? c * c : c;
        B[d.src[s] * N + j] += v / NF;
      }
    }
  }
  if (nrm > 0)
    for (int j = 0; j < N; ++j)
      for (int i = 0; i < N; ++i) {
        double b = B[i * N + j];
        B[i * N + j] = b / std::sqrt(b * b + V[j] / V[i]);
      }
  return 0;
}

// Mean residual sum of squares for target j (GTRSS equivalent).
double selvar_gtrss(int T, int N, const double* X, int ML, int BS,
                    const int* A, int j) {
  // guard for direct callers: a lag in A larger than ML would index before
  // the series start (no-op when the caller already raised ML, as gtstat does
  // before computing its NF/BS normalization)
  for (int idx = 0; idx < N * N; ++idx) ML = std::max(ML, A[idx]);
  ML = clamp_ml(ML, T);
  clamp_bs(&BS, T, ML);
  int NF = (T - ML) / BS;
  Design d = active_set(A, N, j);
  std::vector<double> beta;
  double score = 0.0;
  for (int k = 0; k < NF; ++k) {
    double rss = 0.0;
    if (batch_ols(T, N, X, ML + k * BS, BS, j, d, beta, &rss)) score += rss;
  }
  return score / (double(NF) * BS);
}

// Per-edge statistics (GTSTAT equivalent). job: 0 = delta-RSS, 1 = log
// likelihood ratio, 2 = F statistic. DF is (N, 2) row-major.
int selvar_gtstat(int T, int N, const double* X, int ML, int BS, int* A,
                  int job, double* B, int* DF) {
  // one consistent lag ceiling for the whole statistic: at least every lag in
  // A (a smaller explicit ML would index before the series start), inferred
  // entirely from A when ML < 1 as in the Fortran
  for (int idx = 0; idx < N * N; ++idx) ML = std::max(ML, A[idx]);
  ML = clamp_ml(ML, T);
  clamp_bs(&BS, T, ML);
  int NF = (T - ML) / BS;
  std::memset(B, 0, sizeof(double) * N * N);
  for (int j = 0; j < N; ++j) {
    DF[j * 2] = 0;
    double full = selvar_gtrss(T, N, X, ML, BS, A, j);
    for (int i = 0; i < N; ++i) {
      if (A[i * N + j] <= 0) continue;
      DF[j * 2] += NF;
      int saved = A[i * N + j];
      A[i * N + j] = 0;
      double reduced = selvar_gtrss(T, N, X, ML, BS, A, j);
      A[i * N + j] = saved;
      if (job == 2) B[i * N + j] = (reduced - full) / full;
      else if (job == 1) B[i * N + j] = (std::log(reduced) - std::log(full)) * NF * BS;
      else B[i * N + j] = reduced - full;
    }
    DF[j * 2 + 1] = DF[j * 2] - NF;
  }
  if (job == 2)
    for (int j = 0; j < N; ++j) {
      DF[j * 2 + 1] = BS * NF - DF[j * 2];
      DF[j * 2] = NF;
      for (int i = 0; i < N; ++i) B[i * N + j] *= DF[j * 2 + 1];
    }
  return 0;
}

// Full SELVAR: hill-climb structure/lag selection + ABS coefficient scores
// (SLVAR equivalent). Returns the number of hill-climb iterations of the last
// target; fills B (scores) and A (selected lags).
int selvar_slvar(int T, int N, const double* X, int BS, int ML, int MXITR,
                 double* B, int* A) {
  int adaptive = (ML < 1) ? 1 : 0;
  ML = clamp_ml(ML, T);
  clamp_bs(&BS, T, ML);
  std::memset(A, 0, sizeof(int) * N * N);
  int itr = 0;
  if (MXITR != 0) {
    for (int j = 0; j < N; ++j) {
      itr = 0;
      if (adaptive) ML = 1;
      double scr = press_score(T, N, X, ML, &BS, A, j);
      bool improved = true;
      while (improved && (MXITR < 0 || itr < MXITR)) {
        ++itr;
        improved = false;
        double best = scr;
        int ibst = -1, kbst = 0;
        for (int K = 0; K <= ML; ++K)
          for (int i = 0; i < N; ++i) {
            int cur = A[i * N + j];
            if (K == cur) continue;
            A[i * N + j] = K;
            double s = press_score(T, N, X, ML, &BS, A, j);
            A[i * N + j] = cur;
            if (s >= 0.0 && s < best) {
              best = s;
              ibst = i;
              kbst = K;
            }
          }
        if (ibst >= 0) {
          A[ibst * N + j] = kbst;
          scr = best;
          improved = true;
        }
        if (adaptive) ML = std::min(ML + 1, T / 2);
      }
    }
  }
  selvar_gtcoef(T, N, X, ML, BS, A, /*job=*/1, /*nrm=*/0, B);
  return itr;
}

}  // extern "C"
