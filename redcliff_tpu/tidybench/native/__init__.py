"""Native (C++) core for SELVAR — build-on-demand via g++, loaded with ctypes.

The reference shipped its native component as Fortran 77 compiled through
``f2py -llapack`` (/root/reference/tidybench/selvar.py:8-10). Here the
equivalent C++ (selvar.cpp) is compiled once into a shared library next to
this file and bound with ctypes, so the framework needs no build step at
install time and no LAPACK.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "selvar.cpp")
_LIB = os.path.join(_DIR, "libselvar.so")
_lock = threading.Lock()
_lib = None
_build_error = None


def _compile():
    cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++14", _SRC, "-o", _LIB]
    subprocess.run(cmd, check=True, capture_output=True, text=True)


def load_native():
    """Return the bound ctypes library, building it if needed; None if the
    toolchain is unavailable (callers fall back to the numpy implementation)."""
    global _lib, _build_error
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        try:
            if not os.path.exists(_LIB) or (
                    os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
                _compile()
            lib = ctypes.CDLL(_LIB)
        except (OSError, subprocess.CalledProcessError) as e:  # no g++ / bad build
            _build_error = e
            return None

        dptr = ctypes.POINTER(ctypes.c_double)
        iptr = ctypes.POINTER(ctypes.c_int)
        lib.selvar_slvar.restype = ctypes.c_int
        lib.selvar_slvar.argtypes = [ctypes.c_int, ctypes.c_int, dptr,
                                     ctypes.c_int, ctypes.c_int, ctypes.c_int,
                                     dptr, iptr]
        lib.selvar_gtcoef.restype = ctypes.c_int
        lib.selvar_gtcoef.argtypes = [ctypes.c_int, ctypes.c_int, dptr,
                                      ctypes.c_int, ctypes.c_int, iptr,
                                      ctypes.c_int, ctypes.c_int, dptr]
        lib.selvar_gtrss.restype = ctypes.c_double
        lib.selvar_gtrss.argtypes = [ctypes.c_int, ctypes.c_int, dptr,
                                     ctypes.c_int, ctypes.c_int, iptr,
                                     ctypes.c_int]
        lib.selvar_gtstat.restype = ctypes.c_int
        lib.selvar_gtstat.argtypes = [ctypes.c_int, ctypes.c_int, dptr,
                                      ctypes.c_int, ctypes.c_int, iptr,
                                      ctypes.c_int, dptr, iptr]
        _lib = lib
        return _lib


def _as_c(X):
    return np.ascontiguousarray(X, dtype=np.float64)


def slvar_native(X, batchsize, maxlags, mxitr):
    """(scores, lags, info) via the C++ core, or None if it cannot be built."""
    lib = load_native()
    if lib is None:
        return None
    X = _as_c(X)
    T, N = X.shape
    B = np.zeros((N, N), dtype=np.float64)
    A = np.zeros((N, N), dtype=np.int32)
    dptr = ctypes.POINTER(ctypes.c_double)
    iptr = ctypes.POINTER(ctypes.c_int)
    info = lib.selvar_slvar(T, N, X.ctypes.data_as(dptr), int(batchsize),
                            int(maxlags), int(mxitr),
                            B.ctypes.data_as(dptr), A.ctypes.data_as(iptr))
    return B, A, info


def gtcoef_native(X, maxlags, batchsize, A, job="ABS", nrm=0):
    lib = load_native()
    if lib is None:
        return None
    X = _as_c(X)
    T, N = X.shape
    A = np.ascontiguousarray(A, dtype=np.int32)
    B = np.zeros((N, N), dtype=np.float64)
    jobcode = {"RAW": 0, "ABS": 1, "SQR": 2}[job]
    dptr = ctypes.POINTER(ctypes.c_double)
    iptr = ctypes.POINTER(ctypes.c_int)
    lib.selvar_gtcoef(T, N, X.ctypes.data_as(dptr), int(maxlags),
                      int(batchsize), A.ctypes.data_as(iptr), jobcode,
                      int(nrm), B.ctypes.data_as(dptr))
    return B


def gtstat_native(X, maxlags, batchsize, A, job="DF"):
    lib = load_native()
    if lib is None:
        return None
    X = _as_c(X)
    T, N = X.shape
    A = np.ascontiguousarray(A, dtype=np.int32)
    B = np.zeros((N, N), dtype=np.float64)
    DF = np.zeros((N, 2), dtype=np.int32)
    jobcode = {"DF": 0, "LR": 1, "FS": 2}[job]
    dptr = ctypes.POINTER(ctypes.c_double)
    iptr = ctypes.POINTER(ctypes.c_int)
    lib.selvar_gtstat(T, N, X.ctypes.data_as(dptr), int(maxlags),
                      int(batchsize), A.ctypes.data_as(iptr), jobcode,
                      B.ctypes.data_as(dptr), DF.ctypes.data_as(iptr))
    return B, DF
