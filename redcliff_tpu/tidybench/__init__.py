"""tidybench — score-based causal-discovery baselines (Table 2 stack).

TPU-framework equivalents of /root/reference/tidybench/: SLARAC, QRBS and
LASAR in vectorized numpy, and SELVAR with a native C++ core (the reference's
only in-repo native component was selvarF.f, Fortran 77 + LAPACK).

All algorithms take a (T timepoints × N variables) array and return an N×N
score matrix whose (i, j) entry scores the link X_i → X_j, and accept the
common pre/post-processing switches documented in
redcliff_tpu.tidybench.utils.common_pre_post_processing.
"""
from redcliff_tpu.tidybench.lasar import lasar
from redcliff_tpu.tidybench.qrbs import qrbs
from redcliff_tpu.tidybench.selvar import gtcoef, gtstat, selvar, slvar
from redcliff_tpu.tidybench.slarac import slarac

__all__ = ["slarac", "qrbs", "lasar", "selvar", "slvar", "gtcoef", "gtstat"]
