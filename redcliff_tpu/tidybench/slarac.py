"""SLARAC — Subsampled Linear Auto-Regression Absolute Coefficients.

Behavioral equivalent of /root/reference/tidybench/slarac.py:14-100: fit a lagged
linear VAR by least squares on the full series and on bootstrap subsamples, each
time with a randomly drawn effective lag; average the absolute coefficients and
aggregate over lags into an N×N score matrix where entry (i, j) scores the link
X_i → X_j.

This implementation is deterministic given an explicit ``rng`` (the reference
used global ``np.random``) and solves all regressions through one vectorized
normal-equations path.
"""
from __future__ import annotations

import numpy as np

from redcliff_tpu.tidybench.utils import common_pre_post_processing

__all__ = ["slarac", "INV_GOLDEN_RATIO"]

INV_GOLDEN_RATIO = 2.0 / (1.0 + np.sqrt(5.0))
_DEFAULT_FRACTIONS = tuple(INV_GOLDEN_RATIO ** (1.0 / k) for k in (1, 2, 3, 6))


def _lagged_design(data, maxlags):
    """Return targets Y (T−L, N) and design Z (T−L, 1+L·N): intercept column,
    then the lag-1 block, lag-2 block, … lag-L block."""
    T, N = data.shape
    rows = T - maxlags
    blocks = [np.ones((rows, 1))]
    for k in range(1, maxlags + 1):
        blocks.append(data[maxlags - k : T - k])
    return data[maxlags:], np.concatenate(blocks, axis=1)


def _var_abs_coeffs(Y, Z, N, maxlags, rng, bootstrap_rows=None,
                    missing_values=None):
    """One (optionally bootstrapped) VAR fit → (N, 1+L·N) coefficient matrix.

    Matches the reference's quirks deliberately: a feasibility heuristic caps
    the lag when the sample is short, a random *effective* lag ≤ max(maxlags,
    feasible) is drawn per fit, and only the first ``1 + efflag·N`` design
    columns enter the regression (the rest of the coefficient row stays 0).
    Rows containing the ``missing_values`` sentinel in either target or design
    are dropped after subsampling, as in the reference.
    """
    if bootstrap_rows is not None:
        idx = rng.integers(0, Y.shape[0], size=bootstrap_rows)
        Y, Z = Y[idx], Z[idx]
    if missing_values is not None:
        if isinstance(missing_values, float) and np.isnan(missing_values):
            bad = np.any(np.isnan(Y), axis=1) | np.any(np.isnan(Z), axis=1)
        else:
            bad = (np.any(Y == missing_values, axis=1)
                   | np.any(Z == missing_values, axis=1))
        Y, Z = Y[~bad], Z[~bad]
    rows, cols = Z.shape[0], Z.shape[1]
    feasible = maxlags
    if rows / cols < INV_GOLDEN_RATIO:
        feasible = int(np.floor((rows / INV_GOLDEN_RATIO - 1) / N))
    efflag = int(rng.integers(1, max(maxlags, feasible) + 1))
    cut = efflag * N + 1
    Zc = Z[:, :cut]
    B = np.zeros((N, Z.shape[1]))
    coef, *_ = np.linalg.lstsq(Zc.T @ Zc, Zc.T @ Y, rcond=None)
    B[:, :cut] = coef.T
    return B


@common_pre_post_processing
def slarac(data, maxlags=1, n_subsamples=200, subsample_sizes=_DEFAULT_FRACTIONS,
           missing_values=None, aggregate_lags=None, rng=None):
    """Score lagged links of a linear VAR via subsampled absolute coefficients.

    Parameters mirror the reference; ``missing_values`` marks a sentinel whose
    rows are excluded from each fit; ``aggregate_lags`` maps the
    (N_to, maxlags, N_from) lag-resolved score stack to N×N (default: max over
    lags, transposed so (i, j) reads X_i → X_j). ``rng`` is a numpy Generator
    (or seed) for the subsample draws.
    """
    data = np.asarray(data, dtype=np.float64)
    rng = np.random.default_rng(rng)
    if aggregate_lags is None:
        aggregate_lags = lambda x: x.max(axis=1).T  # noqa: E731
    T, N = data.shape
    Y, Z = _lagged_design(data, maxlags)

    scores = np.abs(_var_abs_coeffs(Y, Z, N, maxlags, rng,
                                    missing_values=missing_values))
    fractions = rng.choice(np.asarray(subsample_sizes), size=n_subsamples)
    for frac in fractions:
        rows = int(np.round(frac * T))
        scores += np.abs(_var_abs_coeffs(Y, Z, N, maxlags, rng,
                                         bootstrap_rows=rows,
                                         missing_values=missing_values))

    scores = scores[:, 1:] / (n_subsamples + 1)  # drop intercepts, average
    return aggregate_lags(scores.reshape(N, maxlags, N))
