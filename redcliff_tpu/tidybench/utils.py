"""Shared pre/post-processing for the tidybench score-based algorithms.

Equivalent of /root/reference/tidybench/utils.py:4-61 (`common_pre_post_processing`
decorator): optional z-scoring of the input data, and optional standardise /
[0,1]-rescale / edge-prior (divide-by-mean) transforms of the returned scores.
Implemented as an explicit wrapper so the processing order is visible in one place.
"""
from __future__ import annotations

import functools

import numpy as np

__all__ = ["common_pre_post_processing", "standardise"]


def standardise(X, axis=0):
    """Z-score ``X`` along ``axis`` (``axis=None`` → over all entries)."""
    X = np.asarray(X, dtype=np.float64)
    mu = X.mean(axis=axis, keepdims=axis is not None)
    sd = X.std(axis=axis, keepdims=axis is not None)
    return (X - mu) / sd


def common_pre_post_processing(func_raw):
    """Decorator adding the tidybench-standard data/score transforms.

    Keyword switches (all default False), applied in this order:
      pre_normalise       — z-score the data columns before the algorithm runs
      post_standardise    — z-score the scores over all entries
      post_zeroonescaling — rescale scores to [0, 1]
      post_edgeprior      — divide scores by their mean

    If the wrapped algorithm returns a tuple, only its first element (the score
    matrix) is transformed.
    """

    @functools.wraps(func_raw)
    def wrapped(data, *args, **kwargs):
        pre_normalise = kwargs.pop("pre_normalise", False)
        post_standardise = kwargs.pop("post_standardise", False)
        post_zeroonescaling = kwargs.pop("post_zeroonescaling", False)
        post_edgeprior = kwargs.pop("post_edgeprior", False)

        if pre_normalise:
            data = standardise(np.array(data, dtype=np.float64, copy=True))

        out = func_raw(data, *args, **kwargs)
        is_tuple = isinstance(out, tuple) and len(out) > 1
        scores = out[0] if is_tuple else out

        if post_standardise:
            scores = standardise(scores, axis=None)
        if post_zeroonescaling:
            lo, hi = scores.min(), scores.max()
            scores = (scores - lo) / (hi - lo)
        if post_edgeprior:
            scores = scores / scores.mean()

        if is_tuple:
            return (scores,) + tuple(out[1:])
        return scores

    return wrapped
