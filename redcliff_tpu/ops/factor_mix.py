"""Fused factor-mix kernel: embedder-weighted mixing of per-factor predictions.

The REDCLIFF-S forward ends every sim step with the mixture

    combined[b, t, c] = sum_k weightings[b, k] * preds[k, b, t, c]

(models/redcliff.py ``jnp.einsum("bk,kbtc->btc", ...)`` — the
embedder-softmax-weighted sum of the K per-factor one-step predictions).
Stock XLA emits a broadcast-multiply + reduce with an HBM round trip between
them at grid scale; the Pallas kernel here keeps each batch block VMEM-
resident and contracts K in one pass on the MXU.

Contract (the same discipline as ops/pallas_prox.py):

* :func:`factor_mix_reference` is the EXACT pre-existing einsum — the
  non-TPU production path and the bit-parity anchor. ``precision_mode="f32"``
  fits on CPU/GPU trace byte-identical graphs to a build that never heard
  of this module.
* :func:`factor_mix_pallas` is the fused kernel; parity vs the reference is
  pinned BITWISE in f32 interpret mode (tests/test_parallel_grid.py).
  It carries a ``jax.custom_vjp`` (the training step differentiates through
  the mix): the backward pass stays jnp — two small einsums — so gradients
  are exact while the fused forward rides the hot path.
* :func:`factor_mix` dispatches: Pallas on real TPU hardware (killable via
  ``REDCLIFF_FACTOR_MIX_PALLAS=0``), the reference everywhere else.

``block_b`` defaults to the persisted autotune winner for this
(platform, (K, M), B-bucket) when one exists (ops/autotune.py), else 32.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from redcliff_tpu.ops import autotune as _autotune

__all__ = ["factor_mix", "factor_mix_reference", "factor_mix_pallas",
           "graph_mix", "graph_mix_reference",
           "DEFAULT_BLOCK_B", "ENV_DISABLE"]

DEFAULT_BLOCK_B = 32
ENV_DISABLE = "REDCLIFF_FACTOR_MIX_PALLAS"
# f32 sublane multiple on the compiled TPU path; interpret mode keeps
# exact batch counts so parity tests see the unpadded reduction
_SUBLANE = 8


def factor_mix_reference(weightings, preds):
    """The jnp reference: ``einsum("bk,kbtc->btc")`` — byte-identical to the
    historical in-model expression (the bit-parity anchor)."""
    return jnp.einsum("bk,kbtc->btc", weightings, preds)


def _factor_mix_kernel(w_ref, p_ref, out_ref):
    # w (TB, K); p (K, TB, M); out (TB, M): batched mat-vec contracting K,
    # f32 accumulation on the MXU
    out_ref[:] = jax.lax.dot_general(
        w_ref[:], p_ref[:],
        dimension_numbers=(((1,), (0,)), ((0,), (1,))),
        preferred_element_type=jnp.float32)


def _tuned_block_b(batch, k, m):
    return _autotune.tuned_tile("factor_mix", f"k{int(k)}m{int(m)}", batch,
                                "block_b", DEFAULT_BLOCK_B)


def _mix_fwd_impl(weightings, preds, block_b, interpret):
    K, B, T, C = preds.shape
    M = T * C
    flat = jnp.reshape(preds, (K, B, M))
    if block_b is None:
        block_b = _tuned_block_b(B, K, M)
    tb = max(min(int(block_b), B), 1)
    if not interpret:
        tb = -(-tb // _SUBLANE) * _SUBLANE
    pad = (-B) % tb
    w = weightings
    if pad:
        w = jnp.pad(w, ((0, pad), (0, 0)))
        flat = jnp.pad(flat, ((0, 0), (0, pad), (0, 0)))
    n_blocks = w.shape[0] // tb
    out = pl.pallas_call(
        _factor_mix_kernel,
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((tb, K), lambda i: (i, 0)),
            pl.BlockSpec((K, tb, M), lambda i: (0, i, 0)),
        ],
        out_specs=pl.BlockSpec((tb, M), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((w.shape[0], M), flat.dtype),
        interpret=interpret,
    )(w, flat)
    if pad:
        out = out[:B]
    return jnp.reshape(out, (B, T, C))


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _factor_mix_vjp(weightings, preds, block_b, interpret):
    return _mix_fwd_impl(weightings, preds, block_b, interpret)


def _mix_fwd(weightings, preds, block_b, interpret):
    return _mix_fwd_impl(weightings, preds, block_b, interpret), (weightings,
                                                                  preds)


def _mix_bwd(block_b, interpret, res, g):
    # exact jnp backward: d w[b,k] = sum_{t,c} g[b,t,c] p[k,b,t,c];
    # d p[k,b,t,c] = w[b,k] g[b,t,c]
    weightings, preds = res
    dw = jnp.einsum("btc,kbtc->bk", g, preds)
    dp = jnp.einsum("bk,btc->kbtc", weightings, g)
    return dw, dp


_factor_mix_vjp.defvjp(_mix_fwd, _mix_bwd)


def factor_mix_pallas(weightings, preds, block_b=None, interpret=None):
    """Fused mix via Pallas: ``weightings (B, K)``, ``preds (K, B, T, C)``
    -> ``(B, T, C)``. Differentiable (custom VJP; jnp backward)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _factor_mix_vjp(weightings, preds, block_b, bool(interpret))


def factor_mix(weightings, preds):
    """Production dispatch: the fused Pallas kernel on real TPU hardware
    (``REDCLIFF_FACTOR_MIX_PALLAS=0`` kills it back to the reference), the
    exact reference einsum everywhere else."""
    if (jax.default_backend() == "tpu"
            and os.environ.get(ENV_DISABLE, "1") not in ("0", "off")):
        return factor_mix_pallas(weightings, preds)
    return factor_mix_reference(weightings, preds)


def graph_mix_reference(weightings, static_gc):
    """The serve-path jnp reference: ``einsum("sk,kij->sij")`` — byte-
    identical to the historical in-engine graph blend (serve/engine.py),
    so the non-TPU serve path never changes bytes."""
    return jnp.einsum("sk,kij->sij", weightings, static_gc)


def graph_mix(weightings, static_gc, interpret=None):
    """Serve-path graph blend: per-lane mixture of the K static per-factor
    GC graphs, ``weightings (S, K)`` x ``static_gc (K, C, C)`` -> ``(S, C,
    C)``. The slot-axis (S) plays the batch role, the graph plays the
    per-factor-prediction role, so the blend rides the SAME autotuned
    Pallas kernel as the training mix on real TPU hardware (broadcast of
    ``static_gc`` across lanes fuses into the kernel's VMEM block load; no
    materialized (K, S, C, C)). Everywhere else — and under
    ``REDCLIFF_FACTOR_MIX_PALLAS=0`` — it is the exact reference einsum.
    ``interpret`` forces the kernel's interpret mode (the bitwise parity
    anchor, tests/test_serve_elastic.py)."""
    if interpret is None:
        if (jax.default_backend() != "tpu"
                or os.environ.get(ENV_DISABLE, "1") in ("0", "off")):
            return graph_mix_reference(weightings, static_gc)
        interpret = False
    K = static_gc.shape[0]
    S = weightings.shape[0]
    preds = jnp.broadcast_to(static_gc[:, None], (K, S) + static_gc.shape[1:])
    return factor_mix_pallas(weightings, preds, interpret=interpret)
