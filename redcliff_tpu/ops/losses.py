"""Differentiable loss terms shared by the model zoo.

jit-side counterparts of the reference's in-loss computations
(ref models/redcliff_s_cmlp.py:620-686, models/cmlp_fm.py:156-180,
general_utils/metrics.py:342-381,433-443). All are pure jnp functions over
batched tensors — no Python loops over factors or samples.
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = [
    "channelwise_forecast_mse",
    "lag_weighted_adjacency_l1",
    "pairwise_cosine_penalty",
    "factor_weight_l1",
    "dagness_penalty",
]


def channelwise_forecast_mse(preds, targets):
    """sum_c MSE(preds[:, :, c], targets[:, :, c]) — the reference's forecasting
    loss sums per-channel means (ref redcliff_s_cmlp.py:625), equal to
    C * mean over all entries."""
    return preds.shape[-1] * jnp.mean((preds - targets) ** 2)


def lag_weighted_adjacency_l1(gc_lagged):
    """sum over leading axes and lags of log(l+2) * ||A[..., l]||_1
    (ref redcliff_s_cmlp.py:663). gc_lagged: (..., C, C, L)."""
    L = gc_lagged.shape[-1]
    lag_w = jnp.log(jnp.arange(L, dtype=gc_lagged.dtype) + 2.0)
    return jnp.sum(jnp.sum(jnp.abs(gc_lagged), axis=(-3, -2)) * lag_w)


def _flatten_minus_eye(G):
    """Subtract identity from each (C, C) slice then flatten trailing dims.

    G: (..., K, C, C). Mirrors include_diag=False in the reference's cosine
    penalty (ref metrics.py:342-369)."""
    C = G.shape[-1]
    return (G - jnp.eye(C, dtype=G.dtype)).reshape(G.shape[:-2] + (C * C,))


def pairwise_cosine_penalty(G, include_diag=False, epsilon=1e-8):
    """Sum of upper-triangle pairwise cosine similarities between factor graphs.

    G: (..., K, C, C) — leading axes are batched (e.g. per-sample conditional
    graphs). Matches compute_cosine_similarities_within_set_of_pytorch_tensors
    summed over pairs i<j (ref redcliff_s_cmlp.py:660, metrics.py:372-381).
    """
    K = G.shape[-3]
    if K <= 1:
        return jnp.zeros(G.shape[:-3], dtype=G.dtype) if G.ndim > 3 else jnp.array(0.0, G.dtype)
    flat = _flatten_minus_eye(G) if not include_diag else G.reshape(G.shape[:-2] + (-1,))
    norms = jnp.linalg.norm(flat, axis=-1)  # (..., K)
    gram = jnp.einsum("...kd,...jd->...kj", flat, flat)
    denom = jnp.maximum(norms[..., :, None], epsilon) * jnp.maximum(norms[..., None, :], epsilon)
    cos = gram / denom
    iu = jnp.triu_indices(K, k=1)
    return cos[..., iu[0], iu[1]].sum(axis=-1)


def factor_weight_l1(scores):
    """FACTOR_WEIGHT penalty ||s||_1 - 1 on the first-step factor scores
    (ref redcliff_s_cmlp.py:653)."""
    return jnp.sum(jnp.abs(scores)) - 1.0


def dagness_penalty(W0):
    """(tr(exp(W∘W)) - N)^2 with ELEMENTWISE exp, matching the reference's literal
    computation (ref metrics.py:433-443). Defined for parity; the reference keeps
    the corresponding loss terms disabled for numerical stability
    (ref redcliff_s_cmlp.py:678,682) and so does the default config here."""
    if W0.ndim == 3 and W0.shape[2] == 1:
        W0 = W0[:, :, 0]
    n = W0.shape[0]
    return (jnp.trace(jnp.exp(W0 * W0)) - n) ** 2.0
