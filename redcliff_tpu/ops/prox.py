"""Proximal operators for Granger-causal first-layer weight blocks.

The reference applies GISTA-style proximal updates in-place per output-series
network (ref models/cmlp.py:117-144, general_utils/model_utils.py:212-294). Here
the K factors x C output series are one tensorized weight block

    W1: (..., C_out, H, C_in, L)

and each penalty is a single fused soft-threshold over group norms — one XLA
kernel instead of K*C Python-loop iterations. A Pallas TPU kernel for the GL case
lives in redcliff_tpu.ops.pallas_prox; these jnp versions are the reference
implementations and the fallback path.

Group structures (matching the reference):
  GL   — one group per (output series, input series): norm over (H, L)
  GSGL — per-lag groups (norm over H) THEN the GL group
  H    — hierarchical: nested prefixes [:l+1] of the lag axis, lowest lag index
         = most-lagged value (ref cmlp.py:137-141)
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["prox_update", "soft_threshold_by_group_norm", "group_lasso_penalty",
           "ridge_penalty"]


def _group_norm(W, axes):
    return jnp.sqrt(jnp.sum(W * W, axis=axes, keepdims=True))


def soft_threshold_by_group_norm(W, norm, thresh):
    """W <- (W / max(norm, thresh)) * max(norm - thresh, 0) (ref cmlp.py:130-131)."""
    return (W / jnp.maximum(norm, thresh)) * jnp.maximum(norm - thresh, 0.0)


def prox_update(W1, lam, lr, penalty="GL"):
    """Proximal update on a first-layer block W1 (..., H, C_in, L) where the last
    three axes are (hidden, input-series, lag) and any leading axes (factor,
    output-series, grid-config) are batched.

    Returns the updated block (functional; no in-place mutation).
    """
    h_axis, lag_axis = -3, -1
    if penalty == "GL":
        norm = _group_norm(W1, (h_axis, lag_axis))
        return soft_threshold_by_group_norm(W1, norm, lr * lam)
    elif penalty == "GSGL":
        norm = _group_norm(W1, (h_axis,))
        W1 = soft_threshold_by_group_norm(W1, norm, lr * lam)
        norm = _group_norm(W1, (h_axis, lag_axis))
        return soft_threshold_by_group_norm(W1, norm, lr * lam)
    elif penalty == "H":
        L = W1.shape[lag_axis]
        for i in range(L):
            prefix = W1[..., : i + 1]
            norm = _group_norm(prefix, (h_axis, lag_axis))
            updated = soft_threshold_by_group_norm(prefix, norm, lr * lam)
            W1 = jnp.concatenate([updated, W1[..., i + 1 :]], axis=lag_axis)
        return W1
    raise ValueError(f"unsupported penalty: {penalty}")


def group_lasso_penalty(W1, lam, penalty="GL"):
    """Nonsmooth penalty value matching the prox structure (ref model_utils.py:270-292)."""
    h_axis, lag_axis = -3, -1
    if penalty == "GL":
        return lam * jnp.sum(jnp.sqrt(jnp.sum(W1 * W1, axis=(h_axis, lag_axis))))
    elif penalty == "GSGL":
        return lam * (
            jnp.sum(jnp.sqrt(jnp.sum(W1 * W1, axis=(h_axis, lag_axis))))
            + jnp.sum(jnp.sqrt(jnp.sum(W1 * W1, axis=(h_axis,))))
        )
    elif penalty == "H":
        L = W1.shape[lag_axis]
        total = 0.0
        for i in range(L):
            prefix = W1[..., : i + 1]
            total = total + jnp.sum(jnp.sqrt(jnp.sum(prefix * prefix, axis=(h_axis, lag_axis))))
        return lam * total
    raise ValueError(f"unsupported penalty: {penalty}")


def ridge_penalty(params_l2_leaves, lam):
    """Ridge penalty over the non-first-layer weights (ref model_utils.py:294-307)."""
    total = 0.0
    for leaf in params_l2_leaves:
        total = total + jnp.sum(leaf * leaf)
    return lam * total
