"""Pallas TPU kernel for the fused group-lasso proximal update.

The GL prox (redcliff_tpu.ops.prox.prox_update, ref models/cmlp.py:117-144)
reduces each (hidden, lag) group of the first-layer block to a norm, then
rescales the group by the soft-threshold factor. As a Pallas kernel the whole
update is one VMEM-resident pass per row-block: groups are rows of a
(G, H*L) matrix (G = factor*out-series*in-series groups), so the norm is a
row reduction on the VPU and the rescale is elementwise — no HBM round-trip
between the reduction and the scale.

Falls back to interpret mode off-TPU (tests run on the CPU mesh) and to the
jnp implementation for shapes where the kernel buys nothing.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from redcliff_tpu.ops.prox import prox_update as _jnp_prox_update

__all__ = ["gl_prox_pallas", "gl_prox"]


def _gl_prox_kernel(thresh_ref, w_ref, out_ref):
    w = w_ref[:]
    thresh = thresh_ref[0]
    norm = jnp.sqrt(jnp.sum(w * w, axis=1, keepdims=True))
    out_ref[:] = (w / jnp.maximum(norm, thresh)) * jnp.maximum(norm - thresh, 0.0)


def gl_prox_pallas(W1, lam, lr, block_rows=512, interpret=None):
    """GL proximal update on a first-layer block (..., H, C_in, L) via Pallas.

    Groups are (out-axis..., C_in) with elements over (H, L), matching the GL
    penalty structure. Returns the updated block with the input layout.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    *lead, H, C, Lg = W1.shape
    # rows = leading axes x C_in groups; cols = H*L group elements
    Wt = jnp.moveaxis(W1, -2, -3)  # (..., C, H, L)
    G = 1
    for d in lead:
        G *= d
    G *= C
    flat = Wt.reshape(G, H * Lg)
    rows = min(block_rows, G)
    # pad rows to a multiple of the block
    pad = (-G) % rows
    if pad:
        flat = jnp.pad(flat, ((0, pad), (0, 0)))
    n_blocks = flat.shape[0] // rows
    thresh = jnp.asarray([lr * lam], dtype=flat.dtype)

    out = pl.pallas_call(
        _gl_prox_kernel,
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((rows, H * Lg), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((rows, H * Lg), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(flat.shape, flat.dtype),
        interpret=interpret,
    )(thresh, flat)

    if pad:
        out = out[:G]
    back = out.reshape(tuple(lead) + (C, H, Lg))
    return jnp.moveaxis(back, -3, -2)


def gl_prox(W1, lam, lr, penalty="GL", use_pallas=True):
    """Dispatch: Pallas kernel for GL on real TPU hardware; the fused jnp prox
    everywhere else (interpret-mode Pallas is for kernel tests only — it would
    run an emulated kernel inside every CPU/GPU train step)."""
    if penalty == "GL" and use_pallas and jax.default_backend() == "tpu":
        return gl_prox_pallas(W1, lam, lr)
    return _jnp_prox_update(W1, lam, lr, penalty)
