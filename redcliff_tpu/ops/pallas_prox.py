"""Pallas TPU kernel for the fused group-lasso proximal update.

The GL prox (redcliff_tpu.ops.prox.prox_update, ref models/cmlp.py:117-144)
reduces each (hidden, lag) group of the first-layer block to a norm, then
rescales the group by the soft-threshold factor. As a Pallas kernel the whole
update is one VMEM-resident pass per row-block: groups are rows of a
(G, H*L) matrix (G = factor*out-series*in-series groups), so the norm is a
row reduction on the VPU and the rescale is elementwise — no HBM round-trip
between the reduction and the scale.

Production routing (ISSUE 14): every prox path — the cmlp_fm baseline, the
REDCLIFF-S trainers' ``prox_penalty`` knob, and the grid engine's vmapped
per-lane prox — dispatches through :func:`gl_prox`, so real-TPU fits run
this kernel while the jnp implementation stays the bit-parity anchor and
the non-TPU path (real-chip parity pinned at max abs err 5e-7 on v5e, r05).

Tiling: ``block_rows`` defaults to the persisted autotune winner for this
(platform, cols, G-bucket) when one exists (ops/autotune.py — searched once
per fleet, reused everywhere beside the compile cache), else 512. Row
counts that do not divide the tile are zero-padded up to it (padded rows
are sliced off after the call; real rows' math is row-independent, so
padding never moves a real result), and on the compiled TPU path the tile
is rounded up to the f32 sublane multiple so off-tile first-layer shapes
compile instead of falling back.

Falls back to interpret mode off-TPU (tests run on the CPU mesh) and to the
jnp implementation for shapes where the kernel buys nothing.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from redcliff_tpu.ops import autotune as _autotune
from redcliff_tpu.ops.prox import prox_update as _jnp_prox_update

__all__ = ["gl_prox_pallas", "gl_prox", "DEFAULT_BLOCK_ROWS"]

DEFAULT_BLOCK_ROWS = 512
# f32 sublane multiple on the compiled TPU path (pallas_guide.md tiling
# constraints); interpret mode keeps exact row counts
_SUBLANE = 8


def _gl_prox_kernel(thresh_ref, w_ref, out_ref):
    w = w_ref[:]
    thresh = thresh_ref[0]
    norm = jnp.sqrt(jnp.sum(w * w, axis=1, keepdims=True))
    out_ref[:] = (w / jnp.maximum(norm, thresh)) * jnp.maximum(norm - thresh, 0.0)


def _tuned_block_rows(rows, cols):
    """The persisted autotune winner for this (platform, cols, row-bucket),
    else the default (lookup only; searches run from the engines/bench)."""
    return _autotune.tuned_tile("gl_prox", f"cols{int(cols)}", rows,
                                "block_rows", DEFAULT_BLOCK_ROWS)


def gl_prox_pallas(W1, lam, lr, block_rows=None, interpret=None):
    """GL proximal update on a first-layer block (..., H, C_in, L) via Pallas.

    Groups are (out-axis..., C_in) with elements over (H, L), matching the GL
    penalty structure. Returns the updated block with the input layout.
    ``block_rows=None`` resolves the autotuned winner (ops/autotune.py).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    *lead, H, C, Lg = W1.shape
    # rows = leading axes x C_in groups; cols = H*L group elements
    Wt = jnp.moveaxis(W1, -2, -3)  # (..., C, H, L)
    G = 1
    for d in lead:
        G *= d
    G *= C
    cols = H * Lg
    flat = Wt.reshape(G, cols)
    if block_rows is None:
        block_rows = _tuned_block_rows(G, cols)
    rows = max(min(int(block_rows), G), 1)
    if not interpret:
        # compiled TPU path: round the tile UP to the f32 sublane multiple
        # so off-tile row counts (G < 8, odd G) compile instead of erroring;
        # the extra rows are zero padding, masked off by the slice below
        rows = -(-rows // _SUBLANE) * _SUBLANE
    # pad rows to a multiple of the block (zero rows: row-independent math,
    # sliced away after the call)
    pad = (-G) % rows
    if pad:
        flat = jnp.pad(flat, ((0, pad), (0, 0)))
    n_blocks = flat.shape[0] // rows
    thresh = jnp.asarray([lr * lam], dtype=flat.dtype)

    out = pl.pallas_call(
        _gl_prox_kernel,
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((rows, cols), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((rows, cols), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(flat.shape, flat.dtype),
        interpret=interpret,
    )(thresh, flat)

    if pad:
        out = out[:G]
    back = out.reshape(tuple(lead) + (C, H, Lg))
    return jnp.moveaxis(back, -3, -2)


def gl_prox(W1, lam, lr, penalty="GL", use_pallas=True):
    """Dispatch: Pallas kernel for GL on real TPU hardware; the fused jnp prox
    everywhere else (interpret-mode Pallas is for kernel tests only — it would
    run an emulated kernel inside every CPU/GPU train step)."""
    if penalty == "GL" and use_pallas and jax.default_backend() == "tpu":
        return gl_prox_pallas(W1, lam, lr)
    return _jnp_prox_update(W1, lam, lr, penalty)
