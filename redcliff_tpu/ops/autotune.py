"""Iterative kernel-tiling autotuner with a persistent, fleet-shared store.

The Pallas hot-path kernels (ops/pallas_prox.py GL prox, ops/factor_mix.py
factor mix) expose one tiling knob each (``block_rows`` / ``block_b``).
Picking it by hand is exactly the problem the iterative-search line of work
(*AutoKernel*; *Learning to Optimize Tensor Programs* — PAPERS.md) solves by
measuring a small candidate ladder and keeping the winner; this module is
that loop, sized for this repo's kernels:

* **Search** (:func:`tune`): measure every candidate of a ladder (median of
  ``reps`` timed runs, synced via ``jax.device_get`` — never a
  ``block_until_ready`` device sync, per the observability lint) and keep
  the fastest. The ladder is evaluated in a FIXED order and ties break to
  the first (smallest) candidate, so the same measurements always produce
  the same winner.
* **Store**: winners persist as ``autotune_v<VERSION>.json`` beside the
  compile cache (``REDCLIFF_AUTOTUNE_DIR`` override, else the
  ``REDCLIFF_COMPILE_CACHE`` base dir — the same resolution as the PR-8
  cost model), keyed per ``(platform, kernel, shape, G-bucket)``.
  Read-modify-write under a best-effort ``flock`` with atomic replace;
  corrupt or wrong-version stores degrade to "no winner" (defaults), never
  to an error on a training path. A fleet of workers tunes once and
  inherits the winner everywhere, exactly like the persistent compile
  cache the store lives beside.
* **Zero re-search**: :func:`winner` / :func:`tune` consult an in-process
  memo first and the store second — a second fit with the same
  (platform, kernel, shape, G-bucket) performs zero search steps (the CI
  smoke leg pins this).

``REDCLIFF_AUTOTUNE=0`` disables searching (stored winners are still
read); searching also requires a resolvable store dir so throwaway
processes don't burn measurement time on winners nobody will reuse —
unless the caller passes an explicit ``base_dir``.

Every search/lookup appends a record to a process-level ring that engines
drain into schema-registered ``autotune`` events (:func:`drain_records`),
so fits show which tilings they ran and what the search cost.

jax only inside function bodies (lazy-jax lint module): the store half is
stdlib and must stay importable by backend-free processes.
"""
from __future__ import annotations

import json
import os
import threading
import time

__all__ = ["STORE_VERSION", "STORE_NAME", "ENV_STORE_DIR", "ENV_ENABLE",
           "MAX_WINNERS", "winner_key", "store_path", "search_enabled",
           "load_store", "winner", "tuned_tile", "record_winner", "tune",
           "drain_records", "clear_memo", "gl_prox_ladder",
           "measure_gl_prox", "tune_gl_prox", "factor_mix_ladder",
           "measure_factor_mix", "tune_factor_mix", "tune_for_model"]

STORE_VERSION = 1
STORE_NAME = f"autotune_v{STORE_VERSION}.json"
ENV_STORE_DIR = "REDCLIFF_AUTOTUNE_DIR"
ENV_CACHE_DIR = "REDCLIFF_COMPILE_CACHE"  # literal on purpose: no runtime
#                                           import from this stdlib half
ENV_ENABLE = "REDCLIFF_AUTOTUNE"
MAX_WINNERS = 512

_lock = threading.Lock()
# in-process caches: key -> winner record (hot-path lookups must not re-read
# JSON per traced kernel call), plus the drained-event ring
_memo: dict = {}
_records: list = []


def winner_key(platform, kernel, shape_key, g_bucket):
    """The store's winner id: ``<platform>|<kernel>|<shape>|g<bucket>``."""
    return f"{platform}|{kernel}|{shape_key}|g{int(g_bucket)}"


def store_path(base_dir=None):
    """Resolve the store file path (``REDCLIFF_AUTOTUNE_DIR`` override, else
    the compile-cache base dir), or None when no base dir is known."""
    base = (base_dir or os.environ.get(ENV_STORE_DIR)
            or os.environ.get(ENV_CACHE_DIR) or None)
    if not base:
        return None
    if str(base).endswith(".json"):
        return str(base)
    return os.path.join(base, STORE_NAME)


def search_enabled():
    """True unless ``REDCLIFF_AUTOTUNE`` explicitly disables searching."""
    return os.environ.get(ENV_ENABLE, "1") not in ("0", "off", "false")


def _empty_store():
    return {"version": STORE_VERSION, "updated_at": None, "runs": 0,
            "winners": {}}


def _read_store(path):
    """Parse a store file; None on missing/corrupt/wrong-version — the
    corrupt-store->defaults discipline shared with the PR-8 cost model."""
    try:
        with open(path) as f:
            store = json.load(f)
    except (OSError, ValueError):
        return None
    if not (isinstance(store, dict)
            and store.get("version") == STORE_VERSION
            and isinstance(store.get("winners"), dict)):
        return None
    return store


def load_store(base_dir=None):
    """The persisted store dict (or an empty one), plus its path."""
    path = store_path(base_dir)
    if path is None or not os.path.exists(path):
        return _empty_store(), path
    return _read_store(path) or _empty_store(), path


def clear_memo():
    """Drop the in-process winner memo (tests; store-dir changes)."""
    with _lock:
        _memo.clear()


def drain_records():
    """Pop every pending search/lookup record (engines log these as
    schema-registered ``autotune`` events)."""
    with _lock:
        out = list(_records)
        _records.clear()
    return out


def _note(record):
    with _lock:
        _records.append(record)
        del _records[:-64]  # bounded ring


def winner(kernel, shape_key, g_bucket, platform=None, base_dir=None):
    """The persisted winner record for a bucket (memo -> store), or None.
    Misses are memoized too — a traced kernel call must never re-read JSON
    per trace (record_winner refreshes the memo after a search). The memo
    is keyed by the RESOLVED store path as well: a lookup against one
    store can never replay a winner (or a miss) cached from another."""
    platform = platform or _platform()
    key = winner_key(platform, kernel, shape_key, g_bucket)
    path = store_path(base_dir)
    with _lock:
        if (path, key) in _memo:
            return _memo[(path, key)]
    store, _path = load_store(base_dir)
    rec = store["winners"].get(key)
    with _lock:
        _memo[(path, key)] = rec
    return rec


def tuned_tile(kernel, shape_key, size, field, default):
    """The one winner-unpack helper every kernel's hot-path lookup shares:
    the persisted winner's ``tile[field]`` for (kernel, shape, pow2 bucket
    of ``size``), else ``default``. Lookup only — searches run from the
    engines/bench via the tune_* entry points, never inside a traced
    kernel call."""
    rec = winner(kernel, shape_key, _pow2_bucket(size))
    if rec is not None:
        try:
            return int(rec["tile"][field])
        except (KeyError, TypeError, ValueError):
            pass
    return default


def record_winner(kernel, shape_key, g_bucket, tile, platform=None,
                  base_dir=None, search_ms=None, candidates=None,
                  speedup_vs_default=None, now=None):
    """Persist a winner — read-modify-write under a best-effort flock with
    an atomic replace (concurrent fits merge instead of clobbering).
    Returns the winner record (memoized even when no store dir resolves,
    so the current process still reuses it)."""
    platform = platform or _platform()
    now = time.time() if now is None else now
    key = winner_key(platform, kernel, shape_key, g_bucket)
    rec = {"kernel": kernel, "platform": platform, "shape": shape_key,
           "g_bucket": int(g_bucket), "tile": dict(tile),
           "search_ms": (round(float(search_ms), 3)
                         if search_ms is not None else None),
           "candidates": candidates,
           "speedup_vs_default": (round(float(speedup_vs_default), 3)
                                  if speedup_vs_default is not None
                                  else None),
           "runs": 1, "updated_at": now}
    path = store_path(base_dir)
    with _lock:
        _memo[(path, key)] = rec
    if path is None:
        return rec
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with _lock:
        lock_fd = None
        try:
            try:
                import fcntl
            except ImportError:
                fcntl = None
            if fcntl is not None:
                try:
                    lock_fd = os.open(path + ".lock",
                                      os.O_CREAT | os.O_WRONLY)
                except OSError:
                    lock_fd = None
                if lock_fd is not None:
                    try:
                        fcntl.flock(lock_fd, fcntl.LOCK_EX)
                    except OSError:
                        os.close(lock_fd)
                        lock_fd = None
            store = _read_store(path) or _empty_store()
            prior = store["winners"].get(key)
            if prior is not None:
                rec = dict(rec, runs=int(prior.get("runs") or 0) + 1)
            store["winners"][key] = rec
            # bound the store: evict the longest-unobserved winners
            winners = store["winners"]
            if len(winners) > MAX_WINNERS:
                by_age = sorted(winners, key=lambda k:
                                winners[k].get("updated_at") or 0.0)
                for k in by_age[: len(winners) - MAX_WINNERS]:
                    del winners[k]
            store["updated_at"] = now
            store["runs"] = int(store.get("runs") or 0) + 1
            tmp = f"{path}.tmp{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(store, f, indent=1, allow_nan=False)
                f.write("\n")
            os.replace(tmp, path)
            _memo[(path, key)] = rec
        finally:
            if lock_fd is not None:
                os.close(lock_fd)  # closing drops the flock
    return rec


def _platform():
    import jax

    return jax.default_backend()


def tune(kernel, shape_key, g_bucket, candidates, measure, default=None,
         platform=None, base_dir=None, reps=3, force=False):
    """Resolve the tile for a kernel bucket: persisted winner when one
    exists (zero search steps), else an iterative measured search over the
    candidate ladder.

    ``candidates`` is the FIXED-ORDER ladder of tile dicts;
    ``measure(tile)`` returns seconds for one kernel invocation at that
    tile (the caller owns data synthesis + the ``jax.device_get`` sync);
    ``default`` marks the no-autotune tile so the winner's
    ``speedup_vs_default`` can be reported. Ties break deterministically
    to the earliest candidate. Returns ``(tile, record)`` where record
    carries ``searched``/``search_ms``/``search_steps``."""
    platform = platform or _platform()
    rec = winner(kernel, shape_key, g_bucket, platform=platform,
                 base_dir=base_dir)
    if rec is not None and not force:
        out = dict(rec, searched=False, search_steps=0)
        _note({"kernel": kernel, "kind": "reuse", "platform": platform,
               "shape": shape_key, "g_bucket": int(g_bucket),
               "tile": rec["tile"], "search_steps": 0})
        return dict(rec["tile"]), out
    if not force and not (search_enabled()
                          and (base_dir or store_path() is not None)):
        # searching disabled (or no store to persist into): default tile
        tile = dict(default or candidates[0])
        return tile, {"tile": tile, "searched": False, "search_steps": 0,
                      "search_ms": None, "reason": "search_disabled"}
    t0 = time.perf_counter()
    timed = []
    default_s = None
    for tile in candidates:
        samples = sorted(measure(dict(tile)) for _ in range(max(reps, 1)))
        med = samples[len(samples) // 2]
        timed.append((med, tile))
        if default is not None and dict(tile) == dict(default):
            default_s = med
    if default is not None and default_s is None:
        # default tile off the ladder (clipped by the shape): time it too so
        # the winner's speedup-vs-default is always reportable
        samples = sorted(measure(dict(default)) for _ in range(max(reps, 1)))
        default_s = samples[len(samples) // 2]
    best_s, best_tile = min(timed, key=lambda t: t[0])  # stable: first wins
    search_ms = (time.perf_counter() - t0) * 1e3
    speedup = (default_s / best_s if default_s and best_s else None)
    rec = record_winner(kernel, shape_key, g_bucket, best_tile,
                        platform=platform, base_dir=base_dir,
                        search_ms=search_ms, candidates=len(candidates),
                        speedup_vs_default=speedup)
    out = dict(rec, searched=True, search_steps=len(timed))
    _note({"kernel": kernel, "kind": "search", "platform": platform,
           "shape": shape_key, "g_bucket": int(g_bucket),
           "tile": rec["tile"], "candidates": len(candidates),
           "search_ms": rec["search_ms"],
           "speedup_vs_default": rec["speedup_vs_default"],
           "search_steps": len(timed)})
    return dict(best_tile), out


# ---------------------------------------------------------------------------
# kernel-specific ladders + measurement closures
# ---------------------------------------------------------------------------
def gl_prox_ladder(rows):
    """block_rows candidates for the GL-prox kernel: a power-of-two ladder
    clipped to the row count's pow2 bucket (so the single-block tile always
    competes; small shapes get small ladders)."""
    cap = _pow2_bucket(max(rows, 64))
    ladder = [r for r in (64, 128, 256, 512, 1024) if r <= cap]
    if not ladder:
        ladder = [64]
    return [{"block_rows": r} for r in ladder]


def measure_gl_prox(rows, cols, interpret=None):
    """A ``measure(tile)`` closure timing one fused GL-prox pass over a
    synthetic ``(rows, cols)``-group block (``jax.device_get`` sync)."""
    import jax
    import numpy as np

    from redcliff_tpu.ops import pallas_prox

    rng = np.random.default_rng(0)
    # gl_prox_pallas unpacks (*lead, H, C_in, L) and flattens to
    # (prod(lead)*C_in, H*L): a (rows, cols, 1, 1) block — H=cols, C_in=1,
    # L=1 — is exactly the (rows, cols) group problem the winner is keyed
    # for (a (rows, 1, cols, 1) block would degenerate to rows*cols
    # single-element groups and tune the wrong workload)
    W = jax.numpy.asarray(
        rng.normal(size=(rows, cols, 1, 1)).astype(np.float32))

    def measure(tile):
        run = jax.jit(lambda w: pallas_prox.gl_prox_pallas(
            w, 0.01, 0.002, block_rows=tile["block_rows"],
            interpret=interpret))
        jax.device_get(run(W))  # compile + warm outside the timed call
        t0 = time.perf_counter()
        jax.device_get(run(W))
        return time.perf_counter() - t0

    return measure


def tune_gl_prox(rows, cols, platform=None, base_dir=None, interpret=None,
                 reps=3, force=False):
    """Tune (or reuse) the GL-prox ``block_rows`` for a ``(rows, cols)``
    group block; returns ``(block_rows, record)``."""
    tile, rec = tune(
        "gl_prox", f"cols{int(cols)}", _pow2_bucket(rows),
        gl_prox_ladder(rows), measure_gl_prox(rows, cols,
                                              interpret=interpret),
        default={"block_rows": 512}, platform=platform, base_dir=base_dir,
        reps=reps, force=force)
    return int(tile["block_rows"]), rec


def factor_mix_ladder(batch):
    """block_b candidates for the factor-mix kernel (pow2 ladder up to the
    batch's bucket, single-block tile included)."""
    cap = _pow2_bucket(max(batch, 8))
    ladder = [b for b in (8, 16, 32, 64, 128) if b <= cap]
    if not ladder:
        ladder = [8]
    return [{"block_b": b} for b in ladder]


def measure_factor_mix(batch, k, m, interpret=None):
    """A ``measure(tile)`` closure timing one fused factor-mix pass over a
    synthetic ``(B=batch, K=k, M=m)`` problem."""
    import jax
    import numpy as np

    from redcliff_tpu.ops import factor_mix as fm

    rng = np.random.default_rng(0)
    w = jax.numpy.asarray(rng.random((batch, k)).astype(np.float32))
    p = jax.numpy.asarray(
        rng.normal(size=(k, batch, 1, m)).astype(np.float32))

    def measure(tile):
        run = jax.jit(lambda wa, pa: fm.factor_mix_pallas(
            wa, pa, block_b=tile["block_b"], interpret=interpret))
        jax.device_get(run(w, p))
        t0 = time.perf_counter()
        jax.device_get(run(w, p))
        return time.perf_counter() - t0

    return measure


def tune_factor_mix(batch, k, m, platform=None, base_dir=None,
                    interpret=None, reps=3, force=False):
    """Tune (or reuse) the factor-mix ``block_b`` for a (B, K, M) problem;
    returns ``(block_b, record)``."""
    tile, rec = tune(
        "factor_mix", f"k{int(k)}m{int(m)}", _pow2_bucket(batch),
        factor_mix_ladder(batch), measure_factor_mix(batch, k, m,
                                                     interpret=interpret),
        default={"block_b": 32}, platform=platform, base_dir=base_dir,
        reps=reps, force=force)
    return int(tile["block_b"]), rec


def _pow2_bucket(n):
    """Bucket a size onto the power-of-two ladder (the same discipline as
    the grid's G-bucket), so near-identical shapes share one winner."""
    n = max(int(n), 1)
    b = 1
    while b < n:
        b <<= 1
    return b


def tune_for_model(model_config, batch_size, prox_penalty=None,
                   base_dir=None):
    """Tune (or reuse) every hot-path kernel tiling a REDCLIFF-S fit of
    this shape will dispatch — the ONE shape-math site both engines call
    from their constructors on real TPU hardware (the first fit of a
    (platform, shape, G-bucket) searches once; later fits and fleet
    siblings sharing the store reuse the winner with zero search steps).
    No-op off-TPU / when searching is disabled; advisory — never fatal."""
    if _platform() != "tpu" or not search_enabled():
        return
    cfg = model_config
    try:
        if (prox_penalty == "GL"
                and getattr(cfg, "factor_network_type", None) == "cMLP"):
            # the stacked first-layer block (K, C_out, H, C_in, L) flattens
            # to K*C_out*C_in group rows of H*L columns per lane
            rows = cfg.num_factors * cfg.num_series * cfg.num_series
            tune_gl_prox(rows, cfg.gen_hidden[0] * cfg.gen_lag,
                         base_dir=base_dir)
        sims = (cfg.num_sims if cfg.forward_pass_mode
                == "apply_factor_weights_after_sim_completion" else 1)
        tune_factor_mix(int(batch_size), cfg.num_factors,
                        sims * cfg.num_series, base_dir=base_dir)
    except Exception:  # noqa: BLE001 — tuning is advisory, never fatal
        pass
