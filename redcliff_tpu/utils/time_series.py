"""Signal-processing primitives: wavelets, spectral features, filters, outliers.

Capability rebuild of /root/reference/general_utils/time_series.py. Notable
deltas from the reference:

* Stationary wavelet decomposition is implemented natively (the reference
  delegates to ``pywt.swt(..., trim_approx=True, norm=True)``,
  ref time_series.py:10-26): an undecimated "a trous" filter bank with
  orthonormal Daubechies filters scaled by 1/sqrt(2) per level — a tight frame,
  so energy is preserved and the adjoint reconstruction is exact. The
  reference's "additive" signal approximation (summing all bands,
  ref time_series.py:29-43) is exact for haar/db1 and approximate for higher-
  order wavelets, exactly as under pywt.
* The reference's "wavedec" branch crashes as published (it assigns a
  coefficient list into an array row, ref time_series.py:17-18); this build
  raises NotImplementedError for it instead of reproducing the crash.
* Outlier marking and filtering operate on plain arrays or dicts of traces.
* Window-draw helpers take an explicit numpy Generator instead of the global
  ``random`` module state (ref time_series.py:393-425).

Spectral feature generation (CSD power + directed spectrum) stays on host
numpy/scipy in float64: it is one-shot dataset preprocessing, and Wilson
factorization is numerically touchy below f64 (SURVEY.md §7 hard part 4).
"""
from __future__ import annotations

import numpy as np
from scipy.signal import butter, csd, iirnotch, lfilter

from redcliff_tpu.utils.directed_spectrum import get_directed_spectrum

__all__ = [
    "wavelet_filters",
    "swt",
    "iswt",
    "perform_wavelet_decomposition",
    "construct_signal_approx_from_wavelet_coeffs",
    "unsqueeze_triangular_array",
    "squeeze_triangular_array",
    "make_high_level_signal_features",
    "filter_signal",
    "filter_signal_via_bandpass",
    "filter_signal_via_lowpass",
    "mark_outliers",
    "draw_timesteps_to_sample_from",
    "draw_timesteps_to_sample_from_using_label_reference",
    "DEFAULT_MAD_THRESHOLD",
    "LOW_PASS_CUTOFF",
    "LOWCUT",
    "HIGHCUT",
]

# ------------------------------------------------------------------ wavelets

# Daubechies scaling (reconstruction lowpass) filters, standard published
# coefficients; "haar" is an alias of db1.
_DB_SCALING = {
    "db1": [0.7071067811865476, 0.7071067811865476],
    "db2": [0.48296291314469025, 0.8365163037378079,
            0.22414386804185735, -0.12940952255092145],
    "db3": [0.3326705529509569, 0.8068915093133388, 0.4598775021193313,
            -0.13501102001039084, -0.08544127388224149, 0.035226291882100656],
    "db4": [0.23037781330885523, 0.7148465705525415, 0.6308807679295904,
            -0.02798376941698385, -0.18703481171888114, 0.030841381835986965,
            0.032883011666982945, -0.010597401784997278],
}


def wavelet_filters(wavelet_type):
    """(dec_lo, dec_hi) analysis filter pair for a Daubechies wavelet."""
    name = "db1" if wavelet_type in ("haar", "Haar") else wavelet_type
    if name not in _DB_SCALING:
        raise NotImplementedError(
            f"wavelet {wavelet_type!r} not available (have "
            f"{sorted(_DB_SCALING) + ['haar']})")
    rec_lo = np.asarray(_DB_SCALING[name], dtype=np.float64)
    dec_lo = rec_lo[::-1]
    dec_hi = rec_lo * np.array([(-1.0) ** k for k in range(len(rec_lo))])
    return dec_lo, dec_hi


def _dilated_correlate(x, filt, dilation):
    """Circular correlation of x (..., T) with a 2^j-dilated filter."""
    T = x.shape[-1]
    idx = (np.arange(T)[:, None] + dilation * np.arange(len(filt))[None, :]) % T
    return np.einsum("...tk,k->...t", x[..., idx], filt)


def _dilated_correlate_adjoint(x, filt, dilation):
    """Adjoint of _dilated_correlate (circular convolution with the filter)."""
    T = x.shape[-1]
    idx = (np.arange(T)[:, None] - dilation * np.arange(len(filt))[None, :]) % T
    return np.einsum("...tk,k->...t", x[..., idx], filt)


def swt(x, wavelet_type="db1", level=1):
    """Undecimated (stationary) wavelet transform, tight-frame normalized.

    x: (..., T) with T divisible by 2**level. Returns [cA_level, cD_level, ...,
    cD_1] — the pywt ``trim_approx=True`` band order the reference consumes
    (ref time_series.py:20-22). Filters carry a 1/sqrt(2) per-level scale
    (pywt's ``norm=True``), making the frame tight:
    ||x||^2 == ||cA||^2 + sum ||cD_j||^2.
    """
    dec_lo, dec_hi = wavelet_filters(wavelet_type)
    s = 1.0 / np.sqrt(2.0)
    a = np.asarray(x, dtype=np.float64)
    if a.shape[-1] % (2 ** level) != 0:
        raise ValueError(
            f"signal length {a.shape[-1]} must be divisible by 2**level "
            f"({2 ** level})")
    details = []
    for j in range(level):
        d = _dilated_correlate(a, dec_hi * s, 2 ** j)
        a = _dilated_correlate(a, dec_lo * s, 2 ** j)
        details.append(d)
    return [a] + details[::-1]


def iswt(bands, wavelet_type="db1"):
    """Exact inverse of swt via the tight-frame adjoint: at each level
    a_j = H^T a_{j+1} + G^T d_{j+1}."""
    dec_lo, dec_hi = wavelet_filters(wavelet_type)
    s = 1.0 / np.sqrt(2.0)
    level = len(bands) - 1
    a = np.asarray(bands[0], dtype=np.float64)
    for j in range(level - 1, -1, -1):
        d = bands[level - j]  # cD_{j+1} sits at index level-j in trim order
        a = (_dilated_correlate_adjoint(a, dec_lo * s, 2 ** j)
             + _dilated_correlate_adjoint(d, dec_hi * s, 2 ** j))
    return a


def perform_wavelet_decomposition(orig_sig, wavelet_type, level,
                                  decomposition_type="swt"):
    """(1, T, C) -> (1, T, C*(level+1)): channel c's bands occupy columns
    [c*(level+1), (c+1)*(level+1)) in [cA, cD_level, ..., cD_1] order
    (ref time_series.py:10-26)."""
    assert orig_sig.ndim == 3 and orig_sig.shape[0] == 1
    if decomposition_type != "swt":
        raise NotImplementedError(
            f"decomposition_type {decomposition_type!r}: only 'swt' is "
            "supported (the reference's 'wavedec' branch is broken as "
            "published, ref time_series.py:17-18)")
    sig = orig_sig[0].T  # (C, T)
    bands = swt(sig, wavelet_type, level)  # list of (C, T)
    stacked = np.stack(bands, axis=1)  # (C, level+1, T)
    out = stacked.reshape(sig.shape[0] * (level + 1), sig.shape[1])
    return out.T[None]


def construct_signal_approx_from_wavelet_coeffs(coeffs, level,
                                                wavelet_coeff_type="additive"):
    """Sum each channel's bands back into a signal approximation
    (ref time_series.py:29-43). coeffs: (1, T, C*(level+1)) -> (T, C)."""
    assert coeffs.ndim == 3 and coeffs.shape[0] == 1
    if wavelet_coeff_type != "additive":
        raise NotImplementedError(wavelet_coeff_type)
    T, CW = coeffs.shape[1], coeffs.shape[2]
    C = CW // (level + 1)
    return coeffs[0].reshape(T, C, level + 1).sum(axis=2)


# ------------------------------------------------- triangular (un)packing

def _tri_indices(n):
    """Condensed-triangular index map: entry (i, j<=i) lives at i(i+1)/2 + j."""
    rows, cols = np.tril_indices(n)
    flat = (rows * (rows + 1)) // 2 + cols
    return rows, cols, flat


def unsqueeze_triangular_array(arr, dim=0):
    """Condensed triangular axis -> symmetric (n, n) axes
    (ref time_series.py:53-84)."""
    m = arr.shape[dim]
    n = int(round((-1 + np.sqrt(1 + 8 * m)) / 2))
    assert (n * (n + 1)) // 2 == m, f"{(n * (n + 1)) // 2} != {m}"
    arr = np.swapaxes(arr, dim, -1)
    rows, cols, flat = _tri_indices(n)
    new_arr = np.zeros(arr.shape[:-1] + (n, n), dtype=arr.dtype)
    new_arr[..., rows, cols] = arr[..., flat]
    new_arr[..., cols, rows] = arr[..., flat]
    dim_list = list(range(new_arr.ndim - 2)) + [dim]
    dim_list = dim_list[:dim] + [-2, -1] + dim_list[dim + 1:]
    return np.transpose(new_arr, dim_list)


def squeeze_triangular_array(arr, dims=(0, 1)):
    """Symmetric (n, n) axes -> condensed triangular axis; inverse of
    unsqueeze_triangular_array (ref time_series.py:87-118)."""
    assert len(dims) == 2 and dims[1] == dims[0] + 1
    assert arr.shape[dims[0]] == arr.shape[dims[1]]
    n = arr.shape[dims[0]]
    dim_list = list(range(arr.ndim))
    dim_list = dim_list[: dims[0]] + dim_list[dims[1] + 1:] + list(dims)
    arr = np.transpose(arr, dim_list)
    rows, cols, flat = _tri_indices(n)
    new_arr = np.zeros(arr.shape[:-2] + ((n * (n + 1)) // 2,), dtype=arr.dtype)
    new_arr[..., flat] = arr[..., rows, cols]
    dim_list = list(range(new_arr.ndim))
    dim_list = dim_list[: dims[0]] + [-1] + dim_list[dims[0]: -1]
    return np.transpose(new_arr, dim_list)


# ------------------------------------------------------- spectral features

DEFAULT_CSD_PARAMS = {
    "detrend": "constant",
    "window": "hann",
    "nperseg": 512,
    "noverlap": 256,
    "nfft": None,
}


def make_high_level_signal_features(
    X,
    fs=1000,
    min_freq=0.0,
    max_freq=55.0,
    directed_spectrum=False,
    csd_params=None,
    rng=None,
):
    """Cross-power-spectral-density (and optionally directed-spectrum) features
    from a waveform — the DCSFA input features (ref time_series.py:121-211).

    X: (T, C). Returns {'power': (1, C*(C+1)//2, F), 'freq': (F,)
    [, 'dir_spec': (1, C, C, F)]}. NaN-bearing windows are replaced by noise for
    the transform and re-NaN'd after, as in the reference (ref :177-190).
    """
    params = dict(DEFAULT_CSD_PARAMS, **(csd_params or {}))
    n = X.shape[1]
    assert n >= 1, f"{n} < 1"
    X = np.expand_dims(X.T, axis=0).astype(np.float64)  # (1, C, T)

    nan_mask = np.sum(np.isnan(X), axis=(1, 2)) != 0
    if nan_mask.any():
        rng = rng or np.random.default_rng()
        X[nan_mask] = rng.standard_normal(X[nan_mask].shape)
    f, cpsd = csd(X[:, :, np.newaxis], X[:, np.newaxis], fs=fs, **params)
    i1, i2 = np.searchsorted(f, [min_freq, max_freq])
    f = f[i1:i2]
    cpsd = np.abs(cpsd[..., i1:i2])
    cpsd = squeeze_triangular_array(cpsd, dims=(1, 2))
    cpsd = cpsd * f  # scale power features by frequency (ref :189)
    cpsd[nan_mask] = np.nan

    res = {"power": cpsd, "freq": f}

    if directed_spectrum:
        f_temp, dir_spec = get_directed_spectrum(X, fs, csd_params=params)
        f_temp = f_temp[i1:i2]
        assert np.allclose(f, f_temp), f"Frequencies don't match:\n{f}\n{f_temp}"
        dir_spec = dir_spec[:, i1:i2] * f_temp.reshape(1, -1, 1, 1)
        dir_spec = np.moveaxis(dir_spec, 1, -1)  # (1, C, C, F)
        dir_spec[nan_mask] = np.nan
        res["dir_spec"] = dir_spec
    return res


# ------------------------------------------------------------- LFP filters

DEFAULT_MAD_THRESHOLD = 15.0
LOW_PASS_CUTOFF = 35.0
LOWCUT = 30.0
HIGHCUT = 55.0
Q = 2.0
ORDER = 3


def _apply_notch_filters(x, fs, q):
    """Remove 60 Hz electrical noise and harmonics (ref time_series.py:294-298)."""
    for i, freq in enumerate(range(60, int(fs / 2), 60)):
        b, a = iirnotch(freq, (i + 1) * q, fs)
        x = lfilter(b, a, x)
    return x


def filter_signal_via_bandpass(x, fs, lowcut=LOWCUT, highcut=HIGHCUT, q=Q,
                               order=ORDER, apply_notch_filters=True):
    """Butterworth bandpass + optional notch filters, NaN-transparent
    (ref time_series.py:263-301)."""
    assert x.ndim == 1 and lowcut < highcut
    x = np.array(x, dtype=np.float64, copy=True)
    nan_mask = np.isnan(x)
    x[nan_mask] = 0.0
    nyq = 0.5 * fs
    b, a = butter(order, [lowcut / nyq, highcut / nyq], btype="band")
    x = lfilter(b, a, x)
    if apply_notch_filters:
        x = _apply_notch_filters(x, fs, q)
    x[nan_mask] = np.nan
    return x


def filter_signal_via_lowpass(x, fs, cutoff=LOW_PASS_CUTOFF, q=Q, order=ORDER,
                              apply_notch_filters=True):
    """Butterworth lowpass + optional notch filters (ref time_series.py:303-338)."""
    assert x.ndim == 1
    x = np.array(x, dtype=np.float64, copy=True)
    nan_mask = np.isnan(x)
    x[nan_mask] = 0.0
    b, a = butter(order, cutoff / (0.5 * fs), btype="lowpass")
    x = lfilter(b, a, x)
    if apply_notch_filters:
        x = _apply_notch_filters(x, fs, q)
    x[nan_mask] = np.nan
    return x


def filter_signal(x, fs, cutoff=LOW_PASS_CUTOFF, lowcut=LOWCUT, highcut=HIGHCUT,
                  q=Q, order=ORDER, apply_notch_filters=True,
                  filter_type="bandpass"):
    if filter_type == "bandpass":
        return filter_signal_via_bandpass(
            x, fs, lowcut=lowcut, highcut=highcut, q=q, order=order,
            apply_notch_filters=apply_notch_filters)
    if filter_type == "lowpass":
        return filter_signal_via_lowpass(
            x, fs, cutoff=cutoff, q=q, order=order,
            apply_notch_filters=apply_notch_filters)
    raise NotImplementedError(filter_type)


def mark_outliers(lfps, fs, cutoff=LOW_PASS_CUTOFF, lowcut=LOWCUT,
                  highcut=HIGHCUT, mad_threshold=DEFAULT_MAD_THRESHOLD,
                  filter_type="bandpass"):
    """NaN-mask samples whose filtered magnitude exceeds mad_threshold median
    absolute deviations (ref time_series.py:351-390). lfps: dict of 1-D traces
    (modified copies returned) or a single 1-D array."""
    assert mad_threshold > 0.0, "mad_threshold must be positive!"
    single = not isinstance(lfps, dict)
    traces = {"_": lfps} if single else lfps
    out = {}
    for roi, sig in traces.items():
        trace = filter_signal(np.copy(sig), fs, cutoff=cutoff, lowcut=lowcut,
                              highcut=highcut, apply_notch_filters=False,
                              filter_type=filter_type)
        trace = np.abs(trace - np.median(trace))
        thresh = mad_threshold * np.median(trace)
        marked = np.array(sig, dtype=np.float64, copy=True)
        marked[trace > thresh] = np.nan
        out[roi] = marked
    return out["_"] if single else out


# ------------------------------------------------------------ window draws

def _window_hits_nan(start, window_size, nan_locations):
    nan_locations = np.asarray(nan_locations)
    if nan_locations.size == 0:
        return False
    # sorted-array range check (nan locations come from flatnonzero, sorted)
    lo = np.searchsorted(nan_locations, start, side="left")
    return lo < nan_locations.size and nan_locations[lo] <= start + window_size


def draw_timesteps_to_sample_from(interval_start, interval_stop, window_size,
                                  num_samples, nan_locations, max_num_draws=10,
                                  rng=None):
    """Draw non-NaN-overlapping window starts inside an interval; failed draws
    are retried up to max_num_draws then dropped (ref time_series.py:393-407)."""
    rng = rng or np.random.default_rng()
    lo, hi = interval_start, interval_stop - window_size
    starts = list(rng.choice(np.arange(lo, hi), size=num_samples, replace=False))
    for i in range(len(starts) - 1, -1, -1):
        if _window_hits_nan(starts[i], window_size, nan_locations):
            starts[i] = None
            for _ in range(max_num_draws):
                cand = int(rng.integers(lo, hi))
                if cand not in starts and not _window_hits_nan(
                        cand, window_size, nan_locations):
                    starts[i] = cand
                    break
            if starts[i] is None:
                starts.pop(i)
    return [int(s) for s in starts]


def draw_timesteps_to_sample_from_using_label_reference(
        labels, window_size, num_samples, nan_locations, max_num_draws=10,
        rng=None):
    """Like draw_timesteps_to_sample_from, additionally requiring the binary
    label trace to be active across the whole window (ref time_series.py:411-425)."""
    rng = rng or np.random.default_rng()
    labels = np.asarray(labels)
    hi = len(labels) - window_size

    def ok(start, others=()):
        return (start not in others
                and not _window_hits_nan(start, window_size, nan_locations)
                and labels[start: start + window_size].sum() == window_size)

    starts = list(rng.choice(np.arange(hi), size=num_samples, replace=False))
    for i in range(len(starts) - 1, -1, -1):
        if not ok(starts[i]):
            starts[i] = None
            for _ in range(max_num_draws):
                cand = int(rng.integers(0, hi))
                if ok(cand, starts):
                    starts[i] = cand
                    break
            if starts[i] is None:
                starts.pop(i)
    return [int(s) for s in starts]
