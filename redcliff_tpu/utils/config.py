"""Legacy cached-args config compatibility layer.

The reference's config system is two levels of stringly-typed JSON
(/root/reference/general_utils/input_argument_utils.py): model cached-args
(every value a string, parsed per model family, "None"/"inf" sentinels,
"[1,2]" int lists) and data cached-args carrying paths, channel counts, and
ground-truth adjacency tensors serialized as Python-repr strings.  This module
reads both formats so reference datasets and experiment configs run unchanged
(SURVEY.md §7 design delta 5), minus the reference's matplotlib side effects.
"""
from __future__ import annotations

import copy
import json

import numpy as np

__all__ = [
    "parse_input_list_of_ints",
    "parse_input_list_of_strs",
    "parse_tensor_string_representation",
    "serialize_tensor_to_string",
    "read_in_data_adjacency_matrices",
    "read_in_model_args",
    "read_in_data_args",
    "load_true_gc_factors",
]


def load_true_gc_factors(data_cached_args_file,
                         model_type="REDCLIFF_S_CMLP"):
    """The per-dataset true factor graphs from a cached-args file — the one
    place the eval layer goes through the cached-args truth contract
    (``model_type`` only selects the parsing schema; the default reads the
    most generic format, ref eval_utils.py:33)."""
    args = read_in_data_args({"model_type": model_type,
                              "data_cached_args_file": data_cached_args_file},
                             read_in_gc_factors_for_eval=True)
    return args["true_GC_factors"]


def parse_input_list_of_ints(list_string):
    """'[1,2,3]' -> [1, 2, 3] (ref input_argument_utils.py:10-18)."""
    if list_string == "[]":
        return []
    return [int(chars) for chars in list_string[1:-1].split(",")]


def parse_input_list_of_strs(list_string):
    """'[a,b]' -> ['a', 'b'] (ref :21-29; whitespace kept, as published)."""
    if list_string == "[]":
        return []
    return list(list_string[1:-1].split(","))


def parse_tensor_string_representation(tensor_string):
    """Parse a '[[[...]]]' Python-repr 3D tensor string (ref :32-49).

    Lagged adjacency tensors are stored lag-major ('[[[..C..]..C..]..L..]');
    square slices are transposed to (C, C, L).  The single-element case
    follows the reference's special-path.
    """
    if ",],],]" in tensor_string:
        slices = [[[float(tensor_string[3:-6])]]]
    else:
        slices = tensor_string[3:-3].split("]], [[")
        for i, matrix_slice in enumerate(slices):
            rows = matrix_slice.split("], [")
            slices[i] = [[float(x) for x in row.split(",")] for row in rows]
    tensor = np.array(slices)
    assert tensor.ndim == 3
    if tensor.shape[1] == tensor.shape[2]:
        tensor = np.transpose(tensor, axes=[1, 2, 0])
    assert tensor.shape[0] == tensor.shape[1]
    return tensor


def serialize_tensor_to_string(tensor, reverse_lags=True):
    """Inverse writer for data cached-args: (C, C, L) -> repr string in the
    curation's on-disk format (reverse-lag-major, which the readers correct
    back — ref data/data_utils.py:32-45 + input_argument_utils.py:62).
    Contract: parse_tensor_string_representation(s)[:, :, ::-1] == tensor."""
    tensor = np.asarray(tensor)
    assert tensor.ndim == 3
    if reverse_lags:
        tensor = tensor[:, :, ::-1]
    lag_major = np.transpose(tensor, (2, 0, 1))
    return repr([[list(map(float, row)) for row in sl] for sl in lag_major])


def _factor_index(key):
    """'net<i>_adjacency_tensor' -> i-1, parsing the full integer (the
    reference reads only key[3], breaking at 10+ factors — ref :65)."""
    assert key.startswith("net"), key
    return int(key[3 : key.index("_")]) - 1


def _fill_gc_views(args_dict, lagged_by_index):
    """Per-factor lagged + nontemporal views and their sums
    (ref :51-93 read_in_data_adjacency_matrices semantics).  The factor list
    keeps the reference's minimum of 4 slots but grows with the data."""
    n_slots = max(4, max(lagged_by_index, default=-1) + 1)
    args_dict["true_lagged_GC_tensor"] = None
    args_dict["true_nontemporal_GC_tensor"] = None
    args_dict["true_lagged_GC_tensor_factors"] = [None] * n_slots
    args_dict["true_nontemporal_GC_tensor_factors"] = [None] * n_slots
    for idx in sorted(lagged_by_index):
        lagged = lagged_by_index[idx]
        nontemporal = lagged.sum(axis=2)
        args_dict["true_lagged_GC_tensor_factors"][idx] = lagged
        args_dict["true_nontemporal_GC_tensor_factors"][idx] = nontemporal
        if args_dict["true_lagged_GC_tensor"] is None:
            args_dict["true_lagged_GC_tensor"] = lagged
            args_dict["true_nontemporal_GC_tensor"] = nontemporal
        else:
            args_dict["true_lagged_GC_tensor"] = \
                args_dict["true_lagged_GC_tensor"] + lagged
            args_dict["true_nontemporal_GC_tensor"] = \
                args_dict["true_nontemporal_GC_tensor"] + nontemporal
    return args_dict


def read_in_data_adjacency_matrices(args_dict, cached_args_file_path):
    """Load per-factor true GC tensors from a data cached-args file
    (ref :51-93, minus the plotting side effects).  Lagged tensors are stored
    reverse-lag-major and corrected here (ref :62)."""
    with open(cached_args_file_path, "r") as f:
        data_args = json.load(f)
    lagged_by_index = {
        _factor_index(key):
            parse_tensor_string_representation(val)[:, :, ::-1].copy()
        for key, val in data_args.items() if "adjacency_tensor" in key
    }
    return _fill_gc_views(args_dict, lagged_by_index)


def _opt(value, cast=str):
    return None if value == "None" else cast(value)


def _read_redcliff_common(args_dict, a):
    """Shared REDCLIFF(+_S_) fields (ref :136-195 / :332-398)."""
    args_dict["num_factors"] = int(a["num_factors"])
    args_dict["num_supervised_factors"] = int(a["num_supervised_factors"])
    model_type = args_dict["model_type"]
    if "_S_" in model_type:
        args_dict["use_sigmoid_restriction"] = bool(
            int(a["use_sigmoid_restriction"]))
        args_dict["factor_score_embedder_type"] = \
            a["factor_score_embedder_type"]
        emb_type = a["factor_score_embedder_type"]
        if emb_type == "cEmbedder":
            args_dict["factor_score_embedder_args"] = [
                ("sigmoid_eccentricity_coeff",
                 float(a["sigmoid_eccentricity_coeff"])),
                ("lag", int(a["embed_lag"])),
                ("hidden", copy.deepcopy(args_dict["embed_hidden_sizes"])),
            ]
        elif emb_type == "DGCNN":
            args_dict["factor_score_embedder_args"] = [
                ("num_features_per_node", int(a["embed_lag"])),
                ("num_graph_conv_layers",
                 int(a["embed_num_graph_conv_layers"])),
                ("num_hidden_nodes", int(a["embed_num_hidden_nodes"])),
                ("sigmoid_eccentricity_coeff",
                 float(a["sigmoid_eccentricity_coeff"])),
            ]
        elif emb_type == "Vanilla_Embedder":
            args_dict["factor_score_embedder_args"] = []
        else:
            raise ValueError(
                f"UNRECOGNIZED factor_score_embedder_type == {emb_type}")
        args_dict["primary_gc_est_mode"] = a["primary_gc_est_mode"]
        args_dict["forward_pass_mode"] = a["forward_pass_mode"]

    cd = args_dict["coeff_dict"]
    cd["FACTOR_SCORE_COEFF"] = float(a["FACTOR_SCORE_COEFF"])
    cd["DAGNESS_REG_COEFF"] = float(a["DAGNESS_REG_COEFF"])
    cd["DAGNESS_LAG_COEFF"] = float(a["DAGNESS_LAG_COEFF"])
    cd["DAGNESS_NODE_COEFF"] = float(a["DAGNESS_NODE_COEFF"])
    if "_S_" in model_type:
        cd["FACTOR_WEIGHT_L1_COEFF"] = float(a["FACTOR_WEIGHT_L1_COEFF"])
        cd["FACTOR_COS_SIM_COEFF"] = float(a["FACTOR_COS_SIM_COEFF"])
        if "FACTOR_WEIGHT_SMOOTHING_PENALTY_COEFF" in a:
            cd["FACTOR_WEIGHT_SMOOTHING_PENALTY_COEFF"] = float(
                a["FACTOR_WEIGHT_SMOOTHING_PENALTY_COEFF"])
    args_dict["training_mode"] = a["training_mode"]
    args_dict["embed_lr"] = float(a["embed_lr"])
    args_dict["embed_eps"] = float(a["embed_eps"])
    args_dict["embed_weight_decay"] = float(a["embed_weight_decay"])
    args_dict["num_pretrain_epochs"] = int(a["num_pretrain_epochs"])
    if "_S_" in model_type:
        args_dict["num_acclimation_epochs"] = int(a["num_acclimation_epochs"])
    args_dict["prior_factors_path"] = _opt(a["prior_factors_path"])
    args_dict["cost_criteria"] = a["cost_criteria"]
    args_dict["unsupervised_start_index"] = int(a["unsupervised_start_index"])
    args_dict["max_factor_prior_batches"] = int(a["max_factor_prior_batches"])
    args_dict["stopping_criteria_forecast_coeff"] = float(
        a["stopping_criteria_forecast_coeff"])
    args_dict["stopping_criteria_factor_coeff"] = float(
        a["stopping_criteria_factor_coeff"])
    args_dict["stopping_criteria_cosSim_coeff"] = float(
        a["stopping_criteria_cosSim_coeff"])
    args_dict["deltaConEps"] = float(a["deltaConEps"])
    args_dict["in_degree_coeff"] = float(a["in_degree_coeff"])
    args_dict["out_degree_coeff"] = float(a["out_degree_coeff"])


def read_in_model_args(args_dict):
    """Per-model-family cached-args schema reader (ref :95-466).

    args_dict must carry "model_type" and "model_cached_args_file"; returns
    args_dict with the family's typed fields filled in.
    """
    model_type = args_dict["model_type"]
    with open(args_dict["model_cached_args_file"], "r") as f:
        a = json.load(f)

    is_redcliff = "REDCLIFF" in model_type

    if "cMLP" in model_type or ("CMLP" in model_type and is_redcliff):
        args_dict["num_sims"] = int(a["num_sims"])
        args_dict["embed_hidden_sizes"] = parse_input_list_of_ints(
            a["embed_hidden_sizes"])
        args_dict["batch_size"] = int(a["batch_size"])
        args_dict["gen_eps"] = float(a["gen_eps"])
        args_dict["gen_weight_decay"] = float(a["gen_weight_decay"])
        args_dict["max_iter"] = int(a["max_iter"])
        args_dict["lookback"] = int(a["lookback"])
        args_dict["check_every"] = int(a["check_every"])
        args_dict["verbose"] = int(a["verbose"])
        args_dict["output_length"] = int(a["output_length"])
        args_dict["wavelet_level"] = _opt(a["wavelet_level"], int)
        args_dict["gen_hidden"] = parse_input_list_of_ints(a["gen_hidden"])
        args_dict["gen_lr"] = float(a["gen_lr"])
        args_dict["input_length"] = int(a["gen_lag_and_input_len"])
        args_dict["gen_lag"] = int(a["gen_lag_and_input_len"])
        args_dict["coeff_dict"] = {
            "FORECAST_COEFF": float(a["FORECAST_COEFF"]),
            "ADJ_L1_REG_COEFF": float(a["ADJ_L1_REG_COEFF"]),
        }
        args_dict["signal_format"] = (
            "wavelet_decomp" if args_dict["wavelet_level"] is not None
            else "original")
        if not is_redcliff:
            for key in ("DAGNESS_REG_COEFF", "DAGNESS_LAG_COEFF",
                        "DAGNESS_NODE_COEFF"):
                args_dict["coeff_dict"][key] = float(a[key])
        else:
            if "_S_" in model_type:
                args_dict["embed_lag"] = int(a["embed_lag"])
            _read_redcliff_common(args_dict, a)

    elif "cLSTM" in model_type or ("CLSTM" in model_type and is_redcliff):
        args_dict["num_sims"] = int(a["num_sims"])
        args_dict["embed_hidden_sizes"] = parse_input_list_of_ints(
            a["embed_hidden_sizes"])
        args_dict["coeff_dict"] = {
            "FORECAST_COEFF": float(a["FORECAST_COEFF"]),
            "ADJ_L1_REG_COEFF": float(a["ADJ_L1_REG_COEFF"]),
            "DAGNESS_REG_COEFF": float(a["DAGNESS_REG_COEFF"]),
        }
        args_dict["batch_size"] = int(a["batch_size"])
        args_dict["gen_eps"] = float(a["gen_eps"])
        args_dict["gen_weight_decay"] = float(a["gen_weight_decay"])
        args_dict["max_iter"] = int(a["max_iter"])
        args_dict["lookback"] = int(a["lookback"])
        args_dict["check_every"] = int(a["check_every"])
        args_dict["verbose"] = int(a["verbose"])
        args_dict["wavelet_level"] = _opt(a["wavelet_level"], int)
        args_dict["gen_hidden"] = int(a["gen_hidden"])
        args_dict["gen_lr"] = float(a["gen_lr"])
        args_dict["context"] = int(a["context"])
        args_dict["max_input_length"] = int(a["max_input_length"])
        args_dict["signal_format"] = (
            "wavelet_decomp" if args_dict["wavelet_level"] is not None
            else "original")
        if is_redcliff:
            if "_S_" in model_type:
                args_dict["num_in_timesteps"] = int(a["embed_lag"])
            _read_redcliff_common(args_dict, a)
            # the reference zeroes lag/node DAGness for CLSTM (ref :248-249)
            args_dict["coeff_dict"]["DAGNESS_LAG_COEFF"] = 0
            args_dict["coeff_dict"]["DAGNESS_NODE_COEFF"] = 0

    elif "DCSFA" in model_type:
        args_dict["batch_size"] = int(a["batch_size"])
        args_dict["best_model_name"] = a["best_model_name"]
        args_dict["num_high_level_node_features"] = int(
            a["num_high_level_node_features"])
        args_dict["num_node_features"] = int(a["num_node_features"])
        args_dict["n_components"] = int(a["n_components"])
        args_dict["n_sup_networks"] = int(a["n_sup_networks"])
        args_dict["signal_format"] = a["signal_format"]
        args_dict["h"] = int(a["h"])
        args_dict["momentum"] = float(a["momentum"])
        args_dict["lr"] = float(a["lr"])
        args_dict["recon_weight"] = float(a["recon_weight"])
        args_dict["sup_weight"] = float(a["sup_weight"])
        args_dict["sup_recon_weight"] = float(a["sup_recon_weight"])
        args_dict["sup_smoothness_weight"] = float(a["sup_smoothness_weight"])
        args_dict["n_epochs"] = int(a["n_epochs"])
        args_dict["n_pre_epochs"] = int(a["n_pre_epochs"])
        args_dict["nmf_max_iter"] = int(a["nmf_max_iter"])
        nnf = args_dict["num_node_features"]
        # recordings are truncated to num_node_features steps before feature
        # extraction (ref model_utils.py:692-717 max_num_features_per_series)
        args_dict["max_num_features_per_series"] = nnf
        args_dict["dirspec_params"] = {
            "fs": 1000, "min_freq": 0.0, "max_freq": 250.0,
            "directed_spectrum": True,
            "csd_params": {"detrend": "constant", "window": "hann",
                           "nperseg": nnf, "noverlap": int(nnf * 0.5),
                           "nfft": None},
        }  # (ref input_argument_utils.py:297-309)

    elif "DGCNN" in model_type:
        if not is_redcliff:
            args_dict["num_classes"] = int(a["num_classes"])
            args_dict["batch_size"] = int(a["batch_size"])
            args_dict["gen_eps"] = float(a["gen_eps"])
            args_dict["gen_weight_decay"] = float(a["gen_weight_decay"])
            args_dict["max_iter"] = int(a["max_iter"])
            args_dict["lookback"] = int(a["lookback"])
            args_dict["check_every"] = int(a["check_every"])
            args_dict["verbose"] = int(a["verbose"])
            args_dict["num_features_per_node"] = int(
                a["num_features_per_node"])
            args_dict["num_graph_conv_layers"] = int(
                a["num_graph_conv_layers"])
            args_dict["num_hidden_nodes"] = int(a["num_hidden_nodes"])
            args_dict["wavelet_level"] = (
                0 if a["wavelet_level"] == "None" else int(a["wavelet_level"]))
            args_dict["num_wavelets_per_chan"] = int(
                a["num_wavelets_per_chan"])
            args_dict["gen_lr"] = float(a["gen_lr"])
            args_dict["signal_format"] = (
                "wavelet_decomp" if args_dict["wavelet_level"] else "original")
        else:
            args_dict["num_sims"] = int(a["num_sims"])
            args_dict["embed_hidden_sizes"] = parse_input_list_of_ints(
                a["embed_hidden_sizes"])
            args_dict["coeff_dict"] = {
                "FORECAST_COEFF": float(a["FORECAST_COEFF"]),
                "ADJ_L1_REG_COEFF": float(a["ADJ_L1_REG_COEFF"]),
                "DAGNESS_REG_COEFF": float(a["DAGNESS_REG_COEFF"]),
            }
            if "_S_" in model_type:
                args_dict["embed_num_features_per_node"] = int(a["embed_lag"])
            _read_redcliff_common(args_dict, a)
            args_dict["coeff_dict"]["DAGNESS_LAG_COEFF"] = 0
            args_dict["coeff_dict"]["DAGNESS_NODE_COEFF"] = 0

    elif "DYNOTEARS" in model_type:
        args_dict["signal_format"] = a["signal_format"]
        args_dict["lambda_w"] = float(a["lambda_w"])
        args_dict["lambda_a"] = float(a["lambda_a"])
        args_dict["max_iter"] = int(a["max_iter"])
        args_dict["h_tol"] = float(a["h_tol"])
        args_dict["w_threshold"] = float(a["w_threshold"])
        args_dict["tabu_edges"] = _opt(a["tabu_edges"])
        args_dict["tabu_parent_nodes"] = _opt(a["tabu_parent_nodes"])
        args_dict["tabu_child_nodes"] = _opt(a["tabu_child_nodes"])
        args_dict["X_train"] = None
        args_dict["X_val"] = None
        args_dict["lag_size"] = int(a["lag_size"])
        if "Vanilla" not in model_type:
            args_dict["batch_size"] = int(a["batch_size"])
            args_dict["grad_step"] = float(a["grad_step"])
            args_dict["wa_est"] = _opt(a["wa_est"])
            args_dict["rho"] = float(a["rho"])
            args_dict["alpha"] = float(a["alpha"])
            args_dict["h_value"] = (np.inf if a["h_value"] == "inf"
                                    else float(a["h_value"]))
            args_dict["h_new"] = (np.inf if a["h_new"] == "inf"
                                  else float(a["h_new"]))
            args_dict["max_data_iter"] = int(a["max_data_iter"])
            args_dict["iter_start"] = int(a["iter_start"])
            args_dict["num_iters_prior_to_stop"] = int(
                a["num_iters_prior_to_stop"])
            args_dict["reuse_rho"] = bool(int(a["reuse_rho"]))
            args_dict["reuse_alpha"] = bool(int(a["reuse_alpha"]))
            args_dict["reuse_h_val"] = bool(int(a["reuse_h_val"]))
            args_dict["reuse_h_new"] = bool(int(a["reuse_h_new"]))
            args_dict["check_every"] = int(a["check_every"])

    elif "NAVAR" in model_type:
        args_dict["num_nodes"] = int(a["num_nodes"])
        args_dict["num_hidden"] = int(a["num_hidden"])
        args_dict["maxlags"] = int(a["maxlags"])
        args_dict["hidden_layers"] = int(a["hidden_layers"])
        args_dict["dropout"] = float(a["dropout"])
        args_dict["X_train"] = None
        args_dict["y_train"] = None
        args_dict["X_val"] = None
        args_dict["y_val"] = None
        args_dict["batch_size"] = int(a["batch_size"])
        args_dict["signal_format"] = a.get("signal_format", "original")
        for key in ("epochs", "val_proportion", "learning_rate",
                    "lambda1", "check_every", "verbose"):
            if key in a:
                cast = (int if key in ("epochs", "check_every", "verbose")
                        else float)
                args_dict[key] = cast(a[key])

    else:
        raise ValueError(f"UNRECOGNIZED model_type == {model_type}")

    return args_dict


def read_in_data_args(args_dict, include_gc_views_for_eval=False,
                      read_in_gc_factors_for_eval=False):
    """Data cached-args reader (ref :467-682, minus plotting).

    Fills data_root_path / num_channels and the family-appropriate true-GC
    views: cMLP/REDCLIFF keep per-factor lagged tensors; cLSTM/DCSFA/DGCNN
    collapse lags; read_in_gc_factors_for_eval=True returns the per-factor
    lagged tensors regardless of family (used by eval drivers,
    ref eval_sysOptF1...py:71).
    """
    with open(args_dict["data_cached_args_file"], "r") as f:
        a = json.load(f)
    args_dict["data_root_path"] = a["data_root_path"]
    args_dict["num_channels"] = int(a["num_channels"])
    model_type = args_dict.get("model_type", "")

    lagged_tensors = {}
    for key, val in a.items():
        if "adjacency_tensor" in key:
            t = parse_tensor_string_representation(val)
            lagged_tensors[key] = t[:, :, ::-1].copy()

    # order by the parsed factor index, not lexicographically (net10 < net2)
    keys_sorted = sorted(lagged_tensors, key=_factor_index)
    if read_in_gc_factors_for_eval:
        args_dict["true_GC_factors"] = [lagged_tensors[k]
                                        for k in keys_sorted]

    if "cMLP" in model_type or "REDCLIFF" in model_type:
        factors = [lagged_tensors[k] for k in keys_sorted]
        args_dict["true_GC_factors"] = factors
        total = None
        for t in factors:
            total = t if total is None else total + t
        # the reference overwrites the sum with the LAST factor at :493
        # (latent bug); here the summed tensor is kept deliberately
        args_dict["true_GC_tensor"] = [total] if factors else None
    elif model_type:
        # every lag-collapsing family (cLSTM/DCSFA/DGCNN/DYNOTEARS/NAVAR)
        # shares the summed nontemporal view (ref :494-660)
        total = None
        for k in keys_sorted:
            nt = lagged_tensors[k].sum(axis=2)
            total = nt if total is None else total + nt
        args_dict["true_GC_tensor"] = [total] if total is not None else None

    if include_gc_views_for_eval:
        # lagged + nontemporal per-factor views (ref :644-660), derived from
        # the tensors already parsed above
        _fill_gc_views(args_dict, {_factor_index(k): lagged_tensors[k]
                                   for k in keys_sorted})

    for extra in ("num_samples", "num_folds", "num_states",
                  "sample_recording_len"):
        if extra in a:
            args_dict[extra] = int(a[extra])
    if "data_set_name" in a and "data_set_name" not in args_dict:
        args_dict["data_set_name"] = a["data_set_name"]
    return args_dict
