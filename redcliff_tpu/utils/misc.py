"""GC-tensor plumbing and small host-side helpers.

Semantics-parity rebuild of /root/reference/general_utils/misc.py: top-k edge
filters, normalization/diagonal masking, Hungarian alignment of unsupervised factor
estimates, flatten/unflatten of lagged GC tensors and directed-spectrum features,
and k-fold CV split construction.
"""
from __future__ import annotations

import numpy as np

from redcliff_tpu.utils.metrics import (
    compute_cosine_similarity,
    solve_linear_sum_assignment_between_graph_options,
)

__all__ = [
    "apply_top_k_filter_to_edges",
    "normalize_array",
    "mask_diag_elements",
    "place_on_zero_to_one_scale",
    "sort_unsupervised_estimates",
    "factor_alignment_order",
    "get_avg_cosine_similarity_between_combos",
    "get_topk_graph_mask",
    "get_preds_from_masked_normalized_matrix",
    "flatten_gc_with_lags",
    "unflatten_gc_with_lags",
    "flatten_directed_spectrum_features",
    "unflatten_directed_spectrum_features",
    "make_kfolds_cv_splits",
]


def apply_top_k_filter_to_edges(A, k=None):
    """Zero all but the k largest entries (ref misc.py:21-37)."""
    if k is None:
        return A
    A = np.asarray(A)
    flat = A.ravel()
    # k may exceed the entry count; the reference's list slice [-k:] then keeps
    # every entry, so clamp rather than raise
    kth_largest = np.sort(flat)[-min(k, flat.size)]
    return np.where(A >= kth_largest, A, 0.0)


def normalize_array(A):
    """Scale by the max entry (ref misc.py:39-40)."""
    A = np.asarray(A)
    return A / np.max(A)


def mask_diag_elements(A):
    """Zero the diagonal of a square matrix, returning a copy (ref misc.py:42-48)."""
    A = np.array(A, copy=True)
    assert A.ndim == 2 and A.shape[0] == A.shape[1]
    np.fill_diagonal(A, 0.0)
    return A


def place_on_zero_to_one_scale(elements):
    """Min-max rescale a list of scalars (ref misc.py:50-55)."""
    lo = np.min(elements)
    hi = np.max(elements)
    return [float((x - lo) / (hi - lo)) for x in elements]


def sort_unsupervised_estimates(
    graph_estimates,
    true_graphs,
    cost_criteria="CosineSimilarity",
    unsupervised_start_index=0,
    return_sorting_inds=False,
):
    """Hungarian-align unsupervised factor estimates to ground-truth graphs
    (ref misc.py:83-91): estimates before unsupervised_start_index keep their
    position; the remainder are permuted to their matched truth slots, with any
    unmatched estimates appended."""
    tail_est = list(graph_estimates[unsupervised_start_index:])
    tail_true = list(true_graphs[unsupervised_start_index:])
    matched_est, matched_true = solve_linear_sum_assignment_between_graph_options(
        tail_est, tail_true, cost_criteria=cost_criteria
    )
    sorted_ests = [None] * len(tail_true)
    for est_ind, gt_ind in zip(matched_est, matched_true):
        sorted_ests[gt_ind] = tail_est[est_ind]
    unsorted = [tail_est[i] for i in range(len(tail_est)) if i not in matched_est]
    result = list(graph_estimates[:unsupervised_start_index]) + sorted_ests + unsorted
    if return_sorting_inds:
        return result, matched_est, matched_true
    return result


def factor_alignment_order(preds, labels, num_factors, unsupervised_start_index=0):
    """Permutation of range(num_factors) aligning factor indices to supervised
    labels via Hungarian assignment on the predicted factor-weighting series
    (ref redcliff_s_cmlp.py:147-202 initialize_factors_with_prior).

    preds: (N, K) factor-weighting predictions; labels: (N, S) label traces.
    Factors before unsupervised_start_index keep their position. The matched-slot
    list is sized by the LABEL count (S may exceed the match count when labels
    carry more columns than factors), so no index can overflow it.
    """
    preds = np.asarray(preds)
    labels = np.asarray(labels)
    usi = unsupervised_start_index
    est_series = [preds[:, i] for i in range(preds.shape[1])]
    true_series = [labels[:, i] for i in range(labels.shape[1])]
    _, matched_est, matched_gt = sort_unsupervised_estimates(
        est_series, true_series, unsupervised_start_index=usi,
        return_sorting_inds=True)
    K = num_factors
    tail = list(range(usi, K))
    order_tail = [None] * (len(true_series) - usi)
    for e, g in zip(matched_est, matched_gt):
        order_tail[g] = tail[e]
    unmatched = [tail[i] for i in range(len(tail)) if i not in list(matched_est)]
    order = list(range(usi)) + [o for o in order_tail if o is not None] + unmatched
    order = order + [k for k in range(K) if k not in order]
    return order[:K]


def get_avg_cosine_similarity_between_combos(elements):
    """Mean pairwise cosine similarity after per-element max-normalization
    (ref misc.py:93-104)."""
    total, count = 0.0, 0
    for i in range(len(elements)):
        for j in range(i + 1, len(elements)):
            a = np.asarray(elements[i]) / np.max(elements[i])
            b = np.asarray(elements[j]) / np.max(elements[j])
            total += compute_cosine_similarity(a, b)
            count += 1
    return total / count


def get_topk_graph_mask(A, k, for_no_lag=True):
    """Keep entries >= the k-th largest value; optionally lag-summed first
    (ref misc.py:106-112)."""
    A = np.asarray(A)
    if for_no_lag:
        A = A.sum(axis=2)
    kth = np.sort(A.ravel())[-k]
    return (A >= kth) * A, kth


def get_preds_from_masked_normalized_matrix(matrix, pred_scale, mask_thresh):
    """Max-normalize, threshold-mask, rescale (ref misc.py:114-122)."""
    matrix = np.asarray(matrix, dtype=np.float64)
    matrix = matrix / np.max(matrix)
    return pred_scale * matrix * (matrix >= mask_thresh)


def flatten_gc_with_lags(GC):
    """(m, n, L) -> (m, n*L) with lag-major column blocks (ref misc.py:131-138)."""
    GC = np.asarray(GC)
    m, n, L = GC.shape
    return np.transpose(GC, (0, 2, 1)).reshape(m, n * L)


def unflatten_gc_with_lags(GC):
    """(m, m*L) -> (m, m, L) inverse of flatten_gc_with_lags (ref misc.py:140-146)."""
    GC = np.asarray(GC)
    m = GC.shape[0]
    L = GC.shape[1] // m
    return np.transpose(GC.reshape(m, L, m), (0, 2, 1))


def flatten_directed_spectrum_features(x):
    """(n, n, m) directed-spectrum tensor -> (n, m*(2n-1)) row layout
    (ref misc.py:159-176): for each feature block, row j holds x[j, :, i] followed
    by the off-diagonal column entries x[:j, j, i] and x[j+1:, j, i]."""
    x = np.asarray(x)
    assert x.ndim == 3 and x.shape[0] == x.shape[1]
    n, _, m = x.shape
    x_flat = np.zeros((n, m * (2 * n - 1)), dtype=x.dtype)
    for i in range(m):
        c0 = i * (2 * n - 1)
        for j in range(n):
            x_flat[j, c0 : c0 + n] = x[j, :, i]
            x_flat[j, c0 + n : c0 + n + j] = x[:j, j, i]
            x_flat[j, c0 + n + j : c0 + (2 * n - 1)] = x[j + 1 :, j, i]
    return x_flat


def unflatten_directed_spectrum_features(x_flat, accumulate_shared_entries=False):
    """Inverse of flatten_directed_spectrum_features (ref misc.py:178-195).

    The reference's implementation ACCUMULATES the row and column writes
    (``x[...] = x_flat[...] + x[...]``), so every off-diagonal entry — which
    appears in two nodes' flattened rows — comes out doubled; it is not a
    true inverse. ``accumulate_shared_entries=True`` reproduces that exactly
    (the reference's only call site, the DCSFA GC readout
    ref dcsfa_nmf.py:1305, inherits the doubling); the default keeps the
    exact inverse for feature round-trips.
    """
    x_flat = np.asarray(x_flat)
    assert x_flat.ndim == 2
    n = x_flat.shape[0]
    m = x_flat.shape[1] // (2 * n - 1)
    # float64 output like the reference's np.zeros (also keeps the halving
    # below exact for integer inputs)
    x = np.zeros((n, n, m))
    for i in range(m):
        c0 = i * (2 * n - 1)
        for j in range(n):
            x[j, :, i] += x_flat[j, c0 : c0 + n]
            x[:j, j, i] += x_flat[j, c0 + n : c0 + n + j]
            x[j + 1 :, j, i] += x_flat[j, c0 + n + j : c0 + (2 * n - 1)]
    if not accumulate_shared_entries:
        # halve the doubled off-diagonal entries back to the true inverse
        off = ~np.eye(n, dtype=bool)
        x[off] *= 0.5
    return x


def make_kfolds_cv_splits(data, labels, num_folds=10):
    """Sequential (non-shuffled) k-fold CV splits keyed by fold id
    (ref misc.py:197-220). Each fold maps to {"train": [[x, y], ...],
    "validation": [[x, y], ...]}."""
    assert len(data) == len(labels)
    n = len(data)
    min_val = n // num_folds
    assert min_val > 0
    extra = n % num_folds
    folds = {}
    for fold_id in range(num_folds):
        n_val = min_val + (1 if fold_id < extra else 0)
        start = fold_id * min_val
        val_idx = list(range(start, start + n_val))
        train_idx = [i for i in range(n) if i < start or i >= start + n_val]
        folds[fold_id] = {
            "train": [[data[i], labels[i]] for i in train_idx],
            "validation": [[data[i], labels[i]] for i in val_idx],
        }
    return folds
