"""Directed spectrum via Wilson spectral factorization.

Capability rebuild of /root/reference/general_utils/directed_spectrum.py:48-322
(the reference vendors neil-gallagher/directed-spectrum; the underlying
algorithm is G.T. Wilson, "The Factorization of Matricial Spectral Densities",
SIAM J. Appl. Math. 23(4), 1972). Given multi-channel windows, computes the
pairwise directed power spectrum ds[w, f, i, j] = directed power i -> j.

Design deltas from the reference:
* The reference runs one Python convergence loop per window
  (ref directed_spectrum.py:192-218); here ALL windows — and for the pairwise
  mode all channel pairs, folded into the window axis — iterate together as one
  batched linear-algebra program, with converged windows frozen via a mask.
  The pairwise mode therefore performs W*C*(C-1)/2 tiny 2x2 factorizations as
  one (W*P, F, 2, 2) batch instead of nested host loops.
* Stays on host numpy in float64/complex128: this is one-shot dataset
  preprocessing and the iteration is numerically touchy below f64
  (SURVEY.md §7 hard part 4).
"""
from __future__ import annotations

from itertools import combinations
from warnings import warn

import numpy as np
from scipy.fft import fft, ifft
from scipy.signal import csd

__all__ = ["get_directed_spectrum", "wilson_factorize"]

DEFAULT_CSD_PARAMS = {
    "detrend": "constant",
    "window": "hann",
    "nperseg": 512,
    "noverlap": 256,
    "nfft": None,
}


def _hermitian(M):
    return M.conj().swapaxes(-1, -2)


def _plus_operator(g):
    """Causal (non-negative-lag) part of a frequency-domain array g
    (..., F, N, N), plus the zero-lag time-domain component
    (ref directed_spectrum.py:288-322)."""
    gamma = ifft(g, axis=-3).real.astype(g.dtype)
    F = gamma.shape[-3]
    half = F // 2
    gamma[..., 0, :, :] *= 0.5
    if F % 2 == 0:
        gamma[..., half, :, :] *= 0.5
    gamma[..., half + 1:, :, :] = 0
    return fft(gamma, axis=-3), gamma[..., 0, :, :]


def _max_rel_change(x, x0):
    """Per-window max relative |x - x0| / |x| with tiny entries clamped to 1
    (ref directed_spectrum.py:325-348)."""
    diff = np.abs(x - x0)
    ref = np.abs(x)
    eps = np.finfo(ref.dtype).eps
    ref[ref <= 2 * eps] = 1.0
    return (diff / ref).reshape(x.shape[0], -1).max(axis=1)


def wilson_factorize(cpsd, max_iter=1000, tol=1e-6, eps_multiplier=100):
    """Factorize two-sided CPSD matrices into minimum-phase transfer matrices H
    and innovation covariances Sigma, batched over windows.

    cpsd: (W, F, N, N) complex. Returns (H (W, F, N, N), Sigma (W, N, N)) with
    cpsd ~= H @ Sigma @ H^* at every frequency.
    """
    cpsd = np.asarray(cpsd, dtype=np.complex128)
    cond = np.linalg.cond(cpsd)
    if np.any(cond > 1 / np.finfo(cpsd.dtype).eps):
        warn("CPSD matrix is singular!")
        this_eps = np.spacing(np.abs(cpsd)).max()
        cpsd = cpsd + np.eye(cpsd.shape[-1]) * this_eps * eps_multiplier

    # init: psi = chol(zero-lag autocovariance)^H tiled over frequency
    gamma0 = ifft(cpsd, axis=1)[:, 0]
    gamma0 = np.real(gamma0 + _hermitian(gamma0)) / 2.0
    A0 = _hermitian(np.linalg.cholesky(gamma0)).astype(np.complex128)
    psi = np.repeat(A0[:, None], cpsd.shape[1], axis=1)
    L = np.linalg.cholesky(cpsd)

    W = cpsd.shape[0]
    I = np.eye(cpsd.shape[-1])
    active = np.ones(W, dtype=bool)
    for _ in range(max_iter):
        # g = psi \ cpsd / psi^* + I, computed from the Cholesky factor
        pic = np.linalg.solve(psi, L)
        g = pic @ _hermitian(pic) + I
        gplus, g0 = _plus_operator(g)
        # S makes g0 + S upper triangular with S + S^H = 0
        S = -np.tril(g0, -1)
        S = S - _hermitian(S)
        psi_new = psi @ (gplus + S[:, None])
        A0_new = A0 @ (g0 + S)
        psi_delta = _max_rel_change(psi_new, psi)
        a0_delta = _max_rel_change(A0_new, A0)
        # freeze converged windows so extra iterations don't perturb them
        m = active[:, None, None, None]
        psi = np.where(m, psi_new, psi)
        A0 = np.where(m[:, 0], A0_new, A0)
        active = active & ((psi_delta >= tol) | (a0_delta >= tol))
        if not active.any():
            break
    else:
        if active.any():
            warn("Wilson factorization failed to converge.", stacklevel=2)

    H = np.linalg.solve(A0[:, None].swapaxes(-1, -2), psi.swapaxes(-1, -2))
    H = H.swapaxes(-1, -2)  # H = psi @ inv(A0)
    Sigma = np.real(A0 @ A0.swapaxes(-1, -2))
    return H, Sigma


def _pair_ds(H, Sigma):
    """Directed power for 2x2 factorizations: returns (ds01, ds10), each
    (W, F) — the power channel 0 receives from 1 and vice versa
    (ref directed_spectrum.py:222-260 specialized to singleton groups)."""
    H01 = H[..., 0, 1]
    H10 = H[..., 1, 0]
    s00, s01 = Sigma[:, 0, 0], Sigma[:, 0, 1]
    s10, s11 = Sigma[:, 1, 0], Sigma[:, 1, 1]
    # conditional innovation covariances
    sig1_0 = s11 - s10 * s01 / s00
    sig0_1 = s00 - s01 * s10 / s11
    ds10 = np.real(H01 * sig1_0[:, None] * H01.conj())
    ds01 = np.real(H10 * sig0_1[:, None] * H10.conj())
    return ds01, ds10


def get_directed_spectrum(X, fs, pairwise=True, max_iter=1000, tol=1e-6,
                          csd_params=None):
    """Directed spectrum of multi-channel windows (ref
    directed_spectrum.py:48-144).

    X: (C, T) or (W, C, T). Returns (f (F',), ds (W, F', C, C)) one-sided, with
    ds[w, f, i, j] the directed power i -> j.
    """
    X = np.asarray(X)
    if X.ndim == 2:
        X = X[None]
    assert X.ndim == 3, f"len({X.shape}) != 3"
    W, C, _ = X.shape
    params = dict(DEFAULT_CSD_PARAMS, **(csd_params or {}))

    f, cpsd = csd(X[:, np.newaxis], X[:, :, np.newaxis], fs=fs,
                  return_onesided=False, **params)  # (F,), (W, C, C, F)
    cpsd = np.moveaxis(cpsd, 3, 1)  # (W, F, C, C)
    F = cpsd.shape[1]

    pairs = list(combinations(range(C), 2))
    ds = np.zeros((W, F, C, C), dtype=np.float64)
    if pairs:
        if pairwise:
            # fold (window, pair) into one batch of 2x2 factorizations
            sub = np.stack(
                [cpsd[:, :, np.ix_([i, j], [i, j])[0], np.ix_([i, j], [i, j])[1]]
                 for (i, j) in pairs], axis=1)  # (W, P, F, 2, 2)
            sub = sub.reshape(W * len(pairs), F, 2, 2)
            H, Sigma = wilson_factorize(sub, max_iter, tol)
            ds01, ds10 = _pair_ds(H, Sigma)
            ds01 = ds01.reshape(W, len(pairs), F)
            ds10 = ds10.reshape(W, len(pairs), F)
            for p, (i, j) in enumerate(pairs):
                ds[:, :, i, j] = ds01[:, p]
                ds[:, :, j, i] = ds10[:, p]
        else:
            H, Sigma = wilson_factorize(cpsd, max_iter, tol)
            for (i, j) in pairs:
                subH = H[:, :, np.ix_([i, j], [i, j])[0], np.ix_([i, j], [i, j])[1]]
                subS = Sigma[:, np.ix_([i, j], [i, j])[0], np.ix_([i, j], [i, j])[1]]
                ds01, ds10 = _pair_ds(subH, subS)
                ds[:, :, i, j] = ds01
                ds[:, :, j, i] = ds10

    # fold to a one-sided spectrum (ref :135-142)
    nyquist = F // 2
    ds = ds[:, : nyquist + 1]
    ds[:, 1:nyquist] *= 2
    if F % 2 != 0:
        ds[:, nyquist] *= 2
    f = np.abs(f[: nyquist + 1])
    return f, ds
