"""Graph-comparison metric library.

Numpy/host-side reimplementation (semantics-parity) of the reference metric suite
(/root/reference/general_utils/metrics.py) used for scoring Granger-causal graph
estimates against ground truth:

- optimal-threshold F1 via precision-recall scan   (ref metrics.py:11)
- fixed-threshold F1                               (ref metrics.py:33)
- confusion rates / sensitivity / specificity / LR (ref metrics.py:43-71)
- DeltaCon0 + directed-degree variant              (ref metrics.py:162,191)
- Deltaffinity / path-length MSE                   (ref metrics.py:218,235)
- Hungarian graph matching                         (ref metrics.py:274)
- cosine similarities (incl. set-pairwise)         (ref metrics.py:321-381)
- DAGness penalty                                  (ref metrics.py:433)

These run on host (eval layer); differentiable/jit-side counterparts used inside
training losses live in `redcliff_tpu.ops`.
"""
from __future__ import annotations

import numpy as np
from scipy.linalg import null_space
from scipy.optimize import linear_sum_assignment

__all__ = [
    "precision_recall_curve",
    "compute_optimal_f1",
    "compute_f1",
    "roc_auc",
    "confusion_counts",
    "compute_sensitivity",
    "compute_specificity",
    "compute_positive_likelihood_ratio",
    "compute_negative_likelihood_ratio",
    "matsusita_distance",
    "deltacon0",
    "deltacon0_with_directed_degrees",
    "deltaffinity",
    "path_length_mse",
    "solve_linear_sum_assignment_between_graph_options",
    "get_number_of_connected_components",
    "compute_cosine_similarity",
    "pairwise_cosine_similarities",
    "compute_mse",
    "l1_norm_difference",
    "get_f1_score",
    "dagness_penalty",
]


# ---------------------------------------------------------------------------
# Threshold-scan classification metrics
# ---------------------------------------------------------------------------

def precision_recall_curve(labels: np.ndarray, scores: np.ndarray):
    """Precision/recall at every distinct score threshold (descending-score scan).

    Matches sklearn.metrics.precision_recall_curve semantics (which the reference
    relies on at metrics.py:18): thresholds are the distinct predicted scores; a
    sample is predicted positive when score >= threshold. Returns (precision,
    recall, thresholds) with the conventional trailing (1, 0) point appended.
    """
    labels = np.asarray(labels).ravel().astype(np.float64)
    scores = np.asarray(scores).ravel().astype(np.float64)
    order = np.argsort(-scores, kind="mergesort")
    labels = labels[order]
    scores = scores[order]
    # indices where the score changes (last occurrence of each distinct value)
    distinct = np.where(np.diff(scores))[0]
    threshold_idx = np.r_[distinct, labels.size - 1]
    tp = np.cumsum(labels)[threshold_idx]
    fp = (1 + threshold_idx) - tp
    total_pos = labels.sum()
    precision = np.where(tp + fp > 0, tp / np.maximum(tp + fp, 1e-300), 0.0)
    recall = tp / total_pos if total_pos > 0 else np.ones_like(tp)
    thresholds = scores[threshold_idx]
    # reverse so recall is decreasing, then append the conventional (1, 0) endpoint
    precision = np.r_[precision[::-1], 1.0]
    recall = np.r_[recall[::-1], 0.0]
    thresholds = thresholds[::-1]
    return precision, recall, thresholds


def compute_optimal_f1(labels, pred_logits):
    """Best-F1 threshold scan over the precision-recall curve (ref metrics.py:11-30)."""
    precision, recall, thresholds = precision_recall_curve(labels, pred_logits)
    precision = precision[:-1]
    recall = recall[:-1]
    with np.errstate(divide="ignore", invalid="ignore"):
        f1 = (2.0 * precision * recall) / (precision + recall)
    f1 = np.where(np.isfinite(f1), f1, 0.0)
    opt_threshold = thresholds[int(np.argmax(f1))]
    opt_f1 = float(np.max(f1))
    assert np.isfinite(opt_f1)
    return float(opt_threshold), opt_f1


def compute_f1(labels, pred_logits, pred_cutoff):
    """F1 at a fixed cutoff: positive iff score > cutoff (ref metrics.py:33-41)."""
    labels = np.asarray(labels).ravel()
    preds = (np.asarray(pred_logits).ravel() > pred_cutoff).astype(np.int64)
    tp = float(np.sum((preds == 1) & (labels == 1)))
    fp = float(np.sum((preds == 1) & (labels == 0)))
    fn = float(np.sum((preds == 0) & (labels == 1)))
    denom = 2 * tp + fp + fn
    return 0.0 if denom == 0 else 2 * tp / denom


def roc_auc(labels, scores):
    """ROC-AUC via the rank-statistic (Mann-Whitney) formulation with tie handling.

    Equivalent to sklearn.metrics.roc_auc_score used throughout the reference
    (e.g. general_utils/model_utils.py:54-67).
    """
    labels = np.asarray(labels).ravel().astype(np.float64)
    scores = np.asarray(scores).ravel().astype(np.float64)
    n_pos = labels.sum()
    n_neg = labels.size - n_pos
    if n_pos == 0 or n_neg == 0:
        raise ValueError("roc_auc undefined with a single class present")
    order = np.argsort(scores, kind="mergesort")
    ranks = np.empty(scores.size, dtype=np.float64)
    sorted_scores = scores[order]
    # average ranks for ties
    i = 0
    while i < scores.size:
        j = i
        while j + 1 < scores.size and sorted_scores[j + 1] == sorted_scores[i]:
            j += 1
        ranks[order[i : j + 1]] = 0.5 * (i + j) + 1.0
        i = j + 1
    rank_sum_pos = ranks[labels == 1].sum()
    return float((rank_sum_pos - n_pos * (n_pos + 1) / 2.0) / (n_pos * n_neg))


def confusion_counts(labels, preds, pred_cutoff=None):
    """(tp, tn, fp, fn) counts; thresholds preds if a cutoff is given (ref metrics.py:43-48)."""
    labels = np.asarray(labels).ravel()
    preds = np.asarray(preds).ravel()
    if pred_cutoff is not None:
        preds = (preds > pred_cutoff).astype(np.int64)
    tp = int(np.sum((preds == 1) & (labels == 1)))
    tn = int(np.sum((preds == 0) & (labels == 0)))
    fp = int(np.sum((preds == 1) & (labels == 0)))
    fn = int(np.sum((preds == 0) & (labels == 1)))
    return tp, tn, fp, fn


def compute_sensitivity(labels, preds, pred_cutoff=None):
    tp, _, _, fn = confusion_counts(labels, preds, pred_cutoff)
    return tp / (tp + fn)


def compute_specificity(labels, preds, pred_cutoff=None):
    _, tn, fp, _ = confusion_counts(labels, preds, pred_cutoff)
    return tn / (tn + fp)


def compute_positive_likelihood_ratio(labels, preds, pred_cutoff=None):
    sens = compute_sensitivity(labels, preds, pred_cutoff)
    spec = compute_specificity(labels, preds, pred_cutoff)
    return sens / (1.0 - spec)


def compute_negative_likelihood_ratio(labels, preds, pred_cutoff=None):
    sens = compute_sensitivity(labels, preds, pred_cutoff)
    spec = compute_specificity(labels, preds, pred_cutoff)
    return (1.0 - sens) / spec


# ---------------------------------------------------------------------------
# DeltaCon0 family (Koutra, CMU-CS-15-126 Alg 7.4) — ref metrics.py:109-269
# ---------------------------------------------------------------------------

def matsusita_distance(S1, S2):
    """sqrt(sum((sqrt(S1)-sqrt(S2))^2)) — eq. 7.3 (ref metrics.py:130-134).

    Deliberate deviation from the reference: affinity matrices from signed
    (negative-valued) graph estimates produce negative entries, where the
    reference silently emits NaN; we clamp entries at zero before the sqrt
    so the distance stays finite (negative affinity ~ zero similarity mass).
    """
    S1 = np.maximum(np.asarray(S1, dtype=np.float64), 0.0)
    S2 = np.maximum(np.asarray(S2, dtype=np.float64), 0.0)
    return float(np.sqrt(np.sum((np.sqrt(S1) - np.sqrt(S2)) ** 2.0)))


def _node_affinity(I, D, A, eps):
    return np.linalg.inv(I + (eps**2.0) * D - eps * A)


def deltacon0(A1, A2, eps, make_graphs_undirected=False):
    """DeltaCon0 similarity 1/(1+d) between adjacency matrices (ref metrics.py:162-189).

    In-degree is taken as the column sum (axis=0 row-sum of the transpose), matching
    the reference's choice for directed Granger graphs.
    """
    G1 = np.array(A1, dtype=np.float64, copy=True)
    G2 = np.array(A2, dtype=np.float64, copy=True)
    assert G1.shape == G2.shape and G1.ndim == 2 and G1.shape[0] == G1.shape[1]
    if make_graphs_undirected:
        G1 = np.maximum(G1, G1.T)
        G2 = np.maximum(G2, G2.T)
    n = G1.shape[0]
    I = np.eye(n)
    S1 = _node_affinity(I, np.diag(G1.sum(axis=0)), G1, eps)
    S2 = _node_affinity(I, np.diag(G2.sum(axis=0)), G2, eps)
    return 1.0 / (1.0 + matsusita_distance(S1, S2))


def deltacon0_with_directed_degrees(A1, A2, eps, in_degree_coeff=1.0, out_degree_coeff=1.0):
    """Directed DeltaCon0: averages matsusita distances over in- and out-degree
    affinity matrices (ref metrics.py:191-216)."""
    A1 = np.asarray(A1, dtype=np.float64)
    A2 = np.asarray(A2, dtype=np.float64)
    assert A1.shape == A2.shape and A1.ndim == 2 and A1.shape[0] == A1.shape[1]
    n = A1.shape[0]
    I = np.eye(n)
    d_in = matsusita_distance(
        _node_affinity(I, np.diag(A1.sum(axis=0)), A1, eps),
        _node_affinity(I, np.diag(A2.sum(axis=0)), A2, eps),
    )
    d_out = matsusita_distance(
        _node_affinity(I, np.diag(A1.sum(axis=1)), A1, eps),
        _node_affinity(I, np.diag(A2.sum(axis=1)), A2, eps),
    )
    d = (in_degree_coeff * d_in + out_degree_coeff * d_out) / 2.0
    return 1.0 / (1.0 + d)


def _affinity_no_echo(A, eps, max_path_length):
    n = A.shape[0]
    S = np.eye(n)
    Ak = np.eye(n)
    for k in range(1, max_path_length + 1):
        Ak = Ak @ A
        S = S + (eps**k) * Ak
    return S


def deltaffinity(A1, A2, eps, max_path_length=None):
    """DeltaCon without degree attenuation: power-series affinity (ref metrics.py:218-233)."""
    A1 = np.asarray(A1, dtype=np.float64)
    A2 = np.asarray(A2, dtype=np.float64)
    assert A1.shape == A2.shape and A1.ndim == 2 and A1.shape[0] == A1.shape[1]
    n = A1.shape[0]
    if max_path_length is None:
        max_path_length = n - 1
    assert 0 < max_path_length < n
    S1 = _affinity_no_echo(A1, eps, max_path_length)
    S2 = _affinity_no_echo(A2, eps, max_path_length)
    return 1.0 / (1.0 + matsusita_distance(S1, S2))


def path_length_mse(A1, A2, max_path_length=None):
    """Per-path-length MSE between A^k powers; returns (sum, per-k list)
    (ref metrics.py:235-251)."""
    A1 = np.asarray(A1, dtype=np.float64)
    A2 = np.asarray(A2, dtype=np.float64)
    assert A1.shape == A2.shape and A1.ndim == 2 and A1.shape[0] == A1.shape[1]
    n = A1.shape[0]
    if max_path_length is None:
        max_path_length = n - 1
    mses = []
    P1 = np.eye(n)
    P2 = np.eye(n)
    for _ in range(max_path_length):
        P1 = P1 @ A1
        P2 = P2 @ A2
        mses.append(float(((P1 - P2) ** 2.0).mean()))
    return float(sum(mses)), mses


# ---------------------------------------------------------------------------
# Matching / graph structure helpers
# ---------------------------------------------------------------------------

def solve_linear_sum_assignment_between_graph_options(
    graph_estimates, true_graphs, cost_criteria="CosineSimilarity", inf_approximation=1e10
):
    """Hungarian matching of estimated graphs to ground-truth graphs using cosine
    similarity as cost (ref metrics.py:274-301). Note: the reference minimizes
    cosine similarity (scipy's default), matching that exactly."""
    if cost_criteria != "CosineSimilarity":
        raise NotImplementedError(cost_criteria)
    n_w, n_j = len(graph_estimates), len(true_graphs)
    cost = np.zeros((n_w, n_j))
    for w in range(n_w):
        for j in range(n_j):
            cost[w, j] = compute_cosine_similarity(graph_estimates[w], true_graphs[j])
    finite = np.isfinite(cost)
    cost[~finite] = 0.0
    cost = cost + inf_approximation * (1 - finite)
    return linear_sum_assignment(cost)


def get_symmetric_graph_laplacian(A):
    symm = A + A.T
    return np.diag(symm.sum(axis=1)) - symm


def get_number_of_connected_components(A, add_self_connections=True):
    """Nullity of the symmetrized Laplacian (ref metrics.py:303-319)."""
    A = np.asarray(A, dtype=np.float64)
    if add_self_connections:
        A = A + np.eye(A.shape[0])
    L = get_symmetric_graph_laplacian(A)
    return null_space(L).shape[1]


# ---------------------------------------------------------------------------
# Cosine similarity / elementwise comparisons
# ---------------------------------------------------------------------------

def compute_cosine_similarity(A, B, epsilon=1e-8):
    """Flattened cosine similarity with epsilon-floored norms (ref metrics.py:321-339)."""
    A = np.asarray(A, dtype=np.float64).ravel()
    B = np.asarray(B, dtype=np.float64).ravel()
    a_norm = np.linalg.norm(A)
    b_norm = np.linalg.norm(B)
    if not np.isfinite(a_norm):
        a_norm = -1.0
    if not np.isfinite(b_norm):
        b_norm = -1.0
    return float(A @ B / (max(a_norm, epsilon) * max(b_norm, epsilon)))


def pairwise_cosine_similarities(tensors, include_diag=True):
    """Upper-triangle pairwise cosine sims within a set of same-shape arrays
    (ref metrics.py:372-381). With include_diag=False, identity is subtracted
    from each (per lag slice for 3-D inputs) before comparison."""
    if len(tensors) <= 1:
        return None
    prepped = []
    for T in tensors:
        T = np.asarray(T, dtype=np.float64)
        if not include_diag:
            if T.ndim == 2:
                T = T - np.eye(T.shape[0])
            elif T.ndim == 3:
                T = T - np.eye(T.shape[0])[:, :, None]
            else:
                raise NotImplementedError(T.shape)
        prepped.append(T.ravel())
    sims = []
    for i in range(len(prepped)):
        for j in range(i + 1, len(prepped)):
            a, b = prepped[i], prepped[j]
            denom = max(np.linalg.norm(a), 1e-8) * max(np.linalg.norm(b), 1e-8)
            sims.append(float(a @ b / denom))
    return np.asarray(sims)


def compute_mse(A, B):
    return float(((np.asarray(A) - np.asarray(B)) ** 2).mean())


def l1_norm_difference(A_hat, A):
    """|  ||A_hat||_1 - ||A||_1 | over flattened entries (ref metrics.py:387-393)."""
    return float(abs(np.abs(np.asarray(A_hat)).sum() - np.abs(np.asarray(A)).sum()))


def get_f1_score(A_hat, A):
    """F1 treating strictly-positive entries as predicted/true edges (ref metrics.py:396-430)."""
    A_hat = np.asarray(A_hat)
    A = np.asarray(A)
    pos_pred = A_hat > 0.0
    pos_label = A > 0.0
    tp = float(np.sum(pos_pred & pos_label))
    fp = float(np.sum(pos_pred & ~pos_label))
    fn = float(np.sum(~pos_pred & pos_label))
    precision = tp / (tp + fp) if (tp + fp) > 0 else np.nan
    recall = tp / (tp + fn) if (tp + fn) > 0 else np.nan
    if not np.isfinite(precision) or not np.isfinite(recall) or (precision + recall) == 0.0:
        return 0.0
    return float(2.0 * precision * recall / (precision + recall))


def dagness_penalty(W0):
    """(tr(exp(W∘W)) - N)^2 acyclicity score (ref metrics.py:433-443).

    Matches the reference's literal computation: elementwise exp of the squared
    weights, so the trace reduces to sum_i exp(W_ii^2). (The NOTEARS paper's h(W)
    uses the matrix exponential; the reference implements elementwise exp and this
    build reproduces that behavior exactly.) Host/numpy version; the differentiable
    jax version lives in redcliff_tpu.ops.losses.
    """
    W0 = np.asarray(W0, dtype=np.float64)
    if W0.ndim == 3 and W0.shape[2] == 1:
        W0 = W0[:, :, 0]
    assert W0.ndim == 2 and W0.shape[0] == W0.shape[1]
    n = W0.shape[0]
    return float((np.trace(np.exp(W0 * W0)) - n) ** 2.0)
