"""Matplotlib visualization helpers.

Rebuilds the plotting surface of /root/reference/general_utils/plotting.py
(~25 helpers called from every fit()/save_checkpoint() and the eval drivers):
GC est-vs-true heatmap comparisons (:291-398), scatter + standard-error
overlays (:128-258), metric-history curves, signal-channel and wavelet plots
(:399-580), state-score traces (:582-634), and cross-experiment summary grids
(:14-126).  All helpers write a PNG and close their figure (the reference's
fit loops emit dozens of figures per checkpoint; leaking them OOMs long runs).
"""
from __future__ import annotations

import matplotlib

matplotlib.use("Agg")

import matplotlib.pyplot as plt  # noqa: E402
import numpy as np  # noqa: E402

__all__ = [
    "plot_heatmap",
    "plot_gc_est_comparison",
    "plot_gc_est_comparisons_by_factor",
    "make_scatter_and_std_err_of_mean_plot_overlay",
    "make_bar_and_whisker_plot_overlay",
    "plot_metric_histories",
    "plot_all_signal_channels",
    "plot_x_wavelet_comparison",
    "plot_x_simulation_comparison",
    "plot_state_score_traces",
    "plot_reconstruction_comparison",
    "plot_cross_experiment_summary_grid",
    "plot_cross_experiment_summary",
    "plot_confidence_interval_summary",
    "plot_scattered_results",
    "plot_training_loss",
    "plot_scatter",
    "plot_curve",
    "plot_curve_comparison",
    "plot_curve_comparison_from_dict",
    "plot_system_state_score_comparison",
    "plot_avg_system_state_score_comparison",
    "plot_estimated_vs_true_curve",
]

# reference-name aliases (the reference spells it "comparisson")
def _save(fig, save_path):
    fig.tight_layout()
    fig.savefig(save_path)
    plt.close(fig)


def plot_heatmap(A, save_path, title="", xlabel="source", ylabel="target",
                 cmap="viridis"):
    """Single-matrix heatmap (ref plotting.py:259-289)."""
    A = np.asarray(A)
    fig, ax = plt.subplots(figsize=(5, 4))
    im = ax.imshow(A, cmap=cmap, aspect="auto")
    ax.set_title(title)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    fig.colorbar(im, ax=ax)
    _save(fig, save_path)


def plot_gc_est_comparison(true_gc, est_gc, save_path, include_lags=False):
    """Side-by-side true vs estimated GC heatmaps; with ``include_lags`` each
    lag slice gets its own column pair (ref plotting.py:291-381)."""
    est_gc = None if est_gc is None else np.asarray(est_gc)
    true_gc = None if true_gc is None else np.asarray(true_gc)
    mats = []
    if include_lags:
        for name, M in (("true", true_gc), ("est", est_gc)):
            if M is None:
                continue
            M = M[:, :, None] if M.ndim == 2 else M
            for l in range(M.shape[2]):
                mats.append((f"{name} lag {l + 1}", M[:, :, l]))
    else:
        for name, M in (("true", true_gc), ("est", est_gc)):
            if M is None:
                continue
            if M.ndim == 3:
                M = M.sum(axis=2)
            mats.append((name, M))
    if not mats:
        return
    fig, axes = plt.subplots(1, len(mats),
                             figsize=(3.2 * len(mats), 3), squeeze=False)
    for ax, (title, M) in zip(axes[0], mats):
        im = ax.imshow(M, cmap="viridis", aspect="auto")
        ax.set_title(title)
        fig.colorbar(im, ax=ax, fraction=0.046)
    _save(fig, save_path)


def plot_gc_est_comparisons_by_factor(true_gcs, est_gcs, save_path,
                                      include_lags=False):
    """One row per factor of true/est GC heatmaps (ref plotting.py:383-398;
    either list may be None, matching the curation-time usage that plots
    ground truth alone)."""
    n_true = 0 if true_gcs is None else len(true_gcs)
    n_est = 0 if est_gcs is None else len(est_gcs)
    K = max(n_true, n_est)
    if K == 0:
        return
    fig, axes = plt.subplots(K, 2, figsize=(7, 3 * K), squeeze=False)
    for k in range(K):
        for col, (name, src) in enumerate((("true", true_gcs),
                                           ("est", est_gcs))):
            ax = axes[k][col]
            if src is None or k >= len(src):
                ax.axis("off")
                continue
            M = np.asarray(src[k])
            if M.ndim == 3:
                M = M.sum(axis=2) if not include_lags else M[:, :, 0]
            im = ax.imshow(M, cmap="viridis", aspect="auto")
            ax.set_title(f"factor {k} {name}")
            fig.colorbar(im, ax=ax, fraction=0.046)
    _save(fig, save_path)


def make_scatter_and_std_err_of_mean_plot_overlay(results_by_group, save_path,
                                                  title, xlabel, ylabel,
                                                  alpha=0.5,
                                                  make_diff_plots=False):
    """Per-group value scatter with mean ± SEM overlay — the cross-algorithm
    comparison figure (ref plotting.py:128-258).  With ``make_diff_plots``,
    each group additionally gets a ``<group>_IMPROVEMENTS/`` subfolder holding
    the same figure over its pairwise per-sample differences vs every other
    group (the reference's improvement panels, ref :177-198)."""
    if make_diff_plots:
        import os

        folder, fname = os.path.split(save_path)
        for g1, v1 in results_by_group.items():
            diffs = {
                f"{g1} - {g2}": [a - b for a, b in zip(v1, v2)]
                for g2, v2 in results_by_group.items() if g2 != g1
            }
            diff_dir = os.path.join(folder, f"{g1}_IMPROVEMENTS")
            os.makedirs(diff_dir, exist_ok=True)
            make_scatter_and_std_err_of_mean_plot_overlay(
                diffs, os.path.join(diff_dir, fname),
                f"{title}\n vs {g1} performance", xlabel, ylabel, alpha=alpha,
                make_diff_plots=False)
    groups = list(results_by_group.keys())
    fig, ax = plt.subplots(figsize=(max(6, 1.2 * len(groups)), 4))
    rng = np.random.default_rng(0)
    for i, g in enumerate(groups):
        vals = np.asarray([v for v in results_by_group[g] if v is not None],
                          dtype=np.float64)
        if vals.size == 0:
            continue
        jitter = rng.uniform(-0.15, 0.15, size=vals.size)
        ax.scatter(np.full(vals.size, i) + jitter, vals, alpha=alpha, s=18)
        mean = vals.mean()
        sem = vals.std() / np.sqrt(vals.size)
        ax.errorbar([i], [mean], yerr=[sem], fmt="o", color="black",
                    capsize=4, markersize=6, zorder=3)
    ax.set_xticks(range(len(groups)))
    ax.set_xticklabels(groups, rotation=30, ha="right", fontsize=8)
    ax.set_title(title)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    _save(fig, save_path)


def plot_metric_histories(histories, save_path, title="training histories",
                          ylog=False):
    """Overlayed per-epoch metric curves from a {name: [values]} dict — the
    loss-curve panels every checkpoint writes (ref save_checkpoint usage,
    redcliff_s_cmlp.py:942-1112)."""
    fig, ax = plt.subplots(figsize=(7, 4))
    for name, vals in histories.items():
        vals = [v for v in vals if v is not None and np.isscalar(v)
                or isinstance(v, (int, float, np.floating))]
        if not vals:
            continue
        ax.plot(range(len(vals)), vals, label=str(name), linewidth=1.2)
    if ylog:
        ax.set_yscale("log")
    ax.set_xlabel("epoch")
    ax.legend(fontsize=7)
    ax.set_title(title)
    _save(fig, save_path)


def plot_all_signal_channels(X, save_path, title="signal", fs=None, zoom=None):
    """Stacked per-channel traces of one (T, C) recording
    (ref plotting.py:399-460, 548-579).  ``zoom`` additionally writes
    ``*_ZOOMED`` / ``*_partiallyZOOMED`` companions restricted to the first
    ``zoom`` / ``2*zoom`` steps, like the reference's curation plots."""
    X = np.asarray(X)

    def _one(Xv, path):
        T, C = Xv.shape
        t = np.arange(T) / fs if fs else np.arange(T)
        fig, axes = plt.subplots(C, 1, figsize=(8, 1.2 * C), sharex=True,
                                 squeeze=False)
        for c in range(C):
            axes[c][0].plot(t, Xv[:, c], linewidth=0.7)
            axes[c][0].set_ylabel(f"ch{c}", fontsize=7)
        axes[-1][0].set_xlabel("time (s)" if fs else "step")
        axes[0][0].set_title(title)
        _save(fig, path)

    _one(X, save_path)
    if zoom is not None:
        import os

        root, ext = os.path.splitext(save_path)
        ext = ext or ".png"
        _one(X[:zoom], f"{root}_ZOOMED{ext}")
        _one(X[: 2 * zoom], f"{root}_partiallyZOOMED{ext}")


def plot_x_wavelet_comparison(X, X_wavelet, save_path):
    """Original signal vs its wavelet-band decomposition
    (ref plotting.py:462-520)."""
    X = np.asarray(X)
    X_wavelet = np.asarray(X_wavelet)
    fig, axes = plt.subplots(2, 1, figsize=(8, 5), sharex=True)
    axes[0].plot(X, linewidth=0.7)
    axes[0].set_title("original channels")
    axes[1].plot(X_wavelet.reshape(X_wavelet.shape[0], -1), linewidth=0.5)
    axes[1].set_title("wavelet bands")
    _save(fig, save_path)


def plot_state_score_traces(scores, save_path, labels=None,
                            title="state scores"):
    """Per-state factor-score traces over time (ref plotting.py:582-634)."""
    scores = np.asarray(scores)  # (num_states, T)
    fig, ax = plt.subplots(figsize=(8, 3.5))
    for s in range(scores.shape[0]):
        name = labels[s] if labels else f"state {s}"
        ax.plot(scores[s], label=name, linewidth=1.0)
    ax.set_xlabel("step")
    ax.set_ylabel("score")
    ax.set_title(title)
    ax.legend(fontsize=8)
    _save(fig, save_path)


def plot_reconstruction_comparison(x_orig, x_recon, save_path):
    """Flattened original vs reconstruction overlay (the DCSFA evaluate
    figure, ref models/dcsfa_nmf.py:1346)."""
    fig, ax = plt.subplots(figsize=(8, 3.5))
    ax.plot(np.asarray(x_orig).ravel(), label="original", linewidth=0.7)
    ax.plot(np.asarray(x_recon).ravel(), label="reconstruction",
            linewidth=0.7, alpha=0.8)
    ax.legend(fontsize=8)
    ax.set_title("reconstruction comparison")
    _save(fig, save_path)


def plot_cross_experiment_summary_grid(summary, save_path, metric_key,
                                       title=None):
    """Grid of per-(dataset, algorithm) metric means — the cross-experiment
    summary figure (ref plotting.py:14-126).  ``summary`` maps
    {dataset: {algorithm: value}}."""
    datasets = list(summary.keys())
    algs = sorted({a for d in summary.values() for a in d})
    M = np.full((len(datasets), len(algs)), np.nan)
    for i, d in enumerate(datasets):
        for j, a in enumerate(algs):
            v = summary[d].get(a)
            if v is not None:
                M[i, j] = v
    fig, ax = plt.subplots(figsize=(1.2 * len(algs) + 2,
                                    0.6 * len(datasets) + 2))
    im = ax.imshow(M, cmap="viridis", aspect="auto")
    ax.set_xticks(range(len(algs)))
    ax.set_xticklabels(algs, rotation=30, ha="right", fontsize=8)
    ax.set_yticks(range(len(datasets)))
    ax.set_yticklabels(datasets, fontsize=8)
    for i in range(len(datasets)):
        for j in range(len(algs)):
            if np.isfinite(M[i, j]):
                ax.text(j, i, f"{M[i, j]:.3f}", ha="center", va="center",
                        fontsize=7, color="white")
    fig.colorbar(im, ax=ax)
    ax.set_title(title or metric_key)
    _save(fig, save_path)


def plot_cross_experiment_summary(save_path, means, sems, alg_names,
                                  dataset_names, title="", xlabel="", ylabel="",
                                  x_domain_lim=None,
                                  abbreviate_dataset_names=True):
    """The paper's headline comparison figure (ref plotting.py:14-107):
    horizontal grouped bars — one group per dataset, one bar per algorithm —
    with SEM whiskers.  ``means``/``sems`` are flat lists ordered
    dataset-major (all algs for dataset 0, then dataset 1, ...), matching the
    layout the summary condensers emit."""
    A, D = len(alg_names), len(dataset_names)
    assert len(means) == A * D, (len(means), A, D)
    means = np.asarray(means, dtype=np.float64)
    sems = np.asarray(sems, dtype=np.float64)

    def _alias(name):
        # "numN10_numE20_numF5" -> "10-20-5" (the paper's axis shorthand)
        parts = str(name).split("_")
        nums = []
        for part in parts:
            digits = "".join(ch for ch in part if ch.isdigit())
            if not digits:
                return str(name)
            nums.append(digits)
        return "-".join(nums)

    fig, ax = plt.subplots(figsize=(9, max(4, 0.6 * A * D)))
    group_stride = A + 1  # one blank row between dataset groups
    cmap = plt.get_cmap("tab10")
    for a, alg in enumerate(alg_names):
        ys = [d * group_stride + a for d in range(D)]
        idx = [d * A + a for d in range(D)]
        ax.barh(ys, means[idx], xerr=sems[idx], height=0.9,
                color=cmap(a % 10), capsize=4, label=str(alg))
    ax.set_yticks([d * group_stride + (A - 1) / 2 for d in range(D)])
    labels = [_alias(n) if abbreviate_dataset_names else str(n)
              for n in dataset_names]
    ax.set_yticklabels(labels)
    ax.invert_yaxis()
    ax.grid(True, axis="x", linestyle=":", linewidth=0.6, color="grey")
    if x_domain_lim is not None:
        ax.set_xlim(*x_domain_lim)
    ax.set_title(title)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    ax.legend(fontsize=8)
    _save(fig, save_path)


def plot_confidence_interval_summary(save_path, center, lower_bnd, upper_bnd,
                                     center_label="center", title="",
                                     criteria_name="", domain_name=""):
    """Center curve with lower/upper bound curves (ref plotting.py:110-125)."""
    fig, ax = plt.subplots(figsize=(12, 4))
    ax.plot(center, marker=".", label=center_label)
    ax.plot(lower_bnd, marker=".", label="lower-bound")
    ax.plot(upper_bnd, marker=".", label="upper-bound")
    ax.set_title(title)
    ax.set_ylabel(criteria_name)
    ax.set_xlabel(domain_name)
    ax.legend(fontsize=8)
    ax.grid(True, linestyle=":")
    _save(fig, save_path)


def make_bar_and_whisker_plot_overlay(vals_by_label, save_path, title="",
                                      xlabel="", ylabel="", alpha=0.5,
                                      color="darkred"):
    """Bars of per-group means with boxplot overlays
    (ref plotting.py:201-226)."""
    groups = list(vals_by_label.keys())
    data = [np.asarray(vals_by_label[g], dtype=np.float64) for g in groups]
    fig, ax = plt.subplots(figsize=(max(6, 1.2 * len(groups)), 4.5))
    ax.bar(range(1, len(groups) + 1), [d.mean() if d.size else np.nan
                                       for d in data],
           align="center", alpha=alpha, color=color)
    ax.boxplot(data, positions=range(1, len(groups) + 1))
    ax.set_xticks(range(1, len(groups) + 1))
    ax.set_xticklabels(groups, rotation=70, fontsize=8)
    ax.set_title(title)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    _save(fig, save_path)


def plot_scattered_results(x_vals, y_vals, save_path, title="", xlabel="",
                           ylabel="", x_eps=0.0, y_eps=0.0, alpha=0.5,
                           rng=None):
    """Scatter with optional gaussian jitter to de-overlap discrete values
    (ref plotting.py:229-241)."""
    rng = rng or np.random.default_rng(0)
    x = np.asarray(x_vals, dtype=np.float64)
    y = np.asarray(y_vals, dtype=np.float64)
    if x_eps:
        x = x + rng.normal(0.0, x_eps, size=x.shape)
    if y_eps:
        y = y + rng.normal(0.0, y_eps, size=y.shape)
    fig, ax = plt.subplots(figsize=(6, 6))
    ax.scatter(x, y, alpha=alpha)
    ax.set_title(title)
    ax.set_xlabel(f"{xlabel} (eps={x_eps})" if x_eps else xlabel)
    ax.set_ylabel(f"{ylabel} (eps={y_eps})" if y_eps else ylabel)
    _save(fig, save_path)


def plot_training_loss(train_loss_list, save_path, steps_per_entry=50):
    """Loss-vs-training-step curve; entries are ``steps_per_entry`` apart
    (the reference hard-codes 50, ref plotting.py:244-256)."""
    fig, ax = plt.subplots(figsize=(7, 4))
    ax.plot(steps_per_entry * np.arange(len(train_loss_list)), train_loss_list)
    ax.set_title("Training Loss")
    ax.set_ylabel("Loss")
    ax.set_xlabel("Training steps")
    _save(fig, save_path)


def plot_x_simulation_comparison(x, x_sim, save_path):
    """Per-channel actual-vs-simulated column pair for the first batch sample
    (ref plotting.py:458-480); ``x``/``x_sim`` are (B, T, C)."""
    x_sim = np.asarray(x_sim)
    C = x_sim.shape[2]
    fig, axes = plt.subplots(C, 2, figsize=(8, 2 * C), squeeze=False)
    for c in range(C):
        if x is not None:
            axes[c][0].plot(np.asarray(x)[0, :, c], linewidth=0.8)
        axes[c][0].set_title(f"actual ch{c}", fontsize=8)
        axes[c][1].plot(x_sim[0, :, c], linewidth=0.8)
        axes[c][1].set_title(f"simulated ch{c}", fontsize=8)
    _save(fig, save_path)


def plot_scatter(x, y, title, xlabel, ylabel, save_path):
    """Bare scatter (ref plotting.py:483-493)."""
    fig, ax = plt.subplots()
    ax.scatter(x, y)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    ax.set_title(title)
    _save(fig, save_path)


def plot_curve(values, title, xlabel, ylabel, save_path, domain_start=0):
    """Single curve over a shifted integer domain (ref plotting.py:495-505)."""
    fig, ax = plt.subplots()
    ax.plot(np.arange(domain_start, domain_start + len(values)), values)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    ax.set_title(title)
    _save(fig, save_path)


def _plot_curves_with_mean(curves, labels, title, xlabel, ylabel, save_path,
                           domain_start):
    fig, ax = plt.subplots()
    stacked = []
    for label, curve in zip(labels, curves):
        curve = np.asarray(curve, dtype=np.float64)
        ax.plot(np.arange(domain_start, domain_start + len(curve)), curve,
                label=label, alpha=0.5)
        stacked.append(curve)
    if stacked:
        n = min(len(c) for c in stacked)
        mean = np.mean([c[:n] for c in stacked], axis=0)
        ax.plot(np.arange(domain_start, domain_start + n), mean, label="mean",
                alpha=0.8, linewidth=1.6, color="black")
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    ax.set_title(title)
    ax.legend(fontsize=7)
    _save(fig, save_path)


def plot_curve_comparison(lists_of_curve_values, title, xlabel, ylabel,
                          save_path, domain_start=0, label_root=""):
    """Overlay of curves + their mean (ref plotting.py:507-525)."""
    labels = [f"{label_root}{i}" for i in range(len(lists_of_curve_values))]
    _plot_curves_with_mean(lists_of_curve_values, labels, title, xlabel,
                           ylabel, save_path, domain_start)


def plot_curve_comparison_from_dict(dict_of_curve_values, title, xlabel,
                                    ylabel, save_path, domain_start=0,
                                    label_root=""):
    """Dict-keyed overlay of curves + their mean (ref plotting.py:527-546)."""
    keys = list(dict_of_curve_values.keys())
    _plot_curves_with_mean([dict_of_curve_values[k] for k in keys],
                           [f"{label_root}{k}" for k in keys], title, xlabel,
                           ylabel, save_path, domain_start)


def plot_system_state_score_comparison(save_path, scores, title="",
                                       colors=None, markers=None, labels=None):
    """State-score traces with dashed boundaries between equal-length state
    segments (ref plotting.py:582-599); ``scores`` is (num_states, T)."""
    scores = np.asarray(scores)
    S, T = scores.shape
    seg = T // S
    fig, ax = plt.subplots(figsize=(9, 4))
    for s in range(S):
        ax.plot(scores[s], alpha=0.6,
                color=None if colors is None else colors[s],
                marker=None if markers is None else markers[s],
                label=f"state {s}" if labels is None else labels[s])
        if s > 0:
            ax.axvline(x=s * seg, color="k", linestyle="dashed", linewidth=0.8)
    ax.set_xlabel("Recording Time ID")
    ax.set_ylabel("Amplitude")
    ax.set_title(title)
    ax.legend(fontsize=8)
    _save(fig, save_path)


def plot_avg_system_state_score_comparison(save_path, scores,
                                           true_label_traces, title="",
                                           colors=None, markers=None,
                                           labels=None, ylim=(-1, 2.5)):
    """Sample score traces faint in the background; averaged predictions
    (solid) vs averaged true label traces (dotted) per state on top
    (ref plotting.py:602-632).  ``scores``/``true_label_traces`` are lists of
    (num_states, T) arrays."""
    scores = [np.asarray(s) for s in scores]
    truths = [np.asarray(t) for t in true_label_traces]
    avg_pred = np.mean(scores, axis=0)
    avg_true = np.mean(truths, axis=0)
    S = avg_pred.shape[0]
    cmap = plt.get_cmap("tab10")
    col = lambda s: cmap(s % 10) if colors is None else colors[s]
    fig, ax = plt.subplots(figsize=(10, 6))
    for rec in scores:
        for s in range(S):
            ax.plot(rec[s], color=col(s), alpha=0.025)
    for s in range(S):
        name = f"state {s}" if labels is None else labels[s]
        ax.plot(avg_pred[s], color=col(s), alpha=0.6,
                marker=None if markers is None else markers[s],
                label=f"avg_pred_{name}")
        ax.plot(avg_true[s], color=col(s), alpha=0.6, linestyle="dotted",
                marker=None if markers is None else markers[s],
                label=f"true_{name}")
    ax.set_xlabel("Time Step")
    ax.set_ylabel("Amplitude")
    ax.set_title(title)
    if ylim is not None:
        ax.set_ylim(*ylim)
    ax.legend(fontsize=7)
    _save(fig, save_path)


def plot_estimated_vs_true_curve(save_path, est_curve, true_curve, title="",
                                 xlabel="", ylabel=""):
    """Estimated vs true curve overlay (ref plotting.py:635-646)."""
    fig, ax = plt.subplots()
    ax.plot(true_curve, color="k", marker="+", label="true", alpha=0.5)
    ax.plot(est_curve, color="salmon", marker="x", label="estimated",
            alpha=0.5)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    ax.set_title(title)
    ax.legend(fontsize=8)
    _save(fig, save_path)


# aliases matching the reference's spelling for drop-in compatibility
plot_gc_est_comparisson = plot_gc_est_comparison
plot_gc_est_comparissons_by_factor = plot_gc_est_comparisons_by_factor
make_scatter_and_stdErrOfMean_plot_overlay_vis = \
    make_scatter_and_std_err_of_mean_plot_overlay
plot_reconstruction_comparisson = plot_reconstruction_comparison
plot_x_simulation_comparisson = plot_x_simulation_comparison
plot_curve_comparisson = plot_curve_comparison
plot_curve_comparisson_from_dict = plot_curve_comparison_from_dict
make_bar_and_whisker_plot_overlay_vis = make_bar_and_whisker_plot_overlay
plot_system_state_score_comparisson = plot_system_state_score_comparison
plot_avg_system_state_score_comparisson = \
    plot_avg_system_state_score_comparison
