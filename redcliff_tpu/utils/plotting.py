"""Matplotlib visualization helpers.

Rebuilds the plotting surface of /root/reference/general_utils/plotting.py
(~25 helpers called from every fit()/save_checkpoint() and the eval drivers):
GC est-vs-true heatmap comparisons (:291-398), scatter + standard-error
overlays (:128-258), metric-history curves, signal-channel and wavelet plots
(:399-580), state-score traces (:582-634), and cross-experiment summary grids
(:14-126).  All helpers write a PNG and close their figure (the reference's
fit loops emit dozens of figures per checkpoint; leaking them OOMs long runs).
"""
from __future__ import annotations

import matplotlib

matplotlib.use("Agg")

import matplotlib.pyplot as plt  # noqa: E402
import numpy as np  # noqa: E402

__all__ = [
    "plot_heatmap",
    "plot_gc_est_comparison",
    "plot_gc_est_comparisons_by_factor",
    "make_scatter_and_std_err_of_mean_plot_overlay",
    "plot_metric_histories",
    "plot_all_signal_channels",
    "plot_x_wavelet_comparison",
    "plot_state_score_traces",
    "plot_reconstruction_comparison",
    "plot_cross_experiment_summary_grid",
]

# reference-name aliases (the reference spells it "comparisson")
def _save(fig, save_path):
    fig.tight_layout()
    fig.savefig(save_path)
    plt.close(fig)


def plot_heatmap(A, save_path, title="", xlabel="source", ylabel="target",
                 cmap="viridis"):
    """Single-matrix heatmap (ref plotting.py:259-289)."""
    A = np.asarray(A)
    fig, ax = plt.subplots(figsize=(5, 4))
    im = ax.imshow(A, cmap=cmap, aspect="auto")
    ax.set_title(title)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    fig.colorbar(im, ax=ax)
    _save(fig, save_path)


def plot_gc_est_comparison(true_gc, est_gc, save_path, include_lags=False):
    """Side-by-side true vs estimated GC heatmaps; with ``include_lags`` each
    lag slice gets its own column pair (ref plotting.py:291-381)."""
    est_gc = None if est_gc is None else np.asarray(est_gc)
    true_gc = None if true_gc is None else np.asarray(true_gc)
    mats = []
    if include_lags:
        for name, M in (("true", true_gc), ("est", est_gc)):
            if M is None:
                continue
            M = M[:, :, None] if M.ndim == 2 else M
            for l in range(M.shape[2]):
                mats.append((f"{name} lag {l + 1}", M[:, :, l]))
    else:
        for name, M in (("true", true_gc), ("est", est_gc)):
            if M is None:
                continue
            if M.ndim == 3:
                M = M.sum(axis=2)
            mats.append((name, M))
    if not mats:
        return
    fig, axes = plt.subplots(1, len(mats),
                             figsize=(3.2 * len(mats), 3), squeeze=False)
    for ax, (title, M) in zip(axes[0], mats):
        im = ax.imshow(M, cmap="viridis", aspect="auto")
        ax.set_title(title)
        fig.colorbar(im, ax=ax, fraction=0.046)
    _save(fig, save_path)


def plot_gc_est_comparisons_by_factor(true_gcs, est_gcs, save_path,
                                      include_lags=False):
    """One row per factor of true/est GC heatmaps (ref plotting.py:383-398;
    either list may be None, matching the curation-time usage that plots
    ground truth alone)."""
    n_true = 0 if true_gcs is None else len(true_gcs)
    n_est = 0 if est_gcs is None else len(est_gcs)
    K = max(n_true, n_est)
    if K == 0:
        return
    fig, axes = plt.subplots(K, 2, figsize=(7, 3 * K), squeeze=False)
    for k in range(K):
        for col, (name, src) in enumerate((("true", true_gcs),
                                           ("est", est_gcs))):
            ax = axes[k][col]
            if src is None or k >= len(src):
                ax.axis("off")
                continue
            M = np.asarray(src[k])
            if M.ndim == 3:
                M = M.sum(axis=2) if not include_lags else M[:, :, 0]
            im = ax.imshow(M, cmap="viridis", aspect="auto")
            ax.set_title(f"factor {k} {name}")
            fig.colorbar(im, ax=ax, fraction=0.046)
    _save(fig, save_path)


def make_scatter_and_std_err_of_mean_plot_overlay(results_by_group, save_path,
                                                  title, xlabel, ylabel,
                                                  alpha=0.5):
    """Per-group value scatter with mean ± SEM overlay — the cross-algorithm
    comparison figure (ref plotting.py:128-258)."""
    groups = list(results_by_group.keys())
    fig, ax = plt.subplots(figsize=(max(6, 1.2 * len(groups)), 4))
    rng = np.random.default_rng(0)
    for i, g in enumerate(groups):
        vals = np.asarray([v for v in results_by_group[g] if v is not None],
                          dtype=np.float64)
        if vals.size == 0:
            continue
        jitter = rng.uniform(-0.15, 0.15, size=vals.size)
        ax.scatter(np.full(vals.size, i) + jitter, vals, alpha=alpha, s=18)
        mean = vals.mean()
        sem = vals.std() / np.sqrt(vals.size)
        ax.errorbar([i], [mean], yerr=[sem], fmt="o", color="black",
                    capsize=4, markersize=6, zorder=3)
    ax.set_xticks(range(len(groups)))
    ax.set_xticklabels(groups, rotation=30, ha="right", fontsize=8)
    ax.set_title(title)
    ax.set_xlabel(xlabel)
    ax.set_ylabel(ylabel)
    _save(fig, save_path)


def plot_metric_histories(histories, save_path, title="training histories",
                          ylog=False):
    """Overlayed per-epoch metric curves from a {name: [values]} dict — the
    loss-curve panels every checkpoint writes (ref save_checkpoint usage,
    redcliff_s_cmlp.py:942-1112)."""
    fig, ax = plt.subplots(figsize=(7, 4))
    for name, vals in histories.items():
        vals = [v for v in vals if v is not None and np.isscalar(v)
                or isinstance(v, (int, float, np.floating))]
        if not vals:
            continue
        ax.plot(range(len(vals)), vals, label=str(name), linewidth=1.2)
    if ylog:
        ax.set_yscale("log")
    ax.set_xlabel("epoch")
    ax.legend(fontsize=7)
    ax.set_title(title)
    _save(fig, save_path)


def plot_all_signal_channels(X, save_path, title="signal", fs=None):
    """Stacked per-channel traces of one (T, C) recording
    (ref plotting.py:399-460)."""
    X = np.asarray(X)
    T, C = X.shape
    t = np.arange(T) / fs if fs else np.arange(T)
    fig, axes = plt.subplots(C, 1, figsize=(8, 1.2 * C), sharex=True,
                             squeeze=False)
    for c in range(C):
        axes[c][0].plot(t, X[:, c], linewidth=0.7)
        axes[c][0].set_ylabel(f"ch{c}", fontsize=7)
    axes[-1][0].set_xlabel("time (s)" if fs else "step")
    axes[0][0].set_title(title)
    _save(fig, save_path)


def plot_x_wavelet_comparison(X, X_wavelet, save_path):
    """Original signal vs its wavelet-band decomposition
    (ref plotting.py:462-520)."""
    X = np.asarray(X)
    X_wavelet = np.asarray(X_wavelet)
    fig, axes = plt.subplots(2, 1, figsize=(8, 5), sharex=True)
    axes[0].plot(X, linewidth=0.7)
    axes[0].set_title("original channels")
    axes[1].plot(X_wavelet.reshape(X_wavelet.shape[0], -1), linewidth=0.5)
    axes[1].set_title("wavelet bands")
    _save(fig, save_path)


def plot_state_score_traces(scores, save_path, labels=None,
                            title="state scores"):
    """Per-state factor-score traces over time (ref plotting.py:582-634)."""
    scores = np.asarray(scores)  # (num_states, T)
    fig, ax = plt.subplots(figsize=(8, 3.5))
    for s in range(scores.shape[0]):
        name = labels[s] if labels else f"state {s}"
        ax.plot(scores[s], label=name, linewidth=1.0)
    ax.set_xlabel("step")
    ax.set_ylabel("score")
    ax.set_title(title)
    ax.legend(fontsize=8)
    _save(fig, save_path)


def plot_reconstruction_comparison(x_orig, x_recon, save_path):
    """Flattened original vs reconstruction overlay (the DCSFA evaluate
    figure, ref models/dcsfa_nmf.py:1346)."""
    fig, ax = plt.subplots(figsize=(8, 3.5))
    ax.plot(np.asarray(x_orig).ravel(), label="original", linewidth=0.7)
    ax.plot(np.asarray(x_recon).ravel(), label="reconstruction",
            linewidth=0.7, alpha=0.8)
    ax.legend(fontsize=8)
    ax.set_title("reconstruction comparison")
    _save(fig, save_path)


def plot_cross_experiment_summary_grid(summary, save_path, metric_key,
                                       title=None):
    """Grid of per-(dataset, algorithm) metric means — the cross-experiment
    summary figure (ref plotting.py:14-126).  ``summary`` maps
    {dataset: {algorithm: value}}."""
    datasets = list(summary.keys())
    algs = sorted({a for d in summary.values() for a in d})
    M = np.full((len(datasets), len(algs)), np.nan)
    for i, d in enumerate(datasets):
        for j, a in enumerate(algs):
            v = summary[d].get(a)
            if v is not None:
                M[i, j] = v
    fig, ax = plt.subplots(figsize=(1.2 * len(algs) + 2,
                                    0.6 * len(datasets) + 2))
    im = ax.imshow(M, cmap="viridis", aspect="auto")
    ax.set_xticks(range(len(algs)))
    ax.set_xticklabels(algs, rotation=30, ha="right", fontsize=8)
    ax.set_yticks(range(len(datasets)))
    ax.set_yticklabels(datasets, fontsize=8)
    for i in range(len(datasets)):
        for j in range(len(algs)):
            if np.isfinite(M[i, j]):
                ax.text(j, i, f"{M[i, j]:.3f}", ha="center", va="center",
                        fontsize=7, color="white")
    fig.colorbar(im, ax=ax)
    ax.set_title(title or metric_key)
    _save(fig, save_path)


# aliases matching the reference's spelling for drop-in compatibility
plot_gc_est_comparisson = plot_gc_est_comparison
plot_gc_est_comparissons_by_factor = plot_gc_est_comparisons_by_factor
make_scatter_and_stdErrOfMean_plot_overlay_vis = \
    make_scatter_and_std_err_of_mean_plot_overlay
plot_reconstruction_comparisson = plot_reconstruction_comparison
