"""Structured observability: jsonl metric logging + opt-in jax.profiler traces.

The reference's only observability is high-density ``print(..., flush=True)``
inside every fit loop, and parts of the analysis layer *parse the captured
stdout* (ref README.md:96, models/redcliff_s_cmlp.py:1549-1569). This build
makes metrics machine-readable, first-class artifacts (SURVEY §5):

* every trainer appends one JSON object per epoch to
  ``<save_dir>/metrics.jsonl`` (schema below), so analyses read structured
  records instead of log scrapes;
* an opt-in ``jax.profiler`` trace context captures XLA/TPU timelines around
  the train loop for perf work (view with TensorBoard / xprof).

jsonl schema: every line is one JSON object with at least ``{"event": str,
"wall_time": float}``. Events emitted by the trainers:

* ``fit_start``  — model class, config snapshot, resume epoch
* ``epoch``      — epoch index, phase list, per-term validation losses,
                   stopping criteria, latest GC-vs-oracle metrics when a
                   tracker is active
* ``anomaly``    — the numerics sentinel skipped step(s) this epoch:
                   ``cause`` (``nonfinite_grad``), the epoch's skipped-step
                   count, and the gradient-norm running stats
                   (``grad_norm_mean/std/max/last``)
* ``numerics``   — a sentinel intervention: ``kind`` is ``rollback``
                   (``cause``, ``restored_epoch``, ``lr_scale``, the new
                   ``learning_rates``, cumulative ``rollbacks``) or
                   ``abort`` (``cause``, e.g. ``all_nonfinite_validation``)
* ``fit_end``    — best_it, best_loss, final validation loss, abort cause
                   (None for a clean fit)

Records are STRICT JSON: non-finite floats are mapped to ``null`` by
``jsonable`` (any standards-compliant consumer can read the file), so a
missing value in a plot is a recorded anomaly, not a parser crash.
"""
from __future__ import annotations

import contextlib
import json
import math
import os
import threading
import time
from dataclasses import asdict, is_dataclass

import numpy as np

__all__ = ["MetricLogger", "profiler_trace", "jsonable", "read_jsonl"]


def jsonable(v):
    """Recursively coerce numpy/jax scalars and arrays into STRICT
    JSON-encodable Python values. Arrays become (nested) lists; non-finite
    floats (NaN/inf, scalar or array element) become ``None`` — the emitted
    lines never contain the JSON-standard-breaking ``NaN``/``Infinity``
    tokens."""
    if isinstance(v, bool) or v is None or isinstance(v, (int, str)):
        return v
    if isinstance(v, float):
        return v if math.isfinite(v) else None
    if is_dataclass(v) and not isinstance(v, type):
        return {k: jsonable(x) for k, x in asdict(v).items()}
    if isinstance(v, dict):
        return {str(k): jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [jsonable(x) for x in v]
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        f = float(v)
        return f if math.isfinite(f) else None
    if hasattr(v, "ndim"):  # numpy / jax arrays without importing jax here
        arr = np.asarray(v)
        if arr.ndim == 0:
            return jsonable(arr.item())
        return [jsonable(x) for x in arr.tolist()]
    return str(v)


class MetricLogger:
    """Append-only jsonl metric writer.

    ``MetricLogger(save_dir)`` writes to ``<save_dir>/metrics.jsonl``;
    ``MetricLogger(None)`` is a no-op sink so call sites never branch.
    Resumed runs keep appending to the same file — the ``epoch`` field makes
    replays self-describing.
    """

    def __init__(self, target, filename="metrics.jsonl", resume=True):
        self._fh = None
        # the liveness watchdog logs hang incidents from its own thread
        # while the fit loop logs epochs; serialized writes keep every
        # jsonl line intact (a torn line would break strict-JSON readers)
        self._lock = threading.Lock()
        if target is None:
            return
        path = target
        if not str(target).endswith(".jsonl"):
            os.makedirs(target, exist_ok=True)
            path = os.path.join(target, filename)
        else:
            parent = os.path.dirname(path)
            if parent:
                os.makedirs(parent, exist_ok=True)
        self.path = path
        self._fh = open(path, "a" if resume else "w")

    @property
    def active(self):
        return self._fh is not None

    def log(self, event, **fields):
        if self._fh is None:
            return
        rec = {"event": event, "wall_time": time.time()}
        rec.update({k: jsonable(v) for k, v in fields.items()})
        # allow_nan=False is the strictness backstop: jsonable already maps
        # non-finite floats to null, so a violation here is a bug, not data
        line = json.dumps(rec, allow_nan=False) + "\n"
        with self._lock:
            if self._fh is not None:
                self._fh.write(line)
                self._fh.flush()

    def close(self):
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_jsonl(path, event=None):
    """Load a metrics.jsonl file (optionally filtered by event type)."""
    if os.path.isdir(path):
        path = os.path.join(path, "metrics.jsonl")
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if event is None or rec.get("event") == event:
                out.append(rec)
    return out


@contextlib.contextmanager
def profiler_trace(log_dir):
    """Opt-in ``jax.profiler.trace`` context. ``log_dir=None`` is a no-op, so
    trainers wrap their epoch loops unconditionally and profiling turns on by
    setting ``profile_dir`` in the train config."""
    if not log_dir:
        yield
        return
    import jax

    os.makedirs(log_dir, exist_ok=True)
    with jax.profiler.trace(str(log_dir)):
        yield
