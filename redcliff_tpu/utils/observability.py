"""Back-compat shim: the observability layer grew into the telemetry spine
at :mod:`redcliff_tpu.obs` (trace spans, flight recorder, schema registry,
run-analytics CLI — docs/ARCHITECTURE.md "Telemetry spine").

This module re-exports the original surface so existing imports keep
working; new code should import from ``redcliff_tpu.obs`` directly.
"""
from __future__ import annotations

from redcliff_tpu.obs.logging import (MetricLogger, jsonable, profiler_trace,
                                      read_jsonl)

__all__ = ["MetricLogger", "profiler_trace", "jsonable", "read_jsonl"]
