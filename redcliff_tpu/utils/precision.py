"""Matmul precision control for jit'd steps + the production precision modes.

TPU MXUs run matmuls fastest in bfloat16; parameters stay f32 and only the
contraction precision drops — the standard speed/accuracy trade. The context
applies at trace time, so wrapping a step body inside its jit covers the
forward and (because grad is traced inside it) the backward pass.

Two knobs select it:

* ``matmul_precision`` (legacy, expert): the raw
  ``jax.default_matmul_precision`` string, forwarded verbatim;
* ``precision_mode`` (production): ``"f32"`` — the default; bit-identical
  to a config that never heard of precision (resolves to no context at
  all) — or ``"mixed"`` — bf16 MXU contractions with f32 master params and
  f32 reductions, guarded by the numerics sentinel: a skip/rollback storm
  auto-demotes the fit to f32 mid-run (trainers + grid engine), logs a
  schema-registered ``precision`` event, and persists the demotion in the
  checkpoint so a resume can never silently re-promote.

The mode is part of every resume fingerprint (it changes the update math of
every step), and folds into the cost-model bucket key (obs/costmodel.py) so
bf16 and f32 epoch costs never merge.
"""
from __future__ import annotations

import contextlib

__all__ = ["matmul_precision_ctx", "PRECISION_MODES", "MIXED_MATMUL",
           "resolve_matmul_precision", "check_precision_mode",
           "precision_label"]

PRECISION_MODES = ("f32", "mixed")
# what "mixed" means on the matmul axis: bf16 MXU passes, f32 accumulation
# (jax's "bfloat16" default_matmul_precision keeps f32 outputs/reductions)
MIXED_MATMUL = "bfloat16"


def check_precision_mode(mode):
    """Validate a ``precision_mode`` value at config-construction time (fail
    here, not deep inside the first jit'd step)."""
    if mode not in PRECISION_MODES:
        raise ValueError(
            f"precision_mode must be one of {PRECISION_MODES}, got {mode!r}")
    return mode


def resolve_matmul_precision(precision_mode="f32", matmul_precision=None):
    """The effective ``jax.default_matmul_precision`` string for a config.

    The explicit legacy ``matmul_precision`` knob wins when set (expert
    override — bench probes use it); otherwise ``precision_mode="mixed"``
    resolves to :data:`MIXED_MATMUL` and ``"f32"`` resolves to ``None`` —
    no context manager at all, so an ``"f32"`` fit traces the exact same
    graph as a pre-precision-mode build (decision-stream bit-identity)."""
    if matmul_precision:
        return matmul_precision
    if precision_mode == "mixed":
        return MIXED_MATMUL
    return None


def precision_label(precision_mode="f32", matmul_precision=None):
    """Canonical label for the cost-model bucket key (obs/costmodel.py):
    ``"f32"`` when no precision context applies, ``"mixed"`` for bf16
    contractions (whether selected by mode or by the legacy knob), else the
    raw precision string — bf16 and f32 epoch costs must never merge."""
    resolved = resolve_matmul_precision(precision_mode, matmul_precision)
    if resolved is None:
        return "f32"
    if resolved == MIXED_MATMUL:
        return "mixed"
    return str(resolved)


def matmul_precision_ctx(precision):
    """``jax.default_matmul_precision`` context; ``None`` is a no-op.

    jax is imported lazily: the mode/label helpers above are consumed by
    backend-free processes too (the fleet planner prices mixed-precision
    batches without ever importing jax)."""
    if not precision:
        return contextlib.nullcontext()
    import jax

    return jax.default_matmul_precision(precision)
