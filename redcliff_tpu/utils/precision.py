"""Matmul precision control for jit'd steps.

TPU MXUs run matmuls fastest in bfloat16; parameters stay f32 and only the
contraction precision drops — the standard speed/accuracy trade. The context
applies at trace time, so wrapping a step body inside its jit covers the
forward and (because grad is traced inside it) the backward pass.
"""
from __future__ import annotations

import contextlib

import jax

__all__ = ["matmul_precision_ctx"]


def matmul_precision_ctx(precision):
    """``jax.default_matmul_precision`` context; ``None`` is a no-op."""
    return (jax.default_matmul_precision(precision) if precision
            else contextlib.nullcontext())
