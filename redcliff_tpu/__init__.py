"""redcliff_tpu — TPU-native (JAX/XLA/Pallas) framework with the capabilities of
carlson-lab/redcliff-s-hypothesizing-dynamic-causal-graphs.

REDCLIFF-S fits a generative factor model to multivariate time series: K per-factor
cMLP Granger-causal forecasters whose one-step predictions are mixed by a factor-score
embedder conditioned on the recent signal window; first-layer weight norms of each
factor network are read out as per-state (dynamic) Granger-causal graphs.

This package is a ground-up TPU-first redesign (not a port): pure functional models
(param pytrees + apply fns), a single jit'd train step shared by every model family,
vmap over the factor/series/config axes where the reference loops in Python, and
jax.sharding/shard_map over a device mesh where the reference used SLURM job arrays.
"""

__version__ = "0.1.0"
