"""Learned per-(shape, G-bucket) step/compile cost model.

The telemetry spine records what every compiled program family costs (the
obs report's cost table: observed epoch step time and compile time per
(shape_key, G-bucket)); this module *consumes* that telemetry the way "A
Learned Performance Model for Tensor Processing Units" (PAPERS.md) fits
cost models from measured program executions: fold observations into a
persistent, versioned store and answer the questions scheduling and
admission planning will ask — "what will one epoch of this shape at this
width cost?", "what does a cold compile of its program cost?", "when will
this fit finish?".

**Store** (``cost_model_v<VERSION>.json``). Lives under the persistent
compile-cache base directory (``compile_cache_dir`` /
``REDCLIFF_COMPILE_CACHE``, overridable via ``REDCLIFF_COST_MODEL_DIR``) so
it ACCUMULATES across runs, restarts, and tenants exactly like the compiled
programs it prices. One JSON object::

    {"version": 1, "updated_at": <wall>, "runs": <n folds>,
     "buckets": {"<platform>|<shape_key>|g<width>|<precision>": {
         "platform", "shape", "g_bucket", "precision",
         "epochs", "epoch_ms_total",           # step-cost accumulators
         "compiles", "compile_ms_total",       # compile-cost accumulators
         "cache_hits", "cache_misses", "runs", "updated_at"}}}

Buckets are keyed by backend platform too — a CPU epoch and a TPU epoch of
the same program family are different costs, and mixing them would wreck
both predictions — and by the matmul-precision label (ISSUE 14 satellite:
bf16 and f32 epoch costs previously merged into one bucket and poisoned
ETAs/planner ordering; legacy precision-less keys backfill to "f32" on
read, since every pre-precision fit trained at the backend default). Updates are read-modify-write under a best-effort
``flock`` with an atomic replace, so concurrent fits (grid lanes under the
supervisor, parallel test children) merge instead of clobbering. The store
is bounded (:data:`MAX_BUCKETS`, oldest-updated evicted) and ADVISORY:
corrupt or missing files degrade to "no prediction", never to an error on a
training path.

**Prediction fallback ladder** (:class:`CostModel`): exact (platform,
shape, width) bucket -> nearest-width bucket of the same (platform, shape)
scaled linearly by the width ratio (lane math is width-independent in the
vmapped engine, so per-lane cost is ~flat across buckets; the XLA
width-rounding caveat is a ~1 ulp numerics effect, not a cost effect),
CLAMPED to the adjacent rung (width ratio <= 2 — the log-spaced ladder
makes any longer reach extrapolation, which previously answered
confidently-wrong ETAs at the ladder extremes) -> no prediction
(``None``). ``predict_fit_eta`` prices ``epochs`` epochs plus
``cold_programs`` cold compiles.

**Scoring & steering**: the grid engine emits a schema-registered
``cost_model`` event each check window (prediction vs actual epoch time,
residual pct, running MAPE, remaining-fit ETA) and ``obs report``
aggregates them into the per-bucket accuracy table. As of ISSUE 15 the
predictions also STEER: the predictive scheduling policy
(parallel/policy.py, ``REDCLIFF_PREDICTIVE``) prices bucket widths and
compaction points from this store, and the fleet worker's deadline-aware
preemption prices queued tenants' fit ETAs against running batches.

stdlib only — the supervisor (which must never initialize a jax backend)
and the watch/report CLIs all import this path.
"""
from __future__ import annotations

import json
import os
import threading
import time

__all__ = ["STORE_VERSION", "STORE_NAME", "ENV_STORE_DIR", "MAX_BUCKETS",
           "CostModel", "store_path", "load", "update_store",
           "update_store_from_report", "fit_from_report", "bucket_key",
           "rows_from_dispatch_stats"]

STORE_VERSION = 1
STORE_NAME = f"cost_model_v{STORE_VERSION}.json"
# store dir override; default rides the compile-cache base dir so the model
# accumulates exactly where the compiled programs it prices live
ENV_STORE_DIR = "REDCLIFF_COST_MODEL_DIR"
ENV_CACHE_DIR = "REDCLIFF_COMPILE_CACHE"  # literal: this module stays
#                                           importable without jax/runtime
MAX_BUCKETS = 512

_lock = threading.Lock()


def bucket_key(platform, shape_key, g_bucket, precision="f32"):
    """The store's bucket id:
    ``<platform>|<shape_key>|g<width>|<precision>``.

    ``precision`` is the matmul-precision label of the epochs being priced
    (utils/precision.py ``precision_label``: "f32" | "mixed" | raw string).
    Without it, bf16 and f32 epoch costs of the same program family merged
    into one bucket and poisoned every ETA and planner ordering (ISSUE 14
    satellite); legacy 3-segment keys are backfilled to "f32" on read —
    every pre-precision fit trained at the backend default."""
    return f"{platform}|{shape_key}|g{int(g_bucket)}|{precision or 'f32'}"


def store_path(base_dir=None):
    """Resolve the store file path, or None when no base directory is
    known (no compile cache configured anywhere)."""
    base = (base_dir or os.environ.get(ENV_STORE_DIR)
            or os.environ.get(ENV_CACHE_DIR) or None)
    if not base:
        return None
    if str(base).endswith(".json"):
        return str(base)
    return os.path.join(base, STORE_NAME)


def _empty_store():
    return {"version": STORE_VERSION, "updated_at": None, "runs": 0,
            "buckets": {}}


def _backfill_precision(store):
    """Normalize pre-precision buckets in place: a 3-segment legacy key
    (``platform|shape|gN``) becomes ``platform|shape|gN|f32`` and the
    bucket gains ``precision: "f32"`` — every fit recorded before the
    precision axis existed trained at the backend default."""
    buckets = store["buckets"]
    for key in list(buckets):
        b = buckets[key]
        if not isinstance(b, dict):
            continue
        if "precision" not in b:
            b["precision"] = "f32"
        want = bucket_key(b.get("platform"), b.get("shape"),
                          b.get("g_bucket") or 0, b["precision"])
        if key != want and want not in buckets:
            buckets[want] = buckets.pop(key)
    return store


def _read_store(path):
    """Parse a store file; None on missing/corrupt/wrong-version (the store
    is advisory — a bad file means 'no model', never an exception).
    Legacy precision-less buckets are backfilled to "f32" on read."""
    try:
        with open(path) as f:
            store = json.load(f)
    except (OSError, ValueError):
        return None
    if not (isinstance(store, dict)
            and store.get("version") == STORE_VERSION
            and isinstance(store.get("buckets"), dict)):
        return None
    return _backfill_precision(store)


class CostModel:
    """Read-side view over a store dict (or an in-memory equivalent)."""

    def __init__(self, store, path=None):
        self._store = store or _empty_store()
        self.path = path

    @property
    def buckets(self):
        return self._store["buckets"]

    @property
    def runs(self):
        return int(self._store.get("runs") or 0)

    @property
    def updated_at(self):
        return self._store.get("updated_at")

    def staleness_s(self, now=None):
        """Seconds since the store last absorbed an observation (None for
        a never-updated store)."""
        if self.updated_at is None:
            return None
        return max((now if now is not None else time.time())
                   - float(self.updated_at), 0.0)

    # ------------------------------------------------------------------
    def _candidates(self, shape_key, platform, precision="f32"):
        """Buckets matching (platform?, shape, precision), best-sampled
        first. Precision is part of the cost identity: a bf16 epoch and an
        f32 epoch of the same program family must never predict each
        other."""
        out = []
        for b in self.buckets.values():
            if b.get("shape") != shape_key:
                continue
            if platform is not None and b.get("platform") != platform:
                continue
            if precision is not None \
                    and (b.get("precision") or "f32") != precision:
                continue
            out.append(b)
        # best-sampled first; platform name breaks ties deterministically
        out.sort(key=lambda b: (-int(b.get("epochs") or 0),
                                str(b.get("platform"))))
        return out

    def epoch_ms_mean(self, shape_key, g_bucket, platform=None,
                      precision="f32"):
        """Mean observed epoch time for the EXACT bucket, or None."""
        for b in self._candidates(shape_key, platform, precision):
            if int(b.get("g_bucket") or 0) == int(g_bucket) \
                    and (b.get("epochs") or 0) > 0:
                return float(b["epoch_ms_total"]) / int(b["epochs"])
        return None

    # how far from an observed rung the linear width-scaling fallback may
    # reach: the ladder is log-spaced (powers of two, mesh-adjusted), so one
    # rung away is a 2x width ratio — the largest step where "per-lane cost
    # is ~flat" is still evidence rather than extrapolation. Scaling bucket
    # 4's mean out to 256 (a 64x ratio) answered confidently-wrong ETAs at
    # the ladder extremes (ISSUE 15 satellite); past the clamp the answer
    # is None — no evidence, never a wild guess.
    ADJACENT_RUNG_RATIO = 2.0

    def predict_epoch_ms(self, shape_key, g_bucket, platform=None,
                         precision="f32"):
        """Predicted wall ms for one epoch of ``shape_key`` at execution
        width ``g_bucket``: exact bucket mean, else the nearest-width
        bucket of the same (shape, precision) scaled linearly by the width
        ratio — CLAMPED to adjacent-rung scaling
        (:data:`ADJACENT_RUNG_RATIO`) — else None (no evidence)."""
        exact = self.epoch_ms_mean(shape_key, g_bucket, platform=platform,
                                   precision=precision)
        if exact is not None:
            return exact
        want = int(g_bucket)
        best = None
        for b in self._candidates(shape_key, platform, precision):
            w = int(b.get("g_bucket") or 0)
            n = int(b.get("epochs") or 0)
            if w <= 0 or n <= 0:
                continue
            if max(w, want) / min(w, want) > self.ADJACENT_RUNG_RATIO:
                continue  # beyond the adjacent rung: extrapolation, not
                #           evidence (None beats a 64x-scaled guess)
            # nearest width on the (log-spaced) bucket ladder
            d = abs(w - want) / max(w, want)
            if best is None or d < best[0]:
                best = (d, w, float(b["epoch_ms_total"]) / n)
        if best is None:
            return None
        _, w, mean_ms = best
        return mean_ms * (want / w)

    def compile_warm(self, shape_key, g_bucket, platform=None,
                     precision="f32"):
        """Whether the EXACT (platform?, shape, width, precision) bucket has
        compile evidence: the program family was compiled before on this
        store's lifetime, so — the persistent XLA cache riding the same base
        dir — a first touch is a warm retrieval, not a cold compile. The
        predictive scheduling policy (parallel/policy.py) treats warm rungs
        as free to move to and prices cold ones by
        :meth:`predict_compile_ms`."""
        for b in self._candidates(shape_key, platform, precision):
            if int(b.get("g_bucket") or 0) == int(g_bucket) \
                    and int(b.get("compiles") or 0) > 0:
                return True
        return False

    def predict_compile_ms(self, shape_key, g_bucket, platform=None,
                           precision="f32"):
        """Predicted wall ms of ONE cold compile of the bucket's program
        family (exact bucket, else nearest-width same-shape unscaled —
        compile cost is dominated by the program, not the lane count), or
        None."""
        want = int(g_bucket)
        best = None
        for b in self._candidates(shape_key, platform, precision):
            n = int(b.get("compiles") or 0)
            if n <= 0:
                continue
            w = int(b.get("g_bucket") or 0)
            d = 0.0 if w == want else abs(w - want) / max(w, want, 1)
            mean = float(b.get("compile_ms_total") or 0.0) / n
            if best is None or d < best[0]:
                best = (d, mean)
        return best[1] if best is not None else None

    def predict_fit_eta(self, shape_key, g_bucket, epochs, platform=None,
                        cold_programs=0, precision="f32"):
        """Predicted wall SECONDS for ``epochs`` epochs of ``shape_key`` at
        width ``g_bucket`` plus ``cold_programs`` cold compiles; None when
        the model has no step-cost evidence for the shape."""
        em = self.predict_epoch_ms(shape_key, g_bucket, platform=platform,
                                   precision=precision)
        if em is None:
            return None
        eta_ms = em * max(int(epochs), 0)
        if cold_programs:
            cm = self.predict_compile_ms(shape_key, g_bucket,
                                         platform=platform,
                                         precision=precision)
            if cm is not None:
                eta_ms += cm * int(cold_programs)
        return eta_ms / 1e3

    def accuracy_rows(self):
        """Store-side accuracy context per bucket (sample counts + means;
        residual MAPE lives in the run's ``cost_model`` events, which obs
        report joins with these rows)."""
        rows = []
        for key in sorted(self.buckets):
            b = self.buckets[key]
            n = int(b.get("epochs") or 0)
            rows.append({
                "bucket": key, "platform": b.get("platform"),
                "shape": b.get("shape"), "g_bucket": b.get("g_bucket"),
                "precision": b.get("precision") or "f32",
                "epochs": n,
                "mean_epoch_ms": (round(b["epoch_ms_total"] / n, 3)
                                  if n else None),
                "compiles": int(b.get("compiles") or 0),
                "mean_compile_ms": (
                    round(b["compile_ms_total"] / b["compiles"], 3)
                    if b.get("compiles") else None),
                "cache_hits": int(b.get("cache_hits") or 0),
                "cache_misses": int(b.get("cache_misses") or 0),
                "runs": int(b.get("runs") or 0),
                "updated_at": b.get("updated_at"),
            })
        return rows


def load(base_dir=None):
    """Load the persistent store as a :class:`CostModel`, or None when no
    store directory is configured / no usable store file exists yet."""
    path = store_path(base_dir)
    if path is None or not os.path.exists(path):
        return None
    store = _read_store(path)
    if store is None:
        return None
    return CostModel(store, path=path)


def fit_from_report(report, platform="any", precision="f32"):
    """In-memory model fit from one obs-report dict's ``cost_table`` (no
    persistence) — offline training / tests. ``precision`` labels the
    report's epochs (a mixed-precision run's report must say so, or its
    bf16 costs would contaminate the f32 bucket)."""
    model = CostModel(_empty_store())
    _merge_rows(model._store,
                _rows_from_cost_table(report, precision=precision),
                platform, now=time.time())
    return model


# ---------------------------------------------------------------------------
# write side
# ---------------------------------------------------------------------------
def _rows_from_cost_table(report, precision="f32"):
    rows = []
    for r in (report or {}).get("cost_table") or []:
        rows.append({
            "shape": r.get("shape"), "g_bucket": r.get("g_bucket"),
            # per-row label when the report carries one (future reports),
            # else the caller's fit-level label
            "precision": r.get("precision") or precision,
            "epochs": r.get("epochs") or 0,
            "epoch_ms": r.get("total_epoch_ms") or 0.0,
            "compiles": r.get("compiles") or 0,
            "compile_ms": r.get("compile_ms") or 0.0,
            "cache_hits": r.get("cache_hits") or 0,
            "cache_misses": r.get("cache_misses") or 0,
        })
    return rows


def rows_from_dispatch_stats(shape_key, stats, precision="f32"):
    """Store-update rows from one fit's ``dispatch_stats``: one row per
    execution width from the exact per-width accumulators; the fit-level
    compile/cache totals attach to the WIDEST row (cold compiles happen at
    the fit's starting bucket, before compaction shrinks it).
    ``precision`` stamps the rows' matmul-precision label so mixed and f32
    epochs land in distinct buckets.

    Each width's FIRST epoch is excluded when more epochs exist: it
    carries the compile / cache-priming skew (measured 20x the steady
    state on short fits), and a store that averages it in systematically
    overpredicts — compile cost is learned separately from the compile
    accumulators."""
    by_n = stats.get("epochs_by_width") or {}
    by_ms = stats.get("epoch_ms_by_width") or {}
    by_first = stats.get("first_epoch_ms_by_width") or {}
    widths = sorted((int(w) for w in by_n), reverse=True)
    rows = []
    for i, w in enumerate(widths):
        n = int(by_n.get(str(w), 0))
        if n <= 0:
            continue
        total = float(by_ms.get(str(w), 0.0))
        first = by_first.get(str(w))
        if n > 1 and isinstance(first, (int, float)) \
                and total - first > 0:
            n -= 1
            total -= float(first)
        rows.append({
            "shape": shape_key, "g_bucket": w, "precision": precision,
            "epochs": n,
            "epoch_ms": total,
            "compiles": int(stats.get("compiles") or 0) if i == 0 else 0,
            "compile_ms": float(stats.get("compile_ms") or 0.0)
            if i == 0 else 0.0,
            "cache_hits": int(stats.get("cache_hits") or 0) if i == 0 else 0,
            "cache_misses": int(stats.get("cache_misses") or 0)
            if i == 0 else 0,
        })
    return rows


def _merge_rows(store, rows, platform, now):
    changed = False
    for r in rows:
        shape, width = r.get("shape"), r.get("g_bucket")
        if not shape or not width or not (r.get("epochs")
                                          or r.get("compiles")):
            continue
        precision = r.get("precision") or "f32"
        key = bucket_key(platform, shape, width, precision)
        b = store["buckets"].get(key)
        if b is None:
            b = store["buckets"][key] = {
                "platform": platform, "shape": shape,
                "g_bucket": int(width), "precision": precision,
                "epochs": 0, "epoch_ms_total": 0.0,
                "compiles": 0, "compile_ms_total": 0.0, "cache_hits": 0,
                "cache_misses": 0, "runs": 0}
        b["epochs"] += int(r.get("epochs") or 0)
        b["epoch_ms_total"] = round(
            b["epoch_ms_total"] + float(r.get("epoch_ms") or 0.0), 3)
        b["compiles"] += int(r.get("compiles") or 0)
        b["compile_ms_total"] = round(
            b["compile_ms_total"] + float(r.get("compile_ms") or 0.0), 3)
        b["cache_hits"] += int(r.get("cache_hits") or 0)
        b["cache_misses"] += int(r.get("cache_misses") or 0)
        b["runs"] += 1
        b["updated_at"] = now
        changed = True
    if not changed:
        return False
    # bound the store: evict the longest-unobserved buckets past the cap
    buckets = store["buckets"]
    if len(buckets) > MAX_BUCKETS:
        by_age = sorted(buckets, key=lambda k: buckets[k].get("updated_at")
                        or 0.0)
        for k in by_age[: len(buckets) - MAX_BUCKETS]:
            del buckets[k]
    store["updated_at"] = now
    store["runs"] += 1
    return True


def update_store(base_dir, rows, platform, now=None):
    """Fold observation ``rows`` (see :func:`rows_from_dispatch_stats`) into
    the persistent store under ``base_dir`` — read-modify-write under a
    best-effort flock, atomic replace, corrupt stores restarted fresh.
    Returns the store path, or None when no base dir resolves."""
    path = store_path(base_dir)
    if path is None:
        return None
    now = time.time() if now is None else now
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with _lock:
        lock_fd = None
        try:
            try:
                import fcntl
            except ImportError:
                fcntl = None
            if fcntl is not None:
                try:
                    lock_fd = os.open(path + ".lock",
                                      os.O_CREAT | os.O_WRONLY)
                except OSError:
                    lock_fd = None  # lockless fallback (RO dir): atomic
                    #                 replace still prevents torn files
                if lock_fd is not None:
                    try:
                        fcntl.flock(lock_fd, fcntl.LOCK_EX)
                    except OSError:
                        # flock unsupported (some network mounts): release
                        # the fd NOW — the finally below only sees lock_fd
                        os.close(lock_fd)
                        lock_fd = None
            store = _read_store(path) or _empty_store()
            if not _merge_rows(store, rows, platform, now):
                return path
            tmp = f"{path}.tmp{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(store, f, indent=1, allow_nan=False)
                f.write("\n")
            os.replace(tmp, path)
            return path
        finally:
            if lock_fd is not None:
                os.close(lock_fd)  # closing drops the flock


def update_store_from_report(base_dir, report, platform, now=None,
                             precision="f32"):
    """Fold one obs-report's cost table into the persistent store — the
    offline "train the model from a finished run's telemetry" path.
    ``precision`` labels the report's epochs (read it off the run's
    ``fit_start.precision_mode``)."""
    return update_store(base_dir,
                        _rows_from_cost_table(report, precision=precision),
                        platform, now=now)
