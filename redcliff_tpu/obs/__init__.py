"""redcliff_tpu.obs — the telemetry spine (docs/ARCHITECTURE.md "Telemetry
spine").

One instrumentation layer every subsystem reports through (the
production-monitoring shape of large-scale ML systems, arXiv:1605.08695):

* :mod:`.spans` — lifecycle trace spans (monotonic + wall clocks, pid/host,
  parent propagation) and cross-thread counters; zero-cost when disabled
  (``REDCLIFF_TRACE=0``), never a host sync;
* :mod:`.flight` — the crash flight recorder: bounded in-memory rings of
  each component's last spans/events, dumped as ``flight_record.json`` on
  hang / host-loss / numerics-abort escalation;
* :mod:`.logging` — strict-JSON ``metrics.jsonl`` writing (seq/pid/host
  identity on every record, size-capped rotation) and crash-tolerant
  reading (torn lines skipped and counted);
* :mod:`.schema` — the versioned event-schema registry + validator (the
  tier-1 tripwire validates every emitted event against it);
* :mod:`.report` — the run-analytics CLI: ``python -m redcliff_tpu.obs
  report <run_dir>``;
* :mod:`.costmodel` — the learned per-(shape, G-bucket) step/compile cost
  model (persistent store under the compile-cache dir; stdlib-only);
* :mod:`.watch` — the live run watch CLI: ``python -m redcliff_tpu.obs
  watch <run_dir>`` (``--once --json`` for scripts);
* :mod:`.regress` — the cross-round bench regression sentinel:
  ``python -m redcliff_tpu.obs regress`` (stdlib-only);
* :mod:`.memory` — the analytical HBM footprint model (abstract shapes, no
  device work) + live ``device.memory_stats()`` watermark polling;
* :mod:`.profiling` — bounded ``jax.profiler`` capture windows
  (``REDCLIFF_PROFILE=epoch:3`` / ``profile_window``) replacing whole-fit
  traces;
* :mod:`.trace_export` — Perfetto / Chrome trace-event export:
  ``python -m redcliff_tpu.obs trace <run_dir> [-o trace.json]``; with
  ``--fleet`` a whole fleet root joins into one timeline (per-request
  tracks spanning processes, queue counter tracks);
* :mod:`.quality` — the model-quality observatory: live per-lane
  Granger-graph summaries at check-window boundaries (column norms, edge
  energy, top-k edge sets, factor-score entropy), convergence diagnostics
  (edge-set Jaccard stability, edge-energy plateau detection,
  ``plateaued_at_epoch``), and live AUROC/AUPR against ground-truth graphs
  (``REDCLIFF_QUALITY``; numpy at import, jax lazy);
* :mod:`.slo` — fleet service-level objectives from the request-lifecycle
  ledger (per-tenant queue-wait percentiles, time-to-first-attempt,
  deadline hit-rate, attempts-per-request, dead-letter rate;
  ``REDCLIFF_SLO_*`` breach thresholds; stdlib-only).

Import discipline: this ``__init__`` (and ``spans``/``flight``/``schema``)
is stdlib-only — the watchdog, the supervisor, and bench.py's backend-free
parent import it safely; numpy-using pieces (``logging``, ``report``) load
lazily on first attribute access.
"""
from __future__ import annotations

from redcliff_tpu.obs import flight, schema, spans  # noqa: F401 (stdlib-only)
from redcliff_tpu.obs import memory, profiling  # noqa: F401 (stdlib at import; jax lazy)
from redcliff_tpu.obs.spans import COUNTERS as counters  # noqa: F401
from redcliff_tpu.obs.spans import (NOOP, Span, enabled, record_span,  # noqa: F401
                                    set_enabled, span)

__all__ = [
    "span", "record_span", "Span", "NOOP", "enabled", "set_enabled",
    "counters",
    "flight", "schema", "spans", "memory", "profiling", "quality",
    "MetricLogger", "jsonable", "read_jsonl", "jsonl_files",
    "profiler_trace", "build_report", "render_text", "build_snapshot",
    "run_sentinel", "build_trace", "build_fleet_trace", "validate_trace",
    "compute_slo", "slo_for_root",
]

_LAZY = {
    "MetricLogger": "redcliff_tpu.obs.logging",
    "jsonable": "redcliff_tpu.obs.logging",
    "read_jsonl": "redcliff_tpu.obs.logging",
    "jsonl_files": "redcliff_tpu.obs.logging",
    "profiler_trace": "redcliff_tpu.obs.logging",
    "build_report": "redcliff_tpu.obs.report",
    "render_text": "redcliff_tpu.obs.report",
    "build_snapshot": "redcliff_tpu.obs.watch",
    "run_sentinel": "redcliff_tpu.obs.regress",
    "build_trace": "redcliff_tpu.obs.trace_export",
    "build_fleet_trace": "redcliff_tpu.obs.trace_export",
    "validate_trace": "redcliff_tpu.obs.trace_export",
    "compute_slo": "redcliff_tpu.obs.slo",
    "slo_for_root": "redcliff_tpu.obs.slo",
}


# whole modules loaded lazily on attribute access: quality pulls numpy at
# import time, which the stdlib-only importers above must not pay for
_LAZY_MODULES = {"quality": "redcliff_tpu.obs.quality"}


def __getattr__(name):
    import importlib

    mod = _LAZY_MODULES.get(name)
    if mod is not None:
        return importlib.import_module(mod)
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    return getattr(importlib.import_module(mod), name)
