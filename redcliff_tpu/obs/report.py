"""Run-analytics CLI: join a run directory's telemetry into one summary.

``python -m redcliff_tpu.obs report <run_dir>`` reads everything the spine
wrote — ``metrics.jsonl`` (rotation chain, torn lines tolerated),
``run_ledger.jsonl`` (supervisor attempts), the checkpointed
``dispatch_stats`` inside ``grid_checkpoint.pkl``, and any
``flight_record.json`` — and produces:

* a per-run summary: wall time in compile / train dispatch / val dispatch /
  checkpoint stall / prefetch stall, lane-epochs by G-bucket, the
  compaction/remesh history, quarantine + numerics skip/rollback counts,
  supervisor attempt classifications;
* a machine-readable per-(shape, G-bucket) **cost table** — observed epoch
  step cost and compile cost per compiled program family. This table is the
  training input for ROADMAP item 4's learned cost model (choose bucket
  ladders/compaction points by predicted wall-clock) and item 1's admission
  planner (pack requests into G-buckets the mesh can absorb);
* a schema audit: every record validated against the versioned registry
  (:mod:`redcliff_tpu.obs.schema`), torn-line counts per file;
* the learned-cost-model view (obs/costmodel.py): per-(shape, G-bucket)
  prediction accuracy from the run's ``cost_model`` residual events (MAPE,
  sample counts, last ETA) joined with the persistent store's state
  (bucket sample counts, staleness);
* provenance of the cached real-TPU evidence
  (``experiments/TPU_BENCH_CACHE.json``) so dated TPU measurements stay
  visible next to CPU-fallback telemetry.

``--json`` prints the full report as one JSON object; ``-o PATH`` writes it.
The builder is importable (:func:`build_report`) for tests and services.
A missing or telemetry-less run dir exits with code 2 and a one-line
diagnosis. This module also hosts the ``obs`` CLI dispatcher: ``report``,
``watch`` (:mod:`redcliff_tpu.obs.watch`), ``trace``
(:mod:`redcliff_tpu.obs.trace_export`) and ``regress``
(:mod:`redcliff_tpu.obs.regress`).
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys

from redcliff_tpu.obs import costmodel as _costmodel
from redcliff_tpu.obs import schema as _schema
from redcliff_tpu.obs.logging import read_jsonl

__all__ = ["build_report", "render_text", "main", "LEDGER_NAME"]

LEDGER_NAME = "run_ledger.jsonl"

# dispatch_stats keys summed across attempts for the time breakdown
_SUM_STATS = ("train_dispatches", "val_dispatches", "epochs", "compactions",
              "remeshes", "lane_epochs", "lane_epochs_nominal",
              "compile_ms", "compiles", "cache_hits", "cache_misses",
              "ckpt_stall_ms", "ckpt_barrier_stall_ms", "prefetch_stall_ms",
              "prefetch_items", "train_time_ms", "val_time_ms")


# canonical (shape, G-bucket) key shared with the cost-model store
_shape_key = _schema.shape_key


def _tpu_cache_provenance():
    """Cached real-TPU evidence provenance (lazy import: regress owns the
    reader and the repo-root default); None when neither cache file
    parses."""
    try:
        from redcliff_tpu.obs import regress as _regress

        return _regress.load_tpu_cache_provenance()
    except Exception:  # noqa: BLE001 — provenance is garnish, never fatal
        return None


def _cost_model_store_info(run_cache_dir=None):
    """State of the persistent cost-model store (obs/costmodel.py): where
    it is, how much evidence it holds, how stale it is.

    ``run_cache_dir`` is the VERSIONED jax compile-cache dir the run's
    fit_start recorded; when the env-resolved store is absent (the report
    is read on a host without the writer's env), the store that fit
    actually wrote — ``<dirname(run_cache_dir)>/cost_model_v*.json`` — is
    tried next."""
    path = _costmodel.store_path()
    if (path is None or not os.path.exists(path)) and run_cache_dir:
        alt = _costmodel.store_path(os.path.dirname(str(run_cache_dir)))
        if alt and os.path.exists(alt):
            path = alt
    if path is None:
        return {"configured": False, "path": None}
    # load(path) handles both base forms store_path supports — a directory
    # and a direct *.json file (REDCLIFF_COST_MODEL_DIR may be either)
    model = _costmodel.load(path)
    if model is None:
        return {"configured": True, "path": path, "present": False}
    stale = model.staleness_s()
    return {"configured": True, "path": path, "present": True,
            "version": _costmodel.STORE_VERSION, "runs": model.runs,
            "buckets": len(model.buckets),
            "updated_at": model.updated_at,
            "staleness_s": round(stale, 1) if stale is not None else None}


def _read_ledger(run_dir, stats):
    path = os.path.join(run_dir, LEDGER_NAME)
    if not os.path.exists(path):
        return []
    return read_jsonl(path, stats=stats)


def _checkpoint_stats(run_dir):
    """dispatch_stats snapshot stored in the newest grid checkpoint
    generation, or None (older checkpoints / no checkpoint / no numpy)."""
    path = os.path.join(run_dir, "grid_checkpoint.pkl")
    if not os.path.exists(path):
        return None
    try:
        from redcliff_tpu.runtime import checkpoint as durable_ckpt

        ckpt, _src = durable_ckpt.load_checkpoint(path,
                                                  allow_quarantine=False)
        if isinstance(ckpt, dict):
            return ckpt.get("dispatch_stats")
    except Exception:  # noqa: BLE001 — a torn checkpoint must not kill
        return None    # the report; the metrics chain still has the story
    return None


def build_report(run_dir):
    """Aggregate one run directory's telemetry into a plain-dict report
    (strict-JSON-able; see module docstring for the sections)."""
    mstats, lstats = {}, {}
    try:
        records = read_jsonl(run_dir, stats=mstats)
    except FileNotFoundError:
        records = []
        mstats = {"files": [], "records": 0, "torn_lines": 0}
    ledger = _read_ledger(run_dir, lstats)

    fits = []
    cur = None            # current fit context: {"shape_key", "shape", ...}
    manifest = {}         # request_id -> {tenant, start, stop} (fleet runs)
    fleet_kind_counts = {}  # fleet-event lifecycle counts (fleet roots)
    packing_counts = {}     # packing-event kind tallies (ISSUE 18)
    packing_last_plan = None  # newest priced packed-vs-serial verdict
    partial_streamed = partial_final = 0  # partial_result rows seen
    autoscale_counts = {}   # autoscale decision-kind counts (ISSUE 16)
    last_autoscale = None
    qos_last = {}           # tenant -> newest qos demote/restore event
    qos_demotes = 0
    bp_rejects = 0
    bp_last = None
    cost = {}             # (shape_key, g_bucket) -> accumulators
    cm_acc = {}           # (shape_key, g_bucket) -> residual-event accuracy
    run_cache_dir = None  # the versioned compile-cache dir fit_start logs
    profiles = []         # capture-window artifacts (`profile` events)
    compactions, remeshes, failures, hangs = [], [], [], []
    anomalies = rollbacks = aborts = skipped_steps = 0
    precision_events = []  # mixed-precision demotions (ISSUE 14)
    autotune_events = []   # kernel-tiling searches/lookups (ops/autotune.py)
    policy_events = []     # predictive-policy decisions (ISSUE 15)
    preempt_events = []    # deadline-aware preemption events (ISSUE 15)
    quarantined = 0
    stats_sum = {k: 0 for k in _SUM_STATS}
    t_first = t_last = None

    # two epoch-count sources per (shape, width): EXACT per-width
    # accumulators from fit_end's dispatch_stats (the grid counts every
    # epoch), and SAMPLED counts from `epoch` events (the grid only emits
    # those on the check_every cadence — up to check_every x fewer than ran;
    # the trainers emit every epoch, so sampling is exact there). Exact wins
    # whenever present
    def _cost(shape_key, width):
        key = (shape_key, int(width))
        if key not in cost:
            cost[key] = {"epochs_sampled": 0, "epoch_ms_sampled": 0.0,
                         "epochs_exact": 0, "epoch_ms_exact": 0.0,
                         "compiles": 0, "compile_ms": 0.0, "cache_hits": 0,
                         "cache_misses": 0}
        return cost[key]

    for rec in records:
        ev = rec.get("event")
        wt = rec.get("wall_time")
        if isinstance(wt, (int, float)):
            t_first = wt if t_first is None else min(t_first, wt)
            t_last = wt if t_last is None else max(t_last, wt)
        if ev == "fit_start":
            cur = {"model": rec.get("model"),
                   "shape": rec.get("shape"),
                   "shape_key": _shape_key(rec.get("shape")),
                   "grid_size": rec.get("grid_size"),
                   "grid_width": rec.get("grid_width"),
                   "stream_mode": rec.get("stream_mode"),
                   "resumed_from_epoch": rec.get("resumed_from_epoch"),
                   "mesh": rec.get("mesh")}
            fits.append(cur)
            if rec.get("compile_cache_dir"):
                run_cache_dir = rec["compile_cache_dir"]
        elif ev == "epoch":
            width = rec.get("grid_width") or 1
            if isinstance(rec.get("epoch_ms"), (int, float)):
                c = _cost(cur["shape_key"] if cur else "unknown", width)
                c["epochs_sampled"] += 1
                c["epoch_ms_sampled"] += rec["epoch_ms"]
            skipped_steps = max(skipped_steps,
                                rec.get("guarded_steps_skipped") or 0)
        elif ev == "compile":
            width = rec.get("grid_width") or (cur or {}).get("grid_width") \
                or 1
            c = _cost(cur["shape_key"] if cur else "unknown", width)
            c["compiles"] += rec.get("programs") or 0
            c["compile_ms"] += rec.get("compile_ms") or 0.0
            c["cache_hits"] += rec.get("cache_hits") or 0
            c["cache_misses"] += rec.get("cache_misses") or 0
        elif ev == "cost_model":
            # learned-cost-model residual events (one per check window):
            # the prediction-accuracy evidence the accuracy table reports
            width = rec.get("grid_width") or (cur or {}).get("grid_width") \
                or 1
            key = (cur["shape_key"] if cur else "unknown", int(width))
            a = cm_acc.setdefault(key, {
                "samples": 0, "abs_pct_sum": 0.0, "sources": set(),
                "last": None})
            a["samples"] += 1
            if isinstance(rec.get("residual_pct"), (int, float)):
                a["abs_pct_sum"] += abs(rec["residual_pct"])
            if rec.get("source"):
                a["sources"].add(rec["source"])
            a["last"] = rec
        elif ev == "memory":
            # device-memory observatory (obs/memory.py): the analytical
            # prediction at fit start + the max measured watermark across
            # this fit's polls — the predicted-vs-measured view per fit
            if cur is not None:
                m = cur.setdefault("_memory", {
                    "predicted_bytes": None, "g_bucket": None,
                    "measured_peak_bytes": None, "polls": 0,
                    "bytes_limit": None, "fits_device": None,
                    "backend": None})
                if rec.get("kind") == "predicted":
                    m["predicted_bytes"] = rec.get("predicted_bytes")
                    m["g_bucket"] = rec.get("g_bucket")
                    m["fits_device"] = rec.get("fits")
                    m["backend"] = rec.get("backend")
                    if rec.get("bytes_limit") is not None:
                        m["bytes_limit"] = rec["bytes_limit"]
                elif rec.get("kind") == "measured":
                    m["polls"] += 1
                    peak = rec.get("peak_bytes")
                    if peak is None:
                        peak = rec.get("bytes_in_use")
                    if isinstance(peak, (int, float)):
                        m["measured_peak_bytes"] = max(
                            m["measured_peak_bytes"] or 0, peak)
                    if rec.get("bytes_limit") is not None:
                        m["bytes_limit"] = rec["bytes_limit"]
        elif ev == "quality":
            # model-quality observatory (obs/quality.py): per-check-window
            # graph summaries; the last event + the fit_end snapshot below
            # become the report's model-quality section
            if cur is not None:
                qv = cur.setdefault("_quality", {"windows": 0, "last": None,
                                                 "snapshot": None})
                qv["windows"] += 1
                qv["last"] = rec
        elif ev == "packing":
            # spatial mesh packing (ISSUE 18): slot-lifecycle tallies +
            # the newest priced packed-vs-serial verdict
            kind = str(rec.get("kind"))
            packing_counts[kind] = packing_counts.get(kind, 0) + 1
            if kind == "plan":
                packing_last_plan = rec
        elif ev == "partial_result":
            # per-point result streaming (ISSUE 18): at-least-once rows,
            # so tally final vs streaming separately (a resumed batch may
            # re-stream a point; consumers keep the last row per point)
            partial_streamed += 1
            partial_final += bool(rec.get("final"))
        elif ev == "fleet":
            # tenant manifest (fleet/run_batch.py): request id -> merged
            # point range; restart attempts re-log it, latest wins
            kind = rec.get("kind")
            fleet_kind_counts[str(kind)] = \
                fleet_kind_counts.get(str(kind), 0) + 1
            if kind == "manifest":
                for row in rec.get("requests") or []:
                    if isinstance(row, dict) and row.get("request_id"):
                        manifest[row["request_id"]] = row
        elif ev == "autoscale":
            # the SLO-driven control loop's decision stream (ISSUE 16)
            kind = str(rec.get("kind"))
            autoscale_counts[kind] = autoscale_counts.get(kind, 0) + 1
            # headline = the newest POOL decision; start/stop are loop
            # lifecycle markers, holds are steady-state noise
            if kind not in ("hold", "start", "stop"):
                last_autoscale = rec
        elif ev == "qos":
            if rec.get("tenant") is not None:
                qos_last[str(rec["tenant"])] = rec
            qos_demotes += rec.get("kind") == "demote"
        elif ev == "backpressure":
            bp_rejects += rec.get("kind") == "reject"
            bp_last = rec
        elif ev == "profile":
            profiles.append({k: rec.get(k) for k in
                             ("path", "spec", "first_epoch", "last_epoch",
                              "dur_ms", "truncated")})
        elif ev == "compaction":
            compactions.append({k: rec.get(k) for k in
                                ("epoch", "from_width", "to_width",
                                 "lanes_live", "retired")})
            if cur is not None:
                cur["grid_width"] = rec.get("to_width")
        elif ev == "remesh":
            remeshes.append({k: rec.get(k) for k in
                             ("epoch", "from_width", "to_width",
                              "from_devices", "to_devices",
                              "lanes_migrated", "plan_ms")})
        elif ev == "anomaly":
            anomalies += 1
        elif ev == "numerics":
            if rec.get("kind") == "rollback":
                rollbacks += 1
            elif rec.get("kind") == "abort":
                aborts += 1
        elif ev == "precision":
            precision_events.append({k: rec.get(k) for k in
                                     ("kind", "epoch", "cause", "mode_from",
                                      "mode_to", "lanes")})
        elif ev == "autotune":
            autotune_events.append({k: rec.get(k) for k in
                                    ("kernel", "kind", "shape", "g_bucket",
                                     "tile", "search_ms",
                                     "speedup_vs_default")})
        elif ev == "policy":
            # predictive scheduling decisions (ISSUE 15, parallel/policy.py
            # via the grid engine / fleet worker): kept with the emitting
            # fit's shape key so compaction decisions can be joined against
            # the observed per-width epoch costs (predicted vs REALIZED)
            policy_events.append(dict(
                rec, _shape_key=(cur or {}).get("shape_key", "unknown")))
        elif ev == "preempt":
            preempt_events.append(rec)
        elif ev == "fit_end":
            ds = rec.get("dispatch_stats")
            # quality snapshot: inside dispatch_stats for the grid engine,
            # a top-level field for the trainers; missing on pre-quality
            # runs (.get everywhere — never a KeyError)
            q_snap = (ds.get("quality") if isinstance(ds, dict) else None) \
                or rec.get("quality")
            if isinstance(q_snap, dict) and cur is not None:
                cur.setdefault("_quality", {"windows": 0, "last": None,
                                            "snapshot": None})["snapshot"] \
                    = q_snap
            if isinstance(ds, dict):
                for k in _SUM_STATS:
                    v = ds.get(k)
                    if isinstance(v, (int, float)):
                        stats_sum[k] += v
                # exact per-width epoch/step-cost accumulators (every epoch
                # counted, not just the check-window-sampled ones)
                em = ds.get("epoch_ms_by_width") or {}
                sk = cur["shape_key"] if cur else "unknown"
                for w, n in (ds.get("epochs_by_width") or {}).items():
                    c = _cost(sk, int(w))
                    c["epochs_exact"] += int(n)
                    c["epoch_ms_exact"] += float(em.get(w, 0.0))
            for f in rec.get("failures") or []:
                failures.append(f)
            quarantined += len(rec.get("failures") or [])
        elif ev in ("hang", "host_lost", "hang_exit", "host_lost_exit"):
            hangs.append({"event": ev,
                          "components": sorted(rec.get("components") or {}),
                          "exit_code": rec.get("exit_code")})

    ck_stats = _checkpoint_stats(run_dir)

    # the worker stamps the same tenant manifest into the supervisor ledger
    # (fleet/worker.py) — it covers attempts that died before the metrics
    # chain got the run_batch manifest event
    for rec in ledger:
        if rec.get("event") == "fleet" and rec.get("kind") == "manifest":
            for row in rec.get("requests") or []:
                if isinstance(row, dict) and row.get("request_id"):
                    manifest.setdefault(row["request_id"], row)

    attempts = [r for r in ledger if r.get("event") == "attempt"]
    classes = {}
    for a in attempts:
        c = a.get("classification") or "?"
        classes[c] = classes.get(c, 0) + 1
    final = next((r for r in reversed(ledger)
                  if r.get("event") == "final"), None)

    cost_table = []
    by_bucket = {}
    for (sk, width), acc in sorted(cost.items()):
        exact = acc["epochs_exact"] > 0
        n = acc["epochs_exact"] if exact else acc["epochs_sampled"]
        ms = acc["epoch_ms_exact"] if exact else acc["epoch_ms_sampled"]
        cost_table.append(
            {"shape": sk, "g_bucket": width, "epochs": n,
             "mean_epoch_ms": round(ms / n, 3) if n else None,
             "total_epoch_ms": round(ms, 3),
             # sampled=True: epoch counts/times come from check-window
             # `epoch` events only (the emitting fit never wrote its
             # dispatch_stats — e.g. it crashed before fit_end), so they
             # undercount by up to check_every
             "sampled": not exact,
             "compiles": acc["compiles"],
             "compile_ms": round(acc["compile_ms"], 3),
             "cache_hits": acc["cache_hits"],
             "cache_misses": acc["cache_misses"]})
        if n:
            by_bucket[str(width)] = by_bucket.get(str(width), 0) + n

    # cost-model accuracy table: the run's prediction-vs-actual residuals
    # per (shape, G-bucket) — the "is the learned model any good yet" view
    cm_rows = []
    for (sk, width), a in sorted(cm_acc.items()):
        last = a["last"] or {}
        cm_rows.append({
            "shape": sk, "g_bucket": width, "samples": a["samples"],
            "mape_pct": (round(a["abs_pct_sum"] / a["samples"], 2)
                         if a["samples"] else None),
            "sources": sorted(a["sources"]),
            "last_predicted_epoch_ms": last.get("predicted_epoch_ms"),
            "last_actual_epoch_ms": last.get("actual_epoch_ms"),
            "last_eta_s": last.get("eta_s"),
            "last_epoch": last.get("epoch"),
        })

    # predictive policy decision table (ISSUE 15): what the policy decided
    # (compact / hold / widen / fallback), the saving it PREDICTED, and —
    # for executed compactions, joined against the observed per-width epoch
    # means above — the saving it REALIZED. The fallback count is the
    # "how often did the store have no usable prior" health signal
    def _observed_mean_ms(sk, width):
        acc = cost.get((sk, int(width or 0)))
        if not acc:
            return None
        exact = acc["epochs_exact"] > 0
        n = acc["epochs_exact"] if exact else acc["epochs_sampled"]
        ms = acc["epoch_ms_exact"] if exact else acc["epoch_ms_sampled"]
        return ms / n if n else None

    policy_decisions = None
    if policy_events or preempt_events:
        by_action = {}
        fallbacks = 0
        pred_sum = real_sum = 0.0
        joined = 0
        rows = []
        for p in policy_events:
            key = f"{p.get('kind')}:{p.get('action') or '?'}"
            by_action[key] = by_action.get(key, 0) + 1
            if p.get("fallback"):
                fallbacks += 1
            realized = None
            if p.get("kind") == "compaction" and p.get("action") == "compact" \
                    and isinstance(p.get("saving_ms"), (int, float)) \
                    and isinstance(p.get("epochs_remaining"), (int, float)):
                mf = _observed_mean_ms(p.get("_shape_key"),
                                       p.get("from_width"))
                mt = _observed_mean_ms(p.get("_shape_key"),
                                       p.get("to_width"))
                if mf is not None and mt is not None:
                    realized = (mf - mt) * p["epochs_remaining"]
                    pred_sum += p["saving_ms"]
                    real_sum += realized
                    joined += 1
            rows.append({
                "kind": p.get("kind"), "action": p.get("action"),
                "epoch": p.get("epoch"),
                "fallback": bool(p.get("fallback")),
                "from_width": p.get("from_width"),
                "to_width": p.get("to_width"),
                "chosen_width": p.get("chosen_width"),
                "heuristic_width": p.get("heuristic_width"),
                "predicted_saving_ms": p.get("saving_ms"),
                "realized_saving_ms": (round(realized, 3)
                                       if realized is not None else None),
                "compile_ms": p.get("compile_ms"),
                "epochs_remaining": p.get("epochs_remaining"),
                "beneficiary": p.get("beneficiary"),
                "reason": p.get("reason")})
        policy_decisions = {
            "decisions": len(policy_events),
            "by_action": dict(sorted(by_action.items())),
            "fallbacks": fallbacks,
            "predicted_saving_ms": (round(pred_sum, 3) if joined else None),
            "realized_saving_ms": (round(real_sum, 3) if joined else None),
            "preempts": sum(1 for p in preempt_events
                            if p.get("kind") == "preempted"),
            "preempt_signals": sum(1 for p in preempt_events
                                   if p.get("kind") == "signal"),
            "rows": rows[-16:],
        }

    # model-quality section (obs/quality.py): per-fit convergence readouts
    # from the quality events + the fit_end snapshot, and — on fleet batch
    # run dirs — the per-request quality blocks run_batch stamped into
    # results/<id>.json (requests with no quality events render n/a)
    quality_fits = []
    for i, f in enumerate(fits):
        qv = f.pop("_quality", None)
        if qv is None:
            continue
        snap = qv.get("snapshot") or {}
        last = qv.get("last") or {}
        quality_fits.append({
            "fit": i, "model": f.get("model"),
            "windows": snap.get("windows") or qv["windows"],
            "lanes": snap.get("lanes"),
            "plateaued_count": (snap.get("plateaued_count")
                                if snap else last.get("plateaued_count")),
            "converged_at_epoch": snap.get("converged_at_epoch"),
            "final_stability": (snap.get("mean_edge_stability")
                                if snap else last.get("mean_jaccard")),
            "final_auroc": (snap.get("mean_auroc")
                            if snap else last.get("mean_auroc")),
            "final_aupr": (snap.get("mean_aupr")
                           if snap else last.get("mean_aupr")),
        })
    request_quality = {}
    results_dir = os.path.join(run_dir, "results")
    if manifest and os.path.isdir(results_dir):
        for rid in manifest:
            try:
                with open(os.path.join(results_dir, f"{rid}.json")) as fh:
                    rec_ = json.load(fh)
                request_quality[rid] = (rec_ or {}).get("quality")
            except (OSError, ValueError):
                request_quality[rid] = None
    quality_section = {"fits": quality_fits, "requests": request_quality}

    # device-memory section: predicted vs measured peak per fit + the
    # profile-artifact inventory (capture windows announce their artifacts
    # via `profile` events; stray artifact dirs under the run dir are
    # globbed too so un-announced traces still surface)
    mem_fits = []
    for i, f in enumerate(fits):
        m = f.pop("_memory", None)
        if m is None:
            continue
        pred, meas = m["predicted_bytes"], m["measured_peak_bytes"]
        err = (round(100.0 * (pred - meas) / meas, 1)
               if isinstance(pred, (int, float))
               and isinstance(meas, (int, float)) and meas else None)
        mem_fits.append({"fit": i, "model": f.get("model"), **m,
                         "err_pct": err})
    artifact_dirs = sorted(
        os.path.relpath(p, run_dir)
        for p in glob.glob(os.path.join(run_dir, "profile*"))
        if os.path.isdir(p))
    memory_section = {
        "fits": mem_fits,
        "measured_available": any(
            m["measured_peak_bytes"] is not None for m in mem_fits),
        "profiles": profiles,
        "profile_artifacts": artifact_dirs,
    }

    # per-tenant section (fleet runs, docs/ARCHITECTURE.md "Fleet sweep
    # service"): fits/points/lane-epochs/wall attributed through the tenant
    # manifest's merged point ranges; quarantine causes keyed by which
    # range the failing ORIGINAL point id falls in
    tenants = {}
    if manifest:
        # lane-epochs attributed by point share of the engine's EXACT
        # total (dispatch_stats lane_epochs counts what actually computed,
        # early-stop/compaction included) — per-tenant numbers always sum
        # to the run's own lane-epoch accounting above, never beyond it
        total_pts = sum(
            max(int(r.get("stop") or 0) - int(r.get("start") or 0), 0)
            for r in manifest.values()) or 1
        exact_lane_epochs = int(stats_sum["lane_epochs"])
        for row in manifest.values():
            t = tenants.setdefault(str(row.get("tenant")), {
                "requests": 0, "points": 0, "lane_epochs": 0,
                "quarantined": {}, "wall_s": (round(t_last - t_first, 3)
                                              if t_first is not None
                                              else None)})
            n = int(row.get("stop") or 0) - int(row.get("start") or 0)
            t["requests"] += 1
            t["points"] += n
        # largest-remainder apportionment of the exact total by point
        # share (independent rounding could sum past the engine's number)
        shares = sorted(
            ((exact_lane_epochs * t["points"]) % total_pts, name)
            for name, t in tenants.items())
        leftover = exact_lane_epochs - sum(
            exact_lane_epochs * t["points"] // total_pts
            for t in tenants.values())
        for frac, name in reversed(shares):
            t = tenants[name]
            t["lane_epochs"] = exact_lane_epochs * t["points"] // total_pts
            if leftover > 0 and frac:
                t["lane_epochs"] += 1
                leftover -= 1
        for f in failures:
            p = f.get("point")
            if not isinstance(p, int):
                continue
            for row in manifest.values():
                if int(row.get("start") or 0) <= p < int(row.get("stop")
                                                         or 0):
                    q = tenants[str(row.get("tenant"))]["quarantined"]
                    cause = f.get("cause") or "?"
                    q[cause] = q.get(cause, 0) + 1
                    break

    # fleet containment section (fleet ROOTS only, docs/ARCHITECTURE.md
    # "Fleet failure containment"): dead-letter dossiers, per-request
    # attempt budgets, and the containment-lifecycle event counts (bisect /
    # deadletter / cancel / requeue / renew_error)
    containment = None
    fleet_slo = None
    fleet_autoscale = None
    if os.path.exists(os.path.join(run_dir, "requests.jsonl")) \
            or os.path.isdir(os.path.join(run_dir, "leases")):
        from redcliff_tpu.fleet import autoscale as _as
        from redcliff_tpu.fleet.queue import FleetQueue
        from redcliff_tpu.obs import slo as _slo

        # fleet-SLO section (ISSUE 12): per-tenant queue-wait percentiles,
        # time-to-first-attempt, deadline hit-rate, attempts-per-request,
        # dead-letter rate from the durable lifecycle ledger, with
        # REDCLIFF_SLO_* breach flags
        fleet_slo = _slo.slo_for_root(run_dir)
        q = FleetQueue(run_dir, create=False)  # pure reader
        st = q.status()
        containment = {
            "counts": st["counts"],
            "deadletters": [{
                "request_id": rec.get("request_id"),
                "deadlettered_at": rec.get("deadlettered_at"),
                "dossier": rec.get("dossier"),
            } for rec in q.deadletters()],
            "attempt_records": q.attempt_records(),
            "events": {k: fleet_kind_counts[k] for k in sorted(
                fleet_kind_counts)
                if k in ("deadletter", "bisect", "cancel", "requeue",
                         "renew_error", "lease_lost", "reclaim")},
        }
        # autoscale section (ISSUE 16): decision-kind tallies from the
        # metrics chain, the last non-hold decision, the durable published
        # control state, active QoS rungs, and admission-gate rejects
        auto_state = _as.load_state(run_dir)
        qos_rungs = _as.active_qos(run_dir)
        if autoscale_counts or auto_state is not None or qos_rungs \
                or bp_rejects or qos_last:
            fleet_autoscale = {
                "decisions": {k: autoscale_counts[k]
                              for k in sorted(autoscale_counts)},
                "last_decision": ({k: last_autoscale.get(k) for k in
                                   ("kind", "reason", "workers", "target",
                                    "queue_depth", "drain_eta_s",
                                    "breaches", "wall_time")}
                                  if last_autoscale else None),
                "state": auto_state,
                "qos": {t: {"rung": r.get("rung"), "reason": r.get("reason")}
                        for t, r in sorted(qos_rungs.items())},
                "qos_demotes": int(qos_demotes),
                "qos_last_events": {t: {k: e.get(k) for k in
                                        ("kind", "rung", "from_rung",
                                         "reason")}
                                    for t, e in sorted(qos_last.items())},
                "backpressure": {
                    "rejects": int(bp_rejects),
                    "last": ({k: bp_last.get(k) for k in
                              ("tenant", "eta_s", "threshold_s",
                               "queue_depth", "workers")}
                             if bp_last else None),
                },
            }

    # spatial-packing section (ISSUE 18): slot-lifecycle tallies, the
    # newest priced verdict, and the partial-result streaming progress.
    # None on run dirs/roots that never packed or streamed.
    fleet_packing = None
    if not partial_streamed:
        # fleet ROOT: partial_result events live in each batch's RUN-DIR
        # chain, not here — count the durable stream files instead (the
        # same at-least-once contract `fleet status` / `obs watch` read)
        for pf in sorted(glob.glob(os.path.join(
                run_dir, "work", "*", "results", "*.partial.jsonl")))[:256]:
            try:
                with open(pf, "r", encoding="utf-8") as fh:
                    for line in fh:
                        if not line.strip():
                            continue
                        partial_streamed += 1
                        try:
                            partial_final += bool(
                                json.loads(line).get("final"))
                        except ValueError:
                            pass
            except OSError:
                continue
    if packing_counts or partial_streamed:
        fleet_packing = {
            "events": {k: packing_counts[k] for k in sorted(packing_counts)},
            "last_plan": ({k: packing_last_plan.get(k) for k in
                           ("decision", "reason", "makespan_s", "serial_s",
                            "makespan_ratio", "n_devices", "pool",
                            "headroom_violations")}
                          if packing_last_plan else None),
            "partial_results": {"streamed": int(partial_streamed),
                                "final": int(partial_final)},
        }

    # streaming-inference section (ISSUE 17): the serve plane's cumulative
    # counters + latency SLO view (obs/slo.py compute_serve_slo over the
    # run's `serve` events, REDCLIFF_SLO_SERVE_* breach flags) and the
    # session-lifecycle tallies. None on run dirs that never served.
    serve_section = None
    serve_events = [r for r in records if r.get("event") == "serve"]
    if serve_events:
        from redcliff_tpu.obs import slo as _slo_mod

        session_kinds = {}
        qos_demotes_serve = 0
        for r in records:
            if r.get("event") == "session":
                k = str(r.get("kind"))
                session_kinds[k] = session_kinds.get(k, 0) + 1
            elif r.get("event") == "serve" and r.get("kind") == "qos" \
                    and (r.get("rung") or 0) > (r.get("from_rung") or 0):
                qos_demotes_serve += 1
        serve_section = {
            "slo": _slo_mod.compute_serve_slo(records),
            "sessions": {k: session_kinds[k]
                         for k in sorted(session_kinds)},
            "qos_demotes": qos_demotes_serve,
        }
        # elastic-data-plane occupancy table (ISSUE 20): rung ride history
        # from the `serve_ladder` decisions, dead-lane % from the tick-level
        # width-vs-capacity ratio (the fraction of slot-table FLOPs the
        # ladder did NOT dispatch), fuse-depth distribution from the newest
        # cumulative `serve_fuse` histogram, and serve-scoped precision
        # demotions (the poisoned-lane-storm sentinel)
        ladder_counts = {}
        rung_history = []
        ladder_mode = None
        width_sum = width_n = live_sum = 0
        capacity = None
        fuse_hist = None
        fused_samples = 0
        serve_demotions = []
        for r in records:
            ev = r.get("event")
            if ev == "serve":
                if r.get("capacity") is not None:
                    capacity = r["capacity"]
                if r.get("mode") is not None:
                    ladder_mode = r["mode"]
                if r.get("kind") == "tick" and r.get("width") is not None:
                    width_sum += r["width"]
                    width_n += 1
                    live_sum += r.get("live") or 0
                if r.get("fused_samples") is not None:
                    fused_samples = r["fused_samples"]
            elif ev == "serve_ladder":
                k = str(r.get("kind"))
                ladder_counts[k] = ladder_counts.get(k, 0) + 1
                if k in ("grow", "shrink") and len(rung_history) < 64:
                    rung_history.append(
                        {"kind": k, "from": r.get("from_width"),
                         "to": r.get("to_width"), "live": r.get("live"),
                         "tick": r.get("ticks")})
            elif ev == "serve_fuse" and r.get("kind") == "stats":
                fuse_hist = r.get("hist") or fuse_hist
                if r.get("fused_samples") is not None:
                    fused_samples = r["fused_samples"]
            elif ev == "precision" and r.get("scope") == "serve":
                serve_demotions.append(
                    {"kind": r.get("kind"), "cause": r.get("cause"),
                     "lanes_poisoned": r.get("lanes_poisoned"),
                     "tick": r.get("ticks")})
        if width_n or ladder_counts or fuse_hist or serve_demotions:
            mean_width = (width_sum / width_n) if width_n else None
            dead_pct = None
            if mean_width is not None and capacity:
                dead_pct = round(100.0 * (1.0 - mean_width / capacity), 1)
            serve_section["occupancy"] = {
                "ladder_mode": ladder_mode,
                "capacity": capacity,
                "mean_rung": (round(mean_width, 2)
                              if mean_width is not None else None),
                "mean_live": (round(live_sum / width_n, 2)
                              if width_n else None),
                "dead_lane_flops_saved_pct": dead_pct,
                "decisions": {k: ladder_counts[k]
                              for k in sorted(ladder_counts)},
                "rung_history": rung_history,
                "fuse_depth_hist": fuse_hist,
                "fused_samples": int(fused_samples),
                "demotions": serve_demotions,
            }

    schema_errors = _schema.validate_records(records)
    ledger_errors = _schema.validate_records(ledger, kind="ledger")

    saved = None
    if stats_sum["lane_epochs_nominal"]:
        saved = round(100.0 * (1 - stats_sum["lane_epochs"]
                               / stats_sum["lane_epochs_nominal"]), 1)
    return {
        "run_dir": os.path.abspath(run_dir),
        "schema_version": _schema.SCHEMA_VERSION,
        "wall_span_s": (round(t_last - t_first, 3)
                        if t_first is not None else None),
        "fits": fits,
        "attempts": {"n": len(attempts), "classifications": classes,
                     "final": (final or {}).get("classification"),
                     "meshes": [a.get("mesh") for a in attempts
                                if a.get("mesh")]},
        "time_breakdown_ms": {
            "compile": round(stats_sum["compile_ms"], 3),
            "train_dispatch": round(stats_sum["train_time_ms"], 3),
            "val_dispatch": round(stats_sum["val_time_ms"], 3),
            "ckpt_stall": round(stats_sum["ckpt_stall_ms"], 3),
            "ckpt_barrier_stall": round(stats_sum["ckpt_barrier_stall_ms"],
                                        3),
            "prefetch_stall": round(stats_sum["prefetch_stall_ms"], 3),
            # the rows are NESTED measurements, not a partition:
            # ckpt_barrier_stall is contained in ckpt_stall (the async
            # submit barrier runs inside the save hand-off), and cold
            # compiles + prefetch stalls happen inside the train_dispatch
            # wall time — summing the rows double-counts
            "overlap_note": "nested, not disjoint: ckpt_barrier_stall "
                            "within ckpt_stall; compile and prefetch_stall "
                            "within train_dispatch",
        },
        "dispatches": {"train": int(stats_sum["train_dispatches"]),
                       "val": int(stats_sum["val_dispatches"]),
                       "epochs": int(stats_sum["epochs"])},
        "lane_epochs": {"total": int(stats_sum["lane_epochs"]),
                        "nominal": int(stats_sum["lane_epochs_nominal"]),
                        "saved_pct": saved,
                        "by_bucket": by_bucket},
        "compactions": compactions,
        "remeshes": remeshes,
        "policy_decisions": policy_decisions,
        "tenants": tenants,
        "fleet_containment": containment,
        "fleet_slo": fleet_slo,
        "fleet_autoscale": fleet_autoscale,
        "fleet_packing": fleet_packing,
        "serve": serve_section,
        "quality": quality_section,
        "memory": memory_section,
        "numerics": {"anomaly_events": anomalies,
                     "guarded_steps_skipped": int(skipped_steps),
                     "rollbacks": rollbacks, "aborts": aborts,
                     "quarantined_lanes": quarantined,
                     "failures": failures},
        "precision": precision_events,
        "autotune": autotune_events,
        "hang_incidents": hangs,
        "flight_records": sorted(
            os.path.basename(p) for p in
            glob.glob(os.path.join(run_dir, "flight_record*.json"))),
        "checkpoint_dispatch_stats": ck_stats,
        "cost_table": cost_table,
        "cost_model": {"accuracy": cm_rows,
                       "store": _cost_model_store_info(run_cache_dir)},
        "tpu_bench_cache": _tpu_cache_provenance(),
        "read_audit": {
            "metrics": mstats, "ledger": lstats,
            "schema_errors": [
                {"index": i, "errors": errs} for i, errs in schema_errors],
            "ledger_schema_errors": [
                {"index": i, "errors": errs} for i, errs in ledger_errors],
        },
    }


def _fmt_ms(ms):
    if ms is None:
        return "-"
    if ms >= 60_000:
        return f"{ms / 60_000:.1f}min"
    if ms >= 1_000:
        return f"{ms / 1_000:.2f}s"
    return f"{ms:.1f}ms"


def _fmt_bytes(b):
    if not isinstance(b, (int, float)):
        return "-"
    for unit, div in (("GB", 1 << 30), ("MB", 1 << 20), ("KB", 1 << 10)):
        if b >= div:
            return f"{b / div:.2f}{unit}"
    return f"{int(b)}B"


def _fmt_score(v):
    return f"{v:.3f}" if isinstance(v, (int, float)) else "n/a"


def _fmt_quality(q):
    """One-line per-request quality rendering (fleet results blocks):
    requests with no quality events show an explicit n/a."""
    if not isinstance(q, dict) or not q.get("windows"):
        return "quality n/a"
    conv = q.get("converged_at_epoch")
    stab = q.get("edge_stability") or []
    stab = [s for s in stab if isinstance(s, (int, float))]
    auc = q.get("auroc") or []
    auc = [a for a in auc if isinstance(a, (int, float))]
    mean = lambda xs: sum(xs) / len(xs) if xs else None
    return ("quality "
            + (f"converged@{conv}" if conv is not None else "not converged")
            + f", stability {_fmt_score(mean(stab))}"
            + f", auroc {_fmt_score(mean(auc))}"
            + f" ({q.get('windows')} window(s))")


def render_text(report):
    """Human-readable rendering of :func:`build_report` output."""
    r = report
    out = [f"run report: {r['run_dir']}",
           f"  schema v{r['schema_version']}; wall span "
           f"{_fmt_ms((r['wall_span_s'] or 0) * 1e3)}; "
           f"{len(r['fits'])} fit attempt(s)"]
    at = r["attempts"]
    if at["n"]:
        cls = ", ".join(f"{k}x{v}" for k, v in
                        sorted(at["classifications"].items()))
        out.append(f"  supervisor: {at['n']} attempt(s) [{cls}] -> "
                   f"{at['final'] or '?'}")
    tb = r["time_breakdown_ms"]
    out.append("time breakdown (nested measurements — do not sum: barrier "
               "within ckpt_stall; compile/prefetch within train_dispatch):")
    for k in ("compile", "train_dispatch", "val_dispatch", "ckpt_stall",
              "ckpt_barrier_stall", "prefetch_stall"):
        out.append(f"  {k:<20} {_fmt_ms(tb[k])}")
    d = r["dispatches"]
    le = r["lane_epochs"]
    out.append(f"dispatches: {d['train']} train / {d['val']} val over "
               f"{d['epochs']} epoch(s)")
    out.append(f"lane-epochs: {le['total']} of {le['nominal']} nominal"
               + (f" ({le['saved_pct']}% saved by compaction)"
                  if le["saved_pct"] is not None else "")
               + f"; by bucket {le['by_bucket']}")
    if r["compactions"]:
        out.append(f"compactions: " + "; ".join(
            f"epoch {c['epoch']}: {c['from_width']}->{c['to_width']}"
            for c in r["compactions"]))
    if r["remeshes"]:
        out.append(f"remeshes: " + "; ".join(
            f"epoch {c['epoch']}: {c['from_devices']}->{c['to_devices']} "
            f"devices" for c in r["remeshes"]))
    tn = r.get("tenants") or {}
    if tn:
        out.append("per-tenant (fleet manifest, redcliff_tpu/fleet):")
        for tenant, t in sorted(tn.items()):
            quar = (", ".join(f"{k}x{v}"
                              for k, v in sorted(t["quarantined"].items()))
                    or "none")
            out.append(f"  {tenant}: {t['requests']} request(s), "
                       f"{t['points']} point(s), {t['lane_epochs']} "
                       f"lane-epoch(s), wall {_fmt_ms((t['wall_s'] or 0) * 1e3)}, "
                       f"quarantined: {quar}")
        rq = (r.get("quality") or {}).get("requests") or {}
        if rq:
            for rid in sorted(rq):
                out.append(f"  request {rid}: {_fmt_quality(rq[rid])}")
    fc = r.get("fleet_containment")
    if fc:
        c = fc["counts"]
        out.append("fleet containment (docs/ARCHITECTURE.md 'Fleet failure "
                   "containment'):")
        out.append(f"  terminal states: {c['done']} done | {c['failed']} "
                   f"failed | {c['deadletter']} dead-lettered | "
                   f"{c['canceled']} canceled "
                   f"(of {c['submitted']} submitted)")
        if fc["events"]:
            out.append("  lifecycle events: " + "  ".join(
                f"{k}x{v}" for k, v in sorted(fc["events"].items())))
        for d in fc["deadletters"]:
            doss = d.get("dossier") or {}
            causes = doss.get("quarantine_causes")
            out.append(f"  dead-letter {d['request_id']} "
                       f"[{doss.get('tenant')}]: {doss.get('reason')} after "
                       f"{doss.get('attempts')} attempt(s), classifications "
                       f"{doss.get('classifications')}"
                       + (f", quarantine causes {causes}" if causes else ""))
            for fr in doss.get("flight_records") or []:
                out.append(f"    flight record: {fr}")
        budgets = [a for a in fc["attempt_records"]
                   if a.get("attempts") or a.get("reclaims")]
        if budgets:
            out.append("  attempt budgets: " + "  ".join(
                f"{a['request_id']}={a.get('attempts', 0)}f/"
                f"{a.get('reclaims', 0)}r" for a in budgets))
    slo = r.get("fleet_slo")
    if slo:
        out.append("fleet SLOs (lifecycle ledger history.jsonl, "
                   "obs/slo.py; docs/ARCHITECTURE.md 'Request lifecycle "
                   "tracing & SLOs'):")
        out.append(f"  {'scope':<14} {'req':>4} {'setl':>5} "
                   f"{'qwait p50/p99':>16} {'ttfa p50/p99':>15} "
                   f"{'deadline':>9} {'att/req':>8} {'dl%':>6}")

        def _s(v):
            return f"{v:.2f}s" if isinstance(v, (int, float)) else "-"

        def _pair(dist):
            d = dist or {}
            return f"{_s(d.get('p50'))}/{_s(d.get('p99'))}"

        for name, b in ([("overall", slo["overall"])]
                        + sorted(slo["tenants"].items())):
            dl = b.get("deadline") or {}
            hit = (f"{dl['hit_pct']:.0f}%" if dl.get("hit_pct") is not None
                   else "-")
            att = b.get("attempts_per_request")
            dlp = b.get("deadletter_pct")
            out.append(
                f"  {name:<14} {b['requests']:>4} {b['settled']:>5} "
                f"{_pair(b.get('queue_wait_s')):>16} "
                f"{_pair(b.get('ttfa_s')):>15} {hit:>9} "
                f"{(f'{att:.2f}' if att is not None else '-'):>8} "
                f"{(f'{dlp:.1f}' if dlp is not None else '-'):>6}")
        for br in slo.get("breaches") or []:
            out.append(f"  SLO BREACH [{br['scope']}] {br['slo']}: "
                       f"{br['value']:.3f} vs threshold "
                       f"{br['threshold']:.3f}")
    fa = r.get("fleet_autoscale")
    if fa:
        out.append("fleet autoscale (SLO-driven control loop, "
                   "fleet/autoscale.py; docs/ARCHITECTURE.md 'SLO-driven "
                   "autoscaling & degraded QoS'):")
        if fa.get("decisions"):
            out.append("  decisions: " + "  ".join(
                f"{k}={v}" for k, v in sorted(fa["decisions"].items())))
        ld = fa.get("last_decision")
        if ld:
            out.append(f"  last decision: {ld.get('kind')} "
                       f"({ld.get('reason')}), workers={ld.get('workers')} "
                       f"target={ld.get('target')}")
        st_ = fa.get("state") or {}
        if st_:
            out.append(f"  published state: {st_.get('workers')}/"
                       f"{st_.get('max_workers')} worker(s), pending "
                       f"{st_.get('pending')}, drain eta "
                       f"{st_.get('drain_eta_s')}s")
        for tenant, q_ in sorted((fa.get("qos") or {}).items()):
            out.append(f"  qos tenant {tenant}: rung {q_.get('rung')} "
                       f"({q_.get('reason')})")
        bp = fa.get("backpressure") or {}
        if bp.get("rejects"):
            last = bp.get("last") or {}
            out.append(f"  backpressure: {bp['rejects']} reject(s)"
                       + (f", last [{last.get('tenant')}] eta "
                          f"{last.get('eta_s')}s vs slo "
                          f"{last.get('threshold_s')}s" if last else ""))
    fp = r.get("fleet_packing")
    if fp:
        out.append("fleet packing (spatial multi-tenant mesh packing, "
                   "parallel/packing.py; docs/ARCHITECTURE.md 'Spatial "
                   "mesh packing & gang scheduling'):")
        if fp.get("events"):
            out.append("  events: " + "  ".join(
                f"{k}={v}" for k, v in sorted(fp["events"].items())))
        lp_ = fp.get("last_plan")
        if lp_:
            ratio = lp_.get("makespan_ratio")
            out.append(
                f"  last plan: {lp_.get('decision')} ({lp_.get('reason')})"
                + (f", makespan ratio {ratio:.3f}"
                   if isinstance(ratio, (int, float)) else "")
                + f", pool {lp_.get('pool')}/{lp_.get('n_devices')} "
                  f"device(s), headroom violations "
                  f"{lp_.get('headroom_violations', 0)}")
        pr = fp.get("partial_results") or {}
        if pr.get("streamed"):
            out.append(f"  partial results: {pr['streamed']} row(s) "
                       f"streamed, {pr['final']} final")
    sv = r.get("serve")
    if sv:
        out.append("serve (streaming inference service, "
                   "redcliff_tpu/serve; docs/ARCHITECTURE.md 'Streaming "
                   "inference service'):")
        ss = sv.get("slo") or {}
        lat = ss.get("latency") or {}

        def _ms(v):
            return f"{v:.2f}ms" if isinstance(v, (int, float)) else "-"

        out.append(
            f"  {ss.get('samples_out') or 0}/{ss.get('samples_in') or 0} "
            f"samples answered over {ss.get('streams') or 0} stream(s); "
            f"lat p50/p99 {_ms(lat.get('p50_ms'))}/{_ms(lat.get('p99_ms'))}"
            f" (n={lat.get('n') or 0})"
            + (f"; {ss['rejects']} admission reject(s)"
               if ss.get("rejects") else "")
            + (f"; {ss['dropped']} slow-consumer drop(s)"
               if ss.get("dropped") else ""))
        if sv.get("sessions"):
            out.append("  sessions: " + "  ".join(
                f"{k}={v}" for k, v in sorted(sv["sessions"].items())))
        if sv.get("qos_demotes"):
            out.append(f"  qos: {sv['qos_demotes']} cadence demotion(s)")
        occ = sv.get("occupancy")
        if occ:
            out.append(
                f"  occupancy [ladder={occ.get('ladder_mode') or '?'}]: "
                f"mean rung {occ.get('mean_rung')}/"
                f"{occ.get('capacity')} slot(s) "
                f"(mean live {occ.get('mean_live')}), dead-lane FLOPs "
                f"saved {occ.get('dead_lane_flops_saved_pct')}%"
                + (", decisions " + " ".join(
                    f"{k}={v}"
                    for k, v in sorted(occ["decisions"].items()))
                   if occ.get("decisions") else ""))
            hist = occ.get("rung_history") or []
            if hist:
                ride = " -> ".join(str(h["to"]) for h in hist)
                out.append(f"    rung ride: {hist[0].get('from')} -> {ride}"
                           f" ({len(hist)} transition(s))")
            if occ.get("fuse_depth_hist"):
                out.append(
                    f"    fuse depths: " + "  ".join(
                        f"{k}x{v}" for k, v in sorted(
                            occ["fuse_depth_hist"].items(),
                            key=lambda kv: int(kv[0])))
                    + f" ({occ.get('fused_samples', 0)} fused sample(s))")
            for d in occ.get("demotions") or []:
                out.append(
                    f"    PRECISION DEMOTION [{d.get('kind')}] "
                    f"{d.get('cause')}"
                    + (f" ({d['lanes_poisoned']} lane(s) poisoned)"
                       if d.get("lanes_poisoned") is not None else ""))
        for br in ss.get("breaches") or []:
            out.append(f"  SLO BREACH [{br['scope']}] {br['slo']}: "
                       f"{br['value']:.3f} vs threshold "
                       f"{br['threshold']:.3f}")
    qf = (r.get("quality") or {}).get("fits") or []
    if qf:
        out.append("model quality (live Granger-graph readouts, "
                   "obs/quality.py):")
        for q in qf:
            conv = (f"converged@{q['converged_at_epoch']}"
                    if q.get("converged_at_epoch") is not None
                    else f"{q.get('plateaued_count') or 0} plateaued")
            out.append(
                f"  fit {q['fit']} {q.get('model')}: "
                f"{q.get('windows') or 0} window(s), "
                f"lanes={q.get('lanes') if q.get('lanes') is not None else '-'}, "
                f"{conv}, stability {_fmt_score(q.get('final_stability'))}, "
                f"auroc {_fmt_score(q.get('final_auroc'))}, "
                f"aupr {_fmt_score(q.get('final_aupr'))}")
    mem = r.get("memory") or {}
    out.append("device memory (predicted vs measured peak, obs/memory.py):")
    for m in mem.get("fits") or []:
        meas = (_fmt_bytes(m["measured_peak_bytes"])
                if m.get("measured_peak_bytes") is not None
                else f"n/a ({m.get('backend') or 'backend'})")
        err = (f", err {m['err_pct']:+.1f}%"
               if m.get("err_pct") is not None else "")
        out.append(f"  fit {m['fit']} {m.get('model')} "
                   f"bucket={m.get('g_bucket')}: predicted "
                   f"{_fmt_bytes(m.get('predicted_bytes'))}, measured peak "
                   f"{meas}{err} ({m.get('polls', 0)} poll(s))")
    if not mem.get("fits"):
        out.append("  (no memory events recorded)")
    profs = mem.get("profiles") or []
    arts = mem.get("profile_artifacts") or []
    if profs or arts:
        for p in profs:
            out.append(f"  profile [{p.get('spec')}] epochs "
                       f"{p.get('first_epoch')}-{p.get('last_epoch')}"
                       + (" (truncated)" if p.get("truncated") else "")
                       + f" -> {p.get('path')}")
        # compare by leaf name, not absolute path: the `profile` event
        # holds the WRITER's absolute path, which no longer matches after
        # the run dir is copied off-host for post-mortem analysis
        announced = {os.path.basename(os.path.normpath(p["path"]))
                     for p in profs if p.get("path")}
        for a in arts:
            if os.path.basename(os.path.normpath(a)) not in announced:
                out.append(f"  profile artifact (unannounced): {a}")
    else:
        out.append("  profiles: none (REDCLIFF_PROFILE=epoch:N / "
                   "profile_window to capture a bounded window)")
    n = r["numerics"]
    out.append(f"numerics: {n['anomaly_events']} anomaly event(s), "
               f"{n['guarded_steps_skipped']} guarded step(s) skipped, "
               f"{n['rollbacks']} rollback(s), {n['aborts']} abort(s), "
               f"{n['quarantined_lanes']} quarantined lane(s)")
    for p in r.get("precision") or []:
        out.append(f"  precision {p.get('kind')}: "
                   f"{p.get('mode_from')}->{p.get('mode_to')} at epoch "
                   f"{p.get('epoch')} ({p.get('cause') or 'resume'})")
    for a in r.get("autotune") or []:
        tile = a.get("tile") or {}
        out.append(f"  autotune {a.get('kind') or 'search'} "
                   f"{a.get('kernel')}[{a.get('shape')} g{a.get('g_bucket')}]"
                   f": tile={tile}"
                   + (f" search {a['search_ms']:.0f}ms"
                      if a.get("search_ms") else ""))
    if r["hang_incidents"]:
        out.append(f"hang/host-loss incidents: {len(r['hang_incidents'])} "
                   f"(flight records: {r['flight_records'] or 'none'})")
    out.append("cost table (per shape x G-bucket):")
    out.append(f"  {'g_bucket':>8} {'epochs':>7} {'mean_epoch':>11} "
               f"{'compile':>9} {'hits/miss':>10}  shape")
    for row in r["cost_table"]:
        # "~" marks sampled rows (check-window epoch events only — the fit
        # never wrote its dispatch_stats, so counts undercount)
        n = f"{row['epochs']}~" if row.get("sampled") else f"{row['epochs']}"
        out.append(
            f"  {row['g_bucket']:>8} {n:>7} "
            f"{_fmt_ms(row['mean_epoch_ms']):>11} "
            f"{_fmt_ms(row['compile_ms']):>9} "
            f"{row['cache_hits']:>4}/{row['cache_misses']:<5}  "
            f"{row['shape']}")
    if not r["cost_table"]:
        out.append("  (no timed epochs recorded)")
    cm = r.get("cost_model") or {}
    rows = cm.get("accuracy") or []
    out.append("cost model accuracy (prediction vs actual per shape x "
               "G-bucket, obs/costmodel.py):")
    if rows:
        out.append(f"  {'g_bucket':>8} {'samples':>8} {'mape_pct':>9} "
                   f"{'last_pred':>10} {'last_act':>9} {'eta':>8}  shape")
        for row in rows:
            out.append(
                f"  {row['g_bucket']:>8} {row['samples']:>8} "
                f"{row['mape_pct'] if row['mape_pct'] is not None else '-':>9} "
                f"{_fmt_ms(row['last_predicted_epoch_ms']):>10} "
                f"{_fmt_ms(row['last_actual_epoch_ms']):>9} "
                f"{_fmt_ms((row['last_eta_s'] or 0) * 1e3) if row['last_eta_s'] is not None else '-':>8}  "
                f"{row['shape']}")
    else:
        out.append("  (no cost_model residual events in this run)")
    st = cm.get("store") or {}
    if st.get("present"):
        stale = st.get("staleness_s")
        out.append(f"  store: {st['buckets']} bucket(s) over {st['runs']} "
                   f"fold(s), updated {_fmt_ms((stale or 0) * 1e3)} ago "
                   f"({st['path']})")
    elif st.get("configured"):
        out.append(f"  store: not written yet ({st['path']})")
    else:
        out.append("  store: no compile-cache dir configured "
                   "(REDCLIFF_COMPILE_CACHE / compile_cache_dir)")
    pd = r.get("policy_decisions")
    if pd:
        out.append(
            f"predictive policy decisions (parallel/policy.py, "
            f"REDCLIFF_PREDICTIVE): {pd['decisions']} decision(s), "
            f"{pd['fallbacks']} heuristic fallback(s), "
            f"{pd['preempt_signals']} preempt signal(s), "
            f"{pd['preempts']} preemption(s)")
        if pd.get("by_action"):
            out.append("  by action: " + "  ".join(
                f"{k}={v}" for k, v in pd["by_action"].items()))
        if pd.get("predicted_saving_ms") is not None:
            out.append(
                f"  executed compactions: predicted saving "
                f"{_fmt_ms(pd['predicted_saving_ms'])} vs realized "
                f"{_fmt_ms(pd['realized_saving_ms'])}")
        for row in pd.get("rows") or []:
            if row["kind"] == "compaction":
                body = (f"{row['from_width']}->{row['to_width']} "
                        f"pred {_fmt_ms(row['predicted_saving_ms'])}"
                        f" real {_fmt_ms(row['realized_saving_ms'])}"
                        f" ({row['epochs_remaining']} epochs left)")
            elif row["kind"] == "initial_width":
                body = (f"rung {row['chosen_width']} "
                        f"(heuristic {row['heuristic_width']})")
            else:
                body = row.get("beneficiary") or row.get("reason") or ""
            out.append(f"  {row['kind']}:{row['action']}"
                       + (" [fallback]" if row["fallback"] else "")
                       + (f" @e{row['epoch']}"
                          if row.get("epoch") is not None else "")
                       + f" {body}")
    tc = r.get("tpu_bench_cache")
    if tc:
        out.append(f"cached real-TPU evidence: {tc.get('value')} w/s on "
                   f"{tc.get('device')}, measured {tc.get('measured_at')} "
                   f"({tc.get('file')}; pallas prox max err "
                   f"{tc.get('pallas_prox_max_abs_err')})")
    audit = r["read_audit"]
    torn = (audit["metrics"].get("torn_lines", 0)
            + audit["ledger"].get("torn_lines", 0))
    nerr = len(audit["schema_errors"]) + len(audit["ledger_schema_errors"])
    out.append(f"read audit: {audit['metrics'].get('records', 0)} metric "
               f"record(s), {torn} torn line(s) skipped, "
               f"{nerr} schema violation(s)")
    for e in (audit["schema_errors"] + audit["ledger_schema_errors"])[:5]:
        out.append(f"  record {e['index']}: {'; '.join(e['errors'])}")
    return "\n".join(out)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m redcliff_tpu.obs",
        description="Performance-observatory tooling (docs/ARCHITECTURE.md "
                    "'Telemetry spine' / 'Performance observatory').")
    sub = ap.add_subparsers(dest="cmd", required=True)
    rp = sub.add_parser(
        "report", help="join metrics.jsonl + run_ledger.jsonl + checkpointed "
                       "dispatch_stats into a per-run summary, the "
                       "per-(shape, G-bucket) cost table, and the "
                       "cost-model accuracy view")
    rp.add_argument("run_dir", help="run directory (holds metrics.jsonl)")
    rp.add_argument("--json", action="store_true",
                    help="print the full report as one JSON object")
    rp.add_argument("-o", "--output", default=None,
                    help="also write the JSON report to this path")
    wp = sub.add_parser(
        "watch", help="live, rotation-chain-aware tail of a run dir: lanes, "
                      "G-bucket, epoch rate, stalls, numerics, heartbeat "
                      "ages, cost-model ETA (obs/watch.py)")
    wp.add_argument("run_dir", help="run directory (holds metrics.jsonl)")
    wp.add_argument("--once", action="store_true",
                    help="print one snapshot and exit")
    wp.add_argument("--json", action="store_true",
                    help="with --once: print the snapshot as one "
                         "schema-valid JSON object")
    wp.add_argument("--interval", type=float, default=2.0,
                    help="follow-mode refresh seconds (default 2)")
    tp = sub.add_parser(
        "trace", help="export the run's spans + engine events + ledger "
                      "attempts as Chrome trace-event JSON for Perfetto "
                      "(obs/trace_export.py)")
    tp.add_argument("run_dir", help="run directory (holds metrics.jsonl), "
                                    "or a fleet root with --fleet")
    tp.add_argument("-o", "--output", default=None,
                    help="write the trace JSON here (default: stdout)")
    tp.add_argument("--fleet", action="store_true",
                    help="treat run_dir as a fleet root: join the "
                         "lifecycle ledger, worker metrics, and every "
                         "batch run dir into one timeline (per-request "
                         "tracks + queue counter tracks)")
    gp = sub.add_parser(
        "regress", help="compare the newest BENCH_r*.json against the prior "
                        "trajectory per metric family with noise bands "
                        "(obs/regress.py; exit 3 when a family regressed)")
    gp.add_argument("--bench-dir", default=None)
    gp.add_argument("--current", default=None)
    gp.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    if args.cmd == "report":
        from redcliff_tpu.obs.watch import diagnose_run_dir

        diag = diagnose_run_dir(args.run_dir)
        if diag is not None:
            print(f"obs report: {diag}", file=sys.stderr)
            return 2
        report = build_report(args.run_dir)
        if args.output:
            with open(args.output, "w") as f:
                json.dump(report, f, indent=2, allow_nan=False)
                f.write("\n")
        if args.json:
            json.dump(report, sys.stdout, indent=2, allow_nan=False)
            sys.stdout.write("\n")
        else:
            print(render_text(report))
        return 0
    if args.cmd == "watch":
        from redcliff_tpu.obs.watch import run_watch

        return run_watch(args.run_dir, once=args.once, as_json=args.json,
                         interval=args.interval)
    if args.cmd == "trace":
        from redcliff_tpu.obs.trace_export import main as trace_main

        targv = [args.run_dir]
        if args.output:
            targv += ["-o", args.output]
        if args.fleet:
            targv.append("--fleet")
        return trace_main(targv)
    if args.cmd == "regress":
        from redcliff_tpu.obs.regress import main as regress_main

        rargv = []
        if args.bench_dir:
            rargv += ["--bench-dir", args.bench_dir]
        if args.current:
            rargv += ["--current", args.current]
        if args.json:
            rargv.append("--json")
        return regress_main(rargv)
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
