"""``python -m redcliff_tpu.obs report <run_dir>`` — run-analytics CLI."""
import sys

from redcliff_tpu.obs.report import main

if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
