"""``python -m redcliff_tpu.obs {report,watch,trace,regress}`` — observatory CLIs."""
import sys

from redcliff_tpu.obs.report import main

if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
