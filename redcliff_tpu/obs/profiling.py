"""Scoped device-profile capture windows.

Before this module, profiling meant wrapping the WHOLE fit in one
``jax.profiler.trace`` (``profile_dir``): a week-long sweep produced a
multi-GB trace nobody could open, and the interesting epochs — steady
state, after compiles and cache priming — were buried under the cold
start. A *capture window* brackets exactly the epochs you asked for with
programmatic ``jax.profiler.start_trace`` / ``stop_trace``:

* ``TrainConfig.profile_window`` / env ``REDCLIFF_PROFILE`` take a spec —
  ``"epoch:3"`` captures epoch 3 only, ``"epoch:2-4"`` an inclusive range;
  unset/``off`` disables (the shared :data:`NOOP` window, one no-op method
  call per epoch boundary);
* the artifact is written under the run dir (``<run_dir>/profile`` by
  default, or the legacy ``profile_dir``) and announced by a
  schema-registered ``profile`` event so ``obs report`` can inventory it;
* ``profile_dir`` is kept as an alias: setting it WITHOUT a window spec now
  captures one bounded steady-state window (epoch 1, falling back to epoch
  0 on one-epoch fits) instead of the whole fit — long sweeps stop
  producing unbounded traces;
* a fit that ends inside an open window (early stop, exception,
  preemption) still closes the capture — the window is a context manager
  scoped around the fit, and ``__exit__`` stops any live trace and marks
  the event ``truncated``.

Cost discipline (same contract as the spans, pinned by the obs/schema.py
source tripwire): zero-cost when off — the epoch hooks on :data:`NOOP` do
nothing — and NEVER a host sync; ``start_trace``/``stop_trace`` run only at
the requested window's boundaries, so the decision stream is bit-identical
with profiling on or off. jax is imported lazily inside the start/stop
methods only.
"""
from __future__ import annotations

import os
import time

__all__ = ["ENV_PROFILE", "parse_window", "CaptureWindow", "NOOP",
           "window_for"]

ENV_PROFILE = "REDCLIFF_PROFILE"


def parse_window(spec):
    """Parse a capture-window spec into ``(first_epoch, last_epoch)`` or
    None (disabled). Accepted: ``"epoch:N"``, ``"epoch:N-M"`` (inclusive),
    and off-values (None/empty/``0``/``off``). Raises ValueError on
    malformed specs — a typo'd knob must fail loudly, not silently profile
    nothing."""
    if spec is None:
        return None
    spec = str(spec).strip().lower()
    if spec in ("", "0", "off", "false", "none"):
        return None
    kind, sep, rest = spec.partition(":")
    if kind != "epoch" or not sep:
        raise ValueError(
            f"unrecognized profile window spec {spec!r} (expected "
            f"'epoch:N' or 'epoch:N-M')")
    first, sep, last = rest.partition("-")
    try:
        a = int(first)
        b = int(last) if sep else a
    except ValueError:
        raise ValueError(f"non-integer epoch in profile window {spec!r}")
    if a < 0 or b < a:
        raise ValueError(f"invalid epoch range in profile window {spec!r}")
    return (a, b)


class _NoopWindow:
    """The shared disabled window: every hook is a no-op."""

    __slots__ = ()
    enabled = False

    def on_epoch_start(self, epoch):
        pass

    def on_epoch_end(self, epoch, logger=None):
        pass

    def finish(self, logger=None):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NOOP = _NoopWindow()


class CaptureWindow:
    """One bounded profiler capture: arms at ``first_epoch``'s start, stops
    at ``last_epoch``'s end (or at fit teardown, marked truncated). Engines
    call the two epoch hooks from their loop and scope the window as a
    context manager around the fit."""

    enabled = True

    def __init__(self, out_dir, first_epoch, last_epoch, spec=None):
        # absolute: the announcing `profile` event is read post-mortem from
        # other cwds/hosts, where a fit-cwd-relative path is meaningless
        self.out_dir = os.path.abspath(str(out_dir))
        self.first_epoch = int(first_epoch)
        self.last_epoch = int(last_epoch)
        self.spec = spec or f"epoch:{first_epoch}-{last_epoch}"
        self._active = False
        self._done = False
        self._t0 = None
        self._started_epoch = None
        self._last_seen_epoch = None
        self._logger = None

    def on_epoch_start(self, epoch):
        """Start the capture when ``epoch`` enters the window. Late resumes
        that land past ``first_epoch`` but inside the window still capture
        their remaining window epochs; a resume past the window never
        starts it."""
        if self._active or self._done:
            return
        if self.first_epoch <= epoch <= self.last_epoch:
            import jax

            os.makedirs(self.out_dir, exist_ok=True)
            jax.profiler.start_trace(self.out_dir)
            self._active = True
            self._started_epoch = epoch
            self._t0 = time.perf_counter()

    def on_epoch_end(self, epoch, logger=None):
        """Stop the capture when ``epoch`` closes the window; remembers the
        newest logger so a teardown stop can still announce the artifact."""
        if logger is not None:
            self._logger = logger
        if self._active:
            # track the newest epoch actually captured so a teardown stop
            # (fit died mid-window) announces the real captured range
            self._last_seen_epoch = epoch
            if epoch >= self.last_epoch:
                self._stop(last_epoch=epoch, logger=logger)

    def _stop(self, last_epoch, logger=None, truncated=False):
        import jax

        try:
            jax.profiler.stop_trace()
        except Exception:  # noqa: BLE001 — a double-stop must not kill a fit
            pass
        self._active = False
        self._done = True
        dur_ms = (time.perf_counter() - self._t0) * 1e3 \
            if self._t0 is not None else None
        logger = logger or self._logger
        if logger is not None and getattr(logger, "active", False):
            logger.log("profile", path=self.out_dir, spec=self.spec,
                       first_epoch=self._started_epoch,
                       last_epoch=last_epoch,
                       dur_ms=round(dur_ms, 3) if dur_ms is not None
                       else None,
                       truncated=truncated)

    def finish(self, logger=None):
        """Close an open capture early (truncated) — engines call this
        BEFORE closing their MetricLogger on non-loop exit paths
        (preemption, deadlines, early exit), so the announcing `profile`
        event still lands in metrics.jsonl; the context-manager __exit__
        then has nothing left to do."""
        if self._active:
            last = (self._last_seen_epoch
                    if self._last_seen_epoch is not None
                    else self._started_epoch)
            self._stop(last_epoch=last, logger=logger, truncated=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        # the fit ended inside the window without a finish() (an exception
        # escaping the loop): close the capture so the artifact is
        # readable; the event still lands if the logger is open
        self.finish()
        return False


def window_for(config, run_dir=None, max_iter=None):
    """Resolve the capture window for one fit from (in precedence order)
    ``config.profile_window``, the ``REDCLIFF_PROFILE`` env var, and the
    legacy ``config.profile_dir`` alias (one bounded steady-state window:
    epoch 1, or epoch 0 when the fit has a single epoch). An EXPLICIT off
    spec (``profile_window="off"`` / ``REDCLIFF_PROFILE=0``) disables
    profiling even when ``profile_dir`` is set — the operator's off switch
    beats a committed config's alias. Returns the shared :data:`NOOP` when
    profiling is off or no output location exists (neither ``profile_dir``
    nor a run dir)."""
    profile_dir = getattr(config, "profile_dir", None)
    spec = getattr(config, "profile_window", None)
    if spec is None:
        spec = os.environ.get(ENV_PROFILE)
    if spec is not None:
        win = parse_window(spec)
        if win is None:
            return NOOP  # explicit off — do not fall through to the alias
    else:
        win = None
    if win is None:
        if not profile_dir:
            return NOOP
        # profile_dir alias: one bounded window at the first steady-state
        # epoch (epoch 0 carries the cold compiles the window should skip)
        last = (max_iter - 1) if max_iter is not None else 1
        e = min(1, max(last, 0))
        win = (e, e)
        spec = f"epoch:{e}"
    out_dir = profile_dir or (os.path.join(run_dir, "profile")
                              if run_dir else None)
    if out_dir is None:
        return NOOP
    return CaptureWindow(out_dir, win[0], win[1], spec=str(spec))
