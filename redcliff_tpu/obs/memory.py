"""Analytical HBM footprint model + live device-memory watermark polling.

The device-memory half of the observatory (docs/ARCHITECTURE.md "Device
memory & profile observatory"). Two independent signals:

* **Predicted** — :func:`grid_footprint` / :func:`tree_bytes` compute the
  HBM bytes a fit will pin from ABSTRACT shapes only (``jax.eval_shape``
  over the model's init, dataset ``.nbytes`` metadata): per-lane parameter
  state, Adam moments, best/accepted copies, the device-resident dataset
  the epoch engine keeps in HBM, and the transient permuted epoch gather.
  No device work, no allocation — callable before the first dispatch and
  per (shape, G-bucket) rung of the ladder (:func:`footprint_by_bucket`),
  which is what ROADMAP item 1's admission planner packs against and what
  the bucket ladder consults before growing a width
  (:func:`check_headroom`). The per-shape memory features mirror what a
  learned TPU cost model consumes (arXiv:2008.01040): bytes, like
  milliseconds, are a per-(shape, G) property of the compiled program.

* **Measured** — :func:`poll_watermark` reads ``device.memory_stats()``
  (a host-side allocator API: no dispatch, no sync, no transfer). TPU and
  GPU backends report ``bytes_in_use`` / ``peak_bytes_in_use`` /
  ``bytes_limit``; this container's CPU backend returns ``None`` and every
  consumer degrades to an explicit ``n/a (backend)``. ``REDCLIFF_MEM_POLL=0``
  disables polling entirely (prediction is unaffected — it never touches a
  device).

The grid engine emits both as schema-registered ``memory`` events and
``dispatch_stats["memory"]`` fields; ``obs report`` renders predicted vs
measured peak per fit and ``obs trace`` exports the watermark as a Perfetto
counter track.

Import discipline: jax is imported LAZILY inside functions only (the
no-host-sync source tripwire in obs/schema.py checks this), and nothing
here may call ``block_until_ready`` — the memory axis must observe, never
serialize, the dispatch stream.
"""
from __future__ import annotations

import os

__all__ = ["ENV_MEM_POLL", "polling_enabled", "tree_bytes", "param_bytes",
           "grid_footprint", "trainer_footprint", "footprint_by_bucket",
           "device_memory_stats", "poll_watermark", "check_headroom"]

ENV_MEM_POLL = "REDCLIFF_MEM_POLL"


def polling_enabled():
    """Whether live watermark polling is armed (default on; the poll is a
    host allocator read, so the default costs nothing on backends without
    ``memory_stats`` support)."""
    return os.environ.get(ENV_MEM_POLL, "1").strip().lower() not in (
        "0", "off", "false")


# ---------------------------------------------------------------------------
# analytical footprint (abstract shapes only — no device work)
# ---------------------------------------------------------------------------
def _leaf_bytes(leaf):
    """Bytes of one array-like leaf from its shape/dtype METADATA
    (ShapeDtypeStruct, jax array, numpy array); 0 for non-array leaves
    (ints, None, hyperparam scalars inside optimizer states)."""
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None or dtype is None:
        return 0
    import numpy as np

    n = 1
    for d in shape:
        n *= int(d)
    return n * int(np.dtype(dtype).itemsize)


def tree_bytes(tree):
    """Total bytes of every array leaf in a pytree, from metadata only."""
    import jax

    return sum(_leaf_bytes(l) for l in jax.tree.leaves(tree))


def param_bytes(model):
    """Per-point parameter bytes of ``model`` by optimizer group, computed
    abstractly: ``jax.eval_shape`` traces ``model.init`` without running a
    single device op. Returns ``{"embedder", "factors", "other", "total"}``
    (groups the model does not define are 0)."""
    import jax
    import numpy as np

    key = jax.ShapeDtypeStruct((2,), np.uint32)
    try:
        shapes = jax.eval_shape(model.init, key)
    except Exception:
        # models whose init consumes the key concretely (e.g. host-side
        # numpy seeding) cannot be abstractly traced AT ALL — eval_shape
        # abstracts a concrete key too, so the only fallback is a real
        # throwaway init (one allocation, host-cheap at these model sizes)
        shapes = model.init(jax.random.PRNGKey(0))
    out = {"embedder": 0, "factors": 0, "other": 0}
    if isinstance(shapes, dict):
        for group, sub in shapes.items():
            g = group if group in ("embedder", "factors") else "other"
            out[g] += tree_bytes(sub)
    else:
        out["other"] = tree_bytes(shapes)
    out["total"] = out["embedder"] + out["factors"] + out["other"]
    return out


def grid_footprint(model, train_config, g_exec, train_ds=None, val_ds=None,
                   stream_mode=None, freeze=False):
    """Predicted HBM bytes of one grid fit at execution width ``g_exec``.

    The terms mirror what the engine actually pins (parallel/grid.py):

    * ``params_bytes`` — the live (G, ...) parameter grid;
    * ``opt_bytes`` — Adam first+second moments per group (2x params);
    * ``best_bytes`` — the best-criteria parameter copy (+ the Freeze-mode
      ``accepted`` tree when ``freeze``);
    * ``dataset_bytes`` — train+val arrays the epoch engine keeps
      device-resident (0 for host-streamed modes);
    * ``epoch_gather_bytes`` — the transient permuted epoch copy the
      one-dispatch epoch scan gathers before scanning (bounded by the
      dataset size; 0 off the epoch path).

    ``per_lane_bytes`` is the lane-proportional slope (params + opt + best
    [+ accepted]); ``total_bytes = per_lane_bytes * g_exec + fixed``. All
    arithmetic is host-side on shape metadata. ``stream_mode`` defaults to
    ``train_config.stream_mode``; ``freeze`` to whether the model config's
    training mode runs the accept/revert choreography."""
    from redcliff_tpu.data import pipeline

    if stream_mode is None and train_config is not None:
        stream_mode = getattr(train_config, "stream_mode", None)
    if not freeze:
        mode = getattr(getattr(model, "config", None), "training_mode", "")
        freeze = "Freeze" in str(mode)
    pb = param_bytes(model)
    per_point = pb["total"]
    # Adam (scale_by_adam / optax.adam): mu + nu mirror each optimized group
    opt_per_point = 2 * (pb["embedder"] + pb["factors"])
    if opt_per_point == 0:
        opt_per_point = 2 * per_point  # single-group models optimize it all
    copies = 2 + (1 if freeze else 0)  # live + best (+ accepted)
    per_lane = per_point * copies + opt_per_point
    train_bytes = pipeline.dataset_device_bytes(train_ds) or 0
    val_bytes = pipeline.dataset_device_bytes(val_ds) or 0
    on_epoch_path = stream_mode in (None, "auto", "epoch")
    # device-batch-capable datasets stay HBM-resident on the per-batch and
    # kscan paths too (the engine gathers batches from the device copy);
    # only the epoch scan additionally pays the transient permuted copy
    resident = on_epoch_path or bool(
        getattr(train_ds, "supports_device_batches", False))
    dataset_bytes = (train_bytes + val_bytes) if resident else 0
    gather_bytes = train_bytes if on_epoch_path else 0
    g_exec = int(g_exec)
    return {
        "g_bucket": g_exec,
        "params_bytes": per_point * g_exec,
        "opt_bytes": opt_per_point * g_exec,
        "best_bytes": per_point * (copies - 1) * g_exec,
        "per_lane_bytes": per_lane,
        "dataset_bytes": dataset_bytes,
        "epoch_gather_bytes": gather_bytes,
        "total_bytes": per_lane * g_exec + dataset_bytes + gather_bytes,
    }


def trainer_footprint(params, opt_states=(), extra_copies=2,
                      train_ds=None, val_ds=None):
    """Predicted HBM bytes of one per-point trainer fit, from the CONCRETE
    parameter tree's metadata (shape/dtype reads only — no transfer):
    live params + ``extra_copies`` full copies (best / accepted / divergence
    snapshot) + the given optimizer states + the device-batch dataset
    cache."""
    from redcliff_tpu.data import pipeline

    pb = tree_bytes(params)
    opt = sum(tree_bytes(s) for s in opt_states)
    ds_bytes = ((pipeline.dataset_device_bytes(train_ds) or 0)
                + (pipeline.dataset_device_bytes(val_ds) or 0))
    return {
        "params_bytes": pb * (1 + int(extra_copies)),
        "opt_bytes": opt,
        "dataset_bytes": ds_bytes,
        "total_bytes": pb * (1 + int(extra_copies)) + opt + ds_bytes,
    }


def footprint_by_bucket(model, train_config, g_real, n_devices=1,
                        max_width=None, train_ds=None, val_ds=None,
                        stream_mode=None, freeze=False):
    """Predicted footprint per bucket-ladder rung from the width ``g_real``
    requires up to ``max_width`` (default: 4 rungs) — the admission
    planner's packing input: how much HBM each candidate G-bucket pins for
    this shape. Returns ``[{..., "g_bucket", "total_bytes"}, ...]``."""
    from redcliff_tpu.parallel import compaction

    return [grid_footprint(model, train_config, w, train_ds=train_ds,
                           val_ds=val_ds, stream_mode=stream_mode,
                           freeze=freeze)
            for w in compaction.ladder_widths(g_real, n_devices,
                                              max_width=max_width)]


# ---------------------------------------------------------------------------
# live watermark (host allocator API — None where unsupported)
# ---------------------------------------------------------------------------
def device_memory_stats(device=None):
    """``device.memory_stats()`` as a plain dict, or None where the backend
    does not report (this container's CPU). Host-side allocator metadata —
    never a dispatch or a sync."""
    if device is None:
        import jax

        devs = jax.local_devices()
        if not devs:
            return None
        device = devs[0]
    try:
        stats = device.memory_stats()
    except Exception:  # noqa: BLE001 — backends without the API raise
        return None
    return dict(stats) if stats else None


def poll_watermark(devices=None):
    """Aggregate live/peak HBM across ``devices`` (default: all local
    devices): ``{"bytes_in_use", "peak_bytes", "bytes_limit", "n_devices",
    "device_kind"}`` — per-device MAX for use/peak (the binding constraint
    on a replicated grid), min for the limit. None when no device reports
    (CPU backend)."""
    if devices is None:
        import jax

        devices = jax.local_devices()
    in_use = peak = limit = None
    n = 0
    kind = None
    for d in devices:
        stats = device_memory_stats(d)
        if not stats:
            continue
        n += 1
        kind = getattr(d, "device_kind", None)
        u = stats.get("bytes_in_use")
        p = stats.get("peak_bytes_in_use", u)
        li = stats.get("bytes_limit")
        if u is not None:
            in_use = u if in_use is None else max(in_use, u)
        if p is not None:
            peak = p if peak is None else max(peak, p)
        if li is not None:
            limit = li if limit is None else min(limit, li)
    if n == 0:
        return None
    return {"bytes_in_use": in_use, "peak_bytes": peak,
            "bytes_limit": limit, "n_devices": n, "device_kind": kind}


def check_headroom(predicted_bytes, devices=None, n_devices=None):
    """Does ``predicted_bytes`` fit the visible devices' HBM? The headroom
    signal the bucket ladder consults before growing a width and the
    admission planner will consume per request.

    Returns ``{"fits", "bytes_limit", "budget_bytes", "headroom_bytes",
    "backend"}``. ``bytes_limit`` is always the PER-DEVICE limit — the same
    unit every watermark poll reports — while ``budget_bytes`` is the
    aggregate the verdict is judged against: ``n_devices * bytes_limit``
    for a grid whose lane axis shards over the mesh. ``fits`` is None (with
    both limits None) when the backend does not report memory stats —
    callers degrade to an explicit ``n/a (backend)``, never a guess."""
    import jax

    wm = poll_watermark(devices)
    backend = jax.default_backend()
    if wm is None or wm.get("bytes_limit") is None:
        return {"fits": None, "bytes_limit": None, "budget_bytes": None,
                "headroom_bytes": None, "backend": backend}
    scale = int(n_devices or wm["n_devices"] or 1)
    budget = wm["bytes_limit"] * scale
    return {"fits": bool(predicted_bytes <= budget),
            "bytes_limit": wm["bytes_limit"],
            "budget_bytes": budget,
            "headroom_bytes": int(budget - predicted_bytes),
            "backend": backend}
