"""Model-quality observatory: live Granger-graph readouts during training.

REDCLIFF-S's deliverable is not a loss curve — it is the per-state
Granger-causal graphs read out of each factor's first-layer weights
(PAPER.md §3). Everything else in the observatory watches the RUNTIME
(spans, cost, memory, SLOs); this module watches the SCIENCE: at every
check-window boundary the engines compute a cheap jit'd per-lane **graph
summary** on device and this module turns the gathered numbers into
convergence diagnostics and (when ground truth is in hand) live
AUROC/AUPR against the true graphs.

Two halves:

* **Device summary** (:func:`make_summary_fn`) — a pure jit-able function
  ``(params, X) -> dict of small arrays``: the per-factor lag-summed GC
  matrices (the same readout :mod:`redcliff_tpu.eval.gc_estimates`
  computes offline — the golden-parity contract below), their per-factor
  column norms, total edge energy, the sparsity fraction of the combined
  graph, its top-k edge indices (``lax.top_k`` magnitude order), and the
  factor-score entropy of the embedder weightings on a fixed validation
  window. The grid engine vmaps this over the lane axis and calls it
  INSIDE the existing check-window device->host transfer — no new host
  syncs, no effect on any update stream (the summary only reads params).

* **Host diagnostics** (:class:`QualityMonitor`) — per-ORIGINAL-point-id
  state across check windows (compaction-safe): top-k edge-set Jaccard
  stability vs the previous window, edge-energy plateau detection with a
  ``plateaued_at_epoch`` readout (ROADMAP item 3's missing input for
  predictive scheduling), a stable hash of the top-k edge SET, and —
  when ``true_gc`` is supplied (synthetic sVAR, DREAM4) — per-lane
  AUROC/AUPR on the :func:`~redcliff_tpu.eval.gc_estimates
  .get_model_gc_summary_matrices` readout convention. Each window lands
  as one schema-registered ``quality`` event and the rolling snapshot
  rides ``dispatch_stats["quality"]`` into every checkpoint.

Golden-parity contract (tests/test_quality.py): the live summary's
per-factor column norms match the offline
``eval/gc_estimates.get_model_gc_summary_matrices`` readout within 1e-6
and the top-k edge sets are identical — the live signal is trustworthy as
science, not merely as telemetry.

Readout mode: conditional ``primary_gc_est_mode`` values are forced to
``fixed_factor_exclusive`` exactly like the system-level eval path
(eval/gc_estimates.py get_model_gc_estimates), so the summary is a pure
function of params and never depends on which batch happened to be in
flight; ``raw_embedder`` (non-square map) is forced the same way.

Zero-cost contract: ``REDCLIFF_QUALITY=0`` disables everything — no jit'd
summary is built, no per-window work runs, and decision streams/params are
bit-identical either way (pinned by test_quality.py). Knobs:

* ``REDCLIFF_QUALITY`` — 1 (default) on / 0 off;
* ``REDCLIFF_QUALITY_TOPK`` — top-k edge-set size (default 8);
* ``REDCLIFF_QUALITY_PLATEAU_WINDOW`` — consecutive flat check windows
  before a lane counts as plateaued (default 3);
* ``REDCLIFF_QUALITY_PLATEAU_TOL`` — relative edge-energy change below
  which a window counts as flat (default 0.01).

Import discipline: jax only inside function bodies (the LAZY_JAX no-host-
sync tripwire in obs/schema.py covers this module); ``block_until_ready``
is banned — the summary must ride the existing check-window sync, never
add one.
"""
from __future__ import annotations

import hashlib
import os

import numpy as np

from redcliff_tpu.utils.metrics import roc_auc

__all__ = ["enabled", "topk_k", "plateau_window", "plateau_tol",
           "readout_mode", "make_summary_fn", "summarize_host",
           "topk_indices_np", "topk_hash", "jaccard", "average_precision",
           "graph_scores", "QualityMonitor",
           "SPARSITY_REL_EPS", "ENV_ENABLE", "ENV_TOPK",
           "ENV_PLATEAU_WINDOW", "ENV_PLATEAU_TOL"]

ENV_ENABLE = "REDCLIFF_QUALITY"
ENV_TOPK = "REDCLIFF_QUALITY_TOPK"
ENV_PLATEAU_WINDOW = "REDCLIFF_QUALITY_PLATEAU_WINDOW"
ENV_PLATEAU_TOL = "REDCLIFF_QUALITY_PLATEAU_TOL"

# combined-graph entries at or below this fraction of the max |edge| count
# as "off" for the sparsity fraction (a relative threshold: GC magnitudes
# are scale-free across models/coefficients)
SPARSITY_REL_EPS = 1e-2


def enabled():
    """Whether the quality observatory is on (``REDCLIFF_QUALITY``,
    default on). Read per fit, so tests/tools can flip it per run."""
    return os.environ.get(ENV_ENABLE, "1") != "0"


def topk_k(default=8):
    try:
        return max(int(os.environ.get(ENV_TOPK, default)), 1)
    except ValueError:
        return default


def plateau_window(default=3):
    try:
        return max(int(os.environ.get(ENV_PLATEAU_WINDOW, default)), 1)
    except ValueError:
        return default


def plateau_tol(default=0.01):
    try:
        return float(os.environ.get(ENV_PLATEAU_TOL, default))
    except ValueError:
        return default


def readout_mode(config):
    """The GC readout mode the summary uses: the model's primary mode with
    conditional (X-dependent) and raw-embedder (non-square) modes forced to
    ``fixed_factor_exclusive`` — the same override the system-level eval
    applies (eval/gc_estimates.py), so live and offline readouts agree."""
    mode = config.primary_gc_est_mode
    if "conditional" in mode or mode == "raw_embedder":
        return "fixed_factor_exclusive"
    return mode


def make_summary_fn(model, k=None):
    """Build the device graph-summary function for a REDCLIFF-family model.

    Returns ``summary(params, X) -> dict`` of small device arrays (one
    lane; the grid engine vmaps it over the stacked lane axis):

    * ``gc`` — per-factor LAG-SUMMED GC matrices ``(K, C, C)``, float32:
      byte-compatible with the offline
      ``eval/gc_estimates.get_model_gc_summary_matrices`` readout;
    * ``col_norms`` — per-factor column L2 norms ``(K, C)``;
    * ``edge_energy`` — ``sum(gc**2)`` scalar;
    * ``sparsity`` — fraction of combined-graph entries with magnitude
      <= :data:`SPARSITY_REL_EPS` x max magnitude;
    * ``topk_idx`` / ``topk_val`` — the k largest-|edge| flat indices of
      the combined (factor-summed) graph, ``lax.top_k`` order;
    * ``entropy`` — mean Shannon entropy (nats) of the normalized
      first-sim factor weightings on ``X`` (the factor-score sharpness).

    Pure read of ``params``: jit/vmap freely, never donates, never syncs.
    """
    import jax
    import jax.numpy as jnp

    cfg = model.config
    mode = readout_mode(cfg)
    kk = k if k is not None else topk_k()

    def summary(params, X):
        est = model.gc(params, mode, threshold=False, ignore_lag=False,
                       combine_wavelet_representations=True,
                       rank_wavelets=False)
        # fixed modes: (1, K', C, C, L') — fold the singleton sample axis
        E = jnp.sum(est.reshape((-1,) + est.shape[-3:]), axis=-1)  # (K,C,C)
        col_norms = jnp.linalg.norm(E, axis=-2)                    # (K, C)
        edge_energy = jnp.sum(E * E)
        A = jnp.sum(E, axis=0)                                     # (C, C)
        mag = jnp.abs(A)
        m = jnp.max(mag)
        thr = SPARSITY_REL_EPS * jnp.where(m > 0, m, 1.0)
        sparsity = jnp.mean((mag <= thr).astype(jnp.float32))
        k_eff = min(kk, mag.size)
        topk_val, topk_idx = jax.lax.top_k(mag.ravel(), k_eff)
        # factor-score entropy: the embedder's first-sim weightings on the
        # fixed window, rows normalized to distributions by |w| mass
        w = jnp.abs(model.forward(params, X)[2][0])                # (B, K)
        p = w / jnp.maximum(jnp.sum(w, axis=-1, keepdims=True), 1e-12)
        entropy = jnp.mean(-jnp.sum(p * jnp.log(p + 1e-12), axis=-1))
        return {"gc": E.astype(jnp.float32), "col_norms": col_norms,
                "edge_energy": edge_energy, "sparsity": sparsity,
                "topk_idx": topk_idx.astype(jnp.int32),
                "topk_val": topk_val, "entropy": entropy}

    return summary


# ---------------------------------------------------------------------------
# host-side twins (numpy): the generic trainer's readout path and the
# golden-parity test both consume these
# ---------------------------------------------------------------------------

def _lagsum(mat):
    mat = np.asarray(mat, dtype=np.float32)
    return mat.sum(axis=2) if mat.ndim == 3 else mat


def topk_indices_np(A, k):
    """Flat indices of the k largest-|entry| edges, replicating
    ``lax.top_k`` tie order (ties resolve to the smaller index)."""
    flat = np.abs(np.asarray(A)).ravel()
    order = np.argsort(-flat, kind="stable")
    return order[: min(k, flat.size)].astype(np.int64)


def summarize_host(mats, k=None):
    """Numpy twin of :func:`make_summary_fn` for models whose GC readout is
    host-side (the generic trainer's per-family ``model.gc`` lists).
    ``mats``: per-factor ``(C, C[, L])`` arrays. Returns the summary dict
    WITH a leading 1-lane axis (QualityMonitor's input convention);
    ``entropy`` is None (no factor scores on this path)."""
    E = np.stack([_lagsum(m) for m in mats])                     # (K, C, C)
    col_norms = np.linalg.norm(E, axis=-2)
    edge_energy = float(np.sum(E * E))
    A = E.sum(axis=0)
    mag = np.abs(A)
    m = float(mag.max()) if mag.size else 0.0
    thr = SPARSITY_REL_EPS * (m if m > 0 else 1.0)
    sparsity = float(np.mean(mag <= thr))
    idx = topk_indices_np(A, k if k is not None else topk_k())
    return {"gc": E[None], "col_norms": col_norms[None],
            "edge_energy": np.asarray([edge_energy], np.float32),
            "sparsity": np.asarray([sparsity], np.float32),
            "topk_idx": idx[None].astype(np.int32),
            "topk_val": mag.ravel()[idx][None],
            "entropy": None}


def topk_hash(indices):
    """Stable 12-hex-digit hash of a top-k edge SET (order-free: the set is
    sorted before hashing, so hash equality == identical edge sets)."""
    blob = ",".join(str(int(i)) for i in sorted(int(i) for i in indices))
    return hashlib.sha1(blob.encode("ascii")).hexdigest()[:12]


def jaccard(a, b):
    """Jaccard similarity of two edge-index collections (1.0 for two empty
    sets — a degenerate but stable graph is "stable")."""
    sa, sb = set(int(i) for i in a), set(int(i) for i in b)
    union = sa | sb
    if not union:
        return 1.0
    return len(sa & sb) / len(union)


def average_precision(labels, scores):
    """Area under the precision-recall curve (sklearn-style step AP, ties
    grouped). None when no positive labels exist."""
    labels = np.asarray(labels).ravel().astype(bool)
    scores = np.asarray(scores).ravel().astype(np.float64)
    n_pos = int(labels.sum())
    if n_pos == 0 or labels.size == 0:
        return None
    order = np.argsort(-scores, kind="mergesort")
    lab, sc = labels[order], scores[order]
    tp = np.cumsum(lab)
    fp = np.cumsum(~lab)
    prec = tp / (tp + fp)
    rec = tp / n_pos
    distinct = np.r_[sc[1:] != sc[:-1], True]
    prec, rec = prec[distinct], rec[distinct]
    return float(np.sum(np.diff(np.r_[0.0, rec]) * prec))


def _prep_like_tracker(mat):
    """The tracker's comparison prep (train/tracking.py _prep): lag-sum,
    max-normalize. Self-connections kept (remove_self=False convention)."""
    mat = np.asarray(mat, dtype=np.float64)
    if mat.ndim == 3:
        mat = mat.sum(axis=2)
    m = np.max(mat)
    return mat / m if m != 0.0 else mat


def graph_scores(true_gc, est_mats):
    """Mean per-factor (AUROC, AUPR) of lag-summed estimates against the
    true graphs — the live counterpart of the oracle metrics the offline
    eval computes on the ``eval/gc_estimates`` readout. ``est_mats``:
    ``(K, C, C)`` (already lag-summed); ``true_gc``: list of
    ``(C, C[, L])`` truths. Factor i scores against truth i (single-class
    truths contribute the 0.5 / base-rate convention like the tracker)."""
    est = np.asarray(est_mats, dtype=np.float64)
    n = min(est.shape[0], len(true_gc))
    if n == 0:
        return None, None
    aucs, aps = [], []
    for i in range(n):
        truth = _prep_like_tracker(true_gc[i])
        labels = (truth.ravel() > 0).astype(int)
        scores = _prep_like_tracker(est[i]).ravel()
        if labels.sum() == 0 or labels.sum() == labels.size:
            aucs.append(0.5)
            aps.append(float(labels.sum()) / labels.size)
            continue
        aucs.append(roc_auc(labels, scores))
        ap = average_precision(labels, scores)
        aps.append(ap if ap is not None else 0.0)
    return float(np.mean(aucs)), float(np.mean(aps))


# ---------------------------------------------------------------------------
# host-side convergence monitor
# ---------------------------------------------------------------------------

class QualityMonitor:
    """Per-lane convergence diagnostics across check windows.

    State is keyed by ORIGINAL point id (the ``orig_ids`` lane->point map),
    so diagnostics survive lane compaction unchanged. One
    :meth:`update` per check window consumes the gathered device summary
    and returns the ``quality`` event payload; :meth:`snapshot` is the
    rolling JSON-able view the grid engine stamps into
    ``dispatch_stats["quality"]`` (-> every checkpoint; the
    ``plateaued_at_epoch`` readout is ROADMAP item 3's plateau signal).

    Diagnostics are per-ATTEMPT: a resumed fit restarts the Jaccard /
    plateau history (the durable artifacts — quality events + the
    checkpointed snapshot — carry the prior attempt's story)."""

    def __init__(self, true_gc=None, window=None, tol=None, mode=None):
        self.true_gc = ([np.asarray(g) for g in true_gc]
                        if true_gc is not None and len(true_gc) else None)
        self.window = window if window is not None else plateau_window()
        self.tol = tol if tol is not None else plateau_tol()
        self.mode = mode
        self.windows = 0
        self.plateaued = {}       # pid -> epoch the plateau was confirmed
        self._energy = {}         # pid -> last edge energy
        self._flat = {}           # pid -> consecutive flat windows
        self._topk = {}           # pid -> previous top-k index set
        self._last = {}           # pid -> last per-lane record

    def update(self, epoch, host, orig_ids):
        """Fold one gathered check-window summary. ``host``: numpy arrays
        with a leading lane axis (``gc``/``col_norms``/``edge_energy``/
        ``sparsity``/``topk_idx``/``topk_val``/``entropy``; ``entropy``
        may be None); ``orig_ids``: lane -> original point id (< 0 =
        bucket filler, skipped). Returns the ``quality`` event payload."""
        ids = np.asarray(orig_ids).ravel()
        rows = [(r, int(p)) for r, p in enumerate(ids) if p >= 0]
        ent = host.get("entropy")
        lanes, energy, sparsity, entropy = [], [], [], []
        hashes, jacs, plats = [], [], []
        aurocs, auprs = [], []
        for r, pid in rows:
            e = float(np.asarray(host["edge_energy"]).ravel()[r])
            idx = np.asarray(host["topk_idx"])[r].ravel()
            cur = frozenset(int(i) for i in idx)
            prev = self._topk.get(pid)
            jac = jaccard(cur, prev) if prev is not None else None
            self._topk[pid] = cur
            prev_e = self._energy.get(pid)
            if prev_e is not None:
                rel = abs(e - prev_e) / max(abs(prev_e), 1e-12)
                self._flat[pid] = self._flat.get(pid, 0) + 1 \
                    if rel < self.tol else 0
                if (self._flat[pid] >= self.window
                        and pid not in self.plateaued):
                    self.plateaued[pid] = int(epoch)
            self._energy[pid] = e
            lanes.append(pid)
            energy.append(e)
            sparsity.append(float(np.asarray(host["sparsity"]).ravel()[r]))
            entropy.append(float(np.asarray(ent).ravel()[r])
                           if ent is not None else None)
            hashes.append(topk_hash(cur))
            jacs.append(jac)
            plats.append(self.plateaued.get(pid))
            if self.true_gc is not None:
                auc, ap = graph_scores(self.true_gc,
                                       np.asarray(host["gc"])[r])
                aurocs.append(auc)
                auprs.append(ap)
            self._last[pid] = {
                "edge_energy": e, "sparsity": sparsity[-1],
                "entropy": entropy[-1], "topk_hash": hashes[-1],
                "jaccard": jac,
                "auroc": aurocs[-1] if self.true_gc is not None else None,
                "aupr": auprs[-1] if self.true_gc is not None else None,
            }
        self.windows += 1
        known_j = [j for j in jacs if j is not None]
        known_a = [a for a in aurocs if a is not None]
        known_p = [a for a in auprs if a is not None]
        return {
            "epoch": int(epoch),
            "mode": self.mode,
            "lanes": lanes,
            "topk_k": int(np.asarray(host["topk_idx"]).shape[-1]),
            "edge_energy": energy,
            "sparsity": sparsity,
            "entropy": entropy,
            "topk_hash": hashes,
            "jaccard": jacs,
            "plateaued": plats,
            "auroc": aurocs if self.true_gc is not None else None,
            "aupr": auprs if self.true_gc is not None else None,
            "mean_jaccard": (float(np.mean(known_j)) if known_j else None),
            "mean_auroc": (float(np.mean(known_a)) if known_a else None),
            "mean_aupr": (float(np.mean(known_p)) if known_p else None),
            "plateaued_count": sum(p is not None for p in plats),
        }

    def snapshot(self):
        """Rolling JSON-able view (string point-id keys — the checkpoint /
        fit_end / fleet results consumers round-trip through JSON)."""
        pids = sorted(self._last)
        last = self._last
        has_gt = self.true_gc is not None
        jacs = [last[p]["jaccard"] for p in pids
                if last[p]["jaccard"] is not None]
        aucs = [last[p]["auroc"] for p in pids
                if last[p]["auroc"] is not None]
        aps = [last[p]["aupr"] for p in pids
               if last[p]["aupr"] is not None]
        return {
            "windows": self.windows,
            "mode": self.mode,
            "lanes": len(pids),
            "plateaued_count": len(self.plateaued),
            # per-fit convergence epoch: when the SLOWEST lane plateaued;
            # None while any lane is still moving (ROADMAP item 3 readout)
            "converged_at_epoch": (max(self.plateaued.values())
                                   if self.plateaued
                                   and len(self.plateaued) == len(pids)
                                   and pids else None),
            "plateaued_at_epoch": {str(p): self.plateaued.get(p)
                                   for p in pids},
            "edge_stability": {str(p): last[p]["jaccard"] for p in pids},
            "topk_hash": {str(p): last[p]["topk_hash"] for p in pids},
            "edge_energy": {str(p): last[p]["edge_energy"] for p in pids},
            "entropy": {str(p): last[p]["entropy"] for p in pids},
            "auroc": ({str(p): last[p]["auroc"] for p in pids}
                      if has_gt else None),
            "aupr": ({str(p): last[p]["aupr"] for p in pids}
                     if has_gt else None),
            "mean_edge_stability": (float(np.mean(jacs)) if jacs else None),
            "mean_auroc": (float(np.mean(aucs)) if aucs else None),
            "mean_aupr": (float(np.mean(aps)) if aps else None),
        }
