"""Fleet service-level objectives from the request-lifecycle ledger.

A multi-tenant queue lives or dies by numbers no single run dir holds:
how long tenants WAIT (queue-wait percentiles), how fast submitted work
first touches a device (time-to-first-attempt), whether deadlines are met
(hit-rate), how much retry churn each request costs (attempts-per-request),
and how often requests die in containment (dead-letter rate). This module
computes all of them from the durable lifecycle ledger
(``<root>/history.jsonl``, fleet/history.py) — which survives worker
restarts and SIGKILL storms — per tenant and fleet-wide, and flags
threshold breaches via the ``REDCLIFF_SLO_*`` knobs.

Definitions (docs/ARCHITECTURE.md "Request lifecycle tracing & SLOs"):

* **queue_wait_s** — first EFFECTIVE ``claimed`` wall time −
  ``submitted_at``: how long the request sat before a worker picked it up
  and actually did something with the claim. A claim rolled back by a
  lease ``released`` transition before any attempt (an all-or-nothing
  batch-claim rollback, a budget-route back to the queue) does not end
  the wait — the request is back in line and the tenant is still waiting;
  a claim that reaches an attempt or a settle locks the wait in, and
  reclaims after that do not reset it;
* **ttfa_s** — earliest ``attempt.started_at`` − ``submitted_at``: time to
  the first supervised run actually starting (claim + plan + spawn);
* **deadline hit-rate** — among SETTLED requests submitted with a
  ``deadline_s``: settled ``done`` with (settle wall − ``submitted_at``)
  <= deadline. A request that finished late, failed, or was dead-lettered
  counts as a miss; an unsettled request is not yet judged, and a
  ``canceled`` request is excluded from the denominator entirely — a
  voluntary tenant cancel is not a service miss;
* **attempts_per_request** — mean total supervisor attempts per request
  over requests with at least one recorded ``attempt`` transition;
* **deadletter rate** — settled ``deadletter`` over all settled, percent.

The default view is ALL-TIME (every request the ledger ever saw — what
``obs report`` archives). ``compute_slo(records, window_s=...)`` instead
restricts the population to requests with lifecycle activity inside the
trailing window (last event wall time within ``window_s`` of the ledger's
newest wall) — the view the fleet autoscaler (fleet/autoscale.py) reacts
to, so a breach absorbed an hour ago cannot keep the pool inflated. The
windowed output carries ``window.window_s``/``window.cutoff_wall``; the
all-time output is bit-identical to what it was before windowing existed.

Percentiles are **nearest-rank** (p-th percentile of n sorted values =
value at rank ``ceil(p/100 * n)``): exact on small populations — a ledger
with known synthetic timings yields exactly predictable p50/p99 (pinned by
tests/test_fleet_obs.py), no interpolation surprises.

Requeued dead-letters re-enter the live population: a ``requeued``
transition clears the settled state, and the request's eventual re-settle
is judged afresh. Racing settle writers (the queue's converging-settle
discipline) may leave two ``settled`` transitions for one request — the
winner is the queue's fixed priority order (done > failed > deadletter >
canceled), mirroring what actually survives on disk.

Thresholds (each unset by default = no breach checking for that SLO)::

    REDCLIFF_SLO_QUEUE_P99_S      max acceptable queue-wait p99, seconds
    REDCLIFF_SLO_TTFA_P99_S       max acceptable time-to-first-attempt p99
    REDCLIFF_SLO_DEADLINE_PCT     min acceptable deadline hit-rate, percent
    REDCLIFF_SLO_DEADLETTER_PCT   max acceptable dead-letter rate, percent

**Serve SLOs (ISSUE 17).** The streaming inference service has its own
latency objective: per-sample ingest->answer latency, judged on the same
nearest-rank percentiles from the cumulative reservoir the service's
``serve`` kind=tick/drain events carry (``p50_ms``/``p99_ms``/``n``).
:func:`compute_serve_slo` folds a run dir's serve events into one block and
flags breaches of::

    REDCLIFF_SLO_SERVE_P50_MS     max acceptable per-sample p50, milliseconds
    REDCLIFF_SLO_SERVE_P99_MS     max acceptable per-sample p99, milliseconds

stdlib only, no jax (obs/schema.py ``--check`` enforces it): SLO math runs
in observer processes that must never initialize a backend.
"""
from __future__ import annotations

import math
import os

__all__ = ["percentile", "compute_slo", "slo_for_root",
           "thresholds_from_env", "ENV_QUEUE_P99_S", "ENV_TTFA_P99_S",
           "ENV_DEADLINE_PCT", "ENV_DEADLETTER_PCT",
           "compute_serve_slo", "serve_thresholds_from_env",
           "ENV_SERVE_P50_MS", "ENV_SERVE_P99_MS"]

ENV_QUEUE_P99_S = "REDCLIFF_SLO_QUEUE_P99_S"
ENV_TTFA_P99_S = "REDCLIFF_SLO_TTFA_P99_S"
ENV_DEADLINE_PCT = "REDCLIFF_SLO_DEADLINE_PCT"
ENV_DEADLETTER_PCT = "REDCLIFF_SLO_DEADLETTER_PCT"
ENV_SERVE_P50_MS = "REDCLIFF_SLO_SERVE_P50_MS"
ENV_SERVE_P99_MS = "REDCLIFF_SLO_SERVE_P99_MS"

# the queue's converging-settle priority (fleet/queue.py TERMINAL_STATES):
# when racing writers recorded two settles, this is the one that survived
_STATE_PRIORITY = ("done", "failed", "deadletter", "canceled")


def percentile(values, p):
    """Nearest-rank percentile: the value at rank ``ceil(p/100 * n)`` of
    the sorted population (p in (0, 100]). Exact — never interpolates —
    so known synthetic timings yield exactly predictable results. None on
    an empty population."""
    if not values:
        return None
    ordered = sorted(values)
    rank = min(max(int(math.ceil(p / 100.0 * len(ordered))), 1),
               len(ordered))
    return ordered[rank - 1]


def _env_float(name):
    raw = os.environ.get(name)
    if raw is None or not str(raw).strip():
        return None
    try:
        return float(raw)
    except ValueError:
        return None


def thresholds_from_env():
    """The breach thresholds from the ``REDCLIFF_SLO_*`` env knobs (None =
    that SLO is not checked)."""
    return {
        "queue_p99_s": _env_float(ENV_QUEUE_P99_S),
        "ttfa_p99_s": _env_float(ENV_TTFA_P99_S),
        "deadline_hit_pct": _env_float(ENV_DEADLINE_PCT),
        "deadletter_pct": _env_float(ENV_DEADLETTER_PCT),
    }


def _wall(rec):
    wt = rec.get("wall_time")
    return wt if isinstance(wt, (int, float)) else None


def _requests_from_history(records):
    """Fold the lifecycle ledger into per-request summaries:
    ``{request_id: {tenant, submitted_at, deadline_s, first_claimed,
    first_attempt_start, attempts, settled_state, settled_at}}``."""
    reqs = {}
    ordered = sorted((r for r in records if r.get("kind")),
                     key=lambda r: (_wall(r) or 0.0, r.get("seq") or 0))
    for rec in ordered:
        kind = rec.get("kind")
        rid = rec.get("request_id")
        if rid is None:
            continue  # batch-scoped transitions (planned/bisected)
        r = reqs.setdefault(rid, {
            "request_id": rid, "tenant": None, "trace_id": None,
            "submitted_at": None, "deadline_s": None,
            "first_claimed": None, "first_attempt_start": None,
            "attempts": 0, "settled_state": None, "settled_at": None,
            "last_wall": None, "_pending_claim": None})
        wt_any = _wall(rec)
        if wt_any is not None and (r["last_wall"] is None
                                   or wt_any > r["last_wall"]):
            r["last_wall"] = wt_any
        if rec.get("tenant") is not None:
            r["tenant"] = str(rec["tenant"])
        if rec.get("trace_id") is not None and r["trace_id"] is None:
            r["trace_id"] = rec["trace_id"]
        if kind == "submitted":
            sub = rec.get("submitted_at")
            r["submitted_at"] = sub if isinstance(sub, (int, float)) \
                else _wall(rec)
            if rec.get("deadline_s") is not None:
                r["deadline_s"] = float(rec["deadline_s"])
        elif kind == "claimed":
            # provisional until the claim leads to an attempt or a settle:
            # a claim rolled back by a lease release never did any work,
            # so it must not end the tenant's queue wait
            wt = _wall(rec)
            if wt is not None and r["first_claimed"] is None \
                    and r["_pending_claim"] is None:
                r["_pending_claim"] = wt
        elif kind == "released":
            if r["first_claimed"] is None:
                r["_pending_claim"] = None
        elif kind == "attempt":
            if r["first_claimed"] is None \
                    and r["_pending_claim"] is not None:
                r["first_claimed"] = r["_pending_claim"]
            start = rec.get("started_at")
            start = start if isinstance(start, (int, float)) else _wall(rec)
            if start is not None and (r["first_attempt_start"] is None
                                      or start < r["first_attempt_start"]):
                r["first_attempt_start"] = start
            n = rec.get("attempts")
            r["attempts"] += int(n) if isinstance(n, int) and n > 0 else 1
        elif kind == "settled":
            if r["first_claimed"] is None \
                    and r["_pending_claim"] is not None:
                r["first_claimed"] = r["_pending_claim"]
            state = str(rec.get("state") or "?")
            prev = r["settled_state"]
            if prev is None or (state in _STATE_PRIORITY
                                and (prev not in _STATE_PRIORITY
                                     or _STATE_PRIORITY.index(state)
                                     < _STATE_PRIORITY.index(prev))):
                r["settled_state"] = state
                r["settled_at"] = _wall(rec)
        elif kind == "requeued":
            # back into the live population: the re-settle is judged fresh
            r["settled_state"] = None
            r["settled_at"] = None
    for r in reqs.values():
        # a claim still pending at ledger end is live right now (the
        # worker holds the lease mid-batch): it did end the queue wait
        if r["first_claimed"] is None and r["_pending_claim"] is not None:
            r["first_claimed"] = r["_pending_claim"]
        del r["_pending_claim"]
    return reqs


def _dist(values):
    if not values:
        return None
    return {"n": len(values),
            "p50": percentile(values, 50.0),
            "p99": percentile(values, 99.0),
            "mean": sum(values) / len(values),
            "max": max(values)}


def _block(reqs):
    """One SLO block (per tenant, or fleet-wide) from request summaries."""
    queue_waits, ttfas, attempt_counts = [], [], []
    states = {s: 0 for s in _STATE_PRIORITY}
    with_deadline = hits = 0
    for r in reqs:
        sub = r["submitted_at"]
        if sub is not None and r["first_claimed"] is not None:
            queue_waits.append(r["first_claimed"] - sub)
        if sub is not None and r["first_attempt_start"] is not None:
            ttfas.append(r["first_attempt_start"] - sub)
        if r["attempts"]:
            attempt_counts.append(r["attempts"])
        state = r["settled_state"]
        if state in states:
            states[state] += 1
            if r["deadline_s"] is not None and sub is not None \
                    and r["settled_at"] is not None \
                    and state != "canceled":
                with_deadline += 1
                if state == "done" \
                        and (r["settled_at"] - sub) <= r["deadline_s"]:
                    hits += 1
    settled = sum(states.values())
    return {
        "requests": len(reqs),
        "settled": settled,
        "states": states,
        "queue_wait_s": _dist(queue_waits),
        "ttfa_s": _dist(ttfas),
        "deadline": ({"with_deadline": with_deadline, "hits": hits,
                      "hit_pct": 100.0 * hits / with_deadline}
                     if with_deadline else None),
        "attempts_per_request": (sum(attempt_counts) / len(attempt_counts)
                                 if attempt_counts else None),
        "deadletter_pct": (100.0 * states["deadletter"] / settled
                           if settled else None),
    }


def _breaches_of(scope, block, thr):
    out = []

    def breach(slo, value, threshold, worse_above=True):
        if value is None or threshold is None:
            return
        if (value > threshold) if worse_above else (value < threshold):
            out.append({"scope": scope, "slo": slo, "value": value,
                        "threshold": threshold})

    qw, tt = block.get("queue_wait_s"), block.get("ttfa_s")
    breach("queue_p99_s", (qw or {}).get("p99"), thr.get("queue_p99_s"))
    breach("ttfa_p99_s", (tt or {}).get("p99"), thr.get("ttfa_p99_s"))
    breach("deadline_hit_pct", (block.get("deadline") or {}).get("hit_pct"),
           thr.get("deadline_hit_pct"), worse_above=False)
    breach("deadletter_pct", block.get("deadletter_pct"),
           thr.get("deadletter_pct"))
    return out


def compute_slo(records, thresholds=None, window_s=None):
    """Compute the fleet SLO view from lifecycle-ledger records
    (fleet/history.py). Returns ``{"requests", "settled", "overall",
    "tenants": {tenant: block}, "thresholds", "breaches", "window"}`` —
    strict-JSON-able; ``None`` sub-blocks mean no evidence yet, never
    zero. ``thresholds`` defaults to :func:`thresholds_from_env`.

    ``window_s`` restricts the population to requests with lifecycle
    activity in the trailing window (see module docstring); ``None`` — the
    default — is the all-time view, whose output is bit-identical to the
    pre-windowing era."""
    thr = dict(thresholds_from_env(), **(thresholds or {}))
    reqs = list(_requests_from_history(records).values())
    walls = [w for rec in records for w in (_wall(rec),) if w is not None]
    cutoff_wall = None
    if window_s is not None and walls:
        cutoff_wall = max(walls) - float(window_s)
        reqs = [r for r in reqs if r["last_wall"] is not None
                and r["last_wall"] >= cutoff_wall]
    by_tenant = {}
    for r in reqs:
        by_tenant.setdefault(r["tenant"] or "?", []).append(r)
    overall = _block(reqs)
    tenants = {t: _block(rs) for t, rs in sorted(by_tenant.items())}
    breaches = _breaches_of("overall", overall, thr)
    for t, block in tenants.items():
        breaches.extend(_breaches_of(t, block, thr))
    window = {"first_wall": min(walls) if walls else None,
              "last_wall": max(walls) if walls else None}
    if window_s is not None:
        window["window_s"] = float(window_s)
        window["cutoff_wall"] = cutoff_wall
    return {
        "requests": overall["requests"],
        "settled": overall["settled"],
        "overall": overall,
        "tenants": tenants,
        "thresholds": thr,
        "breaches": breaches,
        "window": window,
    }


def serve_thresholds_from_env():
    """Serve latency thresholds from ``REDCLIFF_SLO_SERVE_*`` (None = that
    SLO is not checked)."""
    return {
        "serve_p50_ms": _env_float(ENV_SERVE_P50_MS),
        "serve_p99_ms": _env_float(ENV_SERVE_P99_MS),
    }


def compute_serve_slo(records, thresholds=None):
    """Fold a metrics chain's ``serve`` events into the serve SLO block.

    The service emits CUMULATIVE latency percentiles (nearest-rank over its
    bounded reservoir) on every kind=tick/drain record, so the newest such
    record IS the run's current view — no re-derivation, byte-agreement
    with what the service itself computed. Returns ``{"latency":
    {"p50_ms", "p99_ms", "n"}, "streams", "rejects", "dropped",
    "samples_in", "samples_out", "width", "fused_samples", "mode",
    "thresholds", "breaches"}`` (the last three from the elastic data
    plane, ISSUE 20: newest dispatched rung width, cumulative fused-sample
    count, ladder mode), or None when the records carry no serve events
    at all.
    """
    thr = dict(serve_thresholds_from_env(), **(thresholds or {}))
    last_lat = None
    # counters are cumulative but scattered across kinds (drain carries no
    # rejects, stop no streams): keep the newest non-None value per field
    counts = {k: None for k in ("streams", "rejects", "dropped",
                                "samples_in", "samples_out", "width",
                                "fused_samples", "mode")}
    seen = False
    for rec in records:
        if rec.get("event") != "serve":
            continue
        seen = True
        for k in counts:
            if rec.get(k) is not None:
                counts[k] = rec[k]
        if rec.get("n") and rec.get("p99_ms") is not None:
            last_lat = rec
    if not seen:
        return None
    latency = None
    if last_lat is not None:
        latency = {"p50_ms": last_lat.get("p50_ms"),
                   "p99_ms": last_lat.get("p99_ms"),
                   "n": last_lat.get("n")}
    breaches = []
    if latency is not None:
        for slo, key in (("serve_p50_ms", "p50_ms"),
                         ("serve_p99_ms", "p99_ms")):
            value, limit = latency.get(key), thr.get(slo)
            if value is not None and limit is not None and value > limit:
                breaches.append({"scope": "serve", "slo": slo,
                                 "value": value, "threshold": limit})
    return dict(counts, latency=latency, thresholds=thr, breaches=breaches)


def slo_for_root(root, thresholds=None, stats=None, window_s=None):
    """The SLO view for a fleet root (reads ``<root>/history.jsonl``), or
    None when the root holds no lifecycle ledger yet."""
    from redcliff_tpu.fleet.history import read_history

    records = read_history(root, stats=stats)
    if not records:
        return None
    return compute_slo(records, thresholds=thresholds, window_s=window_s)
