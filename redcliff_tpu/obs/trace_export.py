"""Perfetto / Chrome trace-event export of a run directory's telemetry.

``python -m redcliff_tpu.obs trace <run_dir> [-o trace.json]`` joins the
span records, the structured engine events (``cost_model`` / ``memory`` /
``compile`` / ``compaction`` / ``remesh`` / numerics / deadline / hang),
and the supervisor's ledger attempts from ``metrics.jsonl`` +
``run_ledger.jsonl`` — rotation-chain- and torn-tail-aware via the spine's
:func:`~redcliff_tpu.obs.logging.read_jsonl` — into one Chrome
trace-event-format JSON object loadable in Perfetto (ui.perfetto.dev) or
``chrome://tracing``.

Mapping (the trace-event format's vocabulary):

* each writing ``(host, pid)`` becomes a trace *process* (``M`` metadata
  names it), each span component a *thread* within it — so a supervisor
  restart or a multi-host run renders as parallel process lanes;
* ``span`` records become complete (``ph="X"``) events with their measured
  ``dur_ms``, placed at the span's true START — ``Span`` stamps ``t_wall``
  at entry; ``record_span`` entries stamp it at record time (the end) and
  are backed off by their duration;
* supervisor ledger ``attempt`` records become ``X`` events on a synthetic
  ``supervisor`` process (``started_at`` + ``duration_s``);
* ``epoch`` events feed a ``lanes_live`` counter track and ``memory``
  events an ``hbm_bytes`` counter track (``ph="C"``) — the live-width and
  HBM-watermark curves next to the timeline;
* every other registered event lands as an instant (``ph="i"``) carrying
  its fields in ``args``.

stdlib + the spine's jsonl reader only — no jax, never a backend; the
export runs post-mortem on any machine holding the run dir.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

from redcliff_tpu.obs.logging import read_jsonl

__all__ = ["build_trace", "validate_trace", "write_trace", "main"]

# events never rendered as instants: spans get their own "X" events, and a
# record that already fed a counter sample this pass is not duplicated as
# an instant (epoch / measured-memory records would otherwise appear twice
# — once on the counter track, once on the timeline)
_INSTANT_SKIP = ("span",)

_COUNTER_NUMERIC = (int, float)


class _Ids:
    """Stable small-int ids for (host, pid) processes and their threads."""

    def __init__(self):
        self.pids = {}
        self.tids = {}
        self.meta = []

    def pid(self, host, pid):
        key = (host if host is not None else "?",
               pid if pid is not None else 0)
        if key not in self.pids:
            self.pids[key] = len(self.pids) + 1
            self.meta.append({"ph": "M", "name": "process_name",
                              "pid": self.pids[key], "tid": 0,
                              "args": {"name": f"{key[0]}:{key[1]}"}})
        return self.pids[key]

    def tid(self, pid, component):
        key = (pid, component or "events")
        if key not in self.tids:
            self.tids[key] = len([k for k in self.tids if k[0] == pid]) + 1
            self.meta.append({"ph": "M", "name": "thread_name",
                              "pid": pid, "tid": self.tids[key],
                              "args": {"name": key[1]}})
        return self.tids[key]


def _num(v):
    return v if isinstance(v, _COUNTER_NUMERIC) \
        and not isinstance(v, bool) else None


def _args_of(rec):
    """Event fields minus the identity/core plumbing, JSON-safe as-is
    (records come from strict-JSON metrics.jsonl)."""
    return {k: v for k, v in rec.items()
            if k not in ("event", "wall_time", "seq", "pid", "host")}


def _span_start(rec):
    """A span record's wall-clock START. ``Span`` stamps ``t_wall`` at
    __enter__ (wall_time - t_wall ≈ dur); ``record_span`` stamps it at
    record time, i.e. the END (wall_time - t_wall ≈ 0) — distinguish by
    which gap the duration better explains and back the end-stamped case
    off by its duration."""
    wall = _num(rec.get("wall_time"))
    t_wall = _num(rec.get("t_wall"))
    dur_s = (_num(rec.get("dur_ms")) or 0.0) / 1e3
    if t_wall is None:
        return (wall - dur_s) if wall is not None else None
    if wall is not None and (wall - t_wall) < 0.5 * dur_s:
        return t_wall - dur_s
    return t_wall


def build_trace(run_dir):
    """Export one run directory as a Chrome trace-event JSON dict:
    ``{"traceEvents": [...], "displayTimeUnit": "ms", "otherData": {...}}``.
    Timestamps are microseconds relative to the run's earliest record."""
    mstats, lstats = {}, {}
    try:
        records = read_jsonl(run_dir, stats=mstats)
    except FileNotFoundError:
        records, mstats = [], {"files": [], "records": 0, "torn_lines": 0}
    ledger_path = os.path.join(run_dir, "run_ledger.jsonl")
    ledger = (read_jsonl(ledger_path, stats=lstats)
              if os.path.exists(ledger_path) else [])

    walls = [r["wall_time"] for r in records
             if _num(r.get("wall_time")) is not None]
    # span STARTS bound the time base too (a long first span would
    # otherwise begin before t0 and get a negative timestamp)
    walls += [s for r in records if r.get("event") == "span"
              for s in (_span_start(r),) if s is not None]
    walls += [r["started_at"] for r in ledger
              if _num(r.get("started_at")) is not None]
    t0 = min(walls) if walls else 0.0
    ts = lambda wall: round((wall - t0) * 1e6, 1)

    ids = _Ids()
    events = []
    for rec in records:
        ev = rec.get("event")
        wall = _num(rec.get("wall_time"))
        if ev is None or wall is None:
            continue
        pid = ids.pid(rec.get("host"), rec.get("pid"))
        if ev == "span":
            name = rec.get("name") or "span"
            comp = str(name).partition(".")[0]
            dur = _num(rec.get("dur_ms")) or 0.0
            start = _span_start(rec)
            e = {"ph": "X", "name": name, "cat": "span",
                 "ts": ts(start if start is not None else wall),
                 "dur": round(dur * 1e3, 1),
                 "pid": pid, "tid": ids.tid(pid, comp)}
            args = {k: rec[k] for k in ("span_id", "parent_id")
                    if rec.get(k) is not None}
            args.update(rec.get("attrs") or {})
            if args:
                e["args"] = args
            events.append(e)
            continue
        tid = ids.tid(pid, ev.partition("_")[0] if ev.startswith("fit")
                      else "events")
        counted = False
        if ev == "epoch":
            lanes = _num(rec.get("lanes_live"))
            if lanes is None:
                lanes = _num(rec.get("num_active"))
            if lanes is not None:
                c = {"lanes_live": lanes}
                width = _num(rec.get("grid_width"))
                if width is not None:
                    c["grid_width"] = width
                events.append({"ph": "C", "name": "lanes_live",
                               "ts": ts(wall), "pid": pid,
                               "tid": ids.tid(pid, "counters"), "args": c})
                counted = True
        if ev == "memory":
            hbm = {k: v for k in ("bytes_in_use", "peak_bytes")
                   for v in (_num(rec.get(k)),) if v is not None}
            if hbm:
                events.append({"ph": "C", "name": "hbm_bytes",
                               "ts": ts(wall), "pid": pid,
                               "tid": ids.tid(pid, "counters"),
                               "args": hbm})
                counted = True
        if ev in _INSTANT_SKIP or counted:
            continue
        events.append({"ph": "i", "name": ev, "cat": ev, "s": "t",
                       "ts": ts(wall), "pid": pid, "tid": tid,
                       "args": _args_of(rec)})

    # supervisor ledger: attempts as spans on a synthetic process
    sup_pid = None
    for rec in ledger:
        if rec.get("event") != "attempt":
            continue
        start = _num(rec.get("started_at"))
        if start is None:
            continue
        if sup_pid is None:
            sup_pid = ids.pid("supervisor", 0)
        dur_s = _num(rec.get("duration_s")) or 0.0
        events.append({
            "ph": "X",
            "name": f"attempt {rec.get('attempt')} "
                    f"[{rec.get('classification') or '?'}]",
            "cat": "attempt", "ts": ts(start),
            "dur": round(dur_s * 1e6, 1),
            "pid": sup_pid, "tid": ids.tid(sup_pid, "attempts"),
            "args": _args_of(rec)})

    events.sort(key=lambda e: e.get("ts", 0.0))
    return {
        "traceEvents": ids.meta + events,
        "displayTimeUnit": "ms",
        "otherData": {
            "run_dir": os.path.abspath(run_dir),
            "t0_wall": t0,
            "records": mstats.get("records", 0),
            "torn_lines": (mstats.get("torn_lines", 0)
                           + lstats.get("torn_lines", 0)),
            "ledger_records": len(ledger),
        },
    }


_VALID_PH = {"X", "B", "E", "i", "I", "C", "M", "b", "e", "n", "s", "t",
             "f"}


def validate_trace(trace):
    """Structural validation against the Chrome trace-event schema subset
    this exporter emits. Returns a list of error strings (empty = valid);
    shared by the tier-1 round-trip test and the bench probe."""
    errors = []
    if not isinstance(trace, dict):
        return ["trace is not an object"]
    events = trace.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents is not a list"]
    for i, e in enumerate(events):
        where = f"traceEvents[{i}]"
        if not isinstance(e, dict):
            errors.append(f"{where}: not an object")
            continue
        ph = e.get("ph")
        if ph not in _VALID_PH:
            errors.append(f"{where}: bad ph {ph!r}")
            continue
        if not isinstance(e.get("pid"), int) \
                or not isinstance(e.get("tid"), int):
            errors.append(f"{where}: pid/tid must be ints")
        if ph == "M":
            if not isinstance((e.get("args") or {}).get("name"), str):
                errors.append(f"{where}: metadata without args.name")
            continue
        if _num(e.get("ts")) is None or e["ts"] < 0:
            errors.append(f"{where}: missing/negative ts")
        if not isinstance(e.get("name"), str):
            errors.append(f"{where}: missing name")
        if ph == "X" and (_num(e.get("dur")) is None or e["dur"] < 0):
            errors.append(f"{where}: X event without non-negative dur")
        if ph == "C":
            args = e.get("args")
            if not isinstance(args, dict) or not args or not all(
                    _num(v) is not None for v in args.values()):
                errors.append(f"{where}: C event args must be numeric")
    return errors


def write_trace(run_dir, output):
    """Build and write the trace; returns the trace dict."""
    trace = build_trace(run_dir)
    with open(output, "w") as f:
        json.dump(trace, f, allow_nan=False)
        f.write("\n")
    return trace


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m redcliff_tpu.obs trace",
        description="Export a run directory's telemetry as Chrome "
                    "trace-event JSON (open in ui.perfetto.dev).")
    ap.add_argument("run_dir", help="run directory (holds metrics.jsonl)")
    ap.add_argument("-o", "--output", default=None,
                    help="write the trace JSON here (default: stdout)")
    args = ap.parse_args(argv)
    from redcliff_tpu.obs.watch import diagnose_run_dir

    diag = diagnose_run_dir(args.run_dir)
    if diag is not None:
        print(f"obs trace: {diag}", file=sys.stderr)
        return 2
    if args.output:
        trace = write_trace(args.run_dir, args.output)
        od = trace["otherData"]
        print(f"obs trace: {len(trace['traceEvents'])} event(s) from "
              f"{od['records']} record(s) ({od['torn_lines']} torn line(s) "
              f"skipped) -> {args.output}")
    else:
        json.dump(build_trace(args.run_dir), sys.stdout, allow_nan=False)
        sys.stdout.write("\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
