"""Perfetto / Chrome trace-event export of a run directory's telemetry.

``python -m redcliff_tpu.obs trace <run_dir> [-o trace.json]`` joins the
span records, the structured engine events (``cost_model`` / ``memory`` /
``compile`` / ``compaction`` / ``remesh`` / numerics / deadline / hang),
and the supervisor's ledger attempts from ``metrics.jsonl`` +
``run_ledger.jsonl`` — rotation-chain- and torn-tail-aware via the spine's
:func:`~redcliff_tpu.obs.logging.read_jsonl` — into one Chrome
trace-event-format JSON object loadable in Perfetto (ui.perfetto.dev) or
``chrome://tracing``.

Mapping (the trace-event format's vocabulary):

* each writing ``(host, pid)`` becomes a trace *process* (``M`` metadata
  names it), each span component a *thread* within it — so a supervisor
  restart or a multi-host run renders as parallel process lanes;
* ``span`` records become complete (``ph="X"``) events with their measured
  ``dur_ms``, placed at the span's true START — ``Span`` stamps ``t_wall``
  at entry; ``record_span`` entries stamp it at record time (the end) and
  are backed off by their duration;
* supervisor ledger ``attempt`` records become ``X`` events on a synthetic
  ``supervisor`` process (``started_at`` + ``duration_s``);
* ``epoch`` events feed a ``lanes_live`` counter track and ``memory``
  events an ``hbm_bytes`` counter track (``ph="C"``) — the live-width and
  HBM-watermark curves next to the timeline;
* every other registered event lands as an instant (``ph="i"``) carrying
  its fields in ``args``.

**Fleet mode** (``obs trace --fleet <root>``, ISSUE 12): pointed at a
fleet sweep-service root (fleet/queue.py layout), :func:`build_fleet_trace`
joins the root's own metrics chain, the request-lifecycle ledger
(``history.jsonl``, fleet/history.py), and every ``work/<batch_id>`` run
dir + supervisor ledger into ONE timeline: per-worker / per-child
(host, pid) process lanes, a ``fleet-requests`` process with one track
per request spanning submit -> settle across every process that touched
it (under its submit-minted ``trace_id``), and queue-depth / in-flight /
dead-letter-depth counter tracks replayed from the ledger.

stdlib + the spine's jsonl reader only — no jax, never a backend; the
export runs post-mortem on any machine holding the run dir.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

from redcliff_tpu.obs.logging import read_jsonl

__all__ = ["build_trace", "build_fleet_trace", "validate_trace",
           "write_trace", "main"]

# events never rendered as instants: spans get their own "X" events, and a
# record that already fed a counter sample this pass is not duplicated as
# an instant (epoch / measured-memory records would otherwise appear twice
# — once on the counter track, once on the timeline)
_INSTANT_SKIP = ("span",)

_COUNTER_NUMERIC = (int, float)


class _Ids:
    """Stable small-int ids for (host, pid) processes and their threads."""

    def __init__(self):
        self.pids = {}
        self.tids = {}
        self.meta = []

    def pid(self, host, pid):
        key = (host if host is not None else "?",
               pid if pid is not None else 0)
        if key not in self.pids:
            self.pids[key] = len(self.pids) + 1
            self.meta.append({"ph": "M", "name": "process_name",
                              "pid": self.pids[key], "tid": 0,
                              "args": {"name": f"{key[0]}:{key[1]}"}})
        return self.pids[key]

    def tid(self, pid, component):
        key = (pid, component or "events")
        if key not in self.tids:
            self.tids[key] = len([k for k in self.tids if k[0] == pid]) + 1
            self.meta.append({"ph": "M", "name": "thread_name",
                              "pid": pid, "tid": self.tids[key],
                              "args": {"name": key[1]}})
        return self.tids[key]


def _num(v):
    return v if isinstance(v, _COUNTER_NUMERIC) \
        and not isinstance(v, bool) else None


def _args_of(rec):
    """Event fields minus the identity/core plumbing, JSON-safe as-is
    (records come from strict-JSON metrics.jsonl)."""
    return {k: v for k, v in rec.items()
            if k not in ("event", "wall_time", "seq", "pid", "host")}


def _span_start(rec):
    """A span record's wall-clock START. ``Span`` stamps ``t_wall`` at
    __enter__ (wall_time - t_wall ≈ dur); ``record_span`` stamps it at
    record time, i.e. the END (wall_time - t_wall ≈ 0) — distinguish by
    which gap the duration better explains and back the end-stamped case
    off by its duration."""
    wall = _num(rec.get("wall_time"))
    t_wall = _num(rec.get("t_wall"))
    dur_s = (_num(rec.get("dur_ms")) or 0.0) / 1e3
    if t_wall is None:
        return (wall - dur_s) if wall is not None else None
    if wall is not None and (wall - t_wall) < 0.5 * dur_s:
        return t_wall - dur_s
    return t_wall


def _read_run_dir(run_dir):
    """(records, ledger, mstats, lstats) for one run dir — missing files
    degrade to empty, torn lines counted by the spine's reader."""
    mstats, lstats = {}, {}
    try:
        records = read_jsonl(run_dir, stats=mstats)
    except FileNotFoundError:
        records, mstats = [], {"files": [], "records": 0, "torn_lines": 0}
    ledger_path = os.path.join(run_dir, "run_ledger.jsonl")
    ledger = (read_jsonl(ledger_path, stats=lstats)
              if os.path.exists(ledger_path) else [])
    return records, ledger, mstats, lstats


def _walls_of(records, ledger):
    """Every wall-clock timestamp that must bound the trace's time base."""
    walls = [r["wall_time"] for r in records
             if _num(r.get("wall_time")) is not None]
    # span STARTS bound the time base too (a long first span would
    # otherwise begin before t0 and get a negative timestamp)
    walls += [s for r in records if r.get("event") == "span"
              for s in (_span_start(r),) if s is not None]
    walls += [r["started_at"] for r in ledger
              if _num(r.get("started_at")) is not None]
    return walls


def _metric_events(records, ids, ts, events):
    """Map one metrics-chain record list into trace events: spans ->
    ``X``, epoch/memory -> counter samples, everything else -> instants —
    each on its writing (host, pid)'s process lane."""
    for rec in records:
        ev = rec.get("event")
        wall = _num(rec.get("wall_time"))
        if ev is None or wall is None:
            continue
        pid = ids.pid(rec.get("host"), rec.get("pid"))
        if ev == "span":
            name = rec.get("name") or "span"
            comp = str(name).partition(".")[0]
            dur = _num(rec.get("dur_ms")) or 0.0
            start = _span_start(rec)
            e = {"ph": "X", "name": name, "cat": "span",
                 "ts": ts(start if start is not None else wall),
                 "dur": round(dur * 1e3, 1),
                 "pid": pid, "tid": ids.tid(pid, comp)}
            args = {k: rec[k] for k in ("span_id", "parent_id", "trace")
                    if rec.get(k) is not None}
            args.update(rec.get("attrs") or {})
            if args:
                e["args"] = args
            events.append(e)
            continue
        tid = ids.tid(pid, ev.partition("_")[0] if ev.startswith("fit")
                      else "events")
        counted = False
        if ev == "epoch":
            lanes = _num(rec.get("lanes_live"))
            if lanes is None:
                lanes = _num(rec.get("num_active"))
            if lanes is not None:
                c = {"lanes_live": lanes}
                width = _num(rec.get("grid_width"))
                if width is not None:
                    c["grid_width"] = width
                events.append({"ph": "C", "name": "lanes_live",
                               "ts": ts(wall), "pid": pid,
                               "tid": ids.tid(pid, "counters"), "args": c})
                counted = True
        if ev == "memory":
            hbm = {k: v for k in ("bytes_in_use", "peak_bytes")
                   for v in (_num(rec.get(k)),) if v is not None}
            if hbm:
                events.append({"ph": "C", "name": "hbm_bytes",
                               "ts": ts(wall), "pid": pid,
                               "tid": ids.tid(pid, "counters"),
                               "args": hbm})
                counted = True
        if ev in _INSTANT_SKIP or counted:
            continue
        events.append({"ph": "i", "name": ev, "cat": ev, "s": "t",
                       "ts": ts(wall), "pid": pid, "tid": tid,
                       "args": _args_of(rec)})


def _ledger_events(ledger, ids, ts, events, proc_name="supervisor"):
    """Supervisor ledger attempts as ``X`` events on a synthetic
    process."""
    sup_pid = None
    for rec in ledger:
        if rec.get("event") != "attempt":
            continue
        start = _num(rec.get("started_at"))
        if start is None:
            continue
        if sup_pid is None:
            sup_pid = ids.pid(proc_name, 0)
        dur_s = _num(rec.get("duration_s")) or 0.0
        events.append({
            "ph": "X",
            "name": f"attempt {rec.get('attempt')} "
                    f"[{rec.get('classification') or '?'}]",
            "cat": "attempt", "ts": ts(start),
            "dur": round(dur_s * 1e6, 1),
            "pid": sup_pid, "tid": ids.tid(sup_pid, "attempts"),
            "args": _args_of(rec)})


def build_trace(run_dir):
    """Export one run directory as a Chrome trace-event JSON dict:
    ``{"traceEvents": [...], "displayTimeUnit": "ms", "otherData": {...}}``.
    Timestamps are microseconds relative to the run's earliest record."""
    records, ledger, mstats, lstats = _read_run_dir(run_dir)
    walls = _walls_of(records, ledger)
    t0 = min(walls) if walls else 0.0
    ts = lambda wall: round((wall - t0) * 1e6, 1)

    ids = _Ids()
    events = []
    _metric_events(records, ids, ts, events)
    _ledger_events(ledger, ids, ts, events)

    events.sort(key=lambda e: e.get("ts", 0.0))
    return {
        "traceEvents": ids.meta + events,
        "displayTimeUnit": "ms",
        "otherData": {
            "run_dir": os.path.abspath(run_dir),
            "t0_wall": t0,
            "records": mstats.get("records", 0),
            "torn_lines": (mstats.get("torn_lines", 0)
                           + lstats.get("torn_lines", 0)),
            "ledger_records": len(ledger),
        },
    }


def _request_track_events(history, ids, ts, events):
    """Per-request tracks from the lifecycle ledger: one thread per
    request on a synthetic ``fleet-requests`` process, holding one ``X``
    event spanning submit -> settle (the whole cross-process lifetime
    under one trace_id) plus an instant per transition (claimed / attempt
    / settled ...). Batch-scoped transitions (planned / bisected) land on
    a ``fleet-batches`` thread."""
    per_req = {}
    batch_events = []
    for rec in history:
        if rec.get("request_id") is not None:
            per_req.setdefault(rec["request_id"], []).append(rec)
        elif rec.get("kind") in ("planned", "bisected"):
            batch_events.append(rec)
    if not per_req and not batch_events:
        return
    pid = ids.pid("fleet-requests", 0)
    for rid in sorted(per_req):
        recs = sorted(per_req[rid],
                      key=lambda r: (_num(r.get("wall_time")) or 0.0,
                                     r.get("seq") or 0))
        tenant = next((r.get("tenant") for r in recs
                       if r.get("tenant") is not None), "?")
        trace_id = next((r.get("trace_id") for r in recs
                         if r.get("trace_id") is not None), None)
        walls = [w for r in recs for w in (_num(r.get("wall_time")),)
                 if w is not None]
        if not walls:
            continue
        sub = next((_num(r.get("submitted_at")) or _num(r.get("wall_time"))
                    for r in recs if r.get("kind") == "submitted"),
                   min(walls))
        settled = next((r for r in recs if r.get("kind") == "settled"),
                       None)
        end = (_num(settled.get("wall_time")) if settled is not None
               else None)
        tid = ids.tid(pid, rid)
        args = {"request_id": rid, "tenant": tenant}
        if trace_id is not None:
            args["trace_id"] = trace_id
        args["state"] = (settled.get("state") if settled is not None
                         else "live")
        events.append({"ph": "X", "name": f"{tenant}/{rid}",
                       "cat": "request", "ts": ts(min(sub, min(walls))),
                       "dur": round(max((end if end is not None
                                         else max(walls)) - sub, 0.0) * 1e6,
                                    1),
                       "pid": pid, "tid": tid, "args": args})
        for r in recs:
            wall = _num(r.get("wall_time"))
            if wall is None:
                continue
            events.append({"ph": "i", "name": str(r.get("kind")),
                           "cat": "fleet_lifecycle", "s": "t",
                           "ts": ts(wall), "pid": pid, "tid": tid,
                           "args": _args_of(r)})
    if batch_events:
        tid = ids.tid(pid, "fleet-batches")
        for r in batch_events:
            wall = _num(r.get("wall_time"))
            if wall is None:
                continue
            events.append({"ph": "i", "name": str(r.get("kind")),
                           "cat": "fleet_lifecycle", "s": "t",
                           "ts": ts(wall), "pid": pid, "tid": tid,
                           "args": _args_of(r)})


def _queue_counter_events(history, ids, ts, events):
    """Replay the lifecycle ledger into queue-depth / in-flight /
    dead-letter-depth counter tracks (one sample per transition)."""
    ordered = sorted((r for r in history if r.get("request_id") is not None),
                     key=lambda r: (_num(r.get("wall_time")) or 0.0,
                                    r.get("seq") or 0))
    if not ordered:
        return
    pid = ids.pid("fleet-queue", 0)
    tid = ids.tid(pid, "counters")
    state = {}  # request_id -> "queued" | "running" | terminal state
    queued = in_flight = deadletter = 0
    for rec in ordered:
        kind, rid = rec.get("kind"), rec["request_id"]
        wall = _num(rec.get("wall_time"))
        if wall is None:
            continue
        prev = state.get(rid)
        if kind == "submitted" and prev is None:
            state[rid] = "queued"
            queued += 1
        elif kind == "claimed" and prev == "queued":
            state[rid] = "running"
            queued -= 1
            in_flight += 1
        elif kind == "released" and prev == "running":
            # a lease release (budget-route, bisection, all-or-nothing
            # claim rollback) returns the request to the queue — without
            # this the in-flight curve would stay high through exactly the
            # crash-loop incidents the counters exist to diagnose
            state[rid] = "queued"
            in_flight -= 1
            queued += 1
        elif kind == "settled" and prev in ("queued", "running"):
            if prev == "queued":
                queued -= 1
            else:
                in_flight -= 1
            state[rid] = str(rec.get("state") or "settled")
            if state[rid] == "deadletter":
                deadletter += 1
        elif kind == "requeued" and prev not in ("queued", "running"):
            if prev == "deadletter":
                deadletter -= 1
            state[rid] = "queued"
            queued += 1
        else:
            continue
        events.append({"ph": "C", "name": "queue_depth", "ts": ts(wall),
                       "pid": pid, "tid": tid, "args": {"queued": queued}})
        events.append({"ph": "C", "name": "in_flight", "ts": ts(wall),
                       "pid": pid, "tid": tid,
                       "args": {"in_flight": in_flight}})
        events.append({"ph": "C", "name": "deadletter_depth",
                       "ts": ts(wall), "pid": pid, "tid": tid,
                       "args": {"deadletter": deadletter}})


def build_fleet_trace(root):
    """Export a FLEET ROOT (fleet/queue.py layout) as one joined Chrome
    trace: the root's own metrics chain (worker fleet events + spans), the
    lifecycle ledger's per-request tracks and queue/in-flight/dead-letter
    counter curves, and every ``work/<batch_id>`` run dir's records +
    supervisor ledger — each writing (host, pid) its own process lane, so
    one request's track visibly spans submit CLI -> worker -> supervised
    jax child (and any reclaiming worker after a SIGKILL) under one
    trace_id."""
    from redcliff_tpu.fleet.history import read_history

    hstats = {}
    root_records, _ledger, rstats, _ = _read_run_dir(root)
    # the root chain never has a run_ledger; fleet_lifecycle records ride
    # history.jsonl, not metrics.jsonl
    history = read_history(root, stats=hstats)
    work_dir = os.path.join(root, "work")
    try:
        batch_dirs = sorted(
            os.path.join(work_dir, d) for d in os.listdir(work_dir)
            if os.path.isdir(os.path.join(work_dir, d)))
    except OSError:
        batch_dirs = []
    runs = []
    torn = rstats.get("torn_lines", 0) + hstats.get("torn_lines", 0)
    n_records = rstats.get("records", 0) + hstats.get("records", 0)
    for d in batch_dirs:
        records, ledger, mstats, lstats = _read_run_dir(d)
        runs.append((d, records, ledger))
        torn += mstats.get("torn_lines", 0) + lstats.get("torn_lines", 0)
        n_records += mstats.get("records", 0)

    walls = _walls_of(root_records, [])
    walls += [w for r in history
              for w in (_num(r.get("wall_time")),
                        _num(r.get("submitted_at")),
                        _num(r.get("started_at"))) if w is not None]
    for _d, records, ledger in runs:
        walls += _walls_of(records, ledger)
    t0 = min(walls) if walls else 0.0
    ts = lambda wall: round((wall - t0) * 1e6, 1)

    ids = _Ids()
    events = []
    _metric_events(root_records, ids, ts, events)
    for d, records, ledger in runs:
        _metric_events(records, ids, ts, events)
        _ledger_events(ledger, ids, ts, events,
                       proc_name=f"supervisor:{os.path.basename(d)}")
    _request_track_events(history, ids, ts, events)
    _queue_counter_events(history, ids, ts, events)

    events.sort(key=lambda e: e.get("ts", 0.0))
    return {
        "traceEvents": ids.meta + events,
        "displayTimeUnit": "ms",
        "otherData": {
            "fleet_root": os.path.abspath(root),
            "t0_wall": t0,
            "records": n_records,
            "history_records": hstats.get("records", 0),
            "batch_run_dirs": len(runs),
            "torn_lines": torn,
        },
    }


_VALID_PH = {"X", "B", "E", "i", "I", "C", "M", "b", "e", "n", "s", "t",
             "f"}


def validate_trace(trace):
    """Structural validation against the Chrome trace-event schema subset
    this exporter emits. Returns a list of error strings (empty = valid);
    shared by the tier-1 round-trip test and the bench probe."""
    errors = []
    if not isinstance(trace, dict):
        return ["trace is not an object"]
    events = trace.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents is not a list"]
    for i, e in enumerate(events):
        where = f"traceEvents[{i}]"
        if not isinstance(e, dict):
            errors.append(f"{where}: not an object")
            continue
        ph = e.get("ph")
        if ph not in _VALID_PH:
            errors.append(f"{where}: bad ph {ph!r}")
            continue
        if not isinstance(e.get("pid"), int) \
                or not isinstance(e.get("tid"), int):
            errors.append(f"{where}: pid/tid must be ints")
        if ph == "M":
            if not isinstance((e.get("args") or {}).get("name"), str):
                errors.append(f"{where}: metadata without args.name")
            continue
        if _num(e.get("ts")) is None or e["ts"] < 0:
            errors.append(f"{where}: missing/negative ts")
        if not isinstance(e.get("name"), str):
            errors.append(f"{where}: missing name")
        if ph == "X" and (_num(e.get("dur")) is None or e["dur"] < 0):
            errors.append(f"{where}: X event without non-negative dur")
        if ph == "C":
            args = e.get("args")
            if not isinstance(args, dict) or not args or not all(
                    _num(v) is not None for v in args.values()):
                errors.append(f"{where}: C event args must be numeric")
    return errors


def write_trace(run_dir, output, fleet=False):
    """Build and write the trace; returns the trace dict. ``fleet=True``
    treats ``run_dir`` as a fleet root (:func:`build_fleet_trace`)."""
    trace = build_fleet_trace(run_dir) if fleet else build_trace(run_dir)
    with open(output, "w") as f:
        json.dump(trace, f, allow_nan=False)
        f.write("\n")
    return trace


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m redcliff_tpu.obs trace",
        description="Export a run directory's telemetry as Chrome "
                    "trace-event JSON (open in ui.perfetto.dev).")
    ap.add_argument("run_dir", help="run directory (holds metrics.jsonl), "
                                    "or a fleet root with --fleet")
    ap.add_argument("-o", "--output", default=None,
                    help="write the trace JSON here (default: stdout)")
    ap.add_argument("--fleet", action="store_true",
                    help="treat run_dir as a fleet sweep-service root: "
                         "join the lifecycle ledger, worker metrics, and "
                         "every batch run dir into one timeline "
                         "(per-request tracks + queue counter tracks)")
    args = ap.parse_args(argv)
    from redcliff_tpu.obs.watch import diagnose_run_dir, is_fleet_root

    diag = diagnose_run_dir(args.run_dir)
    if diag is None and args.fleet and not is_fleet_root(args.run_dir):
        diag = (f"not a fleet root (no requests.jsonl / leases/): "
                f"{args.run_dir}")
    if diag is not None:
        print(f"obs trace: {diag}", file=sys.stderr)
        return 2
    if args.output:
        trace = write_trace(args.run_dir, args.output, fleet=args.fleet)
        od = trace["otherData"]
        print(f"obs trace: {len(trace['traceEvents'])} event(s) from "
              f"{od['records']} record(s) ({od['torn_lines']} torn line(s) "
              f"skipped) -> {args.output}")
    else:
        trace = (build_fleet_trace(args.run_dir) if args.fleet
                 else build_trace(args.run_dir))
        json.dump(trace, sys.stdout, allow_nan=False)
        sys.stdout.write("\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
