"""Structured jsonl metric logging + the opt-in jax.profiler trace hook.

The write half of the telemetry spine (docs/ARCHITECTURE.md "Telemetry
spine"): every engine appends strict-JSON event records to
``<save_dir>/metrics.jsonl`` through :class:`MetricLogger`, and every record
carries the schema-v1 identity triple — a process-wide monotonic ``seq``,
``pid``, and ``host`` — so interleaved multi-attempt / multi-host logs have
a total order per process (order across processes by ``(host, pid, seq)``
plus ``wall_time``). Files rotate at a byte cap
(``REDCLIFF_METRICS_MAX_BYTES`` / the ``max_bytes`` knob) to
``metrics.jsonl.1`` … so chaos soaks and week-long sweeps cannot grow one
file unbounded.

The read half is crash-tolerant: :func:`read_jsonl` walks the rotation
chain oldest-first and SKIPS unparseable lines instead of raising — a
SIGKILL mid-append leaves a torn final line, which used to poison the whole
file for every reader; now it is skipped and counted (``stats`` out-param),
so post-mortem tooling reads everything the run managed to flush. Event
schemas are registered in :mod:`redcliff_tpu.obs.schema`.

numpy at module scope (for :func:`jsonable`) but never jax — bench.py's
backend-free parent imports this path.
"""
from __future__ import annotations

import contextlib
import itertools
import json
import math
import os
import re
import threading
import time
from dataclasses import asdict, is_dataclass

import numpy as np

from redcliff_tpu.obs import spans as _spans

__all__ = ["MetricLogger", "profiler_trace", "jsonable", "read_jsonl",
           "jsonl_files", "ENV_MAX_BYTES", "DEFAULT_MAX_BACKUPS"]

ENV_MAX_BYTES = "REDCLIFF_METRICS_MAX_BYTES"
DEFAULT_MAX_BACKUPS = 8

# process-wide event sequence: one counter shared by every logger in the
# process, so (pid, seq) totally orders a process's records even when two
# loggers (e.g. a fit's and the watchdog's) interleave on different files
_seq = itertools.count(1)


def jsonable(v):
    """Recursively coerce numpy/jax scalars and arrays into STRICT
    JSON-encodable Python values. Arrays become (nested) lists; non-finite
    floats (NaN/inf, scalar or array element) become ``None`` — the emitted
    lines never contain the JSON-standard-breaking ``NaN``/``Infinity``
    tokens."""
    if isinstance(v, bool) or v is None or isinstance(v, (int, str)):
        return v
    if isinstance(v, float):
        return v if math.isfinite(v) else None
    if is_dataclass(v) and not isinstance(v, type):
        return {k: jsonable(x) for k, x in asdict(v).items()}
    if isinstance(v, dict):
        return {str(k): jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [jsonable(x) for x in v]
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        f = float(v)
        return f if math.isfinite(f) else None
    if hasattr(v, "ndim"):  # numpy / jax arrays without importing jax here
        arr = np.asarray(v)
        if arr.ndim == 0:
            return jsonable(arr.item())
        return [jsonable(x) for x in arr.tolist()]
    return str(v)


class MetricLogger:
    """Append-only jsonl metric writer.

    ``MetricLogger(save_dir)`` writes to ``<save_dir>/metrics.jsonl``;
    ``MetricLogger(None)`` is a no-op sink so call sites never branch.
    Resumed runs keep appending to the same file — the ``epoch`` field makes
    replays self-describing, and the ``seq``/``pid``/``host`` identity
    triple stamped on every record totally orders interleaved attempts.

    Rotation: when ``max_bytes`` (default: the ``REDCLIFF_METRICS_MAX_BYTES``
    env var; 0/unset = never rotate) is exceeded after a write, the file
    rotates — ``metrics.jsonl`` -> ``metrics.jsonl.1``, shifting existing
    backups up and dropping the oldest past ``max_backups``. Records are
    never split across the rotation boundary (whole lines only), and
    :func:`read_jsonl` reads the chain back oldest-first.
    """

    def __init__(self, target, filename="metrics.jsonl", resume=True,
                 max_bytes=None, max_backups=DEFAULT_MAX_BACKUPS):
        self._fh = None
        # the liveness watchdog logs hang incidents from its own thread
        # while the fit loop logs epochs; serialized writes keep every
        # jsonl line intact (a torn line would break strict-JSON readers)
        self._lock = threading.Lock()
        if max_bytes is None:
            try:
                max_bytes = int(os.environ.get(ENV_MAX_BYTES, "0")) or None
            except ValueError:
                max_bytes = None
        self.max_bytes = max_bytes
        self.max_backups = max(int(max_backups), 1)
        self._pid = os.getpid()
        self._host = _spans.HOST
        if target is None:
            return
        path = target
        if not str(target).endswith(".jsonl"):
            os.makedirs(target, exist_ok=True)
            path = os.path.join(target, filename)
        else:
            parent = os.path.dirname(path)
            if parent:
                os.makedirs(parent, exist_ok=True)
        self.path = path
        self._fh = open(path, "a" if resume else "w")
        try:
            self._bytes = os.path.getsize(path)
        except OSError:
            self._bytes = 0

    @property
    def active(self):
        return self._fh is not None

    def log(self, event, **fields):
        if self._fh is None:
            return
        rec = {"event": event, "wall_time": time.time(),
               "seq": next(_seq), "pid": self._pid, "host": self._host}
        # fleet trace identity (spans.set_trace_ctx / REDCLIFF_TRACE_CTX):
        # while a request-scoped context is live and tracing is on, every
        # record this process writes carries the batch/request join keys —
        # the cross-process half of the identity triple. One None check
        # when no context is set; REDCLIFF_TRACE=0 drops the stamping
        # entirely (the zero-cost contract)
        ctx = _spans.trace_ctx()
        if ctx is not None and _spans.enabled() and "trace" not in fields:
            rec["trace"] = ctx
        rec.update({k: jsonable(v) for k, v in fields.items()})
        # allow_nan=False is the strictness backstop: jsonable already maps
        # non-finite floats to null, so a violation here is a bug, not data
        line = json.dumps(rec, allow_nan=False) + "\n"
        with self._lock:
            if self._fh is not None:
                self._fh.write(line)
                self._fh.flush()
                self._bytes += len(line)
                if self.max_bytes and self._bytes > self.max_bytes:
                    self._rotate_locked()

    def _rotate_locked(self):
        """Rotate under the held lock: close, shift the backup chain up
        (dropping the oldest), reopen fresh. Rotation is best-effort: if the
        head rename fails (e.g. the directory lost write permission — rename
        needs it, appending to the existing file does not), the file is
        reopened for APPEND, never truncated — a failed rotation may grow
        the file past the cap but can never destroy recorded telemetry."""
        self._fh.close()
        rotated = False
        try:
            oldest = f"{self.path}.{self.max_backups}"
            if os.path.exists(oldest):
                os.remove(oldest)
            for i in range(self.max_backups - 1, 0, -1):
                src = f"{self.path}.{i}"
                if os.path.exists(src):
                    os.replace(src, f"{self.path}.{i + 1}")
            os.replace(self.path, f"{self.path}.1")
            rotated = True
        except OSError:
            pass  # appending must keep working
        self._fh = open(self.path, "w" if rotated else "a")
        if rotated:
            self._bytes = 0

    def close(self):
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def jsonl_files(path):
    """The rotation chain for a jsonl path (or a run dir), oldest first:
    ``[path.N, ..., path.1, path]`` — only files that exist. The base path
    is always last so readers see records in write order."""
    if os.path.isdir(path):
        path = os.path.join(path, "metrics.jsonl")
    rotated = []
    parent = os.path.dirname(path) or "."
    base = os.path.basename(path)
    pat = re.compile(re.escape(base) + r"\.(\d+)$")
    try:
        for name in os.listdir(parent):
            m = pat.match(name)
            if m:
                rotated.append((int(m.group(1)), os.path.join(parent, name)))
    except OSError:
        pass
    out = [p for _, p in sorted(rotated, reverse=True)]
    if os.path.exists(path):
        out.append(path)
    return out


def read_jsonl(path, event=None, stats=None, strict=False):
    """Load a metrics.jsonl file (optionally filtered by event type),
    following the rotation chain oldest-first.

    Crash-tolerant by default: a line that fails to parse — the torn final
    line a SIGKILL mid-append leaves behind, or a line truncated by disk
    full — is SKIPPED and counted instead of poisoning the whole file.
    Pass a dict as ``stats`` to receive ``{"files", "records",
    "torn_lines"}``; ``strict=True`` restores raise-on-bad-line.
    """
    files = jsonl_files(path)
    if not files:
        # preserve the pre-rotation contract: a missing file raises
        raise FileNotFoundError(
            path if str(path).endswith(".jsonl")
            else os.path.join(path, "metrics.jsonl"))
    out = []
    torn = 0
    for fpath in files:
        with open(fpath) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    if strict:
                        raise
                    torn += 1
                    continue
                if event is None or rec.get("event") == event:
                    out.append(rec)
    if stats is not None:
        stats.update(files=files, records=len(out), torn_lines=torn)
    return out


@contextlib.contextmanager
def profiler_trace(log_dir):
    """Opt-in whole-block ``jax.profiler.trace`` context (``log_dir=None``
    is a no-op). LEGACY for fit loops: the engines now capture bounded
    windows via :mod:`redcliff_tpu.obs.profiling` (``profile_dir`` is an
    alias for one bounded window there); this stays for ad-hoc scripts that
    really do want an entire region traced."""
    if not log_dir:
        yield
        return
    import jax

    os.makedirs(log_dir, exist_ok=True)
    with jax.profiler.trace(str(log_dir)):
        yield
